"""Static per-position value priors from VSA (consumer (c) of
``analysis/vsa.py``) — the ``kbz-value-prior-v1`` sidecar.

ROADMAP item 4's value-conditioned model ("Not all bytes are equal",
arxiv 1711.04596) predicts which VALUES a position should take, not
just which positions matter.  Training starts from zero today; this
module ships the static initialization surface — a histogram per
input-byte position derived before a single exec:

* every affine guard inversion contributes its satisfying byte
  values, weighted by how many distinct guards select them (a value
  three compares agree on outweighs a value one compare admits);
* the residual probability mass sits on the position's VSA domain
  interval (``lo``/``hi``/``stride``), so sampling can fall back to
  the interval when the explicit histogram misses;
* positions VSA says nothing about are absent — the model treats
  them as uniform, exactly like an untrained prior.

The sidecar is plain JSON keyed by ``program_sig``, so a consumer
can reject a stale prior the same way the corpus store rejects a
stale VSA doc.  The model that consumes these lands later (ROADMAP
item 4); nothing in the fuzzing loop reads them yet.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from .vsa import VsaResult, analyze_vsa, affine_sat_set

PRIOR_SCHEMA = "kbz-value-prior-v1"


def value_priors(program, vsa: Optional[VsaResult] = None,
                 target: str = "") -> Dict:
    """Build the ``kbz-value-prior-v1`` document for ``program``.

    Returns ``{"schema", "target", "program_sig", "positions"}``
    where ``positions`` maps the stringified byte index to::

        {"values": [v, ...],     # explicit histogram support
         "weights": [w, ...],    # guard-agreement counts, same order
         "interval": [lo, hi],   # VSA domain hull for the position
         "stride": s}

    Deterministic: values sorted ascending, positions sorted
    numerically (JSON keys as strings for sidecar friendliness).
    """
    from ..models.vm import CMP_EQ
    if vsa is None:
        vsa = analyze_vsa(program)

    hist: Dict[int, Dict[int, int]] = {}
    for f in vsa.branches:
        for aff, other in ((f.x_affine, f.y_dom),
                           (f.y_affine, f.x_dom)):
            if aff is None or other.const_val is None:
                continue
            if f.cmp not in ("eq", "ne"):
                continue
            sat = affine_sat_set(aff, CMP_EQ, other.const_val, True)
            if not sat or len(sat) > 16:
                continue
            h = hist.setdefault(aff[0], {})
            for v in sat:
                h[v] = h.get(v, 0) + 1

    positions: Dict[str, Dict] = {}
    seen = set(hist) | set(vsa.byte_domains)
    for i in sorted(seen):
        dom = vsa.byte_domains.get(i)
        h = hist.get(i, {})
        vals = sorted(h)
        entry: Dict = {
            "values": vals,
            "weights": [h[v] for v in vals],
            "interval": [dom.lo, dom.hi] if dom is not None
            else [0, 255],
            "stride": dom.stride if dom is not None else 1,
        }
        # a domain small enough to enumerate IS a histogram — merge
        # its members at weight 1 so interval-only positions still
        # carry explicit support
        if dom is not None and not vals:
            ev = dom.enum(16)
            if ev:
                entry["values"] = sorted(v for v in ev
                                         if 0 <= v <= 255)
                entry["weights"] = [1] * len(entry["values"])
        positions[str(i)] = entry

    return {
        "schema": PRIOR_SCHEMA,
        "target": target,
        "program_sig": vsa.program_sig,
        "positions": positions,
    }


def save_priors(path, doc: Dict) -> None:
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_priors(path, program=None) -> Optional[Dict]:
    """Read a prior sidecar; ``None`` on schema mismatch, or on
    ``program_sig`` mismatch when ``program`` is given (stale prior
    for a different build of the target)."""
    from .vsa import program_sig
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != PRIOR_SCHEMA:
        return None
    if program is not None and doc.get("program_sig") != \
            program_sig(program):
        return None
    return doc
