"""Control-flow graph reconstruction from a KBVM instruction array.

The coverage blocks (OP_BLOCK instructions) are the CFG nodes; block
``-1`` is the program entry (pc 0 runs until the first OP_BLOCK).
Successors come from the instruction semantics — OP_JMP's target,
OP_BR's target + fallthrough, OP_HALT/OP_CRASH terminate, everything
else falls through — the same walk ``vm.compute_edges`` uses to
enumerate the static edge universe, extended with per-edge step costs
so ``max_steps`` can be validated against real (loop-free) paths.

Step accounting matches the engine exactly: every executed
instruction is one step (``lane_steps`` in ``vm._step_batched``),
including the OP_BLOCK marker, the terminal HALT/CRASH, and the step
in which an out-of-range pc is detected.

All walks are iterative (no recursion-limit games) and polynomial:
costs come from a longest-path DP over the cycle-cut graph; only when
cycle cutting finds retreating edges (irreducible regions, whose
loop-free paths CAN use them) does a budget-capped exact path search
refine the result.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

from ..models.vm import OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP

ENTRY = -1  # virtual entry node (prev_loc == 0 before the first block)

#: edge-visit budget for the exact path-search refinements on
#: irreducible graphs (exponential worst case; real programs finish
#: in microseconds — the budget is a runaway backstop, and on
#: exhaustion the DP lower bound stands)
_PATH_SEARCH_BUDGET = 2_000_000


def instr_successors(instrs: np.ndarray, pc: int) -> List[int]:
    """Successor pcs of one instruction (out-of-range pcs included —
    the engine crashes the lane on the NEXT step's fetch)."""
    op, a, b, c = (int(x) for x in instrs[pc])
    if op in (OP_HALT, OP_CRASH):
        return []
    if op == OP_JMP:
        return [a]
    if op == OP_BR:
        return [c, pc + 1]
    return [pc + 1]


@dataclass
class ControlFlowGraph:
    """Block-level CFG of one Program (node ``ENTRY`` = entry path).

    ``succ[f]`` holds destination block indices; ``edge_cost[(f, t)]``
    is the maximum number of VM steps spent from f's block head
    (inclusive) to t's block head (exclusive) along any pc-acyclic
    path; ``term_cost[f]`` is the maximum steps from f's head through
    a terminal (HALT/CRASH/off-end), or None when no block-free path
    from f terminates.
    """

    n_blocks: int
    block_pcs: List[int]
    succ: Dict[int, Set[int]]
    edge_cost: Dict[Tuple[int, int], int]
    term_cost: Dict[int, Optional[int]]
    reachable: Set[int]
    dominators: Dict[int, Set[int]] = field(default_factory=dict)
    loop_headers: Set[int] = field(default_factory=set)
    back_edges: Set[Tuple[int, int]] = field(default_factory=set)
    #: longest loop-free complete path (entry -> terminal) in VM
    #: steps — the hang budget must cover at least this much
    longest_acyclic_path: int = 0

    @property
    def edges(self) -> List[Tuple[int, int]]:
        return sorted((f, t) for f, ts in self.succ.items() for t in ts)

    def unreachable_blocks(self) -> List[int]:
        return [k for k in range(self.n_blocks) if k not in self.reachable]


def _classify_edges(graph: Dict[int, List[int]],
                    roots: Iterable[int]):
    """Iterative DFS edge classification: returns ``(retreating
    edges, post-order)``.  Removing the retreating edges makes the
    graph acyclic (any cycle contains one in any DFS)."""
    color: Dict[int, int] = {}          # absent/0 white, 1 gray, 2 black
    retreating: Set[Tuple[int, int]] = set()
    order: List[int] = []
    for root in roots:
        if color.get(root, 0):
            continue
        color[root] = 1
        stack = [(root, iter(graph.get(root, ())))]
        while stack:
            n, it = stack[-1]
            pushed = False
            for t in it:
                c = color.get(t, 0)
                if c == 1:
                    retreating.add((n, t))
                elif c == 0:
                    color[t] = 1
                    stack.append((t, iter(graph.get(t, ()))))
                    pushed = True
                    break
            if not pushed:
                color[n] = 2
                order.append(n)
                stack.pop()
    return retreating, order


def _region_walk(instrs: np.ndarray, start_pc: int,
                 idx_of_pc: Dict[int, int], skip_start: bool):
    """Max-step walk from ``start_pc`` stopping at block heads.

    Returns ``(to_blocks, term)``: ``to_blocks[t]`` = max steps from
    start_pc (inclusive) to block t's head (exclusive); ``term`` = max
    steps through a terminal, or None if no block-free path from here
    terminates.  ``skip_start`` executes through the start pc even
    when it is itself a block head (a block region starts AT its own
    marker; a later branch back to it is a self-edge).

    Costs are a longest-path DP over the region's cycle-cut pc graph
    (linear — reconverging branch diamonds are fine); when the region
    is irreducible (retreating pc edges a loop-free path could still
    take) a budget-capped exact search refines the DP lower bound.
    """
    ni = instrs.shape[0]

    def is_head(pc: int) -> bool:
        return int(instrs[pc, 0]) == OP_BLOCK

    if not skip_start and is_head(start_pc):
        # the entry region ends immediately: pc 0 IS a block head
        return {idx_of_pc[start_pc]: 0}, None

    # -- discover the region: interior pcs + sink/terminal edges ------
    interior_succ: Dict[int, List[int]] = {}
    heads_of: Dict[int, List[int]] = {}     # pc -> block sinks entered
    halt_at: Set[int] = set()               # pc executes HALT/CRASH
    bad_from: Set[int] = set()              # pc has an off-range succ
    stack = [start_pc]
    seen = {start_pc}
    while stack:
        pc = stack.pop()
        succs = instr_successors(instrs, pc)
        if not succs:
            halt_at.add(pc)
        interior = []
        for s in succs:
            if s < 0 or s >= ni:
                bad_from.add(pc)
            elif is_head(s):
                heads_of.setdefault(pc, []).append(idx_of_pc[s])
            else:
                interior.append(s)
                if s not in seen:
                    seen.add(s)
                    stack.append(s)
        interior_succ[pc] = interior

    to_blocks: Dict[int, int] = {}
    term: Optional[int] = None

    def apply(pc: int, d: int) -> None:
        """Fold one arrival at ``pc`` with ``d`` instructions already
        executed into the sink costs."""
        nonlocal term
        if pc in halt_at:               # the HALT/CRASH step itself
            term = max(term or 0, d + 1)
        if pc in bad_from:              # executed pc, crashed on fetch
            term = max(term or 0, d + 2)
        for h in heads_of.get(pc, ()):
            if d + 1 > to_blocks.get(h, -1):
                to_blocks[h] = d + 1

    # -- longest-path DP over the cycle-cut region ---------------------
    retreating, order = _classify_edges(interior_succ, [start_pc])
    dist: Dict[int, int] = {start_pc: 0}
    for n in reversed(order):           # reverse post-order = topo
        if n not in dist:
            continue
        apply(n, dist[n])
        for t in interior_succ[n]:
            if (n, t) in retreating:
                continue
            if dist[n] + 1 > dist.get(t, -1):
                dist[t] = dist[n] + 1

    # -- irreducible region: exact (budgeted) refinement ---------------
    if retreating:
        budget = _PATH_SEARCH_BUDGET
        on_path = {start_pc}
        pstack = [(start_pc, iter(interior_succ[start_pc]))]
        apply(start_pc, 0)
        while pstack and budget > 0:
            n, it = pstack[-1]
            moved = False
            for t in it:
                budget -= 1
                if t in on_path:
                    continue
                apply(t, len(pstack))
                on_path.add(t)
                pstack.append((t, iter(interior_succ[t])))
                moved = True
                break
            if not moved:
                pstack.pop()
                on_path.discard(n)

    return to_blocks, term


def _dominators(succ: Dict[int, Set[int]], reachable: Set[int]
                ) -> Dict[int, Set[int]]:
    """Iterative dominator sets over the entry-reachable subgraph
    (``dom[n]`` includes ``n`` and ``ENTRY``)."""
    nodes = [ENTRY] + sorted(reachable)
    node_set = set(nodes)
    preds: Dict[int, Set[int]] = {n: set() for n in nodes}
    for f, ts in succ.items():
        if f not in node_set:
            continue
        for t in ts:
            if t in preds:
                preds[t].add(f)
    dom: Dict[int, Set[int]] = {n: set(nodes) for n in nodes}
    dom[ENTRY] = {ENTRY}
    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n == ENTRY:
                continue
            ps = [dom[p] for p in preds[n]]
            new = set.intersection(*ps) if ps else set()
            new = new | {n}
            if new != dom[n]:
                dom[n] = new
                changed = True
    return dom


def _longest_simple_path(graph: Dict[int, List[int]],
                         edge_cost: Dict[Tuple[int, int], int],
                         term_cost: Dict[int, Optional[int]]) -> int:
    """Exact longest block-simple path from ENTRY to a terminal —
    the irreducible-CFG fallback (a DAG longest-path after dropping
    retreating edges would UNDERCOUNT: loop-free executions can take
    a retreating edge whose target they have not visited).  Budgeted;
    iterative."""
    budget = _PATH_SEARCH_BUDGET
    t0 = term_cost.get(ENTRY)
    longest = t0 if t0 is not None else 0
    on_path = {ENTRY}
    stack = [(ENTRY, 0, iter(graph.get(ENTRY, ())))]
    while stack and budget > 0:
        n, d, it = stack[-1]
        moved = False
        for s in it:
            budget -= 1
            if s in on_path:
                continue
            nd = d + edge_cost[(n, s)]
            tc = term_cost.get(s)
            if tc is not None:
                longest = max(longest, nd + tc)
            on_path.add(s)
            stack.append((s, nd, iter(graph.get(s, ()))))
            moved = True
            break
        if not moved:
            stack.pop()
            on_path.discard(n)
    return longest


def build_cfg(program) -> ControlFlowGraph:
    """Reconstruct the block-level CFG of a ``Program``."""
    instrs = np.asarray(program.instrs)
    ni = instrs.shape[0]
    block_pcs = [pc for pc in range(ni)
                 if int(instrs[pc, 0]) == OP_BLOCK]
    idx_of_pc = {pc: k for k, pc in enumerate(block_pcs)}
    nb = len(block_pcs)

    succ: Dict[int, Set[int]] = {}
    edge_cost: Dict[Tuple[int, int], int] = {}
    term_cost: Dict[int, Optional[int]] = {}
    starts = [(ENTRY, 0)] if ni else [(ENTRY, -1)]
    starts += [(k, pc) for k, pc in enumerate(block_pcs)]
    for f, start_pc in starts:
        if start_pc < 0:                # empty program: entry crashes
            succ[f] = set()
            term_cost[f] = 1
            continue
        to_blocks, term = _region_walk(instrs, start_pc, idx_of_pc,
                                       skip_start=(f != ENTRY))
        succ[f] = set(to_blocks)
        term_cost[f] = term
        for t, cost in to_blocks.items():
            edge_cost[(f, t)] = cost

    reachable = _reachable_from_entry(succ)
    dom = _dominators(succ, reachable)

    # natural back edges: target dominates source (self-loops always)
    back = {(f, t) for (f, t) in edge_cost
            if f != ENTRY and f in reachable and t in reachable
            and (t == f or t in dom.get(f, ()))}
    headers = {t for _, t in back}

    # loop-free longest path: drop natural back edges (acyclic
    # executions never take one — the target dominates, hence already
    # preceded, the source) and longest-path the remainder.  A
    # reducible CFG is then a DAG; irreducible leftovers (blocks
    # branching into each other with neither dominating) are handled
    # by an EXACT bounded path search, because a loop-free execution
    # CAN traverse a retreating edge it hasn't visited yet.
    dag: Dict[int, List[int]] = {
        f: sorted(t for t in ts if (f, t) not in back)
        for f, ts in succ.items()}
    retreating, order = _classify_edges(dag, [ENTRY] + sorted(dag))
    if retreating:
        longest = _longest_simple_path(dag, edge_cost, term_cost)
    else:
        dist: Dict[int, int] = {ENTRY: 0}
        longest = 0
        for n in reversed(order):       # reverse post-order = topo
            if n not in dist:
                continue                # not reachable from entry
            d = dist[n]
            t_c = term_cost.get(n)
            if t_c is not None:
                longest = max(longest, d + t_c)
            for t in dag.get(n, ()):
                nd = d + edge_cost[(n, t)]
                if nd > dist.get(t, -1):
                    dist[t] = nd

    return ControlFlowGraph(
        n_blocks=nb, block_pcs=block_pcs, succ=succ,
        edge_cost=edge_cost, term_cost=term_cost, reachable=reachable,
        dominators=dom, loop_headers=headers,
        back_edges=back | retreating, longest_acyclic_path=longest)


def _reachable_from_entry(succ: Dict[int, Set[int]]) -> Set[int]:
    seen: Set[int] = set()
    stack = [ENTRY]
    while stack:
        n = stack.pop()
        for t in succ.get(n, ()):
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return seen


def static_edge_prior(program, cfg: Optional[ControlFlowGraph] = None
                      ) -> Dict[int, float]:
    """Static edge-frequency prior, keyed by AFL map SLOT (the
    coverage-signature vocabulary): probability mass reaching each
    edge when every branch is a coin flip, flowed over the loop-free
    CFG.  Rare edges (deep behind many branches) get small mass — the
    cold-start stand-in for FairFuzz's dynamic corpus hit counts.
    Colliding slots sum their mass (aliased edges are already
    indistinguishable to a signature)."""
    cfg = cfg or build_cfg(program)
    dag: Dict[int, List[int]] = {
        f: sorted(t for t in ts if (f, t) not in cfg.back_edges)
        for f, ts in cfg.succ.items()}
    _, order = _classify_edges(dag, [ENTRY] + sorted(dag))

    prob: Dict[int, float] = {ENTRY: 1.0}
    edge_prob: Dict[Tuple[int, int], float] = {}
    for f in reversed(order):           # topological over the DAG
        if f not in prob:
            continue
        ts = cfg.succ.get(f, ())
        if not ts:
            continue
        share = prob[f] / len(ts)
        for t in ts:
            # back edges receive their share too (loops run OFTEN —
            # they must not read as statically rare) but do not
            # propagate mass, keeping the flow well-founded
            edge_prob[(f, t)] = max(edge_prob.get((f, t), 0.0), share)
            if (f, t) not in cfg.back_edges:
                prob[t] = prob.get(t, 0.0) + share

    slots = np.asarray(program.edge_slot)
    ef = np.asarray(program.edge_from)
    et = np.asarray(program.edge_to)
    out: Dict[int, float] = {}
    for i in range(len(slots)):
        p = edge_prob.get((int(ef[i]), int(et[i])), 0.0)
        s = int(slots[i])
        out[s] = out.get(s, 0.0) + p
    return out
