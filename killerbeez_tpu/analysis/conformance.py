"""Counterexample-guided proxy conformance: gap ingestion, replay
clustering, and divergence localization.

The hybrid tier (docs/HYBRID.md) mints one ``kbz-proxy-gap-v1``
report per input where the KBVM proxy and the real binary disagree.
Each report is a concrete COUNTEREXAMPLE against the proxy program —
exactly what a CEGAR-style pass needs.  This module is the analysis
half of that loop (repair.py is the synthesis half):

  1. **ingestion** — :func:`parse_gap_report` validates accumulated
     reports against the schema contract (added keys tolerated,
     ``schema`` gates parsing; PR 17-shaped reports without
     ``input_hex`` parse but are counted unreplayable, never
     silently dropped).
  2. **replay clustering** — :func:`replay_gaps` re-executes every
     replayable counterexample through the lockstep reference
     interpreter (solver.concrete_run, shared trace cache) and
     clusters by (final trace edge, proxy verdict class): one
     cluster ≈ one diverging guard.
  3. **localization** — :func:`localize` walks a cluster's traces
     backwards to the last branch whose outcome the native verdict
     contradicts, ranks blame candidates by the dataflow layer's
     per-branch dependency sets + guarding constants (Angora's
     byte-level-taint idea, arxiv 1803.01307, turned from search
     guidance into blame assignment), and emits a
     ``kbz-proxy-blame-v1`` record: branch pc, cmp, observed
     operands, gap inputs covered.
  4. **conformance lint** — :func:`conformance_lint` turns the gap
     directory's bookkeeping into kb-lint findings:
     ``proxy-gap-backlog`` (warning) when unconsumed counterexamples
     pile up, ``conformance-drift`` (error) when gaps recur on a
     site the repair ledger says was fixed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from .dataflow import BranchFact, DataflowResult, analyze_dataflow
from .lint import SEV_ERROR, SEV_WARNING, Finding
from .solver import ConcreteTrace, concrete_run

GAP_SCHEMA = "kbz-proxy-gap-v1"
BLAME_SCHEMA = "kbz-proxy-blame-v1"

#: unconsumed gap reports tolerated before the backlog lint fires
DEFAULT_BACKLOG_THRESHOLD = 8

#: blame candidates / observed operand samples kept per record
MAX_BLAME_CANDIDATES = 3
MAX_OBSERVED = 8


class GapParseError(ValueError):
    """A report that fails the ``kbz-proxy-gap-v1`` contract."""


def verdict_class(status: int) -> str:
    """FUZZ_* verdict -> the cross-tier verdict-class vocabulary."""
    if status == FUZZ_CRASH:
        return "crash"
    if status == FUZZ_HANG:
        return "hang"
    if status == FUZZ_NONE:
        return "ok"
    return "error"


@dataclass
class GapReport:
    """One parsed counterexample (validated ``kbz-proxy-gap-v1``)."""

    md5: str
    kind: str                       # "crash" | "hang"
    binding: str
    proxy_target: str
    proxy_status: int
    native_statuses: List[int]
    repro: int
    repeats: int
    t: Optional[float]
    #: concrete input bytes — None for PR 17-era reports (parse, but
    #: cannot be replayed as a counterexample)
    input: Optional[bytes] = None
    #: proxy-trace edge recorded at emit time (may be stale wrt the
    #: current program; replay recomputes)
    edge: Optional[Tuple[int, int]] = None
    raw: Dict[str, Any] = field(default_factory=dict, repr=False)

    @property
    def native_cls(self) -> Optional[str]:
        """Majority native verdict class over the repeats, errors
        excluded; None when the native side never measured."""
        votes: Dict[str, int] = {}
        for s in self.native_statuses:
            if s == FUZZ_ERROR:
                continue
            c = verdict_class(s)
            votes[c] = votes.get(c, 0) + 1
        if not votes:
            return None
        return max(sorted(votes), key=lambda c: votes[c])

    @property
    def proxy_cls(self) -> str:
        return verdict_class(self.proxy_status)


def parse_gap_report(obj: Any) -> GapReport:
    """Validate one report dict; raises :class:`GapParseError` with a
    machine-greppable ``gap:<field>`` reason."""
    if not isinstance(obj, dict):
        raise GapParseError("gap:not-a-dict")
    if obj.get("schema") != GAP_SCHEMA:
        raise GapParseError(f"gap:schema {obj.get('schema')!r}")
    md5 = obj.get("md5")
    if not isinstance(md5, str) or not md5:
        raise GapParseError("gap:md5")
    kind = obj.get("kind")
    if kind not in ("crash", "hang"):
        raise GapParseError(f"gap:kind {kind!r}")
    binding = obj.get("binding")
    if not isinstance(binding, str) or not binding:
        raise GapParseError("gap:binding")
    proxy = obj.get("proxy")
    if not isinstance(proxy, dict) or \
            not isinstance(proxy.get("target"), str) or \
            not isinstance(proxy.get("status"), int):
        raise GapParseError("gap:proxy")
    native = obj.get("native")
    if not isinstance(native, dict):
        raise GapParseError("gap:native")
    statuses = native.get("statuses")
    if not isinstance(statuses, list) or \
            not all(isinstance(s, int) for s in statuses):
        raise GapParseError("gap:native.statuses")
    try:
        repro = int(native.get("repro", 0))
        repeats = int(native.get("repeats", 0))
    except (TypeError, ValueError):
        raise GapParseError("gap:native.repro")
    t = obj.get("t")
    if t is not None and not isinstance(t, (int, float)):
        raise GapParseError("gap:t")
    buf: Optional[bytes] = None
    if "input_hex" in obj:
        try:
            buf = bytes.fromhex(obj["input_hex"])
        except (TypeError, ValueError):
            raise GapParseError("gap:input_hex")
    edge = None
    raw_edge = proxy.get("edge")
    if raw_edge is not None:
        if not (isinstance(raw_edge, (list, tuple))
                and len(raw_edge) == 2
                and all(isinstance(e, int) for e in raw_edge)):
            raise GapParseError("gap:proxy.edge")
        edge = (raw_edge[0], raw_edge[1])
    return GapReport(
        md5=md5, kind=kind, binding=binding,
        proxy_target=proxy["target"],
        proxy_status=int(proxy["status"]),
        native_statuses=[int(s) for s in statuses],
        repro=repro, repeats=repeats,
        t=float(t) if t is not None else None,
        input=buf, edge=edge, raw=obj)


def load_gap_reports(gaps_dir: str
                     ) -> Tuple[List[GapReport],
                                List[Tuple[str, str]]]:
    """Parse every report in a ``proxy_gaps/`` directory.  Returns
    ``(reports, rejects)`` where each reject is (filename, reason) —
    malformed files are surfaced, never silently skipped."""
    import json

    from ..hybrid.gaps import INDEX_FILE, LEDGER_FILE

    reports: List[GapReport] = []
    rejects: List[Tuple[str, str]] = []
    if not os.path.isdir(gaps_dir):
        return reports, rejects
    for name in sorted(os.listdir(gaps_dir)):
        if not name.endswith(".json") or \
                name in (INDEX_FILE, LEDGER_FILE):
            continue
        try:
            with open(os.path.join(gaps_dir, name),
                      encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            rejects.append((name, f"gap:json {type(e).__name__}"))
            continue
        try:
            reports.append(parse_gap_report(obj))
        except GapParseError as e:
            rejects.append((name, str(e)))
    return reports, rejects


# --------------------------------------------------------------------
# replay clustering
# --------------------------------------------------------------------

@dataclass
class GapCluster:
    """Counterexamples that replay down the same proxy path tail."""

    #: final (from-block, to-block) edge of the replayed trace
    edge: Optional[Tuple[int, int]]
    #: the proxy's verdict class on these inputs (replayed)
    proxy_cls: str
    #: the native tier's verdict class the proxy must be bent toward
    native_cls: str
    reports: List[GapReport] = field(default_factory=list)
    traces: List[ConcreteTrace] = field(default_factory=list)


@dataclass
class ReplayResult:
    clusters: List[GapCluster]
    #: replayed clean: current proxy already agrees with the native
    #: verdict (e.g. the program was repaired since the report)
    stale: List[GapReport] = field(default_factory=list)
    #: not replayable: no input bytes, or native never measured —
    #: (report, reason) pairs, counted, never silently dropped
    skipped: List[Tuple[GapReport, str]] = field(default_factory=list)


def replay_gaps(program, reports: List[GapReport],
                trace_cache: Optional[Dict[bytes, ConcreteTrace]]
                = None) -> ReplayResult:
    """Replay every replayable counterexample through the reference
    interpreter and cluster divergences by (final trace edge, proxy
    verdict class) — one cluster per suspected diverging guard.

    ``trace_cache`` follows the crack/search-tier convention
    (Dict[bytes, ConcreteTrace]) so repeated passes share replays."""
    if trace_cache is None:
        trace_cache = {}
    out = ReplayResult(clusters=[])
    by_key: Dict[Tuple, GapCluster] = {}
    for rep in reports:
        if rep.input is None:
            out.skipped.append((rep, "no-input"))
            continue
        native_cls = rep.native_cls
        if native_cls is None:
            out.skipped.append((rep, "native-never-measured"))
            continue
        buf = rep.input
        trace = trace_cache.get(buf)
        if trace is None:
            trace = concrete_run(program, buf)
            trace_cache[buf] = trace
        proxy_cls = verdict_class(trace.status)
        if proxy_cls == native_cls:
            out.stale.append(rep)
            continue
        edge = tuple(trace.edges[-1]) if trace.edges else None
        key = (edge, proxy_cls, native_cls)
        cluster = by_key.get(key)
        if cluster is None:
            cluster = GapCluster(edge=edge, proxy_cls=proxy_cls,
                                 native_cls=native_cls)
            by_key[key] = cluster
            out.clusters.append(cluster)
        cluster.reports.append(rep)
        cluster.traces.append(trace)
    return out


# --------------------------------------------------------------------
# divergence localization
# --------------------------------------------------------------------

def _input_dependent(fact: Optional[BranchFact]) -> bool:
    """A branch whose outcome can depend on the input at all: taint
    top (deps is ANY=None), a nonempty byte set, or a length
    dependency.  Constant-only branches cannot explain an
    input-specific divergence."""
    if fact is None:
        return True         # unknown to dataflow: cannot rule it out
    if fact.deps is None:
        return True         # ANY — taint top
    if fact.deps:
        return True
    return bool(fact.len_dep)


@dataclass
class BlameRecord:
    """One ``kbz-proxy-blame-v1`` record: the guard a cluster of
    counterexamples indicts, with evidence."""

    pc: int
    cmp: str
    block: int
    edge: Optional[Tuple[int, int]]
    proxy_cls: str
    native_cls: str
    #: guarding constant from dataflow (None when not constant)
    const: Optional[int]
    #: input byte positions the guard depends on (None = ANY)
    deps: Optional[List[int]]
    #: observed (x, y, taken) operand triples at the blamed branch
    observed: List[Tuple[int, int, bool]]
    #: md5s of the gap inputs this record covers
    inputs: List[str]
    #: runner-up blamed pcs, best first (bounded)
    candidates: List[int] = field(default_factory=list)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "schema": BLAME_SCHEMA,
            "pc": self.pc, "cmp": self.cmp, "block": self.block,
            "edge": list(self.edge) if self.edge else None,
            "proxy_cls": self.proxy_cls,
            "native_cls": self.native_cls,
            "const": self.const,
            "deps": self.deps,
            "observed": [[x, y, bool(tk)]
                         for x, y, tk in self.observed],
            "inputs": list(self.inputs),
            "candidates": list(self.candidates),
        }


def localize(program, cluster: GapCluster,
             dataflow: Optional[DataflowResult] = None
             ) -> Optional[BlameRecord]:
    """Blame assignment for one cluster: the LAST branch executed on
    the cluster's traces whose outcome the native verdict contradicts
    — i.e. the last input-dependent guard before the diverging tail.

    Candidates are ranked per trace by recency (closest to the
    divergence first), filtered to input-dependent branches via the
    dataflow layer's dependency sets, then voted across the cluster's
    traces.  Returns None when no trace executed any input-dependent
    branch (nothing to blame — the divergence is out of the branch
    model; repair reports it unrepairable)."""
    dataflow = dataflow or analyze_dataflow(program)
    facts: Dict[int, BranchFact] = {f.pc: f for f in
                                    dataflow.branches}
    # votes[pc] accumulates recency-weighted support across traces
    votes: Dict[int, float] = {}
    observed: Dict[int, List[Tuple[int, int, bool]]] = {}
    for trace in cluster.traces:
        rank = 0
        for pc, x, y, taken in reversed(trace.branches):
            if not _input_dependent(facts.get(pc)):
                continue
            votes[pc] = votes.get(pc, 0.0) + 1.0 / (1 + rank)
            obs = observed.setdefault(pc, [])
            if len(obs) < MAX_OBSERVED and (x, y, taken) not in obs:
                obs.append((x, y, taken))
            rank += 1
            if rank >= MAX_BLAME_CANDIDATES:
                break
    if not votes:
        return None
    ranked = sorted(votes, key=lambda pc: (-votes[pc], -pc))
    top = ranked[0]
    fact = facts.get(top)
    return BlameRecord(
        pc=top,
        cmp=fact.cmp if fact else "?",
        block=fact.block if fact else -1,
        edge=cluster.edge,
        proxy_cls=cluster.proxy_cls,
        native_cls=cluster.native_cls,
        const=fact.const if fact else None,
        deps=(sorted(fact.deps) if fact and fact.deps is not None
              else None),
        observed=observed.get(top, []),
        inputs=[r.md5 for r in cluster.reports],
        candidates=ranked[:MAX_BLAME_CANDIDATES])


# --------------------------------------------------------------------
# conformance lint (kb-lint --gaps-dir)
# --------------------------------------------------------------------

def conformance_lint(gaps_dir: str,
                     backlog_threshold: int =
                     DEFAULT_BACKLOG_THRESHOLD) -> List[Finding]:
    """Lint the gap directory's bookkeeping (no replay needed):

    * ``proxy-gap-backlog`` (warning) — more unconsumed gap reports
      than ``backlog_threshold``: counterexamples are piling up with
      no repair pass consuming them.
    * ``conformance-drift`` (error) — a gap report NEWER than a
      ledger entry that claims its (binding, edge) site repaired:
      the repaired proxy regressed, or the unrepaired one is still
      deployed.
    """
    from ..hybrid.gaps import GapIndex, load_ledger

    out: List[Finding] = []
    index = GapIndex(gaps_dir)
    ledger = load_ledger(gaps_dir)
    consumed = set()
    for rec in ledger:
        for md5 in rec.get("consumed") or []:
            consumed.add(md5)
    backlog = [e for e in index.entries
               if e.get("md5") not in consumed]
    if len(backlog) > max(0, int(backlog_threshold)):
        bindings = sorted({e.get("binding") for e in backlog
                           if e.get("binding")})
        out.append(Finding(
            SEV_WARNING, "proxy-gap-backlog",
            f"{len(backlog)} unconsumed proxy-gap counterexamples "
            f"in {gaps_dir} (threshold {backlog_threshold}) — run "
            f"kb-repair to fold them into the proxy, or the hybrid "
            f"tier keeps paying the proxy_only tax",
            {"unconsumed": len(backlog),
             "threshold": int(backlog_threshold),
             "bindings": bindings,
             "binding": bindings[0] if bindings else None,
             "gaps_dir": gaps_dir}))
    # drift: repaired (binding, edge) sites with newer gap reports
    repaired: Dict[Tuple, float] = {}
    for rec in ledger:
        if rec.get("status") != "repaired":
            continue
        key = (rec.get("binding"),
               tuple(rec["edge"]) if rec.get("edge") else None)
        t = float(rec.get("t") or 0.0)
        repaired[key] = max(repaired.get(key, 0.0), t)
    for key, t_fixed in sorted(repaired.items(),
                               key=lambda kv: str(kv[0])):
        binding, edge = key
        newer = [e for e in index.entries
                 if e.get("binding") == binding
                 and (edge is None or
                      (e.get("edge") and tuple(e["edge"]) == edge))
                 and float(e.get("t") or 0.0) > t_fixed]
        if newer:
            out.append(Finding(
                SEV_ERROR, "conformance-drift",
                f"binding {binding!r} edge {list(edge) if edge else '?'} "
                f"was repaired at t={t_fixed:.0f} but "
                f"{len(newer)} newer gap report(s) hit the same "
                f"site — the repair regressed or was never "
                f"installed",
                {"binding": binding,
                 "edge": list(edge) if edge else None,
                 "repaired_t": t_fixed,
                 "newer": [e.get("md5") for e in newer][:8],
                 "gaps_dir": gaps_dir}))
    out.sort(key=lambda f: 0 if f.severity == SEV_ERROR else 1)
    return out
