"""Crash-consistent campaign checkpoints — one atomic epoch.

Before this module the campaign's durable state was scattered across
files written at different times: ``campaign.json`` (scheduler +
counters), ``solver.json`` (crack verdicts), ``mutator.state`` /
``instrumentation.state`` (component resume state), plus the
``events.jsonl`` seq implicit in the log tail.  Each write was
individually atomic, but a kill BETWEEN writes left them mutually
inconsistent — e.g. a kill after the corpus persist but before the
solver-cache save forgets crack verdicts the corpus already reflects,
and the next plateau re-solves (or re-injects) them.

``checkpoint.json`` replaces that with one document written in one
``tmp + fsync + rename`` step under a **monotone epoch counter**::

    {"epoch": N, "saved_at": t,
     "campaign":   {...},      # what campaign.json used to hold
     "solver":     {...},      # what solver.json used to hold
     "event_seq":  M,          # events.jsonl high-water at save time
     "components": {"mutator": "...", "instrumentation": "..."}}

A kill at ANY instruction leaves either the previous epoch or the new
one — never a blend.  Two extra defenses, both pinned by the chaos
suite:

  * before each save the current file is hardlinked to
    ``checkpoint.json.prev``, so even a filesystem that tears the
    rename itself (or a chaos ``torn`` fault writing garbage straight
    over the live file) falls back to the last good epoch;
  * ``load`` validates shape + epoch and silently steps back through
    ``.prev`` on any parse failure.

Legacy files remain readable: loaders in ``CorpusStore`` fall back to
``campaign.json`` / ``solver.json`` / ``*.state`` when no checkpoint
exists (a pre-checkpoint campaign resumes fine), and offline tools
keep working against either layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from ..utils.logging import WARNING_MSG

CHECKPOINT_FILE = "checkpoint.json"
PREV_SUFFIX = ".prev"

#: current checkpoint document version
VERSION = 1


def _paths(root: str):
    p = os.path.join(root, CHECKPOINT_FILE)
    return p, p + PREV_SUFFIX


def _read_doc(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "epoch" not in doc:
        return None
    return doc


def load(root: str) -> Optional[Dict[str, Any]]:
    """The newest readable checkpoint: the live file, else the
    ``.prev`` fallback (torn-write healing), else None."""
    live, prev = _paths(root)
    for p in (live, prev):
        doc = _read_doc(p)
        if doc is not None:
            if p == prev:
                WARNING_MSG("checkpoint: %s unreadable; resumed from "
                            "previous epoch %s", live, doc.get("epoch"))
            return doc
    return None


def last_epoch(root: str) -> int:
    doc = load(root)
    return int(doc.get("epoch", 0)) if doc else 0


def save(root: str, doc: Dict[str, Any],
         atomic_write=None) -> Optional[int]:
    """Write one checkpoint epoch atomically; returns the epoch
    number (None when the write failed — persistence degrades to
    warnings, it must never kill a campaign).

    ``atomic_write(path, bytes)`` is injected by the corpus store so
    the chaos harness's ``persist`` point covers this path exactly
    like every other store write."""
    live, prev = _paths(root)
    epoch = int(doc.get("epoch") or 0)
    if epoch <= 0:
        epoch = last_epoch(root) + 1
    doc = dict(doc)
    doc["version"] = doc.get("version", VERSION)
    doc["epoch"] = epoch
    # keep the CURRENT epoch reachable while the new one replaces the
    # live file: hardlink (same directory, so same filesystem); a
    # kill between the link and the rename leaves .prev == live,
    # which load() handles (same doc twice).  Only a live file that
    # PARSES may refresh .prev — linking an unvalidated (torn) live
    # file would destroy the last good epoch, and a kill before the
    # rename would then leave NO readable checkpoint at all
    if _read_doc(live) is not None:
        try:
            tmp_link = prev + ".tmp"
            try:
                os.unlink(tmp_link)
            except OSError:
                pass
            os.link(live, tmp_link)
            os.replace(tmp_link, prev)
        except OSError:
            pass                        # no .prev safety net this epoch
    if atomic_write is None:
        atomic_write = _default_atomic_write
    try:
        atomic_write(live, json.dumps(doc).encode())
    except OSError as e:
        WARNING_MSG("checkpoint write failed (epoch %d): %s", epoch, e)
        return None
    return epoch


def _default_atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
