"""Dispatch watchdog — a deadline on every blocking device wait.

ROADMAP item 1 (device-resident loop) will make hangs *harder* to see
from the host: once the TPU runs N generations per round trip, the
only host-visible symptom of a wedged device is a ``block_until_ready``
(or a blocking ``np.asarray`` on a lazy device array) that never
returns.  Python cannot interrupt that wait — the GIL is released
inside the runtime, but no exception can be delivered into it — so
the only honest escalation is: record what was in flight, then kill
the process and let the supervisor restart into ``--resume``.

Mechanics: the fuzzing loop wraps each blocking region in
``watchdog.guard(stage)``; a monitor thread checks the armed deadline
and, when it expires, (1) emits a ``watchdog_stall`` campaign event,
(2) calls the loop's dump hook (in-flight pipeline lane state +
flight-recorder export — the post-mortem artifact), then (3) runs the
escalation action, by default ``os._exit(WATCHDOG_EXIT_CODE)`` so the
supervisor classifies the exit as a watchdog kill.

The deadline scales with the measured batch time so slow targets
don't false-positive and fast ones don't wait minutes: it is
``multiplier x EMA batch seconds`` clamped to ``[min_deadline,
max_deadline]``.  EMA batch seconds prefers the telemetry registry's
``execs`` EMA rate (batch_size / rate — the same number kb-stats
shows), falling back to the watchdog's own EMA of observed guarded
waits until the registry has weight.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from . import WATCHDOG_EXIT_CODE
from ..utils.logging import CRITICAL_MSG, WARNING_MSG


class DispatchWatchdog:
    """Deadline monitor for one fuzzing loop's blocking device waits.

    ``guard(stage)`` is the loop-facing API::

        with watchdog.guard("host_transfer"):
            arr = np.asarray(packed)        # may block on the device

    ``note_batch(n)`` tells the deadline model the loop's batch size
    (needed to turn the registry's execs/sec EMA into seconds/batch).
    """

    #: monitor poll cadence; the deadline guarantee is
    #: ``deadline + _TICK`` worst case, well inside the 2x bound the
    #: chaos suite pins
    _TICK = 0.25

    def __init__(self, registry=None, multiplier: float = 8.0,
                 min_deadline: float = 5.0,
                 max_deadline: float = 120.0,
                 telemetry=None,
                 dump_fn: Optional[Callable] = None,
                 action: Optional[Callable] = None):
        self.registry = registry
        self.multiplier = float(multiplier)
        self.min_deadline = float(min_deadline)
        self.max_deadline = max(float(max_deadline), self.min_deadline)
        self.telemetry = telemetry
        self.dump_fn = dump_fn
        self.action = action if action is not None \
            else (lambda: os._exit(WATCHDOG_EXIT_CODE))
        self.batch_size = 0
        self.stalls = 0
        #: batches one dispatch legitimately covers (--generations:
        #: a G-generation dispatch waits ~G x one batch, so guards
        #: arm G x the per-batch deadline — without this the mode
        #: false-positives exit 86 by construction)
        self.dispatch_scale = 1.0
        self._ema_batch_s = 0.0         # fallback when registry is cold
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._armed_deadline = 0.0
        self._armed_stage = ""
        self._armed_scale = 1.0
        self._thread: Optional[threading.Thread] = None
        self._halt = threading.Event()

    # -- deadline model --------------------------------------------------

    def note_batch(self, n: int) -> None:
        self.batch_size = int(n)

    def note_dispatch_scale(self, k: float) -> None:
        """Effective batches (generations) per device dispatch: the
        next guards arm ``k x`` the per-batch deadline — and the
        ceiling scales too, else a large G would be clamped back to
        a one-batch budget and false-positive anyway.  Observed waits
        fold into the per-batch EMA divided by ``k`` so the estimate
        stays per-batch across mode switches."""
        self.dispatch_scale = max(float(k), 1.0)

    def ema_batch_seconds(self) -> float:
        """Best estimate of one batch's wall time: the registry's
        execs EMA (authoritative once warm), else the watchdog's own
        EMA of guarded waits."""
        reg = self.registry
        if reg is not None and self.batch_size > 0:
            r = reg.rates.get("execs")
            if r is not None and r.weight > 0.1 and r.rate > 0:
                return self.batch_size / r.rate
        return self._ema_batch_s

    def deadline(self) -> float:
        est = self.ema_batch_seconds()
        scale = self.dispatch_scale
        if est <= 0:
            # cold start: the first dispatch includes XLA compilation,
            # which dwarfs any steady-state batch — grant the ceiling
            # until a real batch time has been observed (a genuinely
            # wedged FIRST dispatch still dies, just at max_deadline)
            return self.max_deadline * scale
        return min(max(self.multiplier * est * scale,
                       self.min_deadline),
                   self.max_deadline * scale)

    # -- arming ----------------------------------------------------------

    def guard(self, stage: str) -> "_Guard":
        return _Guard(self, stage)

    def _arm(self, stage: str) -> None:
        if self._thread is None or not self._thread.is_alive():
            # (re)start the monitor: stop() at run end parks it, and
            # repeated run() calls (bench loops) re-arm cleanly
            self._halt = threading.Event()
            self._thread = threading.Thread(
                target=self._monitor, name="kbz-watchdog", daemon=True)
            self._thread.start()
        with self._lock:
            self._armed_stage = stage
            self._armed_deadline = self.deadline()
            self._armed_at = time.monotonic()
            self._armed_scale = self.dispatch_scale

    def _disarm(self) -> None:
        with self._lock:
            t0 = self._armed_at
            scale = self._armed_scale
            self._armed_at = None
        if t0 is not None:
            # the guarded wait IS (an upper bound on) the batch time
            # — per effective batch: a G-generation dispatch's wait
            # divides by G so the EMA stays per-batch; a 0.2 alpha
            # tracks regime changes within ~5 batches
            waited = (time.monotonic() - t0) / max(scale, 1.0)
            self._ema_batch_s += 0.2 * (waited - self._ema_batch_s)

    def stop(self) -> None:
        self._halt.set()

    # -- the monitor -----------------------------------------------------

    def _monitor(self) -> None:
        while not self._halt.wait(self._TICK):
            with self._lock:
                t0 = self._armed_at
                deadline = self._armed_deadline
                stage = self._armed_stage
            if t0 is None:
                continue
            waited = time.monotonic() - t0
            if waited < deadline:
                continue
            self._stall(stage, waited, deadline)
            return                      # one stall ends the process

    def _stall(self, stage: str, waited: float,
               deadline: float) -> None:
        """Deadline blown: record, dump, escalate.  Runs on the
        monitor thread — the main thread is the thing that is stuck."""
        self.stalls += 1
        CRITICAL_MSG(
            "watchdog: %s stalled %.1fs (deadline %.1fs, ema batch "
            "%.3fs) — dumping in-flight state and escalating",
            stage, waited, deadline, self.ema_batch_seconds())
        if self.telemetry is not None:
            try:
                self.telemetry.event(
                    "watchdog_stall", stage=stage,
                    waited_s=round(waited, 3),
                    deadline_s=round(deadline, 3),
                    batch_size=int(self.batch_size))
            except Exception as e:
                WARNING_MSG("watchdog: stall event failed: %s", e)
        if self.dump_fn is not None:
            try:
                self.dump_fn(stage, waited, deadline)
            except Exception as e:
                WARNING_MSG("watchdog: state dump failed: %s", e)
        self.action()


class _Guard:
    __slots__ = ("wd", "stage")

    def __init__(self, wd: DispatchWatchdog, stage: str):
        self.wd = wd
        self.stage = stage

    def __enter__(self) -> "_Guard":
        self.wd._arm(self.stage)
        return self

    def __exit__(self, *exc) -> None:
        self.wd._disarm()
