"""Deterministic fault injection — the chaos harness.

Killerbeez's manager tier was designed against workers that die
constantly; the only way to keep that property true here is to make
the deaths cheap to produce.  This module plants named **chaos
points** at every seam where the real world fails — device dispatch,
the blocking device wait, every persistence write, manager RPC — and
fires configured faults at them deterministically, so a test (or an
operator) can replay the exact same failure at the exact same
instruction across runs.

A chaos point is a module-level call that compiles to one attribute
read when chaos is off::

    from ..resilience.chaos import chaos_point
    chaos_point("device_dispatch")          # no-op unless configured

Configuration is a JSON spec (``--chaos`` on the fuzzer CLI, the
``KBZ_CHAOS`` environment variable for child processes, or
``configure()`` from tests)::

    {"seed": 7,
     "faults": [
       {"point": "device_dispatch", "mode": "raise",  "hit": 12},
       {"point": "device_wait",     "mode": "hang",   "hit": 5,
        "seconds": 30},
       {"point": "persist",         "mode": "kill",   "prob": 0.05},
       {"point": "persist",         "mode": "torn",   "hit": 3},
       {"point": "manager_rpc",     "mode": "http500", "every": 3}]}

Triggers (exactly one per fault; ``hit`` defaults to 1):

  * ``hit: N``   — fire on the Nth hit of that point (1-based), once.
  * ``every: N`` — fire on every Nth hit.
  * ``prob: p``  — fire per hit with probability ``p`` from the
                   spec-seeded RNG (deterministic given the seed and
                   the hit sequence).

Modes (what firing does):

  * ``raise``   — raise :class:`XlaRuntimeError` with a DEVICE_LOST
                  message (the supervisor classifies it device-lost).
  * ``hang``    — sleep ``seconds`` (default 3600): a stuck dispatch
                  for the watchdog to kill.
  * ``enospc``  — raise ``OSError(ENOSPC)``: disk full.
  * ``torn``    — write HALF the payload straight to the final path
                  (bypassing the temp+rename discipline), then raise:
                  the torn in-place write every loader must survive.
  * ``kill``    — ``SIGKILL`` this process: the mid-write power cut.
  * ``http500`` — raise ``urllib.error.HTTPError(500)``: the manager
                  saw the request and failed.
  * ``timeout`` — raise ``urllib.error.URLError``: network partition.
  * ``partition`` — like ``timeout``, but SCOPED: the fault carries a
                  ``match`` substring and only severs requests whose
                  URL (the ``url=`` context at the chaos point)
                  contains it, so a spec can cut one worker off from
                  the manager while its peer gossip keeps flowing, or
                  sever exactly one peer edge out of a mesh.  A
                  fleet-sim harness typically installs it with
                  ``every: 1`` (total blackout of the matched
                  endpoint) and clears it by reconfiguring.

``match`` may also scope any other mode: a fault with ``match`` set
only counts and fires on hits whose ``url`` context contains the
substring (hit counting stays deterministic given the URL sequence).

Registered chaos points (grep for ``chaos_point(`` to verify):

  ``device_dispatch`` (loop, before each device batch dispatch),
  ``device_wait`` (loop, before each blocking host transfer),
  ``persist`` (corpus store ``_atomic_write``: entries, sidecars,
  checkpoint, campaign/solver state), ``fs_write`` (finding files),
  ``event_append`` (events.jsonl), ``manager_rpc`` (every worker /
  sync / heartbeat / peer-gossip HTTP request), ``gossip_serve``
  (gossip sidecar, before serving each inbound peer request),
  ``manager_db_write`` (manager, before every DB mutation — the
  degraded-mode seam).
"""

from __future__ import annotations

import errno
import json
import os
import random
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import WARNING_MSG


class XlaRuntimeError(RuntimeError):
    """Chaos stand-in for ``jax.errors.JaxRuntimeError`` /
    ``xla_extension.XlaRuntimeError`` — same NAME on purpose, so exit
    classification (``resilience.is_device_loss``) exercises the same
    string match it applies to the real thing."""


MODES = ("raise", "hang", "enospc", "torn", "kill", "http500",
         "timeout", "partition")


class _Fault:
    __slots__ = ("point", "mode", "hit", "every", "prob", "seconds",
                 "match", "seen", "fired")

    def __init__(self, spec: Dict[str, Any]):
        self.point = str(spec["point"])
        self.mode = str(spec.get("mode", "raise"))
        if self.mode not in MODES:
            raise ValueError(f"chaos: unknown mode {self.mode!r} "
                             f"(one of {', '.join(MODES)})")
        self.hit = spec.get("hit")
        self.every = spec.get("every")
        self.prob = spec.get("prob")
        if self.hit is None and self.every is None and self.prob is None:
            self.hit = 1
        self.seconds = float(spec.get("seconds", 3600.0))
        #: endpoint scoping: only hits whose ``url`` context contains
        #: this substring count toward (and fire) this fault — how a
        #: ``partition`` severs one named peer/manager endpoint while
        #: the rest of the fleet's traffic flows
        self.match = spec.get("match")
        if self.match is not None:
            self.match = str(self.match)
        self.seen = 0        # per-fault hit count (match-scoped only)
        self.fired = 0

    def matches(self, ctx: Dict[str, Any]) -> bool:
        if self.match is None:
            return True
        return self.match in str(ctx.get("url", ""))

    def should_fire(self, n: int, rng: random.Random) -> bool:
        if self.hit is not None:
            return n == int(self.hit)
        if self.every is not None:
            return int(self.every) > 0 and n % int(self.every) == 0
        return rng.random() < float(self.prob)


class ChaosEngine:
    """One configured fault table: counts hits per point, fires the
    matching faults.  Thread-safe (heartbeat/watchdog threads hit
    chaos points too); the counters themselves are the determinism
    anchor, so specs should target points hit from ONE thread when
    exact replay matters."""

    def __init__(self, spec: Dict[str, Any]):
        self.rng = random.Random(int(spec.get("seed", 0)))
        self.faults: List[_Fault] = [
            _Fault(f) for f in spec.get("faults", [])]
        self.hits: Dict[str, int] = {}
        self._lock = threading.Lock()

    def hit(self, point: str, **ctx) -> None:
        with self._lock:
            n = self.hits[point] = self.hits.get(point, 0) + 1
            due = []
            for f in self.faults:
                if f.point != point or not f.matches(ctx):
                    continue
                # match-scoped faults count their own hits (the point
                # counter mixes every endpoint's traffic; a scoped
                # fault's trigger must be deterministic given only the
                # MATCHED request sequence)
                if f.match is not None:
                    f.seen += 1
                if f.should_fire(f.seen if f.match is not None else n,
                                 self.rng):
                    f.fired += 1
                    due.append(f)
        for f in due:
            self._fire(f, point, n, ctx)

    # -- the faults themselves ------------------------------------------

    def _fire(self, f: _Fault, point: str, n: int,
              ctx: Dict[str, Any]) -> None:
        WARNING_MSG("chaos: firing %s at %s (hit %d)", f.mode, point, n)
        if f.mode == "raise":
            raise XlaRuntimeError(
                f"DEVICE_LOST: chaos-injected device failure at "
                f"{point} hit {n}")
        if f.mode == "hang":
            time.sleep(f.seconds)
            return
        if f.mode == "enospc":
            raise OSError(errno.ENOSPC,
                          f"chaos: No space left on device ({point})")
        if f.mode == "torn":
            path, data = ctx.get("path"), ctx.get("data")
            if path is not None and data:
                try:
                    with open(path, "wb") as fh:   # IN PLACE: the tear
                        fh.write(bytes(data)[:max(1, len(data) // 2)])
                except OSError:
                    pass
            raise OSError(errno.EIO, f"chaos: torn write ({point})")
        if f.mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
            return                                  # unreachable
        if f.mode == "http500":
            import urllib.error
            raise urllib.error.HTTPError(
                str(ctx.get("url", point)), 500,
                "chaos: injected server error", None, None)
        if f.mode == "timeout":
            import urllib.error
            raise urllib.error.URLError(
                f"chaos: injected network partition ({point})")
        if f.mode == "partition":
            import urllib.error
            raise urllib.error.URLError(
                f"chaos: partitioned from "
                f"{ctx.get('url', point)} ({point})")

    def state(self) -> Dict[str, Any]:
        return {"hits": dict(self.hits),
                "fired": {f"{f.point}/{f.mode}": f.fired
                          for f in self.faults}}


_engine: Optional[ChaosEngine] = None


def configure(spec) -> Optional[ChaosEngine]:
    """Install (or clear) the process-wide chaos engine.  ``spec`` is
    a dict, a JSON string, ``@path`` to a JSON file, or None/''/falsy
    to disable.  Returns the engine (None when disabled)."""
    global _engine
    if not spec:
        _engine = None
        return None
    if isinstance(spec, str):
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        spec = json.loads(spec)
    if not isinstance(spec, dict):
        raise ValueError("chaos: spec must be a JSON object")
    _engine = ChaosEngine(spec)
    return _engine


def configure_from_env() -> Optional[ChaosEngine]:
    """Pick up ``KBZ_CHAOS`` (how a supervisor injects faults into
    one child launch without touching its argv)."""
    return configure(os.environ.get("KBZ_CHAOS"))


def active() -> Optional[ChaosEngine]:
    return _engine


def chaos_point(name: str, **ctx) -> None:
    """Fire any faults due at this seam.  One attribute read when
    chaos is off — safe on hot paths."""
    if _engine is not None:
        _engine.hit(name, **ctx)
