"""Simulated fleet — the chaos convergence rig.

PR 8's chaos harness proves ONE campaign survives kills and torn
writes; this module proves the FLEET converges: tens to ~100
in-process workers, each with a real corpus store, a real
:class:`~killerbeez_tpu.corpus.gossip.GossipSync` client (sidecar
HTTP server included) and a real event stream, exchanging corpus
entries through a real manager — while the test injects manager
SIGKILLs, scoped network partitions and poisoned entries between
rounds.

The workers are *simulated* only in that they do not run the fuzzing
loop: each mints deterministic synthetic edge-novel findings instead
(seeded per worker, unique coverage signatures), because the thing
under test is the EXCHANGE tier — admission, gossip, quarantine,
journal, convergence — not the mutator.  Everything from
``note_entry`` on down is the production path.

Convergence invariant (the fleet-chaos CI gate): after the faults
heal and enough rounds pass, every worker's admitted ``cov_hash``
set equals the fault-free control's union, the manager's corpus
table covers that union, and each worker's event stream is stored
gapless and duplicate-free — no finding and no event is lost to a
dead hub, a partition, or a poisoned peer.
"""

from __future__ import annotations

import base64
import os
import random
import time
from typing import Any, Dict, List, Optional, Set

from ..corpus.gossip import GossipSync
from ..corpus.quarantine import PeerBans
from ..corpus.schedule import Arm, make_scheduler
from ..corpus.store import CorpusEntry, CorpusStore
from ..telemetry import Telemetry
from ..utils.fileio import md5_hex
from ..utils.logging import DEBUG_MSG


class SimWorker:
    """One in-process fleet worker: real store + scheduler + gossip
    client + event queue, synthetic discoveries.

    Quacks like the ``Fuzzer`` where the sync client needs it
    (``telemetry``, ``scheduler``, ``store``, ``_seen``,
    ``feedback``) — the exchange tier cannot tell it from the real
    loop."""

    def __init__(self, name: str, campaign: str, manager_url: str,
                 root: str, fanout: int = 2, seed: int = 0,
                 ban_threshold: int = 3,
                 peer_refresh_rounds: int = 1):
        self.name = name
        self.campaign = str(campaign)
        self.manager_url = manager_url.rstrip("/")
        self.telemetry = Telemetry(None)
        self.scheduler = make_scheduler("rr")
        self.scheduler.base_seed = b"SIM"
        self.store = CorpusStore(os.path.join(root, name))
        self._seen: Dict[str, Set[str]] = {"new_paths": set()}
        self.feedback = 1
        self.rng = random.Random(hash((seed, name)) & 0x7FFFFFFF)
        self.sync = GossipSync(
            manager_url, campaign, worker=name, interval_s=0.0,
            attempts=1, rng=self.rng, fanout=fanout,
            # sim rounds are fast and scripted: refresh the directory
            # every round while the hub answers (failures keep the
            # cache — that IS the partition-tolerance under test)
            peer_refresh_rounds=peer_refresh_rounds,
            bans=PeerBans(threshold=ban_threshold, base_s=30.0,
                          rng=self.rng))
        self.sync.sidecar.attach_store(self.store)
        self._find_n = 0
        self._poison_n = 0
        #: worker-minted event records awaiting a successful POST to
        #: the manager (monotone seq; re-sends are dedup-safe)
        self._event_seq = 0
        self._events_pending: List[Dict[str, Any]] = []
        self.events_acked = 0

    # -- synthetic discovery -------------------------------------------

    def discover(self, n: int = 1) -> List[CorpusEntry]:
        """Mint ``n`` deterministic edge-novel findings and run them
        through the production admission path (store write-through,
        sync note, scheduler admission, event record)."""
        out = []
        for _ in range(int(n)):
            i = self._find_n
            self._find_n += 1
            buf = f"{self.name}:find:{i}".encode()
            # unique, deterministic coverage: no two synthetic
            # findings (across the whole fleet) share a cov_hash
            base = int.from_bytes(
                md5_hex(buf)[:8].encode(), "big") % 1000003
            sig = sorted({base, 1000100 + i * 131 + len(self.name)})
            entry = CorpusEntry(buf, seq=self.store.next_seq(),
                                sig=sig, parent="base",
                                source="local")
            self.store.put(entry)
            self._seen["new_paths"].add(entry.md5)
            self.scheduler.admit(Arm.from_entry(entry))
            self.sync.note_entry(entry)
            self._queue_event("new_path", md5=entry.md5,
                              cov_hash=entry.cov_hash)
            out.append(entry)
        return out

    def poison(self, n: int = 1) -> List[str]:
        """EVIL MODE: publish ``n`` forged rows straight into this
        worker's sidecar — valid bytes, FORGED cov_hash — bypassing
        every honest path.  Returns the forged hashes so the test can
        assert none was ever admitted anywhere."""
        forged = []
        with self.sync.sidecar._lock:
            for _ in range(int(n)):
                # own counter: poisoning must not shift the honest
                # discovery sequence (the control run never poisons,
                # and the convergence gate compares unions exactly)
                i = self._poison_n
                self._poison_n += 1
                buf = f"{self.name}:poison:{i}".encode()
                fake = f"sig:{md5_hex(buf)}"     # never re-derivable
                forged.append(fake)
                self.sync.sidecar._rows.append({
                    "id": len(self.sync.sidecar._rows) + 1,
                    "md5": md5_hex(buf),
                    "cov_hash": fake,
                    "worker": self.name,
                    "content_b64":
                        base64.b64encode(buf).decode(),
                    "meta": {"sig": [1], "cov_hash": fake,
                             "md5": md5_hex(buf)},
                })
        return forged

    # -- event stream ---------------------------------------------------

    def _queue_event(self, etype: str, **fields) -> None:
        rec = {"v": 1, "seq": self._event_seq, "t": time.time(),
               "type": etype}
        rec.update(fields)
        self._event_seq += 1
        self._events_pending.append(rec)

    def flush_events(self) -> bool:
        """POST pending events (through the manager_rpc chaos seam);
        pending survives failure and re-sends are dedup-safe."""
        if not self._events_pending:
            return True
        from ..manager.worker import _request_retry
        try:
            _request_retry(
                f"{self.manager_url}/api/events/{self.campaign}",
                {"worker": self.name,
                 "events": self._events_pending},
                attempts=1)
        except Exception as e:
            DEBUG_MSG("simworker %s event flush failed: %s",
                      self.name, e)
            return False
        self.events_acked += len(self._events_pending)
        self._events_pending = []
        return True

    # -- rounds / state -------------------------------------------------

    def round(self) -> None:
        """One exchange round: manager anti-entropy + peer gossip
        (the production ``maybe_sync``) then the event flush."""
        self.sync.maybe_sync(self, force=True)
        self.flush_events()

    def cov_hashes(self) -> Set[str]:
        """Every admitted cov_hash in this worker's durable store."""
        return {e.cov_hash for e in self.store.load()}

    @property
    def registry(self):
        return self.telemetry.registry

    def close(self) -> None:
        self.sync.close()


class SimFleet:
    """N workers on one campaign, driven round by round."""

    def __init__(self, n_workers: int, campaign: str,
                 manager_url: str, root: str, fanout: int = 2,
                 seed: int = 0, ban_threshold: int = 3,
                 peer_refresh_rounds: int = 1):
        self.campaign = str(campaign)
        self.workers: List[SimWorker] = [
            SimWorker(f"w{i:03d}", campaign, manager_url,
                      root, fanout=fanout, seed=seed + i,
                      ban_threshold=ban_threshold,
                      peer_refresh_rounds=peer_refresh_rounds)
            for i in range(int(n_workers))]

    def round(self, discoveries: int = 0,
              skip: Optional[Set[int]] = None) -> None:
        """One fleet round: each worker (minus ``skip``) mints
        ``discoveries`` findings then exchanges."""
        for i, w in enumerate(self.workers):
            if skip and i in skip:
                continue
            if discoveries:
                w.discover(discoveries)
            w.round()

    def rounds_until_converged(self, target: Set[str],
                               max_rounds: int = 64) -> int:
        """Exchange-only rounds until every worker's store holds
        ``target``; returns rounds used (== max_rounds means it never
        converged — the caller's assert then prints the holdouts)."""
        for r in range(int(max_rounds)):
            if all(target <= w.cov_hashes() for w in self.workers):
                return r
            self.round()
        return int(max_rounds)

    def union(self) -> Set[str]:
        out: Set[str] = set()
        for w in self.workers:
            out |= w.cov_hashes()
        return out

    def close(self) -> None:
        for w in self.workers:
            w.close()
