"""Campaign supervisor — ``kbz-supervise``.

The reference's manager/BOINC tier assumes workers die constantly and
campaigns survive anyway (PAPER.md §L3+); our TPU tier had the
opposite posture — one ``XlaRuntimeError``, a preempted slice, a
stuck dispatch or a mid-write SIGKILL killed the campaign and
recovery was a human typing ``--resume``.  The supervisor closes that
gap: it runs the fuzz loop as a CHILD process, classifies every exit,
and restarts into ``--resume`` with capped exponential backoff +
jitter — the same preemption-tolerant checkpoint/restart shape
training stacks use.

State machine (docs/RESILIENCE.md has the diagram)::

    LAUNCH -> RUNNING -> classify exit
      clean         -> DONE (exit 0)
      watchdog-kill -> BACKOFF -> RESTART (--resume)
      crash         -> BACKOFF -> RESTART (--resume)
      device-lost   -> PROBE (fresh process re-inits the JAX runtime)
                         devices >= need        -> BACKOFF -> RESTART
                         0 < devices < need     -> DEGRADE (mesh
                                                   dp-shrink) -> RESTART
                         none after probe budget-> FALLBACK argv
                                                   (native tier) or DONE

Exit classification:

  * rc 0                      -> clean
  * rc ``WATCHDOG_EXIT_CODE`` -> watchdog-kill (stuck dispatch; the
                                 child already dumped its state)
  * rc ``DEVICE_LOST_EXIT_CODE`` or a device-loss marker in the
    stderr tail              -> device-lost
  * anything else (including signals: rc < 0) -> crash

Device probing runs in a FRESH subprocess because a process that lost
its accelerator cannot re-initialize JAX in-place; a fresh child gets
a fresh runtime.  ``--probe-cmd`` overrides the probe (tests use
``echo N``; operators can point it at their platform's health check).

Usage::

    kbz-supervise [supervisor flags] -- file jit_harness havoc \
        -i '{"target": "tlvstack_vm"}' -sf seed -o out -n -1

Everything after ``--`` is the fuzzer argv (exactly what you would
pass to ``kbz-fuzzer``).  The supervisor injects ``--corpus-dir
<out>/corpus`` when absent (there must be something to resume) and
appends ``--resume`` from the second launch on.  Supervision history
is appended to ``<out>/supervisor.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shlex
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import (
    DEVICE_LOST_EXIT_CODE, WATCHDOG_EXIT_CODE, is_device_loss,
)
from .. import DEFAULT_BATCH_SIZE
from ..utils.logging import INFO_MSG, WARNING_MSG, setup_logging

#: exit classes
CLEAN, CRASH, DEVICE_LOST, WATCHDOG = \
    "clean", "crash", "device_lost", "watchdog"

#: default probe: count visible JAX devices in a fresh interpreter
_DEFAULT_PROBE = (
    f"{shlex.quote(sys.executable)} -c "
    "\"import jax; print(len(jax.devices()))\"")


def classify_exit(rc: int, stderr_tail: List[str]) -> str:
    """Map a child's return code + captured stderr tail onto an exit
    class.  Signals surface as negative rc from subprocess."""
    if rc == 0:
        return CLEAN
    if rc == WATCHDOG_EXIT_CODE:
        return WATCHDOG
    if rc == DEVICE_LOST_EXIT_CODE:
        return DEVICE_LOST
    if any(is_device_loss(line) for line in stderr_tail):
        return DEVICE_LOST
    return CRASH


def _arg_value(argv: List[str], *names: str,
               default: Optional[str] = None) -> Optional[str]:
    for i, a in enumerate(argv):
        if a in names and i + 1 < len(argv):
            return argv[i + 1]
    return default


def shrink_mesh(mesh: str, devices: int,
                batch: int = 0) -> Optional[str]:
    """Degrade a ``dp,mp`` mesh to fit ``devices`` chips by shrinking
    dp (candidate sharding degrades gracefully; mp is the coverage
    model partition and is not renegotiable here).  When ``batch`` is
    known, the new dp must also DIVIDE it — the sharded campaign
    driver rejects ``-b % dp != 0`` at startup, so a dp that merely
    fits the chips would turn one device loss into a restart crash
    loop.  Returns the new mesh string, the same one when it already
    fits, or None when no dp >= 1 satisfies both constraints."""
    try:
        dp, mp = (int(x) for x in mesh.split(","))
    except ValueError:
        return None
    limit = devices // mp if mp else 0
    for cand in range(min(dp, limit), 0, -1):
        if batch > 0 and batch % cand:
            continue
        return f"{cand},{mp}"
    return None


class Supervisor:
    """Run-classify-restart driver for one campaign."""

    def __init__(self, fuzzer_argv: List[str],
                 max_restarts: int = -1,
                 backoff_base: float = 1.0,
                 backoff_cap: float = 60.0,
                 healthy_after: float = 60.0,
                 probe_cmd: Optional[str] = None,
                 probe_attempts: int = 5,
                 fallback: Optional[str] = None,
                 chaos: Optional[str] = None,
                 chaos_launches: int = 1,
                 child_cmd: Optional[List[str]] = None,
                 rng: Optional[random.Random] = None,
                 sleep_fn=time.sleep):
        self.argv = list(fuzzer_argv)
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        #: a child that lived this long resets the backoff streak
        self.healthy_after = float(healthy_after)
        self.probe_cmd = probe_cmd or _DEFAULT_PROBE
        self.probe_attempts = int(probe_attempts)
        #: native-tier-only argv (string, shlex-split) used when no
        #: device ever comes back
        self.fallback = fallback
        #: chaos spec injected into the first ``chaos_launches``
        #: launches only (later restarts run clean — the harness
        #: tests recovery, not perpetual re-failure)
        self.chaos = chaos
        self.chaos_launches = int(chaos_launches)
        #: child command prefix (tests substitute a stub script)
        self.child_cmd = child_cmd or [sys.executable, "-m",
                                       "killerbeez_tpu.fuzzer"]
        self.rng = rng or random.Random()
        self.sleep = sleep_fn
        self.output_dir = _arg_value(self.argv, "-o", "--output",
                                     default="output")
        if "--corpus-dir" not in self.argv and \
                "--resume" not in self.argv:
            self.argv += ["--corpus-dir",
                          os.path.join(self.output_dir, "corpus")]
        self.restarts = 0
        self.launches = 0
        self.streak = 0                 # unhealthy exits in a row
        self.history: List[Dict[str, Any]] = []
        self._on_fallback = False

    # -- supervision log -------------------------------------------------

    def _log(self, event: str, **fields) -> None:
        rec = {"t": time.time(), "event": event}
        rec.update(fields)
        self.history.append(rec)
        try:
            os.makedirs(self.output_dir, exist_ok=True)
            with open(os.path.join(self.output_dir,
                                   "supervisor.jsonl"), "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        except OSError as e:
            WARNING_MSG("supervisor log append failed: %s", e)

    # -- one child launch ------------------------------------------------

    def _child_argv(self) -> List[str]:
        argv = list(self.argv)
        if self.launches > 0 and "--resume" not in argv:
            argv.append("--resume")
        return self.child_cmd + argv

    def launch_once(self) -> Tuple[int, List[str], float]:
        """Run the child to exit; returns (rc, stderr tail lines,
        lifetime seconds).  Stderr is teed: forwarded live to our
        stderr AND kept in a bounded tail for classification."""
        env = dict(os.environ)
        chaotic = bool(self.chaos
                       and self.launches < self.chaos_launches)
        if chaotic:
            env["KBZ_CHAOS"] = self.chaos
        else:
            env.pop("KBZ_CHAOS", None)
        argv = self._child_argv()
        self._log("launch", n=self.launches, argv=argv, chaos=chaotic)
        INFO_MSG("supervisor: launch %d: %s", self.launches,
                 " ".join(shlex.quote(a) for a in argv))
        t0 = time.monotonic()
        proc = subprocess.Popen(argv, stderr=subprocess.PIPE, env=env)
        tail: deque = deque(maxlen=64)

        def _tee():
            for raw in proc.stderr:
                try:
                    line = raw.decode(errors="replace")
                except Exception:
                    continue
                tail.append(line.rstrip("\n"))
                try:
                    sys.stderr.write(line)
                except OSError:
                    pass

        t = threading.Thread(target=_tee, daemon=True)
        t.start()
        rc = proc.wait()
        t.join(timeout=5)
        self.launches += 1
        return rc, list(tail), time.monotonic() - t0

    # -- backoff ---------------------------------------------------------

    def backoff_seconds(self) -> float:
        """Capped exponential on the unhealthy streak, with +-50%
        jitter so a preempted FLEET doesn't restart in lockstep."""
        base = min(self.backoff_cap,
                   self.backoff_base * (2 ** max(self.streak - 1, 0)))
        return base * (0.5 + self.rng.random())

    # -- device recovery -------------------------------------------------

    def probe_devices(self) -> int:
        """Count usable accelerator devices from a FRESH process (the
        only way to re-initialize the JAX runtime after a loss).
        Returns -1 when the probe itself fails."""
        try:
            out = subprocess.run(
                self.probe_cmd, shell=True, capture_output=True,
                text=True, timeout=120)
        except (subprocess.TimeoutExpired, OSError) as e:
            WARNING_MSG("supervisor: device probe failed: %s", e)
            return -1
        if out.returncode != 0:
            return -1
        try:
            return int(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return -1

    def _mesh_need(self) -> int:
        mesh = _arg_value(self.argv, "--mesh")
        if not mesh:
            return 1
        try:
            dp, mp = (int(x) for x in mesh.split(","))
            return dp * mp
        except ValueError:
            return 1

    def _handle_device_loss(self) -> bool:
        """Probe (with backoff) until devices return; degrade the
        mesh or fall back to the native-tier argv when they don't.
        Returns True when a restart is worth attempting."""
        need = self._mesh_need()
        for attempt in range(self.probe_attempts):
            n = self.probe_devices()
            self._log("device_probe", attempt=attempt, devices=n,
                      need=need)
            if n >= need:
                return True
            if n > 0:
                mesh = _arg_value(self.argv, "--mesh")
                if mesh:
                    # the shrunken dp must divide the campaign batch
                    # (the driver rejects -b % dp at startup); the
                    # rest of the argv — including -G/--generations —
                    # is preserved verbatim, only the --mesh value is
                    # rewritten in place
                    try:
                        batch = int(_arg_value(
                            self.argv, "-b", "--batch-size",
                            default=str(DEFAULT_BATCH_SIZE)) or 0)
                    except ValueError:
                        batch = 0
                    smaller = shrink_mesh(mesh, n, batch=batch)
                    if smaller and smaller != mesh:
                        # dp=4 -> dp=2: keep fuzzing on the chips
                        # that still answer instead of crash-looping
                        # on the dead one
                        i = self.argv.index("--mesh")
                        self.argv[i + 1] = smaller
                        self._log("degrade", mesh_from=mesh,
                                  mesh_to=smaller, devices=n)
                        WARNING_MSG(
                            "supervisor: %d/%d devices alive — mesh "
                            "degraded %s -> %s", n, need, mesh, smaller)
                        return True
                # single-chip campaign and at least one device: go
                return True
            self.streak += 1
            delay = self.backoff_seconds()
            WARNING_MSG("supervisor: no devices (probe %d/%d); "
                        "retrying in %.1fs", attempt + 1,
                        self.probe_attempts, delay)
            self.sleep(delay)
        if self.fallback and not self._on_fallback:
            # no device ever came back: hand the campaign to the
            # native tier (host forkserver) rather than abandoning it
            self._on_fallback = True
            old = self.argv
            self.argv = shlex.split(self.fallback)
            if "--corpus-dir" not in self.argv:
                self.argv += ["--corpus-dir",
                              os.path.join(self.output_dir, "corpus")]
            self._log("fallback", argv_from=old, argv_to=self.argv)
            WARNING_MSG("supervisor: no devices after %d probes — "
                        "falling back to native-tier argv",
                        self.probe_attempts)
            return True
        self._log("giveup", reason="no devices")
        return False

    # -- the supervision loop --------------------------------------------

    def run(self) -> int:
        self._log("start", argv=self.argv,
                  max_restarts=self.max_restarts)
        while True:
            rc, tail, lifetime = self.launch_once()
            cls = classify_exit(rc, tail)
            self._log("exit", rc=rc, **{"class": cls},
                      lifetime_s=round(lifetime, 3))
            INFO_MSG("supervisor: child exited rc=%d (%s) after "
                     "%.1fs", rc, cls, lifetime)
            if cls == CLEAN:
                self._log("done", restarts=self.restarts)
                return 0
            if lifetime >= self.healthy_after:
                self.streak = 0         # it WAS working; fresh budget
            if 0 <= self.max_restarts <= self.restarts:
                self._log("giveup", reason="restart budget",
                          restarts=self.restarts)
                WARNING_MSG("supervisor: restart budget (%d) "
                            "exhausted; giving up with rc=%d",
                            self.max_restarts, rc)
                return rc if rc > 0 else 1
            if cls == DEVICE_LOST:
                if not self._handle_device_loss():
                    return rc if rc > 0 else 1
            self.streak += 1
            self.restarts += 1
            delay = self.backoff_seconds()
            self._log("restart", n=self.restarts, backoff_s=
                      round(delay, 3), **{"class": cls})
            INFO_MSG("supervisor: restart %d (%s) in %.1fs",
                     self.restarts, cls, delay)
            self.sleep(delay)


# -- CLI ----------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kbz-supervise",
        description="run a fuzzing campaign under fault supervision: "
                    "classify child exits (clean / crash / "
                    "device-lost / watchdog-kill) and restart into "
                    "--resume with capped exponential backoff",
        epilog="everything after -- is the fuzzer argv, exactly as "
               "you would pass it to kbz-fuzzer")
    p.add_argument("--max-restarts", type=int, default=-1,
                   help="give up after this many restarts "
                        "(-1 = never, the default)")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first restart delay in seconds (default 1)")
    p.add_argument("--backoff-cap", type=float, default=60.0,
                   help="restart delay ceiling in seconds "
                        "(default 60)")
    p.add_argument("--healthy-after", type=float, default=60.0,
                   help="a child that lived this long resets the "
                        "backoff streak (default 60)")
    p.add_argument("--probe-cmd",
                   help="shell command printing the usable device "
                        "count after a device loss (default: count "
                        "jax.devices() in a fresh interpreter)")
    p.add_argument("--probe-attempts", type=int, default=5,
                   help="device probes before degrading/falling "
                        "back (default 5)")
    p.add_argument("--fallback",
                   help="fuzzer argv STRING to switch to when no "
                        "device returns (native-tier-only campaign "
                        "sharing the same corpus dir)")
    p.add_argument("--chaos",
                   help="chaos spec (JSON or @file) injected into "
                        "the first --chaos-launches launches via "
                        "KBZ_CHAOS; later restarts run clean — see "
                        "docs/RESILIENCE.md")
    p.add_argument("--chaos-launches", type=int, default=1,
                   help="how many launches receive the --chaos spec "
                        "(default 1: only the first)")
    p.add_argument("-l", "--logging-options",
                   help="logging JSON options")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        sup_args, fuzz_args = argv[:split], argv[split + 1:]
    else:
        sup_args, fuzz_args = [], argv
    args = build_parser().parse_args(sup_args)
    if not fuzz_args:
        print("error: no fuzzer argv (kbz-supervise [flags] -- "
              "<fuzzer args...>)", file=sys.stderr)
        return 2
    setup_logging(args.logging_options)
    sup = Supervisor(fuzz_args,
                     max_restarts=args.max_restarts,
                     backoff_base=args.backoff_base,
                     backoff_cap=args.backoff_cap,
                     healthy_after=args.healthy_after,
                     probe_cmd=args.probe_cmd,
                     probe_attempts=args.probe_attempts,
                     fallback=args.fallback,
                     chaos=args.chaos,
                     chaos_launches=args.chaos_launches)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
