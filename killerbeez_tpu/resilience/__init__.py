"""Campaign resilience subsystem — the manager/BOINC tier's fault
model (PAPER.md §L3+: workers die constantly, campaigns survive
anyway) brought to the TPU tier.

Four pieces:

  * ``chaos.py``      — deterministic fault injection at every seam
                        (device dispatch, persistence, manager RPC,
                        SIGKILL at randomized points); the test
                        harness that proves the rest works.
  * ``watchdog.py``   — dispatch watchdog: a deadline on every
                        blocking device wait, scaled from the EMA
                        batch time; a stuck dispatch dumps state and
                        escalates to a supervisor-mediated restart.
  * ``checkpoint.py`` — crash-consistent campaign checkpoints: ONE
                        atomic ``checkpoint.json`` epoch covering
                        scheduler/campaign state, solver cache, event
                        seq and component states, so a kill at any
                        instruction resumes consistent.
  * ``supervisor.py`` — ``kbz-supervise``: runs the fuzz loop as a
                        child, classifies exits (clean / crash /
                        device-lost / watchdog-kill) and restarts
                        into ``--resume`` with capped exponential
                        backoff, re-probing JAX devices on device
                        loss and degrading (mesh shrink, native-tier
                        fallback) when chips stay dead.
  * ``fleetsim.py``   — the fleet-tier chaos rig: tens-to-100
                        in-process gossiping workers (real stores,
                        real sidecars, real exchange clients) driven
                        round by round under manager SIGKILLs,
                        scoped partitions and poisoned peers; the
                        fleet-chaos CI lane gates its convergence
                        invariant.

Exit-code contract between the loop and the supervisor (chosen clear
of the CLI's 0/1/2 usage codes and shells' 126+ conventions):

  * ``WATCHDOG_EXIT_CODE`` (86) — the dispatch watchdog killed a
    stuck device wait after dumping in-flight state.
  * ``DEVICE_LOST_EXIT_CODE`` (87) — the loop died on a device-loss
    error (XlaRuntimeError / preemption); devices need re-probing
    before a restart is worth attempting.
"""

from __future__ import annotations

#: the dispatch watchdog killed the process over a stuck device wait
WATCHDOG_EXIT_CODE = 86

#: the loop exited on a classified device-loss error
DEVICE_LOST_EXIT_CODE = 87

#: substrings (lowercased) that mark an exception or a stderr line as
#: a device loss rather than a plain crash: JAX/XLA runtime failures,
#: TPU preemptions, and the chaos harness's injected stand-in
_DEVICE_LOSS_MARKERS = (
    "xlaruntimeerror", "device_lost", "device lost", "data_loss",
    "preempt", "tpu_terminated", "slice became unhealthy",
    "failed to connect to all addresses", "deadline_exceeded",
    "device or resource busy",
)


def is_device_loss(exc_or_text) -> bool:
    """True when an exception (or a stderr line) looks like the
    accelerator went away — the class of failure where restarting
    without re-probing devices would just die again."""
    if isinstance(exc_or_text, BaseException):
        text = f"{type(exc_or_text).__name__}: {exc_or_text}"
    else:
        text = str(exc_or_text)
    low = text.lower()
    return any(m in low for m in _DEVICE_LOSS_MARKERS)


from .chaos import chaos_point  # noqa: E402  (hot-path no-op hook)

__all__ = [
    "DEVICE_LOST_EXIT_CODE", "WATCHDOG_EXIT_CODE", "chaos_point",
    "is_device_loss",
]
