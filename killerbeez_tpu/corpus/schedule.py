"""Pluggable seed-scheduling policies for the fuzzing loop.

Extracted from ``Fuzzer._rotate_seed`` / ``_credit_period``: the loop
owns WHEN to rotate (cadence, pipeline safety, shape-stable seed
swaps); the scheduler owns WHICH seed the next period fuzzes.  Angora
frames search strategy as a swappable policy and FairFuzz shows the
choice dominates coverage growth (PAPERS.md) — so the policy is an
interface, not a hard-coded heuristic:

  * ``bandit``    — the default: greedy optimistic bandit with
    per-period decay, an exact port of the in-loop behavior it
    replaces (same arm scores, same tie-breaks, same splice RNG
    stream — ``--schedule bandit`` reproduces the old rotation
    decisions bit-for-bit on a fixed seed).
  * ``rare-edge`` — FairFuzz-style: prefer arms whose coverage
    signature contains the globally rarest edges (hit by the fewest
    corpus entries), probing unsigned arms once.
  * ``rr``        — round-robin over base + arms, the baseline
    ``bench.py --schedule`` compares against.

Arms are ``Arm`` objects — ``list`` subclasses holding the loop's
historical ``[buf, selections, finds]`` triple (credit pointers keep
working across cap evictions exactly as before) plus the store
metadata (md5, signature, lineage) the persistence tier needs.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

from .store import CorpusEntry, coverage_hash
from ..utils.fileio import md5_hex


class Arm(list):
    """One rotation arm: ``[buf, selections, finds]`` (the loop's
    historical shape — credits write through indices 1/2) plus store
    metadata as attributes."""

    __slots__ = ("md5", "seq", "sig", "state_sig", "parent",
                 "source", "discovered", "provenance", "tier",
                 "validation")

    def __init__(self, buf: bytes, selections: float = 0.0,
                 finds: float = 0.0, md5: Optional[str] = None,
                 seq: int = 0, sig: Optional[List[int]] = None,
                 parent: Optional[str] = None, source: str = "local",
                 discovered: Optional[float] = None,
                 state_sig: Optional[List] = None,
                 provenance=None, tier: Optional[str] = None,
                 validation=None):
        super().__init__([bytes(buf), selections, finds])
        self.md5 = md5 or md5_hex(buf)
        self.seq = int(seq)
        self.sig = sorted(set(int(s) for s in sig)) if sig else None
        self.state_sig = state_sig
        self.parent = parent
        #: mutation provenance (learn tier): set at admission, rides
        #: into the entry sidecar
        self.provenance = provenance
        #: hybrid campaign tags: minting tier + cross-tier verdict
        #: (docs/HYBRID.md) — ride into/out of the entry sidecar
        self.tier = tier
        self.validation = validation
        self.source = source
        self.discovered = discovered

    @property
    def buf(self) -> bytes:
        return self[0]

    @property
    def cov_hash(self) -> str:
        return coverage_hash(self.sig, self[0], self.state_sig)

    def to_entry(self) -> CorpusEntry:
        return CorpusEntry(
            self[0], md5=self.md5, seq=self.seq, sig=self.sig,
            edge_hits=None, selections=float(self[1]),
            finds=float(self[2]), parent=self.parent,
            source=self.source, discovered=self.discovered,
            state_sig=self.state_sig, provenance=self.provenance,
            tier=self.tier, validation=self.validation)

    @classmethod
    def from_entry(cls, e: CorpusEntry) -> "Arm":
        return cls(e.buf, selections=e.selections, finds=e.finds,
                   md5=e.md5, seq=e.seq, sig=e.sig, parent=e.parent,
                   source=e.source, discovered=e.discovered,
                   state_sig=e.state_sig,
                   provenance=getattr(e, "provenance", None),
                   tier=getattr(e, "tier", None),
                   validation=getattr(e, "validation", None))


class Scheduler:
    """Seed-scheduling policy: owns the arm list, the base-seed stats
    and the per-period credit fold; ``select()`` names the next
    period's seed.  The loop calls, in order per feedback period:
    ``credit_find`` per edge-novel finding (to the GENERATING arm),
    ``admit`` per finding entering rotation, ``credit_period`` at the
    boundary, then ``select``."""

    name = "base"

    #: rotation keeps at most this many arms (oldest evicted; the
    #: loop's historical CORPUS_CAP)
    CAP = 256

    #: per-period decay of arm stats (bandit scoring; kept for every
    #: policy so observability and resume see comparable stats)
    DECAY = 0.8

    #: find-equivalent credit for a native-confirmed verdict (hybrid
    #: bridge): ground truth on the real binary is worth a full find
    CONFIRM_CREDIT = 1.0

    #: cap on the remembered confirmed-md5 set (enough for any real
    #: campaign; bounds resume state)
    CONFIRM_CAP = 4096

    def __init__(self, cap: Optional[int] = None):
        self.arms: List[Arm] = []
        self.base_stats: List[float] = [0.0, 0.0]  # [selections, finds]
        self.base_seed: Optional[bytes] = None
        self.rotations = 0
        #: md5s whose findings the native tier confirmed on the real
        #: binary (hybrid bridge write-back; docs/HYBRID.md) — the
        #: cross-tier credit boost keys off membership here
        self.confirmed_md5s: set = set()
        if cap is not None:
            self.CAP = int(cap)
        # deterministic splice/choice stream — the loop's historical
        # seed, so the default policy replays old campaigns exactly
        self.rng = random.Random(0x6b62)
        self._seq = 0

    # -- corpus membership ---------------------------------------------

    def admit(self, arm: Arm) -> Optional[Arm]:
        """Add an arm; returns the evicted oldest arm when over cap
        (the eviction only drops it from ROTATION — the store keeps
        the entry on disk)."""
        arm.seq = max(arm.seq, self._seq)
        self._seq = arm.seq + 1
        self.arms.append(arm)
        if len(self.arms) > self.CAP:
            return self.arms.pop(0)
        return None

    def drop(self, index: int) -> Arm:
        """Remove an arm that cannot be scheduled (e.g. wider than the
        candidate buffer)."""
        return self.arms.pop(index)

    # -- credit fold (shared by every policy) ---------------------------

    def credit_find(self, arm: Optional[list]) -> None:
        """One edge-novel find, credited to the arm whose candidates
        produced it (None = the base seed).  A capped-out arm's entry
        may already be off the list — the credit is then a harmless
        write to a dead object, exactly as before the extraction."""
        if arm is None:
            self.base_stats[1] += 1
        else:
            arm[2] += 1

    def credit_period(self, active: Optional[list],
                      period: int = 1) -> None:
        """Close one feedback period: decay every arm's stats and
        charge the period's selection to the arm that generated it.

        ``DECAY`` is the PER-BATCH forgetting rate; ``period`` is how
        many batches this period spanned (the loop's -fb cadence), so
        the compounded ``DECAY ** period`` keeps an arm's stats
        half-life a fixed number of EXECUTIONS regardless of how
        often rotation fires — at the default cadence of 8 this is
        0.8^8 ~ 0.17 per call, intentionally much stronger than a
        flat 0.8-per-period would be, not an accidental 9x change.
        ``min(..., 16)`` only floors the factor (0.8^16 ~ 0.03) so
        extreme cadences don't flush history to zero in one call."""
        g = self.DECAY ** min(period or 1, 16)
        self.base_stats[0] *= g
        self.base_stats[1] *= g
        for e in self.arms:
            e[1] *= g
            e[2] *= g
        if active is None:
            self.base_stats[0] += 1
        else:
            active[1] += 1

    def note_validation(self, md5: str, verdict: str,
                        parent: Optional[str] = None) -> None:
        """Fold one cross-tier verdict (hybrid bridge).  A
        ``confirmed`` verdict — the finding reproduced on the real
        native binary — marks the finding AND its generating seed
        (``parent``) confirmed, and credits any arm carrying either
        md5 with a find-equivalent boost (RareEdgeScheduler
        additionally sharpens their rarity).  Idempotent per finding
        md5; other verdicts are recorded nowhere here (proxy_only
        feeds the proxy-gap report, not scheduling).  With no hybrid
        bridge attached this is never called, so every policy's
        ordering is exactly the historical one (parity-pinned)."""
        if verdict != "confirmed" or md5 in self.confirmed_md5s:
            return
        for m in (md5, parent):
            if m and len(self.confirmed_md5s) < self.CONFIRM_CAP:
                self.confirmed_md5s.add(m)
        for arm in self.arms:
            if arm.md5 == md5 or (parent and arm.md5 == parent):
                arm[2] += self.CONFIRM_CREDIT

    # -- selection ------------------------------------------------------

    def select(self) -> Tuple[Optional[int], Optional[bytes]]:
        """(arm index or None for the base seed, candidate bytes).
        ``(None, None)`` means nothing schedulable (no base, no arms).
        The candidate may differ from the arm's buffer (splice)."""
        raise NotImplementedError

    def favored_count(self) -> int:
        """How many arms the policy currently considers frontier
        (the ``corpus_favored`` gauge)."""
        return len(self.arms)

    # -- persistence ----------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        st = self.rng.getstate()
        d = {
            "scheduler": self.name,
            "base_stats": list(self.base_stats),
            "rotations": self.rotations,
            "rng_state": [st[0], list(st[1]), st[2]],
            "seq": self._seq,
        }
        # only hybrid campaigns carry verdict state — pre-hybrid
        # checkpoints stay byte-identical in shape
        if self.confirmed_md5s:
            d["confirmed"] = sorted(self.confirmed_md5s)
        return d

    def load_state(self, d: Dict[str, Any]) -> None:
        self.base_stats = [float(v) for v in
                           d.get("base_stats", [0.0, 0.0])]
        self.rotations = int(d.get("rotations", 0))
        self._seq = int(d.get("seq", self._seq))
        self.confirmed_md5s = set(
            str(m) for m in d.get("confirmed", []))
        rs = d.get("rng_state")
        if rs:
            self.rng.setstate((rs[0], tuple(rs[1]), rs[2]))

    def load_entries(self, entries: List[CorpusEntry]) -> None:
        """Rebuild the arm list from stored entries (resume): entries
        in admission order, rotation keeps the newest CAP of them —
        exactly what a continuously-running loop would hold."""
        for e in sorted(entries, key=lambda e: e.seq):
            self.admit(Arm.from_entry(e))


class BanditScheduler(Scheduler):
    """Greedy optimistic decay bandit — the loop's historical policy,
    ported verbatim.  Each arm scores ``(finds+1)/(selections+1)``
    (unexplored arms score 1.0 — every new frontier probed once),
    ties break toward the NEWEST discovery, and when two or more
    findings exist half the corpus-arm turns fuzz an AFL-style splice
    of the arm with a random partner (crossover inside the differing
    region so magic bytes / headers survive)."""

    name = "bandit"

    def select(self) -> Tuple[Optional[int], Optional[bytes]]:
        best, best_score = None, 0.0
        if self.base_seed is not None:
            best_score = ((self.base_stats[1] + 1.0)
                          / (self.base_stats[0] + 1.0))
        for i, (buf, sel, finds) in enumerate(self.arms):
            score = (finds + 1.0) / (sel + 1.0)
            if score >= best_score:     # >= : newest wins ties
                best, best_score = i, score
        if best is None:
            return None, self.base_seed
        cand = self.arms[best][0]
        if len(self.arms) >= 2 and self.rng.random() < 0.5:
            partner = self.rng.choice(
                [e[0] for j, e in enumerate(self.arms) if j != best])
            # AFL-style splice (afl locate_diffs semantics): cross
            # over INSIDE the differing region so the common prefix
            # — magic bytes, headers — survives
            n = min(len(cand), len(partner))
            fd = next((i for i in range(n)
                       if cand[i] != partner[i]), None)
            if fd is not None:
                ld = next(i for i in range(n - 1, -1, -1)
                          if cand[i] != partner[i])
                if ld > fd + 1:
                    k = self.rng.randrange(fd + 1, ld)
                    cand = cand[:k] + partner[k:]
        return best, cand

    def favored_count(self) -> int:
        """Arms whose score matches or beats the base seed's — the
        frontier the greedy choice draws from."""
        base = ((self.base_stats[1] + 1.0)
                / (self.base_stats[0] + 1.0)) \
            if self.base_seed is not None else 0.0
        return sum(1 for _, sel, finds in self.arms
                   if (finds + 1.0) / (sel + 1.0) >= base)


class RoundRobinScheduler(Scheduler):
    """Round-robin over the base seed plus every arm, in admission
    order — the uniform-budget baseline coverage-guided policies are
    measured against (``bench.py --schedule``)."""

    name = "rr"

    def __init__(self, cap: Optional[int] = None):
        super().__init__(cap)
        self._cursor = 0

    def select(self) -> Tuple[Optional[int], Optional[bytes]]:
        slots = (1 if self.base_seed is not None else 0) + len(self.arms)
        if slots == 0:
            return None, None
        pos = self._cursor % slots
        self._cursor += 1
        if self.base_seed is not None:
            if pos == 0:
                return None, self.base_seed
            pos -= 1
        return pos, self.arms[pos][0]

    def state_dict(self) -> Dict[str, Any]:
        d = super().state_dict()
        d["cursor"] = self._cursor
        return d

    def load_state(self, d: Dict[str, Any]) -> None:
        super().load_state(d)
        self._cursor = int(d.get("cursor", 0))


class RareEdgeScheduler(Scheduler):
    """FairFuzz-style rarity scheduling: prefer arms whose coverage
    signature contains the edges hit by the FEWEST corpus entries —
    the rare-branch frontier rarity targeting dominates coverage
    growth on (PAPERS.md).  Global hit counts fold over every
    admitted signature (local and synced), so a fleet's pulls sharpen
    each worker's rarity estimate.  Unsigned arms (no signature
    available on this tier) are probed once, then fall behind signed
    arms; among equal rarity the least-selected arm wins, ties toward
    the newest."""

    name = "rare-edge"

    def __init__(self, cap: Optional[int] = None,
                 static_prior: Optional[Dict[int, float]] = None):
        super().__init__(cap)
        self.edge_hits: Dict[int, int] = {}
        #: optional static edge-frequency prior (slot -> probability
        #: mass, ``analysis.static_edge_prior``): breaks COLD-START
        #: ties only — it enters the selection key after every
        #: dynamic statistic, so once corpus-wide hit counts or
        #: selection counts differ at all the choice is identical to
        #: an unprimed scheduler (parity-pinned in tests)
        self.static_prior: Optional[Dict[int, float]] = \
            dict(static_prior) if static_prior else None

    def set_static_prior(self,
                         prior: Optional[Dict[int, float]]) -> None:
        """Install the static rarity prior (e.g. from
        ``analysis.static_edge_prior(program)``)."""
        self.static_prior = dict(prior) if prior else None

    def _prior_key(self, arm: Arm) -> float:
        """Statically-expected frequency of the arm's rarest edge
        (0.0 when no prior is installed — the key element is then a
        constant and the ordering is exactly the historical one)."""
        if not self.static_prior or not arm.sig:
            return 0.0
        return min(self.static_prior.get(e, 1.0) for e in arm.sig)

    def _forget(self, arm: Optional[Arm]) -> None:
        if arm is None or not arm.sig:
            return
        for e in arm.sig:
            n = self.edge_hits.get(e, 0) - 1
            if n <= 0:
                self.edge_hits.pop(e, None)
            else:
                self.edge_hits[e] = n

    def admit(self, arm: Arm) -> Optional[Arm]:
        if arm.sig:
            for e in arm.sig:
                self.edge_hits[e] = self.edge_hits.get(e, 0) + 1
        evicted = super().admit(arm)
        self._forget(evicted)
        return evicted

    def drop(self, index: int) -> Arm:
        """Arms dropped from rotation (e.g. wider than the candidate
        buffer) must release their edge counts too, or surviving
        arms' rarity reads permanently stale."""
        arm = super().drop(index)
        self._forget(arm)
        return arm

    #: rarity scale for native-confirmed arms: a confirmed seed's
    #: rarest edge counts as half as common, so at equal raw rarity
    #: ground-truthed frontier outranks proxy-only frontier — the
    #: cross-tier extension of FairFuzz rarity (docs/HYBRID.md).
    #: With an empty confirmed set the scale never applies and the
    #: ordering is exactly the historical one.
    CONFIRM_RARITY_SCALE = 0.5

    def _rarity(self, arm: Arm) -> float:
        if not arm.sig:
            # unsigned: probe once (rarity 0 beats everything), then
            # deprioritize below any signed arm
            return 0.0 if arm[1] == 0 else float("inf")
        r = float(min(self.edge_hits.get(e, 1) for e in arm.sig))
        if self.confirmed_md5s and arm.md5 in self.confirmed_md5s:
            r *= self.CONFIRM_RARITY_SCALE
        return r

    def select(self) -> Tuple[Optional[int], Optional[bytes]]:
        if not self.arms:
            return None, self.base_seed
        best, best_key = None, None
        for i, arm in enumerate(self.arms):
            key = (self._rarity(arm), float(arm[1]),
                   self._prior_key(arm), -arm.seq)
            if best_key is None or key < best_key:
                best, best_key = i, key
        if best_key is not None and best_key[0] == float("inf") \
                and self.base_seed is not None:
            # every arm is unsigned and already probed: split budget
            # with the base seed instead of thrashing blind arms
            if self.rng.random() < 0.5:
                return None, self.base_seed
        return best, self.arms[best][0]

    def favored_count(self) -> int:
        if not self.edge_hits:
            return len(self.arms)
        rarest = min(self.edge_hits.values())
        return sum(1 for a in self.arms if a.sig and
                   min(self.edge_hits.get(e, 1) for e in a.sig)
                   <= rarest)

    def load_entries(self, entries: List[CorpusEntry]) -> None:
        super().load_entries(entries)   # admit() folds edge_hits


SCHEDULERS = {
    "bandit": BanditScheduler,
    "rare-edge": RareEdgeScheduler,
    "rr": RoundRobinScheduler,
}


def make_scheduler(name: str, cap: Optional[int] = None) -> Scheduler:
    try:
        cls = SCHEDULERS[name]
    except KeyError:
        raise ValueError(
            f"unknown scheduler {name!r} (choose from "
            f"{', '.join(sorted(SCHEDULERS))})")
    return cls(cap)
