"""Peer-to-peer corpus gossip — corpus flow without a living hub.

PR 2's exchange made the manager the sole corpus distributor: every
entry flows worker -> hub -> workers, so a partitioned, slow or dead
manager stops fleet-wide corpus flow cold (the decorrelated sync
backoff keeps workers *fuzzing*, but each one re-discovers what its
peers already know).  The reference solved fleet scale with a BOINC
work-distribution tier; this module solves it the epidemic way:

  * every gossiping worker runs a :class:`GossipSidecar` — a small
    HTTP server exposing the SAME cursor API the manager serves
    (``GET /api/corpus/<campaign>?since=N``), backed by the worker's
    own admitted entries;
  * each sync round, :class:`GossipSync` picks ``fanout`` random
    live peers from the peer directory and pulls their cursors
    directly, deduping by the existing ``cov_hash`` exactly like the
    manager path — one worker's frontier reaches the whole fleet in
    O(log n) rounds with no hub on the data path;
  * the manager is demoted to **peer directory + anti-entropy
    backstop**: ``POST /api/peers/<campaign>`` registers this
    worker's endpoint and returns the current directory (one round
    trip), and the inherited manager push/pull still runs when the
    hub is reachable, catching up stragglers and late joiners.  The
    directory is CACHED — a dead manager stops refreshes, not gossip.

Trust boundary: everything pulled from a peer passes the
poisoned-entry quarantine (``quarantine.EntryValidator``) before
admission; a peer whose entries keep failing validation is banned
for a decorrelated-backoff interval (``quarantine.PeerBans``).
Outbound peer requests ride the same ``manager_rpc`` chaos seam as
hub traffic (one `--chaos` spec covers both; ``match`` scopes a
partition to a named endpoint), and the sidecar's serve path carries
its own ``gossip_serve`` seam.
"""

from __future__ import annotations

import base64
import json
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set
from urllib.parse import parse_qs, urlparse

from ..resilience.chaos import chaos_point
from ..utils.logging import DEBUG_MSG, INFO_MSG
from .quarantine import EntryValidator, PeerBans
from .store import CorpusEntry
from .sync import CorpusSync


class GossipSidecar:
    """One worker's corpus server: an append-only METADATA log (the
    content bytes live in the attached corpus store and are read at
    serve time) behind the manager's cursor-GET shape, so the pull
    client is the same code for hub and peers.  Responses are paged.

        GET /api/corpus/<campaign>?since=N[&limit=K]
            -> {campaign, boot, latest, entries: [...]}
        GET /api/ping -> {worker, campaign, entries, boot}

    ``boot`` is a per-process nonce: a restarted sidecar restarts its
    row ids at 0, and the nonce tells pullers to reset their cursor
    instead of silently missing everything below their stale one.
    """

    #: default per-GET page cap (bounds response size; pullers catch
    #: up across rounds — cov_hash dedup makes overlap harmless)
    PAGE = 256

    def __init__(self, campaign: str, worker: str,
                 host: str = "127.0.0.1", port: int = 0):
        self.campaign = str(campaign)
        self.worker = worker
        self.boot = f"{time.time():.0f}-{random.randrange(1 << 30)}"
        self._rows: List[Dict[str, Any]] = []
        self._known: Set[str] = set()        # cov_hashes published
        self._lock = threading.Lock()
        self.served_n = 0                    # entries served out
        #: the worker's durable corpus store, once attached: rows
        #: then hold METADATA ONLY and content is read from disk at
        #: serve time — the sidecar must not carry a second full
        #: copy of the corpus in heap (content dominates; a long
        #: campaign's store is arbitrarily large).  Entries with no
        #: store backing keep their bytes in the row.
        self.store = None
        sidecar = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    # chaos seam: inbound peer traffic — the ctx url
                    # carries this sidecar's endpoint, so a partition
                    # ``match``-scoped to one worker's host:port
                    # severs exactly that worker's serving
                    chaos_point("gossip_serve",
                                url=sidecar.endpoint + self.path)
                    sidecar._serve(self)
                except Exception as e:   # serving must never kill us
                    try:
                        self.send_error(500, str(e)[:100])
                    except OSError:
                        pass

        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        self.endpoint = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    # -- publishing -----------------------------------------------------

    def publish(self, entry: CorpusEntry) -> bool:
        """Append one locally-admitted entry to the served log (local
        finds AND entries learned from peers — re-serving what we
        learned is what makes the epidemic converge).  Dedup by
        cov_hash; returns True when newly published.  Bytes stay in
        the row ONLY while no attached store holds them."""
        with self._lock:
            if entry.cov_hash in self._known:
                return False
            self._known.add(entry.cov_hash)
            row: Dict[str, Any] = {
                "id": len(self._rows) + 1,
                "md5": entry.md5,
                "cov_hash": entry.cov_hash,
                "worker": self.worker,
                "meta": entry.meta_dict(),
            }
            if not self._store_has(entry.md5):
                row["_buf"] = bytes(entry.buf)
            self._rows.append(row)
        return True

    def _store_has(self, md5: str) -> bool:
        store = self.store
        if store is None:
            return False
        try:
            return os.path.exists(store.entry_path(md5))
        except OSError:
            return False

    def attach_store(self, store) -> None:
        """Wire the durable corpus store in (the sync round does this
        on its serve-side bootstrap) and drop every cached buffer the
        store already holds — heap shrinks to metadata."""
        if store is None:
            return
        with self._lock:
            self.store = store
            for row in self._rows:
                if "_buf" in row and self._store_has(row["md5"]):
                    del row["_buf"]

    def _row_content_b64(self, row: Dict[str, Any]) -> Optional[str]:
        """Wire content for one row: the raw forged row's b64 (tests
        publish those directly), the cached buffer, or a store read."""
        if isinstance(row.get("content_b64"), str):
            return row["content_b64"]
        buf = row.get("_buf")
        if buf is None and self.store is not None:
            try:
                with open(self.store.entry_path(row["md5"]),
                          "rb") as f:
                    buf = f.read()
            except OSError:
                return None
        if buf is None:
            return None
        return base64.b64encode(buf).decode()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    # -- serving --------------------------------------------------------

    def _serve(self, handler) -> None:
        parsed = urlparse(handler.path)
        query = parse_qs(parsed.query)
        if parsed.path == "/api/ping":
            self._json(handler, 200, {
                "worker": self.worker, "campaign": self.campaign,
                "boot": self.boot, "entries": len(self)})
            return
        if parsed.path == f"/api/corpus/{self.campaign}":
            since = int(query.get("since", ["0"])[0])
            limit = int(query.get("limit", [str(self.PAGE)])[0])
            limit = max(1, min(limit, self.PAGE))
            with self._lock:
                latest = len(self._rows)
                page = list(self._rows[since:since + limit])
            out = []
            for row in page:
                b64 = self._row_content_b64(row)
                if b64 is None:
                    # unreadable store entry: serve the rest of the
                    # page (its ids still advance the puller's
                    # cursor); the row retries on a later pull
                    continue
                out.append({"id": row["id"], "md5": row["md5"],
                            "cov_hash": row["cov_hash"],
                            "worker": row["worker"],
                            "meta": row["meta"],
                            "content_b64": b64})
            with self._lock:
                self.served_n += len(out)
            self._json(handler, 200, {
                "campaign": self.campaign, "boot": self.boot,
                "latest": latest, "entries": out})
            return
        self._json(handler, 404,
                   {"error": f"no route {parsed.path}"})

    @staticmethod
    def _json(handler, code: int, obj: Any) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)


class GossipSync(CorpusSync):
    """The partition-tolerant exchange client: manager anti-entropy
    (inherited) + per-round peer fanout pulls + a serving sidecar.

    Rides the loop's existing sync hook unchanged — ``note_entry`` at
    triage, ``maybe_sync`` between batches — so ``--gossip`` is a
    flag, not a new loop mode.  Peer transport failures never fail
    the ROUND (the round gate and its backoff stay manager-signal
    only); a failed peer is simply skipped until a later round's
    random fanout picks it again."""

    def __init__(self, manager_url: str, campaign: str,
                 worker: str = "anon", interval_s: float = 30.0,
                 attempts: int = 1,
                 backoff_cap: Optional[float] = None,
                 rng: Optional[random.Random] = None,
                 validator: Optional[EntryValidator] = None,
                 fanout: int = 2,
                 listen_host: str = "127.0.0.1",
                 listen_port: int = 0,
                 advertise: Optional[str] = None,
                 peer_refresh_rounds: int = 4,
                 bans: Optional[PeerBans] = None):
        super().__init__(manager_url, campaign, worker=worker,
                         interval_s=interval_s, attempts=attempts,
                         backoff_cap=backoff_cap, rng=rng,
                         validator=validator)
        self.fanout = int(fanout)
        self.sidecar = GossipSidecar(campaign, worker,
                                     host=listen_host,
                                     port=listen_port)
        #: the URL peers reach us at (defaults to the bind address —
        #: override when NAT/containers make that unreachable)
        self.advertise = advertise or self.sidecar.endpoint
        self.peers_url = (f"{self.manager_url}/api/peers/"
                          f"{self.campaign}")
        #: cached peer directory {worker: endpoint} — survives a dead
        #: manager (gossip outlives the hub on the last known fleet)
        self.peers: Dict[str, str] = {}
        self.peer_refresh_rounds = max(1, int(peer_refresh_rounds))
        self._rounds = 0
        #: per-peer pull cursor {worker: [boot, since]}
        self._peer_cursor: Dict[str, List[Any]] = {}
        self.bans = bans or PeerBans(rng=self._rng)
        self._served_seen = 0
        self._store_published = False
        self.gossip_pulled_n = 0
        INFO_MSG("gossip sidecar for %s serving on %s", worker,
                 self.advertise)

    def close(self) -> None:
        self.sidecar.close()

    # -- publishing hooks ----------------------------------------------

    def note_entry(self, entry: CorpusEntry) -> None:
        super().note_entry(entry)
        self.sidecar.publish(entry)

    def _admit_entries(self, fuzzer, entries) -> int:
        admitted = super()._admit_entries(fuzzer, entries)
        for e in entries:
            self.sidecar.publish(e)
        return admitted

    # -- peer directory -------------------------------------------------

    def _refresh_peers(self) -> None:
        """Register our endpoint and pull the directory in ONE
        request; a failure keeps the cached directory — the manager
        is only the phone book, never the data path."""
        from ..manager.worker import _request_retry
        try:
            resp = _request_retry(
                self.peers_url,
                {"worker": self.worker, "endpoint": self.advertise},
                attempts=self.attempts)
        except Exception as e:
            DEBUG_MSG("gossip: peer-directory refresh failed "
                      "(cached %d peers kept): %s", len(self.peers), e)
            return
        if not isinstance(resp, dict):
            return
        peers = {}
        for p in resp.get("peers", []):
            if not isinstance(p, dict):
                continue
            w, ep = p.get("worker"), p.get("endpoint")
            if isinstance(w, str) and isinstance(ep, str) \
                    and w != self.worker:
                peers[w] = ep
        if not peers and self.peers:
            # an EMPTY directory never replaces a non-empty cache: a
            # write-degraded manager freezes last_seen fleet-wide, so
            # after dead_after its directory reads empty while every
            # peer is actually alive — overwriting the cache here
            # would halt gossip during exactly the outage it exists
            # to survive (stale cached peers just fail their pulls)
            DEBUG_MSG("gossip: empty peer directory (manager "
                      "degraded=%s); keeping %d cached peers",
                      resp.get("degraded"), len(self.peers))
            return
        self.peers = peers

    # -- the peer exchange round ---------------------------------------

    def _pull_peer(self, fuzzer, name: str, endpoint: str) -> int:
        """One cursor GET against one peer; returns entries admitted
        (-1 on transport failure).  Rides the manager_rpc chaos seam
        (worker._request), so ``--chaos`` specs cover peer traffic."""
        from ..manager.worker import _request_retry
        cur = self._peer_cursor.setdefault(name, [None, 0])
        url = (f"{endpoint.rstrip('/')}/api/corpus/{self.campaign}"
               f"?since={cur[1]}")
        try:
            resp = _request_retry(url, None, method="GET",
                                  attempts=self.attempts)
        except Exception as e:
            DEBUG_MSG("gossip: pull from peer %s (%s) failed: %s",
                      name, endpoint, e)
            return -1
        if not isinstance(resp, dict):
            return 0
        boot = resp.get("boot")
        if cur[0] is not None and boot != cur[0]:
            # peer restarted: its ids restarted too, and THIS response
            # was served against our stale cursor — reset and re-pull
            # from 0 next round (cov_hash dedup absorbs the overlap);
            # advancing the cursor from this response would clobber
            # the reset and skip everything the restarted peer serves
            cur[0], cur[1] = boot, 0
            return 0
        cur[0] = boot
        rows = resp.get("entries", [])
        # advance by the PAGE actually returned, never to `latest`:
        # the sidecar truncates responses to its page cap, and a
        # cursor jumped to latest would permanently skip the rows the
        # truncated page did not carry.  Ids parse PER ROW — one
        # malformed id from a hostile peer must not blow the whole
        # page's advance and fall back to the latest-jump
        ids = []
        if isinstance(rows, list):
            for r in rows:
                if not isinstance(r, dict):
                    continue
                try:
                    ids.append(int(r.get("id", 0)))
                except (TypeError, ValueError):
                    continue
        if ids:
            cur[1] = max([cur[1]] + ids)
        elif not rows:
            # an EMPTY page means the cursor is at (or past) the
            # peer's tail — latest is then safe to trust as a floor
            try:
                cur[1] = max(cur[1], int(resp.get("latest", 0)))
            except (TypeError, ValueError):
                pass
        before = len(self._quarantined_round)
        entries = self._entries_from_rows(rows, peer=name)
        if len(self._quarantined_round) == before and entries:
            self.bans.clean(name)
        admitted = self._admit_entries(fuzzer, entries)
        self.gossip_pulled_n += admitted
        return admitted

    def _peer_round(self, fuzzer, reg) -> None:
        self._rounds += 1
        # serve-side bootstrap: a resumed campaign's pre-existing
        # store must be servable before the first admission — and
        # attaching the store lets the sidecar drop every cached
        # buffer the store already holds (metadata-only heap)
        if not self._store_published and fuzzer.store is not None:
            self._store_published = True
            self.sidecar.attach_store(fuzzer.store)
            for e in fuzzer.store.load():
                self.sidecar.publish(e)
        if self._rounds == 1 or \
                self._rounds % self.peer_refresh_rounds == 0 or \
                not self.peers:
            self._refresh_peers()
        candidates = [(w, ep) for w, ep in sorted(self.peers.items())
                      if not self.bans.is_banned(w)]
        picked = (self._rng.sample(candidates,
                                   min(self.fanout, len(candidates)))
                  if candidates else [])
        pulled = 0
        failed_peers = []
        for name, endpoint in picked:
            got = self._pull_peer(fuzzer, name, endpoint)
            if got < 0:
                failed_peers.append(name)
            else:
                pulled += got
        # counters: in/out deltas + round count (fold-able sums)
        reg.count("gossip_rounds")
        if pulled:
            reg.count("gossip_entries_in", pulled)
        served = self.sidecar.served_n
        if served > self._served_seen:
            reg.count("gossip_entries_out",
                      served - self._served_seen)
            self._served_seen = served
        reg.gauge("gossip_peers", len(self.peers))
        reg.gauge("peers_banned_active", len(self.bans.active()))
        if picked:
            fuzzer.telemetry.event(
                "gossip_round", peers=[n for n, _ in picked],
                pulled=int(pulled), failed=failed_peers)

    def _flush_quarantine(self, fuzzer, reg) -> None:
        batch = list(self._quarantined_round)
        super()._flush_quarantine(fuzzer, reg)
        # strike the offenders; threshold crossings ban with
        # decorrelated backoff and land in the event stream
        by_peer: Dict[str, int] = {}
        for _, _, peer in batch:
            if peer is not None:
                by_peer[peer] = by_peer.get(peer, 0) + 1
        for peer, n in sorted(by_peer.items()):
            if self.bans.strike(peer, n):
                reg.count("peers_banned")
                fuzzer.telemetry.event(
                    "peer_banned", peer=peer,
                    until=self.bans.banned_until.get(peer))
        if by_peer:
            reg.gauge("peers_banned_active",
                      len(self.bans.active()))
