"""On-disk corpus store — the campaign's durable seed set.

Layout mirrors the reference's ``new_paths/`` (one file per entry
named by its input md5) and adds what the reference kept only in
operator heads: a ``<md5>.json`` metadata sidecar per entry and a
``campaign.json`` state record, so a killed campaign resumes with its
full corpus, bandit stats and lineage instead of starting over.

    <corpus-dir>/
        <md5>            raw input bytes (same naming as new_paths/)
        <md5>.json       metadata sidecar (schema below)
        checkpoint.json  ONE atomic campaign checkpoint epoch
                         (campaign + solver + event seq + component
                         states; resilience/checkpoint.py) — the
                         resume source of truth
        checkpoint.json.prev  previous epoch (torn-write fallback)
        campaign.json    legacy scheduler/campaign state (read when
                         no checkpoint exists)
        solver.json      legacy / offline-tool solver cache
        mutator.state    legacy mutator resume state (JSON string)
        instrumentation.state   legacy coverage resume state

Sidecar schema (docs/CORPUS.md):

    {"md5": ..., "seq": N,            # admission order (monotone)
     "cov_hash": ...,                 # coverage dedup key (sync)
     "sig": [slot, ...] | null,       # coverage signature (edge slots)
     "state_sig": [[state, slot], ...] | null,  # state x edge pairs
                                      # (stateful session tier)
     "edge_hits": {slot: count} | null,   # edge-hit summary
     "selections": float, "finds": float, # bandit arm stats (decayed)
     "parent": md5 | "base" | null,   # lineage: generating arm
     "provenance": {"mutator": ..., "stage": ...,  # mutation
                    "bitmap": b64, "bytes": N} | null,
                                      # provenance: which parent byte
                                      # positions were mutated (the
                                      # learn tier's training labels;
                                      # docs/LEARN.md) — optional,
                                      # pre-learn sidecars omit it
     "source": "local" | "sync",
     "discovered": unix_time,
     "tier": "tpu" | "native" | ... | null,  # execution tier that
                                      # minted the entry (hybrid
                                      # campaigns; docs/HYBRID.md) —
                                      # pre-hybrid sidecars omit it
     "validation": {"verdict": "confirmed" | "proxy_only" | "flaky",
                    "tier": ..., "repro": N, "repeats": N,
                    "attempts": N, "statuses": [...], "t": unix_time,
                    "repair": {"verdict": "repaired" |
                                          "unrepairable",
                               "patch": str | null,
                               "reason": str | null,
                               "t": unix_time} | absent}
                                      # | null — cross-tier verdict
                                      # written back by the hybrid
                                      # bridge (docs/HYBRID.md); the
                                      # repair subsection by
                                      # kb-repair / --auto-repair
                                      # (docs/ANALYSIS.md)

Every write is atomic (tmp file + ``os.replace``, the telemetry
sink's discipline) so a tailer or a crash mid-write never leaves a
torn entry; ``load()`` skips unreadable sidecars instead of dying —
a store survives its own worst write.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from ..resilience import checkpoint as _ckpt
from ..resilience.chaos import chaos_point
from ..utils.fileio import ensure_dir, md5_hex
from ..utils.logging import WARNING_MSG

STATE_FILE = "campaign.json"
MUTATOR_STATE_FILE = "mutator.state"
INSTR_STATE_FILE = "instrumentation.state"
SOLVER_STATE_FILE = "solver.json"
VSA_STATE_FILE = "vsa.json"
CHECKPOINT_FILE = _ckpt.CHECKPOINT_FILE
_RESERVED = (STATE_FILE, MUTATOR_STATE_FILE, INSTR_STATE_FILE,
             SOLVER_STATE_FILE, VSA_STATE_FILE, CHECKPOINT_FILE,
             CHECKPOINT_FILE + _ckpt.PREV_SUFFIX)

# Cross-tier validation verdicts (hybrid bridge; docs/HYBRID.md).
# Shared by the sidecar schema, EntryValidator bounds and the hybrid
# validator itself so the taxonomy cannot drift between layers.
VALIDATION_VERDICTS = ("confirmed", "proxy_only", "flaky")

# Sidecar schema bound on ``validation.statuses`` (one status per
# native repeat).  NativeValidator clamps its repeats to this and
# EntryValidator rejects longer lists, so a sidecar minted anywhere
# in the fleet always syncs past every peer's validator.
MAX_VALIDATION_REPEATS = 64

# Repair verdicts a proxy-gap entry's sidecar may carry under
# validation.repair (kb-repair / --auto-repair write-back;
# docs/ANALYSIS.md "Conformance & repair").  Honest by construction:
# there is no "best-effort" value.
REPAIR_VERDICTS = ("repaired", "unrepairable")


def coverage_hash(sig: Optional[List[int]],
                  buf: Optional[bytes] = None,
                  state_sig: Optional[List] = None) -> str:
    """Dedup key for cross-worker exchange: the sha1 of the sorted
    edge-slot signature when one exists (two different inputs hitting
    the same edge set are one frontier), else the content md5 — an
    unsigned entry still dedups exactly.  Stateful session entries
    fold their state x edge pairs in too: a sequence admitted for
    STATE-only novelty (same edge set, new protocol states) is a
    distinct frontier and must not dedup against its stateless
    twin."""
    if sig or state_sig:
        parts = ",".join(str(s) for s in sorted(set(sig or [])))
        if state_sig:
            parts += "|" + ",".join(
                f"{a}:{b}" for a, b in
                sorted((int(a), int(b)) for a, b in state_sig))
        return "sig:" + hashlib.sha1(parts.encode()).hexdigest()
    return "md5:" + (md5_hex(buf) if buf is not None else "")


class CorpusEntry:
    """One stored corpus entry: input bytes + metadata sidecar."""

    __slots__ = ("buf", "md5", "seq", "sig", "state_sig", "edge_hits",
                 "selections", "finds", "parent", "source",
                 "discovered", "cov_hash", "provenance", "tier",
                 "validation")

    def __init__(self, buf: bytes, md5: Optional[str] = None,
                 seq: int = 0, sig: Optional[List[int]] = None,
                 edge_hits: Optional[Dict[int, int]] = None,
                 selections: float = 0.0, finds: float = 0.0,
                 parent: Optional[str] = None, source: str = "local",
                 discovered: Optional[float] = None,
                 cov_hash: Optional[str] = None,
                 state_sig: Optional[List] = None,
                 provenance: Optional[Dict[str, Any]] = None,
                 tier: Optional[str] = None,
                 validation: Optional[Dict[str, Any]] = None):
        self.buf = bytes(buf)
        self.md5 = md5 or md5_hex(self.buf)
        self.seq = int(seq)
        self.sig = sorted(set(int(s) for s in sig)) if sig else None
        # state x edge pairs from the stateful session tier, sorted
        # [[state, slot], ...] (kb-corpus's state-coverage column)
        self.state_sig = (sorted([int(a), int(b)] for a, b in state_sig)
                          if state_sig else None)
        self.edge_hits = ({int(k): int(v) for k, v in edge_hits.items()}
                          if edge_hits else None)
        self.selections = float(selections)
        self.finds = float(finds)
        self.parent = parent
        # mutation provenance (learn tier, optional): a dict with
        # mutator id, stage, and the mutated-byte bitmap — sidecars
        # without it load unchanged
        self.provenance = (dict(provenance)
                           if isinstance(provenance, dict) else None)
        self.source = source
        # hybrid campaign tags (optional): the tier that minted this
        # entry and the cross-tier validation verdict written back by
        # the hybrid bridge — pre-hybrid sidecars load unchanged
        self.tier = str(tier) if tier else None
        self.validation = (dict(validation)
                           if isinstance(validation, dict) else None)
        self.discovered = (time.time() if discovered is None
                           else float(discovered))
        self.cov_hash = cov_hash or coverage_hash(
            self.sig, self.buf, self.state_sig)

    def meta_dict(self) -> Dict[str, Any]:
        return {
            "md5": self.md5, "seq": self.seq, "cov_hash": self.cov_hash,
            "sig": self.sig, "state_sig": self.state_sig,
            "edge_hits": ({str(k): v for k, v in self.edge_hits.items()}
                          if self.edge_hits else None),
            "selections": self.selections, "finds": self.finds,
            "parent": self.parent, "provenance": self.provenance,
            "source": self.source,
            "discovered": self.discovered,
            "tier": self.tier, "validation": self.validation,
        }

    @classmethod
    def from_meta(cls, buf: bytes, meta: Dict[str, Any]) -> "CorpusEntry":
        return cls(buf, md5=meta.get("md5"), seq=meta.get("seq", 0),
                   sig=meta.get("sig"),
                   edge_hits=meta.get("edge_hits"),
                   selections=meta.get("selections", 0.0),
                   finds=meta.get("finds", 0.0),
                   parent=meta.get("parent"),
                   source=meta.get("source", "local"),
                   discovered=meta.get("discovered"),
                   cov_hash=meta.get("cov_hash"),
                   state_sig=meta.get("state_sig"),
                   provenance=meta.get("provenance"),
                   tier=meta.get("tier"),
                   validation=meta.get("validation"))


def _atomic_write(path: str, data: bytes) -> None:
    # chaos seam: every store write (entries, sidecars, campaign /
    # solver state, checkpoint epochs) can be made to tear, hit
    # ENOSPC, or die mid-write under --chaos
    chaos_point("persist", path=path, data=data)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)               # atomic on POSIX


class CorpusStore:
    """Directory-backed corpus with atomic entry/state writes.

    The store is the durable tier under the in-memory scheduler arms:
    admissions write through immediately (a kill after an admission
    loses nothing), arm stats and campaign state flush periodically
    (bounded staleness, bandit scores re-converge within one decay
    period).  All store I/O degrades to warnings — persistence must
    never kill a campaign over a full disk.
    """

    def __init__(self, root: str):
        self.root = str(root)
        ensure_dir(self.root)
        #: last checkpoint doc THIS process saved (single-writer
        #: cache; None until the first save — readers then hit disk)
        self._ckpt_doc: Optional[Dict[str, Any]] = None
        # continue the admission counter past any existing entries:
        # writing into a pre-populated store without load() (e.g.
        # --corpus-dir reused without --resume) must not mint
        # colliding seq numbers — resume's seq-ordered rebuild
        # depends on a monotone timeline
        self._next_seq = 0
        try:
            for name in os.listdir(self.root):
                if not name.endswith(".json") or name in _RESERVED:
                    continue
                try:
                    with open(os.path.join(self.root, name)) as f:
                        seq = int(json.load(f).get("seq", -1))
                    self._next_seq = max(self._next_seq, seq + 1)
                except (OSError, ValueError):
                    continue
        except OSError:
            pass

    # -- entries --------------------------------------------------------

    def entry_path(self, md5: str) -> str:
        return os.path.join(self.root, md5)

    def meta_path(self, md5: str) -> str:
        return os.path.join(self.root, md5 + ".json")

    def next_seq(self) -> int:
        n = self._next_seq
        self._next_seq += 1
        return n

    def put(self, entry: CorpusEntry) -> bool:
        """Write one entry (buf + sidecar, both atomic); returns False
        when an entry with this md5 already exists (content dedup)."""
        path = self.entry_path(entry.md5)
        if os.path.exists(path):
            return False
        try:
            _atomic_write(path, entry.buf)
            _atomic_write(self.meta_path(entry.md5),
                          json.dumps(entry.meta_dict()).encode())
        except OSError as e:
            WARNING_MSG("corpus store write failed for %s: %s",
                        entry.md5, e)
            return False
        self._next_seq = max(self._next_seq, entry.seq + 1)
        return True

    def update_meta(self, entry: CorpusEntry) -> None:
        """Rewrite one entry's sidecar (stats flush)."""
        try:
            _atomic_write(self.meta_path(entry.md5),
                          json.dumps(entry.meta_dict()).encode())
        except OSError as e:
            WARNING_MSG("corpus sidecar update failed for %s: %s",
                        entry.md5, e)

    def update_validation(self, md5: str,
                          validation: Dict[str, Any]) -> bool:
        """Fold a cross-tier verdict into one entry's sidecar (hybrid
        bridge write-back).  Reads the sidecar as stored rather than
        regenerating it from an in-memory entry so concurrently
        flushed stats are not clobbered; returns False when no
        sidecar exists for ``md5`` (findings that never became corpus
        entries live only in the findings sidecar)."""
        path = self.meta_path(md5)
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        meta["validation"] = dict(validation)
        try:
            _atomic_write(path, json.dumps(meta).encode())
        except OSError as e:
            WARNING_MSG("corpus validation update failed for %s: %s",
                        md5, e)
            return False
        return True

    def update_repair(self, md5: str,
                      repair: Dict[str, Any]) -> bool:
        """Fold a repair verdict into one entry's ``validation``
        block (``validation.repair``: verdict, patch/reason, t).
        Entries without a validation block are skipped — a repair
        claim only makes sense on a cross-tier-validated finding."""
        path = self.meta_path(md5)
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        val = meta.get("validation")
        if not isinstance(val, dict):
            return False
        val["repair"] = dict(repair)
        try:
            _atomic_write(path, json.dumps(meta).encode())
        except OSError as e:
            WARNING_MSG("corpus repair update failed for %s: %s",
                        md5, e)
            return False
        return True

    def remove(self, md5: str) -> None:
        for p in (self.entry_path(md5), self.meta_path(md5)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def load(self) -> List[CorpusEntry]:
        """Every readable entry, in admission (seq) order.  A missing
        or torn sidecar degrades to default metadata — the input bytes
        are the artifact that must never be lost."""
        entries: List[CorpusEntry] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return entries
        for name in sorted(names):
            if name in _RESERVED or \
                    name.endswith((".json", ".tmp", ".prev")):
                continue
            path = os.path.join(self.root, name)
            if not os.path.isfile(path):
                continue
            try:
                with open(path, "rb") as f:
                    buf = f.read()
            except OSError as e:
                WARNING_MSG("corpus entry %s unreadable: %s", name, e)
                continue
            meta: Dict[str, Any] = {}
            try:
                with open(self.meta_path(name)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {"md5": name, "seq": self._next_seq}
            entries.append(CorpusEntry.from_meta(buf, meta))
        entries.sort(key=lambda e: e.seq)
        if entries:
            self._next_seq = max(self._next_seq,
                                 max(e.seq for e in entries) + 1)
        return entries

    def __len__(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n not in _RESERVED
                       and not n.endswith((".json", ".tmp", ".prev"))
                       and os.path.isfile(os.path.join(self.root, n)))
        except OSError:
            return 0

    # -- crash-consistent checkpoint (resilience/checkpoint.py) ---------

    def save_checkpoint(self, doc: Dict[str, Any]) -> Optional[int]:
        """Write ONE atomic checkpoint epoch covering campaign state,
        solver cache, event seq and component states — a kill at any
        instruction resumes to a consistent campaign.  Sections the
        caller omits (e.g. ``solver`` on a crack-less interval
        persist) carry forward from the previous epoch instead of
        being dropped; ``components`` carries forward PER KEY, so a
        transient ``get_state()`` failure on one component cannot
        erase its last good state from the epoch chain."""
        prev = self.load_checkpoint()
        if prev:
            for section in ("campaign", "solver", "vsa",
                            "event_seq"):
                if section not in doc and section in prev:
                    doc[section] = prev[section]
            pc = prev.get("components")
            if isinstance(pc, dict):
                dc = doc.get("components")
                if isinstance(dc, dict):
                    for k, v in pc.items():
                        dc.setdefault(k, v)
                elif "components" not in doc:
                    doc["components"] = pc
            if not doc.get("epoch"):
                doc["epoch"] = int(prev.get("epoch", 0)) + 1
        epoch = _ckpt.save(self.root, doc, atomic_write=_atomic_write)
        if epoch is not None:
            cached = dict(doc)
            cached["epoch"] = epoch
            self._ckpt_doc = cached
        return epoch

    def load_checkpoint(self) -> Optional[Dict[str, Any]]:
        # this process is the only checkpoint writer for its corpus
        # dir, so the last successfully saved doc is authoritative —
        # interval persists never re-read/re-parse the (potentially
        # large) document from disk
        if self._ckpt_doc is not None:
            return self._ckpt_doc
        return _ckpt.load(self.root)

    # -- campaign state -------------------------------------------------
    #
    # load_state / load_solver_cache / load_component_state read the
    # CHECKPOINT first (the unified epoch is the source of truth) and
    # fall back to the legacy per-file layout, so pre-checkpoint
    # campaigns and offline tools keep working.  The legacy savers
    # remain for non-loop callers (kb-descend rounds, bench sweeps).

    def save_state(self, state: Dict[str, Any]) -> None:
        try:
            _atomic_write(os.path.join(self.root, STATE_FILE),
                          json.dumps(state).encode())
        except OSError as e:
            WARNING_MSG("campaign state write failed: %s", e)

    def load_state(self) -> Optional[Dict[str, Any]]:
        ck = self.load_checkpoint()
        if ck and isinstance(ck.get("campaign"), dict):
            return ck["campaign"]
        try:
            with open(os.path.join(self.root, STATE_FILE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def save_component_state(self, which: str, state: str) -> None:
        """Persist a component's serialized resume state (``mutator``
        or ``instrumentation``) next to the corpus."""
        name = (MUTATOR_STATE_FILE if which == "mutator"
                else INSTR_STATE_FILE)
        try:
            _atomic_write(os.path.join(self.root, name), state.encode())
        except OSError as e:
            WARNING_MSG("%s state write failed: %s", which, e)

    def load_component_state(self, which: str) -> Optional[str]:
        ck = self.load_checkpoint()
        if ck:
            comp = ck.get("components") or {}
            if isinstance(comp.get(which), str):
                return comp[which]
        name = (MUTATOR_STATE_FILE if which == "mutator"
                else INSTR_STATE_FILE)
        try:
            with open(os.path.join(self.root, name)) as f:
                return f.read()
        except OSError:
            return None

    # -- solver cache (crack stage) -------------------------------------

    def save_solver_cache(self, cache: Dict[str, Any]) -> None:
        """Per-edge solve results ("f:t" -> {status, input_hex,
        reason}) — the solver is a pure function of the program, so a
        resumed campaign re-injects/skips instead of re-solving.
        Loop-attached crackers persist through the unified checkpoint
        instead (fuzzer._persist_campaign); this file remains the
        offline-tool path.  When a checkpoint already exists the
        cache ALSO writes through a fresh epoch — checkpoint-first
        loaders would otherwise shadow these newer verdicts with the
        epoch's stale solver section."""
        try:
            _atomic_write(os.path.join(self.root, SOLVER_STATE_FILE),
                          json.dumps(cache).encode())
        except OSError as e:
            WARNING_MSG("solver cache write failed: %s", e)
        if self.load_checkpoint() is not None:
            self.save_checkpoint({"solver": dict(cache)})

    def load_solver_cache(self) -> Dict[str, Any]:
        ck = self.load_checkpoint()
        if ck and isinstance(ck.get("solver"), dict):
            return ck["solver"]
        try:
            with open(os.path.join(self.root, SOLVER_STATE_FILE)) as f:
                d = json.load(f)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    # -- VSA document (value-set fixpoint; analysis/vsa.py) -------------

    def save_vsa_doc(self, doc: Dict[str, Any]) -> None:
        """The serialized value-set fixpoint (``VsaResult.to_doc``) —
        a pure function of the program, keyed by ``program_sig``, so
        ``--resume`` and repeated cracks never re-run the analysis.
        Same dual-write discipline as the solver cache: standalone
        file for offline tools, write-through epoch when a checkpoint
        exists (checkpoint-first loaders must not shadow a newer doc
        with a stale ``vsa`` section)."""
        try:
            _atomic_write(os.path.join(self.root, VSA_STATE_FILE),
                          json.dumps(doc).encode())
        except OSError as e:
            WARNING_MSG("vsa doc write failed: %s", e)
        if self.load_checkpoint() is not None:
            self.save_checkpoint({"vsa": dict(doc)})

    def load_vsa_doc(self) -> Optional[Dict[str, Any]]:
        ck = self.load_checkpoint()
        if ck and isinstance(ck.get("vsa"), dict):
            return ck["vsa"]
        try:
            with open(os.path.join(self.root, VSA_STATE_FILE)) as f:
                d = json.load(f)
            return d if isinstance(d, dict) else None
        except (OSError, ValueError):
            return None
