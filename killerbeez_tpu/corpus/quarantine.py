"""Poisoned-entry quarantine — the fleet's immune system.

Corpus exchange (manager-mediated ``sync.py`` or peer-to-peer
``gossip.py``) admits inputs produced by MACHINES WE DO NOT TRUST: a
misbehaving worker, a corrupted store, a manager whose disk tore a
row, or an attacker on the fleet network can all ship entries that
are oversized, malformed, or lie about their coverage.  Admitting
them poisons the rotation (the scheduler fuzzes garbage), poisons
the dedup sets (a forged ``cov_hash`` masks a real frontier), and —
worst — a crash while *parsing* one kills the worker.

Every synced-in entry therefore passes :class:`EntryValidator`
before admission:

  * **schema** — the row must be a dict with the documented fields
    at the documented types (``content_b64`` str, ``md5`` hex str,
    ``cov_hash`` str, ``meta`` dict-or-None, ``sig`` int-list…);
  * **size caps** — content and metadata are bounded (defaults: 4 MB
    input, 256 KB meta, 65536 signature slots) so one entry cannot
    OOM the worker or bloat every peer's store;
  * **cov_hash recomputed** — the dedup key is re-derived from the
    claimed signature/content (``store.coverage_hash``) and compared;
    a mismatch means the peer lied about (or corrupted) the one field
    the whole exchange dedups by;
  * **optional re-execution** — callers with a local instrumentation
    can pass ``executor(buf) -> sig`` and the entry's claimed
    signature is checked against a real execution.

Failures never raise into the caller: the entry is written to the
quarantine directory (``<corpus>/quarantine/<md5>{,.json}``) for the
operator, the ``sync_quarantined`` counter increments, and — on the
peer path — the offending peer's strike count rises until
:class:`PeerBans` bans it for a decorrelated-backoff interval
(``U[base, 3x previous]``, capped), the same anti-lockstep discipline
as the sync round gate.
"""

from __future__ import annotations

import base64
import binascii
import json
import os
import random
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.fileio import ensure_dir, md5_hex
from ..utils.logging import WARNING_MSG
from .store import (
    CorpusEntry, MAX_VALIDATION_REPEATS, REPAIR_VERDICTS,
    VALIDATION_VERDICTS,
    coverage_hash,
)

#: quarantine subdirectory under a corpus store root
QUARANTINE_DIR = "quarantine"


class EntryValidator:
    """Validate one exchange row before it becomes a corpus entry.

    ``validate(row)`` returns ``(entry, reason)``: a
    :class:`CorpusEntry` and ``None`` on success, or ``None`` and a
    short machine-greppable reason string on failure.  Pure and
    exception-free — a validator that crashes on hostile input is
    itself the vulnerability.
    """

    def __init__(self, max_content_bytes: int = 4 << 20,
                 max_meta_bytes: int = 256 << 10,
                 max_sig_slots: int = 65536,
                 executor: Optional[Callable[[bytes], Any]] = None):
        self.max_content_bytes = int(max_content_bytes)
        self.max_meta_bytes = int(max_meta_bytes)
        self.max_sig_slots = int(max_sig_slots)
        #: optional re-execution hook: bytes -> edge-slot list (the
        #: local instrumentation); claimed signatures must reproduce
        self.executor = executor

    def validate(self, row: Any) -> Tuple[Optional[CorpusEntry],
                                          Optional[str]]:
        try:
            return self._validate(row)
        except Exception as e:      # hostile input must never raise
            return None, f"validator-error:{type(e).__name__}"

    def _validate(self, row: Any) -> Tuple[Optional[CorpusEntry],
                                           Optional[str]]:
        if not isinstance(row, dict):
            return None, "schema:not-a-dict"
        b64 = row.get("content_b64")
        if not isinstance(b64, str):
            return None, "schema:content_b64"
        # cheap pre-decode cap: 4 b64 chars ~ 3 bytes
        if len(b64) > (self.max_content_bytes * 4) // 3 + 8:
            return None, "size:content"
        try:
            buf = base64.b64decode(b64, validate=True)
        except (binascii.Error, ValueError):
            return None, "schema:content_b64-decode"
        if len(buf) > self.max_content_bytes:
            return None, "size:content"
        if not buf:
            return None, "schema:empty-content"
        md5 = row.get("md5")
        if md5 is not None and md5 != "":
            if not (isinstance(md5, str) and len(md5) == 32 and
                    all(c in "0123456789abcdef" for c in md5)):
                return None, "schema:md5"
            if md5 != md5_hex(buf):
                return None, "integrity:md5-mismatch"
        meta = row.get("meta")
        if meta is None:
            meta = {}
        if not isinstance(meta, dict):
            return None, "schema:meta"
        try:
            if len(json.dumps(meta)) > self.max_meta_bytes:
                return None, "size:meta"
        except (TypeError, ValueError):
            return None, "schema:meta-not-json"
        sig = meta.get("sig")
        if sig is not None:
            if not isinstance(sig, list) or \
                    len(sig) > self.max_sig_slots or \
                    not all(isinstance(s, int) and 0 <= s < (1 << 32)
                            for s in sig):
                return None, "schema:sig"
        ssig = meta.get("state_sig")
        if ssig is not None:
            if not isinstance(ssig, list) or \
                    len(ssig) > self.max_sig_slots or \
                    not all(isinstance(p, list) and len(p) == 2
                            and all(isinstance(v, int)
                                    and 0 <= v < (1 << 32) for v in p)
                            for p in ssig):
                return None, "schema:state_sig"
        hits = meta.get("edge_hits")
        if hits is not None:
            if not isinstance(hits, dict) or \
                    len(hits) > self.max_sig_slots:
                return None, "schema:edge_hits"
            try:
                for k, v in hits.items():
                    int(k), int(v)
            except (TypeError, ValueError):
                return None, "schema:edge_hits"
        prov = meta.get("provenance")
        if prov is not None:
            # mutation provenance (learn tier, optional): mutator id,
            # stage, packed mutated-byte bitmap.  Bounded and typed —
            # a peer must not be able to ship a multi-megabyte
            # "bitmap" or a non-string mutator through the learn
            # tier's label path.  Old rows without it pass untouched.
            if not isinstance(prov, dict):
                return None, "schema:provenance"
            if not isinstance(prov.get("mutator"), str) or \
                    len(prov["mutator"]) > 64:
                return None, "schema:provenance"
            stage = prov.get("stage")
            if stage is not None and not (isinstance(stage, str)
                                          and len(stage) <= 64):
                return None, "schema:provenance"
            bm = prov.get("bitmap")
            if bm is not None:
                # packbits over the content: ~len(buf)/6 b64 chars
                if not isinstance(bm, str) or \
                        len(bm) > (len(buf) // 8) * 2 + 16:
                    return None, "schema:provenance"
            nb = prov.get("bytes")
            if nb is not None and not (isinstance(nb, int)
                                       and 0 <= nb <= len(buf)):
                return None, "schema:provenance"
        tier = meta.get("tier")
        if tier is not None:
            # hybrid tier tag (docs/HYBRID.md): a short identifier —
            # a peer must not be able to ship arbitrary blobs through
            # the per-tier fold.  Old rows without it pass untouched.
            if not isinstance(tier, str) or not (0 < len(tier) <= 32) \
                    or not all(c.isalnum() or c in "-_" for c in tier):
                return None, "schema:tier"
        val = meta.get("validation")
        if val is not None:
            # cross-tier verdict write-back (hybrid bridge): verdict
            # from the fixed taxonomy plus bounded numeric fields —
            # the claim "native-confirmed" steers scheduling, so its
            # shape is checked as strictly as provenance.
            if not isinstance(val, dict):
                return None, "schema:validation"
            if val.get("verdict") not in VALIDATION_VERDICTS:
                return None, "schema:validation"
            vtier = val.get("tier")
            if vtier is not None and not (isinstance(vtier, str)
                                          and len(vtier) <= 32):
                return None, "schema:validation"
            for key in ("repro", "repeats", "attempts"):
                v = val.get(key)
                if v is not None and not (isinstance(v, int)
                                          and 0 <= v <= 4096):
                    return None, "schema:validation"
            t = val.get("t")
            if t is not None and not isinstance(t, (int, float)):
                return None, "schema:validation"
            sts = val.get("statuses")
            if sts is not None:
                if not isinstance(sts, list) or \
                        len(sts) > MAX_VALIDATION_REPEATS or \
                        not all(isinstance(s, int) for s in sts):
                    return None, "schema:validation"
            detail = val.get("detail")
            if detail is not None and not (isinstance(detail, str)
                                           and len(detail) <= 256):
                return None, "schema:validation"
            repair = val.get("repair")
            if repair is not None:
                # kb-repair / --auto-repair write-back: verdict from
                # the fixed (honest) taxonomy, bounded strings — a
                # "repaired" claim changes which proxy peers trust,
                # so its shape syncs as strictly as the verdict's
                if not isinstance(repair, dict) or \
                        repair.get("verdict") not in REPAIR_VERDICTS:
                    return None, "schema:repair"
                rt = repair.get("t")
                if rt is not None and not isinstance(rt,
                                                     (int, float)):
                    return None, "schema:repair"
                for key in ("patch", "reason"):
                    v = repair.get(key)
                    if v is not None and not (isinstance(v, str)
                                              and len(v) <= 256):
                        return None, "schema:repair"
        for key in ("selections", "finds", "discovered", "seq"):
            v = meta.get(key)
            if v is not None and not isinstance(v, (int, float)):
                return None, f"schema:{key}"
        for key in ("parent", "source"):
            v = meta.get(key)
            if v is not None and not isinstance(v, str):
                return None, f"schema:{key}"
        # the field the whole exchange dedups by: re-derive and compare
        claimed = row.get("cov_hash", meta.get("cov_hash"))
        if claimed is not None:
            if not isinstance(claimed, str) or len(claimed) > 256:
                return None, "schema:cov_hash"
            if claimed != coverage_hash(sig, buf, ssig):
                return None, "integrity:cov_hash-mismatch"
        if self.executor is not None and sig:
            try:
                got = self.executor(bytes(buf))
            except Exception as e:
                return None, f"reexec-error:{type(e).__name__}"
            if got is not None and \
                    sorted(set(int(s) for s in got)) != \
                    sorted(set(int(s) for s in sig)):
                return None, "integrity:reexec-sig-mismatch"
        entry_meta = dict(meta)
        entry_meta.setdefault("md5", md5 or None)
        if claimed is not None:
            entry_meta["cov_hash"] = claimed
        return CorpusEntry.from_meta(buf, entry_meta), None


class QuarantineStore:
    """On-disk quarantine: rejected entries land in
    ``<root>/quarantine/`` as ``<md5>`` (raw bytes) + ``<md5>.json``
    (reason, peer, wall time) so an operator can inspect what the
    fleet refused — and a bug in the validator itself never silently
    destroys a real finding."""

    def __init__(self, root: str):
        self.root = os.path.join(str(root), QUARANTINE_DIR)
        self._ready = False

    def put(self, buf: bytes, reason: str,
            peer: Optional[str] = None) -> None:
        try:
            if not self._ready:
                ensure_dir(self.root)
                self._ready = True
            digest = md5_hex(buf)
            path = os.path.join(self.root, digest)
            if not os.path.exists(path):
                with open(path, "wb") as f:
                    f.write(buf)
            with open(path + ".json", "w") as f:
                json.dump({"md5": digest, "reason": reason,
                           "peer": peer, "t": time.time()}, f)
        except OSError as e:    # quarantine must never kill the loop
            WARNING_MSG("quarantine write failed: %s", e)

    def load(self):
        """[(md5, reason-record dict)] for tools/tests."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    out.append((name[:-5], json.load(f)))
            except (OSError, ValueError):
                continue
        return out

    def __len__(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n.endswith(".json"))
        except OSError:
            return 0


class PeerBans:
    """Strike ledger: ``threshold`` quarantined entries from one peer
    ban it for a decorrelated-backoff interval (next ban length ~
    U[base, 3x previous], capped) — repeat offenders stay out longer,
    and a fleet full of healthy peers never bans in lockstep.  A
    clean pull resets the peer's strike count (transient corruption
    is forgiven; persistent poisoning is not)."""

    def __init__(self, threshold: int = 3, base_s: float = 60.0,
                 cap_s: float = 3600.0,
                 rng: Optional[random.Random] = None,
                 time_fn=time.time):
        self.threshold = int(threshold)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self._rng = rng or random.Random()
        self._time = time_fn
        #: peer -> consecutive quarantined-entry strikes
        self.strikes: Dict[str, int] = {}
        #: peer -> ban expiry (wall clock)
        self.banned_until: Dict[str, float] = {}
        #: peer -> previous ban length (decorrelated backoff state)
        self._prev_ban: Dict[str, float] = {}
        #: lifetime ban count (the ``peers_banned`` counter delta
        #: source)
        self.total_bans = 0

    def strike(self, peer: str, n: int = 1) -> bool:
        """Record ``n`` quarantined entries from ``peer``; returns
        True when this crossed the threshold and the peer is now
        banned."""
        s = self.strikes.get(peer, 0) + int(n)
        self.strikes[peer] = s
        if s < self.threshold or self.is_banned(peer):
            return False
        prev = self._prev_ban.get(peer, 0.0)
        length = min(self.cap_s,
                     self._rng.uniform(self.base_s,
                                      max(self.base_s, 3.0 * prev)))
        self._prev_ban[peer] = length
        self.banned_until[peer] = self._time() + length
        self.strikes[peer] = 0          # strikes reset per ban epoch
        self.total_bans += 1
        WARNING_MSG("gossip: banning peer %s for %.0fs "
                    "(%d poisoned entries)", peer, length, s)
        return True

    def clean(self, peer: str) -> None:
        """A pull from ``peer`` validated clean: forgive its strikes
        (the ban backoff state keeps its memory)."""
        self.strikes.pop(peer, None)

    def is_banned(self, peer: str) -> bool:
        until = self.banned_until.get(peer)
        if until is None:
            return False
        if self._time() >= until:
            del self.banned_until[peer]
            return False
        return True

    def active(self) -> Dict[str, float]:
        """{peer: seconds remaining} for every live ban."""
        now = self._time()
        return {p: round(u - now, 1)
                for p, u in self.banned_until.items() if u > now}
