"""Manager-mediated corpus exchange — fleet workers share findings.

The reference's fleet shares coverage only through operators running
the merger tool between campaigns; here workers exchange the corpus
itself while running: every edge-novel entry is POSTed to the
manager's ``/api/corpus/<campaign>`` (deduped server-side by coverage
hash), and each worker periodically pulls peers' entries into its
local store and rotation — one worker's frontier becomes every
worker's next seed.

Transport discipline adapts the stats heartbeats' to an IN-LOOP
caller: HTTP-level rejections fail fast per entry (the manager saw
the request — retrying is a poison pill), transport errors abort the
ROUND, and — because ``maybe_sync()`` runs on the fuzzing-loop
thread, not a heartbeat thread — the in-loop default is a single
attempt per request (``attempts=1``): the interval gate already
retries at round granularity, so a dead manager costs one failed
connection per round instead of inline backoff sleeps.  Failed
rounds widen the gate with DECORRELATED jitter (next extra delay
drawn from U[interval, 3x previous], capped) so a recovering manager
is not hit by the whole fleet in interval-lockstep, and the
``sync_consecutive_failures`` gauge tells kb-fleet's stall alert
"partitioned" apart from "plateaued".  Everything degrades to
warnings — corpus sync must never stall or kill the fuzzing loop.

Every PULLED row passes the poisoned-entry quarantine
(``quarantine.EntryValidator``) before admission: schema and size
caps, ``cov_hash`` recomputed and compared, optional re-execution.
Failures land in ``<corpus>/quarantine/`` and the
``sync_quarantined`` counter — a corrupt manager row (or, on the
gossip path, a lying peer) can never crash a worker or poison its
rotation.  ``gossip.GossipSync`` extends this client with the
peer-to-peer exchange tier.
"""

from __future__ import annotations

import base64
import contextlib
import random
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ..utils.logging import DEBUG_MSG, WARNING_MSG
from .quarantine import EntryValidator, QuarantineStore
from .schedule import Arm
from .store import CorpusEntry


class CorpusSync:
    """One campaign's exchange client: push local edge-novel entries,
    pull peers' entries into the local store + scheduler."""

    def __init__(self, manager_url: str, campaign: str,
                 worker: str = "anon", interval_s: float = 30.0,
                 attempts: int = 1, backoff_cap: Optional[float] = None,
                 rng: Optional[random.Random] = None,
                 validator: Optional[EntryValidator] = None):
        self.url = f"{manager_url.rstrip('/')}/api/corpus/{campaign}"
        self.manager_url = manager_url.rstrip("/")
        self.campaign = str(campaign)
        self.worker = worker
        #: poisoned-entry gate on every pulled row (default on;
        #: ``validator=False`` disables — raw-transport tests only)
        if validator is None:
            validator = EntryValidator()
        self.validator = validator or None
        #: rejected rows from the LAST pull: [(buf|None, reason,
        #: peer|None)] — the sync round writes them to quarantine +
        #: counters and strikes the offending peer
        self._quarantined_round: List[
            Tuple[Optional[bytes], str, Optional[str]]] = []
        self.quarantined_n = 0
        self.interval_s = float(interval_s)
        self.attempts = int(attempts)
        self._last_sync = 0.0
        # round backoff after transport failures: DECORRELATED jitter
        # (next extra delay ~ U[interval, 3*previous], capped) — a
        # whole fleet whose manager just recovered must NOT retry in
        # interval-lockstep, which is exactly what a deterministic
        # backoff would produce across workers started together
        self.backoff_cap = (float(backoff_cap) if backoff_cap
                            else 16.0 * self.interval_s)
        self._backoff = 0.0              # extra delay beyond interval
        self._rng = rng or random.Random()
        #: consecutive failed rounds — surfaced as the
        #: ``sync_consecutive_failures`` gauge so kb-fleet's
        #: coverage-stall alert can tell "plateaued" from
        #: "partitioned"
        self.consecutive_failures = 0
        self._pushed: Set[str] = set()      # cov_hashes sent (or known)
        self._pending: List[CorpusEntry] = []   # admitted, not yet sent
        self._store_scanned = False
        self._cursor = 0                     # server-side id high-water
        self.pushed_n = 0
        self.pulled_n = 0

    def note_entry(self, entry: CorpusEntry) -> None:
        """The loop hands every admitted entry here at triage time;
        the next sync round pushes it.  O(1) — no store rescans."""
        self._pending.append(entry)

    def close(self) -> None:
        """Release any transport resources (the gossip subclass shuts
        its sidecar down here); the manager-only client holds none."""

    # -- transport (heartbeat discipline) -------------------------------

    def _request(self, payload: Optional[Dict[str, Any]] = None,
                 method: str = "POST",
                 query: str = "") -> Any:
        from ..manager.worker import _request_retry
        return _request_retry(self.url + query, payload, method,
                              attempts=self.attempts)

    # -- push -----------------------------------------------------------

    def push_entry(self, entry: CorpusEntry) -> Optional[bool]:
        """POST one entry; True when the manager stored it as new,
        False when it was a coverage-hash duplicate or the manager
        REJECTED it (HTTP error: the request arrived and was refused
        — retrying the same entry forever would poison every future
        round), None on transport failure (the caller aborts the
        round — one failed request must not become one backoff cycle
        PER entry)."""
        import urllib.error
        if entry.cov_hash in self._pushed:
            return False
        try:
            resp = self._request({
                "worker": self.worker,
                "md5": entry.md5,
                "cov_hash": entry.cov_hash,
                "content_b64": base64.b64encode(entry.buf).decode(),
                "meta": entry.meta_dict(),
            })
        except urllib.error.HTTPError as e:
            if getattr(e, "code", None) == 503:
                # write-degraded manager: "try again later", NOT a
                # rejection — dropping the entry here would lose it
                # from sync forever once the manager recovers
                WARNING_MSG("corpus push to %s deferred (manager "
                            "degraded): %s", self.url, e)
                return None
            WARNING_MSG("corpus push rejected by %s (%s): dropping "
                        "entry %s from sync", self.url, e, entry.md5)
            self._pushed.add(entry.cov_hash)    # never retried
            return False
        except Exception as e:
            WARNING_MSG("corpus push to %s failed: %s", self.url, e)
            return None
        self._pushed.add(entry.cov_hash)
        if resp and resp.get("new"):
            self.pushed_n += 1
            return True
        return False

    # -- pull -----------------------------------------------------------

    def _entries_from_rows(self, rows: Any,
                           peer: Optional[str] = None
                           ) -> List[CorpusEntry]:
        """Exchange rows -> validated, locally-unseen entries.  Rows
        the validator rejects go to ``_quarantined_round`` (the sync
        round writes them to the quarantine store, bumps counters and
        strikes the peer) instead of ever reaching admission."""
        out: List[CorpusEntry] = []
        if not isinstance(rows, list):
            self._quarantined_round.append(
                (None, "schema:entries-not-a-list", peer))
            return out
        for row in rows:
            cov = row.get("cov_hash", "") \
                if isinstance(row, dict) else ""
            if cov and cov in self._pushed:
                continue                 # already have this frontier
            if self.validator is not None:
                entry, reason = self.validator.validate(row)
                if entry is None:
                    buf = None
                    if isinstance(row, dict) and \
                            isinstance(row.get("content_b64"), str):
                        try:
                            buf = base64.b64decode(
                                row["content_b64"][: (8 << 20)])
                        except Exception:
                            buf = None
                    self._quarantined_round.append((buf, reason, peer))
                    continue
            else:
                try:
                    buf = base64.b64decode(row["content_b64"])
                except (KeyError, TypeError, ValueError):
                    continue
                meta = dict(row.get("meta") or {})
                meta.setdefault("md5", row.get("md5"))
                entry = CorpusEntry.from_meta(buf, meta)
            entry.source = "sync"
            # NOT added to _pushed here: only an ADMITTED foreign
            # entry is excluded from pushing (_admit_entries).  An
            # entry we authored ourselves can gossip back to us
            # before we ever reached the manager (hub down, peers
            # re-serving what they learned) — marking it known here
            # would mean NOBODY ever pushes it and the recovered
            # manager misses it forever.
            out.append(entry)
        return out

    def pull(self) -> Optional[List[CorpusEntry]]:
        """GET peers' entries newer than the cursor; returns the new
        (locally unseen, not self-authored, validator-clean) ones —
        None on transport failure (the round counts as failed and
        backs off)."""
        from urllib.parse import quote
        try:
            resp = self._request(
                None, method="GET",
                query=f"?since={self._cursor}"
                      f"&exclude={quote(self.worker, safe='')}")
        except Exception as e:
            WARNING_MSG("corpus pull from %s failed: %s", self.url, e)
            return None
        if not resp:
            return []
        try:
            self._cursor = max(self._cursor,
                               int(resp.get("latest", 0)))
        except (TypeError, ValueError):
            pass                         # hostile latest: keep cursor
        return self._entries_from_rows(resp.get("entries", []))

    # -- loop hook ------------------------------------------------------

    def maybe_sync(self, fuzzer, force: bool = False) -> bool:
        """Called by the loop between batches: when the interval has
        elapsed, push unsynced local arms/store entries and fold
        peers' entries into the local store, scheduler and dedup set.
        ``force`` skips the interval gate — the loop forces one round
        after its end-of-run drain, so findings triaged after the
        last in-loop sync (short campaigns triage EVERYTHING in the
        drain) still reach the fleet.  Returns True when a sync round
        ran."""
        now = time.time()
        gate = self.interval_s + self._backoff
        if not force and now - self._last_sync < gate:
            return False
        self._last_sync = now
        # flight recorder: the round gets its own trace lane (a slow
        # round shows up as host time stolen from the pipeline) and a
        # sync_round event carrying the per-round deltas
        tr = fuzzer.telemetry.trace
        with (tr.span("sync_round", lane="sync") if tr is not None
              else contextlib.nullcontext()):
            return self._sync_round(fuzzer)

    def _sync_round(self, fuzzer) -> bool:
        reg = fuzzer.telemetry.registry
        # push set: entries the loop admitted since the last round
        # (note_entry, O(1)) plus — ONCE, for resumed campaigns — the
        # pre-existing store and rotation arms; never a per-round
        # store rescan
        batch: List[CorpusEntry] = self._pending
        self._pending = []
        if not self._store_scanned:
            self._store_scanned = True
            batch = batch + [a.to_entry()
                             for a in fuzzer.scheduler.arms]
            if fuzzer.store is not None:
                batch = batch + fuzzer.store.load()
        sent = 0
        failed = False
        seen_local: Set[str] = set()
        for i, e in enumerate(batch):
            if e.source == "sync":
                # a previously-PULLED entry (resume): known frontier —
                # never pushed back, and the pull loop must not
                # re-admit it after a restart resets the cursor
                self._pushed.add(e.cov_hash)
                continue
            if e.cov_hash in seen_local or e.cov_hash in self._pushed:
                continue
            seen_local.add(e.cov_hash)
            ok = self.push_entry(e)
            if ok is None:
                # transport down: requeue the remainder and bail —
                # one backoff cycle per ROUND, not per entry
                self._pending = [x for x in batch[i:]
                                 if x.cov_hash not in self._pushed] \
                    + self._pending
                failed = True
                break
            sent += int(ok)
        # pull: peers' frontier into store + rotation
        pulled = 0
        if not failed:
            got = self.pull()
            if got is None:
                failed = True
                got = []
            pulled = self._admit_entries(fuzzer, got)
        # the gossip tier (peer fanout pulls) rides the same round;
        # its transport failures back off PEERS, never the round
        self._peer_round(fuzzer, reg)
        self._flush_quarantine(fuzzer, reg)
        # per-round deltas: restored cumulative counters (--resume)
        # keep counting up instead of snapping to process-local totals
        if sent:
            reg.count("corpus_synced_out", sent)
        if pulled:
            reg.count("corpus_synced_in", pulled)
        if failed:
            self.consecutive_failures += 1
            self._backoff = min(
                self.backoff_cap,
                self._rng.uniform(self.interval_s,
                                  max(self.interval_s,
                                      3.0 * self._backoff)))
            DEBUG_MSG("corpus sync: round failed (%d in a row); "
                      "next round in ~%.1fs",
                      self.consecutive_failures,
                      self.interval_s + self._backoff)
        else:
            self.consecutive_failures = 0
            self._backoff = 0.0
        reg.gauge("sync_consecutive_failures",
                  self.consecutive_failures)
        reg.gauge("corpus_arms", len(fuzzer.scheduler.arms))
        fuzzer.telemetry.event(
            "sync_round", pushed=int(sent), pulled=int(pulled),
            transport_failed=bool(failed))
        return True

    # -- shared admission / quarantine plumbing -------------------------

    def _admit_entries(self, fuzzer, entries: List[CorpusEntry]) -> int:
        """Fold validated pulled entries into the local store,
        dedup set and rotation; returns how many were new here."""
        admitted = 0
        for e in entries:
            if e.md5 in fuzzer._seen["new_paths"]:
                continue            # already local (e.g. post-resume)
            self._pushed.add(e.cov_hash)    # foreign: never push back
            admitted += 1
            self.pulled_n += 1
            if fuzzer.store is not None:
                e.seq = fuzzer.store.next_seq()
                fuzzer.store.put(e)
            # a pulled entry is a known path now: don't re-record
            # it as a local finding if this worker reproduces it
            fuzzer._seen["new_paths"].add(e.md5)
            if fuzzer.feedback:
                fuzzer.scheduler.admit(Arm.from_entry(e))
            DEBUG_MSG("corpus sync: pulled %s from %s", e.md5,
                      e.parent or "peer")
        return admitted

    def _peer_round(self, fuzzer, reg) -> None:
        """Gossip hook — the manager-only client has no peers."""

    def _flush_quarantine(self, fuzzer, reg) -> None:
        """Write the round's rejected rows to the quarantine store
        (when a durable corpus exists) and count them; subclasses
        strike the offending peers here too."""
        if not self._quarantined_round:
            return
        batch = self._quarantined_round
        self._quarantined_round = []
        qstore = (QuarantineStore(fuzzer.store.root)
                  if fuzzer.store is not None else None)
        for buf, reason, peer in batch:
            self.quarantined_n += 1
            who = peer or "manager"
            WARNING_MSG("corpus sync: quarantined entry from %s "
                        "(%s)", who, reason)
            if qstore is not None and buf:
                qstore.put(buf, reason, peer=who)
        reg.count("sync_quarantined", len(batch))
        fuzzer.telemetry.event(
            "sync_quarantine", n=len(batch),
            reasons=sorted({r for _, r, _ in batch}))
