"""Persistent corpus subsystem.

The reference treats the corpus as a durable artifact (``new_paths/``
on disk, merger/picker tools, manager-distributed seed sets); the
loop's in-memory rotation list lost every arm, its bandit stats and
its lineage on exit, and fleet workers never saw each other's
findings.  This package makes the corpus first-class:

  * ``store.py``    — on-disk corpus store: one buffer file per entry
    keyed by md5 plus a JSON metadata sidecar (coverage signature,
    bandit stats, lineage, discovery order), atomic-rename writes,
    and a campaign-state record that lets ``--resume`` continue a
    killed campaign exactly where it stopped.
  * ``schedule.py`` — the seed-scheduling policy behind the loop's
    rotation, extracted into a ``Scheduler`` interface: ``bandit``
    (the default greedy-optimistic decay bandit, behavior-preserving),
    ``rare-edge`` (FairFuzz-style rarest-edge preference) and ``rr``
    (round-robin baseline).
  * ``sync.py``     — manager-mediated corpus exchange: workers POST
    edge-novel entries to ``/api/corpus/<campaign>`` and periodically
    pull peers' entries into their local store (coverage-hash dedup,
    heartbeat-style retry/backoff).
  * ``gossip.py``   — peer-to-peer corpus gossip: every worker serves
    its own entries behind the same cursor API (``GossipSidecar``)
    and pulls a random fanout of peers each round, with the manager
    demoted to peer directory + anti-entropy backstop — a dead hub
    no longer stops corpus flow.
  * ``quarantine.py`` — the poisoned-entry gate on every synced-in
    row: schema/size validation, ``cov_hash`` recompute, disk
    quarantine and decorrelated-backoff peer bans.
"""

from __future__ import annotations

from .gossip import GossipSidecar, GossipSync
from .quarantine import EntryValidator, PeerBans, QuarantineStore
from .schedule import (
    Arm, BanditScheduler, RareEdgeScheduler, RoundRobinScheduler,
    SCHEDULERS, Scheduler, make_scheduler,
)
from .store import CorpusEntry, CorpusStore
from .sync import CorpusSync

__all__ = [
    "Arm", "BanditScheduler", "CorpusEntry", "CorpusStore",
    "CorpusSync", "EntryValidator", "GossipSidecar", "GossipSync",
    "PeerBans", "QuarantineStore", "RareEdgeScheduler",
    "RoundRobinScheduler", "SCHEDULERS", "Scheduler",
    "make_scheduler",
]
