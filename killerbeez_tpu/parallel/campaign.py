"""Multi-chip fuzzing campaigns — the sharded step as a Driver.

The reference scales out as N independent fuzzer processes plus an
offline merger and a manager handing out jobs
(dynamorio_instrumentation.c:418-431 multi-instance fuzzer_ids,
merger/merger.c:79-108).  Here one CLI invocation IS the fleet: the
(dp, mp) `shard_map` step executes batch_per_device lanes per chip
with per-step ICI collectives doing the merger's AND-fold online,
and this adapter routes its verdicts through the ordinary
`Fuzzer._record` path so findings land md5-deduped in
``output/{crashes,hangs,new_paths}`` exactly like a single-chip run.

State flows through the attached jit_harness instrumentation: its
virgin maps seed the sharded state (so ``-isf`` resume works), and
after every step they point at the mp-sharded device arrays, so
``get_state()`` exports the standard merger-compatible JSON
(np.asarray gathers the shards).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..drivers.base import BatchOutcome, Driver
from ..instrumentation.base import BatchResult, CompactReport
from ..ops.generations import MeshGenerationOutcome, gen_ring_caps
from ..telemetry import merge, merge_two
from ..utils.logging import INFO_MSG
from .distributed import (
    ShardedFuzzState, make_mesh, make_sharded_fuzz_step,
    make_sharded_generations, shard_stat_snapshots,
    sharded_gen_ring_init,
)


def parse_mesh_spec(spec: str):
    """"dp,mp" (e.g. "4,2") -> (dp, mp); bare "4" means mp=1."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) == 1:
        parts.append("1")
    if len(parts) != 2:
        raise ValueError(f"mesh spec {spec!r}: expected 'dp,mp'")
    try:
        dp, mp = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"mesh spec {spec!r}: expected integers")
    if dp < 1 or mp < 1:
        raise ValueError(f"mesh spec {spec!r}: axes must be >= 1")
    return dp, mp


class ShardedCampaignDriver(Driver):
    """Driver running the (dp, mp)-sharded fuzz step each batch.

    Candidates are generated per-chip from mesh-shape-independent
    per-global-lane PRNG keys (the sharded step's contract), executed
    with the instrumentation's engine, and triaged against mp-sharded
    virgin maps with ICI collectives — the host only sees verdict
    arrays and candidate tensors.
    """

    name = "sharded"

    def __init__(self, mesh_spec, instrumentation, mutator,
                 batch_size: int, interpret: Optional[bool] = None):
        # bypass Driver.__init__ option parsing: this driver is
        # constructed by the CLI mesh path, not the factory
        self.options = {}
        self.instrumentation = instrumentation
        self.mutator = mutator
        self.last_input = None
        self._check_input_info()

        n_dp, n_mp = parse_mesh_spec(mesh_spec)
        if batch_size % n_dp:
            raise ValueError(
                f"batch size {batch_size} not divisible by dp={n_dp}")
        self.batch_per_device = batch_size // n_dp
        self.mesh = make_mesh(n_dp, n_mp)
        if interpret is None:
            # pallas engines need interpret mode off-TPU (CPU mesh)
            interpret = jax.default_backend() != "tpu"
        prog = instrumentation.program
        engine = instrumentation.engine
        # stateful session tier: inherit the instrumentation's
        # resolved StatefulSpec (jit_harness coerced the engine to
        # xla already); the state x edge map rides the mesh state as
        # a P("dp")-sharded [dp, M] block, dp-folded like the
        # classic maps
        sspec = getattr(instrumentation, "stateful_spec", None)
        self._stateful = None if sspec is None else (
            sspec.m_max, sspec.n_states, sspec.state_reg)
        self._step = make_sharded_fuzz_step(
            prog, self.mesh, self.batch_per_device,
            max_len=mutator.max_length,
            stack_pow2=int(mutator.options.get("stack_pow2", 4)),
            engine=engine, interpret=interpret,
            seed=int(mutator.options.get("seed", 0)),
            stateful=self._stateful)
        # seed the device state from the instrumentation's maps so
        # -isf resume and merged states carry over
        spec = NamedSharding(self.mesh, P("mp"))
        if self._stateful is not None:
            vs_np = np.tile(np.asarray(instrumentation.virgin_state),
                            (n_dp, 1))
        else:
            vs_np = np.full((n_dp, 1), 0xFF, np.uint8)
        self.state = ShardedFuzzState(
            virgin_bits=jax.device_put(
                jnp.asarray(np.asarray(instrumentation.virgin_bits)),
                spec),
            virgin_crash=jax.device_put(
                jnp.asarray(np.asarray(instrumentation.virgin_crash)),
                spec),
            virgin_tmout=jax.device_put(
                jnp.asarray(np.asarray(instrumentation.virgin_tmout)),
                spec),
            step=jnp.int32(0),
            virgin_state=jax.device_put(
                jnp.asarray(vs_np),
                NamedSharding(self.mesh, P("dp"))),
        )
        #: accumulated mesh-wide stats: per-shard snapshots folded
        #: through telemetry.aggregate each sync epoch (associative,
        #: so per-epoch folds compose into the campaign total)
        self.fleet_stats: dict = {}
        self._host_step = 0   # mirrors state.step without device syncs
        #: mesh-resident generation loop state (--generations on
        #: --mesh): the dispatch builder + per-dp-shard seed rings,
        #: built lazily on the first generations dispatch
        self._gen_dispatch = None
        self._gen_ring = None
        self._gen_ring_key = None
        self._gen_count = 0
        self._gen_cap = 0
        self._interpret = interpret
        INFO_MSG("sharded campaign: mesh dp=%d mp=%d, %d lanes/chip, "
                 "engine=%s", n_dp, n_mp, self.batch_per_device, engine)

    @property
    def supports_batch(self) -> bool:
        return True

    @property
    def batch_quantum(self) -> int:
        """The loop may only request whole mesh batches."""
        return self.batch_per_device * self.mesh.shape["dp"]

    def _check_full_batch(self, n: int) -> None:
        b = self.batch_per_device * self.mesh.shape["dp"]
        if n != b:
            raise ValueError(
                f"sharded campaigns run full batches: asked {n}, "
                f"mesh batch is {b} (use -n as a multiple of -b)")

    def _sync_after(self, bufs, lens, n: int, execs: int) -> None:
        """Post-step bookkeeping shared by the per-batch and K-step
        paths: expose the sharded maps through the instrumentation
        (get_state()/merge()/coverage_bytes() see campaign coverage)
        and defer last-input materialization."""
        instr = self.instrumentation
        instr.virgin_bits = self.state.virgin_bits
        instr.virgin_crash = self.state.virgin_crash
        instr.virgin_tmout = self.state.virgin_tmout
        if self._stateful is not None:
            # dp rows are fold-identical; row 0 is the canonical view
            # get_state()/merge()/state_coverage_stats() export
            instr.virgin_state = self.state.virgin_state[0]
        instr.total_execs += execs
        # mesh telemetry fold: one merge of the dp shards' epoch
        # snapshots, accumulated into the campaign view (host-side
        # values only — never forces a device sync) and surfaced
        # through the loop's registry so stats.jsonl / kb-stats show
        # the mesh shape and shard clock alongside the loop counters
        self._host_step += execs // max(self.batch_quantum, 1)
        epoch = merge(shard_stat_snapshots(
            self.mesh, execs // self.mesh.shape["dp"],
            self._host_step))
        if epoch is not None:
            self.fleet_stats = merge_two(self.fleet_stats, epoch)
            timer = self.stage_timer
            if timer is not None:
                for k, v in self.fleet_stats["gauges"].items():
                    timer.reg.gauge(k, v)
                timer.reg.gauge("mesh_dp", self.mesh.shape["dp"])
                timer.reg.gauge("mesh_mp", self.mesh.shape["mp"])
                # flight recorder: one instant per dp shard per step
                # on a named shard lane, so the trace shows the mesh
                # clock advancing next to the host pipeline lanes
                tr = getattr(timer, "tracer", None)
                if tr is not None:
                    per_shard = execs // self.mesh.shape["dp"]
                    for i in range(self.mesh.shape["dp"]):
                        tr.instant(
                            "shard_step",
                            lane=tr.lane_id(f"shard-{i}"),
                            args={"step": self._host_step,
                                  "execs": per_shard})
        if n > 0:
            self._last_batch_tail = (bufs, lens, n - 1)
            self.last_input = None

    def test_batch(self, n: int, pad_to: Optional[int] = None,
                   prefetch_next=True) -> BatchOutcome:
        self._check_full_batch(n)
        mut = self.mutator
        its = mut.peek_iterations(n)
        # PRNG step: fold the RAW absolute mutator iteration into the
        # keys, not a derived batch counter.  Iterations are consumed
        # monotonically, so a state resumed under a DIFFERENT -b can
        # never land on a (step, lane) pair an earlier run already
        # used — any division-derived counter (floor or ceil) can
        # collide when the batch size changes across a resume.  Passed
        # as the raw Python int: the step folds all 64 bits (two
        # uint32 halves), so campaigns past 2^32 execs neither crash
        # (NumPy 2.x uint32 conversion) nor replay old key pairs.
        base_it = int(its[0])
        seed_buf = jnp.asarray(mut.seed_buf)
        (self.state, statuses, rets, uc, uh, exit_codes, bufs,
         lens, compact) = self._step(self.state, seed_buf,
                                     jnp.int32(mut.seed_len),
                                     base_it)
        mut.advance(n)
        self._sync_after(bufs, lens, n, n)
        return BatchOutcome(
            result=BatchResult(statuses=statuses, new_paths=rets,
                               unique_crashes=uc, unique_hangs=uh,
                               exit_codes=exit_codes),
            inputs=bufs, lengths=lens,
            compact=CompactReport(*compact))

    def supports_fused_multi(self) -> bool:
        """Mesh campaigns get their own K-step accumulation: virgin
        maps (and the per-step ICI folds) thread a per-shard
        lax.scan, one transfer set per K global batches — the
        multi-chip twin of the single-chip superbatch."""
        return True

    def test_batch_fused_multi(self, n: int, k: int):
        self._check_full_batch(n)
        mut = self.mutator
        its = mut.peek_iterations(n)
        base_it = int(its[0])  # same 64-bit counter contract as
        # test_batch; step j inside the scan adds j*n on device
        seed_buf = jnp.asarray(mut.seed_buf)
        (self.state, packed, bufs, lens, compact) = self._step.multi(
            self.state, seed_buf, jnp.int32(mut.seed_len), base_it, k)
        mut.advance(k * n)
        self._sync_after(bufs[k - 1], lens[k - 1], n, k * n)
        return packed, bufs, lens, compact

    # -- mesh-resident generations (--generations on --mesh) ------------

    def supports_batch_generations(self) -> bool:
        """Mesh campaigns run the generation scan under shard_map
        (distributed.make_sharded_generations): delegate to the
        instrumentation's own gate (fused candidate spec, no
        crack-stage focus mask, no edges mode) — the same conditions
        the single-chip loop checks, minus the single-chip quantum,
        and with no risk of drifting from them."""
        supports = getattr(self.instrumentation,
                           "supports_generations", None)
        return supports is not None and supports(self.mutator)

    def _ensure_gen_dispatch(self):
        """(Re)build the mesh generation dispatch + per-shard rings.
        Rebuilt when the candidate buffer width changes (a new base
        seed shape would make stale ring slots unloadable)."""
        mut = self.mutator
        instr = self.instrumentation
        seed_buf, seed_len, _key, stack_pow2 = mut.fused_spec()
        L = int(mut.max_length)
        slots = max(int(instr.options.get("gen_ring_slots", 32)), 2)
        # learned shaping is decided by weight presence (the loop
        # installs them before the FIRST dispatch, so the flag never
        # flips mid-campaign and the ring never rebuilds for it)
        learn = getattr(instr, "learn_params", None) is not None
        # grammar tables are compiled at instrumentation init, so
        # presence is likewise stable for the campaign's lifetime
        grammar = getattr(instr, "grammar_tables", None) is not None
        key = (L, slots, learn, grammar)
        if self._gen_ring is not None and self._gen_ring_key == key:
            return
        bpd = self.batch_per_device
        # ring sizing PER SHARD, against the per-chip batch — shared
        # with the single-chip path (gen_ring_caps has the measured
        # auto-cap rationale)
        adm_cap, cap = gen_ring_caps(
            instr.options.get("gen_admits", 8),
            instr.options.get("gen_findings_cap", 0), bpd, slots)
        self._gen_cap = cap
        salt = int(self.mutator.options.get("seed", 0)) & 0xFFFFFFFF
        self._gen_dispatch = make_sharded_generations(
            instr.program, self.mesh, bpd, max_len=L,
            stack_pow2=int(stack_pow2),
            engine=instr.engine, interpret=self._interpret,
            seed=int(self.mutator.options.get("seed", 0)),
            salt=salt, adm_cap=adm_cap, findings_cap=cap,
            stateful=self._stateful, learn=learn, grammar=grammar)
        self._gen_ring = sharded_gen_ring_init(
            self.mesh, seed_buf, int(seed_len), slots, L)
        self._gen_ring_key = key

    def test_batch_generations(self, n: int, g: int,
                               pad_to: Optional[int] = None,
                               reseed: bool = True):
        """``g`` full mesh generations in one device dispatch: each
        dp shard mutates from its own seed-slot ring, executes,
        triages against the (periodically dp-folded) virgin maps and
        reseeds on device; the host gets back one lazy
        MeshGenerationOutcome (per-shard findings rings + admission
        ledgers).  Generation j consumed counter ``it0 + j*n``; the
        mutator advances by g*n."""
        self._check_full_batch(n)
        mut = self.mutator
        self._ensure_gen_dispatch()
        instr = self.instrumentation
        its = mut.peek_iterations(n)
        base_it = int(its[0])   # same 64-bit counter contract as
        # test_batch; generation j inside the scan adds j*n on device
        fold_every = int(instr.options.get("gen_fold_every", 0))
        gtab = getattr(instr, "grammar_tables", None)
        with self._span("execute"):     # the whole loop is in-kernel
            self.state, self._gen_ring, rep = self._gen_dispatch(
                self.state, self._gen_ring, base_it, self._gen_count,
                int(g), reseed=bool(reseed), fold_every=fold_every,
                learn_params=getattr(instr, "learn_params", None),
                grammar_tables=(gtab.device()
                                if gtab is not None else None))
        out = MeshGenerationOutcome(
            *rep, ring_filled=self._gen_ring.filled,
            gen0=self._gen_count, g=int(g), n_real=n, cap=self._gen_cap,
            n_shards=self.mesh.shape["dp"])
        self._gen_count += int(g)
        mut.advance(int(g) * n)
        self._sync_after_generations(int(g), int(g) * n)
        return out

    def _sync_after_generations(self, g: int, execs: int) -> None:
        """Generations-mode twin of _sync_after: expose the folded
        maps through the instrumentation, fold the per-shard fleet
        snapshots, and stamp per-shard generation instants on the
        flight recorder (kb-timeline's per-shard occupancy rows).
        Candidate tensors never leave the device in this mode, so
        there is no last-input tail."""
        self._sync_after(None, None, 0, execs)
        self._last_batch_tail = None
        self.last_input = None
        timer = self.stage_timer
        tr = getattr(timer, "tracer", None) if timer is not None \
            else None
        if tr is not None:
            for i in range(self.mesh.shape["dp"]):
                tr.instant(
                    "shard_generations",
                    lane=tr.lane_id(f"shard-{i}"),
                    args={"shard": i, "generations": g,
                          "step": self._host_step})

    def test_input(self, buf: bytes) -> int:
        """Single-input repro path: run through the instrumentation's
        single-chip shim (campaign findings re-verification)."""
        self.instrumentation.enable(buf)
        self.last_input = buf
        return self.instrumentation.get_fuzz_result()
