"""Multi-chip fuzzing tier.

The reference scales by running independent fuzzer processes and
merging coverage offline (merger AND-fold, SURVEY §2.12). Here the
merge is an every-step ICI collective: the candidate batch shards over
a ``dp`` mesh axis, the 64KB coverage map shards over ``mp``, and
virgin-map union rides an all-gather + AND-fold (bitwise AND has no
direct psum; De Morgan over a 64KB array is one cheap gather).
"""

from .campaign import ShardedCampaignDriver, parse_mesh_spec
from .distributed import (
    ShardedFuzzState, ShardedGenRing, make_mesh,
    make_sharded_fuzz_step, make_sharded_generations,
    sharded_gen_ring_init, sharded_state_init,
)

__all__ = ["make_mesh", "make_sharded_fuzz_step", "sharded_state_init",
           "make_sharded_generations", "sharded_gen_ring_init",
           "ShardedGenRing", "ShardedFuzzState",
           "ShardedCampaignDriver", "parse_mesh_spec"]
