"""shard_map fuzzing step over a (dp, mp) device mesh.

Axes:
  * ``dp`` — data parallel over candidate lanes (the reference's
    "N independent fuzzer processes with distinct fuzzer_ids",
    dynamorio_instrumentation.c:418-431 — here distinct PRNG streams).
  * ``mp`` — map parallel over the 64KB coverage bitmap: each shard
    owns a slice of the edge-id space and builds/updates only its
    slice (the scatter, classify and novelty scans all shrink by the
    shard factor).

Collectives per step (all ICI-resident):
  * new-path/crash/hang flags: ``psum`` of per-slice verdicts over mp
  * virgin union over dp: all_gather + bitwise-AND fold (cleared bit =
    seen; AND keeps every clear — the merger tool's fold, made
    per-step)

PRNG: per-lane keys fold in the *global* lane id, so the candidate
stream is identical regardless of dp width — runs are reproducible
across mesh shapes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6
    from jax import shard_map as _shard_map_impl

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs,
                               check_vma=check_vma)
except ImportError:                     # jax 0.4.x: experimental home,
    from jax.experimental.shard_map import (  # check_rep spelling
        shard_map as _shard_map_impl,
    )

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs,
                               check_rep=check_vma)

from .. import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE
from ..instrumentation.base import pack_verdicts
from ..ops.generations import (
    DEFAULT_ADM_CAP, DEFAULT_FINDINGS_CAP, _cached_slot_mask,
    _invalidate_admitted_masks, _ring_append_and_admit,
    _select_slot, carry_donation_argnums,
)
from ..models.vm import Program, _run_batch_impl
from ..utils.logging import WARNING_MSG
from ..ops.coverage import classify_counts, simplify_trace
from ..ops.mutate_core import havoc_at
from ..ops.sparse_coverage import (
    _first_occurrence_multi, stream_hash,
)
from ..ops.static_triage import counts_by_slot, make_static_maps


def shard_stat_snapshots(mesh: Mesh, execs_per_shard: int,
                         step: int) -> list:
    """Per-dp-shard telemetry snapshots for one sync epoch, shaped
    for ``telemetry.aggregate.merge``: each data-parallel shard
    contributes its executed-lane count as a counter (summed by the
    fold) and its step clock as a gauge (max'd — a straggling shard
    shows up as a step gap in the merged view).  Host-side by
    construction: every value here is already known to the host
    without touching a device array, so the fold can run every epoch
    without breaking the async pipeline."""
    return [{"counters": {"execs": execs_per_shard},
             "gauges": {"shard_step": step,
                        "lanes_per_shard": execs_per_shard}}
            for _ in range(mesh.shape["dp"])]


def make_mesh(n_dp: int, n_mp: int = 1, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[:n_dp * n_mp])
    if devices.size != n_dp * n_mp:
        raise ValueError(
            f"need {n_dp * n_mp} devices, have {devices.size}")
    return Mesh(devices.reshape(n_dp, n_mp), ("dp", "mp"))


class ShardedFuzzState(NamedTuple):
    """Device-resident fuzzing state: virgin maps sharded over mp.

    ``virgin_state`` is the stateful tier's state x edge map, carried
    as a P("dp")-sharded [dp, M] array whose rows are identical after
    every dp AND-fold (same doctrine as the classic maps, different
    layout: the map is tiny and every mp shard computes it whole, so
    dp rows are the natural shard unit).  A [dp, 1] dummy when the
    session tier is off — the step signature stays uniform."""
    virgin_bits: jax.Array   # uint8[MAP_SIZE]
    virgin_crash: jax.Array
    virgin_tmout: jax.Array
    step: jax.Array          # int32 scalar, counts batches done
    virgin_state: jax.Array = None  # uint8[dp, M_state] (or [dp, 1])


def sharded_state_init(mesh: Mesh, map_size: int = MAP_SIZE,
                       state_map_size: int = 0) -> ShardedFuzzState:
    """``map_size`` must match the program's (64KB x n_modules);
    ``state_map_size`` the stateful tier's n_states x (E+1) bytes
    (0 = tier off, a 1-byte dummy rides along)."""
    spec = NamedSharding(mesh, P("mp"))
    full = jnp.full((map_size,), 0xFF, dtype=jnp.uint8)
    n_dp = mesh.shape["dp"]
    vs = jnp.full((n_dp, max(int(state_map_size), 1)), 0xFF,
                  dtype=jnp.uint8)
    return ShardedFuzzState(
        virgin_bits=jax.device_put(full, spec),
        virgin_crash=jax.device_put(full, spec),
        virgin_tmout=jax.device_put(full, spec),
        step=jnp.int32(0),
        virgin_state=jax.device_put(vs, NamedSharding(mesh, P("dp"))),
    )


def _shard_static_maps(program: Program, n_mp: int):
    """Host-side partition of the program's static slot universe over
    the mp axis.  The virgin maps are mp-sharded by dense slot ranges
    (state-export compatibility); each shard's per-step WORK however
    runs over only its own u-slots:

    Returns (u_loc int32[n_mp, U_max]  shard-local virgin offsets
             (sentinel = slice_size for padding),
             eidx  int32[n_mp, E]      edge -> shard u-column
             (sentinel = U_max: edge belongs to another shard),
             outside uint8[n_mp, slice_size]  the constant
             simplify-trace class-1 pattern of slots outside the
             universe, per shard slice)."""
    u_slots, seg_id = make_static_maps(program.edge_slot)
    slice_size = program.map_size // n_mp
    shard_of_u = u_slots // slice_size
    counts = np.bincount(shard_of_u, minlength=n_mp)
    u_max = max(int(counts.max(initial=0)), 1)
    u_loc = np.full((n_mp, u_max), slice_size, dtype=np.int32)
    u_pos = np.zeros(len(u_slots), dtype=np.int32)
    for m in range(n_mp):
        idxs = np.where(shard_of_u == m)[0]
        u_loc[m, :len(idxs)] = u_slots[idxs] - m * slice_size
        u_pos[idxs] = np.arange(len(idxs))
    eidx = np.full((n_mp, len(seg_id)), u_max, dtype=np.int32)
    for e, g in enumerate(seg_id):
        eidx[shard_of_u[g], e] = u_pos[g]
    outside = np.ones((n_mp, slice_size), dtype=np.uint8)
    for m in range(n_mp):
        sel = u_loc[m][u_loc[m] < slice_size]
        outside[m, sel] = 0
    return u_loc, eidx, outside


def _gather_and_fold(v_local, axis):
    """Virgin union across an axis: all_gather + AND fold."""
    g = jax.lax.all_gather(v_local, axis)  # [n_axis, M_shard]
    return jax.lax.reduce(g, jnp.uint8(0xFF), jax.lax.bitwise_and,
                          dimensions=(0,))


def _counter_halves(base_it):
    """Split ``base_it`` into uint32 halves host-side (a Python int
    keeps all 64 bits; a device scalar from an older caller becomes
    [it, 0]) so jitted bodies never convert a >=2^32 Python int to
    uint32 — NumPy 2.x raises OverflowError there, and older NumPy
    wraps silently, replaying earlier (counter, lane) PRNG pairs."""
    if isinstance(base_it, (int, np.integer)):
        it = int(base_it)
        return jnp.asarray(
            [it & 0xFFFFFFFF, (it >> 32) & 0xFFFFFFFF],
            dtype=jnp.uint32)
    arr = jnp.asarray(base_it)
    if arr.ndim == 0:
        return jnp.stack([arr.astype(jnp.uint32),
                          jnp.zeros((), jnp.uint32)])
    return arr.astype(jnp.uint32)


class _ShardKernels:
    """Per-shard building blocks shared by the per-batch fuzz step
    and the mesh-resident generation scan: global-lane PRNG keys,
    the engine-switched mutate+execute tier, and the mp-sharded
    coverage/novelty/virgin-clear triage (everything up to — but NOT
    including — the dp AND-fold, which each caller schedules on its
    own cadence: per batch for the step, every E generations for the
    generation scan)."""

    def __init__(self, program: Program, mesh: Mesh,
                 batch_per_device: int, max_len: int,
                 stack_pow2: int = 4, engine: str = "xla",
                 interpret: bool = False, seed: int = 0,
                 stateful=None):
        n_mp = mesh.shape["mp"]
        if program.map_size % n_mp:
            raise ValueError("mp must divide the program's map size")
        if engine not in ("xla", "pallas", "pallas_fused"):
            raise ValueError(f"unknown engine {engine!r}")
        #: stateful session tier: a static (m_max, n_states,
        #: state_reg) tuple; candidates execute as framed sequences
        #: and ``state_triage_local`` folds the state x edge map
        self.stateful = (tuple(int(v) for v in stateful)
                         if stateful is not None else None)
        if self.stateful is not None and engine != "xla":
            raise ValueError(
                "stateful mesh campaigns need the xla engine (the "
                "session executor is the one-hot engine path)")
        self.program = program
        self.mesh = mesh
        self.batch_per_device = int(batch_per_device)
        self.max_len = int(max_len)
        self.stack_pow2 = int(stack_pow2)
        self.engine = engine
        self.interpret = bool(interpret)
        self.seed = int(seed)
        self.slice_size = program.map_size // n_mp
        self.instrs = jnp.asarray(program.instrs)
        self.edge_table = jnp.asarray(program.edge_table)
        from ..ops.vm_kernel import dot_modes
        self.dots = dot_modes(program.instrs, program.n_edges)
        u_loc_np, eidx_np, outside_np = _shard_static_maps(program,
                                                           n_mp)
        self.u_loc_all = jnp.asarray(u_loc_np)
        self.eidx_all = jnp.asarray(eidx_np)
        self.outside_all = jnp.asarray(outside_np)
        self.u_max = u_loc_np.shape[1]

    # -- PRNG: per-GLOBAL-lane keys (mesh-shape independent) ---------

    def lane_keys(self, lo, hi):
        """Keys for this dp shard's lanes at 64-bit counter [lo, hi];
        also returns the lanes' global iteration ids (uint32)."""
        dp_i = jax.lax.axis_index("dp")
        lane = (dp_i.astype(jnp.uint32) * self.batch_per_device
                + jnp.arange(self.batch_per_device, dtype=jnp.uint32))
        base = jax.random.key(self.seed)
        # folding BOTH halves keeps (counter, lane) key pairs unique
        # past 2^32 total execs (under an hour at benched multi-chip
        # rates — a single-fold uint32 counter would wrap and replay
        # earlier mutants)
        folded = jax.random.fold_in(jax.random.fold_in(base, lo), hi)
        keys = jax.vmap(lambda l: jax.random.fold_in(folded, l))(lane)
        return keys, lo + lane

    # -- mutate + execute (engine switch) ----------------------------

    def _exec_pallas(self, bufs, lens):
        """Local-batch pallas execution (padded to the lane tile with
        dup-lane-0 coverage no-ops, sliced back)."""
        from ..ops.vm_kernel import run_batch_pallas_padded
        p = self.program
        return run_batch_pallas_padded(
            self.instrs, self.edge_table, bufs, lens, p.mem_size,
            p.max_steps, p.n_edges, interpret=self.interpret,
            dots=self.dots)

    def mutate_exec(self, keys, seed_buf, seed_len, mask=None,
                    grammar_tables=None):
        """havoc-mutate this shard's lanes from ``seed_buf`` and
        execute them; returns (VMResult, bufs, lens).  ``mask`` is
        the learned dense uint8[L] focus mask (learn/): mutation
        routes through the masked havoc kernel — xla engine only
        (the generation scan guards it), and an all-ones mask is
        bit-identical to the unmasked kernel.  ``grammar_tables`` is
        the compiled structure-table pytree (grammar/): mutation
        routes through ``grammar_havoc_at`` — also xla-only, and the
        degenerate tables are bit-identical to blind havoc (the
        grammar parity anchor)."""
        if mask is not None and self.engine != "xla":
            raise ValueError(
                "learned mutation shaping needs the xla engine")
        if grammar_tables is not None and self.engine != "xla":
            raise ValueError(
                "grammar-structured mutation needs the xla engine")
        p = self.program
        bpd = self.batch_per_device
        if self.engine == "pallas_fused":
            # mutation AND execution in one kernel per dp shard
            from ..ops.vm_kernel import (
                LANE_TILE, fuzz_batch_pallas, havoc_words_for_keys,
            )
            pad = (-bpd) % LANE_TILE
            if pad:
                keys_p = jnp.concatenate(
                    [keys, jnp.repeat(keys[:1], pad, axis=0)], axis=0)
            else:
                keys_p = keys
            words = havoc_words_for_keys(keys_p, self.stack_pow2)
            sb = seed_buf
            if sb.shape[-1] < self.max_len:
                sb = jnp.pad(sb, (0, self.max_len - sb.shape[-1]))
            res, bufs, lens = fuzz_batch_pallas(
                self.instrs, self.edge_table, sb, seed_len, words,
                p.mem_size, p.max_steps, p.n_edges,
                stack_pow2=self.stack_pow2, interpret=self.interpret,
                dots=self.dots)
            if pad:
                from ..ops.vm_kernel import _slice_vmresult
                res = _slice_vmresult(res, bpd)
                bufs = bufs[:bpd]
                lens = lens[:bpd]
            return res, bufs, lens
        if grammar_tables is not None:
            from ..grammar.device import grammar_havoc_at
            bufs, lens = jax.vmap(
                lambda k: grammar_havoc_at(
                    seed_buf, seed_len, k, grammar_tables,
                    stack_pow2=self.stack_pow2))(keys)
        elif mask is not None:
            from ..ops.mutate_core import havoc_mask_at
            bufs, lens = jax.vmap(
                lambda k: havoc_mask_at(
                    seed_buf, seed_len, k, mask,
                    stack_pow2=self.stack_pow2))(keys)
        else:
            bufs, lens = jax.vmap(
                lambda k: havoc_at(seed_buf, seed_len, k,
                                   stack_pow2=self.stack_pow2))(keys)
        if self.stateful is not None:
            # session tier: the mutants are framed sequences and the
            # result carries se_counts alongside the classic fields
            from ..stateful.session import _run_session_impl
            m_max, n_states, state_reg = self.stateful
            res = _run_session_impl(
                self.instrs, self.edge_table, bufs, lens, p.mem_size,
                p.max_steps, p.n_edges, m_max, n_states, state_reg)
        elif self.engine == "pallas":
            res = self._exec_pallas(bufs, lens)
        else:
            res = _run_batch_impl(self.instrs, self.edge_table, bufs,
                                  lens, p.mem_size, p.max_steps,
                                  p.n_edges, False)
        return res, bufs, lens

    # -- mp-sharded triage (everything up to the dp fold) ------------

    def triage_local(self, vb, vc, vh, counts, statuses):
        """Coverage over this shard's u-slots, novelty vs the local
        virgin slices (pmax over mp), per-dp-shard in-batch dedup,
        and the local virgin clears.  Returns (rets, uc, uh, vb2,
        vc2, vh2) — the caller owns WHEN the dp AND-fold runs."""
        mp_i = jax.lax.axis_index("mp")
        u_loc = self.u_loc_all[mp_i]     # [U_max] my virgin offsets
        eidx = self.eidx_all[mp_i]       # [E] edge -> my u-column
        outside = self.outside_all[mp_i]  # [slice] class-1 constant
        slice_size = self.slice_size

        # ---- coverage over MY u-slots (the per-shard share of the
        # static universe — no dense slice is ever materialized) ----
        by = counts_by_slot(counts, eidx, self.u_max + 1)[:, :self.u_max]
        cls = classify_counts(by)                    # [B, U_max]
        simp = simplify_trace(by)

        # ---- local novelty (vs my virgin slice, gathered at my
        # u-slots; padded columns read 0 = never novel) ----
        def novelty(virgin, classes):
            vloc = jnp.where(u_loc < slice_size,
                             virgin[jnp.clip(u_loc, 0, slice_size - 1)],
                             jnp.uint8(0))
            new_count = jnp.any((classes & vloc[None, :]) != 0, axis=1)
            new_tuple = jnp.any((classes != 0) &
                                (vloc[None, :] == 0xFF), axis=1)
            local = jnp.where(new_tuple, 2,
                              jnp.where(new_count, 1, 0)
                              ).astype(jnp.int32)
            # a lane is new if ANY map shard saw novelty: max over mp
            return jax.lax.pmax(local, "mp")

        crash = statuses == FUZZ_CRASH
        hang = statuses == FUZZ_HANG
        rets = novelty(vb, cls)
        crash_rets = novelty(vc, simp)
        hang_rets = novelty(vh, simp)

        # in-batch dedup by full-map hash: shard hashes combined by
        # psum; first occurrence within my dp shard's batch (sort-
        # based — the pairwise matrix is O(B^2) and dominates beyond
        # B~8k, sparse_coverage.first_occurrence).  NOTE the dedup is
        # per-dp-shard: two chips hitting the same new path in the
        # same step BOTH report it (the dp virgin AND-fold makes this
        # self-correct next step — persistence-style over-report,
        # never under-report; pinned by tests, see docs/USAGE.md)
        slice_hash = stream_hash(cls.astype(jnp.uint32))
        full_hash = jax.lax.psum(slice_hash, "mp")
        first, first_crash, first_hang = _first_occurrence_multi(
            full_hash, crash, hang)
        rets = jnp.where(first, rets, 0)
        uc = first_crash & (crash_rets > 0)
        uh = first_hang & (hang_rets > 0)

        # ---- virgin updates: clear my slice with new lanes' bits
        # (scatter at my u-slots; crash/hang maps also clear the
        # constant outside-universe class-1 pattern — dense
        # simplify_trace parity) ----
        def fold_new(traces, active):
            return jax.lax.reduce(
                jnp.where(active[:, None], traces, jnp.uint8(0)),
                jnp.uint8(0), jax.lax.bitwise_or, dimensions=(0,))

        def clear(virgin, seen_u, outside_mask):
            cur = virgin[jnp.clip(u_loc, 0, slice_size - 1)]
            out = virgin & ~outside_mask
            return out.at[u_loc].set(cur & ~seen_u, mode="drop")

        zero_out = jnp.zeros_like(outside)
        vb2 = clear(vb, fold_new(cls, rets > 0), zero_out)
        vc2 = clear(vc, fold_new(simp, crash),
                    jnp.where(jnp.any(crash), outside, zero_out))
        vh2 = clear(vh, fold_new(simp, hang),
                    jnp.where(jnp.any(hang), outside, zero_out))
        return rets, uc, uh, vb2, vc2, vh2

    def state_triage_local(self, vs, se_counts):
        """State x edge novelty for this dp shard's lanes (stateful
        tier).  The map is whole on every shard (it is tiny and
        se_counts is full-width), so the compute is mp-replicated —
        no collectives; the caller schedules the dp AND-fold on its
        own cadence exactly like the classic maps.  Per-dp-shard
        in-batch dedup: over-report between folds, never
        under-report (the established mesh doctrine)."""
        from ..stateful.coverage import state_triage
        return state_triage(vs, se_counts)


def make_sharded_fuzz_step(program: Program, mesh: Mesh,
                           batch_per_device: int, max_len: int,
                           stack_pow2: int = 4, engine: str = "xla",
                           interpret: bool = False, seed: int = 0,
                           compact_cap: int = 1024, stateful=None):
    """Build the jitted multi-chip fuzz step.

    Returns ``step(state, seed_buf, seed_len, base_it) ->
    (state', statuses[B], new_paths[B], uc[B], uh[B], exit_codes[B],
    candidates[B, L], lengths[B], compact)`` where B =
    batch_per_device * n_dp, candidates dp-sharded, virgin maps
    mp-sharded, and ``compact`` = (idx, bufs, lens, counts) is the
    per-shard interesting-lane report. ``base_it`` is the counter the
    per-lane PRNG keys fold in; the CLI campaign passes the absolute
    mutator iteration (monotonically consumed) as a Python int, so
    resumed runs can never replay an earlier run's (counter, lane)
    key pair.  All 64 bits are folded (as two uint32 halves), so the
    guarantee survives past 2^32 total execs.

    ``engine``: "xla" (batched one-hot engine), "pallas" (VMEM VM
    kernel under shard_map), or "pallas_fused" (mutation fused into
    the kernel).  ``interpret`` routes pallas through interpret mode
    (CPU-mesh tests).  ``seed`` is the campaign PRNG root.

    The step also returns a per-dp-shard compaction of interesting
    lanes (idx/bufs/lens blocks of ``compact_cap`` rows per shard +
    per-shard counts) so campaign triage reads a small report
    instead of the full candidate tensor.
    """
    n_dp = mesh.shape["dp"]
    n_mp = mesh.shape["mp"]
    # a shard can never report more interesting lanes than it runs —
    # a bigger cap would make the "compact" report LARGER than the
    # full tensor for small shards
    compact_cap = min(compact_cap, batch_per_device)
    kern = _ShardKernels(program, mesh, batch_per_device, max_len,
                         stack_pow2=stack_pow2, engine=engine,
                         interpret=interpret, seed=seed,
                         stateful=stateful)

    def local_step(vb, vc, vh, vs, seed_buf, seed_len, base_it):
        dp_i = jax.lax.axis_index("dp")
        vs0 = vs[0]                   # P("dp") block: [1, M] -> [M]

        # ---- mutate + execute: per-global-lane keys at the 64-bit
        # counter [lo, hi] (mesh-shape independent) ----
        keys, _its = kern.lane_keys(base_it[0], base_it[1])
        res, bufs, lens = kern.mutate_exec(keys, seed_buf, seed_len)
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)

        # ---- mp-sharded triage: coverage, novelty, dedup, clears ----
        rets, uc, uh, vb2, vc2, vh2 = kern.triage_local(
            vb, vc, vh, res.counts, statuses)
        if kern.stateful is not None:
            # state x edge novelty joins the verdict (max, like the
            # single-chip session step)
            s_rets, vs0 = kern.state_triage_local(vs0, res.se_counts)
            rets = jnp.maximum(rets, s_rets)

        # ---- union across dp (the per-step "merger") ----
        vb2 = _gather_and_fold(vb2, "dp")
        vc2 = _gather_and_fold(vc2, "dp")
        vh2 = _gather_and_fold(vh2, "dp")
        if kern.stateful is not None:
            vs0 = _gather_and_fold(vs0, "dp")
        vs2 = vs0[None]               # back to the [1, M] dp block

        # ---- in-step compaction (per dp shard): gather interesting
        # lanes' candidate bytes here so campaign triage never pulls
        # the full [B, L] tensor to the host (jit_harness
        # _fused_fuzz_step does the same for single-chip) ----
        flags = (statuses != 0) | (rets > 0)
        (sel,) = jnp.nonzero(flags, size=compact_cap, fill_value=0)
        sel_bufs = jnp.take(bufs, sel, axis=0)
        sel_lens = jnp.take(lens, sel)
        # global lane ids so the host maps report rows -> batch lanes
        sel_idx = (sel + dp_i * batch_per_device).astype(jnp.int32)
        count = jnp.sum(flags).astype(jnp.int32).reshape(1)
        return (vb2, vc2, vh2, vs2, statuses, rets, uc, uh,
                res.exit_code, bufs, lens,
                sel_idx, sel_bufs, sel_lens, count)

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("mp"), P("mp"), P("mp"), P("dp"), P(), P(), P()),
        out_specs=(P("mp"), P("mp"), P("mp"), P("dp"), P("dp"),
                   P("dp"), P("dp"), P("dp"), P("dp"),
                   P("dp", None), P("dp"),
                   P("dp"), P("dp", None), P("dp"), P("dp")),
        check_vma=False,
    )

    def local_multi(k):
        """K sharded steps scanned per shard: virgin maps thread the
        carry (ICI folds run INSIDE the scan), verdicts bit-packed —
        the mesh twin of jit_harness._fused_fuzz_multi."""
        n_global = jnp.uint32(n_dp * batch_per_device)

        def body_fn(vb, vc, vh, vs, seed_buf, seed_len, base_it):
            def body(carry, j):
                vb, vc, vh, vs = carry
                off = j * n_global
                lo = base_it[0] + off
                hi = base_it[1] + (lo < base_it[0]).astype(jnp.uint32)
                (vb2, vc2, vh2, vs2, statuses, rets, uc, uh, _ec,
                 bufs, lens, sel_idx, sel_bufs, sel_lens,
                 count) = local_step(
                    vb, vc, vh, vs, seed_buf, seed_len,
                    jnp.stack([lo, hi]))
                packed = pack_verdicts(statuses, rets, uc, uh)
                return (vb2, vc2, vh2, vs2), (packed, bufs, lens,
                                              sel_idx, sel_bufs,
                                              sel_lens, count)

            (vb, vc, vh, vs), outs = jax.lax.scan(
                body, (vb, vc, vh, vs),
                jnp.arange(k, dtype=jnp.uint32))
            return (vb, vc, vh, vs) + tuple(outs)

        return body_fn

    _multi_cache: dict = {}

    def _sharded_multi(k: int):
        fn = _multi_cache.get(k)
        if fn is None:
            fn = jax.jit(shard_map(
                local_multi(k), mesh=mesh,
                in_specs=(P("mp"), P("mp"), P("mp"), P("dp"),
                          P(), P(), P()),
                out_specs=(P("mp"), P("mp"), P("mp"), P("dp"),
                           P(None, "dp"),          # packed [k, B]
                           P(None, "dp", None),    # bufs [k, B, L]
                           P(None, "dp"),          # lens [k, B]
                           P(None, "dp"),          # sel_idx
                           P(None, "dp", None),    # sel_bufs
                           P(None, "dp"),          # sel_lens
                           P(None, "dp")),         # counts [k, n_dp]
                check_vma=False))
            _multi_cache[k] = fn
        return fn

    @jax.jit
    def _step_jit(state: ShardedFuzzState, seed_buf, seed_len, base_it):
        seed_buf = _validate(state, seed_buf)  # defined below; bound
        # at call time — shared with step_multi
        (vb, vc, vh, vs, statuses, rets, uc, uh, exit_codes, bufs,
         lens, sel_idx, sel_bufs, sel_lens, counts) = sharded(
            state.virgin_bits, state.virgin_crash, state.virgin_tmout,
            state.virgin_state, seed_buf, seed_len, base_it)
        new_state = ShardedFuzzState(vb, vc, vh, state.step + 1, vs)
        return (new_state, statuses, rets, uc, uh, exit_codes, bufs,
                lens, (sel_idx, sel_bufs, sel_lens, counts))

    # the module-level _counter_halves owns the 64-bit base_it split
    _halves = _counter_halves

    def step(state: ShardedFuzzState, seed_buf, seed_len, base_it):
        """Public step (see _counter_halves for the base_it
        contract)."""
        return _step_jit(state, seed_buf, seed_len, _halves(base_it))

    def _validate(state: ShardedFuzzState, seed_buf):
        """Shared trace-time checks: both paths must reject a
        mismatched resumed state loudly — clamped indexing into a
        wrong-sized virgin map would silently corrupt triage."""
        if state.virgin_bits.shape[-1] != program.map_size:
            raise ValueError(
                f"state map is {state.virgin_bits.shape[-1]} bytes but "
                f"{program.name!r} needs {program.map_size} — pass "
                f"sharded_state_init(mesh, program.map_size)")
        if seed_buf.shape[-1] > max_len:
            raise ValueError(
                f"seed buffer ({seed_buf.shape[-1]}) exceeds max_len "
                f"({max_len})")
        if seed_buf.shape[-1] < max_len:  # trace-time pad to max_len
            seed_buf = jnp.pad(seed_buf,
                               (0, max_len - seed_buf.shape[-1]))
        return seed_buf

    def step_multi(state: ShardedFuzzState, seed_buf, seed_len,
                   base_it, k: int):
        """K sharded steps in one dispatch: step j executes counter
        ``base_it + j*(dp*batch_per_device)`` (the global batch the
        campaign advances per step), virgin maps threaded on device.
        Returns (state', packed uint8[k, B], bufs[k, B, L],
        lens[k, B], (idx, bufs, lens, counts) stacked compact)."""
        seed_buf = _validate(state, seed_buf)
        (vb, vc, vh, vs, packed, bufs, lens, sel_idx, sel_bufs,
         sel_lens, counts) = _sharded_multi(int(k))(
            state.virgin_bits, state.virgin_crash, state.virgin_tmout,
            state.virgin_state, seed_buf, seed_len, _halves(base_it))
        new_state = ShardedFuzzState(vb, vc, vh, state.step + int(k),
                                     vs)
        return (new_state, packed, bufs, lens,
                (sel_idx, sel_bufs, sel_lens, counts))

    step.multi = step_multi
    return step


# -- mesh-resident generations (ops/generations.py x shard_map) ---------


class ShardedGenRing(NamedTuple):
    """Per-dp-shard device seed-slot rings for the mesh generation
    scan: each dp shard owns S slots x max_len bytes plus lengths,
    occupancy and per-slot hit/find stats (leading ``dp`` axis,
    sharded P("dp"))."""
    bufs: jax.Array      # uint8[dp, S, L]
    lens: jax.Array      # int32[dp, S]
    filled: jax.Array    # int32[dp, S]
    hits: jax.Array      # int32[dp, S]
    finds: jax.Array     # int32[dp, S]
    ptr: jax.Array       # int32[dp] monotone admission counter


def sharded_gen_ring_init(mesh: Mesh, seed_buf, seed_len: int,
                          slots: int, max_len: int) -> ShardedGenRing:
    """Fresh per-shard rings: slot 0 of EVERY dp shard pins the base
    seed; the rest stay empty until edge-novel lanes admit."""
    n_dp = mesh.shape["dp"]
    slots = max(int(slots), 2)
    raw = np.asarray(seed_buf, dtype=np.uint8).reshape(-1)[:max_len]
    bufs = np.zeros((n_dp, slots, max_len), np.uint8)
    bufs[:, 0, :raw.shape[0]] = raw
    lens = np.zeros((n_dp, slots), np.int32)
    lens[:, 0] = int(seed_len)
    filled = np.zeros((n_dp, slots), np.int32)
    filled[:, 0] = 1
    spec = NamedSharding(mesh, P("dp"))

    def put(a):
        return jax.device_put(jnp.asarray(a), spec)

    return ShardedGenRing(
        bufs=put(bufs), lens=put(lens), filled=put(filled),
        hits=put(np.zeros((n_dp, slots), np.int32)),
        finds=put(np.zeros((n_dp, slots), np.int32)),
        ptr=put(np.zeros((n_dp,), np.int32)))


def make_sharded_generations(program: Program, mesh: Mesh,
                             batch_per_device: int, max_len: int,
                             stack_pow2: int = 4, engine: str = "xla",
                             interpret: bool = False, seed: int = 0,
                             salt: int = 0,
                             adm_cap: int = DEFAULT_ADM_CAP,
                             findings_cap: int = DEFAULT_FINDINGS_CAP,
                             stateful=None, learn: bool = False,
                             grammar: bool = False):
    """Build the mesh-resident generation dispatch: the single-chip
    generation scan (ops/generations.py) lifted into a ``shard_map``
    over the (dp, mp) mesh.

    Each dp shard carries its OWN device-resident state through the
    scan carry — virgin-map slices (mp-sharded like the per-batch
    step), a seed-slot ring, and a bounded findings ring — and every
    ``fold_every`` generations the scan AND-folds the virgin maps
    across dp via ICI collectives (``_gather_and_fold``, the merger
    semantics the per-batch step already implements), so shards stop
    re-finding each other's paths without any host round-trip.  The
    final chunk always folds, so the returned state is dp-replicated
    exactly like the per-batch step's.

    Candidate parity: per-lane keys use the SAME derivation as the
    host-driven mesh loop (fold_in(fold_in(base, lo), hi) then the
    global lane id — ``_ShardKernels.lane_keys``), and generation j
    consumes counter ``base_it + j*(dp*batch_per_device)``; with
    reseeding off and ``fold_every=1`` the mesh generation scan is
    bit-identical to the host-driven mesh loop (findings, folded
    virgin maps) — the dp>1 twin of the PR 9 single-chip parity
    contract.  With ``fold_every > 1`` shards may re-find each
    other's paths BETWEEN folds: persistence-style over-report,
    never under-report, and the folded virgin maps still end
    identical (same doctrine as the per-dp-shard dedup).

    Per-shard slot selection salts the pick with the dp index
    (``salt ^ dp_i``) so shards explore different ring slots; the
    per-generation pick lands in the ledger, so host replay never
    re-derives it.

    Returns ``dispatch(state, ring, base_it, gen0, g, reseed,
    fold_every) -> (state', ring', rep)`` where ``rep`` is the
    13-tuple of MeshGenerationOutcome ring/ledger fields (leading dp
    axis).  The jit donates the carry state (ring + virgin buffers
    update in place, see ops.generations.carry_donation_argnums);
    ``ring.filled`` and ``ring.ptr`` are exempt because the outcome
    report exports them after the next dispatch is already in
    flight.
    """
    n_dp = mesh.shape["dp"]
    b = int(batch_per_device)
    if learn and engine != "xla":
        raise ValueError(
            "learned mutation shaping needs the xla engine (the "
            "fused VMEM kernel generates candidates in-kernel and "
            "cannot consume a per-generation mask)")
    if grammar and engine != "xla":
        raise ValueError(
            "grammar-structured mutation needs the xla engine (the "
            "fused VMEM kernel generates candidates in-kernel and "
            "cannot consume the structure tables)")
    if grammar and learn:
        raise ValueError(
            "grammar and learn both reshape the same mutation draw "
            "stream — enable one per campaign")
    kern = _ShardKernels(program, mesh, b, max_len,
                         stack_pow2=stack_pow2, engine=engine,
                         interpret=interpret, seed=seed,
                         stateful=stateful)
    F = int(findings_cap)
    A = max(int(adm_cap), 1)
    salt_u32 = jnp.uint32(int(salt) & 0xFFFFFFFF)

    def gen_body(g: int, reseed: bool, fold_every: int):
        n_chunks = g // fold_every
        A_eff = A if reseed else 1

        def body(vb, vc, vh, rbufs, rlens, rfilled, rhits, rfinds,
                 rptr, vs, base_it, gen0, salt, lp, gtab):
            dp_i = jax.lax.axis_index("dp")
            # P("dp") blocks arrive with a leading axis of 1
            rbufs, rlens, rfilled, rhits, rfinds, rptr, vs = (
                rbufs[0], rlens[0], rfilled[0], rhits[0], rfinds[0],
                rptr[0], vs[0])
            L = rbufs.shape[1]
            # per-shard slot-policy salt (host-replayable: salt ^ d)
            salt_d = salt ^ dp_i.astype(jnp.uint32)

            def one_generation(carry, j):
                (vb, vc, vh, vs, rbufs, rlens, rfilled, rhits,
                 rfinds, rptr, fr_pack, fr_gen, fr_iter, fr_len,
                 fr_bufs, fr_ptr, mask_cache, mask_valid) = carry
                gen_id = gen0 + j
                if reseed:
                    sel = _select_slot(rfilled, gen_id, salt_d)
                else:
                    sel = jnp.int32(0)
                seed_buf = rbufs[sel]
                seed_len = rlens[sel]
                # 64-bit counter for this generation: the global
                # batch advances dp*b per generation, with the lo->hi
                # carry so campaigns past 2^32 execs never replay
                off = j * jnp.uint32(n_dp * b)
                lo = base_it[0] + off
                hi = base_it[1] + (lo < base_it[0]).astype(jnp.uint32)
                keys, its = kern.lane_keys(lo, hi)
                if learn:
                    # in-scan inference on this shard's selected
                    # ring slot (replicated weights, per-shard seed
                    # — shards shape their own streams), with the
                    # shared per-slot mask cache from the scan carry
                    # (_cached_slot_mask; admission invalidates
                    # below)
                    mask, mask_cache, mask_valid = \
                        _cached_slot_mask(lp, seed_buf, seed_len,
                                          sel, mask_cache,
                                          mask_valid)
                else:
                    mask = None
                res, bufs, lens = kern.mutate_exec(
                    keys, seed_buf, seed_len, mask=mask,
                    grammar_tables=gtab if grammar else None)
                statuses = jnp.where(res.status == FUZZ_RUNNING,
                                     FUZZ_HANG, res.status)
                rets, uc, uh, vb, vc, vh = kern.triage_local(
                    vb, vc, vh, res.counts, statuses)
                if kern.stateful is not None:
                    s_rets, vs = kern.state_triage_local(
                        vs, res.se_counts)
                    rets = jnp.maximum(rets, s_rets)
                packed = pack_verdicts(statuses, rets, uc, uh)

                # findings-ring append + FIFO admission + ledger:
                # the EXACT single-chip semantics (shared helper —
                # loop.py's replay and the parity suites pin both
                # scans to it)
                flags = (statuses != FUZZ_NONE) | (rets > 0)
                aflags = rets == 2
                ((rbufs, rlens, rfilled, rhits, rfinds, rptr),
                 (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs,
                  fr_ptr),
                 araw, ledger) = _ring_append_and_admit(
                    flags, aflags, packed, its, bufs, lens, gen_id,
                    sel,
                    (rbufs, rlens, rfilled, rhits, rfinds, rptr),
                    (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs,
                     fr_ptr),
                    A_eff, reseed)
                if learn and reseed:
                    mask_valid = _invalidate_admitted_masks(
                        mask_valid, ledger, rbufs.shape[0])

                carry = (vb, vc, vh, vs, rbufs, rlens, rfilled,
                         rhits, rfinds, rptr, fr_pack, fr_gen,
                         fr_iter, fr_len, fr_bufs, fr_ptr,
                         mask_cache, mask_valid)
                return carry, (sel, araw) + ledger

            def chunk(carry, c):
                j0 = c * jnp.uint32(fold_every)
                carry, ys = jax.lax.scan(
                    one_generation, carry,
                    j0 + jnp.arange(fold_every, dtype=jnp.uint32))
                (vb, vc, vh, vs, *rest) = carry
                # the in-scan "merger": AND-fold virgin maps across
                # dp so shards stop re-finding each other's paths —
                # no host round-trip, same fold as the per-batch step
                vb = _gather_and_fold(vb, "dp")
                vc = _gather_and_fold(vc, "dp")
                vh = _gather_and_fold(vh, "dp")
                if kern.stateful is not None:
                    vs = _gather_and_fold(vs, "dp")
                return (vb, vc, vh, vs) + tuple(rest), ys

            S = rbufs.shape[0]
            mc_shape = (S, L) if learn else (1, 1)
            mv_shape = (S,) if learn else (1,)
            carry0 = (vb, vc, vh, vs, rbufs, rlens, rfilled, rhits,
                      rfinds, rptr,
                      jnp.zeros((F,), jnp.uint8),       # fr_pack
                      jnp.zeros((F,), jnp.int32),       # fr_gen
                      jnp.zeros((F,), jnp.uint32),      # fr_iter
                      jnp.zeros((F,), jnp.int32),       # fr_len
                      jnp.zeros((F, L), jnp.uint8),     # fr_bufs
                      jnp.int32(0),                     # fr_ptr
                      jnp.zeros(mc_shape, jnp.uint8),   # mask_cache
                      jnp.zeros(mv_shape, jnp.int32))   # mask_valid
            carry, ys = jax.lax.scan(
                chunk, carry0, jnp.arange(n_chunks, dtype=jnp.uint32))
            (vb, vc, vh, vs, rbufs, rlens, rfilled, rhits, rfinds,
             rptr, fr_pack, fr_gen, fr_iter, fr_len, fr_bufs,
             fr_ptr, _mc, _mv) = carry
            # [n_chunks, fold_every, ...] -> [g, ...] ledger rows
            ys = jax.tree_util.tree_map(
                lambda a: a.reshape((g,) + a.shape[2:]), ys)
            (sel, adm_raw, adm_valid, adm_slot, adm_iter, adm_len,
             adm_bufs) = ys

            def exp(a):     # restore the leading dp-block axis
                return a[None]

            return (vb, vc, vh, exp(vs),
                    exp(rbufs), exp(rlens), exp(rfilled), exp(rhits),
                    exp(rfinds), exp(rptr),
                    exp(fr_pack), exp(fr_gen), exp(fr_iter),
                    exp(fr_len), exp(fr_bufs), exp(fr_ptr),
                    exp(sel), exp(adm_raw), exp(adm_valid),
                    exp(adm_slot), exp(adm_iter), exp(adm_len),
                    exp(adm_bufs))

        return body

    _cache: dict = {}

    def _jit(g: int, reseed: bool, fold_every: int):
        key = (g, reseed, fold_every)
        fn = _cache.get(key)
        if fn is None:
            dp_specs = (P("dp"),) * 6
            fn = jax.jit(
                shard_map(
                    gen_body(g, reseed, fold_every), mesh=mesh,
                    # the trailing P()s are the learn-model weight
                    # pytree and the grammar-table pytree, both
                    # replicated to every shard (pytree prefixes:
                    # one spec covers all leaves)
                    in_specs=(P("mp"), P("mp"), P("mp"),
                              *dp_specs, P("dp"), P(), P(), P(),
                              P(), P()),
                    out_specs=((P("mp"), P("mp"), P("mp"))
                               + (P("dp"),) * 20),
                    check_vma=False),
                # donate the carry: vb/vc/vh + ring bufs/lens/hits/
                # finds + the state map (9) update in place; ring
                # filled(5)/ptr(8) are exported in the outcome
                # report, never donated
                donate_argnums=carry_donation_argnums(
                    jax.default_backend(), (0, 1, 2, 3, 4, 6, 7, 9)))
            _cache[key] = fn
        return fn

    _fold_warned: set = set()

    def dispatch(state: ShardedFuzzState, ring: ShardedGenRing,
                 base_it, gen0: int, g: int, reseed: bool = True,
                 fold_every: int = 0, learn_params=None,
                 grammar_tables=None):
        """Run ``g`` mesh generations in ONE device program.
        ``fold_every`` <= 0 means auto: once per dispatch with
        reseeding on (cheapest), every generation with reseeding off
        (the host-mesh-loop parity cadence).  A non-dividing E is
        decremented to the nearest divisor of ``g`` (warned, once per
        (E, g) pair) — a dispatch always ends on a fold, so the
        returned maps are dp-replicated."""
        g = int(g)
        fold = int(fold_every)
        if fold <= 0:
            fold = g if reseed else 1
        fold = max(1, min(fold, g))
        while g % fold:
            fold -= 1
        if fold != int(fold_every) and int(fold_every) > 0 \
                and (int(fold_every), g) not in _fold_warned:
            _fold_warned.add((int(fold_every), g))
            WARNING_MSG(
                "gen_fold_every %d does not divide this dispatch's "
                "%d generations: folding every %d instead (a "
                "dispatch must end on a fold so the virgin maps "
                "return dp-replicated)", int(fold_every), g, fold)
        if learn and learn_params is None:
            raise ValueError(
                "this mesh generation dispatch was built with "
                "learn=True — pass the model weights (learn_params)")
        if grammar and grammar_tables is None:
            raise ValueError(
                "this mesh generation dispatch was built with "
                "grammar=True — pass the compiled structure tables "
                "(grammar_tables)")
        lp = learn_params if learn else jnp.zeros((1,), jnp.float32)
        gt = grammar_tables if grammar \
            else jnp.zeros((1,), jnp.int32)
        outs = _jit(g, bool(reseed), fold)(
            state.virgin_bits, state.virgin_crash, state.virgin_tmout,
            ring.bufs, ring.lens, ring.filled, ring.hits, ring.finds,
            ring.ptr, state.virgin_state, _counter_halves(base_it),
            jnp.uint32(int(gen0)), salt_u32, lp, gt)
        (vb, vc, vh, vs, rbufs, rlens, rfilled, rhits, rfinds, rptr,
         *rep) = outs
        new_state = ShardedFuzzState(vb, vc, vh, state.step + g, vs)
        new_ring = ShardedGenRing(rbufs, rlens, rfilled, rhits,
                                  rfinds, rptr)
        return new_state, new_ring, tuple(rep)

    return dispatch
