"""shard_map fuzzing step over a (dp, mp) device mesh.

Axes:
  * ``dp`` — data parallel over candidate lanes (the reference's
    "N independent fuzzer processes with distinct fuzzer_ids",
    dynamorio_instrumentation.c:418-431 — here distinct PRNG streams).
  * ``mp`` — map parallel over the 64KB coverage bitmap: each shard
    owns a slice of the edge-id space and builds/updates only its
    slice (the scatter, classify and novelty scans all shrink by the
    shard factor).

Collectives per step (all ICI-resident):
  * new-path/crash/hang flags: ``psum`` of per-slice verdicts over mp
  * virgin union over dp: all_gather + bitwise-AND fold (cleared bit =
    seen; AND keeps every clear — the merger tool's fold, made
    per-step)

PRNG: per-lane keys fold in the *global* lane id, so the candidate
stream is identical regardless of dp width — runs are reproducible
across mesh shapes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6
    from jax import shard_map as _shard_map_impl

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs,
                               check_vma=check_vma)
except ImportError:                     # jax 0.4.x: experimental home,
    from jax.experimental.shard_map import (  # check_rep spelling
        shard_map as _shard_map_impl,
    )

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs,
                               check_rep=check_vma)

from .. import FUZZ_CRASH, FUZZ_HANG, FUZZ_RUNNING, MAP_SIZE
from ..instrumentation.base import pack_verdicts
from ..models.vm import Program, _run_batch_impl
from ..ops.coverage import classify_counts, simplify_trace
from ..ops.mutate_core import havoc_at
from ..ops.sparse_coverage import (
    _first_occurrence_multi, stream_hash,
)
from ..ops.static_triage import counts_by_slot, make_static_maps


def shard_stat_snapshots(mesh: Mesh, execs_per_shard: int,
                         step: int) -> list:
    """Per-dp-shard telemetry snapshots for one sync epoch, shaped
    for ``telemetry.aggregate.merge``: each data-parallel shard
    contributes its executed-lane count as a counter (summed by the
    fold) and its step clock as a gauge (max'd — a straggling shard
    shows up as a step gap in the merged view).  Host-side by
    construction: every value here is already known to the host
    without touching a device array, so the fold can run every epoch
    without breaking the async pipeline."""
    return [{"counters": {"execs": execs_per_shard},
             "gauges": {"shard_step": step,
                        "lanes_per_shard": execs_per_shard}}
            for _ in range(mesh.shape["dp"])]


def make_mesh(n_dp: int, n_mp: int = 1, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[:n_dp * n_mp])
    if devices.size != n_dp * n_mp:
        raise ValueError(
            f"need {n_dp * n_mp} devices, have {devices.size}")
    return Mesh(devices.reshape(n_dp, n_mp), ("dp", "mp"))


class ShardedFuzzState(NamedTuple):
    """Device-resident fuzzing state: virgin maps sharded over mp."""
    virgin_bits: jax.Array   # uint8[MAP_SIZE]
    virgin_crash: jax.Array
    virgin_tmout: jax.Array
    step: jax.Array          # int32 scalar, counts batches done


def sharded_state_init(mesh: Mesh,
                       map_size: int = MAP_SIZE) -> ShardedFuzzState:
    """``map_size`` must match the program's (64KB x n_modules)."""
    spec = NamedSharding(mesh, P("mp"))
    full = jnp.full((map_size,), 0xFF, dtype=jnp.uint8)
    return ShardedFuzzState(
        virgin_bits=jax.device_put(full, spec),
        virgin_crash=jax.device_put(full, spec),
        virgin_tmout=jax.device_put(full, spec),
        step=jnp.int32(0),
    )


def _shard_static_maps(program: Program, n_mp: int):
    """Host-side partition of the program's static slot universe over
    the mp axis.  The virgin maps are mp-sharded by dense slot ranges
    (state-export compatibility); each shard's per-step WORK however
    runs over only its own u-slots:

    Returns (u_loc int32[n_mp, U_max]  shard-local virgin offsets
             (sentinel = slice_size for padding),
             eidx  int32[n_mp, E]      edge -> shard u-column
             (sentinel = U_max: edge belongs to another shard),
             outside uint8[n_mp, slice_size]  the constant
             simplify-trace class-1 pattern of slots outside the
             universe, per shard slice)."""
    u_slots, seg_id = make_static_maps(program.edge_slot)
    slice_size = program.map_size // n_mp
    shard_of_u = u_slots // slice_size
    counts = np.bincount(shard_of_u, minlength=n_mp)
    u_max = max(int(counts.max(initial=0)), 1)
    u_loc = np.full((n_mp, u_max), slice_size, dtype=np.int32)
    u_pos = np.zeros(len(u_slots), dtype=np.int32)
    for m in range(n_mp):
        idxs = np.where(shard_of_u == m)[0]
        u_loc[m, :len(idxs)] = u_slots[idxs] - m * slice_size
        u_pos[idxs] = np.arange(len(idxs))
    eidx = np.full((n_mp, len(seg_id)), u_max, dtype=np.int32)
    for e, g in enumerate(seg_id):
        eidx[shard_of_u[g], e] = u_pos[g]
    outside = np.ones((n_mp, slice_size), dtype=np.uint8)
    for m in range(n_mp):
        sel = u_loc[m][u_loc[m] < slice_size]
        outside[m, sel] = 0
    return u_loc, eidx, outside


def _gather_and_fold(v_local, axis):
    """Virgin union across an axis: all_gather + AND fold."""
    g = jax.lax.all_gather(v_local, axis)  # [n_axis, M_shard]
    return jax.lax.reduce(g, jnp.uint8(0xFF), jax.lax.bitwise_and,
                          dimensions=(0,))


def make_sharded_fuzz_step(program: Program, mesh: Mesh,
                           batch_per_device: int, max_len: int,
                           stack_pow2: int = 4, engine: str = "xla",
                           interpret: bool = False, seed: int = 0,
                           compact_cap: int = 1024):
    """Build the jitted multi-chip fuzz step.

    Returns ``step(state, seed_buf, seed_len, base_it) ->
    (state', statuses[B], new_paths[B], uc[B], uh[B], exit_codes[B],
    candidates[B, L], lengths[B], compact)`` where B =
    batch_per_device * n_dp, candidates dp-sharded, virgin maps
    mp-sharded, and ``compact`` = (idx, bufs, lens, counts) is the
    per-shard interesting-lane report. ``base_it`` is the counter the
    per-lane PRNG keys fold in; the CLI campaign passes the absolute
    mutator iteration (monotonically consumed) as a Python int, so
    resumed runs can never replay an earlier run's (counter, lane)
    key pair.  All 64 bits are folded (as two uint32 halves), so the
    guarantee survives past 2^32 total execs.

    ``engine``: "xla" (batched one-hot engine), "pallas" (VMEM VM
    kernel under shard_map), or "pallas_fused" (mutation fused into
    the kernel).  ``interpret`` routes pallas through interpret mode
    (CPU-mesh tests).  ``seed`` is the campaign PRNG root.

    The step also returns a per-dp-shard compaction of interesting
    lanes (idx/bufs/lens blocks of ``compact_cap`` rows per shard +
    per-shard counts) so campaign triage reads a small report
    instead of the full candidate tensor.
    """
    n_dp = mesh.shape["dp"]
    n_mp = mesh.shape["mp"]
    if program.map_size % n_mp:
        raise ValueError("mp must divide the program's map size")
    if engine not in ("xla", "pallas", "pallas_fused"):
        raise ValueError(f"unknown engine {engine!r}")
    # a shard can never report more interesting lanes than it runs —
    # a bigger cap would make the "compact" report LARGER than the
    # full tensor for small shards
    compact_cap = min(compact_cap, batch_per_device)
    slice_size = program.map_size // n_mp
    instrs = jnp.asarray(program.instrs)
    edge_table = jnp.asarray(program.edge_table)
    from ..ops.vm_kernel import dot_modes
    dots = dot_modes(program.instrs, program.n_edges)
    u_loc_np, eidx_np, outside_np = _shard_static_maps(program, n_mp)
    u_loc_all = jnp.asarray(u_loc_np)
    eidx_all = jnp.asarray(eidx_np)
    outside_all = jnp.asarray(outside_np)
    u_max = u_loc_np.shape[1]

    def _exec_pallas(bufs, lens):
        """Local-batch pallas execution (padded to the lane tile
        with dup-lane-0 coverage no-ops, sliced back)."""
        from ..ops.vm_kernel import run_batch_pallas_padded
        return run_batch_pallas_padded(
            instrs, edge_table, bufs, lens, program.mem_size,
            program.max_steps, program.n_edges, interpret=interpret,
            dots=dots)

    def local_step(vb, vc, vh, seed_buf, seed_len, base_it):
        # ---- which shard am I ----
        dp_i = jax.lax.axis_index("dp")
        mp_i = jax.lax.axis_index("mp")
        u_loc = u_loc_all[mp_i]          # [U_max] my virgin offsets
        eidx = eidx_all[mp_i]            # [E] edge -> my u-column
        outside = outside_all[mp_i]      # [slice] class-1 constant

        # ---- mutate: per-global-lane keys (mesh-shape independent) ----
        lane = (dp_i.astype(jnp.uint32) * batch_per_device
                + jnp.arange(batch_per_device, dtype=jnp.uint32))
        base = jax.random.key(seed)
        # base_it is the absolute mutator iteration split into two
        # uint32 halves [lo, hi]; folding BOTH halves keeps (counter,
        # lane) key pairs unique past 2^32 total execs (under an hour
        # at benched multi-chip rates — a single-fold uint32 counter
        # would wrap and replay earlier mutants).
        folded = jax.random.fold_in(
            jax.random.fold_in(base, base_it[0]), base_it[1])
        keys = jax.vmap(lambda l: jax.random.fold_in(folded, l))(lane)
        if engine == "pallas_fused":
            # mutation AND execution in one kernel per dp shard
            from ..ops.vm_kernel import (
                LANE_TILE, fuzz_batch_pallas, havoc_words_for_keys,
            )
            pad = (-batch_per_device) % LANE_TILE
            if pad:
                keys_p = jnp.concatenate(
                    [keys, jnp.repeat(keys[:1], pad, axis=0)], axis=0)
            else:
                keys_p = keys
            words = havoc_words_for_keys(keys_p, stack_pow2)
            sb = seed_buf
            if sb.shape[-1] < max_len:
                sb = jnp.pad(sb, (0, max_len - sb.shape[-1]))
            res, bufs, lens = fuzz_batch_pallas(
                instrs, edge_table, sb, seed_len, words,
                program.mem_size, program.max_steps, program.n_edges,
                stack_pow2=stack_pow2, interpret=interpret, dots=dots)
            if pad:
                from ..ops.vm_kernel import _slice_vmresult
                res = _slice_vmresult(res, batch_per_device)
                bufs = bufs[:batch_per_device]
                lens = lens[:batch_per_device]
        else:
            bufs, lens = jax.vmap(
                lambda k: havoc_at(seed_buf, seed_len, k,
                                   stack_pow2=stack_pow2))(keys)
            if engine == "pallas":
                res = _exec_pallas(bufs, lens)
            else:
                res = _run_batch_impl(instrs, edge_table, bufs, lens,
                                      program.mem_size,
                                      program.max_steps,
                                      program.n_edges, False)
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)

        # ---- coverage over MY u-slots (the per-shard share of the
        # static universe — no dense slice is ever materialized) ----
        by = counts_by_slot(res.counts, eidx, u_max + 1)[:, :u_max]
        cls = classify_counts(by)                    # [B, U_max]
        simp = simplify_trace(by)

        # ---- local novelty (vs my virgin slice, gathered at my
        # u-slots; padded columns read 0 = never novel) ----
        def novelty(virgin, classes):
            vloc = jnp.where(u_loc < slice_size,
                             virgin[jnp.clip(u_loc, 0, slice_size - 1)],
                             jnp.uint8(0))
            new_count = jnp.any((classes & vloc[None, :]) != 0, axis=1)
            new_tuple = jnp.any((classes != 0) &
                                (vloc[None, :] == 0xFF), axis=1)
            local = jnp.where(new_tuple, 2,
                              jnp.where(new_count, 1, 0)
                              ).astype(jnp.int32)
            # a lane is new if ANY map shard saw novelty: max over mp
            return jax.lax.pmax(local, "mp")

        crash = statuses == FUZZ_CRASH
        hang = statuses == FUZZ_HANG
        rets = novelty(vb, cls)
        crash_rets = novelty(vc, simp)
        hang_rets = novelty(vh, simp)

        # in-batch dedup by full-map hash: shard hashes combined by
        # psum; first occurrence within my dp shard's batch (sort-
        # based — the pairwise matrix is O(B^2) and dominates beyond
        # B~8k, sparse_coverage.first_occurrence).  NOTE the dedup is
        # per-dp-shard: two chips hitting the same new path in the
        # same step BOTH report it (the dp virgin AND-fold makes this
        # self-correct next step — persistence-style over-report,
        # never under-report; pinned by tests, see docs/USAGE.md)
        slice_hash = stream_hash(cls.astype(jnp.uint32))
        full_hash = jax.lax.psum(slice_hash, "mp")
        first, first_crash, first_hang = _first_occurrence_multi(
            full_hash, crash, hang)
        rets = jnp.where(first, rets, 0)
        uc = first_crash & (crash_rets > 0)
        uh = first_hang & (hang_rets > 0)

        # ---- virgin updates: clear my slice with new lanes' bits
        # (scatter at my u-slots; crash/hang maps also clear the
        # constant outside-universe class-1 pattern — dense
        # simplify_trace parity) ----
        def fold_new(traces, active):
            return jax.lax.reduce(
                jnp.where(active[:, None], traces, jnp.uint8(0)),
                jnp.uint8(0), jax.lax.bitwise_or, dimensions=(0,))

        def clear(virgin, seen_u, outside_mask):
            cur = virgin[jnp.clip(u_loc, 0, slice_size - 1)]
            out = virgin & ~outside_mask
            return out.at[u_loc].set(cur & ~seen_u, mode="drop")

        zero_out = jnp.zeros_like(outside)
        vb2 = clear(vb, fold_new(cls, rets > 0), zero_out)
        vc2 = clear(vc, fold_new(simp, crash),
                    jnp.where(jnp.any(crash), outside, zero_out))
        vh2 = clear(vh, fold_new(simp, hang),
                    jnp.where(jnp.any(hang), outside, zero_out))

        # ---- union across dp (the per-step "merger") ----
        vb2 = _gather_and_fold(vb2, "dp")
        vc2 = _gather_and_fold(vc2, "dp")
        vh2 = _gather_and_fold(vh2, "dp")

        # ---- in-step compaction (per dp shard): gather interesting
        # lanes' candidate bytes here so campaign triage never pulls
        # the full [B, L] tensor to the host (jit_harness
        # _fused_fuzz_step does the same for single-chip) ----
        flags = (statuses != 0) | (rets > 0)
        (sel,) = jnp.nonzero(flags, size=compact_cap, fill_value=0)
        sel_bufs = jnp.take(bufs, sel, axis=0)
        sel_lens = jnp.take(lens, sel)
        # global lane ids so the host maps report rows -> batch lanes
        sel_idx = (sel + dp_i * batch_per_device).astype(jnp.int32)
        count = jnp.sum(flags).astype(jnp.int32).reshape(1)
        return (vb2, vc2, vh2, statuses, rets, uc, uh,
                res.exit_code, bufs, lens,
                sel_idx, sel_bufs, sel_lens, count)

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("mp"), P("mp"), P("mp"), P(), P(), P()),
        out_specs=(P("mp"), P("mp"), P("mp"), P("dp"), P("dp"),
                   P("dp"), P("dp"), P("dp"), P("dp", None), P("dp"),
                   P("dp"), P("dp", None), P("dp"), P("dp")),
        check_vma=False,
    )

    def local_multi(k):
        """K sharded steps scanned per shard: virgin maps thread the
        carry (ICI folds run INSIDE the scan), verdicts bit-packed —
        the mesh twin of jit_harness._fused_fuzz_multi."""
        n_global = jnp.uint32(n_dp * batch_per_device)

        def body_fn(vb, vc, vh, seed_buf, seed_len, base_it):
            def body(carry, j):
                vb, vc, vh = carry
                off = j * n_global
                lo = base_it[0] + off
                hi = base_it[1] + (lo < base_it[0]).astype(jnp.uint32)
                (vb2, vc2, vh2, statuses, rets, uc, uh, _ec, bufs,
                 lens, sel_idx, sel_bufs, sel_lens, count) = local_step(
                    vb, vc, vh, seed_buf, seed_len,
                    jnp.stack([lo, hi]))
                packed = pack_verdicts(statuses, rets, uc, uh)
                return (vb2, vc2, vh2), (packed, bufs, lens, sel_idx,
                                         sel_bufs, sel_lens, count)

            (vb, vc, vh), outs = jax.lax.scan(
                body, (vb, vc, vh), jnp.arange(k, dtype=jnp.uint32))
            return (vb, vc, vh) + tuple(outs)

        return body_fn

    _multi_cache: dict = {}

    def _sharded_multi(k: int):
        fn = _multi_cache.get(k)
        if fn is None:
            fn = jax.jit(shard_map(
                local_multi(k), mesh=mesh,
                in_specs=(P("mp"), P("mp"), P("mp"), P(), P(), P()),
                out_specs=(P("mp"), P("mp"), P("mp"),
                           P(None, "dp"),          # packed [k, B]
                           P(None, "dp", None),    # bufs [k, B, L]
                           P(None, "dp"),          # lens [k, B]
                           P(None, "dp"),          # sel_idx
                           P(None, "dp", None),    # sel_bufs
                           P(None, "dp"),          # sel_lens
                           P(None, "dp")),         # counts [k, n_dp]
                check_vma=False))
            _multi_cache[k] = fn
        return fn

    @jax.jit
    def _step_jit(state: ShardedFuzzState, seed_buf, seed_len, base_it):
        seed_buf = _validate(state, seed_buf)  # defined below; bound
        # at call time — shared with step_multi
        (vb, vc, vh, statuses, rets, uc, uh, exit_codes, bufs,
         lens, sel_idx, sel_bufs, sel_lens, counts) = sharded(
            state.virgin_bits, state.virgin_crash, state.virgin_tmout,
            seed_buf, seed_len, base_it)
        new_state = ShardedFuzzState(vb, vc, vh, state.step + 1)
        return (new_state, statuses, rets, uc, uh, exit_codes, bufs,
                lens, (sel_idx, sel_bufs, sel_lens, counts))

    def _halves(base_it):
        """Split ``base_it`` into uint32 halves host-side (a Python
        int keeps all 64 bits; a device scalar from an older caller
        becomes [it, 0]) so the jitted body never converts a >=2^32
        Python int to uint32 — NumPy 2.x raises OverflowError there,
        and older NumPy wraps silently, replaying earlier
        (counter, lane) PRNG pairs."""
        if isinstance(base_it, (int, np.integer)):
            it = int(base_it)
            return jnp.asarray(
                [it & 0xFFFFFFFF, (it >> 32) & 0xFFFFFFFF],
                dtype=jnp.uint32)
        arr = jnp.asarray(base_it)
        if arr.ndim == 0:
            return jnp.stack([arr.astype(jnp.uint32),
                              jnp.zeros((), jnp.uint32)])
        return arr.astype(jnp.uint32)

    def step(state: ShardedFuzzState, seed_buf, seed_len, base_it):
        """Public step (see _halves for the base_it contract)."""
        return _step_jit(state, seed_buf, seed_len, _halves(base_it))

    def _validate(state: ShardedFuzzState, seed_buf):
        """Shared trace-time checks: both paths must reject a
        mismatched resumed state loudly — clamped indexing into a
        wrong-sized virgin map would silently corrupt triage."""
        if state.virgin_bits.shape[-1] != program.map_size:
            raise ValueError(
                f"state map is {state.virgin_bits.shape[-1]} bytes but "
                f"{program.name!r} needs {program.map_size} — pass "
                f"sharded_state_init(mesh, program.map_size)")
        if seed_buf.shape[-1] > max_len:
            raise ValueError(
                f"seed buffer ({seed_buf.shape[-1]}) exceeds max_len "
                f"({max_len})")
        if seed_buf.shape[-1] < max_len:  # trace-time pad to max_len
            seed_buf = jnp.pad(seed_buf,
                               (0, max_len - seed_buf.shape[-1]))
        return seed_buf

    def step_multi(state: ShardedFuzzState, seed_buf, seed_len,
                   base_it, k: int):
        """K sharded steps in one dispatch: step j executes counter
        ``base_it + j*(dp*batch_per_device)`` (the global batch the
        campaign advances per step), virgin maps threaded on device.
        Returns (state', packed uint8[k, B], bufs[k, B, L],
        lens[k, B], (idx, bufs, lens, counts) stacked compact)."""
        seed_buf = _validate(state, seed_buf)
        (vb, vc, vh, packed, bufs, lens, sel_idx, sel_bufs, sel_lens,
         counts) = _sharded_multi(int(k))(
            state.virgin_bits, state.virgin_crash, state.virgin_tmout,
            seed_buf, seed_len, _halves(base_it))
        new_state = ShardedFuzzState(vb, vc, vh, state.step + int(k))
        return (new_state, packed, bufs, lens,
                (sel_idx, sel_bufs, sel_lens, counts))

    step.multi = step_multi
    return step
