"""Bounded, indexed storage for ``kbz-proxy-gap-v1`` reports.

PR 17 wrote one unbounded file per ``proxy_only`` divergence.  A
long-running campaign against a genuinely divergent proxy can mint
thousands of them — most repeating the same (diverging edge, verdict
class) pair — so ``proxy_gaps/`` now behaves like the other bounded
artifact stores:

  * one emitter (:func:`make_gap_report`) shared by the hybrid
    bridge's write-back and ``kb-repair --probe``, so every report is
    schema-identical regardless of producer;
  * a :class:`GapIndex` manifest (``index.json``) over the directory
    — dedup by ``(edge, verdict-kind, input md5)``, retention capped
    with an oldest-evicted policy (the counterexample SET matters for
    repair, not the Nth duplicate of one divergence);
  * reports now carry the concrete input (``input_hex``, bounded) and
    the proxy-trace edge the divergence clusters under, which is
    exactly what the conformance pass (analysis/conformance.py) needs
    to replay them as counterexamples.  Consumers of the PR 17 shape
    keep working: added keys are tolerated per the contract in
    docs/HYBRID.md, and reports WITHOUT ``input_hex`` still parse
    (they just cannot be replayed — counted, never silently dropped).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..corpus.store import _atomic_write
from ..utils.fileio import ensure_dir
from ..utils.logging import WARNING_MSG

GAP_SCHEMA = "kbz-proxy-gap-v1"
INDEX_SCHEMA = "kbz-proxy-gap-index-v1"
INDEX_FILE = "index.json"
#: the repair ledger kb-repair / --auto-repair append to (the lint
#: tier's "has this gap been consumed" source; analysis/repair.py)
LEDGER_FILE = "repairs.json"

#: default retention cap on stored gap reports per campaign
DEFAULT_GAP_CAP = 256

#: inputs above this size are not inlined into the report (the md5
#: still names the finding file under crashes/ / hangs/)
MAX_GAP_INPUT_BYTES = 1 << 16


def make_gap_report(*, md5: str, kind: str, binding: str,
                    proxy_target: str, proxy_status: int,
                    native_argv, native_delivery: str,
                    statuses: List[int], repro: int, repeats: int,
                    t: Optional[float],
                    input_bytes: Optional[bytes] = None,
                    edge: Optional[Tuple[int, int]] = None
                    ) -> Dict[str, Any]:
    """One ``kbz-proxy-gap-v1`` report dict (the contract in
    docs/HYBRID.md).  The single emitter for every producer."""
    report: Dict[str, Any] = {
        "schema": GAP_SCHEMA,
        "md5": md5, "kind": kind,
        "binding": binding,
        "proxy": {"target": proxy_target,
                  "status": int(proxy_status)},
        "native": {"argv": list(native_argv),
                   "delivery": native_delivery,
                   "statuses": [int(s) for s in statuses],
                   "repro": int(repro),
                   "repeats": int(repeats)},
        "t": t,
    }
    if edge is not None:
        report["proxy"]["edge"] = [int(edge[0]), int(edge[1])]
    if input_bytes is not None:
        if len(input_bytes) <= MAX_GAP_INPUT_BYTES:
            report["input_hex"] = bytes(input_bytes).hex()
        else:
            report["input_omitted"] = len(input_bytes)
    return report


def proxy_trace_edge(program, buf: bytes
                     ) -> Optional[Tuple[int, int]]:
    """The last (from-block, to-block) edge of the proxy's concrete
    trace on ``buf`` — the key divergences cluster under.  None when
    the replay itself fails (a gap report is still worth keeping)."""
    try:
        from ..analysis.solver import concrete_run
        trace = concrete_run(program, bytes(buf))
        return trace.edges[-1] if trace.edges else None
    except Exception:
        return None


def _entry_key(e: Dict[str, Any]) -> Tuple:
    edge = e.get("edge")
    return (tuple(edge) if edge else None, e.get("kind"),
            e.get("md5"))


class GapIndex:
    """Manifest over one ``proxy_gaps/`` directory: dedup, retention
    cap, oldest-evicted.  ``admit`` is the only writer; loading
    tolerates a missing/torn manifest by rebuilding from the report
    files themselves."""

    def __init__(self, gap_dir: str, cap: int = DEFAULT_GAP_CAP):
        self.gap_dir = gap_dir
        self.cap = max(1, int(cap))
        self.entries: List[Dict[str, Any]] = []
        self.evicted = 0
        self.duplicates = 0
        self._load()

    # -- loading ------------------------------------------------------

    def _load(self) -> None:
        path = os.path.join(self.gap_dir, INDEX_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") == INDEX_SCHEMA and \
                    isinstance(doc.get("entries"), list):
                self.entries = [e for e in doc["entries"]
                                if isinstance(e, dict)]
                self.evicted = int(doc.get("evicted", 0))
                self.duplicates = int(doc.get("duplicates", 0))
                return
        except (OSError, ValueError):
            pass
        self._rebuild()

    def _rebuild(self) -> None:
        """No (usable) manifest: index whatever reports exist — a
        PR 17-era directory becomes a bounded one on first touch."""
        self.entries = []
        if not os.path.isdir(self.gap_dir):
            return
        for name in sorted(os.listdir(self.gap_dir)):
            if not name.endswith(".json") or \
                    name in (INDEX_FILE, LEDGER_FILE):
                continue
            try:
                with open(os.path.join(self.gap_dir, name),
                          encoding="utf-8") as f:
                    rep = json.load(f)
            except (OSError, ValueError):
                continue
            if rep.get("schema") != GAP_SCHEMA:
                continue
            self.entries.append(self._entry_of(rep, name))
        self.entries.sort(key=lambda e: (e.get("t") or 0.0,
                                         e.get("file", "")))

    @staticmethod
    def _entry_of(report: Dict[str, Any], filename: str
                  ) -> Dict[str, Any]:
        return {"md5": report.get("md5"),
                "kind": report.get("kind"),
                "binding": report.get("binding"),
                "edge": (report.get("proxy") or {}).get("edge"),
                "t": report.get("t"),
                "file": filename}

    # -- writing ------------------------------------------------------

    def admit(self, report: Dict[str, Any]) -> Optional[str]:
        """Write one report (dedup'd, capped); returns its path, or
        None when it deduplicated against an already-stored one."""
        ensure_dir(self.gap_dir)
        filename = f"{report['md5']}.json"
        entry = self._entry_of(report, filename)
        key = _entry_key(entry)
        if any(_entry_key(e) == key for e in self.entries):
            self.duplicates += 1
            self._save()
            return None
        path = os.path.join(self.gap_dir, filename)
        _atomic_write(path, json.dumps(report, indent=1).encode())
        self.entries.append(entry)
        while len(self.entries) > self.cap:
            old = self.entries.pop(0)
            self.evicted += 1
            try:
                os.unlink(os.path.join(self.gap_dir,
                                       old.get("file") or ""))
            except OSError:
                pass
        self._save()
        return path

    def _save(self) -> None:
        try:
            _atomic_write(
                os.path.join(self.gap_dir, INDEX_FILE),
                json.dumps({"schema": INDEX_SCHEMA,
                            "cap": self.cap,
                            "entries": self.entries,
                            "evicted": self.evicted,
                            "duplicates": self.duplicates},
                           indent=1).encode())
        except OSError as e:        # manifest loss must not kill folds
            WARNING_MSG("proxy-gap index write failed: %s", e)


def load_ledger(gap_dir: str) -> List[Dict[str, Any]]:
    """The repair ledger's entries ([] when none/torn)."""
    try:
        with open(os.path.join(gap_dir, LEDGER_FILE),
                  encoding="utf-8") as f:
            doc = json.load(f)
        reps = doc.get("repairs")
        return [r for r in reps if isinstance(r, dict)] \
            if isinstance(reps, list) else []
    except (OSError, ValueError):
        return []


def append_ledger(gap_dir: str, record: Dict[str, Any],
                  cap: int = 256) -> None:
    """Append one repair record (bounded, atomic)."""
    ensure_dir(gap_dir)
    entries = load_ledger(gap_dir)
    entries.append(record)
    _atomic_write(
        os.path.join(gap_dir, LEDGER_FILE),
        json.dumps({"schema": "kbz-proxy-repair-ledger-v1",
                    "repairs": entries[-cap:]}, indent=1).encode())
