"""Per-tier coverage reconciliation (hybrid campaigns).

Everything fleet-visible carries a ``tier`` tag: corpus sidecars
(store schema), worker heartbeats (``meta["tier"]``) and gossip rows
(the sidecar meta rides the exchange untouched).  This module folds
those tags into the per-tier summaries ``kb-fleet --json`` serves —
worker counts, health, exec/find counters per tier — plus the
fleet-wide validation rollup (queue depth/age, verdict counters).

The native tier appears in the fleet through
:class:`NativeHeartbeat`: a sidecar thread posting the bridge's
counters to the manager with ``meta={"tier": "native"}``, so a
hybrid campaign's single process shows up as one TPU worker AND one
native worker — the same shape a physically split fleet has.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

#: tier assumed for anything minted before the hybrid bridge existed
#: (untagged heartbeats, pre-hybrid sidecars)
DEFAULT_TIER = "tpu"


def tier_of(meta: Optional[Dict[str, Any]]) -> str:
    """The tier a heartbeat / sidecar / gossip row belongs to."""
    if isinstance(meta, dict):
        t = meta.get("tier")
        if isinstance(t, str) and t:
            return t
    return DEFAULT_TIER


#: counters worth showing per tier in kb-fleet (subset of a worker
#: snapshot — the full merge stays fleet-wide)
_TIER_COUNTERS = ("execs", "new_paths", "crashes", "unique_crashes",
                  "hybrid_validations")


def fold_tiers(rows: List[Dict[str, Any]],
               stats: Dict[str, Dict[str, Any]],
               statuses: Dict[str, str]) -> Dict[str, Dict[str, Any]]:
    """Group fleet workers by tier and fold per-tier summaries.

    ``rows`` are the fleet DB worker rows (name + meta), ``stats``
    maps worker -> last posted stats body, ``statuses`` maps worker
    -> health class (healthy/stale/dead/retired).  Pure — the
    manager and tests call it with whatever view they hold."""
    from ..telemetry.aggregate import merge

    tiers: Dict[str, Dict[str, Any]] = {}
    by_tier: Dict[str, List[str]] = {}
    for row in rows:
        name = row.get("worker") or row.get("name")
        if not name:
            continue
        by_tier.setdefault(tier_of(row.get("meta")), []).append(name)
    for tier, names in sorted(by_tier.items()):
        snaps = [stats[n].get("snapshot") or stats[n]
                 for n in names if n in stats]
        merged = merge([s for s in snaps
                        if isinstance(s, dict)]) or {}
        counters = merged.get("counters", {})
        gauges = merged.get("gauges", {})
        counts: Dict[str, int] = {}
        for n in names:
            st = statuses.get(n, "unknown")
            counts[st] = counts.get(st, 0) + 1
        tiers[tier] = {
            "n_workers": len(names),
            "counts": counts,
            "counters": {k: counters[k] for k in _TIER_COUNTERS
                         if k in counters},
            "execs_per_sec_ema":
                merged.get("rates", {}).get("execs_per_sec_ema",
                                            gauges.get(
                                                "execs_per_sec_ema")),
        }
    return tiers


def validation_summary(merged: Dict[str, Any]) -> Dict[str, Any]:
    """The fleet-wide cross-tier validation rollup from a merged
    stats snapshot (kb-fleet --json ``validation`` section)."""
    counters = merged.get("counters", {}) if merged else {}
    gauges = merged.get("gauges", {}) if merged else {}
    return {
        "validations": int(counters.get("hybrid_validations", 0)),
        "verdicts": {
            "confirmed": int(counters.get("hybrid_confirmed", 0)),
            "proxy_only": int(counters.get("hybrid_proxy_only", 0)),
            "flaky": int(counters.get("hybrid_flaky", 0)),
        },
        "proxy_gaps": int(counters.get("hybrid_proxy_gaps", 0)),
        "queue_depth": int(gauges.get("validation_queue_depth", 0)),
        "queue_age_s": float(gauges.get("validation_queue_age", 0.0)),
    }


class NativeHeartbeat(threading.Thread):
    """Posts the hybrid bridge's native-tier stats to the manager.

    One per hybrid campaign process; the TPU loop's own Heartbeat
    keeps posting as before (tier "tpu"), this thread adds the
    ``<worker>-native`` row so per-tier views see both tiers even
    when they share a host process."""

    def __init__(self, bridge, manager_url: str, campaign: str,
                 worker: str, interval: float = 5.0):
        super().__init__(daemon=True, name="hybrid-native-heartbeat")
        self.bridge = bridge
        self.manager_url = manager_url.rstrip("/")
        self.campaign = campaign
        self.worker = worker if worker.endswith("-native") \
            else f"{worker}-native"
        self.interval = float(interval)
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def post_once(self) -> bool:
        from ..manager.worker import _request
        try:
            _request(
                f"{self.manager_url}/api/stats/{self.campaign}",
                {"worker": self.worker,
                 "snapshot": self.bridge.snapshot(),
                 "meta": {"tier": "native", "pid": os.getpid()}})
            return True
        except Exception:
            return False                 # next beat retries

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            self.post_once()
        self.post_once()                 # parting beat
