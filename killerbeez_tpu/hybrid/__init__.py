"""Hybrid native⇄TPU campaign bridge (docs/HYBRID.md).

The PTrix split (PAPERS.md, arxiv 1905.10499) applied across this
repo's two execution tiers: the TPU tier explores cheap soft-KBVM
proxies at millions of execs/s, native workers confirm findings on
the real binary, and both tiers share one corpus / event / fleet
stream.  Four pieces:

  * :mod:`.registry`  — declarative proxy⇄native bindings with a
    bind-time certification check (benign seed behaves identically
    on both sides);
  * :mod:`.translate` — lossless, property-tested seed translation
    between TPU byte buffers and native delivery formats (stdin,
    file, argv, framed TCP/stdin message trains);
  * :mod:`.validate`  — the cross-tier triage pipeline: bounded
    validation queue, native replay with retry/backoff, ``confirmed``
    / ``proxy_only`` / ``flaky`` verdicts, proxy-gap reports;
  * :mod:`.reconcile` — per-tier coverage reconciliation: tier tags
    on entries / heartbeats / gossip rows, per-tier fleet folds, the
    native-tier heartbeat;
  * :mod:`.gaps`      — bounded, deduped, indexed storage for
    proxy-gap reports: the conformance/repair pass's counterexample
    queue (analysis/conformance.py, analysis/repair.py).
"""

from .gaps import (  # noqa: F401
    GAP_SCHEMA,
    GapIndex,
    append_ledger,
    load_ledger,
    make_gap_report,
)
from .registry import (  # noqa: F401
    CertificationError,
    NativeSpec,
    ProxyBinding,
    bind,
    binding_names,
    builtin_bindings,
    certify_binding,
    get_binding,
    install_repaired,
    register_binding,
)
from .translate import (  # noqa: F401
    NativeDelivery,
    from_delivery,
    to_delivery,
)
from .validate import (  # noqa: F401
    VERDICT_CONFIRMED,
    VERDICT_FLAKY,
    VERDICT_PROXY_ONLY,
    HybridBridge,
    NativeValidator,
    ValidationQueue,
    make_bridge,
)
from .reconcile import (  # noqa: F401
    DEFAULT_TIER,
    NativeHeartbeat,
    tier_of,
)
