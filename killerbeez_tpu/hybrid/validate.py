"""Cross-tier triage: TPU findings validated on the native binary.

The pipeline (docs/HYBRID.md):

  loop thread                      native worker thread(s)
  -----------                      -----------------------
  unique crash/hang  --enqueue-->  bounded ValidationQueue
                                   NativeValidator.validate():
                                     translate -> replay xN with
                                     retry/timeout/backoff (the
                                     manager-RPC conventions)
  fold() <--results--------------  verdict record
    sidecar write-back (corpus + findings dir)
    cross_tier_validate event (+ proxy_gap event & report)
    hybrid_validations counters, queue gauges
    scheduler.note_validation credit boost

Verdict taxonomy (store.VALIDATION_VERDICTS):

  * ``confirmed``  — every native repeat reproduced the finding:
    ground truth, earns the scheduler boost.
  * ``proxy_only`` — no repeat reproduced it: the proxy diverges
    from the real binary on this input.  Emits a machine-readable
    proxy-gap report — the signal for improving the proxy — and is
    NEVER silently dropped.
  * ``flaky``      — some repeats reproduced it, or the native
    substrate kept erroring: undecided, kept visible.

All corpus/event/scheduler mutation happens on the LOOP thread (in
``fold()``); worker threads only execute natively and append result
records — the same single-writer discipline the sync tier uses.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG
from ..corpus.store import (
    MAX_VALIDATION_REPEATS,
    VALIDATION_VERDICTS,
    _atomic_write,
)
from ..utils.fileio import ensure_dir
from ..utils.logging import INFO_MSG, WARNING_MSG
from .gaps import GapIndex, make_gap_report, proxy_trace_edge
from .registry import (
    ProxyBinding,
    get_binding,
    native_verdict,
    open_native,
)

VERDICT_CONFIRMED, VERDICT_PROXY_ONLY, VERDICT_FLAKY = \
    VALIDATION_VERDICTS


class ValidationItem:
    """One pending cross-tier validation."""

    __slots__ = ("kind", "buf", "md5", "parent", "proxy_status", "t")

    def __init__(self, kind: str, buf: bytes, md5: str,
                 parent: Optional[str] = None,
                 proxy_status: int = FUZZ_CRASH,
                 t: Optional[float] = None):
        self.kind = kind            # "crash" | "hang"
        self.buf = bytes(buf)
        self.md5 = md5
        self.parent = parent        # generating seed (scheduler boost)
        self.proxy_status = int(proxy_status)
        self.t = time.time() if t is None else float(t)


class ValidationQueue:
    """Bounded FIFO between the loop and the native workers.

    ``put`` REJECTS when full (backpressure toward the fast tier;
    the drop is counted and logged, never silent).  ``oldest_age``
    feeds the ``validation_backlog`` alert rule."""

    def __init__(self, cap: int = 256):
        self.cap = int(cap)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self.dropped = 0
        self._warned = 0.0

    def put(self, item: ValidationItem) -> bool:
        with self._cv:
            if len(self._q) >= self.cap:
                self.dropped += 1
                now = time.time()
                if now - self._warned > 5.0:     # rate-limited
                    self._warned = now
                    WARNING_MSG(
                        "validation queue full (cap %d): dropped %d "
                        "findings so far — native tier cannot keep "
                        "up", self.cap, self.dropped)
                return False
            self._q.append(item)
            self._cv.notify()
            return True

    def get(self, timeout: float = 0.2) -> Optional[ValidationItem]:
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def oldest_age(self, now: Optional[float] = None) -> float:
        with self._cv:
            if not self._q:
                return 0.0
            return (time.time() if now is None else now) - self._q[0].t


class NativeValidator:
    """Replays one finding on the native binary N times.

    Transient native faults (status -2: backend error, e.g. a dying
    forkserver or a refused TCP connect) are retried per attempt with
    exponential backoff — the same 0.5/1/2/4s ladder the manager RPC
    layer uses — before the repeat is recorded as an error.
    ``run_fn`` injects a fake native side for tests."""

    def __init__(self, binding: ProxyBinding, repeats: int = 3,
                 attempts: int = 4, base_delay: float = 0.1,
                 run_fn: Optional[Callable[[bytes], int]] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.binding = binding
        if int(repeats) > MAX_VALIDATION_REPEATS:
            # one status lands per repeat; beyond the sidecar schema
            # bound peers would quarantine the record on sync
            WARNING_MSG(
                "hybrid repeats %d exceeds the sidecar schema bound; "
                "clamped to %d", int(repeats), MAX_VALIDATION_REPEATS)
        self.repeats = max(1, min(int(repeats), MAX_VALIDATION_REPEATS))
        self.attempts = max(1, int(attempts))
        self.base_delay = float(base_delay)
        self._run_fn = run_fn
        self._sleep = sleep_fn
        self._target = None

    def _run_native(self, buf: bytes) -> int:
        """One native replay; returns the FUZZ_* verdict."""
        if self._run_fn is not None:
            return self._run_fn(buf)
        if self._target is None:
            self._target = open_native(self.binding.native)
        delivery = self.binding.translate(buf)
        kind, _ = native_verdict(self._target, self.binding.native,
                                 delivery)
        return kind

    def close(self) -> None:
        if self._target is not None:
            self._target.close()
            self._target = None

    def validate(self, item: ValidationItem) -> Dict[str, Any]:
        """Full verdict record for one finding (sidecar schema)."""
        t0 = time.time()
        want = FUZZ_HANG if item.kind == "hang" else FUZZ_CRASH
        statuses: List[int] = []
        n_execs = 0
        repro = 0
        errors = 0
        for _ in range(self.repeats):
            kind = FUZZ_ERROR
            for attempt in range(self.attempts):
                kind = self._run_native(item.buf)
                n_execs += 1
                if kind != FUZZ_ERROR:
                    break
                # transient native fault: reopen + back off
                self.close()
                self._sleep(self.base_delay * (2 ** attempt))
            statuses.append(int(kind))
            if kind == FUZZ_ERROR:
                errors += 1
            elif kind == want:
                repro += 1
        if errors == self.repeats:
            # never measured: undecided, not a proxy-gap claim
            verdict, detail = VERDICT_FLAKY, "native-exec-error"
        elif repro == self.repeats:
            verdict, detail = VERDICT_CONFIRMED, None
        elif repro == 0:
            verdict, detail = VERDICT_PROXY_ONLY, None
        else:
            verdict, detail = VERDICT_FLAKY, None
        rec: Dict[str, Any] = {
            "md5": item.md5, "kind": item.kind, "verdict": verdict,
            "tier": "native", "repro": repro, "repeats": self.repeats,
            "attempts": n_execs, "statuses": statuses,
            "t": round(time.time(), 3),
            "wall_s": round(time.time() - t0, 3),
        }
        if detail:
            rec["detail"] = detail
        return rec


def write_proxy_gap(output_dir: str, item: ValidationItem,
                    result: Dict[str, Any],
                    binding: ProxyBinding,
                    index: Optional["GapIndex"] = None) -> str:
    """Write the machine-readable proxy-gap report (the contract in
    docs/HYBRID.md) for one ``proxy_only`` divergence; returns its
    path (the existing report's path when the index dedups it).

    Reports carry the concrete input and the proxy-trace edge so the
    conformance pass can replay them as counterexamples; storage is
    bounded+deduped through :class:`hybrid.gaps.GapIndex`."""
    gap_dir = os.path.join(output_dir, "proxy_gaps")
    report = make_gap_report(
        md5=item.md5, kind=item.kind, binding=binding.name,
        proxy_target=binding.proxy_target,
        proxy_status=item.proxy_status,
        native_argv=binding.native.argv,
        native_delivery=binding.native.delivery,
        statuses=result.get("statuses", []),
        repro=result.get("repro", 0),
        repeats=result.get("repeats", 0),
        t=result.get("t"),
        input_bytes=item.buf,
        edge=proxy_trace_edge(binding.program(), item.buf))
    idx = index if index is not None else GapIndex(gap_dir)
    path = idx.admit(report)
    return path or os.path.join(gap_dir, f"{item.md5}.json")


class HybridBridge:
    """Glue between one TPU campaign loop and its native validators.

    Owns the bounded queue, the worker thread(s) and the pending
    result list; the loop calls ``enqueue`` from triage, ``fold``
    beside every sync round and ``finish`` at run end.  With
    ``workers=0`` nothing runs in the background and ``pump()``
    validates synchronously — the deterministic test mode."""

    def __init__(self, binding: ProxyBinding, repeats: int = 3,
                 queue_cap: int = 256, workers: int = 1,
                 validator: Optional[NativeValidator] = None,
                 validator_factory:
                     Optional[Callable[[], NativeValidator]] = None):
        self.binding = binding
        self.queue = ValidationQueue(queue_cap)
        # EVERY thread that replays natively owns its own validator —
        # the underlying ExecTarget handle is not thread-safe and the
        # retry path closes/reopens it mid-validate, so sharing one
        # across workers races (corrupted verdicts, native crashes).
        self._make_validator = validator_factory or (
            lambda: NativeValidator(binding, repeats=repeats))
        # loop-side validator: pump() / workers=0 synchronous mode
        self.validator = validator or self._make_validator()
        # completed (item, verdict-record) pairs awaiting fold()
        self._results: List = []
        self._rlock = threading.Lock()
        self._parents: Dict[str, Optional[str]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._worker_validators: List[NativeValidator] = []
        self.enqueued = 0
        self.validated = 0
        self.native_execs = 0
        # per-verdict tally (mirrors the campaign registry counters):
        # rides the native heartbeat so kb-fleet shows the verdict
        # breakdown even when no TPU-side stats reporter is running
        # (CLI --sync-manager campaigns only sync corpus)
        self.verdict_counts: Dict[str, int] = {}
        self.proxy_gaps = 0
        # lazy: the bounded gap-report index for this campaign's
        # proxy_gaps/ dir (created on the first proxy_only verdict)
        self._gap_index: Optional[GapIndex] = None
        if workers > 0:
            for i in range(int(workers)):
                v = self._make_validator()
                self._worker_validators.append(v)
                th = threading.Thread(target=self._worker, args=(v,),
                                      name=f"hybrid-native-{i}",
                                      daemon=True)
                th.start()
                self._threads.append(th)

    # -- worker side (native thread) ----------------------------------

    def _worker(self, validator: NativeValidator) -> None:
        while not self._stop.is_set():
            item = self.queue.get(0.2)
            if item is None:
                continue
            try:
                result = validator.validate(item)
            except Exception as e:     # never kill the campaign
                WARNING_MSG("hybrid validator died on %s: %s",
                            item.md5, e)
                result = {"md5": item.md5, "kind": item.kind,
                          "verdict": VERDICT_FLAKY,
                          "tier": "native", "repro": 0,
                          "repeats": validator.repeats,
                          "attempts": 0, "statuses": [],
                          "t": round(time.time(), 3),
                          "detail": f"validator-error:"
                                    f"{type(e).__name__}"[:256]}
            with self._rlock:
                self._results.append((item, result))

    # -- loop side ----------------------------------------------------

    def enqueue(self, kind: str, buf: bytes, md5: str,
                parent: Optional[str] = None,
                proxy_status: int = FUZZ_CRASH) -> bool:
        """Queue one unique finding for native validation (loop
        thread).  Idempotent per md5."""
        if md5 in self._parents:
            return False
        ok = self.queue.put(ValidationItem(
            kind, buf, md5, parent=parent, proxy_status=proxy_status))
        if ok:
            # record the dedup key only on admission: a finding the
            # full queue rejected must stay eligible when it recurs
            # after the queue drains
            self._parents[md5] = parent
            self.enqueued += 1
        return ok

    def pump(self, limit: int = 0) -> int:
        """Synchronously validate queued items on the CALLING thread
        (workers=0 mode / tests / final drain); returns how many."""
        n = 0
        while True:
            if limit and n >= limit:
                break
            item = self.queue.get(0.0)
            if item is None:
                break
            result = self.validator.validate(item)
            with self._rlock:
                self._results.append((item, result))
            n += 1
        return n

    def fold(self, fuzzer) -> int:
        """Apply completed verdicts to the campaign (LOOP thread):
        sidecars, events, counters, scheduler credit.  Returns how
        many verdicts landed."""
        with self._rlock:
            done, self._results = self._results, []
        reg = fuzzer.telemetry.registry
        for item, result in done:
            self.validated += 1
            self.native_execs += int(result.get("attempts", 0))
            verdict = result["verdict"]
            self.verdict_counts[verdict] = \
                self.verdict_counts.get(verdict, 0) + 1
            reg.count("hybrid_validations")
            reg.count(f"hybrid_{verdict}")
            # findings sidecar (always — crashes/hangs need not be
            # corpus entries) + corpus sidecar when the entry exists
            self._write_finding_sidecar(fuzzer, item, result)
            if fuzzer.store is not None:
                fuzzer.store.update_validation(item.md5, result)
            gap_path = None
            if verdict == VERDICT_PROXY_ONLY:
                self.proxy_gaps += 1
                reg.count("hybrid_proxy_gaps")
                if self._gap_index is None:
                    self._gap_index = GapIndex(os.path.join(
                        fuzzer.output_dir, "proxy_gaps"))
                gap_path = write_proxy_gap(
                    fuzzer.output_dir, item, result, self.binding,
                    index=self._gap_index)
                fuzzer.telemetry.event(
                    "proxy_gap", md5=item.md5, kind=item.kind,
                    binding=self.binding.name, report=gap_path)
            fuzzer.telemetry.event(
                "cross_tier_validate", md5=item.md5, kind=item.kind,
                verdict=verdict, tier="native",
                repro=result.get("repro", 0),
                repeats=result.get("repeats", 0),
                attempts=result.get("attempts", 0))
            fuzzer.scheduler.note_validation(
                item.md5, verdict, parent=item.parent)
            INFO_MSG("cross-tier verdict for %s %s: %s (%d/%d "
                     "native repros)", item.kind, item.md5[:12],
                     verdict, result.get("repro", 0),
                     result.get("repeats", 0))
        reg.gauge("validation_queue_depth", self.queue.depth())
        reg.gauge("validation_queue_age",
                  round(self.queue.oldest_age(), 1))
        return len(done)

    def _write_finding_sidecar(self, fuzzer, item: ValidationItem,
                               result: Dict[str, Any]) -> None:
        if not fuzzer.write_findings:
            return
        kind_dir = os.path.join(fuzzer.output_dir,
                                "crashes" if item.kind == "crash"
                                else "hangs")
        ensure_dir(kind_dir)
        path = os.path.join(kind_dir, f"{item.md5}.json")
        try:
            _atomic_write(path, json.dumps(
                {"md5": item.md5, "kind": item.kind,
                 "validation": result}).encode())
        except OSError as e:
            WARNING_MSG("finding sidecar write failed for %s: %s",
                        item.md5, e)

    def finish(self, fuzzer, drain_timeout: float = 30.0) -> None:
        """Final drain at run end: wait (bounded) for the queue to
        empty, stop workers, fold everything that completed."""
        deadline = time.monotonic() + drain_timeout
        if self._threads:
            while self.queue.depth() and time.monotonic() < deadline:
                time.sleep(0.05)
            self._stop.set()
            for th in self._threads:
                th.join(timeout=max(0.1, deadline - time.monotonic()))
        else:
            self.pump()
        self.fold(fuzzer)
        if any(th.is_alive() for th in self._threads):
            # a validation still in flight at the drain deadline
            # appends its result after the fold above: grant one
            # grace join and fold again so late verdicts land
            # instead of silently vanishing
            for th in self._threads:
                th.join(timeout=0.5)
            self.fold(fuzzer)
        self.validator.close()
        stuck = 0
        for th, v in zip(self._threads, self._worker_validators):
            if th.is_alive():
                # still mid-validate: closing its target under it is
                # the exact race per-worker validators exist to avoid
                stuck += 1
            else:
                v.close()
        with self._rlock:
            unfolded = len(self._results)
        if self.queue.depth() or self.queue.dropped or unfolded \
                or stuck:
            WARNING_MSG(
                "hybrid bridge exiting with %d unvalidated, %d "
                "dropped and %d unfolded findings; %d native "
                "worker(s) still busy (native tier too slow — raise "
                "--hybrid-queue or add native workers)",
                self.queue.depth(), self.queue.dropped, unfolded,
                stuck)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Native-tier stats block (heartbeat payload shape)."""
        counters = {
            "execs": self.native_execs,
            "hybrid_validations": self.validated,
        }
        for verdict, n in self.verdict_counts.items():
            counters[f"hybrid_{verdict}"] = n
        if self.proxy_gaps:
            counters["hybrid_proxy_gaps"] = self.proxy_gaps
        return {
            "counters": counters,
            "gauges": {
                "validation_queue_depth": self.queue.depth(),
                "validation_queue_age":
                    round(self.queue.oldest_age(), 1),
            },
        }


def make_bridge(binding_name: str, repeats: int = 3,
                queue_cap: int = 256, workers: int = 1,
                certify: bool = True) -> HybridBridge:
    """Resolve a binding by name, certify it, and build the bridge.

    Raises RuntimeError with the stand-down reason when the native
    substrate is unavailable — the CLI surfaces it and exits instead
    of running a hybrid campaign that cannot validate anything."""
    binding = get_binding(binding_name)
    if certify:
        from .registry import bind
        cert = bind(binding, certify=True, strict=True)
        if cert["certified"] is None:
            raise RuntimeError(
                f"hybrid tier unavailable for binding "
                f"{binding_name!r}: {cert['reason']}")
        INFO_MSG("proxy binding %r certified (benign seed verdict-"
                 "identical on both tiers)", binding_name)
    return HybridBridge(binding, repeats=repeats,
                        queue_cap=queue_cap, workers=workers)
