"""Seed translation between TPU byte buffers and native delivery.

The TPU tier's unit of work is a flat byte buffer (a KBVM input, or
a PR 12 framed message sequence).  The native tier's unit of work is
a DELIVERY: bytes on stdin, a file path in argv, or a message train
replayed over TCP/stdin (the reference's ``network_client`` /
``send_tcp_input`` driver layer).  Translation must be LOSSLESS in
the direction that matters — a native-confirmed finding must map
back to the exact buffer the TPU tier minted, or the verdict is
about a different input.

Two invariants, property-tested over arbitrary byte soup
(tests/test_hybrid.py):

  * delivery round-trip identity:
        ``from_delivery(to_delivery(buf, spec)) == buf``
    for every delivery mode — the delivery carries the raw buffer,
    so translation never loses bytes even though the framed DECODE
    is deliberately lossy (``unframe`` is total: count and lengths
    clip).
  * framed fixpoint: ``unframe`` then ``frame_messages`` is
    idempotent — ``canonical = train_to_buffer(buffer_to_train(buf))``
    satisfies ``buffer_to_train(canonical) == buffer_to_train(buf)``
    and re-encoding ``canonical`` returns ``canonical``.  The message
    train a native target consumes is therefore exactly the train the
    TPU stateful tier executed, whatever byte soup the mutator made.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..stateful.framing import frame_messages, unframe

#: delivery modes a :class:`~killerbeez_tpu.hybrid.registry.NativeSpec`
#: may name.  ``stdin`` / ``file`` / ``argv`` are single-shot (the
#: whole buffer is one payload); ``stdin_train`` / ``tcp`` are message
#: trains (the buffer is a PR 12 framed sequence, replayed
#: message-by-message).
DELIVERY_MODES = ("stdin", "file", "argv", "stdin_train", "tcp")
TRAIN_MODES = ("stdin_train", "tcp")


class NativeDelivery:
    """One translated seed, ready for native replay.

    ``raw`` is always the exact TPU-side buffer (the lossless back
    channel); ``payload`` is the single-shot byte string; ``messages``
    is the decoded train for train modes (None otherwise).
    """

    __slots__ = ("mode", "raw", "payload", "messages")

    def __init__(self, mode: str, payload: bytes,
                 raw: Optional[bytes] = None,
                 messages: Optional[List[bytes]] = None):
        self.mode = mode
        # None = built native-side (no TPU buffer to preserve);
        # to_delivery always sets it
        self.raw = bytes(raw) if raw is not None else None
        self.payload = bytes(payload)
        self.messages = ([bytes(m) for m in messages]
                         if messages is not None else None)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        n = len(self.messages) if self.messages is not None else 0
        r = len(self.raw) if self.raw is not None else -1
        return (f"NativeDelivery(mode={self.mode!r}, "
                f"raw={r}B, msgs={n})")


def buffer_to_train(buf: bytes, m_max: int) -> List[bytes]:
    """Decode a TPU buffer as the message train the stateful tier
    would execute.  Total on any byte soup (``unframe`` clips)."""
    return unframe(bytes(buf), m_max)


def train_to_buffer(msgs: Sequence[bytes], m_max: int) -> bytes:
    """Encode a message train as a canonical framed buffer (strict
    format; clips to the format bounds like ``reframe``)."""
    from ..stateful.framing import MAX_MSG_LEN
    clipped = [bytes(m[:MAX_MSG_LEN]) for m in list(msgs)[:m_max]]
    if not clipped:
        clipped = [b""]
    return frame_messages(clipped, m_max)


def to_delivery(buf: bytes, mode: str = "stdin",
                m_max: int = 0) -> NativeDelivery:
    """Translate one TPU buffer into a native delivery."""
    buf = bytes(buf)
    if mode not in DELIVERY_MODES:
        raise ValueError(f"unknown delivery mode {mode!r} "
                         f"(choose from {', '.join(DELIVERY_MODES)})")
    if mode in TRAIN_MODES:
        if m_max <= 0:
            raise ValueError(f"delivery mode {mode!r} needs m_max > 0")
        msgs = buffer_to_train(buf, m_max)
        return NativeDelivery(mode, payload=b"".join(msgs),
                              raw=buf, messages=msgs)
    return NativeDelivery(mode, payload=buf, raw=buf)


def from_delivery(d: NativeDelivery, m_max: int = 0) -> bytes:
    """Translate a delivery back to the TPU-side buffer.  The raw
    buffer rides in the delivery, so this is the identity for
    anything :func:`to_delivery` produced; a delivery built native-
    side (no raw) re-encodes canonically."""
    if d.raw is not None:
        return d.raw
    if d.messages is not None:
        return train_to_buffer(d.messages, m_max or len(d.messages))
    return d.payload
