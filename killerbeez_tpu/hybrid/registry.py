"""Proxy registry: declarative native⇄KBVM bindings + certification.

A :class:`ProxyBinding` ties a native target (argv / stdin / file /
TCP driver spec — the reference's driver layer, PAPER.md L2) to the
soft-KBVM proxy program the TPU tier fuzzes in its place.  ``bind()``
runs a CERTIFICATION check first: the binding's benign seed must
behave identically on both sides (same FUZZ verdict class).  A
binding that fails certification is refused — a proxy that diverges
on a benign input would make every cross-tier verdict meaningless.

Certification uses a BENIGN seed on purpose: a proxy that diverges
only on crashing inputs still binds, and that divergence surfaces
later as a ``proxy_only`` verdict plus a machine-readable proxy-gap
report — the signal for improving the proxy, never a silent drop
(docs/HYBRID.md).

When the native toolchain is absent, certification returns a
skip-with-reason record (``certified: None``) instead of failing:
the stand-down rule is "no native tier, no hybrid claims".
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE, FUZZ_RUNNING
from .translate import DELIVERY_MODES, NativeDelivery, to_delivery

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: where the corpus fixture binaries land (corpus/Makefile)
CORPUS_BUILD_DIR = os.environ.get(
    "KB_CORPUS_BUILD_DIR", os.path.join(_REPO_ROOT, "corpus", "build"))


class CertificationError(ValueError):
    """A binding's benign seed behaves differently on proxy vs
    native — the binding is refused."""


@dataclass
class NativeSpec:
    """How to run the native side of a binding (driver spec)."""

    argv: Tuple[str, ...]
    #: one of translate.DELIVERY_MODES
    delivery: str = "stdin"
    #: file mode: the input path to pass (exec_backend substitutes)
    input_file: Optional[str] = None
    #: train modes: the framed-sequence message cap (PR 12 m_max)
    m_max: int = 0
    #: tcp mode: (host, port) the launched server listens on
    addr: Optional[Tuple[str, int]] = None
    timeout: float = 2.0
    env: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        self.argv = tuple(self.argv)
        if self.delivery not in DELIVERY_MODES:
            raise ValueError(
                f"unknown delivery {self.delivery!r} "
                f"(choose from {', '.join(DELIVERY_MODES)})")
        if self.delivery in ("stdin_train", "tcp") and self.m_max <= 0:
            raise ValueError(
                f"delivery {self.delivery!r} needs m_max > 0")
        # a spec that ExecTarget cannot actually deliver must be
        # refused HERE: running the binary without its payload makes
        # every genuinely-crashing finding classify as proxy_only
        if self.delivery == "argv":
            raise ValueError(
                "delivery 'argv' has no native runner yet (translate "
                "supports it; ExecTarget does not substitute argv "
                "payloads) — use stdin or file")
        if self.delivery == "file" and not self.input_file:
            raise ValueError(
                "delivery 'file' needs input_file (the path "
                "exec_backend rewrites before each run)")


@dataclass
class ProxyBinding:
    """One native target and its soft-KBVM proxy."""

    name: str
    #: built-in KBVM target name (models/targets.py registry)
    proxy_target: str
    native: NativeSpec
    #: certification input: must be verdict-identical on both sides
    benign_seed: bytes = b"hello"
    #: crash reproducers that must ALSO be verdict-identical at bind
    #: time — () for deliberately-divergent fixtures like test_safe,
    #: where crash divergence is the point, not a wiring bug.  These
    #: double as the repair pass's certification obligations: a patch
    #: that "fixes" a gap by breaking a known-good reproducer is
    #: rejected (analysis/repair.py honesty contract).
    crash_seeds: Tuple[bytes, ...] = ()
    #: when set, the proxy program loads from this .npz instead of the
    #: target registry — how a kb-repair patched proxy installs
    #: without code changes
    program_file: Optional[str] = None

    def program(self):
        if self.program_file:
            from ..models.targets import load_program_file
            return load_program_file(self.program_file)
        from ..models.targets import get_target
        return get_target(self.proxy_target)

    def translate(self, buf: bytes) -> NativeDelivery:
        return to_delivery(buf, self.native.delivery,
                           self.native.m_max)


# -- registry ---------------------------------------------------------

_BINDINGS: Dict[str, ProxyBinding] = {}


def register_binding(binding: ProxyBinding) -> ProxyBinding:
    _BINDINGS[binding.name] = binding
    return binding


def get_binding(name: str) -> ProxyBinding:
    _ensure_builtins()
    try:
        return _BINDINGS[name]
    except KeyError:
        raise KeyError(
            f"unknown proxy binding {name!r} (choose from "
            f"{', '.join(sorted(_BINDINGS)) or '<none>'})")


def binding_names() -> List[str]:
    _ensure_builtins()
    return sorted(_BINDINGS)


_BUILTINS_DONE = False


def _ensure_builtins() -> None:
    global _BUILTINS_DONE
    if _BUILTINS_DONE:
        return
    _BUILTINS_DONE = True
    for b in builtin_bindings():
        _BINDINGS.setdefault(b.name, b)


def builtin_bindings() -> List[ProxyBinding]:
    """The shipped proxy⇄native pairs (corpus/ fixtures).

    * ``test`` — the KBVM "test" target and its native twin
      ``corpus/test.c`` (both crash on inputs starting "ABCD"): the
      faithful pair, every TPU finding should confirm.
    * ``test_safe`` — the same proxy bound to ``corpus/hybrid_safe.c``
      (reads input, always exits 0): the DELIBERATELY DIVERGENT pair
      — benign certification passes, crashes never reproduce, every
      crash verdict is ``proxy_only``.  Exists to exercise the
      proxy-gap path end to end.
    """
    d = CORPUS_BUILD_DIR
    return [
        ProxyBinding(
            name="test", proxy_target="test",
            native=NativeSpec(argv=(os.path.join(d, "test-plain"),),
                              delivery="stdin"),
            benign_seed=b"hello",
            crash_seeds=(b"ABCD",)),
        ProxyBinding(
            name="test_safe", proxy_target="test",
            native=NativeSpec(argv=(os.path.join(d, "hybrid-safe"),),
                              delivery="stdin"),
            benign_seed=b"hello"),
    ]


# -- execution (both sides) -------------------------------------------

def proxy_verdict(binding: ProxyBinding, buf: bytes) -> int:
    """Run one input through the soft-KBVM proxy; returns the FUZZ_*
    verdict with the step-budget lane mapped to FUZZ_HANG (the
    engine's wait-loop-timeout convention)."""
    import numpy as np

    from ..models import vm

    program = binding.program()
    data = np.frombuffer(bytes(buf) or b"\x00", dtype=np.uint8)
    inputs = data[None, :]
    lengths = np.array([len(bytes(buf))], dtype=np.int32)
    out = vm.run_batch(program, inputs, lengths, record_stream=False)
    status = int(out.status[0])
    return FUZZ_HANG if status == FUZZ_RUNNING else status


def open_native(spec: NativeSpec):
    """Build an ExecTarget for the binding's native side (launch-style
    for tcp).  Callers own close()."""
    from ..native.exec_backend import ExecTarget

    kwargs: Dict[str, Any] = dict(
        timeout=spec.timeout,
        extra_env=([f"{k}={v}" for k, v in spec.env.items()]
                   if spec.env else None),
    )
    if spec.delivery in ("stdin", "stdin_train"):
        kwargs["use_stdin"] = True
    elif spec.delivery == "file":
        kwargs["input_file"] = spec.input_file
    return ExecTarget(list(spec.argv), **kwargs)


def native_verdict(target, spec: NativeSpec,
                   delivery: NativeDelivery) -> Tuple[int, int]:
    """Replay one delivery on the native side; returns
    ``(FUZZ_* verdict, raw status)``."""
    from ..native.exec_backend import classify, replay_message_train

    if spec.delivery in ("stdin_train", "tcp"):
        status = replay_message_train(
            target, delivery.messages or [delivery.payload],
            mode=spec.delivery, addr=spec.addr,
            timeout=spec.timeout)
    else:
        status = target.run(delivery.payload, spec.timeout)
    kind, _ = classify(status)
    return kind, status


# -- certification ----------------------------------------------------

def _verdict_class(kind: int) -> str:
    if kind == FUZZ_CRASH:
        return "crash"
    if kind == FUZZ_HANG:
        return "hang"
    if kind == FUZZ_NONE:
        return "ok"
    return "error"


def certify_binding(binding: ProxyBinding) -> Dict[str, Any]:
    """Run the binding's benign seed through both sides and compare
    verdict classes.  Returns a certification record::

        {"certified": True | False | None, "reason": ...,
         "proxy": {"verdict": ...}, "native": {"verdict": ..., ...}}

    ``None`` means the native substrate is unavailable (toolchain
    absent / binary missing) — skip-with-reason, never a silent
    pass."""
    from ..native.build import build_error, native_available

    if not native_available():
        return {"certified": None, "binding": binding.name,
                "reason": f"native toolchain unavailable: "
                          f"{build_error()}"}
    exe = binding.native.argv[0]
    if not os.path.exists(exe):
        return {"certified": None, "binding": binding.name,
                "reason": f"native binary missing: {exe} "
                          f"(make -C corpus)"}
    target = open_native(binding.native)
    seeds = [("benign", binding.benign_seed)]
    seeds += [(f"crash[{i}]", s)
              for i, s in enumerate(binding.crash_seeds)]
    try:
        for label, seed in seeds:
            p_kind = proxy_verdict(binding, seed)
            delivery = binding.translate(seed)
            n_kind, n_status = native_verdict(
                target, binding.native, delivery)
            p_cls, n_cls = _verdict_class(p_kind), \
                _verdict_class(n_kind)
            if p_cls != n_cls:
                return {
                    "certified": False, "binding": binding.name,
                    "reason": f"{label} seed diverges: proxy={p_cls} "
                              f"native={n_cls}",
                    "proxy": {"target": binding.proxy_target,
                              "verdict": p_cls},
                    "native": {"argv": list(binding.native.argv),
                               "delivery": binding.native.delivery,
                               "verdict": n_cls,
                               "status": n_status},
                }
    finally:
        target.close()
    return {
        "certified": True, "binding": binding.name, "reason": None,
        "seeds": len(seeds),
        "proxy": {"target": binding.proxy_target, "verdict": p_cls},
        "native": {"argv": list(binding.native.argv),
                   "delivery": binding.native.delivery,
                   "verdict": n_cls, "status": n_status},
    }


def install_repaired(base: ProxyBinding, program_path: str,
                     certify: bool = True) -> ProxyBinding:
    """Register ``<base.name>+repaired``: the same native side bound
    to a kb-repair patched proxy program (.npz).

    RE-certification is mandatory by default — a patched proxy gets
    no grandfather rights from the binding it repairs.  When the
    native substrate is unavailable the install is refused (None
    certification is a skip, and a skipped check cannot admit a
    program whose whole provenance is "I changed the semantics")."""
    import dataclasses

    repaired = dataclasses.replace(
        base, name=f"{base.name}+repaired",
        program_file=os.path.abspath(program_path))
    if certify:
        cert = certify_binding(repaired)
        if cert["certified"] is not True:
            raise CertificationError(
                f"repaired binding {repaired.name!r} refused: "
                f"{cert['reason'] or 'native tier unavailable'}")
    return register_binding(repaired)


def bind(binding: ProxyBinding, certify: bool = True,
         strict: bool = True) -> Dict[str, Any]:
    """Register a binding, certification first.  ``strict`` refuses
    a binding whose benign seed diverges (CertificationError); an
    unavailable native substrate registers anyway with the skip
    reason in the record (the bridge will stand down at attach)."""
    cert: Dict[str, Any] = {"certified": None,
                            "reason": "certification skipped"}
    if certify:
        cert = certify_binding(binding)
        if strict and cert["certified"] is False:
            raise CertificationError(
                f"binding {binding.name!r} refused: {cert['reason']}")
    register_binding(binding)
    return cert
