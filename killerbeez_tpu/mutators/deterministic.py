"""Deterministic walking mutators: nop, bit_flip, arithmetic,
interesting_value, dictionary.

Each decodes an absolute iteration index into an exact mutation
(AFL-style walking order), so runs are reproducible and resumable from
the serialized iteration counter alone — matching the reference's
deterministic-iteration contract (api_mutator.tex:154-177).
"""

from __future__ import annotations

import os
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import mutate_core as mc
from .base import Mutator


class NopMutator(Mutator):
    """Returns the seed unchanged every iteration (plumbing tests)."""
    name = "nop"

    def _generate(self, its: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(its)
        return (np.tile(self.seed_buf, (n, 1)),
                np.full(n, self.seed_len, dtype=np.int32))


class BitFlipMutator(Mutator):
    """Walks the seed flipping num_bits consecutive bits per iteration."""
    name = "bit_flip"
    OPTION_SCHEMA = {"num_bits": int}
    OPTION_DESCS = {"num_bits": "consecutive bits flipped per iteration "
                                "(1/2/4, default 1)"}
    DEFAULTS = {"num_bits": 1}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        nb = int(self.options["num_bits"])
        if nb not in (1, 2, 4, 8, 16, 32):
            raise ValueError(f"bit_flip: unsupported num_bits {nb}")
        self._fn = jax.jit(jax.vmap(
            lambda b, ln, it: mc.bit_flip_at(b, ln, it, num_bits=nb),
            in_axes=(None, None, 0)))

    def get_total_iteration_count(self) -> int:
        return mc.bit_flip_total(self.seed_len,
                                 int(self.options["num_bits"]))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              jnp.asarray(its, dtype=jnp.int32))
        return bufs, lens  # device arrays: base keeps them lazy


class ArithmeticMutator(Mutator):
    """Walks +/- deltas (1..35) over 1/2/4-byte fields, both ends."""
    name = "arithmetic"

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        self._fn = jax.jit(jax.vmap(mc.arithmetic_at,
                                    in_axes=(None, None, 0)))

    def get_total_iteration_count(self) -> int:
        return mc.arithmetic_total(self.seed_len)

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              jnp.asarray(its, dtype=jnp.int32))
        return bufs, lens  # device arrays: base keeps them lazy


class InterestingValueMutator(Mutator):
    """Walks boundary values (AFL interesting 8/16/32) over the seed."""
    name = "interesting_value"

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        self._fn = jax.jit(jax.vmap(mc.interesting_at,
                                    in_axes=(None, None, 0)))

    def get_total_iteration_count(self) -> int:
        return mc.interesting_total(self.seed_len)

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              jnp.asarray(its, dtype=jnp.int32))
        return bufs, lens  # device arrays: base keeps them lazy


class DictionaryMutator(Mutator):
    """Overwrites/inserts dictionary tokens at every position.

    With no token file or inline list, tokens auto-populate from
    static analysis of a KBVM target: the branch-comparison constants
    the abstract interpreter extracts (magic strings, opcode bytes,
    guarded values — ``analysis.extract_dictionary``), the byte-level
    guidance Angora buys with dynamic taint tracking."""
    name = "dictionary"
    OPTION_SCHEMA = {"dictionary": str, "tokens": list, "target": str,
                     "program_file": str}
    OPTION_DESCS = {
        "dictionary": "path to a token file (one token per line; "
                      "\\xNN escapes allowed)",
        "tokens": "inline token list (strings)",
        "target": "KBVM target name: auto-extract tokens from its "
                  "branch-comparison constants (static analysis)",
        "program_file": "compiled .npz KBVM program to auto-extract "
                        "tokens from",
    }

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        toks: List[bytes] = []
        if "tokens" in self.options:
            toks += [t.encode() if isinstance(t, str) else bytes(t)
                     for t in self.options["tokens"]]
        if "dictionary" in self.options:
            path = self.options["dictionary"]
            if not os.path.isfile(path):
                raise ValueError(f"dictionary file not found: {path}")
            with open(path, "rb") as f:
                for line in f.read().splitlines():
                    if line and not line.startswith(b"#"):
                        toks.append(
                            line.decode("latin-1").encode("latin-1")
                            .decode("unicode_escape").encode("latin-1"))
        if not toks and ("target" in self.options
                         or "program_file" in self.options):
            toks += self._static_tokens()
        if not toks:
            raise ValueError(
                "dictionary mutator needs tokens (a token file, "
                "inline tokens, or a KBVM target/program_file to "
                "auto-extract from)")
        toks = [t[:self.max_length] for t in toks if t]
        tl = max(len(t) for t in toks)
        arr = np.zeros((len(toks), tl), dtype=np.uint8)
        for i, t in enumerate(toks):
            arr[i, :len(t)] = np.frombuffer(t, dtype=np.uint8)
        self.tokens = arr
        self.token_lens = np.array([len(t) for t in toks], dtype=np.int32)
        self._fn = jax.jit(jax.vmap(
            mc.dictionary_at, in_axes=(None, None, 0, None, None)))

    def _static_tokens(self) -> List[bytes]:
        """Auto-dictionary from the target's static analysis."""
        from ..analysis import extract_dictionary
        from ..models.targets import load_program_from_options

        prog = load_program_from_options(
            self.options, "dictionary auto-extraction needs a "
                          "'target' or 'program_file' option")
        toks = extract_dictionary(prog)
        if not toks:
            raise ValueError(
                f"static analysis of {prog.name!r} extracted no "
                f"branch-comparison constants; supply tokens")
        return toks

    def get_total_iteration_count(self) -> int:
        return mc.dictionary_total(self.seed_len, len(self.token_lens))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              jnp.asarray(its, dtype=jnp.int32),
                              jnp.asarray(self.tokens),
                              jnp.asarray(self.token_lens))
        return bufs, lens  # device arrays: base keeps them lazy
