"""Mutator engine: vmapped byte-tensor mutators behind the reference's
mutator vtable (SURVEY §2.4)."""

from .base import (
    MUTATE_INDEX_MASK, MUTATE_MULTIPLE_INPUTS, MUTATE_THREAD_SAFE, Mutator,
)
from .factory import (
    mutator_factory, mutator_help, mutator_names, register_mutator,
)

__all__ = [
    "Mutator", "MUTATE_THREAD_SAFE", "MUTATE_MULTIPLE_INPUTS",
    "MUTATE_INDEX_MASK", "mutator_factory", "mutator_help",
    "mutator_names", "register_mutator",
]
