"""The `radamsa` mutator: wraps an external radamsa binary when one
is available (the reference fetches radamsa as an ExternalProject,
CMakeLists.txt:85-97). Gated: creation fails with a clear message if
no binary is on PATH or given via options."""

from __future__ import annotations

import shutil
import subprocess
from typing import Tuple

import numpy as np

from .base import Mutator


class RadamsaMutator(Mutator):
    """External radamsa process; deterministic via per-iteration seed."""
    name = "radamsa"
    OPTION_SCHEMA = {"path": str}
    OPTION_DESCS = {"path": "radamsa binary (default: found on PATH)"}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        self.binary = self.options.get("path") or shutil.which("radamsa")
        if not self.binary:
            raise ValueError(
                "radamsa mutator: no radamsa binary found (set "
                '{"path": ...} or install radamsa)')

    def _generate(self, its: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        n = len(its)
        bufs = np.zeros((n, self.max_length), dtype=np.uint8)
        lens = np.zeros(n, dtype=np.int32)
        base_seed = int(self.options.get("seed", 0))
        for row, it in enumerate(np.asarray(its)):
            out = subprocess.run(
                [self.binary, "-s", str(base_seed + int(it))],
                input=self.seed_bytes, stdout=subprocess.PIPE, check=True
            ).stdout[:self.max_length]
            if not out:
                out = self.seed_bytes[:self.max_length]
            bufs[row, :len(out)] = np.frombuffer(out, dtype=np.uint8)
            lens[row] = len(out)
        return bufs, lens
