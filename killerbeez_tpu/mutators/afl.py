"""The `afl` mutator: AFL's full deterministic pipeline, then havoc.

Stage order mirrors AFL (and the reference's afl mutator, SURVEY
§2.4): walking bit flips (1/2/4), walking byte flips (8/16/32 bits),
arithmetic, interesting values — then endless havoc. The absolute
iteration index decodes to (stage, local index); a batch may span a
stage boundary, in which case it is assembled from per-stage device
calls (stage transitions are rare relative to stage sizes).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import mutate_core as mc
from .base import Mutator


class AflMutator(Mutator):
    """AFL deterministic stages then havoc (never exhausts)."""
    name = "afl"
    OPTION_SCHEMA = {"skip_deterministic": int, "stack_pow2": int}
    OPTION_DESCS = {
        "skip_deterministic": "1 = jump straight to havoc (AFL -d)",
        "stack_pow2": "havoc stack: max edits = 2**stack_pow2 (default 4)",
    }
    DEFAULTS = {"skip_deterministic": 0, "stack_pow2": 4}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        self._build_stages()
        sp = int(self.options["stack_pow2"])
        self._havoc = jax.jit(jax.vmap(
            lambda b, ln, k: mc.havoc_at(b, ln, k, stack_pow2=sp),
            in_axes=(None, None, 0)))
        # focus mask applies to the havoc tail only: the
        # deterministic stages are position-exhaustive walks whose
        # iteration contract must not change under a mask
        self._havoc_focus = jax.jit(jax.vmap(
            lambda b, ln, k, p: mc.havoc_focus_at(b, ln, k, p,
                                                  stack_pow2=sp),
            in_axes=(None, None, 0, None)))
        self._flip = {}
        for nb in (1, 2, 4, 8, 16, 32):
            self._flip[nb] = jax.jit(jax.vmap(
                lambda b, ln, it, nb=nb: mc.bit_flip_at(b, ln, it,
                                                        num_bits=nb),
                in_axes=(None, None, 0)))
        self._arith = jax.jit(jax.vmap(mc.arithmetic_at,
                                       in_axes=(None, None, 0)))
        self._interest = jax.jit(jax.vmap(mc.interesting_at,
                                          in_axes=(None, None, 0)))

    def _build_stages(self) -> None:
        n = self.seed_len
        bits = n * 8
        stages: List[Tuple[str, int, int]] = []  # (kind, param, size)
        if not self.options["skip_deterministic"]:
            stages += [
                ("flip", 1, mc.bit_flip_total(n, 1)),
                ("flip", 2, mc.bit_flip_total(n, 2)),
                ("flip", 4, mc.bit_flip_total(n, 4)),
                # byte flips: byte-aligned windows, one per start byte
                ("byteflip", 8, max(n, 0)),
                ("byteflip", 16, max(n - 1, 0)),
                ("byteflip", 32, max(n - 3, 0)),
                ("arith", 0, mc.arithmetic_total(n)),
                ("interest", 0, mc.interesting_total(n)),
            ]
        self.stages = stages
        self.det_total = sum(s[2] for s in stages)
        del bits

    def set_input(self, input_bytes: bytes,
                  keep_length: bool = False) -> None:
        super().set_input(input_bytes, keep_length)
        self._build_stages()

    def get_total_iteration_count(self) -> int:
        return -1  # havoc tail never exhausts

    def stage_name(self, it: int | None = None) -> str:
        """Human-readable stage for an iteration (status reporting)."""
        it = self.iteration if it is None else it
        for kind, param, size in self.stages:
            if it < size:
                return f"{kind}{param or ''}"
            it -= size
        return "havoc"

    def _run_stage(self, kind: str, param: int,
                   local: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        sb = jnp.asarray(self.seed_buf)
        sl = jnp.int32(self.seed_len)
        if kind == "flip":
            b, ln = self._flip[param](sb, sl,
                                      jnp.asarray(local, dtype=jnp.int32))
        elif kind == "byteflip":
            b, ln = self._flip[param](sb, sl,
                                      jnp.asarray(local * 8,
                                                  dtype=jnp.int32))
        elif kind == "arith":
            b, ln = self._arith(sb, sl, jnp.asarray(local, dtype=jnp.int32))
        elif kind == "interest":
            b, ln = self._interest(sb, sl,
                                   jnp.asarray(local, dtype=jnp.int32))
        else:
            raise AssertionError(kind)
        return np.asarray(b), np.asarray(ln)

    def _generate(self, its: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        out_b = np.empty((len(its), self.max_length), dtype=np.uint8)
        out_l = np.empty(len(its), dtype=np.int32)
        rel = np.asarray(its, dtype=np.int64)
        offset = 0
        remaining_mask = np.ones(len(its), dtype=bool)
        for kind, param, size in self.stages:
            in_stage = remaining_mask & (rel >= offset) & (rel < offset + size)
            if in_stage.any():
                local = (rel[in_stage] - offset).astype(np.int64)
                b, ln = self._run_stage(kind, param, local)
                out_b[in_stage] = b
                out_l[in_stage] = ln
                remaining_mask &= ~in_stage
            offset += size
        if remaining_mask.any():  # havoc tail
            local = rel[remaining_mask] - offset
            base = jax.random.key(int(self.options.get("seed", 0)))
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.asarray(local, dtype=jnp.uint32))
            if self.focus_positions is not None:
                b, ln = self._havoc_focus(
                    jnp.asarray(self.seed_buf),
                    jnp.int32(self.seed_len), keys,
                    jnp.asarray(self.focus_positions))
            else:
                b, ln = self._havoc(jnp.asarray(self.seed_buf),
                                    jnp.int32(self.seed_len), keys)
            out_b[remaining_mask] = np.asarray(b)
            out_l[remaining_mask] = np.asarray(ln)
        return out_b, out_l
