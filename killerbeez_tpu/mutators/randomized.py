"""Randomized mutators: havoc, zzuf, ni, honggfuzz, splice.

Each lane's PRNG key is derived from (base seed, absolute iteration
index) with ``jax.random.fold_in``, so candidate i is the same bytes
whether generated alone or inside any batch — per-lane mutator state
is carried as arrays, never Python state (SPMD-safe, SURVEY §7 hard
part 4).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import mutate_core as mc
from .base import Mutator


class _KeyedMutator(Mutator):
    """Shared plumbing: iteration index -> per-lane key."""

    lazy_batches = True  # _generate returns lazy device arrays

    def _base_key(self) -> jax.Array:
        """The mutator's PRNG root.  fused_spec hands THIS key to the
        fused kernel (which folds in iteration indices exactly like
        _keys), so candidate parity between the fused and unfused
        paths is anchored to one derivation."""
        return jax.random.key(int(self.options.get("seed", 0)))

    def _keys(self, its: np.ndarray) -> jax.Array:
        base = self._base_key()
        return jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.asarray(its, dtype=jnp.uint32))


class HavocMutator(_KeyedMutator):
    """AFL havoc: stacked random edits (flip/arith/interesting/blocks)."""
    name = "havoc"
    OPTION_SCHEMA = {"stack_pow2": int}
    OPTION_DESCS = {"stack_pow2": "max stacked edits = 2**stack_pow2 "
                                  "(default 4; AFL uses 7)"}
    DEFAULTS = {"stack_pow2": 4}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        sp = int(self.options["stack_pow2"])
        if not (1 <= sp <= 7):
            raise ValueError("stack_pow2 must be in 1..7")
        self._fn = jax.jit(jax.vmap(
            lambda b, ln, k: mc.havoc_at(b, ln, k, stack_pow2=sp),
            in_axes=(None, None, 0)))
        # focused variant: positions ride as a traced arg so mask
        # updates (the frontier shrinks as edges crack) only
        # recompile when the mask SIZE changes
        self._fn_focus = jax.jit(jax.vmap(
            lambda b, ln, k, p: mc.havoc_focus_at(b, ln, k, p,
                                                  stack_pow2=sp),
            in_axes=(None, None, 0, None)))

    def _generate(self, its):
        if self.focus_positions is not None:
            bufs, lens = self._fn_focus(
                jnp.asarray(self.seed_buf), jnp.int32(self.seed_len),
                self._keys(its), jnp.asarray(self.focus_positions))
            return bufs, lens
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len), self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy

    def fused_spec(self):
        """What a fused mutate+execute kernel needs to generate this
        mutator's lanes itself: (seed_buf, seed_len, base PRNG key,
        stack_pow2).  The kernel derives per-lane keys as
        fold_in(base, absolute_iteration) — EXACTLY _keys — so fused
        candidates are bit-identical to the mutate-then-execute
        pipeline."""
        return (self.seed_buf, self.seed_len, self._base_key(),
                int(self.options["stack_pow2"]))


class ZzufMutator(_KeyedMutator):
    """zzuf-style: flips each bit with probability ``ratio_bits``."""
    name = "zzuf"
    OPTION_SCHEMA = {"ratio_bits": float}
    OPTION_DESCS = {"ratio_bits": "per-bit flip probability "
                                  "(default 0.004, zzuf's default)"}
    DEFAULTS = {"ratio_bits": 0.004}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        r = float(self.options["ratio_bits"])
        if not (0.0 < r <= 1.0):
            raise ValueError("ratio_bits must be in (0, 1]")
        self._fn = jax.jit(jax.vmap(
            lambda b, ln, k: mc.zzuf_at(b, ln, k, ratio=r),
            in_axes=(None, None, 0)))
        self._fn_focus = jax.jit(jax.vmap(
            lambda b, ln, k, p: mc.zzuf_focus_at(b, ln, k, p, ratio=r),
            in_axes=(None, None, 0, None)))

    def _generate(self, its):
        if self.focus_positions is not None:
            bufs, lens = self._fn_focus(
                jnp.asarray(self.seed_buf), jnp.int32(self.seed_len),
                self._keys(its), jnp.asarray(self.focus_positions))
            return bufs, lens
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len), self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy


class NiMutator(_KeyedMutator):
    """ni-style structure-blind chunk shuffler: swaps/duplicates
    aligned chunks of the seed plus light byte noise."""
    name = "ni"
    OPTION_SCHEMA = {"chunk_size": int}
    OPTION_DESCS = {"chunk_size": "chunk granularity in bytes (default 4)"}
    DEFAULTS = {"chunk_size": 4}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        cs = int(self.options["chunk_size"])
        if cs < 1:
            raise ValueError("chunk_size must be >= 1")

        def _ni(buf, length, key):
            L = buf.shape[-1]
            ks = jax.random.split(key, 5)
            n_chunks = jnp.maximum(length // cs, 1)
            a = jax.random.randint(ks[0], (), 0, n_chunks) * cs
            b = jax.random.randint(ks[1], (), 0, n_chunks) * cs
            idx = jnp.arange(L, dtype=jnp.int32)
            in_a = (idx >= a) & (idx < a + cs)
            in_b = (idx >= b) & (idx < b + cs)
            from_b = buf[jnp.clip(b + (idx - a), 0, L - 1)]
            from_a = buf[jnp.clip(a + (idx - b), 0, L - 1)]
            swapped = jnp.where(in_a, from_b, jnp.where(in_b, from_a, buf))
            # light noise: one random byte xor
            pos = jax.random.randint(ks[2], (), 0, jnp.maximum(length, 1))
            val = jax.random.randint(ks[3], (), 1, 256).astype(jnp.uint8)
            noisy = swapped.at[pos].set(swapped[pos] ^ val)
            use_noise = jax.random.bernoulli(ks[4], 0.5)
            return jnp.where(use_noise, noisy, swapped), length

        self._fn = jax.jit(jax.vmap(_ni, in_axes=(None, None, 0)))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len), self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy


class HonggfuzzMutator(_KeyedMutator):
    """honggfuzz-style mangle: run-oriented byte-set/copy/magic/inc/dec."""
    name = "honggfuzz"
    OPTION_SCHEMA = {"max_ops": int}
    OPTION_DESCS = {"max_ops": "max stacked mangle ops (default 8)"}
    DEFAULTS = {"max_ops": 8}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        mo = int(self.options["max_ops"])
        if not (1 <= mo <= 64):
            raise ValueError("max_ops must be in 1..64")
        self._fn = jax.jit(jax.vmap(
            lambda b, ln, k: mc.mangle_at(b, ln, k, max_ops=mo),
            in_axes=(None, None, 0)))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len), self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy


class SpliceMutator(_KeyedMutator):
    """Splices the seed with corpus files at random cut points."""
    name = "splice"
    OPTION_SCHEMA = {"corpus": list, "corpus_dir": str}
    OPTION_DESCS = {
        "corpus": "inline list of base64 or plain-string second inputs",
        "corpus_dir": "directory of files to splice with",
    }

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        import os
        partners = []
        for item in self.options.get("corpus", []):
            partners.append(item.encode() if isinstance(item, str)
                            else bytes(item))
        if "corpus_dir" in self.options:
            d = self.options["corpus_dir"]
            for fn in sorted(os.listdir(d)):
                p = os.path.join(d, fn)
                if os.path.isfile(p):
                    with open(p, "rb") as f:
                        partners.append(f.read())
        partners = [p for p in partners if p]
        if not partners:
            raise ValueError("splice mutator needs corpus/corpus_dir")
        L = self.max_length
        arr = np.zeros((len(partners), L), dtype=np.uint8)
        lens = np.zeros(len(partners), dtype=np.int32)
        for i, p in enumerate(partners):
            p = p[:L]
            arr[i, :len(p)] = np.frombuffer(p, dtype=np.uint8)
            lens[i] = len(p)
        self.partners, self.partner_lens = arr, lens

        def _splice(buf, length, pbufs, plens, key):
            k0, k1 = jax.random.split(key)
            j = jax.random.randint(k0, (), 0, pbufs.shape[0])
            return mc.splice_at(buf, length, pbufs[j], plens[j], k1)

        self._fn = jax.jit(jax.vmap(
            _splice, in_axes=(None, None, None, None, 0)))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              jnp.asarray(self.partners),
                              jnp.asarray(self.partner_lens),
                              self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy
