"""The `manager` mutator: composes child mutators over a multi-part
input (reference tests/test-fuzzer.sh:220-228 `{"mutators":
["bit_flip","bit_flip"]}`; api_mutator.tex:179-196 get_input_info).

A multi-part seed (e.g. a sequence of network packets) is split into
parts; child mutator i owns part i. ``mutate`` advances one child per
call round-robin (the others replay their current part), and
``mutate_extended(MUTATE_MULTIPLE_INPUTS | i)`` returns part i of the
current composite candidate — exactly the contract the network
drivers consume.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.serialization import b64, unb64, decode_mem_array
from .base import MUTATE_INDEX_MASK, MUTATE_MULTIPLE_INPUTS, Mutator


class ManagerMutator(Mutator):
    """Composes child mutators, one per input part.

    With ``{"framed": 1}`` the composite candidate is a FRAMED
    message sequence (stateful/framing.py) instead of a bare
    concatenation: message boundaries ride in the frame header, so
    per-message mutation can never corrupt them — the structure-aware
    mutation mode of the stateful session tier.  The seed may then be
    either the usual mem-array encoding or an already-framed buffer
    (kb-frame output): framed seeds split back into their messages."""
    name = "manager"
    OPTION_SCHEMA = {"mutators": list, "mutator_options": list,
                     "framed": int, "m_max": int}
    OPTION_DESCS = {
        "mutators": 'child mutator names, e.g. ["bit_flip","havoc"]',
        "mutator_options": "per-child JSON option objects (optional)",
        "framed": "1 = compose candidates as framed message "
                  "sequences (stateful session tier; boundaries "
                  "survive any child mutation by construction)",
        "m_max": "framed: the sequence capacity (must match the "
                 "target's StatefulSpec; default 4)",
    }
    DEFAULTS = {"framed": 0, "m_max": 4}

    def __init__(self, options, input_bytes):
        # input_bytes: either an encoded mem array (JSON list of b64
        # parts) or raw bytes treated as one part
        from .factory import mutator_factory  # local import: cycle
        self._factory = mutator_factory
        super().__init__(options, input_bytes)
        names = self.options.get("mutators")
        if not names:
            raise ValueError('manager mutator needs {"mutators": [...]}')
        child_opts = self.options.get("mutator_options") or [None] * len(names)
        if len(child_opts) != len(names):
            raise ValueError("mutator_options length != mutators length")
        if len(self.parts) != len(names):
            raise ValueError(
                f"seed has {len(self.parts)} parts but {len(names)} "
                "child mutators were configured")
        self.children: List[Mutator] = []
        for name, opts, part in zip(names, child_opts, self.parts):
            o = json.dumps(opts) if isinstance(opts, dict) else opts
            self.children.append(self._factory(name, o, part))
        self.current: List[bytes] = list(self.parts)
        self._next_child = 0

    # -- seed handling: parts ------------------------------------------

    def _set_seed_buffer(self, input_bytes: bytes,
                         keep_length: bool = False) -> None:
        # keep_length is meaningless for multi-part seeds (each child
        # sizes its own part buffer); accepted for vtable parity
        try:
            parts = decode_mem_array(input_bytes.decode("ascii"))
            assert isinstance(parts, list) and parts
        except Exception:
            if self.options.get("framed"):
                # framed mode accepts a kb-frame sequence directly:
                # the framing parse is total, so any buffer splits
                from ..stateful.framing import unframe
                # the framing parse is total: always >= 1 message
                parts = [p or b"\x00"      # children reject empties
                         for p in unframe(
                             input_bytes,
                             int(self.options.get("m_max", 4)))]
            else:
                parts = [input_bytes]
        self.parts = [bytes(p) for p in parts]
        self.seed_bytes = input_bytes
        self.seed_len = len(input_bytes)
        self.max_length = max(len(p) for p in self.parts)

    def _compose(self, parts) -> bytes:
        """Parts -> one candidate buffer: framed sequence when the
        framed option is on (boundaries in the header, clipped to
        the strict frame bounds), bare concatenation otherwise."""
        if self.options.get("framed"):
            from ..stateful.framing import MAX_MSG_LEN, frame_messages
            m_max = int(self.options.get("m_max", 4))
            clipped = [bytes(p[:MAX_MSG_LEN])
                       for p in parts[:m_max]] or [b""]
            return frame_messages(clipped, m_max)
        return b"".join(parts)

    # -- iteration ------------------------------------------------------

    def get_total_iteration_count(self) -> int:
        totals = [c.get_total_iteration_count() for c in self.children]
        if any(t < 0 for t in totals):
            return -1
        return sum(totals)

    def remaining(self) -> int:
        rems = [c.remaining() for c in self.children]
        return sum(rems)

    def mutate(self, max_size: Optional[int] = None) -> Optional[bytes]:
        """Advance one child (round-robin over non-exhausted children),
        return the concatenated composite candidate."""
        n = len(self.children)
        for probe in range(n):
            i = (self._next_child + probe) % n
            child = self.children[i]
            if child.remaining() > 0:
                out = child.mutate()
                if out is not None:
                    self.current[i] = out
                    self._next_child = (i + 1) % n
                    self.iteration += 1
                    whole = self._compose(self.current)
                    return whole[:max_size] if max_size else whole
        return None  # all children exhausted

    def mutate_extended(self, flags: int = 0,
                        max_size: Optional[int] = None) -> Optional[bytes]:
        if flags & MUTATE_MULTIPLE_INPUTS:
            part = flags & MUTATE_INDEX_MASK
            if not (0 <= part < len(self.children)):
                raise ValueError(f"part index {part} out of range")
            if part == 0:
                # advancing happens when part 0 is requested; parts > 0
                # replay the same composite (network drivers iterate
                # parts 0..N-1 per candidate)
                if self.mutate() is None:
                    return None
            out = self.current[part]
            return out[:max_size] if max_size else out
        return self.mutate(max_size)

    def mutate_batch_parts(self, n: int) -> List[List[bytes]]:
        """``n`` composite candidates as per-part byte lists, advancing
        children round-robin exactly like ``n`` sequential mutate()
        calls — but each child generates ALL its turns in one batched
        call (its device path), recomposed on host.  Packet drivers
        consume this form directly (one list = one packet sequence)."""
        if n <= 0:
            raise ValueError("batch size must be positive")
        if self.remaining() < n:
            raise ValueError(
                f"{self.name}: only {self.remaining()} iterations "
                f"left, requested {n}")
        nc = len(self.children)
        rem = [c.remaining() for c in self.children]
        nxt = self._next_child
        turns: List[int] = []
        for _ in range(n):
            for probe in range(nc):
                i = (nxt + probe) % nc
                if rem[i] > 0:
                    turns.append(i)
                    rem[i] -= 1
                    nxt = (i + 1) % nc
                    break
        counts = [turns.count(i) for i in range(nc)]
        child_out: Dict[int, List[bytes]] = {}
        for i, child in enumerate(self.children):
            if counts[i]:
                bufs, lens = child.mutate_batch(counts[i])
                bufs, lens = np.asarray(bufs), np.asarray(lens)
                child_out[i] = [bufs[j, :int(lens[j])].tobytes()
                                for j in range(counts[i])]
        used = [0] * nc
        cur = list(self.current)
        out: List[List[bytes]] = []
        for i in turns:
            cur[i] = child_out[i][used[i]]
            used[i] += 1
            out.append(list(cur))
        self.current = cur
        self._next_child = nxt
        self.iteration += n
        return out

    def mutate_batch(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Composite form of mutate_batch_parts (matches ``mutate``'s
        return shape for single-buffer consumers; framed sequences
        when the framed option is on)."""
        from .base import pack_byte_rows
        parts = self.mutate_batch_parts(n)
        return pack_byte_rows([self._compose(p) for p in parts])

    def get_input_info(self) -> Tuple[int, List[int]]:
        if self.options.get("framed"):
            # framed mode: the composite IS one input (the sequence
            # travels as a single framed buffer — what single-input
            # drivers like `file` consume; parts are internal
            # structure, not separate driver inputs)
            return 1, [len(self._compose(self.current))]
        return len(self.children), [len(p) for p in self.current]

    # -- state ----------------------------------------------------------

    def _state_dict(self) -> Dict[str, Any]:
        return {
            "mutator": self.name,
            "iteration": self.iteration,
            "next_child": self._next_child,
            "current": [b64(p) for p in self.current],
            "children": [c.get_state() for c in self.children],
        }

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("mutator") not in (None, self.name):
            raise ValueError(f"state is for {d.get('mutator')!r}")
        self.iteration = int(d.get("iteration", 0))
        self._next_child = int(d.get("next_child", 0))
        if "current" in d:
            self.current = [unb64(p) for p in d["current"]]
        for child, cs in zip(self.children, d.get("children", [])):
            child.set_state(cs)

    def cleanup(self) -> None:
        for c in self.children:
            c.cleanup()
