"""Grammar-structured mutator: the host-driven twin of the in-scan
structured stages.

``GrammarMutator`` compiles a grammar spec (inline JSON, ``@file``,
``degenerate``, or ``auto`` derived from a named target's static
analysis) to device tables once at construction and vmaps
``grammar_havoc_at`` over per-lane keys — the SAME kernel the
generation scans run, so host-batch campaigns and -G campaigns draw
identical structured candidates for identical (seed, key) pairs.

Parity anchor: with the degenerate grammar every candidate is
bit-identical to ``HavocMutator`` at the same seed/stack_pow2 (the
tables carry ``nondegen == 0`` and the kernel reduces to blind
havoc).  ``fused_spec`` is the plain havoc spec — under -G the
harness's own ``grammar`` option supplies the tables, keeping one
source of structure per campaign.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..grammar import compile_grammar, derive_grammar, grammar_havoc_at
from ..grammar.spec import load_grammar
from ..grammar.tables import STAGE_P
from .randomized import _KeyedMutator


class GrammarMutator(_KeyedMutator):
    """Structure-aware havoc: field-aware splice, token substitution,
    length repair, and subtree regeneration interleaved with blind
    stacked edits, per-lane stage-byte selected."""
    name = "grammar"
    OPTION_SCHEMA = {"stack_pow2": int, "grammar": str,
                     "grammar_stage": int, "target": str}
    OPTION_DESCS = {
        "stack_pow2": "max stacked edits = 2**stack_pow2 (default 4)",
        "grammar": "spec source: inline JSON, @file, 'degenerate', "
                   "or 'auto' (derive from the static analysis of "
                   "--target)",
        "grammar_stage": "structured-stage probability numerator of "
                         "256 (default 128: half the lanes)",
        "target": "built-in target name for grammar='auto' "
                  "derivation",
    }
    DEFAULTS = {"stack_pow2": 4, "grammar": "degenerate",
                "grammar_stage": STAGE_P, "target": ""}

    def __init__(self, options, input_bytes):
        super().__init__(options, input_bytes)
        sp = int(self.options["stack_pow2"])
        if not (1 <= sp <= 7):
            raise ValueError("stack_pow2 must be in 1..7")
        src = str(self.options["grammar"]) or "degenerate"
        if src == "auto":
            tgt = str(self.options["target"])
            if not tgt:
                raise ValueError(
                    "grammar='auto' derivation needs a target name "
                    "(the grammar comes from its static analysis)")
            from ..models.targets import get_target
            gspec = derive_grammar(get_target(tgt))
        else:
            gspec = load_grammar(src)
        self.grammar_tables = compile_grammar(
            gspec, stage_p=int(self.options["grammar_stage"]))
        gtab = self.grammar_tables.device()
        self._fn = jax.jit(jax.vmap(
            lambda b, ln, k: grammar_havoc_at(b, ln, k, gtab,
                                              stack_pow2=sp),
            in_axes=(None, None, 0)))

    def _generate(self, its):
        bufs, lens = self._fn(jnp.asarray(self.seed_buf),
                              jnp.int32(self.seed_len),
                              self._keys(its))
        return bufs, lens  # device arrays: base keeps them lazy

    def fused_spec(self):
        """Fused/generation campaigns take the plain havoc spec; the
        harness's ``grammar`` option carries the structure tables (one
        source of structure per campaign, and the degenerate default
        keeps the fused path parity-anchored)."""
        return (self.seed_buf, self.seed_len, self._base_key(),
                int(self.options["stack_pow2"]))
