"""Mutator factory: name -> instance, plus aggregated help.

Mirrors the reference's mutator_factory/mutator_factory_directory
(fuzzer/main.c:344) — except mutators here are Python classes over
JAX kernels, not DLLs, so the "directory of DLLs" becomes a registry
(extensible via ``register_mutator`` for out-of-tree mutators).
"""

from __future__ import annotations

from typing import Dict, Optional, Type

from .afl import AflMutator
from .base import Mutator
from .deterministic import (
    ArithmeticMutator, BitFlipMutator, DictionaryMutator,
    InterestingValueMutator, NopMutator,
)
from .grammar import GrammarMutator
from .multipart import ManagerMutator
from .radamsa import RadamsaMutator
from .randomized import (
    HavocMutator, HonggfuzzMutator, NiMutator, SpliceMutator, ZzufMutator,
)

_REGISTRY: Dict[str, Type[Mutator]] = {}


def register_mutator(cls: Type[Mutator]) -> Type[Mutator]:
    _REGISTRY[cls.name] = cls
    return cls


for _cls in (NopMutator, BitFlipMutator, ArithmeticMutator,
             InterestingValueMutator, DictionaryMutator, HavocMutator,
             ZzufMutator, NiMutator, HonggfuzzMutator, SpliceMutator,
             AflMutator, ManagerMutator, RadamsaMutator,
             GrammarMutator):
    register_mutator(_cls)


def mutator_names() -> list[str]:
    return sorted(_REGISTRY)


def mutator_factory(name: str, options: Optional[str] = None,
                    input_bytes: bytes = b"") -> Mutator:
    """Create a mutator by name (reference mutator_factory_directory)."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown mutator {name!r}; known: {', '.join(mutator_names())}")
    return _REGISTRY[name](options, input_bytes)


def mutator_help() -> str:
    """Aggregated help across all mutators (reference mutator help)."""
    return "\n".join(_REGISTRY[n].help() for n in mutator_names())
