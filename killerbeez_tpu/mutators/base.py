"""Mutator base class — the reference's mutator vtable, batch-first.

API parity (reference docs/api/api_mutator.tex, docs/api/files/
mutator_t.c): create/cleanup/mutate/mutate_extended/get_state/
set_state/get_current_iteration/get_total_iteration_count/
get_input_info/set_input/help. ``mutate`` returns the mutated buffer
or ``None`` when the walk is exhausted (the C API's 0 return); errors
raise (the C API's -1).

The TPU-native addition is ``mutate_batch(n)``: generate candidates
for iterations ``[it, it+n)`` in one device call as
``(uint8[n, L], int32[n] lengths)``. ``mutate`` is the n==1 case, so
single-buffer semantics and batch semantics cannot drift apart.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.options import parse_options, format_help
from ..utils.serialization import b64, unb64

# mutate_extended flags (reference api_mutator.tex:89-119)
MUTATE_THREAD_SAFE = 1 << 30
MUTATE_MULTIPLE_INPUTS = 1 << 31
MUTATE_INDEX_MASK = 0x00FFFFFF


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pack_byte_rows(rows: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """Pack variable-length byte strings into the framework's batch
    shape: (uint8[n, L] zero-padded, int32[n] lengths), L rounded up
    to 8 with a floor of 8 (the shared candidate-tensor convention)."""
    max_len = max((len(r) for r in rows), default=1)
    L = max(8, _round_up(max_len, 8))
    bufs = np.zeros((len(rows), L), dtype=np.uint8)
    lens = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        bufs[i, :len(r)] = np.frombuffer(r, dtype=np.uint8)
        lens[i] = len(r)
    return bufs, lens


class Mutator:
    """Base mutator. Subclasses set ``name``, ``OPTION_SCHEMA``,
    ``OPTION_DESCS`` and implement ``_generate(its) -> (bufs, lens)``
    over absolute iteration indices."""

    name = "base"
    OPTION_SCHEMA: Dict[str, type] = {}
    OPTION_DESCS: Dict[str, str] = {}
    DEFAULTS: Dict[str, Any] = {}

    #: extra schema shared by every mutator
    _COMMON_SCHEMA = {"ratio": float, "seed": int}
    _COMMON_DESCS = {
        "ratio": "output buffer size as a multiple of the seed size "
                 "(reference setup_mutate_buffer semantics; default 2.0)",
        "seed": "PRNG seed for randomized mutators (default 0)",
    }
    _COMMON_DEFAULTS = {"ratio": 2.0, "seed": 0}

    def __init__(self, options: Optional[str], input_bytes: bytes):
        schema = {**self.OPTION_SCHEMA, **self._COMMON_SCHEMA}
        defaults = {**self._COMMON_DEFAULTS, **self.DEFAULTS}
        self.options = parse_options(options, schema, defaults)
        self.iteration = 0
        self._set_seed_buffer(bytes(input_bytes))

    # -- seed management ------------------------------------------------

    def _set_seed_buffer(self, input_bytes: bytes,
                         keep_length: bool = False) -> None:
        if len(input_bytes) == 0:
            raise ValueError(f"{self.name}: empty seed input")
        if keep_length:
            # corpus-feedback rotation: the candidate tensor width is
            # part of every compiled step's shape — keep it stable so
            # a seed swap costs zero recompiles
            if len(input_bytes) > self.max_length:
                raise ValueError(
                    f"{self.name}: seed ({len(input_bytes)}) exceeds "
                    f"the fixed buffer ({self.max_length})")
        else:
            ratio = float(self.options.get("ratio", 2.0))
            L = max(int(np.ceil(len(input_bytes) * max(ratio, 1.0))), 8)
            self.max_length = _round_up(L, 8)  # word-aligned maps
        buf = np.zeros(self.max_length, dtype=np.uint8)
        buf[:len(input_bytes)] = np.frombuffer(input_bytes, dtype=np.uint8)
        # assigned only after validation: a rejected keep_length swap
        # must not leave seed_bytes describing a seed the buffers
        # don't (state dumps would serialize the wrong walk)
        self.seed_bytes = input_bytes
        self.seed_buf = buf
        self.seed_len = len(input_bytes)

    def set_input(self, input_bytes: bytes,
                  keep_length: bool = False) -> None:
        """Swap the seed (reference set_input, api_mutator.tex:198-214).
        Resets the walk position.  ``keep_length`` keeps the candidate
        buffer width (shape-stable for compiled steps; raises if the
        new seed doesn't fit)."""
        self._set_seed_buffer(bytes(input_bytes), keep_length)
        self._stash = None  # prefetched candidates used the old seed
        self.iteration = 0

    # -- focused mutation (Angora-style byte masks) ---------------------

    #: optional int32[P] byte positions mutation should concentrate
    #: on (the frontier-dependency mask from the static layer); None
    #: = unfocused.  Mutators that can honor it do (havoc, zzuf, the
    #: afl havoc tail); deterministic walks ignore it — their
    #: iteration contract is position-exhaustive by definition.
    focus_positions = None

    def set_focus_mask(self, positions, pad_pow2: bool = False
                       ) -> None:
        """Install (or clear, with None/empty) the focus byte mask.
        Positions beyond the candidate buffer are dropped; an empty
        surviving set clears the mask — a mask must never silently
        pin mutation to nothing.

        ``pad_pow2`` cycles the surviving set up to the next
        power-of-two length: the focused kernels specialize on the
        position-array SHAPE, so a mask source whose size changes
        every install (the learn tier's per-rotation masks) would
        otherwise recompile them per size — padding collapses that
        to log2 shapes.  Repeats only skew the uniform pick WITHIN
        the mask (still a masked position), so the crack stage keeps
        its exact historical unpadded sets."""
        if positions is not None:
            positions = sorted({int(p) for p in positions
                                if 0 <= int(p) < self.max_length})
        if not positions:
            self.focus_positions = None
        else:
            if pad_pow2:
                want = 1 << (len(positions) - 1).bit_length()
                positions = (positions * ((want + len(positions) - 1)
                                          // len(positions)))[:want]
            self.focus_positions = np.asarray(positions, dtype=np.int32)
        self._stash = None  # prefetched candidates used the old mask

    # -- iteration bookkeeping -----------------------------------------

    def get_current_iteration(self) -> int:
        return self.iteration

    def get_total_iteration_count(self) -> int:
        """-1 = infinite (randomized mutators never exhaust)."""
        return -1

    def remaining(self) -> int:
        total = self.get_total_iteration_count()
        if total < 0:
            return 2**62
        return max(total - self.iteration, 0)

    # -- generation -----------------------------------------------------

    def _generate(self, its: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Produce candidates for absolute iteration indices ``its``.
        Returns (uint8[n, L], int32[n])."""
        raise NotImplementedError

    # whether mutate_batch is a real batched path (subclasses that
    # can't batch set this False; drivers consult it)
    batch_capable = True

    def peek_iterations(self, n: int) -> np.ndarray:
        """The next ``n`` absolute iteration indices WITHOUT advancing
        — fused instrumentation paths generate these lanes themselves
        and call ``advance(n)`` after the device step is enqueued."""
        if n <= 0:
            raise ValueError("batch size must be positive")
        if self.remaining() < n:
            raise ValueError(
                f"{self.name}: only {self.remaining()} iterations left, "
                f"requested {n}")
        return np.arange(self.iteration, self.iteration + n,
                         dtype=np.int64)

    def advance(self, n: int) -> None:
        self.iteration += n

    #: (start_iteration, n, bufs, lens) generated ahead of time
    _stash = None
    #: True when _generate returns LAZY device arrays (generation and
    #: transfer overlap other work); eager mutators gain nothing from
    #: prefetch_batch, so it no-ops for them
    lazy_batches = False

    def prefetch_batch(self, n: int) -> None:
        """Generate the NEXT ``n`` candidates now and start their
        device->host copies WITHOUT advancing the walk — host-exec
        drivers call this before a batch executes so the following
        mutate_batch costs zero transfer round-trips (the copies land
        while the target processes run; ~3 RTTs/batch on a tunneled
        device otherwise)."""
        if self.remaining() < n or not self.batch_capable \
                or not self.lazy_batches:
            return
        its = self.peek_iterations(n)
        bufs, lens = self._generate(its)
        for arr in (bufs, lens):
            fn = getattr(arr, "copy_to_host_async", None)
            if fn is not None:
                fn()
        self._stash = (int(its[0]), n, bufs, lens)

    def mutate_batch(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Generate the next ``n`` candidates and advance the walk.
        Raises if a finite walk has fewer than ``n`` left — callers
        clamp with ``remaining()``."""
        if self._stash is not None:
            start, sn, bufs, lens = self._stash
            self._stash = None
            if start == self.iteration and sn == n:
                self.iteration += n
                if isinstance(bufs, np.ndarray):
                    return (np.asarray(bufs, dtype=np.uint8),
                            np.asarray(lens, dtype=np.int32))
                import jax.numpy as jnp
                return bufs.astype(jnp.uint8), lens.astype(jnp.int32)
            # stale (seed swapped / walk moved): fall through
        its = self.peek_iterations(n)
        bufs, lens = self._generate(its)
        self.iteration += n
        if isinstance(bufs, np.ndarray):
            return (np.asarray(bufs, dtype=np.uint8),
                    np.asarray(lens, dtype=np.int32))
        # device-generated candidates stay device arrays: forcing them
        # to numpy here would sync the host every batch AND bounce the
        # tensors device->host->device on their way to a device-backed
        # instrumentation
        import jax.numpy as jnp
        return bufs.astype(jnp.uint8), lens.astype(jnp.int32)

    def mutate(self, max_size: Optional[int] = None) -> Optional[bytes]:
        """Single-buffer API: next candidate, or None when exhausted."""
        if self.remaining() == 0:
            return None
        bufs, lens = self.mutate_batch(1)
        out = bufs[0, :int(lens[0])].tobytes()
        if max_size is not None:
            out = out[:max_size]
        return out

    def mutate_extended(self, flags: int = 0,
                        max_size: Optional[int] = None) -> Optional[bytes]:
        """Flagged mutate (reference api_mutator.tex:89-119).
        MUTATE_MULTIPLE_INPUTS selects a part on multipart mutators;
        single-input mutators accept only part 0."""
        if flags & MUTATE_MULTIPLE_INPUTS:
            part = flags & MUTATE_INDEX_MASK
            if part != 0:
                raise ValueError(
                    f"{self.name}: single-input mutator, part {part} invalid")
        return self.mutate(max_size)

    # -- multi-part contract -------------------------------------------

    def get_input_info(self) -> Tuple[int, List[int]]:
        """(num_inputs, per-input sizes) — single-input by default
        (reference get_input_info, api_mutator.tex:179-196)."""
        return 1, [self.max_length]

    # -- state ----------------------------------------------------------

    def _state_dict(self) -> Dict[str, Any]:
        return {
            "mutator": self.name,
            "iteration": self.iteration,
            "seed": b64(self.seed_bytes),
        }

    def get_state(self) -> str:
        return json.dumps(self._state_dict())

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("mutator") not in (None, self.name):
            raise ValueError(
                f"state is for mutator {d['mutator']!r}, not {self.name!r}")
        if "seed" in d:
            self._set_seed_buffer(unb64(d["seed"]))
        self.iteration = int(d.get("iteration", 0))

    # -- misc -----------------------------------------------------------

    def cleanup(self) -> None:
        pass

    @classmethod
    def help(cls) -> str:
        schema = {**cls.OPTION_SCHEMA, **cls._COMMON_SCHEMA}
        descs = {**cls.OPTION_DESCS, **cls._COMMON_DESCS}
        head = f"{cls.name} mutator"
        doc = (cls.__doc__ or "").strip().splitlines()
        if doc:
            head += f" — {doc[0]}"
        return head + "\n" + format_help(cls.name, schema, descs)
