"""jit_harness — whole-loop-on-device instrumentation over the KBVM.

The TPU-native replacement for the reference's forkserver+SHM path
(afl_instrumentation.c): the target is a compiled KBVM program, a
candidate batch executes under one jit, and coverage triage (classify
-> novelty vs three virgin maps -> unique crash/hang via simplified
traces) happens on-device in the same program — nothing crosses the
host boundary except the few interesting lanes.

AFL-map semantics (SURVEY §2.3): ``virgin_bits`` gates new paths,
``virgin_crash``/``virgin_tmout`` gate *unique* crashes/hangs via
``simplify_trace`` (reference afl_instrumentation.c:668-707
finish_fuzz_round).

Novelty modes:
  * ``exact``      — lanes judged sequentially (lane i sees the virgin
                     map after lanes < i): bit-for-bit the single-exec
                     loop's counts; the smoke-test parity gates run in
                     this mode.
  * ``throughput`` — all lanes vs the incoming map + in-batch hash
                     dedup; over-reports within a batch the same benign
                     way the reference's persistence mode does.
"""

from __future__ import annotations

import json
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE
from ..models import targets as targets_mod
from ..models.vm import run_batch as vm_run_batch
from ..ops.coverage import (
    classify_counts, count_non_255_bytes, has_new_bits,
    merge_virgin, simplify_trace,
)
from ..ops.static_triage import (
    counts_by_slot, expand_to_map, make_static_maps, static_triage,
)
from ..utils.logging import WARNING_MSG
from ..utils.serialization import decode_array, encode_array
from .base import (
    BatchResult, CompactReport, Instrumentation, module_slice_edges,
    pack_verdicts,
)
from .factory import register_instrumentation

# the sequential exact scan is O(B) serial passes; above this lane
# count the DEFAULT novelty flips to throughput (VERDICT weak #5)
EXACT_BATCH_GATE = 1024


def _triage_exact(vb, vc, vh, cls, simp, statuses):
    def step(carry, x):
        vb, vc, vh = carry
        cls_i, simp_i, st = x
        ret, vb_n = has_new_bits(vb, cls_i)
        cret, vc_n = has_new_bits(vc, simp_i)
        hret, vh_n = has_new_bits(vh, simp_i)
        is_crash = st == FUZZ_CRASH
        is_hang = st == FUZZ_HANG
        vc = jnp.where(is_crash, vc_n, vc)
        vh = jnp.where(is_hang, vh_n, vh)
        uc = is_crash & (cret > 0)
        uh = is_hang & (hret > 0)
        return (vb_n, vc, vh), (ret, uc, uh)

    (vb2, vc2, vh2), (new_paths, uc, uh) = jax.lax.scan(
        step, (vb, vc, vh), (cls, simp, statuses))
    return new_paths, uc, uh, vb2, vc2, vh2


def _triage_counts(counts, statuses, u_slots, seg_id, vb, vc, vh,
                   exact):
    """Shared triage tail: static-edge counts -> novelty verdicts +
    virgin updates (exact = sequential dense parity scan)."""
    if exact:
        # dense parity path: expand the static universe back to the
        # 64KB map shape and judge lanes sequentially
        by_slot = counts_by_slot(counts, seg_id, u_slots.shape[0])
        bitmap = expand_to_map(by_slot, u_slots, vb.shape[0])
        cls = classify_counts(bitmap)
        simp = simplify_trace(bitmap)
        return _triage_exact(vb, vc, vh, cls, simp, statuses)
    return static_triage(
        vb, vc, vh, counts, u_slots, seg_id,
        statuses == FUZZ_CRASH, statuses == FUZZ_HANG)


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "exact", "engine", "dots"))
def _fused_step(instrs, edge_table, u_slots, seg_id, inputs, lengths,
                vb, vc, vh, mem_size, max_steps, n_edges, exact,
                engine="xla", dots=("f32", "f32")):
    """mutated batch -> VM exec -> static-edge triage, one XLA program.

    ``engine="pallas"`` runs the VM loop in the Pallas VMEM-resident
    kernel (ops/vm_kernel.py, ~4x the XLA engine on chip); the batch
    is padded to the kernel's lane tile with copies of lane 0
    (coverage no-ops) and results sliced back."""
    from ..models.vm import _run_batch_impl  # batched one-hot engine
    if engine == "pallas":
        from ..ops.vm_kernel import run_batch_pallas_padded
        res = run_batch_pallas_padded(instrs, edge_table, inputs,
                                      lengths, mem_size, max_steps,
                                      n_edges, dots=dots)
    else:
        res = _run_batch_impl(instrs, edge_table, inputs, lengths,
                              mem_size, max_steps, n_edges, False)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG, res.status)
    new_paths, uc, uh, vb2, vc2, vh2 = _triage_counts(
        res.counts, statuses, u_slots, seg_id, vb, vc, vh, exact)
    return (statuses, new_paths, uc, uh, res.exit_code, vb2, vc2, vh2,
            res.counts)


# lanes the in-step compaction can report per batch; batches with
# more interesting lanes than this fall back to a full-tensor pull
COMPACT_CAP = 1024


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "exact", "m_max", "n_states",
                                   "state_reg"))
def _session_step(instrs, edge_table, u_slots, seg_id, inputs,
                  lengths, vb, vc, vh, vs, mem_size, max_steps,
                  n_edges, exact, m_max, n_states, state_reg):
    """The stateful twin of ``_fused_step``: framed-sequence batch ->
    session execution (stateful/session.py) -> classic static-edge
    triage PLUS state x edge triage, one XLA program.  The reported
    per-lane novelty is ``max(classic, state)`` — a lane novel only
    in the state dimension is a finding too (the tier's whole
    point) — while each virgin map updates from its own dimension
    alone."""
    from ..stateful.coverage import state_triage, state_triage_exact
    from ..stateful.session import _run_session_impl
    res = _run_session_impl(instrs, edge_table, inputs, lengths,
                            mem_size, max_steps, n_edges, m_max,
                            n_states, state_reg)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                         res.status)
    new_paths, uc, uh, vb2, vc2, vh2 = _triage_counts(
        res.counts, statuses, u_slots, seg_id, vb, vc, vh, exact)
    s_rets, vs2 = (state_triage_exact if exact else state_triage)(
        vs, res.se_counts)
    combined = jnp.maximum(new_paths, s_rets)
    return (statuses, combined, uc, uh, res.exit_code, vb2, vc2, vh2,
            vs2, res.counts, res.se_counts)


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "exact", "stack_pow2",
                                   "phase1_steps", "dots"))
def _fused_fuzz_step(instrs, edge_table, u_slots, seg_id, seed_buf,
                     seed_len, base_key, its, n_real, vb, vc, vh,
                     mem_size, max_steps, n_edges, exact, stack_pow2,
                     phase1_steps=0, dots=("f32", "f32")):
    """The flagship product path: per-lane PRNG keys, havoc mutation
    AND VM execution in one program (mutate+exec share a single
    pallas_call, ops/vm_kernel.fuzz_batch_pallas) followed by
    static-edge triage — candidates are born, run and judged without
    leaving the device, and only verdicts + the mutant bytes (for
    findings writing) come back.  Key derivation fold_in(base_key,
    it) happens IN the jit: eager per-batch vmap dispatches were
    measured at ~25ms host time each on a tunneled device.  ``its``
    length must already be a LANE_TILE multiple (run_batch_fused
    pads)."""
    from ..ops.vm_kernel import (
        fuzz_batch_pallas_2phase, havoc_words_for_keys,
    )
    keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(its)
    words = havoc_words_for_keys(keys, stack_pow2)
    res, bufs, lens = fuzz_batch_pallas_2phase(
        instrs, edge_table, seed_buf, seed_len, words, mem_size,
        max_steps, n_edges, stack_pow2=stack_pow2,
        phase1_steps=phase1_steps, dots=dots)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG, res.status)
    new_paths, uc, uh, vb2, vc2, vh2 = _triage_counts(
        res.counts, statuses, u_slots, seg_id, vb, vc, vh, exact)
    # in-step compaction: gather the interesting lanes' candidate
    # bytes here so the host reads a ~COMPACT_CAP-row report instead
    # of the full [B, L] tensor (padded lanes >= n_real excluded)
    b = bufs.shape[0]
    flags = ((statuses != FUZZ_NONE) | (new_paths > 0)) & \
        (jnp.arange(b) < n_real)
    (sel_idx,) = jnp.nonzero(flags, size=min(COMPACT_CAP, b),
                             fill_value=0)
    sel_bufs = jnp.take(bufs, sel_idx, axis=0)
    sel_lens = jnp.take(lens, sel_idx)
    count = jnp.sum(flags).astype(jnp.int32)
    return (statuses, new_paths, uc, uh, res.exit_code, vb2, vc2, vh2,
            res.counts, bufs, lens,
            (sel_idx.astype(jnp.int32), sel_bufs, sel_lens, count))


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "exact", "stack_pow2", "k",
                                   "phase1_steps", "dots"))
def _fused_fuzz_multi(instrs, edge_table, u_slots, seg_id, seed_buf,
                      seed_len, base_key, its0, n_real, vb, vc, vh,
                      mem_size, max_steps, n_edges, exact, stack_pow2,
                      k, phase1_steps=0, dots=("f32", "f32")):
    """K fused fuzz steps in ONE XLA program (lax.scan), virgin maps
    threaded through the carry, verdicts bit-packed on device.

    The per-step transfer pattern of the single-step path (packed
    verdict byte + 4 compact arrays per batch) is what makes the CLI
    number hostage to tunnel RTT spikes (docs/PERF.md "Current
    ceiling"): accumulating K steps device-side divides the number of
    device->host transfer events by K — the host reads one [k, B]
    packed array and one stacked compact report per superbatch.
    Candidate streams are bit-identical to K sequential steps: step j
    executes iterations ``its0 + j*n_real`` (monotonic mutator
    consumption), padding lanes duplicate lane 0 exactly like the
    single-step path."""
    from ..ops.vm_kernel import (
        fuzz_batch_pallas_2phase, havoc_words_for_keys,
    )
    b = its0.shape[0]
    cap = min(COMPACT_CAP, b)

    def body(carry, step):
        vb, vc, vh = carry
        its = its0 + step * jnp.uint32(n_real)
        keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(its)
        words = havoc_words_for_keys(keys, stack_pow2)
        res, bufs, lens = fuzz_batch_pallas_2phase(
            instrs, edge_table, seed_buf, seed_len, words, mem_size,
            max_steps, n_edges, stack_pow2=stack_pow2,
            phase1_steps=phase1_steps, dots=dots)
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)
        new_paths, uc, uh, vb2, vc2, vh2 = _triage_counts(
            res.counts, statuses, u_slots, seg_id, vb, vc, vh, exact)
        packed = pack_verdicts(statuses, new_paths, uc, uh)
        flags = ((statuses != FUZZ_NONE) | (new_paths > 0)) & \
            (jnp.arange(b) < n_real)
        (sel_idx,) = jnp.nonzero(flags, size=cap, fill_value=0)
        sel_bufs = jnp.take(bufs, sel_idx, axis=0)
        sel_lens = jnp.take(lens, sel_idx)
        count = jnp.sum(flags).astype(jnp.int32)
        return (vb2, vc2, vh2), (packed, bufs, lens,
                                 sel_idx.astype(jnp.int32), sel_bufs,
                                 sel_lens, count)

    (vb, vc, vh), outs = jax.lax.scan(
        body, (vb, vc, vh), jnp.arange(k, dtype=jnp.uint32))
    return (vb, vc, vh) + tuple(outs)


@register_instrumentation
class JitHarnessInstrumentation(Instrumentation):
    """Executes KBVM targets fully on-device with AFL-map triage."""
    name = "jit_harness"
    supports_batch = True
    device_backed = True
    OPTION_SCHEMA = {"target": str, "program_file": str, "max_steps": int,
                     "novelty": str, "edges": int, "engine": str,
                     "phase1_steps": int, "gen_ring_slots": int,
                     "gen_findings_cap": int, "gen_admits": int,
                     "gen_fold_every": int, "stateful": int,
                     "msgs": int, "n_states": int, "state_reg": int,
                     "learn": int, "grammar": str,
                     "grammar_stage": int}
    OPTION_DESCS = {
        "target": "built-in KBVM target name (test/hang/libtest/cgc_like)",
        "program_file": "path to a .npz compiled KBVM program",
        "max_steps": "override the program's hang step budget",
        "novelty": '"exact" (sequential parity; the default, but '
                   'auto-switches to throughput above 1024-lane '
                   'batches) or "throughput"',
        "edges": "1 = record per-exec edge lists (tracer mode)",
        "engine": '"xla" (default), "pallas" (VMEM-resident VM '
                  'kernel, ~4x on chip) or "pallas_fused" (mutation '
                  "AND execution in one kernel — requires a fusable "
                  "mutator like havoc; the flagship path)",
        "phase1_steps": "fused-engine two-phase tail scheduling: "
                        "phase-1 step budget (-1 = auto: max_steps/8 "
                        "when max_steps >= 256, measured ~1.5x on "
                        "deep targets; 0 = single phase)",
        "gen_ring_slots": "--generations: device seed-slot ring size "
                          "(slot 0 pins the base seed; default 32, "
                          "min 2)",
        "gen_findings_cap": "--generations: bounded findings-ring "
                            "rows per dispatch (overflow is counted "
                            "as findings_ring_drops, never silent; "
                            "0 = auto: min(16384, max(batch/8, 256)) "
                            "— every generation pays an append of "
                            "width min(cap, batch), so the default "
                            "stays well below the batch shape)",
        "gen_admits": "--generations: max ring admissions per "
                      "generation, lane order (default 8)",
        "gen_fold_every": "--generations on --mesh: AND-fold virgin "
                          "maps across dp every E generations INSIDE "
                          "the scan (ICI collectives, no host "
                          "round-trip).  0 = auto: once per dispatch "
                          "with reseeding on (cheapest), every "
                          "generation with reseeding off (-fb 0, the "
                          "host-mesh parity cadence).  Between folds "
                          "shards may re-find each other's paths — "
                          "over-report, never under-report",
        "stateful": "1 = session tier: inputs are framed message "
                    "sequences executed message-by-message from "
                    "carried machine state, with state x edge "
                    "novelty folded alongside the classic map "
                    "(docs/STATEFUL.md; forces the xla engine)",
        "msgs": "stateful: max messages per sequence (0 = the "
                "target's registered StatefulSpec, else 4)",
        "n_states": "stateful: abstract-state buckets the state "
                    "register clips into (0 = registered spec, "
                    "else 16)",
        "state_reg": "stateful: the protocol-state register "
                     "(-1 = registered spec, else r7)",
        "learn": "1 = learned mutation shaping (killerbeez_tpu/"
                 "learn/): the loop's byte-saliency model shapes "
                 "havoc positions — per generation inside the -G "
                 "scan, per rotation via focus masks in the host "
                 "loop (forces the xla engine; docs/LEARN.md)",
        "grammar": "structure-aware generation tier (killerbeez_tpu/"
                   "grammar/; docs/GRAMMAR.md): a grammar spec as "
                   "JSON, @path, \"auto\" (derive from the target's "
                   "static analysis) or \"degenerate\" (the parity "
                   "anchor).  Compiled to device tables the -G scan "
                   "threads; forces the xla engine; exclusive with "
                   "learn",
        "grammar_stage": "grammar: structured-lane probability "
                         "numerator of 256 (default 128 = half the "
                         "lanes run structured stages)",
    }
    DEFAULTS = {"novelty": "exact", "edges": 0, "engine": "xla",
                "phase1_steps": -1, "gen_ring_slots": 32,
                "gen_findings_cap": 0, "gen_admits": 8,
                "gen_fold_every": 0, "stateful": 0, "msgs": 0,
                "n_states": 0, "state_reg": -1, "learn": 0,
                "grammar": "", "grammar_stage": 128}

    def __init__(self, options: Optional[str] = None):
        super().__init__(options)
        self.program = prog = targets_mod.load_program_from_options(
            self.options,
            'jit_harness needs {"target": name} or '
            '{"program_file": path}')
        if self.options["novelty"] not in ("exact", "throughput"):
            raise ValueError('novelty must be "exact" or "throughput"')
        if self.options["engine"] not in ("xla", "pallas",
                                          "pallas_fused"):
            raise ValueError(
                'engine must be "xla", "pallas" or "pallas_fused"')
        self.engine = self.options["engine"]
        # -- stateful session tier (killerbeez_tpu/stateful/) --------
        # resolved spec: explicit options win, then the target's
        # registered StatefulSpec, then the package defaults
        self.stateful_spec = None
        if self.options["stateful"]:
            from ..models.targets_stateful import get_stateful_spec
            from ..stateful import StatefulSpec
            reg_spec = get_stateful_spec(prog.name) or StatefulSpec()
            self.stateful_spec = StatefulSpec(
                m_max=(int(self.options["msgs"]) or reg_spec.m_max),
                n_states=(int(self.options["n_states"])
                          or reg_spec.n_states),
                state_reg=(int(self.options["state_reg"])
                           if int(self.options["state_reg"]) >= 0
                           else reg_spec.state_reg))
            if self.engine != "xla":
                WARNING_MSG(
                    "jit_harness: stateful sessions run the one-hot "
                    "xla engine — %r stands down (the pallas kernel "
                    "executes single-shot inputs only)", self.engine)
                self.engine = "xla"
        # -- learned mutation shaping (killerbeez_tpu/learn/) ---------
        # the loop installs the live model weights here before each
        # --generations dispatch; the scan runs inference per
        # generation (None = shaping off, the exact historical path)
        self.learn_params = None
        if self.options["learn"] and self.engine != "xla":
            WARNING_MSG(
                "jit_harness: learned mutation shaping runs the xla "
                "engine — %r stands down (the fused VMEM kernel "
                "generates candidates in-kernel and cannot consume "
                "a per-generation mask)", self.engine)
            self.engine = "xla"
        # -- grammar tier (killerbeez_tpu/grammar/) -------------------
        # the spec compiles to fixed-shape device tables ONCE here;
        # the -G scan threads them through the jitted carry.  "auto"
        # derives from the target's static analysis; "degenerate"
        # compiles the parity-anchor tables (bit-identical scan)
        self.grammar_tables = None
        if self.options["grammar"]:
            if self.options["learn"]:
                raise ValueError(
                    "grammar and learn are mutually exclusive — "
                    "both tiers would own the in-scan mutation "
                    "kernel")
            from ..grammar import compile_grammar, derive_grammar
            from ..grammar.spec import load_grammar
            src = str(self.options["grammar"])
            gspec = derive_grammar(prog) if src == "auto" \
                else load_grammar(src)
            self.grammar_tables = compile_grammar(
                gspec, stage_p=int(self.options["grammar_stage"]))
            if self.engine != "xla":
                WARNING_MSG(
                    "jit_harness: grammar-structured generations "
                    "run the xla engine — %r stands down (the fused "
                    "VMEM kernel generates candidates in-kernel and "
                    "cannot consume the structure tables)",
                    self.engine)
                self.engine = "xla"
        self._fuse_warned = False
        from ..ops.vm_kernel import auto_phase1_steps, dot_modes
        # exactness-guarded MXU dtypes, decided once per program
        self._dots = dot_modes(prog.instrs, prog.n_edges)
        p1 = int(self.options["phase1_steps"])
        self.phase1_steps = auto_phase1_steps(self.program.max_steps) \
            if p1 < 0 else p1
        self.exact = self.options["novelty"] == "exact"
        # whether the user ASKED for exact (vs inheriting the default):
        # the default flips to throughput above EXACT_BATCH_GATE lanes,
        # an explicit request is honored (with a warning)
        try:
            raw_keys = json.loads(options) if options else {}
        except ValueError:
            raw_keys = {}
        self._novelty_explicit = "novelty" in raw_keys
        self._gate_warned = False
        self._instrs = jnp.asarray(prog.instrs)
        self._edge_table = jnp.asarray(prog.edge_table)
        u_slots, seg_id = make_static_maps(prog.edge_slot)
        self._u_slots = jnp.asarray(u_slots)
        self._seg_id = jnp.asarray(seg_id)
        # one 64KB map per module, laid out flat: module m owns
        # [m*MAP_SIZE, (m+1)*MAP_SIZE) — per-module virgin state like
        # the reference's target_module_t list
        ms = prog.map_size
        self.virgin_bits = jnp.full((ms,), 0xFF, dtype=jnp.uint8)
        self.virgin_crash = jnp.full((ms,), 0xFF, dtype=jnp.uint8)
        self.virgin_tmout = jnp.full((ms,), 0xFF, dtype=jnp.uint8)
        # the state x edge virgin map (stateful tier only; edge-index
        # space, n_states x (E+1) bytes — see stateful/coverage.py)
        if self.stateful_spec is not None:
            from ..stateful.coverage import fresh_virgin_state
            self.virgin_state = fresh_virgin_state(
                self.stateful_spec.n_states, prog.n_edges)
        else:
            self.virgin_state = None
        self.total_execs = 0
        self._last_counts: Optional[np.ndarray] = None
        self._last_se: Optional[np.ndarray] = None
        self._last_unique_crash = False
        self._last_unique_hang = False
        # --generations device state: seed-slot ring (lazy-built from
        # the mutator's buffer on the first dispatch) + the global
        # generation counter that salts deterministic slot selection
        self._gen_ring = None
        self._gen_ring_key = None
        self._gen_count = 0

    # -- batched --------------------------------------------------------

    def _apply_exact_gate(self, b: int) -> None:
        """Flip the DEFAULT novelty to throughput above the gate (an
        explicit "exact" request is honored with a warning).  The flip
        changes counts the persistence-mode way: within one batch all
        lanes are judged against the incoming virgin maps, so several
        lanes covering the same new path each count (over-report,
        never under-report) — see docs/USAGE.md."""
        if not (self.exact and b > EXACT_BATCH_GATE) or self._gate_warned:
            return
        self._gate_warned = True
        if self._novelty_explicit:
            WARNING_MSG(
                "jit_harness: exact novelty judges lanes "
                "sequentially — batch %d will be slow (parity "
                "gates only; use \"novelty\": \"throughput\" for "
                "fuzzing)", b)
        else:
            WARNING_MSG(
                "jit_harness: batch %d > %d — switching default "
                "novelty to \"throughput\": same-step duplicates of a "
                "new path each count, inflating new-path totals the "
                "way the reference's persistence mode does (pass "
                "{\"novelty\": \"exact\"} to force the sequential "
                "parity scan)", b, EXACT_BATCH_GATE)
            self.exact = False

    def run_batch(self, inputs, lengths) -> BatchResult:
        b = int(inputs.shape[0])    # no np.asarray: would sync lazy
                                    # device inputs to host
        self._apply_exact_gate(b)
        inputs = jnp.asarray(inputs, dtype=jnp.uint8)
        lengths = jnp.asarray(lengths, dtype=jnp.int32)
        if self.stateful_spec is not None:
            return self._run_batch_stateful(inputs, lengths)
        (statuses, new_paths, uc, uh, exit_codes, vb, vc, vh,
         counts) = _fused_step(
            self._instrs, self._edge_table, self._u_slots, self._seg_id,
            inputs, lengths, self.virgin_bits,
            self.virgin_crash, self.virgin_tmout, self.program.mem_size,
            self.program.max_steps, self.program.n_edges, self.exact,
            "pallas" if self.engine == "pallas_fused" else self.engine,
            self._dots)
        self.virgin_bits, self.virgin_crash, self.virgin_tmout = vb, vc, vh
        self.total_execs += int(inputs.shape[0])
        if self.options.get("edges"):
            self._last_counts = np.asarray(counts)
        # LAZY device arrays: forcing them here would sync the host to
        # this batch; the fuzzer loop pipelines one batch ahead and
        # materializes results when it triages
        return BatchResult(
            statuses=statuses,
            new_paths=new_paths,
            unique_crashes=uc,
            unique_hangs=uh,
            exit_codes=exit_codes,
        )

    def _run_batch_stateful(self, inputs, lengths) -> BatchResult:
        """Session-tier batch execution: framed sequences through the
        device session scan, dual-map triage (see _session_step)."""
        spec = self.stateful_spec
        (statuses, new_paths, uc, uh, exit_codes, vb, vc, vh, vs,
         counts, se) = _session_step(
            self._instrs, self._edge_table, self._u_slots,
            self._seg_id, inputs, lengths, self.virgin_bits,
            self.virgin_crash, self.virgin_tmout, self.virgin_state,
            self.program.mem_size, self.program.max_steps,
            self.program.n_edges, self.exact, spec.m_max,
            spec.n_states, spec.state_reg)
        self.virgin_bits, self.virgin_crash, self.virgin_tmout = \
            vb, vc, vh
        self.virgin_state = vs
        self.total_execs += int(inputs.shape[0])
        if self.options.get("edges"):
            self._last_counts = np.asarray(counts)
            self._last_se = np.asarray(se)
        # results stay LAZY (see run_batch)
        return BatchResult(statuses=statuses, new_paths=new_paths,
                           unique_crashes=uc, unique_hangs=uh,
                           exit_codes=exit_codes)

    # -- fused mutate+execute (the flagship product path) ---------------

    def wants_fused(self, mutator) -> bool:
        """True when this instrumentation should drive the one-kernel
        mutate+execute path for ``mutator`` (drivers consult this
        before mutate_batch).  Any pallas engine auto-fuses with a
        fusable mutator — the fused kernel consumes the mutator's OWN
        per-lane keys, so candidates and verdicts are bit-identical
        to the mutate-then-execute pipeline, just without the HBM
        round-trip between the two."""
        if self.stateful_spec is not None:
            # sessions execute in the one-hot engine; the fused VMEM
            # kernel runs single-shot inputs only
            return False
        fusable = getattr(mutator, "fused_spec", None) is not None
        if self.engine == "pallas_fused" and not fusable \
                and not self._fuse_warned:
            self._fuse_warned = True
            WARNING_MSG(
                "jit_harness: engine \"pallas_fused\" needs a fusable "
                "mutator (havoc); %s mutates separately — running the "
                "unfused pallas engine",
                getattr(mutator, "name", type(mutator).__name__))
        if getattr(mutator, "focus_positions", None) is not None:
            # a focus mask (crack-stage frontier bytes) is honored
            # only by the mutate-then-execute path; silently fusing
            # would drop the mask, so fusion stands down until the
            # mask clears
            return False
        return self.engine in ("pallas", "pallas_fused") and fusable

    def run_batch_fused(self, mutator, its, pad_to: Optional[int] = None
                        ) -> Tuple[BatchResult, Any, Any, CompactReport]:
        """Execute iterations ``its`` of ``mutator`` (havoc) with
        mutation fused into the VM kernel.  Returns (BatchResult,
        mutant bufs uint8[B, L], lens int32[B], CompactReport) — B is
        ``its`` padded to a LANE_TILE multiple (>= pad_to) with
        REPEATS OF LANE 0's iteration: the duplicate mutants are
        coverage no-ops, exactly like the unfused path's lane-0
        padding; callers triage only the first len(its) lanes."""
        from ..ops.vm_kernel import LANE_TILE
        n = len(its)
        b = max(n, pad_to or 0)
        b += (-b) % LANE_TILE
        self._apply_exact_gate(b)
        seed_buf, seed_len, base_key, stack_pow2 = mutator.fused_spec()
        its = np.asarray(its, dtype=np.uint32)
        if b > n:  # duplicate lane 0's iteration: coverage no-ops
            its = np.concatenate([its, np.repeat(its[:1], b - n)])
        (statuses, new_paths, uc, uh, exit_codes, vb, vc, vh, counts,
         bufs, lens, compact) = _fused_fuzz_step(
            self._instrs, self._edge_table, self._u_slots, self._seg_id,
            jnp.asarray(seed_buf), jnp.int32(seed_len), base_key,
            jnp.asarray(its), jnp.int32(n),
            self.virgin_bits, self.virgin_crash, self.virgin_tmout,
            self.program.mem_size, self.program.max_steps,
            self.program.n_edges, self.exact, stack_pow2,
            self.phase1_steps, self._dots)
        self.virgin_bits, self.virgin_crash, self.virgin_tmout = vb, vc, vh
        # count REQUESTED lanes, not the LANE_TILE-rounded padding:
        # keeps total_execs (and state export/merge) identical across
        # engines for the same campaign
        self.total_execs += n
        if self.options.get("edges"):
            self._last_counts = np.asarray(counts)
        # results stay LAZY (see run_batch): the fuzzer loop pipelines
        return (BatchResult(
            statuses=statuses, new_paths=new_paths, unique_crashes=uc,
            unique_hangs=uh, exit_codes=exit_codes), bufs, lens,
            CompactReport(*compact))

    def run_batch_fused_multi(self, mutator, its, k: int,
                              pad_to: Optional[int] = None):
        """K fused steps in one dispatch (see _fused_fuzz_multi).
        Returns (packed uint8[k, B], bufs uint8[k, B, L],
        lens int32[k, B], (idx, bufs, lens, count) stacked compact) —
        all LAZY device arrays; step j of the superbatch executed
        iterations ``its + j*len(its)``.  Callers advance the mutator
        by k*len(its)."""
        from ..ops.vm_kernel import LANE_TILE
        n = len(its)
        b = max(n, pad_to or 0)
        b += (-b) % LANE_TILE
        self._apply_exact_gate(b)
        seed_buf, seed_len, base_key, stack_pow2 = mutator.fused_spec()
        its = np.asarray(its, dtype=np.uint32)
        if b > n:  # duplicate lane 0's iteration: coverage no-ops
            its = np.concatenate([its, np.repeat(its[:1], b - n)])
        (vb, vc, vh, packed, bufs, lens, sel_idx, sel_bufs, sel_lens,
         counts) = _fused_fuzz_multi(
            self._instrs, self._edge_table, self._u_slots, self._seg_id,
            jnp.asarray(seed_buf), jnp.int32(seed_len), base_key,
            jnp.asarray(its), jnp.int32(n),
            self.virgin_bits, self.virgin_crash, self.virgin_tmout,
            self.program.mem_size, self.program.max_steps,
            self.program.n_edges, self.exact, stack_pow2,
            int(k), self.phase1_steps, self._dots)
        self.virgin_bits, self.virgin_crash, self.virgin_tmout = vb, vc, vh
        self.total_execs += int(k) * n
        return packed, bufs, lens, (sel_idx, sel_bufs, sel_lens, counts)

    # -- device-resident generations (ops/generations.py) ---------------

    def supports_generations(self, mutator) -> bool:
        """True when the G-generation device loop can drive
        ``mutator``: it needs the fused candidate spec (havoc's
        keyed per-lane streams) and stands down while a crack-stage
        focus mask is installed (the device loop generates candidates
        itself and would silently drop the mask).  Unlike the fused
        superbatch path this is engine-agnostic — the XLA engine runs
        the same scan (the CPU/CI surface)."""
        return (getattr(mutator, "fused_spec", None) is not None
                and getattr(mutator, "focus_positions", None) is None
                and not self.options.get("edges"))

    def _ensure_gen_ring(self, seed_buf, seed_len) -> None:
        """(Re)build the device seed-slot ring: slot 0 = the base
        seed, pinned; the rest empty until edge-novel lanes admit.
        Rebuilt when the candidate buffer width changes (a new base
        seed shape would make stale slots unloadable)."""
        slots = max(int(self.options["gen_ring_slots"]), 2)
        L = int(np.asarray(seed_buf).shape[0])
        if self._gen_ring is not None and \
                self._gen_ring_key == (L, slots):
            return
        bufs = jnp.zeros((slots, L), jnp.uint8).at[0].set(
            jnp.asarray(seed_buf, dtype=jnp.uint8))
        lens = jnp.zeros((slots,), jnp.int32).at[0].set(
            jnp.int32(seed_len))
        filled = jnp.zeros((slots,), jnp.int32).at[0].set(1)
        z = jnp.zeros((slots,), jnp.int32)
        self._gen_ring = (bufs, lens, filled, z, z, jnp.int32(0))
        self._gen_ring_key = (L, slots)

    def run_batch_generations(self, mutator, its, g: int,
                              pad_to: Optional[int] = None,
                              reseed: bool = True):
        """Run ``g`` full generations on device in ONE dispatch
        (ops/generations.run_generations): mutate from the device
        seed-slot ring, execute, triage against the device-resident
        virgin maps, reseed the ring from edge-novel lanes, and
        return the bounded findings ring + admission ledger as a LAZY
        GenerationOutcome.  Generation j consumes iterations
        ``its + j*len(its)``; callers advance the mutator by
        ``g*len(its)``.  ``reseed=False`` pins every generation to
        slot 0 (the base seed) — the candidate stream is then
        bit-identical to the host-driven loop's."""
        from ..ops.generations import (
            GenerationOutcome, gen_ring_caps, run_generations,
        )
        from ..ops.vm_kernel import LANE_TILE
        n = len(its)
        b = max(n, pad_to or 0)
        if self.engine in ("pallas", "pallas_fused"):
            b += (-b) % LANE_TILE
        self._apply_exact_gate(b)
        seed_buf, seed_len, base_key, stack_pow2 = mutator.fused_spec()
        self._ensure_gen_ring(seed_buf, seed_len)
        its = np.asarray(its, dtype=np.uint32)
        if b > n:  # duplicate lane 0's iteration: coverage no-ops
            its = np.concatenate([its, np.repeat(its[:1], b - n)])
        salt = int(getattr(mutator, "options", {}).get("seed", 0)) \
            & 0xFFFFFFFF
        # ring sizing shared with the mesh path (the measured-knee
        # auto cap rationale lives on gen_ring_caps)
        adm_cap, cap = gen_ring_caps(
            self.options["gen_admits"],
            self.options["gen_findings_cap"], b,
            self._gen_ring_key[1])
        spec = self.stateful_spec
        stateful = None if spec is None else (
            spec.m_max, spec.n_states, spec.state_reg)
        vs = self.virgin_state if spec is not None \
            else jnp.zeros((1,), jnp.uint8)
        # learned mutation shaping (learn/): the loop installs the
        # live model weights before each dispatch; inference runs
        # per generation INSIDE the scan (docs/LEARN.md)
        learn = self.learn_params is not None
        lp = self.learn_params if learn else ()
        # grammar tier: compiled structure tables ride the dispatch
        # as a replicated pytree (None = the exact historical path)
        grammar = self.grammar_tables is not None
        gtab = self.grammar_tables.device() if grammar else ()
        (vb, vc, vh, vs), ring, rep = run_generations(
            self._instrs, self._edge_table, self._u_slots,
            self._seg_id, *self._gen_ring, base_key,
            jnp.asarray(its), jnp.int32(n),
            jnp.uint32(self._gen_count), jnp.uint32(salt),
            self.virgin_bits, self.virgin_crash, self.virgin_tmout,
            vs, lp, gtab,
            mem_size=self.program.mem_size,
            max_steps=self.program.max_steps,
            n_edges=self.program.n_edges, exact=self.exact,
            stack_pow2=stack_pow2, g=int(g),
            engine=("pallas" if self.engine in ("pallas",
                                                "pallas_fused")
                    else "xla"),
            phase1_steps=self.phase1_steps, dots=self._dots,
            reseed=bool(reseed), adm_cap=adm_cap, findings_cap=cap,
            stateful=stateful, learn=learn, grammar=grammar)
        self.virgin_bits, self.virgin_crash, self.virgin_tmout = \
            vb, vc, vh
        if spec is not None:
            self.virgin_state = vs
        self._gen_ring = ring
        out = GenerationOutcome(*rep, gen0=self._gen_count, g=int(g),
                                n_real=n, cap=cap)
        self._gen_count += int(g)
        self.total_execs += int(g) * n
        return out

    # -- single-exec shim ----------------------------------------------

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        if input_bytes is None:
            raise ValueError("jit_harness needs input bytes")
        L = max(((len(input_bytes) + 7) // 8) * 8, 8)
        buf = np.zeros((1, L), dtype=np.uint8)
        buf[0, :len(input_bytes)] = np.frombuffer(input_bytes,
                                                  dtype=np.uint8)
        res = self.run_batch(buf, np.array([len(input_bytes)],
                                           dtype=np.int32))
        self.last_status = int(res.statuses[0])
        self.last_new_path = int(res.new_paths[0])
        self._last_unique_crash = bool(res.unique_crashes[0])
        self._last_unique_hang = bool(res.unique_hangs[0])

    def last_unique_crash(self) -> bool:
        return self._last_unique_crash

    def last_unique_hang(self) -> bool:
        return self._last_unique_hang

    def get_edges(self) -> Optional[List[Tuple[int, int]]]:
        """Edge slots of the last exec (lane 0) as (slot, hit_count)
        pairs; tracer consumes these (requires {"edges": 1}).

        Counts are mod-256, exactly like AFL's uint8 trace_bits: an
        edge hit a multiple of 256 times wraps to 0 and drops out —
        the same (known) blind spot the reference inherits from its
        map format."""
        if self._last_counts is None:
            return None
        c = self._last_counts[0, :-1].astype(np.int64)
        slots = np.asarray(self.program.edge_slot)
        agg: dict = {}
        for s, n in zip(slots, c):
            if n:
                agg[int(s)] = agg.get(int(s), 0) + int(n)
        return sorted(agg.items())

    def last_trace(self) -> Optional[np.ndarray]:
        """Dense uint8[map_size] bitmap of the last exec (lane 0),
        rebuilt from the static-edge counts — the afl-style raw-trace
        surface the picker consumes (requires {"edges": 1}, like
        get_edges; counts wrap at u8 exactly like trace_bits)."""
        if self._last_counts is None:
            return None
        dense = np.zeros(self.program.map_size, np.uint8)
        np.add.at(dense, np.asarray(self.program.edge_slot),
                  self._last_counts[0, :-1])
        return dense

    def get_edge_pairs(self, module: Optional[str] = None
                       ) -> Optional[List[Tuple[int, int, int]]]:
        """(from_id, to_id, hit_count) records of the last exec —
        the reference's edge mode returns instrumentation_edge_t
        {from, to} lists per module
        (dynamorio_instrumentation.c:1577-1606); the static universe
        makes the pair exact (0 = program entry).  ``module`` filters
        to edges landing in that module.  Counts are mod-256 (see
        get_edges)."""
        if self._last_counts is None:
            return None
        c = self._last_counts[0, :-1]
        ids = self.program.block_ids
        mod_range = None
        if module is not None:
            m = list(self.program.module_names).index(module)
            mod_range = self.program.modules[m][1:]
        out = []
        for e in np.nonzero(c)[0]:
            f = int(self.program.edge_from[e])
            t = int(self.program.edge_to[e])
            if mod_range is not None and not \
                    (mod_range[0] <= t < mod_range[1]):
                continue
            out.append((0 if f < 0 else ids[f], ids[t], int(c[e])))
        return out

    def get_module_info(self) -> List[str]:
        """Coverage module names (reference get_module_info: one entry
        per target module / shared library)."""
        return list(self.program.module_names)

    def module_coverage_bytes(self) -> Dict[str, int]:
        """Touched virgin bytes per module (per-module novelty
        reporting; reference dynamorio keeps per-module virgin maps)."""
        vb = np.asarray(self.virgin_bits)
        out = {}
        for m, name in enumerate(self.program.module_names):
            sl = vb[m * MAP_SIZE:(m + 1) * MAP_SIZE]
            out[name] = int((sl != 0xFF).sum())
        return out

    def module_map_ranges(self):
        return [(name, m * MAP_SIZE, (m + 1) * MAP_SIZE)
                for m, name in enumerate(self.program.module_names)]

    def get_module_edges(self, module: str
                         ) -> Optional[List[Tuple[int, int]]]:
        """get_edges restricted to one module's slot space, with
        module-local slot numbers (the reference's per-module edge
        lists, dynamorio_instrumentation.c:1577-1606)."""
        return module_slice_edges(self.get_edges(),
                                  list(self.program.module_names),
                                  module, MAP_SIZE)

    # -- stateful session surface (showmap / corpus / telemetry) --------

    def state_signature(self, buf: bytes):
        """The state x edge signature of ONE framed input as sorted
        ``[state, slot]`` pairs — PURE (no virgin-map fold; a side
        execution through the session scan).  The corpus sidecar and
        picker/showmap wire format.  None when the tier is off."""
        if self.stateful_spec is None:
            return None
        from ..stateful.session import run_single_session
        _res, pairs = run_single_session(self.program, buf,
                                         self.stateful_spec)
        return pairs

    def state_coverage_stats(self):
        """(touched state x edge pairs, distinct states seen) from
        the live virgin map — the kb-stats gauges.  None when the
        tier is off.  Forces a (tiny) device sync."""
        if self.stateful_spec is None:
            return None
        from ..stateful.coverage import state_coverage_stats
        return state_coverage_stats(np.asarray(self.virgin_state),
                                    self.stateful_spec.n_states)

    def get_state_pairs(self):
        """Last exec's (state, slot, count) records (requires
        {"edges": 1}, like get_edges) — the showmap/picker "state"
        section source."""
        if self._last_se is None:
            return None
        se = self._last_se[0, :, :-1]
        slots = np.asarray(self.program.edge_slot)
        agg: dict = {}
        for s, e in zip(*np.nonzero(se)):
            key = (int(s), int(slots[e]))
            agg[key] = agg.get(key, 0) + int(se[s, e])
        return [(s, slot, c) for (s, slot), c in sorted(agg.items())]

    # -- state / merge --------------------------------------------------

    def get_state(self) -> str:
        d = {
            "instrumentation": self.name,
            "target": self.program.name,
            "total_execs": self.total_execs,
            "virgin_bits": encode_array(np.asarray(self.virgin_bits)),
            "virgin_crash": encode_array(np.asarray(self.virgin_crash)),
            "virgin_tmout": encode_array(np.asarray(self.virgin_tmout)),
        }
        if self.stateful_spec is not None:
            d["virgin_state"] = encode_array(
                np.asarray(self.virgin_state))
            d["stateful"] = {"m_max": self.stateful_spec.m_max,
                             "n_states": self.stateful_spec.n_states,
                             "state_reg": self.stateful_spec.state_reg}
        if len(self.program.modules) > 1:
            d["modules"] = list(self.program.module_names)
        return json.dumps(d)

    def _check_state_layout(self, d: Dict[str, Any], arr) -> None:
        """States only interoperate across identical module layouts:
        a mismatched map size would be silently clamped/dropped by the
        jitted gathers, corrupting novelty verdicts."""
        if arr.shape != (self.program.map_size,):
            raise ValueError(
                f"state map is {arr.shape[0]} bytes but "
                f"{self.program.name!r} has {self.program.map_size} "
                f"({len(self.program.modules)} module(s))")
        mods = d.get("modules")
        if mods is not None and tuple(mods) != self.program.module_names:
            raise ValueError(
                f"state modules {mods} != {self.program.module_names}")

    def _check_state_state_layout(self, d: Dict[str, Any],
                                  arr) -> None:
        """virgin_state interop requires the same (n_states, E+1)
        shape AND the same session spec — two same-SIZED maps built
        under different state registers (or message capacities)
        encode different state machines, and AND-folding them would
        mark genuinely-novel (state, edge) rows as seen (the exact
        aliasing _check_state_layout prevents for modules)."""
        from ..stateful.coverage import state_map_size
        want = state_map_size(self.stateful_spec.n_states,
                              self.program.n_edges)
        if arr.shape != (want,):
            raise ValueError(
                f"state-map is {arr.shape[0]} bytes but "
                f"{self.program.name!r} with n_states="
                f"{self.stateful_spec.n_states} has {want}")
        meta = d.get("stateful")
        if meta is not None:
            mine = {"m_max": self.stateful_spec.m_max,
                    "n_states": self.stateful_spec.n_states,
                    "state_reg": self.stateful_spec.state_reg}
            theirs = {k: meta.get(k) for k in mine}
            if theirs != mine:
                raise ValueError(
                    f"state spec mismatch: state is from "
                    f"{theirs}, this instance runs {mine} — "
                    f"same-sized maps under different specs encode "
                    f"different state machines")

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("instrumentation") not in (None, self.name):
            raise ValueError(
                f"state is for {d.get('instrumentation')!r}, not "
                f"{self.name!r}")
        self.total_execs = int(d.get("total_execs", 0))
        for key in ("virgin_bits", "virgin_crash", "virgin_tmout"):
            if key in d:
                arr = decode_array(d[key])
                self._check_state_layout(d, arr)
                setattr(self, key, jnp.asarray(arr))
        if self.stateful_spec is not None and "virgin_state" in d:
            arr = decode_array(d["virgin_state"])
            self._check_state_state_layout(d, arr)
            self.virgin_state = jnp.asarray(arr)

    def merge(self, other_state: str) -> None:
        d = json.loads(other_state)
        for key in ("virgin_bits", "virgin_crash", "virgin_tmout"):
            if key in d:
                mine = getattr(self, key)
                arr = decode_array(d[key])
                self._check_state_layout(d, arr)
                setattr(self, key, merge_virgin(mine, jnp.asarray(arr)))
        if self.stateful_spec is not None and "virgin_state" in d:
            arr = decode_array(d["virgin_state"])
            self._check_state_state_layout(d, arr)
            self.virgin_state = merge_virgin(self.virgin_state,
                                             jnp.asarray(arr))
        self.total_execs += int(d.get("total_execs", 0))

    def coverage_bytes(self) -> int:
        """Touched virgin bytes (status reporting)."""
        return int(count_non_255_bytes(self.virgin_bits))
