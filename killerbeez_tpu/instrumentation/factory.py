"""Instrumentation factory (reference instrumentation_factory.c:25-104)."""

from __future__ import annotations

from typing import Dict, Optional, Type

from .base import Instrumentation

_REGISTRY: Dict[str, Type[Instrumentation]] = {}


def register_instrumentation(cls: Type[Instrumentation]
                             ) -> Type[Instrumentation]:
    _REGISTRY[cls.name] = cls
    return cls


def instrumentation_names() -> list[str]:
    return sorted(_REGISTRY)


def instrumentation_factory(name: str, options: Optional[str] = None
                            ) -> Instrumentation:
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown instrumentation {name!r}; known: "
            f"{', '.join(instrumentation_names())}")
    return _REGISTRY[name](options)


def instrumentation_help() -> str:
    return "\n".join(_REGISTRY[n].help() for n in instrumentation_names())
