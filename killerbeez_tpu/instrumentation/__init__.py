"""Instrumentation layer: runs the target and classifies outcomes.

Vtable parity with the reference (instrumentation/instrumentation.h:
40-63): create/cleanup/merge/get_state/set_state/enable/is_new_path/
get_fuzz_result + optional get_module_info/get_edges/is_process_done —
plus the TPU-native ``run_batch`` fast path.
"""

from .base import BatchResult, Instrumentation
from .factory import (
    instrumentation_factory, instrumentation_help, instrumentation_names,
    register_instrumentation,
)
from .afl import AflInstrumentation
from .debug import DebugInstrumentation
from .ipt import IptInstrumentation
from .jit_harness import JitHarnessInstrumentation
from .return_code import ReturnCodeInstrumentation

__all__ = [
    "Instrumentation", "BatchResult",
    "instrumentation_factory", "instrumentation_help",
    "instrumentation_names", "register_instrumentation",
    "AflInstrumentation", "DebugInstrumentation", "IptInstrumentation",
    "JitHarnessInstrumentation", "ReturnCodeInstrumentation",
]
