"""ipt — trace-hash novelty instrumentation (hash-set coverage).

TPU-native re-architecture of the reference's Intel-PT path
(SURVEY §2.3/§3.3, reference linux_ipt_instrumentation.c): the
reference needs a custom fast PT packet parser because the hardware
emits compressed TIP/TNT packets, then reduces each exec to a pair of
XXH64 hashes — (control-flow targets, taken/not-taken stream) — and
calls an exec novel when the pair is new in a hash set
(linux_ipt_instrumentation.c:212-426).

On TPU the KBVM already yields the fully *decoded* trace (the per-lane
edge stream) — no packet parsing exists to accelerate. What survives
the port is the novelty semantics: per exec, two 32-bit lane hashes of
the trace stream (murmur3 under vmap; TPU has no native u64 so the
XXH64 pair becomes a murmur3 pair with distinct seeds), novelty =
unseen (tip, tnt) pair in a host-side hash set. The set replaces the
reference's uthash table; ``merge`` is set union (the reference's
merger fold), and address filters become block-id ranges.

Like the reference's IPT mode this is *hash* coverage: finer than the
64KB bitmap (full path sensitivity, no bucket collisions) but with no
partial-credit gradient — pair it with jit_harness when you want
AFL-style bucketed novelty instead.
"""

from __future__ import annotations

import json
import os
import shlex
from functools import partial
from typing import Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_RUNNING
from ..models import targets as targets_mod
from ..models.vm import _run_batch_impl
from ..ops.hashing import murmur3_32
from ..utils.logging import WARNING_MSG
from .base import BatchResult, Instrumentation
from .factory import register_instrumentation

TIP_SEED = np.uint32(0x1994C9A5)  # control-flow-target stream hash
TNT_SEED = np.uint32(0x7E57ED01)  # branch-outcome stream hash


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges"))
def _ipt_step_fast(instrs, edge_table, inputs, lengths, mem_size,
                   max_steps, n_edges):
    """Unfiltered (the default) trace-hash step WITHOUT materializing
    edge streams: the VM's in-loop path hash is the order-sensitive
    component (the reference's TIP stream role) and a positional hash
    of the static-edge counts is the multiset component (the TNT
    role) — together a 64-bit path identity, matching the reference's
    XXH64 pair width (linux_ipt_instrumentation.c:419-425)."""
    from ..ops.sparse_coverage import stream_hash
    res = _run_batch_impl(instrs, edge_table, inputs, lengths, mem_size,
                          max_steps, n_edges, False)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                         res.status)
    tip = res.path_hash
    tnt = stream_hash(res.counts.astype(jnp.uint32))
    return statuses, res.exit_code, tip, tnt


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges"))
def _ipt_step(instrs, edge_table, inputs, lengths, filt_lo, filt_hi,
              mem_size, max_steps, n_edges):
    """VM exec + per-lane (tip, tnt) trace hashes, one XLA program.

    Runs the engine in stream-recording mode: the hash pair is over
    the ORDERED, filter-windowed edge stream, which the static count
    table can't express."""
    res = _run_batch_impl(instrs, edge_table, inputs, lengths, mem_size,
                          max_steps, n_edges, True)
    statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                         res.status)
    ids = res.edge_ids  # int32[B, T], -1 padding
    # address filters (reference create_ipt_filter: only trace the
    # target/library ranges): ids outside every [lo, hi) window drop
    # to the padding value before hashing
    in_range = (ids[..., None] >= filt_lo) & (ids[..., None] < filt_hi)
    keep = in_range.any(axis=-1) & (ids >= 0)
    stream = jnp.where(keep, ids, -1).astype(jnp.uint32)
    tip = murmur3_32(stream, TIP_SEED)
    # the TNT analogue hashes the *transition* stream (first
    # difference): two paths through the same blocks in different
    # order separate here even if the multiset of targets collides
    trans = jnp.concatenate(
        [stream[:, :1], stream[:, 1:] ^ stream[:, :-1]], axis=1)
    tnt = murmur3_32(trans, TNT_SEED)
    return statuses, res.exit_code, tip, tnt


@register_instrumentation
class IptInstrumentation(Instrumentation):
    """Hash-set (path-sensitive) novelty over KBVM trace streams, or —
    with ``{"qemu_mode": 1}`` — over REAL host binaries' block-PC
    streams observed by the kb-trace engine in hash mode (the
    reference fuzzes uninstrumented binaries this way via Intel PT,
    linux_ipt_instrumentation.c:212-426; this host tier gets the same
    (tip, tnt)-pair novelty from ptrace block tracing instead of a PT
    PMU)."""
    name = "ipt"
    supports_batch = True
    device_backed = True
    OPTION_SCHEMA = {"target": str, "program_file": str,
                     "max_steps": int, "filters": list,
                     "qemu_mode": int, "qemu_path": str,
                     "timeout": float}
    OPTION_DESCS = {
        "target": "built-in KBVM target name",
        "program_file": "path to a .npz compiled KBVM program",
        "max_steps": "override the program's hang step budget",
        "filters": "[[lo, hi], ...] block-id ranges to trace "
                   "(default: everything; reference IPT address "
                   "filters; KBVM targets only)",
        "qemu_mode": "1 = hash coverage of an UNINSTRUMENTED host "
                     "binary: run it under kb-trace in hash mode "
                     "(KB_TRACE_HASH=1), novelty = unseen 128-bit "
                     "(tip, tnt) pair over the block-PC stream",
        "qemu_path": "tracer binary for qemu_mode (default "
                     "native/build/kb-trace)",
        "timeout": "qemu_mode: seconds before an exec counts as a "
                   "hang (default 2.0)",
    }
    DEFAULTS: dict = {"qemu_mode": 0, "timeout": 2.0}

    def __init__(self, options: Optional[str] = None):
        super().__init__(options)
        self._host_target = None
        self._host_target_key = None
        if self.options["qemu_mode"]:
            # host-binary tier: targets come from the driver's
            # cmd_line at enable/prepare_host time, like afl
            self.program = None
            self.device_backed = False  # instance override
            qemu = self.options.get("qemu_path")
            if not qemu:
                from ..native.build import build_native, kb_trace_path
                build_native()
                qemu = kb_trace_path()
                self.options["qemu_path"] = qemu
            if not os.path.exists(qemu):
                raise ValueError(
                    f"qemu_mode: tracer binary {qemu!r} not found "
                    "(the bundled default is native/build/kb-trace)")
            if self.options.get("filters"):
                raise ValueError(
                    "ipt filters are block-id ranges of KBVM programs; "
                    "host-binary (qemu_mode) hashing is whole-image")
        else:
            self.program = prog = targets_mod.load_program_from_options(
                self.options,
                'ipt needs {"target": name} or {"program_file": path} '
                'for KBVM targets, or {"qemu_mode": 1} to hash-cover '
                "a real host binary under the kb-trace engine")
            self._instrs = jnp.asarray(prog.instrs)
            self._edge_table = jnp.asarray(prog.edge_table)
        # no filters configured (the default) = whole-trace hashing,
        # which the engines compute in-loop — no stream materialized
        self._unfiltered = not self.options.get("filters")
        filters = self.options.get("filters") or [[0, (1 << 31) - 1]]
        filt = np.asarray(filters, dtype=np.int32)
        if filt.ndim != 2 or filt.shape[1] != 2:
            raise ValueError("filters must be [[lo, hi], ...]")
        self._filt_lo = jnp.asarray(filt[:, 0])
        self._filt_hi = jnp.asarray(filt[:, 1])
        self.hashes: Set[int] = set()
        self.crash_hashes: Set[int] = set()
        self.hang_hashes: Set[int] = set()
        self.total_execs = 0
        self._last_unique_crash = False
        self._last_unique_hang = False

    # -- host-binary tier (qemu_mode) -----------------------------------

    def _ensure_host_target(self, cmd_line: str, use_stdin: bool,
                            input_file: Optional[str]):
        from ..native.exec_backend import ExecTarget
        key = (cmd_line, use_stdin, input_file)
        if self._host_target is not None and \
                self._host_target_key == key:
            return self._host_target
        if self._host_target is not None:
            self._host_target.close()
        argv = [self.options["qemu_path"]] + shlex.split(cmd_line)
        self._host_target = ExecTarget(
            argv, use_stdin=use_stdin, input_file=input_file,
            use_forkserver=True, coverage=True,
            timeout=float(self.options["timeout"]),
            extra_env=["KB_TRACE_HASH=1"])  # hash mode: no re-runs,
        # so no KB_TRACE_BUDGET needed (every exec is a full trace)
        self._host_target_key = key
        return self._host_target

    def prepare_host(self, cmd_line: str, use_stdin: bool,
                     input_file: Optional[str] = None) -> None:
        self._ensure_host_target(cmd_line, use_stdin, input_file)

    @staticmethod
    def _host_pairs(bitmaps: np.ndarray) -> List[int]:
        """The tracer publishes the exec's (tip, tnt) u64 pair in the
        first 16 bytes of the SHM region (kb_trace.c hash mode);
        fold into one 128-bit set key."""
        words = bitmaps[:, :16].copy().view("<u8")
        return [(int(w[0]) << 64) | int(w[1]) for w in words]

    # -- set updates (shared by the KBVM and host tiers) ---------------

    def _update_sets(self, statuses: np.ndarray, pairs: List[int],
                     exit_codes: np.ndarray) -> BatchResult:
        n = len(pairs)
        self.total_execs += n
        new_paths = np.zeros(n, dtype=np.int32)
        uc = np.zeros(n, dtype=bool)
        uh = np.zeros(n, dtype=bool)
        # sequential membership+insert: in-batch duplicates count once
        # (exact single-exec-loop parity, like jit_harness "exact")
        for i, p in enumerate(pairs):
            if statuses[i] == FUZZ_ERROR:
                # a failed exec publishes a zeroed bitmap, so its
                # (tip, tnt) pair is 0 — not a path identity.  It
                # must not enter the hash sets: the first error in a
                # campaign used to count as a new path and record the
                # offending input as a finding.
                continue
            if p not in self.hashes:
                self.hashes.add(p)
                new_paths[i] = 1
            if statuses[i] == FUZZ_CRASH and p not in self.crash_hashes:
                self.crash_hashes.add(p)
                uc[i] = True
            elif statuses[i] == FUZZ_HANG and p not in self.hang_hashes:
                self.hang_hashes.add(p)
                uh[i] = True
        return BatchResult(statuses=statuses, new_paths=new_paths,
                           unique_crashes=uc, unique_hangs=uh,
                           exit_codes=np.asarray(exit_codes))

    # -- batched --------------------------------------------------------

    def run_batch(self, inputs, lengths,
                  pad_to: Optional[int] = None) -> BatchResult:
        if self.options["qemu_mode"]:
            return self._run_batch_host(inputs, lengths, pad_to)
        inputs = jnp.asarray(inputs, dtype=jnp.uint8)
        lengths = jnp.asarray(lengths, dtype=jnp.int32)
        if self._unfiltered:
            statuses, exit_codes, tip, tnt = _ipt_step_fast(
                self._instrs, self._edge_table, inputs, lengths,
                self.program.mem_size, self.program.max_steps,
                self.program.n_edges)
        else:
            statuses, exit_codes, tip, tnt = _ipt_step(
                self._instrs, self._edge_table,
                inputs, lengths, self._filt_lo, self._filt_hi,
                self.program.mem_size, self.program.max_steps,
                self.program.n_edges)
        statuses = np.asarray(statuses)
        tip = np.asarray(tip, dtype=np.uint64)
        tnt = np.asarray(tnt, dtype=np.uint64)
        pairs = [int(p) for p in (tip << np.uint64(32)) | tnt]
        return self._update_sets(statuses, pairs,
                                 np.asarray(exit_codes))

    def _run_batch_host(self, inputs, lengths,
                        pad_to: Optional[int] = None) -> BatchResult:
        from ..native.exec_backend import classify_batch
        if self._host_target is None:
            raise RuntimeError(
                "ipt qemu_mode: prepare_host() not called (the driver "
                "binds the target command first)")
        inputs = np.asarray(inputs)
        lengths = np.asarray(lengths)
        statuses_raw, bitmaps = self._host_target.run_batch(inputs,
                                                            lengths)
        pairs = self._host_pairs(bitmaps)
        verdicts, exit_codes = classify_batch(statuses_raw)
        res = self._update_sets(verdicts, pairs, exit_codes)
        if pad_to is not None:
            from .base import pad_batch_result
            res = pad_batch_result(res, pad_to)
        return res

    # -- single-exec shim ----------------------------------------------

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        if self.options["qemu_mode"]:
            if cmd_line is None:
                raise ValueError(
                    "ipt qemu_mode needs a cmd_line (use a host "
                    "driver: file/stdin)")
            from ..native.exec_backend import classify
            use_stdin = input_bytes is not None
            t = self._ensure_host_target(cmd_line, use_stdin, None)
            t.clear_trace()
            status_raw = t.run(input_bytes or b"")
            verdict, _ = classify(status_raw)
            pair = self._host_pairs(
                t.trace_bits().reshape(1, -1))[0]
            res = self._update_sets(
                np.array([verdict], dtype=np.int32), [pair],
                np.array([0], dtype=np.int32))
        else:
            if input_bytes is None:
                raise ValueError("ipt needs input bytes")
            L = max(((len(input_bytes) + 7) // 8) * 8, 8)
            buf = np.zeros((1, L), dtype=np.uint8)
            buf[0, :len(input_bytes)] = np.frombuffer(input_bytes,
                                                      dtype=np.uint8)
            res = self.run_batch(buf, np.array([len(input_bytes)],
                                               dtype=np.int32))
        self.last_status = int(res.statuses[0])
        self.last_new_path = int(res.new_paths[0])
        self._last_unique_crash = bool(res.unique_crashes[0])
        self._last_unique_hang = bool(res.unique_hangs[0])

    def last_unique_crash(self) -> bool:
        return self._last_unique_crash

    def last_unique_hang(self) -> bool:
        return self._last_unique_hang

    def get_module_info(self) -> List[str]:
        return [self.program.name if self.program is not None
                else "target"]

    def cleanup(self) -> None:
        if self._host_target is not None:
            self._host_target.close()
            self._host_target = None

    # -- state / merge (reference ipt get_state: hash list) -------------

    @staticmethod
    def _dump(hs: Set[int]) -> List[str]:
        return [f"{h:016x}" for h in sorted(hs)]

    @staticmethod
    def _load(items: List[str]) -> Set[int]:
        return {int(h, 16) for h in items}

    @property
    def _hash_scheme(self) -> str:
        """Hash-space identity: fast (in-loop path hash + counts
        hash), filtered (murmur over the windowed stream), and
        host-block (kb-trace 128-bit pairs over real binaries) are
        DIFFERENT spaces — states only union within one."""
        if self.options["qemu_mode"]:
            return "host-block"
        return "path+counts" if self._unfiltered else "stream"

    def _check_scheme(self, d: Dict) -> bool:
        """True when the state's hash space matches ours.  A mismatch
        (including pre-0.2 states that carry no ``hash_scheme`` key)
        is not an error: hashes from a different space are safely
        discardable, so callers degrade to a fresh set with a warning
        rather than breaking cross-version manager flows."""
        theirs = d.get("hash_scheme", "stream")
        if theirs == self._hash_scheme:
            return True
        WARNING_MSG(
            "ipt state hashes are %r but this instance uses %r "
            "(filters change the hash space) — discarding the foreign "
            "hash sets and keeping counters", theirs, self._hash_scheme)
        return False

    def get_state(self) -> str:
        return json.dumps({
            "instrumentation": self.name,
            "target": (self.program.name if self.program is not None
                       else "host"),
            "hash_scheme": self._hash_scheme,
            "total_execs": self.total_execs,
            "hashes": self._dump(self.hashes),
            "crash_hashes": self._dump(self.crash_hashes),
            "hang_hashes": self._dump(self.hang_hashes),
        })

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("instrumentation") not in (None, self.name):
            raise ValueError(
                f"state is for {d.get('instrumentation')!r}, not "
                f"{self.name!r}")
        same_space = self._check_scheme(d)
        self.total_execs = int(d.get("total_execs", 0))
        self.hashes = self._load(d.get("hashes", [])) if same_space \
            else set()
        self.crash_hashes = self._load(d.get("crash_hashes", [])) \
            if same_space else set()
        self.hang_hashes = self._load(d.get("hang_hashes", [])) \
            if same_space else set()

    def merge(self, other_state: str) -> None:
        d = json.loads(other_state)
        if not self._check_scheme(d):
            return
        self.hashes |= self._load(d.get("hashes", []))
        self.crash_hashes |= self._load(d.get("crash_hashes", []))
        self.hang_hashes |= self._load(d.get("hang_hashes", []))
        self.total_execs += int(d.get("total_execs", 0))

    def coverage_bytes(self) -> int:
        return len(self.hashes)
