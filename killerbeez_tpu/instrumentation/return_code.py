"""return_code — dumb host-exec instrumentation.

Parity with the reference's return_code instrumentation
(return_code_instrumentation.c): run the target process, verdict from
the exit status only (signal -> crash, timeout -> hang), no coverage
(``is_new_path`` always 0, ``merge`` unsupported). The process-control
path is host-side by nature; the batched variant simply loops (the
native C++ batch executor accelerates this later).
"""

from __future__ import annotations

import json
import shlex
import signal
import subprocess
from typing import Optional

import numpy as np

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from .base import BatchResult, Instrumentation
from .factory import register_instrumentation


@register_instrumentation
class ReturnCodeInstrumentation(Instrumentation):
    """Exit-status-only verdicts for real host binaries."""
    name = "return_code"
    supports_batch = False
    OPTION_SCHEMA = {"timeout": float}
    OPTION_DESCS = {"timeout": "seconds before an exec counts as a hang "
                               "(default 2.0)"}
    DEFAULTS = {"timeout": 2.0}

    def __init__(self, options: Optional[str] = None):
        super().__init__(options)
        self.last_exit_code = 0
        self.total_execs = 0

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        if not cmd_line:
            raise ValueError("return_code needs a command line from the "
                             "driver")
        try:
            proc = subprocess.run(
                shlex.split(cmd_line),
                input=input_bytes,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=float(self.options["timeout"]))
            rc = proc.returncode
            if rc < 0:  # killed by signal -> crash (WIFSIGNALED)
                self.last_status = FUZZ_CRASH
            else:
                self.last_status = FUZZ_NONE
            self.last_exit_code = rc
        except subprocess.TimeoutExpired:
            self.last_status = FUZZ_HANG
            self.last_exit_code = -int(signal.SIGKILL)
        except OSError:
            self.last_status = FUZZ_ERROR
            self.last_exit_code = -1
        self.total_execs += 1
        self.last_new_path = 0  # dumb fuzzing: no coverage signal

    # -- async exec (network drivers) -----------------------------------

    def start_process(self, cmd_line: str) -> None:
        self._proc = subprocess.Popen(
            shlex.split(cmd_line),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def is_process_done(self) -> bool:
        proc = getattr(self, "_proc", None)
        return proc is None or proc.poll() is not None

    def wait_done(self, timeout: float) -> int:
        proc = self._proc
        try:
            rc = proc.wait(timeout=timeout)
            self.last_status = FUZZ_CRASH if rc < 0 else FUZZ_NONE
            self.last_exit_code = rc
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            self.last_status = FUZZ_HANG
            self.last_exit_code = -int(signal.SIGKILL)
        self._proc = None
        self.total_execs += 1
        self.last_new_path = 0
        return self.last_status

    def abort_process(self) -> int:
        proc = getattr(self, "_proc", None)
        if proc is not None:
            proc.kill()
            proc.wait()
            self._proc = None
        self.last_status = FUZZ_ERROR
        self.last_exit_code = -1
        self.last_new_path = 0
        return FUZZ_ERROR

    # merge: the reference returns NULL state and no merge for
    # return_code; keep get_state minimal for -isd parity
    def get_state(self) -> str:
        return json.dumps({"instrumentation": self.name,
                           "total_execs": self.total_execs})

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        self.total_execs = int(d.get("total_execs", 0))
