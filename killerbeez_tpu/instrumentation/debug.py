"""debug — debugger-based crash triage for host binaries.

The reference's debug instrumentation is Windows-only: a debug thread
waits on WaitForDebugEvent and maps EXCEPTION events to FUZZ_CRASH,
EXIT_PROCESS to FUZZ_NONE (SURVEY §2.3, reference
debug_instrumentation.c:19-88). The Linux equivalent here runs the
target under ptrace (native/kb_exec.cpp kb_target_run_debug): a fatal
signal stop yields the *crash details* — signal, si_code, faulting
address and PC — before the signal is delivered, so findings carry
triage data (NULL deref vs wild write vs abort) instead of just an
exit status. No coverage: ``is_new_path`` is always 0, like the
reference (crash dedup happens on (signal, pc) instead).
"""

from __future__ import annotations

import json
import shlex
import signal as signal_mod
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from ..native.exec_backend import ExecTarget, classify
from .base import Instrumentation
from .factory import register_instrumentation


@register_instrumentation
class DebugInstrumentation(Instrumentation):
    """ptrace-backed crash detail harvesting (no coverage)."""
    name = "debug"
    supports_batch = False
    device_backed = False
    OPTION_SCHEMA = {"timeout": float, "mem_limit": int}
    OPTION_DESCS = {
        "timeout": "seconds before an exec counts as a hang "
                   "(default 2.0)",
        "mem_limit": "child address-space limit in MB (0 = none)",
    }
    DEFAULTS = {"timeout": 2.0, "mem_limit": 0}

    def __init__(self, options: Optional[str] = None):
        super().__init__(options)
        self._target: Optional[ExecTarget] = None
        self._target_key: Optional[Tuple] = None
        self.total_execs = 0
        self.last_crash_info: Dict[str, Any] = {}
        # (signal, pc) pairs seen — the debugger-mode uniqueness notion
        self.crash_sites: Set[Tuple[int, int]] = set()
        self._last_unique_crash = False

    def _ensure_target(self, cmd_line: str, use_stdin: bool
                       ) -> ExecTarget:
        key = (cmd_line, use_stdin)
        if self._target is not None and self._target_key == key:
            return self._target
        if self._target is not None:
            self._target.close()
        self._target = ExecTarget(
            shlex.split(cmd_line), use_stdin=use_stdin,
            use_forkserver=False,  # the debugger IS the supervisor
            mem_limit_mb=int(self.options["mem_limit"]),
            coverage=False,
            timeout=float(self.options["timeout"]))
        self._target_key = key
        return self._target

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        if cmd_line is None:
            raise ValueError("debug instrumentation needs a cmd_line "
                             "(use a host driver: file/stdin)")
        t = self._ensure_target(cmd_line, input_bytes is not None)
        status, info = t.run_debug(input_bytes or b"")
        verdict, _ = classify(status)
        self.total_execs += 1
        self.last_status = verdict
        self.last_new_path = 0  # no coverage, like the reference
        self.last_crash_info = info if verdict == FUZZ_CRASH else {}
        self._last_unique_crash = False
        if verdict == FUZZ_CRASH:
            site = (info.get("signal", 0), info.get("pc", 0))
            if site not in self.crash_sites:
                self.crash_sites.add(site)
                self._last_unique_crash = True

    def last_unique_crash(self) -> bool:
        return self._last_unique_crash

    def crash_description(self) -> str:
        """Human-readable triage line for the last crash."""
        if not self.last_crash_info:
            return "no crash"
        info = self.last_crash_info
        try:
            signame = signal_mod.Signals(info["signal"]).name
        except ValueError:
            signame = f"signal {info['signal']}"
        return (f"{signame} at pc=0x{info['pc']:x} "
                f"fault_addr=0x{info['fault_addr']:x} "
                f"si_code={info['si_code']}")

    # -- state ----------------------------------------------------------

    def get_state(self) -> str:
        return json.dumps({
            "instrumentation": self.name,
            "total_execs": self.total_execs,
            "crash_sites": sorted(
                [s, p] for s, p in self.crash_sites),
        })

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("instrumentation") not in (None, self.name):
            raise ValueError(
                f"state is for {d.get('instrumentation')!r}, not "
                f"{self.name!r}")
        self.total_execs = int(d.get("total_execs", 0))
        self.crash_sites = {(int(s), int(p))
                            for s, p in d.get("crash_sites", [])}

    def merge(self, other_state: str) -> None:
        d = json.loads(other_state)
        self.crash_sites |= {(int(s), int(p))
                             for s, p in d.get("crash_sites", [])}
        self.total_execs += int(d.get("total_execs", 0))

    def cleanup(self) -> None:
        if self._target is not None:
            self._target.close()
            self._target = None
