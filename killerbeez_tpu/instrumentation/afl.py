"""afl — forkserver + SHM-bitmap instrumentation for real host
binaries (the reference's AFL-style path, SURVEY §2.3: reference
afl_instrumentation.c — SysV SHM 64KB map, three virgin maps
virgin_bits/tmout/crash, has_new_bits novelty, simplify_trace for
crash/hang dedup, forkserver options; re-architected here as a native
C++ exec backend (native/kb_exec.cpp) that collects per-exec bitmaps
and a device-side triage that scans the whole batch's maps in one XLA
program).

Targets are built with the kb-cc wrapper (compiled-in runtime,
native/kb_rt.c) or run with the LD_PRELOAD forkserver; the wire
protocol is the reference's (fds 198/199, __AFL_SHM_ID).

Options (reference afl_instrumentation.c:322-337 parity):
  use_fork_server, persistence_max_cnt, deferred_startup, qemu_mode,
  qemu_path, timeout, mem_limit, preload_forkserver, device_triage.
"""

from __future__ import annotations

import json
import os
import shlex
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE, MAP_SIZE
from ..native.exec_backend import (
    ExecPool, ExecTarget, classify, classify_batch,
)
from ..ops.coverage import (
    COUNT_CLASS_LOOKUP, classify_counts, count_non_255_bytes,
    merge_virgin, simplify_trace,
)
from ..utils.logging import WARNING_MSG
from ..utils.serialization import decode_array, encode_array
from .base import BatchResult, Instrumentation, module_slice_edges
from .factory import register_instrumentation
from .jit_harness import _triage_exact


@partial(jax.jit, donate_argnames=("vb", "vc", "vh"))
def _triage_host_bitmaps(bitmaps, statuses, vb, vc, vh):
    """Device triage of host-collected raw bitmaps: classify ->
    sequential-parity novelty scan vs the three virgin maps (exact
    single-exec-loop semantics; host exec dominates the step time, so
    parity costs nothing here)."""
    cls = classify_counts(bitmaps)
    simp = simplify_trace(bitmaps)
    return _triage_exact(vb, vc, vh, cls, simp, statuses)


def _np_classify(trace: np.ndarray) -> np.ndarray:
    return COUNT_CLASS_LOOKUP[trace]


def _np_has_new_bits(virgin: np.ndarray, trace: np.ndarray
                     ) -> Tuple[int, np.ndarray]:
    inter = trace & virgin
    if not inter.any():
        return 0, virgin
    ret = 2 if bool(((trace != 0) & (virgin == 0xFF)).any()) else 1
    return ret, virgin & ~trace


@register_instrumentation
class AflInstrumentation(Instrumentation):
    """Forkserver + 64KB edge bitmap for kb-cc-built host targets."""
    name = "afl"
    supports_batch = True
    device_backed = False
    OPTION_SCHEMA = {
        "use_fork_server": int, "persistence_max_cnt": int,
        "deferred_startup": int, "qemu_mode": int, "qemu_path": str,
        "timeout": float, "mem_limit": int, "preload_forkserver": int,
        "device_triage": int, "ignore_bytes_file": str, "edges": int,
        "workers": int, "modules": int,
    }
    OPTION_DESCS = {
        "use_fork_server": "1 = fork per exec via the forkserver "
                           "(default), 0 = fork+execve per exec",
        "persistence_max_cnt": "N>0: persistent mode, N inputs per "
                               "process (SIGSTOP/SIGCONT loop)",
        "deferred_startup": "1 = target calls __kb_manual_init() "
                            "itself (skip the pre-main forkserver)",
        "qemu_mode": "1 = binary-only target: run it under the "
                     "coverage tracer given by qemu_path (default: "
                     "the bundled kb-trace ptrace tracer; any "
                     "__AFL_SHM_ID-honoring emulator works — proven "
                     "by corpus/qemu_stub.c, an external stub built "
                     "from the documented wire contract alone, "
                     "exercised by test_qemu_path_external_emulator)",
        "qemu_path": "emulator/tracer binary for qemu_mode (default "
                     "native/build/kb-trace)",
        "timeout": "seconds before an exec counts as a hang "
                   "(default 2.0)",
        "mem_limit": "child address-space limit in MB (0 = none)",
        "preload_forkserver": "1 = LD_PRELOAD the forkserver into an "
                              "uninstrumented target",
        "device_triage": "1 = batched novelty scan on the TPU, 0 = "
                         "numpy on host (the default: host triage of "
                         "a 64KB map is ~0.26ms/exec; shipping maps "
                         "to a REMOTE device measured 20x slower — "
                         "profiling/profile_host.py — enable only "
                         "with a locally-attached accelerator and "
                         "large batches)",
        "ignore_bytes_file": "picker-produced JSON mask of "
                             "nondeterministic bitmap bytes to exclude "
                             "from novelty",
        "edges": "1 = keep the last exec's nonzero bitmap slots for "
                 "get_edges() (tracer mode)",
        "workers": "N>1: shard batches over N parallel forkserver "
                   "instances (stdin delivery only; the reference's "
                   "multi-instance fuzzer_id scaling in one process)",
        "modules": "1 = per-module coverage: each kb-cc-built object "
                   "(main binary, shared libraries) claims its own map "
                   "partition + virgin state (reference per-module "
                   "maps, dynamorio_instrumentation.h:27-41)",
    }
    DEFAULTS = {"use_fork_server": 1, "persistence_max_cnt": 0,
                "deferred_startup": 0, "qemu_mode": 0, "timeout": 2.0,
                "mem_limit": 0, "preload_forkserver": 0,
                "device_triage": 0, "edges": 0, "workers": 1,
                "modules": 0}

    def __init__(self, options: Optional[str] = None):
        super().__init__(options)
        if self.options["qemu_mode"]:
            qemu = self.options.get("qemu_path")
            if not qemu:
                # bundled default: the ptrace single-step tracer
                # (built with the other native artifacts on demand)
                from ..native.build import build_native, kb_trace_path
                build_native()
                qemu = kb_trace_path()
                self.options["qemu_path"] = qemu
            if not os.path.exists(qemu):
                raise ValueError(
                    f"qemu_mode: tracer binary {qemu!r} not found "
                    "(qemu_path must point at an __AFL_SHM_ID-honoring "
                    "emulator; the bundled default is "
                    "native/build/kb-trace)")
        self.virgin_bits = np.full(MAP_SIZE, 0xFF, dtype=np.uint8)
        self.virgin_crash = np.full(MAP_SIZE, 0xFF, dtype=np.uint8)
        self.virgin_tmout = np.full(MAP_SIZE, 0xFF, dtype=np.uint8)
        self.total_execs = 0
        self._target: Optional[ExecTarget] = None
        self._target_key: Optional[Tuple] = None
        self._last_unique_crash = False
        self._last_unique_hang = False
        self._last_trace: Optional[np.ndarray] = None
        self._ignore: Optional[np.ndarray] = None
        if self.options.get("ignore_bytes_file"):
            with open(self.options["ignore_bytes_file"]) as f:
                d = json.load(f)
            self._ignore = decode_array(d["ignore_bytes"]) != 0
            if self._ignore.shape != (MAP_SIZE,):
                raise ValueError("ignore_bytes mask must cover the "
                                 f"{MAP_SIZE}-byte map")

    def _mask_ignored(self, trace: np.ndarray) -> np.ndarray:
        """Zero out picker-flagged nondeterministic bytes before
        novelty (reference has_new_bits_with_ignore semantics,
        dynamorio_instrumentation.c:197)."""
        if self._ignore is None:
            return trace
        return np.where(self._ignore, 0, trace)

    # -- target lifecycle ----------------------------------------------

    def _build_argv(self, cmd_line: str) -> List[str]:
        argv = shlex.split(cmd_line)
        if self.options["qemu_mode"]:
            argv = [self.options["qemu_path"]] + argv
        return argv

    def _ensure_target(self, cmd_line: str, use_stdin: bool,
                       input_file: Optional[str]) -> ExecTarget:
        key = (cmd_line, use_stdin, input_file)
        if self._target is not None and self._target_key == key:
            return self._target
        if self._target is not None:
            self._target.close()
        kwargs = dict(
            use_stdin=use_stdin,
            input_file=input_file,
            use_forkserver=bool(self.options["use_fork_server"]),
            use_preload_forkserver=bool(
                self.options["preload_forkserver"]),
            persistent=int(self.options["persistence_max_cnt"]),
            deferred=bool(self.options["deferred_startup"]),
            mem_limit_mb=int(self.options["mem_limit"]),
            coverage=True,
            timeout=float(self.options["timeout"]))
        extra_env = []
        if self.options["modules"]:
            # targets read KB_MODULES at constructor time; delivered
            # as per-target child env, not the fuzzer's own environ
            extra_env.append("KB_MODULES=1")
        if self.options["qemu_mode"]:
            # kb-trace's UnTracer full-map re-run must finish inside
            # the exec's status window or the exec is misreported as
            # a hang: pass the FULL per-exec timeout — the tracer
            # arms its guard with what is LEFT of it after the fast
            # exec (max(min, timeout - elapsed); a fixed fraction
            # starved slow targets whose normal runtime approaches
            # the timeout — kb_trace.c kb_rerun_budget)
            extra_env.append(
                f"KB_TRACE_BUDGET={float(self.options['timeout'])}")
        if extra_env:
            kwargs["extra_env"] = extra_env
        workers = int(self.options["workers"])
        argv = self._build_argv(cmd_line)
        # stdin workers mint private temp files; file-delivery workers
        # derive private @@ paths from the driver's (reference
        # per-instance scaling, dynamorio_instrumentation.c:418-431).
        # A file path the argv doesn't carry as a re-pointable token
        # (whole token or --flag=<path>; no @@, or embedded
        # mid-argument) can't be privatized per worker — those
        # targets keep the old single-instance behavior.
        from ..native.exec_backend import pool_token_matches
        poolable = (input_file is None and use_stdin) or \
            (input_file is not None and
             any(pool_token_matches(a, input_file) for a in argv))
        if workers > 1 and poolable:
            self._target = ExecPool(argv, workers, **kwargs)
        else:
            if workers > 1:
                WARNING_MSG(
                    "afl: workers=%d requested but the input file is "
                    "not a re-pointable argv token (no @@, or embedded "
                    "mid-argument) — running 1 instance", workers)
            self._target = ExecTarget(argv, **kwargs)
        self._target_key = key
        return self._target

    def prepare_host(self, cmd_line: str, use_stdin: bool,
                     input_file: Optional[str] = None) -> None:
        self._ensure_target(cmd_line, use_stdin, input_file)

    # -- single-exec ----------------------------------------------------

    def _finish_exec(self, verdict: int) -> None:
        """Harvest the SHM bitmap and update the three virgin maps
        (reference finish_fuzz_round semantics)."""
        trace = self._target.trace_bits().copy()
        self.total_execs += 1
        self._last_trace = trace
        masked = self._mask_ignored(trace)
        cls = _np_classify(masked)
        ret, self.virgin_bits = _np_has_new_bits(self.virgin_bits, cls)
        self._last_unique_crash = False
        self._last_unique_hang = False
        if verdict in (FUZZ_CRASH, FUZZ_HANG):
            simp = np.where(masked == 0, 1, 128).astype(np.uint8)
            if verdict == FUZZ_CRASH:
                cret, self.virgin_crash = _np_has_new_bits(
                    self.virgin_crash, simp)
                self._last_unique_crash = cret > 0
            else:
                hret, self.virgin_tmout = _np_has_new_bits(
                    self.virgin_tmout, simp)
                self._last_unique_hang = hret > 0
        self.last_status = verdict
        self.last_new_path = ret

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        if cmd_line is None:
            raise ValueError("afl instrumentation needs a cmd_line "
                             "(use a host driver: file/stdin/network)")
        use_stdin = input_bytes is not None
        # File-mode single-exec: the driver already wrote the test
        # file; the backend must not stage over it.
        t = self._ensure_target(cmd_line, use_stdin, None)
        t.clear_trace()
        status_raw = t.run(input_bytes or b"")
        verdict, _ = classify(status_raw)
        self._finish_exec(verdict)

    # -- async exec (network drivers) -----------------------------------

    def start_process(self, cmd_line: str) -> None:
        t = self._ensure_target(cmd_line, False, None)
        t.clear_trace()
        t.launch()

    def is_process_done(self) -> bool:
        return self._target is None or not self._target.alive()

    def wait_done(self, timeout: float) -> int:
        verdict, _ = classify(self._target.wait_done(timeout))
        self._finish_exec(verdict)
        return verdict

    def abort_process(self) -> int:
        if self._target is not None and not self.is_process_done():
            self._target.wait_done(0.0)  # kills + reaps immediately
        self._last_unique_crash = False
        self._last_unique_hang = False
        self.last_status = FUZZ_ERROR
        self.last_new_path = 0
        return FUZZ_ERROR

    def last_unique_crash(self) -> bool:
        return self._last_unique_crash

    def last_unique_hang(self) -> bool:
        return self._last_unique_hang

    # -- batched --------------------------------------------------------

    def run_batch(self, inputs: np.ndarray, lengths: np.ndarray,
                  pad_to: Optional[int] = None) -> BatchResult:
        if self._target is None:
            raise RuntimeError("afl: prepare_host() not called (the "
                               "driver binds the target command first)")
        statuses_raw, bitmaps = self._target.run_batch(inputs, lengths)
        real = len(statuses_raw)
        self.total_execs += real
        if bitmaps is not None and self._ignore is not None:
            bitmaps = np.where(self._ignore[None, :], 0, bitmaps)
        if pad_to is not None and pad_to > real:
            # pad only the RESULT arrays to the stable triage shape:
            # zero bitmaps are novelty no-ops and cost no target
            # executions.  Padded statuses carry a distinct sentinel
            # (-3 -> FUZZ_ERROR) so a caller that ever consumes lanes
            # beyond the real count fails LOUDLY (error-count spike)
            # instead of silently reading plausible exit-0 results.
            pad = pad_to - real
            statuses_raw = np.concatenate(
                [statuses_raw,
                 np.full(pad, -3, dtype=statuses_raw.dtype)])
            if bitmaps is not None:
                bitmaps = np.concatenate(
                    [bitmaps,
                     np.zeros((pad, bitmaps.shape[1]), dtype=np.uint8)])
        n = len(statuses_raw)
        verdicts, exit_codes = classify_batch(statuses_raw)

        if self.options["device_triage"]:
            new_paths, uc, uh, vb, vc, vh = _triage_host_bitmaps(
                jnp.asarray(bitmaps), jnp.asarray(verdicts),
                jnp.asarray(self.virgin_bits),
                jnp.asarray(self.virgin_crash),
                jnp.asarray(self.virgin_tmout))
            self.virgin_bits = np.asarray(vb)
            self.virgin_crash = np.asarray(vc)
            self.virgin_tmout = np.asarray(vh)
            new_paths, uc, uh = (np.asarray(new_paths), np.asarray(uc),
                                 np.asarray(uh))
        else:
            new_paths, uc, uh = self._np_triage_batch(bitmaps, verdicts)
        self._last_trace = bitmaps[real - 1] if real else None
        return BatchResult(statuses=verdicts, new_paths=new_paths,
                           unique_crashes=uc, unique_hangs=uh,
                           exit_codes=exit_codes)

    def _np_triage_batch(self, bitmaps: np.ndarray,
                         verdicts: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Host triage of a batch of raw 64KB maps, sequential-exact.

        The per-lane loop (classify + has_new_bits each exec, the
        "~256µs/exec, saturates ~3.9k execs/s" pole in
        docs/HOST_TIER.md) is replaced by a two-phase scan:

        phase 1 (vectorized word-skip, the C has_new_bits play): find
        the nonzero u64 words of every lane in one pass — fuzzing
        maps are ~98% zero — then classify and test ONLY those words
        against the BATCH-START virgin map.  Virgin maps only ever
        shrink, so a lane with no overlap now cannot become novel
        later in the batch: the gate is exact, not heuristic.

        phase 2 (sequential, candidates only): the ordinary
        has_new_bits fold, preserving single-exec-loop parity for
        in-batch duplicate novelty.  Steady state has ~no candidates,
        so the per-exec cost drops to one memory-bandwidth scan
        (measured on this host: ~256µs/exec full classify -> ~9µs,
        docs/HOST_TIER.md).
        """
        n = len(bitmaps) if bitmaps is not None else 0
        new_paths = np.zeros(n, dtype=np.int32)
        uc = np.zeros(n, dtype=bool)
        uh = np.zeros(n, dtype=bool)
        if n == 0:
            return new_paths, uc, uh
        # word-skip gate: lanes whose nonzero words overlap virgin
        words = np.ascontiguousarray(bitmaps).reshape(n, -1, 8)
        nzl, nzw = np.nonzero(words.view(np.uint64)[..., 0])
        if len(nzl):
            wb = words[nzl, nzw]                        # [K, 8] bytes
            cls = COUNT_CLASS_LOOKUP[wb]
            virg = self.virgin_bits.reshape(-1, 8)[nzw]  # [K, 8]
            hit_lanes = nzl[(cls & virg).any(axis=1)]
            cand = np.zeros(n, dtype=bool)
            cand[hit_lanes] = True
        else:
            cand = np.zeros(n, dtype=bool)

        for i in np.flatnonzero(cand):
            cls = _np_classify(bitmaps[i])
            new_paths[i], self.virgin_bits = _np_has_new_bits(
                self.virgin_bits, cls)
        for i in np.flatnonzero((verdicts == FUZZ_CRASH)
                                | (verdicts == FUZZ_HANG)):
            simp = np.where(bitmaps[i] == 0, 1, 128).astype(np.uint8)
            if verdicts[i] == FUZZ_CRASH:
                r, self.virgin_crash = _np_has_new_bits(
                    self.virgin_crash, simp)
                uc[i] = r > 0
            else:
                r, self.virgin_tmout = _np_has_new_bits(
                    self.virgin_tmout, simp)
                uh[i] = r > 0
        return new_paths, uc, uh

    # -- state / merge (reference afl_get_state/afl_set_state/merge) ---

    def get_state(self) -> str:
        return json.dumps({
            "instrumentation": self.name,
            "total_execs": self.total_execs,
            "virgin_bits": encode_array(self.virgin_bits),
            "virgin_crash": encode_array(self.virgin_crash),
            "virgin_tmout": encode_array(self.virgin_tmout),
        })

    def set_state(self, state: str) -> None:
        d = json.loads(state)
        if d.get("instrumentation") not in (None, self.name):
            raise ValueError(
                f"state is for {d.get('instrumentation')!r}, not "
                f"{self.name!r}")
        self.total_execs = int(d.get("total_execs", 0))
        for key in ("virgin_bits", "virgin_crash", "virgin_tmout"):
            if key in d:
                setattr(self, key, decode_array(d[key]))

    def merge(self, other_state: str) -> None:
        d = json.loads(other_state)
        for key in ("virgin_bits", "virgin_crash", "virgin_tmout"):
            if key in d:
                mine = getattr(self, key)
                theirs = decode_array(d[key])
                setattr(self, key, np.asarray(merge_virgin(mine, theirs)))
        self.total_execs += int(d.get("total_execs", 0))

    def coverage_bytes(self) -> int:
        return int(count_non_255_bytes(self.virgin_bits))

    def last_trace(self) -> Optional[np.ndarray]:
        """Raw (unmasked) 64KB bitmap of the last exec — picker input."""
        return self._last_trace

    def get_edges(self):
        """Nonzero bitmap slots of the last exec as (slot, hit_count)
        pairs; tracer consumes these (requires {"edges": 1})."""
        if not self.options.get("edges") or self._last_trace is None:
            return None
        idx = np.flatnonzero(self._last_trace)
        return [(int(i), int(self._last_trace[i])) for i in idx]

    def get_module_info(self) -> List[str]:
        """Module names. With {"modules": 1} these come from the SHM
        name table each kb_rt copy registered in (main binary + every
        kb-cc-built shared library); otherwise one anonymous module."""
        if self.options["modules"] and self._target is not None:
            names = self._target.module_table()
            if names:
                return names
        return ["target"]

    def _partition_size(self) -> int:
        """Module partition width: 8KB submaps under {"modules": 1}
        once the target has actually REGISTERED modules — a runtime
        that ignores KB_MODULES (old kb_rt, qemu) logs across the full
        map, so the fallback single "target" module must too."""
        from ..native.exec_backend import KB_MOD_SIZE
        if self.options["modules"] and self._target is not None \
                and self._target.module_table():
            return KB_MOD_SIZE
        return MAP_SIZE

    def get_module_edges(self, module: str):
        """get_edges restricted to one module's map partition, with
        partition-local slot numbers (requires {"modules": 1,
        "edges": 1})."""
        return module_slice_edges(self.get_edges(),
                                   self.get_module_info(), module,
                                   self._partition_size())

    def module_coverage_bytes(self) -> Dict[str, int]:
        """Touched virgin bytes per module partition."""
        ps = self._partition_size()
        out = {}
        for m, name in enumerate(self.get_module_info()):
            sl = self.virgin_bits[m * ps:(m + 1) * ps]
            out[name] = int((sl != 0xFF).sum())
        return out

    def module_map_ranges(self):
        ps = self._partition_size()
        return [(name, m * ps, (m + 1) * ps)
                for m, name in enumerate(self.get_module_info())]

    def cleanup(self) -> None:
        if self._target is not None:
            self._target.close()
            self._target = None
