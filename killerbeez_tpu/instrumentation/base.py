"""Instrumentation base class (reference instrumentation.h:40-63).

Two execution APIs:

  * single-exec (reference-shaped): ``enable(input)`` runs one input;
    ``get_fuzz_result()`` / ``is_new_path()`` report on it. Drivers
    built for host exec backends use this.
  * batched (TPU-native): ``run_batch(inputs, lengths)`` executes a
    whole candidate tensor and returns per-lane verdicts + novelty in
    one device round-trip. ``supports_batch`` advertises it.

State is a JSON string (get_state/set_state) and ``merge`` folds two
states' coverage together — the cross-node primitive the merger tool
and the ICI allreduce tier both build on.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from .. import FUZZ_NONE
from ..utils.options import format_help, parse_options


def pack_verdicts(statuses, new_paths, unique_crashes, unique_hangs):
    """One uint8 lane byte: status (3 bits) | new_paths (2) << 3 |
    unique_crash << 5 | unique_hang << 6 — THE wire layout between
    device steps and host triage (works on numpy and jax arrays).
    Change field widths here and in unpack_verdicts ONLY."""
    return (statuses.astype("uint8")
            | (new_paths.astype("uint8") << 3)
            | (unique_crashes.astype("uint8") << 5)
            | (unique_hangs.astype("uint8") << 6))


def unpack_verdicts(packed):
    """(statuses, new_paths, unique_crashes, unique_hangs) from the
    pack_verdicts lane byte."""
    return (packed & 7, (packed >> 3) & 3,
            (packed >> 5) & 1, (packed >> 6) & 1)


def pad_batch_result(res: "BatchResult", pad_to: int) -> "BatchResult":
    """Pad result arrays to ``pad_to`` lanes with the shared padding
    convention: padded lanes read FUZZ_ERROR with zero novelty (a
    consumer that ever reads past the real count fails loudly as an
    error spike instead of silently consuming plausible results)."""
    from .. import FUZZ_ERROR
    n = len(res.statuses)
    if pad_to <= n:
        return res
    pad = pad_to - n
    return BatchResult(
        statuses=np.concatenate(
            [res.statuses, np.full(pad, FUZZ_ERROR, dtype=np.int32)]),
        new_paths=np.concatenate(
            [res.new_paths, np.zeros(pad, dtype=np.int32)]),
        unique_crashes=np.concatenate(
            [res.unique_crashes, np.zeros(pad, dtype=bool)]),
        unique_hangs=np.concatenate(
            [res.unique_hangs, np.zeros(pad, dtype=bool)]),
        exit_codes=np.concatenate(
            [res.exit_codes, np.zeros(pad, dtype=np.int32)]))


class BatchResult(NamedTuple):
    """Per-lane outcome of a batched execution."""
    statuses: np.ndarray      # int32[B] FUZZ_* (RUNNING already -> HANG)
    new_paths: np.ndarray     # int32[B] 0 / 1 (new bucket) / 2 (new edge)
    unique_crashes: np.ndarray  # bool[B] first-seen crash shape
    unique_hangs: np.ndarray    # bool[B] first-seen hang shape
    exit_codes: np.ndarray    # int32[B]


class CompactReport(NamedTuple):
    """Device-side compaction of a batch's interesting lanes (crash /
    hang / new path): the candidate bytes of up to ``capacity`` such
    lanes, gathered IN the jitted step so triage never pulls the full
    [B, L] tensor across a slow device->host link.  ``count`` is the
    true number of interesting lanes — when it exceeds capacity the
    consumer falls back to a full transfer for that batch.  Mesh
    campaigns shard the report: ``count`` becomes a per-dp-shard
    vector and each shard owns a capacity-row block (lane ids stay
    global); Fuzzer._compact_rows handles both layouts."""
    idx: np.ndarray           # int32[C] lane numbers (valid: first count)
    bufs: np.ndarray          # uint8[C, L] candidate bytes of those lanes
    lens: np.ndarray          # int32[C]
    count: np.ndarray         # int32 scalar (or int32[n_dp], sharded)


class Instrumentation:
    name = "base"
    OPTION_SCHEMA: Dict[str, type] = {}
    OPTION_DESCS: Dict[str, str] = {}
    DEFAULTS: Dict[str, Any] = {}
    supports_batch = False
    # device_backed: inputs are tensors handed straight to the device
    # (no target process, no cmd_line). Host backends (afl,
    # return_code) execute real processes and need the driver to
    # describe the command via prepare_host() before batching.
    device_backed = False

    def __init__(self, options: Optional[str] = None):
        self.options = parse_options(options, self.OPTION_SCHEMA,
                                     self.DEFAULTS)
        self.last_status = FUZZ_NONE
        self.last_new_path = 0

    # -- single-exec API ------------------------------------------------

    def enable(self, input_bytes: Optional[bytes] = None,
               cmd_line: Optional[str] = None) -> None:
        """Run the target on one input (blocking in this framework —
        the reference's async enable + is_process_done poll loop
        collapses into one call). Host-exec backends take the
        driver-built ``cmd_line``; device backends ignore it."""
        raise NotImplementedError

    def is_process_done(self) -> bool:
        return True

    # -- async exec (network drivers) -----------------------------------

    def start_process(self, cmd_line: str) -> None:
        """Start the target WITHOUT waiting (reference enable's async
        half). The driver interacts with the live process, then calls
        wait_done() for the verdict + novelty update."""
        raise NotImplementedError(
            f"{self.name} cannot run live targets")

    def wait_done(self, timeout: float) -> int:
        """Wait for a start_process() target; kill on timeout (hang).
        Returns the FUZZ_* verdict and updates novelty state."""
        raise NotImplementedError

    def abort_process(self) -> int:
        """Kill and reap a start_process() target WITHOUT triaging the
        run (no virgin-map updates, no hang/crash attribution) — for
        driver-level failures (e.g. the target never opened its port)
        that say nothing about the input. Returns FUZZ_ERROR."""
        raise NotImplementedError

    def get_fuzz_result(self) -> int:
        return self.last_status

    def is_new_path(self) -> int:
        return self.last_new_path

    def last_unique_crash(self) -> bool:
        """Whether the last exec's crash had a first-seen coverage
        shape (AFL virgin_crash gating). Coverage-less backends have
        no uniqueness notion and report False."""
        return False

    def last_unique_hang(self) -> bool:
        return False

    # -- batched API ----------------------------------------------------

    def prepare_host(self, cmd_line: str, use_stdin: bool,
                     input_file: Optional[str] = None) -> None:
        """Host backends: bind the target command before batch runs
        (drivers call this once; device backends ignore it)."""

    def run_batch(self, inputs: np.ndarray, lengths: np.ndarray,
                  pad_to: Optional[int] = None) -> BatchResult:
        """Execute a [B, L] candidate batch. ``pad_to`` asks host
        backends to pad the RESULT arrays (status FUZZ_NONE, zero
        bitmaps) up to a stable lane count for the jitted triage —
        padding must never cost real target executions. Device
        backends receive already-padded inputs and may ignore it."""
        raise NotImplementedError(f"{self.name} has no batch path")

    # -- coverage plumbing ---------------------------------------------

    def merge(self, other_state: str) -> None:
        """Fold another instrumentation state's coverage into this one
        (reference merge; afl AND-fold). Raises if unsupported."""
        raise NotImplementedError(f"{self.name} cannot merge")

    def get_edges(self) -> Optional[List[Tuple[int, int]]]:
        """Edge list of the last execution (tracer support);
        None when the backend can't report edges."""
        return None

    def get_module_info(self) -> List[str]:
        """Names of instrumented modules (per-module coverage)."""
        return []

    def module_map_ranges(self):
        """[(name, lo, hi)] byte ranges of each module's partition in
        the raw coverage bitmap (picker/per-module mask derivation);
        None when the backend has no raw bitmap."""
        return None

    def get_edge_pairs(self, module: Optional[str] = None):
        """(from, to, count) records of the last execution (reference
        instrumentation_edge_t lists); None when unsupported."""
        return None

    # -- state ----------------------------------------------------------

    def get_state(self) -> str:
        raise NotImplementedError

    def set_state(self, state: str) -> None:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    @classmethod
    def help(cls) -> str:
        head = f"{cls.name} instrumentation"
        doc = (cls.__doc__ or "").strip().splitlines()
        if doc:
            head += f" — {doc[0]}"
        return head + "\n" + format_help(cls.name, cls.OPTION_SCHEMA,
                                         cls.OPTION_DESCS)


def module_slice_edges(edges, module_names: List[str], module: str,
                       partition_size: int):
    """Restrict a global (slot, count) edge list to one module's map
    partition, renumbering slots partition-locally (shared by the afl
    and jit_harness per-module views)."""
    if edges is None:
        return None
    m = module_names.index(module)
    lo, hi = m * partition_size, (m + 1) * partition_size
    return [(s - lo, c) for s, c in edges if lo <= s < hi]
