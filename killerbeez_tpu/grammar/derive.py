"""Grammar auto-derivation from the static layer (PR 3).

``derive_grammar`` reads the same branch facts that feed
``extract_dictionary`` and folds them into a field layout:

* single-byte positional eq/ne compares (``expect_byte`` chains)
  pin positions — one value becomes a literal, several become a
  token field whose alphabet is the value set;
* multi-byte eq/ne compares over a CONSECUTIVE dep span (the wide
  little-endian constants the dictionary now also emits) become
  token fields at the compare width;
* a position guarded by lt/ge (a range check — the KBVM idiom for
  "read a length byte, bound a loop with it") becomes a length
  field measuring the free-bytes field that follows it;
* everything unclaimed is free bytes, the tail unbounded.

The derivation is deliberately conservative: where analysis says
nothing the grammar says "anything", so a derived grammar can only
CONSTRAIN mutation where structure is proven, never exclude bytes an
uncovered branch might read (the same doctrine as the focus masks).
A program with no usable facts derives the degenerate grammar — and
degenerate compiles to the blind-parity tables, so auto-derivation
is always safe to turn on.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..analysis.dataflow import ANY, DataflowResult, analyze_dataflow
from .spec import Field, Grammar, Rule, blob, length, lit, token

#: alphabet size cap per derived token field (matches tables.ALPHA_CAP
#: conservatively; larger value sets stay free bytes — a position
#: compared against dozens of values is a dispatch byte, not magic)
MAX_ALPHA = 16


def _vsa_facts(vsa) -> Tuple[Dict[int, Set[int]], Set[int]]:
    """Per-position value sets and bound positions from VSA affine
    guard inversion — the facts the guarding-constant pass provably
    cannot see: a byte compared through arithmetic (``b0+200==300``
    folds to an affine operand vs a constant outside 0..255) inverts
    to the byte values that flip the guard.  eq/ne guards contribute
    alphabets; lt/ge guards over an affine byte mark the position as
    a length-style bound (the KBVM range-check idiom), exactly like
    the literal ``bounds`` path.  Length-dependent guards exclude
    themselves: LEN yields a non-constant domain, so no constant
    other side exists to invert against."""
    from ..analysis.vsa import affine_sat_set
    from ..models.vm import CMP_EQ
    pins: Dict[int, Set[int]] = {}
    bounds: Set[int] = set()
    for f in sorted(vsa.branches, key=lambda f: f.pc):
        for aff, other in ((f.x_affine, f.y_dom),
                           (f.y_affine, f.x_dom)):
            if aff is None or other.const_val is None:
                continue
            i = aff[0]
            if f.cmp in ("eq", "ne"):
                # the values solving the equality are the magic
                # values regardless of which side carries the byte
                sat = affine_sat_set(aff, CMP_EQ,
                                     other.const_val, True)
                if 0 < len(sat) <= MAX_ALPHA:
                    pins.setdefault(i, set()).update(sat)
            else:                       # lt / ge: a range check
                bounds.add(i)
    return pins, bounds


def derive_grammar(program,
                   result: Optional[DataflowResult] = None,
                   vsa=None) -> Grammar:
    """Fold branch facts into a field layout.  With ``vsa`` (a
    ``VsaResult``), affine guard inversion adds per-field alphabets
    and bound positions the literal pass cannot derive; with
    ``vsa=None`` (the default) the output is bit-identical to the
    pre-VSA derivation — the parity anchor."""
    result = result or analyze_dataflow(program)

    pins: Dict[int, Set[int]] = {}
    wide: Dict[Tuple[int, int], Set[int]] = {}
    bounds: Set[int] = set()
    if vsa is not None:
        vpins, vbounds = _vsa_facts(vsa)
        for i, vals in vpins.items():
            pins.setdefault(i, set()).update(vals)
        bounds |= vbounds
    for f in sorted(result.branches, key=lambda f: f.pc):
        if f.const is None or f.deps is ANY or not f.deps:
            continue
        ds = sorted(f.deps)
        if len(ds) == 1:
            i = ds[0]
            if f.cmp in ("eq", "ne") and 0 <= f.const <= 255:
                pins.setdefault(i, set()).add(f.const)
            elif f.cmp in ("lt", "ge") and not f.len_dep:
                bounds.add(i)
        elif (f.cmp in ("eq", "ne") and 2 <= len(ds) <= 4
                and ds == list(range(ds[0], ds[0] + len(ds)))):
            u = f.const & 0xFFFFFFFF
            if u < (1 << (8 * len(ds))):
                wide.setdefault((ds[0], len(ds)), set()).add(u)

    # claim bytes: single-byte pins first (expect chains are the
    # strongest facts), then non-overlapping wide spans, then length
    # bytes — deterministic position order throughout
    claimed: Set[int] = set(pins)
    items: List[Tuple[int, int, str, List[int]]] = [
        (i, 1, "pin", sorted(v)) for i, v in sorted(pins.items())]
    for (s, w) in sorted(wide):
        span = range(s, s + w)
        if any(p in claimed for p in span):
            continue
        claimed.update(span)
        items.append((s, w, "wide", sorted(wide[(s, w)])))
    for b in sorted(bounds):
        if b not in claimed:
            claimed.add(b)
            items.append((b, 1, "len", []))
    items.sort()

    fields: List[Field] = []
    pending_len: Optional[str] = None
    cur = 0

    def gap(to: int) -> None:
        nonlocal pending_len
        if to > cur:
            fields.append(blob(to - cur, name=pending_len or ""))
            pending_len = None

    for s, w, kind, vals in items:
        if s < cur:
            continue                    # overlap loser — skip
        gap(s)
        if kind == "pin":
            if len(vals) == 1:
                fields.append(lit(bytes([vals[0]])))
            elif len(vals) <= MAX_ALPHA:
                fields.append(token([bytes([v]) for v in vals], 1))
            else:
                fields.append(blob(1))
        elif kind == "wide":
            toks = [v.to_bytes(w, "little")
                    for v in vals[:MAX_ALPHA]]
            fields.append(token(toks, w))
        else:                           # len
            name = f"m{s}"
            fields.append(length(of=name, width=1))
            pending_len = name
        cur = s + w
    # unbounded tail — measured by a trailing length field if one is
    # still waiting for its region
    fields.append(blob(0, name=pending_len or ""))

    return Grammar(rules={"msg": Rule(name="msg",
                                      fields=tuple(fields))},
                   start="msg")
