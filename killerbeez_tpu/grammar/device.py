"""Structured mutation kernels — the device half of the grammar tier.

``grammar_havoc_at`` is the generation scans' structured twin of
``havoc_at``: it parses the seed once against the compiled field
program (``parse_fields``), then runs the stacked-edit scan where
each lane is either BLIND (plain havoc, bit-identical stream) or
STRUCTURED, selected by a per-lane stage byte.  Structured lanes
interleave mask-constrained havoc (edits land only on mutable bytes
— token slots, free bytes, the unparsed tail; literals and length
fields are protected) with four structured ops:

* token substitution — a token from the picked field's alphabet
  overwrites the slot;
* field-aware splice — one field's bytes overwrite another's;
* subtree regeneration — every mutable byte of one rule-instance
  group is redrawn;
* length-field repair — a length field is rewritten to cover the
  net insert/delete the lane's blind edits applied.

RNG discipline (the parity anchor, PR 14 pattern): the base stream
``words = bits(key, (n_steps+1, 8))`` and the stack draw are
byte-identical to ``havoc_at``; ALL grammar randomness comes from a
side key ``fold_in(key, GRAMMAR_SALT)``.  Under the degenerate
grammar (``meta[0] == 0``) every lane is blind with an all-ones mask
— and an all-ones mask is pinned bit-identical to unmasked havoc
(``_havoc_one``) — so the structured kernel IS ``havoc_at``
bit-for-bit, single-chip and mesh (tests/test_grammar.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models.vm import _mix32
from ..ops.mutate_core import _havoc_one, read_bytes
from .tables import KIND_ALPHA, KIND_BLOB, KIND_LEN

#: fold_in salt deriving the grammar side stream from the lane key —
#: the base havoc stream never sees it, so blind lanes (and every
#: degenerate-grammar lane) keep their exact historical bytes
GRAMMAR_SALT = 0x6772616D


class ParsedFields(NamedTuple):
    """One forward parse of a buffer against the field program."""
    offs: jax.Array        # int32[P] field start offsets
    effw: jax.Array        # int32[P] effective widths
    valid: jax.Array       # bool[P]  starts inside the live prefix
    mut_mask: jax.Array    # uint8[L] 1 = mutation may touch the byte
    grp_byte: jax.Array    # int32[L] rule-instance group id (-1 tail)
    edit_byte: jax.Array   # bool[L]  byte belongs to a mutable field


def _width_mask(w):
    """uint32 value mask for a 1/2/4-byte length field."""
    return jnp.select([w == 1, w == 2],
                      [jnp.uint32(0xFF), jnp.uint32(0xFFFF)],
                      jnp.uint32(0xFFFFFFFF))


def parse_fields(buf: jax.Array, length: jax.Array,
                 gt: Tuple) -> ParsedFields:
    """Sequential offset walk over the P field-program entries (P is
    static — the loop unrolls at trace time).  Length fields read
    their little-endian value from the buffer and size the entry they
    measure; width-0 free bytes take the measured width, or the rest
    of the live prefix.  The parse is TOTAL: any buffer parses, and
    bytes past the last entry stay mutable (the field program widens
    to "anything" where structure runs out)."""
    fp_kind, fp_width, fp_aux, fp_grp = gt[0], gt[1], gt[2], gt[3]
    P = fp_kind.shape[0]
    L = buf.shape[-1]
    pr = jnp.arange(P, dtype=jnp.int32)
    idx = jnp.arange(L, dtype=jnp.int32)

    off = jnp.int32(0)
    offs = jnp.zeros((P,), jnp.int32)
    effw = jnp.zeros((P,), jnp.int32)
    measured = jnp.full((P,), -1, jnp.int32)
    for p in range(P):
        kind = fp_kind[p]
        w = fp_width[p]
        # length fields: little-endian read at the current offset,
        # masked to the field width, sizes the measured entry
        val = (read_bytes(buf, off, 4, False)
               & _width_mask(w)).astype(jnp.int32)
        is_len = kind == KIND_LEN
        measured = jnp.where(
            is_len & (fp_aux[p] >= 0) & (pr == fp_aux[p]),
            jnp.clip(val, 0, L), measured)
        is_blob = kind == KIND_BLOB
        w_eff = jnp.where(
            is_blob & (w == 0),
            jnp.where(measured[p] >= 0, measured[p],
                      jnp.maximum(length - off, 0)),
            w)
        w_eff = jnp.clip(w_eff, 0, jnp.maximum(L - off, 0))
        offs = offs.at[p].set(off)
        effw = effw.at[p].set(w_eff)
        off = off + w_eff

    valid = (effw > 0) & (offs < jnp.maximum(length, 1))
    covered = jnp.zeros((L,), bool)
    edit_byte = jnp.zeros((L,), bool)
    grp_byte = jnp.full((L,), -1, jnp.int32)
    for p in range(P):
        in_f = (idx >= offs[p]) & (idx < offs[p] + effw[p])
        editable = (fp_kind[p] == KIND_ALPHA) | \
            (fp_kind[p] == KIND_BLOB)
        edit_byte = edit_byte | (in_f & editable)
        grp_byte = jnp.where(in_f & ~covered, fp_grp[p], grp_byte)
        covered = covered | in_f
    mut_mask = (edit_byte | ~covered).astype(jnp.uint8)
    return ParsedFields(offs=offs, effw=effw, valid=valid,
                        mut_mask=mut_mask, grp_byte=grp_byte,
                        edit_byte=edit_byte)


def _pick(pred, word):
    """Rank-select the ``word % count``-th set entry of ``pred``
    (the same rank idiom as ``_havoc_one``'s mask path)."""
    cnt = jnp.sum(pred).astype(jnp.uint32)
    cs = jnp.cumsum(pred.astype(jnp.int32))
    k = (word % jnp.maximum(cnt, 1)).astype(jnp.int32)
    return jnp.argmax(cs > k).astype(jnp.int32), cnt


def _at(arr, i):
    """arr[i] for a traced scalar index without a dynamic gather
    (one-hot compare-select; see read_bytes for the rationale)."""
    n = arr.shape[0]
    return jnp.sum(jnp.where(
        jnp.arange(n, dtype=jnp.int32) == i, arr,
        jnp.zeros((), arr.dtype))).astype(arr.dtype)


def _structured_one(buf, length, seed_len, gw, pf: ParsedFields, gt):
    """One structured edit: op = ``gw[0] % 4`` over (token sub, field
    splice, subtree regen, length repair).  Every op guards its own
    applicability (no alphabet fields / a single field / no length
    fields -> no-op) so any grammar is safe on any buffer."""
    fp_kind, fp_width, fp_aux, fp_grp = gt[0], gt[1], gt[2], gt[3]
    tok, tok_len, alpha_tok, alpha_n = gt[4], gt[5], gt[6], gt[7]
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    sop = (gw[0] % 4).astype(jnp.int32)

    # -- op 0: token substitution ------------------------------------
    pred_a = pf.valid & (fp_kind == KIND_ALPHA)
    f_a, cnt_a = _pick(pred_a, gw[1])
    off_a = _at(pf.offs, f_a)
    w_a = _at(pf.effw, f_a)
    row = _at(fp_aux, f_a)
    K, AC = alpha_tok.shape
    an = _at(alpha_n, row)
    slot = (gw[2] % jnp.maximum(an.astype(jnp.uint32), 1)
            ).astype(jnp.int32)
    tid = jnp.sum(jnp.where(
        (jnp.arange(K, dtype=jnp.int32)[:, None] == row)
        & (jnp.arange(AC, dtype=jnp.int32)[None, :] == slot),
        alpha_tok, 0)).astype(jnp.int32)
    T, TW = tok.shape
    tl = _at(tok_len, tid)
    tbytes = jnp.sum(jnp.where(
        jnp.arange(T, dtype=jnp.int32)[:, None] == tid, tok, 0),
        axis=0, dtype=jnp.int32).astype(jnp.uint8)      # [TW]
    rel_a = idx - off_a
    wlim = jnp.minimum(jnp.maximum(tl, 1), jnp.maximum(w_a, 1))
    tval = jnp.sum(jnp.where(
        jnp.clip(rel_a, 0, TW - 1)[:, None]
        == jnp.arange(TW, dtype=jnp.int32)[None, :],
        tbytes[None, :], 0), axis=1, dtype=jnp.int32).astype(jnp.uint8)
    in_a = (rel_a >= 0) & (rel_a < wlim) & (cnt_a > 0) & (an > 0)
    out0 = jnp.where(in_a, tval, buf)

    # -- op 1: field-aware splice (field g's bytes over field f) -----
    pred_s = pf.valid
    f_s, cnt_s = _pick(pred_s, gw[1])
    g_s, _ = _pick(pred_s, gw[3])
    off_f = _at(pf.offs, f_s)
    off_g = _at(pf.offs, g_s)
    wmin = jnp.minimum(_at(pf.effw, f_s), _at(pf.effw, g_s))
    src = jnp.clip(idx - off_f + off_g, 0, L - 1)
    oh = src[:, None] == idx[None, :]
    spliced = jnp.sum(jnp.where(oh, buf[None, :], 0),
                      axis=1, dtype=jnp.int32).astype(jnp.uint8)
    in_s = (idx >= off_f) & (idx < off_f + wmin) & (cnt_s >= 2)
    out1 = jnp.where(in_s, spliced, buf)

    # -- op 2: subtree regeneration ----------------------------------
    # pick a mutable field, redraw every mutable byte of its rule-
    # instance group (nested rules inline-expand into groups, so a
    # group IS the subtree); literals and length fields in the group
    # keep their bytes — structure survives its own regeneration
    pred_e = pf.valid & ((fp_kind == KIND_ALPHA)
                         | (fp_kind == KIND_BLOB))
    f_e, cnt_e = _pick(pred_e, gw[1])
    grp_f = _at(fp_grp, f_e)
    rnd = (_mix32((idx.astype(jnp.uint32)
                   * jnp.uint32(0x9E3779B9)) ^ gw[4])
           & jnp.uint32(0xFF)).astype(jnp.uint8)
    in_g = (pf.grp_byte == grp_f) & pf.edit_byte & (cnt_e > 0)
    out2 = jnp.where(in_g, rnd, buf)

    # -- op 3: length-field repair -----------------------------------
    # blind delete/insert edits moved the tail; rewrite one length
    # field to its parse-time measured width plus the lane's net
    # length delta, so the structure the parser sees tracks the edit
    pred_l = pf.valid & (fp_kind == KIND_LEN) & (fp_aux >= 0)
    f_l, cnt_l = _pick(pred_l, gw[1])
    m_idx = _at(fp_aux, f_l)
    w_m = _at(pf.effw, m_idx)
    off_l = _at(pf.offs, f_l)
    w_l = _at(fp_width, f_l)
    delta = length - seed_len
    new_u = jnp.clip(w_m + delta, 0, jnp.int32(0x7FFFFFFF)
                     ).astype(jnp.uint32) & _width_mask(w_l)
    rel_l = idx - off_l
    lbytes = ((new_u >> (8 * jnp.clip(rel_l, 0, 3).astype(jnp.uint32)))
              & 0xFF).astype(jnp.uint8)
    in_l = (rel_l >= 0) & (rel_l < w_l) & (cnt_l > 0)
    out3 = jnp.where(in_l, lbytes, buf)

    out = jnp.where(sop == 0, out0,
                    jnp.where(sop == 1, out1,
                              jnp.where(sop == 2, out2, out3)))
    return out, length


@partial(jax.jit, static_argnames=("stack_pow2",))
def grammar_havoc_at(buf: jax.Array, length: jax.Array,
                     key: jax.Array, gt: Tuple, stack_pow2: int = 4
                     ) -> Tuple[jax.Array, jax.Array]:
    """``havoc_at`` with grammar-structured stages interleaved.

    The base words and stack draw are byte-identical to ``havoc_at``;
    grammar randomness comes only from the ``GRAMMAR_SALT`` side key.
    A lane's stage byte (side stream) selects blind vs structured:
    blind lanes run unmasked-equivalent havoc (all-ones mask);
    structured lanes constrain havoc to mutable bytes and replace 3
    of every 4 stacked edits with a structured op.  ``meta[0] == 0``
    (degenerate grammar) forces every lane blind — the bit-exactness
    anchor."""
    n_steps = 1 << stack_pow2
    words = jax.random.bits(key, (n_steps + 1, 8), dtype=jnp.uint32)
    stack = jnp.uint32(1) << (1 + words[0, 0] % stack_pow2)
    side = jax.random.fold_in(key, GRAMMAR_SALT)
    gwords = jax.random.bits(side, (n_steps + 1, 8),
                             dtype=jnp.uint32)
    meta = gt[8]
    pf = parse_fields(buf, length, gt)
    stage = gwords[0, 0] % 256
    structured = (meta[0] != 0) & \
        (stage < meta[1].astype(jnp.uint32))
    mask = jnp.where(structured, pf.mut_mask, jnp.uint8(1))

    def step(carry, xs):
        i, w, gw = xs
        b, ln = carry
        nb, nln = _havoc_one(b, ln, w, mask=mask)
        sb, sln = _structured_one(b, ln, length, gw, pf, gt)
        use_s = structured & ((gw[7] & 3) != 0)
        nb = jnp.where(use_s, sb, nb)
        nln = jnp.where(use_s, sln, nln)
        active = i < stack
        b = jnp.where(active, nb, b)
        ln = jnp.where(active, nln, ln)
        return (b, ln), None

    (out, out_len), _ = jax.lax.scan(
        step, (buf, length),
        (jnp.arange(n_steps, dtype=jnp.uint32), words[1:],
         gwords[1:]))
    return out, out_len
