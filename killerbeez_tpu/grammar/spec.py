"""Grammar spec model: rules of fields, JSON codec, the degenerate
"anything" grammar.

A grammar is a set of named rules; each rule is a FLAT sequence of
fields.  Field kinds:

* ``lit``    — fixed bytes (magic headers, opcode bytes);
* ``token``  — one slot whose value is drawn from a per-field token
  alphabet (the dictionary-seeded alternatives: versions, commands,
  wide little-endian constants);
* ``len``    — a little-endian length field measuring a NAMED later
  field in the same rule expansion (TLV length bytes; the repair
  kernel keeps it consistent after insert/delete);
* ``bytes``  — free bytes: fixed width, or width 0 = "the rest" /
  "whatever the measuring len field says";
* ``rule``   — a nested rule reference, inline-expanded by the
  compiler up to its depth cap.

The JSON form mirrors the model one field-object per entry, bytes
hex-encoded::

    {"start": "msg", "rules": {"msg": [
        {"lit": "53544b31"},
        {"token": ["01", "02", "ff"], "width": 1},
        {"len": "payload", "width": 1},
        {"bytes": 0, "name": "payload"},
        {"rule": "msg"}]}}

The **degenerate grammar** is one rule with one ``bytes 0`` field:
"anything".  It compiles to tables whose ``nondegen`` flag is 0, and
under it every structured kernel is bit-identical to blind havoc —
the parity anchor the generation scans pin (tests/test_grammar.py).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional


@dataclass(frozen=True)
class Field:
    kind: str                       # lit / token / len / bytes / rule
    value: bytes = b""              # lit
    alphabet: tuple = ()            # token: tuple of bytes values
    width: int = 0                  # token/len slot width; bytes width
    of: str = ""                    # len: name of the measured field
    name: str = ""                  # referenced by len fields
    rule: str = ""                  # rule reference

    def __post_init__(self):
        if self.kind not in ("lit", "token", "len", "bytes", "rule"):
            raise ValueError(f"unknown field kind {self.kind!r}")
        if self.kind == "lit" and not self.value:
            raise ValueError("lit field needs non-empty bytes")
        if self.kind == "len" and self.width not in (1, 2, 4):
            raise ValueError("len field width must be 1, 2 or 4")
        if self.kind == "rule" and not self.rule:
            raise ValueError("rule field needs a rule name")


def lit(value: bytes) -> Field:
    return Field(kind="lit", value=bytes(value))


def token(alphabet, width: int = 0) -> Field:
    alpha = tuple(bytes(t) for t in alphabet)
    if width <= 0:
        width = max((len(t) for t in alpha), default=1)
    return Field(kind="token", alphabet=alpha, width=width)


def length(of: str, width: int = 1) -> Field:
    return Field(kind="len", of=of, width=width)


def blob(width: int = 0, name: str = "") -> Field:
    return Field(kind="bytes", width=int(width), name=name)


def ref(rule: str) -> Field:
    return Field(kind="rule", rule=rule)


@dataclass(frozen=True)
class Rule:
    name: str
    fields: tuple = ()              # tuple of Field (may be empty)


@dataclass
class Grammar:
    rules: Dict[str, Rule] = dc_field(default_factory=dict)
    start: str = ""

    def __post_init__(self):
        if self.start and self.start not in self.rules:
            raise ValueError(f"start rule {self.start!r} undefined")
        for r in self.rules.values():
            for f in r.fields:
                if f.kind == "rule" and f.rule not in self.rules:
                    raise ValueError(
                        f"rule {r.name!r} references undefined rule "
                        f"{f.rule!r}")

    # -- JSON codec ---------------------------------------------------

    def to_json(self) -> str:
        def enc(f: Field) -> dict:
            if f.kind == "lit":
                return {"lit": f.value.hex()}
            if f.kind == "token":
                d = {"token": [t.hex() for t in f.alphabet]}
                if f.width:
                    d["width"] = f.width
                return d
            if f.kind == "len":
                return {"len": f.of, "width": f.width}
            if f.kind == "bytes":
                d = {"bytes": f.width}
                if f.name:
                    d["name"] = f.name
                return d
            return {"rule": f.rule}
        return json.dumps({
            "start": self.start,
            "rules": {n: [enc(f) for f in r.fields]
                      for n, r in sorted(self.rules.items())}})

    @classmethod
    def from_json(cls, text: str) -> "Grammar":
        d = json.loads(text)
        if not isinstance(d, dict) or "rules" not in d:
            raise ValueError('grammar JSON needs {"rules": {...}}')
        rules: Dict[str, Rule] = {}
        for name, fl in d["rules"].items():
            fields: List[Field] = []
            for fd in fl:
                if "lit" in fd:
                    fields.append(lit(bytes.fromhex(fd["lit"])))
                elif "token" in fd:
                    fields.append(token(
                        [bytes.fromhex(t) for t in fd["token"]],
                        int(fd.get("width", 0))))
                elif "len" in fd:
                    fields.append(length(fd["len"],
                                         int(fd.get("width", 1))))
                elif "bytes" in fd:
                    fields.append(blob(int(fd["bytes"]),
                                       fd.get("name", "")))
                elif "rule" in fd:
                    fields.append(ref(fd["rule"]))
                else:
                    raise ValueError(f"unknown field object {fd!r}")
            rules[name] = Rule(name=name, fields=tuple(fields))
        start = d.get("start") or (sorted(rules) and sorted(rules)[0])
        return cls(rules=rules, start=start)


def degenerate_grammar() -> Grammar:
    """The one-rule "anything" grammar: a single unbounded free-bytes
    field.  Compiles with ``nondegen == 0`` — the parity anchor."""
    return Grammar(rules={"any": Rule(name="any",
                                      fields=(blob(0),))},
                   start="any")


def load_grammar(source: str) -> Grammar:
    """Grammar from a JSON string, a ``@file`` path, or the literal
    name ``degenerate`` — the option-string entry point the
    instrumentation / mutator option schemas share."""
    src = source.strip()
    if src == "degenerate":
        return degenerate_grammar()
    if src.startswith("@"):
        with open(src[1:], "r", encoding="utf-8") as fh:
            src = fh.read()
    return Grammar.from_json(src)
