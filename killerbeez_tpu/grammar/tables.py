"""The structure compiler: grammar spec -> fixed-shape device tables.

``compile_grammar`` inline-expands the start rule (nested ``rule``
references up to ``depth_cap``, deeper clipped to free bytes with a
ONE-SHOT warning — never a miscompile) into a flat field program plus
token / alphabet tables, all fixed-shape numpy arrays a jitted scan
can thread as a pytree:

* ``fp_kind / fp_width / fp_aux / fp_grp`` int32[P] — the field
  program.  Kinds: 0 lit, 1 token-alphabet slot, 2 length field,
  3 free bytes.  ``fp_aux`` is the kind-specific link: token id for
  lits, alphabet row for token slots, MEASURED ENTRY INDEX for length
  fields (-1 unresolved), -1 for free bytes.  ``fp_grp`` is the
  rule-instance group — the subtree-regeneration unit;
* ``tok`` uint8[T, TW] + ``tok_len`` int32[T] — interned token bytes;
* ``alpha_tok`` int32[K, AC] + ``alpha_n`` int32[K] — per-field token
  alphabets (rows of token ids; empty alphabets carry n == 0 and the
  kernels guard them);
* ``meta`` int32[4] — ``[nondegen, stage_p, n_entries, clipped]``.
  ``nondegen == 0`` marks the degenerate "anything" grammar: the
  kernels then reduce to blind havoc bit-exactly (the parity anchor).
  ``stage_p`` (0..256) is the per-lane structured-stage probability
  numerator: a lane is structured when its stage byte < stage_p.

Tables are plain data — compiled once per campaign on the host,
shipped to the device by the generation-scan entry points.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple

import numpy as np

from ..utils.logging import WARNING_MSG
from .spec import Field, Grammar

KIND_LIT = 0
KIND_ALPHA = 1
KIND_LEN = 2
KIND_BLOB = 3

#: default inline-expansion depth cap for nested rule references
DEPTH_CAP = 4
#: default structured-stage probability numerator (of 256): half the
#: lanes in a generation run structured stages, half stay blind
STAGE_P = 128
#: hard entry / alphabet bounds (fixed device table shapes)
MAX_ENTRIES = 96
ALPHA_CAP = 32
MAX_TOK_WIDTH = 8


class GrammarTables(NamedTuple):
    fp_kind: np.ndarray     # int32[P]
    fp_width: np.ndarray    # int32[P]
    fp_aux: np.ndarray      # int32[P]
    fp_grp: np.ndarray      # int32[P]
    tok: np.ndarray         # uint8[T, TW]
    tok_len: np.ndarray     # int32[T]
    alpha_tok: np.ndarray   # int32[K, AC]
    alpha_n: np.ndarray     # int32[K]
    meta: np.ndarray        # int32[4]: nondegen, stage_p, n, clipped

    @property
    def nondegen(self) -> bool:
        return bool(self.meta[0])

    def device(self) -> Tuple:
        """The jit-threadable pytree: one jnp array per table, in
        field order (the generation scans and ``grammar_havoc_at``
        consume exactly this tuple)."""
        import jax.numpy as jnp
        return tuple(jnp.asarray(a) for a in self)


def compile_grammar(grammar: Grammar, depth_cap: int = DEPTH_CAP,
                    stage_p: int = STAGE_P) -> GrammarTables:
    """Spec -> tables.  Deterministic: expansion order is rule text
    order, tokens interned first-use-first.  Nesting deeper than
    ``depth_cap`` and programs longer than ``MAX_ENTRIES`` clip to
    free bytes — each compile emits AT MOST ONE warning describing
    every clip, and the clipped program still parses every input
    (clipping widens, never narrows, what mutation may touch)."""
    tokens: List[bytes] = []
    tok_index: Dict[bytes, int] = {}
    alphas: List[List[int]] = []
    entries: List[list] = []     # [kind, width, aux, grp, name, of]
    clipped = [0, 0]             # depth clips, entry-cap clips
    grp_next = [0]

    def intern(tb: bytes) -> int:
        tb = bytes(tb[:MAX_TOK_WIDTH]) or b"\x00"
        if tb not in tok_index:
            tok_index[tb] = len(tokens)
            tokens.append(tb)
        return tok_index[tb]

    def emit(kind, width, aux, grp, name="", of=""):
        if len(entries) >= MAX_ENTRIES:
            clipped[1] += 1
            return
        entries.append([kind, int(width), int(aux), int(grp),
                        name, of])

    def expand(rule_name: str, depth: int, grp: int) -> None:
        for f in grammar.rules[rule_name].fields:
            if f.kind == "rule":
                if depth + 1 > depth_cap:
                    clipped[0] += 1
                    emit(KIND_BLOB, 0, -1, grp)
                else:
                    grp_next[0] += 1
                    expand(f.rule, depth + 1, grp_next[0])
            elif f.kind == "lit":
                emit(KIND_LIT, len(f.value), intern(f.value), grp)
            elif f.kind == "token":
                row = [intern(t) for t in f.alphabet[:ALPHA_CAP]]
                alphas.append(row)
                emit(KIND_ALPHA, f.width, len(alphas) - 1, grp)
            elif f.kind == "len":
                emit(KIND_LEN, f.width, -1, grp, of=f.of)
            else:                       # bytes
                emit(KIND_BLOB, f.width, -1, grp, name=f.name)

    if grammar.start:
        expand(grammar.start, 1, 0)
    if not entries:                     # empty grammar = "anything"
        entries.append([KIND_BLOB, 0, -1, 0, "", ""])
    if clipped[0] or clipped[1]:
        WARNING_MSG(
            "grammar: clipped %d nested rule reference(s) beyond "
            "depth cap %d and %d field(s) beyond the %d-entry table "
            "bound to free bytes (structure widens to 'anything' "
            "there; mutation coverage is preserved)",
            clipped[0], depth_cap, clipped[1], MAX_ENTRIES)

    # resolve length fields to the nearest LATER entry with the
    # measured name (forward TLV convention), else the nearest
    # earlier one; unresolved stays -1 (the kernels skip it)
    for i, e in enumerate(entries):
        if e[0] != KIND_LEN:
            continue
        of = e[5]
        cands = [j for j in range(i + 1, len(entries))
                 if entries[j][4] == of] or \
                [j for j in range(i) if entries[j][4] == of]
        if of and cands:
            e[2] = cands[0]

    n = len(entries)
    nondegen = 0 if (n == 1 and entries[0][0] == KIND_BLOB
                     and entries[0][1] == 0) else 1

    fp = np.asarray([[e[0], e[1], e[2], e[3]] for e in entries],
                    dtype=np.int32)
    T = max(len(tokens), 1)
    TW = max((len(t) for t in tokens), default=1)
    TW = max(TW, 1)
    tok = np.zeros((T, TW), dtype=np.uint8)
    tok_len = np.zeros((T,), dtype=np.int32)
    for i, t in enumerate(tokens):
        tok[i, :len(t)] = np.frombuffer(t, dtype=np.uint8)
        tok_len[i] = len(t)
    K = max(len(alphas), 1)
    AC = max(max((len(a) for a in alphas), default=1), 1)
    alpha_tok = np.zeros((K, AC), dtype=np.int32)
    alpha_n = np.zeros((K,), dtype=np.int32)
    for i, row in enumerate(alphas):
        alpha_tok[i, :len(row)] = row
        alpha_n[i] = len(row)
    meta = np.asarray(
        [nondegen, int(stage_p), n, clipped[0] + clipped[1]],
        dtype=np.int32)
    return GrammarTables(
        fp_kind=fp[:, 0].copy(), fp_width=fp[:, 1].copy(),
        fp_aux=fp[:, 2].copy(), fp_grp=fp[:, 3].copy(),
        tok=tok, tok_len=tok_len,
        alpha_tok=alpha_tok, alpha_n=alpha_n, meta=meta)


def degenerate_tables(stage_p: int = STAGE_P) -> GrammarTables:
    """Compiled tables of the degenerate grammar (``nondegen == 0``)
    — what campaigns without --grammar implicitly run."""
    from .spec import degenerate_grammar
    return compile_grammar(degenerate_grammar(), stage_p=stage_p)
