"""Structure-aware generation tier: grammar specs compiled to
fixed-shape device tables + structured mutation kernels that run
INSIDE the generation scans (ROADMAP item 5).

Layers:

* ``spec``   — the grammar spec model (rules / fields, JSON codec,
  the degenerate "anything" grammar);
* ``tables`` — the structure compiler: spec -> fixed-shape int32 /
  uint8 device tables (``compile_grammar``), nesting inline-expanded
  to a depth cap with a one-shot clip warning;
* ``device`` — the structured mutation kernels (``grammar_havoc_at``)
  the generation scans inline: field parse, token substitution,
  field-aware splice, subtree regeneration, length-field repair;
* ``derive`` — auto-derivation from the static layer
  (``derive_grammar``): dictionary tokens become literal runs and
  token alphabets, length-tainted compares mark length fields.

Parity doctrine (the PR 14 pattern): under the degenerate one-rule
grammar every structured kernel is bit-identical to blind
``havoc_at`` — same PRNG stream, same edits — pinned in
tests/test_grammar.py, so the tier stands up without perturbing v0
candidate streams.
"""

from .spec import Field, Grammar, Rule, degenerate_grammar
from .tables import GrammarTables, compile_grammar
from .device import GRAMMAR_SALT, grammar_havoc_at, parse_fields
from .derive import derive_grammar

__all__ = [
    "Field", "Grammar", "Rule", "degenerate_grammar",
    "GrammarTables", "compile_grammar",
    "GRAMMAR_SALT", "grammar_havoc_at", "parse_fields",
    "derive_grammar",
]
