"""The fuzzing main loop — batch generalization of the reference's
iteration loop (fuzzer/main.c:370-418).

Per step: mutate a candidate batch on device -> execute (device VM or
host backend) -> novelty/verdict reduce on device -> gather only the
interesting lanes to host -> md5-dedup and write findings to
``output/{crashes,hangs,new_paths}/<md5>`` exactly like the reference
(fuzzer/main.c:404-417). Single-exec backends fall back to the
reference-shaped scalar loop.
"""

from __future__ import annotations

import base64
import contextlib
import json
import os
import time
from typing import Dict, Optional, Union

import numpy as np

from .. import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from ..corpus.schedule import Arm, Scheduler, make_scheduler
from ..corpus.store import CorpusStore
from ..drivers.base import Driver
from ..resilience.chaos import chaos_point
from ..telemetry import MetricsRegistry, Telemetry
from ..utils.fileio import ensure_dir, md5_hex, write_buffer_to_file
from ..utils.logging import CRITICAL_MSG, DEBUG_MSG, INFO_MSG, WARNING_MSG

FINDING_DIRS = {FUZZ_CRASH: "crashes", FUZZ_HANG: "hangs"}


class _StackedRows:
    """One stacked [k, ...] device array whose host copy is pulled
    ONCE (async-prefetched), shared by k per-step triage views — the
    transfer-count divider behind the K-step superbatch path."""

    def __init__(self, dev):
        self.dev = dev
        self._np = None
        fn = getattr(dev, "copy_to_host_async", None)
        if fn is not None:
            fn()

    def materialize(self) -> np.ndarray:
        if self._np is None:
            self._np = np.asarray(self.dev)
            self.dev = None
        return self._np

    def row(self, i: int) -> "_LazyRow":
        return _LazyRow(self, i)


class _LazyRow:
    """numpy-coercible view of one row of a _StackedRows holder."""

    def __init__(self, holder: _StackedRows, i: int):
        self._holder = holder
        self._i = i

    def __array__(self, dtype=None, copy=None):
        # np.asarray: scalar rows (e.g. per-step counts) must come
        # back as 0-d ARRAYS, not numpy scalars
        r = np.asarray(self._holder.materialize()[self._i])
        return r.astype(dtype) if dtype is not None else r


class FuzzStats:
    """Thin live view over the telemetry ``MetricsRegistry`` — the
    registry is the single source of truth, so the loop, the CLI, the
    stats files and the manager heartbeat can never disagree about
    counts or rates (they used to: the loop accumulated per-step
    elapsed while callers recomputed rate from their own wall
    clocks).  Field reads/writes map straight onto registry counters;
    ``iterations`` is the registry's ``execs`` series (AFL naming on
    the wire, reference naming in code)."""

    _FIELD_TO_SERIES = {
        "iterations": "execs", "crashes": "crashes", "hangs": "hangs",
        "new_paths": "new_paths", "unique_crashes": "unique_crashes",
        "unique_hangs": "unique_hangs", "errors": "errors",
    }

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._reg = registry if registry is not None \
            else MetricsRegistry()

    @property
    def registry(self) -> MetricsRegistry:
        return self._reg

    @property
    def elapsed(self) -> float:
        """Accumulated in-run wall time (sum of ``run()`` windows,
        not campaign age — warm-up gaps between runs don't count)."""
        return self._reg.active_seconds()

    @property
    def execs_per_sec(self) -> float:
        """Lifetime rate over in-run time."""
        return self._reg.execs_per_sec()

    @property
    def execs_per_sec_ema(self) -> float:
        """Recent rate (EMA over the registry's horizon)."""
        return self._reg.execs_per_sec_ema()

    def as_dict(self) -> Dict[str, float]:
        d: Dict[str, float] = {f: getattr(self, f)
                               for f in self._FIELD_TO_SERIES}
        d["elapsed"] = self.elapsed
        d["execs_per_sec"] = self.execs_per_sec
        d["execs_per_sec_ema"] = self.execs_per_sec_ema
        return d


def _stat_field(series: str) -> property:
    def _get(self: FuzzStats) -> int:
        return int(self._reg.counters.get(series, 0))

    def _set(self: FuzzStats, v: int) -> None:
        self._reg.counters[series] = int(v)

    return property(_get, _set)


for _f, _s in FuzzStats._FIELD_TO_SERIES.items():
    setattr(FuzzStats, _f, _stat_field(_s))


class Fuzzer:
    """Drives driver/instrumentation/mutator to completion."""

    #: corpus-feedback cap: rotation cycles at most this many of the
    #: most recent new-path findings (older ones stay on disk)
    CORPUS_CAP = 256

    #: K-step device-side accumulation default for the fused path
    #: (overridable via accumulate=; 1 disables)
    ACCUMULATE_AUTO = 8

    #: default corpus-feedback cadence (batches between rotations)
    #: when feedback < 0: coverage-guided seeding is ON by default for
    #: RANDOMIZED mutators (get_total_iteration_count() == -1) —
    #: fb_gate.py measures it >= single-seed havoc on every CGC
    #: target.  Deterministic walks (bit_flip, arithmetic, ...) keep
    #: feedback off under auto: rotating the seed mid-walk would
    #: change the reference's deterministic iteration contract (an
    #: explicit -fb N still applies to them).  8 matches the
    #: superbatch depth (K stays 8) and, because finds are credited
    #: to the GENERATING arm, rotation reads the corpus without
    #: draining the pipeline — no throughput cost.  Rotation only
    #: engages once edge-novel findings exist, so short runs and
    #: finding-free targets behave exactly as with feedback off.
    FEEDBACK_AUTO = 8

    def __init__(self, driver: Driver, output_dir: str = "output",
                 batch_size: int = 1024, write_findings: bool = True,
                 debug_triage: bool = False, feedback: int = -1,
                 accumulate: int = 0,
                 telemetry: Union[Telemetry, bool, None] = None,
                 stats_interval: float = 5.0,
                 scheduler: Union[Scheduler, str, None] = None,
                 corpus_dir: Optional[str] = None,
                 resume: bool = False,
                 sync=None,
                 persist_interval: float = 5.0,
                 trace=None,
                 profile_device: int = 0,
                 events_max_mb: float = 0.0,
                 watchdog=None,
                 generations: int = 0,
                 learn=None,
                 hybrid=None):
        self.driver = driver
        self.output_dir = output_dir
        self.batch_size = int(batch_size)
        self.write_findings = write_findings
        self.debug_triage = debug_triage
        # observability: the registry ALWAYS runs (FuzzStats is a view
        # over it); ``telemetry=False`` (CLI --no-stats) only disables
        # the periodic fuzzer_stats/plot_data/stats.jsonl file sink
        # and the campaign event log.  The default follows
        # write_findings: a no-artifacts run (bench timing loops,
        # library callers) must not grow a new filesystem side
        # effect; telemetry=True forces the sink on.  ``trace`` turns
        # the flight-recorder span ring on (True / max-events int /
        # TraceRecorder); it is independent of the sink — trace.json
        # exports at run end whenever findings are being written.
        # a NON-resume campaign starts a fresh event timeline even in
        # a reused output dir (counters restart, so inherited events
        # would break reconciliation); --resume continues the log
        ev_max_bytes = int(float(events_max_mb) * 1e6)
        if telemetry is None:
            telemetry = Telemetry(
                output_dir if write_findings else None,
                interval_s=stats_interval, trace=trace,
                fresh_events=not resume,
                events_max_bytes=ev_max_bytes)
        elif telemetry is True:
            telemetry = Telemetry(output_dir, interval_s=stats_interval,
                                  trace=trace, fresh_events=not resume,
                                  events_max_bytes=ev_max_bytes)
        elif telemetry is False:
            telemetry = Telemetry(None, trace=trace)
        self.telemetry = telemetry
        # drivers time their mutate/execute phases with the loop's
        # stage timer (base.Driver.test_batch)
        driver.stage_timer = telemetry.timer
        if feedback < 0:
            mut = getattr(driver, "mutator", None)
            randomized = (mut is not None
                          and mut.get_total_iteration_count() < 0)
            feedback = self.FEEDBACK_AUTO if randomized else 0
        #: fused superbatch depth: 0 = auto (ACCUMULATE_AUTO when the
        #: driver supports the fused-multi path), 1 = per-batch
        self.accumulate = int(accumulate)
        #: every `feedback` batches, rotate the mutator seed through
        #: new-path findings (coverage-guided corpus loop; 0 = off)
        self.feedback = int(feedback)
        # seed scheduling lives in the corpus subsystem: the scheduler
        # owns the arms ([buf, selections, finds] + metadata), the
        # base-seed stats and the per-period credit fold; the loop
        # owns WHEN to rotate and the shape-stable seed swap.  The
        # default bandit policy is the exact in-loop behavior it
        # replaced (corpus/schedule.py).
        if isinstance(scheduler, Scheduler):
            self.scheduler = scheduler
        else:
            self.scheduler = make_scheduler(scheduler or "bandit",
                                            cap=self.CORPUS_CAP)
        #: durable corpus tier: admissions write through immediately;
        #: scheduler/campaign state flushes on `persist_interval`
        self.store = CorpusStore(corpus_dir) if corpus_dir else None
        #: manager-mediated corpus exchange (corpus/sync.py); polled
        #: between batches, time-gated internally
        self.sync = sync
        #: optional signature hook: bytes -> [edge slot, ...] for the
        #: entry sidecar (rare-edge scheduling, sync coverage dedup)
        self._signer = None
        #: optional plateau crack stage (fuzzer/crack.py): solves
        #: statically-reachable-but-never-hit edges into concrete
        #: inputs when coverage stalls, and feeds the focused-
        #: mutation masks; installed by the CLI's --crack wiring
        self.cracker = None
        #: plateau auto-repair stage (fuzzer/repairer.py): consumes
        #: accumulated proxy-gap counterexamples into a verified
        #: patched proxy; installed by the CLI's --auto-repair wiring
        self.repairer = None
        #: opt-in jax.profiler device capture: trace this many batches
        #: into <output>/device_trace next to the host trace.json
        self.profile_device = int(profile_device)
        self._prof_active = False
        #: monotone dispatched-batch counter — the flight recorder
        #: maps it onto PIPELINE_DEPTH trace lanes (seq % depth), one
        #: lane per in-flight pipeline slot
        self._batch_seq = 0
        self._persist_interval = float(persist_interval)
        self._last_persist = 0.0
        #: dispatch watchdog (resilience/watchdog.py): a deadline on
        #: every blocking device wait; a stall dumps in-flight lane
        #: state and escalates to a supervisor-mediated restart
        self.watchdog = watchdog
        if watchdog is not None:
            watchdog.registry = telemetry.registry
            watchdog.telemetry = telemetry
            watchdog.dump_fn = self._watchdog_dump
            watchdog.note_batch(self.batch_size)
        #: live view of the pipeline's pending deque for the watchdog
        #: dump (set by _run_batched)
        self._pending = None
        #: device-resident generation loop (--generations): the TPU
        #: runs this many full mutate->execute->triage->reseed
        #: generations per host dispatch and the host only drains the
        #: findings ring + admission ledger (ops/generations.py);
        #: <= 1 = host-driven loop.  Auto-stands-down (with a warning)
        #: when the crack stage, focus masks, or a non-fused driver
        #: is active — the same discipline as the superbatch path.
        self.generations = int(generations)
        self._gen_warned = False
        #: whether the CURRENT generations run reseeds on device
        #: (set per run): with reseeding off the device ledger is
        #: empty, so the drain admits edge-novel ring lanes host-side
        #: instead — the store write-through contract must hold in
        #: both regimes
        self._gen_reseed = True
        #: host mirror of the device seed-slot ring ((shard, slot) ->
        #: entry md5; shard 0 on single-chip): the admission-replay
        #: parent map, rebuilt dispatch by dispatch from the device's
        #: per-shard ledgers
        self._ring_mirror: Dict[tuple, str] = {}
        # the arm whose candidates the batch being TRIAGED came from:
        # with a deep pipeline, triage lags generation, so finds must
        # credit the GENERATING arm (entry object, robust to corpus
        # index shifts), not whichever arm is active at triage time
        self._credit_arm: Optional[list] = None
        self._active_entry: Optional[list] = None
        self._iter_base = 0             # execs restored by --resume
        self._fb_batches = 0
        self._accum_warned = False
        self._dbg = None
        #: stateful session tier observability: last gauge refresh +
        #: the high-water of touched state x edge pairs (one
        #: state_cov event per increase)
        self._state_gauge_t = 0.0
        self._state_pairs_seen = 0
        #: learned mutation shaping (killerbeez_tpu/learn/): a
        #: LearnTier collecting labels from the admission stream,
        #: training the byte-saliency model on the device between
        #: dispatches, and serving masks — in-scan weights for the
        #: -G generation scans, set_focus_mask positions at rotation
        #: boundaries for the host-driven loop.  None = off (the
        #: exact historical paths compile).
        self.learn = learn
        #: hybrid native⇄TPU bridge (killerbeez_tpu/hybrid/): unique
        #: findings enqueue for native validation in _triage_lane,
        #: verdicts fold back beside every sync round.  None = off
        #: (the exact historical paths run).
        self.hybrid = hybrid
        #: tier tag stamped onto minted corpus entries
        #: (docs/HYBRID.md sidecar schema); this loop is the TPU tier
        self.tier_tag = "tpu"
        self.stats = FuzzStats(telemetry.registry)
        self._seen = {k: set() for k in ("crashes", "hangs", "new_paths")}
        if write_findings:
            for sub in ("crashes", "hangs", "new_paths"):
                ensure_dir(os.path.join(output_dir, sub))
        if resume:
            if self.store is None:
                raise ValueError("resume requires a corpus_dir")
            self._restore_campaign()

    # -- historical aliases (the scheduler owns this state now) ---------

    @property
    def _corpus(self) -> list:
        return self.scheduler.arms

    @property
    def _base_stats(self) -> list:
        return self.scheduler.base_stats

    @property
    def _base_seed(self) -> Optional[bytes]:
        return self.scheduler.base_seed

    @_base_seed.setter
    def _base_seed(self, v: Optional[bytes]) -> None:
        self.scheduler.base_seed = v

    @property
    def _rotations(self) -> int:
        return self.scheduler.rotations

    @_rotations.setter
    def _rotations(self, v: int) -> None:
        self.scheduler.rotations = v

    @property
    def _fb_rng(self):
        return self.scheduler.rng

    # -- campaign persistence / resume (corpus/store.py) ----------------

    def _persist_campaign(self, force: bool = False,
                          now: bool = False) -> None:
        """Flush the campaign to the corpus store as ONE atomic
        checkpoint epoch (resilience/checkpoint.py): scheduler +
        counters + solver cache + event seq land together, so a kill
        at any instruction resumes consistent — there is no window
        where the corpus reflects crack verdicts the solver cache has
        forgotten.  ``now`` skips the interval gate but stays
        host-side; ``force`` (run end, including interrupts) adds the
        mutator/instrumentation resume states, whose serialization
        may join the device pipeline (never from the watchdog — the
        device is the thing that is stuck)."""
        if self.store is None:
            return
        t = time.time()
        if not force and not now and \
                t - self._last_persist < self._persist_interval:
            return
        self._last_persist = t
        base = self.scheduler.base_seed
        reg = self.telemetry.registry
        counters = dict(reg.counters)
        # run_seconds is normally folded at run_ended(); a hard kill
        # never gets there, so snapshot the LIVE active time — else a
        # resumed campaign divides restored execs by a near-zero
        # denominator and reports an absurd lifetime rate
        counters["run_seconds"] = reg.active_seconds()
        doc = {"campaign": {
            "version": 1,
            "scheduler_state": self.scheduler.state_dict(),
            "counters": counters,
            # arm stats ride in THIS snapshot (one atomic write per
            # interval); per-arm sidecars rewrite only on force — 256
            # fsyncs per 5s interval would stall the loop
            "arm_stats": {a.md5: [float(a[1]), float(a[2])]
                          for a in self.scheduler.arms},
            "fb_batches": self._fb_batches,
            "feedback": self.feedback,
            "base_seed_b64": (base64.b64encode(base).decode()
                              if base else None),
            "saved_at": t,
        }}
        if self.cracker is not None:
            doc["solver"] = self.cracker.cache
        if self.learn is not None:
            # model weights + version ride the SAME epoch: --resume
            # restores the trained model (labels rebuild from the
            # provenance sidecars, see _restore_campaign)
            doc["learn"] = self.learn.state_dict()
        if self.telemetry.events is not None:
            # the log's high-water at save time: resume anchors seq
            # at max(file tail, checkpoint) so a torn/lost log can
            # never regress the stream
            doc["event_seq"] = self.telemetry.events.next_seq
        if force:
            components = {}
            mut = getattr(self.driver, "mutator", None)
            instr = getattr(self.driver, "instrumentation", None)
            for which, comp in (("mutator", mut),
                                ("instrumentation", instr)):
                if comp is None:
                    continue
                try:
                    components[which] = comp.get_state()
                except NotImplementedError:
                    pass
                except Exception as e:
                    WARNING_MSG("%s state persist failed: %s",
                                which, e)
            doc["components"] = components
        self.store.save_checkpoint(doc)
        if force:
            for arm in self.scheduler.arms:
                self.store.update_meta(arm.to_entry())

    def _restore_campaign(self) -> None:
        """Rebuild scheduler arms, campaign counters and component
        states from the corpus store — ``--resume`` continues a killed
        campaign where it stopped."""
        entries = self.store.load()
        self.scheduler.load_entries(entries)
        for e in entries:
            self._seen["new_paths"].add(e.md5)
        # the output dir carries findings the store does not (bucket-
        # only new paths, crashes, hangs) — recover their md5 names so
        # dedup and the corpus_seen gauge continue exactly
        for kind in self._seen:
            d = os.path.join(self.output_dir, kind)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if len(name) == 32 and all(
                        c in "0123456789abcdef" for c in name):
                    self._seen[kind].add(name)
        st = self.store.load_state()
        if st:
            ss = st.get("scheduler_state") or {}
            if ss.get("scheduler") not in (None, self.scheduler.name):
                WARNING_MSG(
                    "resuming a %s-scheduled campaign with %s: arm "
                    "stats carry over, policy state starts fresh",
                    ss.get("scheduler"), self.scheduler.name)
            else:
                self.scheduler.load_state(ss)
            reg = self.telemetry.registry
            for k, v in (st.get("counters") or {}).items():
                reg.counters[k] = v
            # arm stats from the campaign snapshot (fresher than the
            # sidecars between force-persists)
            stats = st.get("arm_stats") or {}
            for a in self.scheduler.arms:
                if a.md5 in stats:
                    a[1], a[2] = stats[a.md5]
            self._fb_batches = int(st.get("fb_batches", 0))
            b64 = st.get("base_seed_b64")
            if b64:
                self.scheduler.base_seed = base64.b64decode(b64)
        mut = getattr(self.driver, "mutator", None)
        instr = getattr(self.driver, "instrumentation", None)
        for which, comp in (("mutator", mut),
                            ("instrumentation", instr)):
            if comp is None:
                continue
            state = self.store.load_component_state(which)
            if state is None:
                continue
            try:
                comp.set_state(state)
            except Exception as e:
                WARNING_MSG("%s state restore failed (fresh %s "
                            "state): %s", which, which, e)
        # no-event-seq-regression invariant: even if events.jsonl was
        # torn away or truncated, the checkpoint's high-water keeps
        # the resumed stream monotone for every cursor consumer
        ck = self.store.load_checkpoint()
        if ck and self.telemetry.events is not None:
            try:
                # event_seq is the checkpointed NEXT seq to mint
                self.telemetry.events.ensure_seq_at_least(
                    int(ck.get("event_seq", 0)))
            except (TypeError, ValueError):
                pass
        if self.learn is not None:
            if ck and isinstance(ck.get("learn"), dict):
                self.learn.load_state(ck["learn"])
            # labels rebuild from the persisted provenance sidecars
            # (entries without the field — pre-learn campaigns —
            # just contribute nothing); explicit reject negatives
            # restart empty, which only slows re-sharpening
            self.learn.bootstrap(entries, self._parent_bytes)
            self.telemetry.registry.gauge(
                "learn_model_version", self.learn.version)
            self.telemetry.registry.gauge(
                "learn_label_count", len(self.learn.labels))
        # -n counts THIS invocation's executions; restored lifetime
        # counters keep stats files and rates cumulative
        self._iter_base = int(self.stats.iterations)
        reg = self.telemetry.registry
        reg.gauge("corpus_seen", len(self._seen["new_paths"]))
        reg.gauge("corpus_arms", len(self.scheduler.arms))
        INFO_MSG("resumed campaign: %d stored entries, %d rotation "
                 "arms, %d execs done",
                 len(entries), len(self.scheduler.arms),
                 self.stats.iterations)

    # -- finding triage (reference fuzzer/main.c:393-417) ---------------

    def _record(self, kind: str, buf: bytes,
                digest: Optional[str] = None) -> bool:
        """Write a finding, deduped by input md5. Returns True if new.
        ``digest`` skips rehashing when the caller already has it."""
        digest = digest or md5_hex(buf)
        if digest in self._seen[kind]:
            return False
        self._seen[kind].add(digest)
        path = os.path.join(self.output_dir, kind, digest)
        if self.write_findings:
            if os.path.exists(path):  # left over from a previous run
                return False
            with self.telemetry.timer("fs_write"):
                write_buffer_to_file(path, buf)
            CRITICAL_MSG("Found a %s! Saving result to %s",
                         kind.rstrip("es") if kind != "crashes"
                         else "crash", path)
        else:
            CRITICAL_MSG("Found a %s (%s)",
                         kind.rstrip("es") if kind != "crashes"
                         else "crash", digest)
        return True

    def _debug_repro(self, buf: bytes) -> None:
        """Re-run a unique crash ONCE under the ptrace debug tier and
        log (and persist) signal-level details — the "re-run the
        interesting lanes under a debugger" post-pass: fuzzing speed
        stays batched, crash detail stays single-exec."""
        instr = self.driver.instrumentation
        if instr is not None and instr.device_backed:
            return  # device targets carry their detail in exit codes
        try:
            spec = self.driver._host_exec_spec()
        except (NotImplementedError, KeyError):
            return
        try:
            if self._dbg is None:
                import json as _json
                from ..instrumentation.debug import DebugInstrumentation
                # inherit execution conditions from the batched tier —
                # a slow or rlimit-dependent crash must re-run under
                # the same timeout/mem_limit to reproduce
                opts = {}
                for key in ("timeout", "mem_limit"):
                    if instr is not None and key in getattr(
                            instr, "options", {}):
                        opts[key] = instr.options[key]
                self._dbg = DebugInstrumentation(
                    _json.dumps(opts) if opts else None)
            if spec.get("use_stdin"):
                self._dbg.enable(buf, cmd_line=spec["cmd_line"])
            else:
                write_buffer_to_file(spec["input_file"], buf)
                self._dbg.enable(None, cmd_line=spec["cmd_line"])
            desc = self._dbg.crash_description()
            CRITICAL_MSG("crash triage: %s", desc)
            if self.write_findings:
                write_buffer_to_file(
                    os.path.join(self.output_dir, "crashes",
                                 md5_hex(buf) + ".info"),
                    (desc + "\n").encode())
        except Exception as e:  # triage detail must never stop fuzzing
            WARNING_MSG("debug triage failed: %s", e)

    _NO_CREDIT = object()   # credit sentinel: None credits the base seed

    def _parent_bytes(self, parent: Optional[str]) -> Optional[bytes]:
        """Resolve a lineage parent key to its input bytes: the base
        seed, a live rotation arm, or (last) the corpus store entry
        on disk.  None when unresolvable — learn labeling then skips
        the sample rather than guessing."""
        if parent in (None, "base"):
            base = self.scheduler.base_seed
            if base is not None:
                return base
            mut = getattr(self.driver, "mutator", None)
            return getattr(mut, "seed_bytes", None)
        for a in self.scheduler.arms:
            if getattr(a, "md5", None) == parent:
                return a[0]
        if self.store is not None:
            try:
                with open(self.store.entry_path(parent), "rb") as f:
                    return f.read()
            except OSError:
                return None
        return None

    def _learn_admission(self, arm: Arm, buf: bytes,
                         parent: str) -> None:
        """Label one admission for the learn tier and attach the
        mutation-provenance record to the arm (it rides into the
        entry sidecar).  Best-effort by design — a failed label must
        never block an admission."""
        if self.learn is None:
            return
        pbuf = self._parent_bytes(parent)
        if not pbuf:
            return
        mut = getattr(self.driver, "mutator", None)
        stage = None
        stage_fn = getattr(mut, "stage_name", None)
        if stage_fn is not None:
            try:
                stage = stage_fn()
            except Exception:
                stage = None
        arm.provenance = self.learn.note_admission(
            parent or "base", pbuf, buf,
            getattr(mut, "name", "?"), stage)

    def _admit_arm(self, buf: bytes, digest: str, parent: str,
                   credit=_NO_CREDIT) -> None:
        """The ADMISSION stage of triage, split out so it is
        device-ownable (ROADMAP item 1): mint a corpus arm for an
        edge-novel finding — signer, store write-through, sync note,
        scheduler admission + find credit.  Shared by host-side lane
        triage and the --generations admission replay, which feeds
        the device ring's decisions back through this same contract
        so store/arms/events stay byte-identical in shape to the
        host loop's.  Never mints a duplicate arm (resume replays and
        ring replays re-present known digests)."""
        reg = self.telemetry.registry
        arm = Arm(buf, parent=parent, discovered=time.time(),
                  tier=self.tier_tag)
        if self._signer is not None:
            try:
                arm.sig = self._signer(buf)
            except Exception as e:
                WARNING_MSG("corpus signer failed: %s", e)
        # stateful session tier: record the entry's state x edge
        # signature in its sidecar (one pure side execution through
        # the session scan — admissions are rare, the hot path never
        # pays it; kb-corpus and sync consumers read it back)
        ssig_fn = getattr(getattr(self.driver, "instrumentation",
                                  None), "state_signature", None)
        if ssig_fn is not None:
            try:
                arm.state_sig = ssig_fn(buf)
            except Exception as e:
                WARNING_MSG("state signature failed: %s", e)
        # learn tier: positive labels + the provenance sidecar record
        # (mutator id, stage, mutated-byte bitmap) BEFORE the store
        # write-through so the sidecar carries it
        self._learn_admission(arm, buf, parent)
        if self.store is not None and not os.path.exists(
                self.store.entry_path(digest)):
            arm.seq = self.store.next_seq()
            with self.telemetry.timer("fs_write"):
                self.store.put(arm.to_entry())
        if self.sync is not None:
            self.sync.note_entry(arm.to_entry())
        if self.feedback and not any(
                getattr(a, "md5", None) == digest
                for a in self.scheduler.arms):
            # admission evicts the oldest arm beyond the cap
            # (rotation only — the store keeps it); the ENTRY-object
            # credit pointers stay valid regardless
            self.scheduler.admit(arm)
            if credit is not self._NO_CREDIT:
                # credit the arm whose candidates PRODUCED this find
                # (None = the base seed; a capped-out arm's entry may
                # already be off the list — the credit is then a
                # harmless write to a dead object)
                self.scheduler.credit_find(credit)
            reg.gauge("corpus_arms", len(self.scheduler.arms))

    def _triage_lane(self, status: int, new_path: int, buf: bytes,
                     unique_crash: bool = False,
                     unique_hang: bool = False,
                     admit: bool = True) -> None:
        """VERDICT + RECORD stages of one lane's triage (counters,
        finding files, events, dedup), then — unless ``admit`` is
        False — the admission stage for edge-novel lanes.  The
        generations drain passes ``admit=False``: the DEVICE already
        made the admission decisions, and _drain_generations replays
        its ledger through _admit_arm instead."""
        s = self.stats
        if status == FUZZ_CRASH:
            s.crashes += 1
            s.unique_crashes += int(unique_crash)
            digest = md5_hex(buf)
            self._record("crashes", buf, digest)
            if unique_crash:
                # event contract (telemetry/events.py): one crash
                # event per unique_crashes increment, raw total riding
                # along — AFL saves crashes at the same granularity
                self.telemetry.event(
                    "crash", md5=digest,
                    crashes=int(s.crashes),
                    unique_crashes=int(s.unique_crashes))
                if self.hybrid is not None:
                    # cross-tier triage (docs/HYBRID.md): one native
                    # validation per UNIQUE finding — the dedup above
                    # is the rate limit
                    self.hybrid.enqueue(
                        "crash", buf, digest,
                        parent=getattr(self._credit_arm, "md5", None),
                        proxy_status=status)
                if self.debug_triage:
                    self._debug_repro(buf)
        elif status == FUZZ_HANG:
            s.hangs += 1
            s.unique_hangs += int(unique_hang)
            digest = md5_hex(buf)
            self._record("hangs", buf, digest)
            if unique_hang:
                self.telemetry.event(
                    "hang", md5=digest, hangs=int(s.hangs),
                    unique_hangs=int(s.unique_hangs))
                if self.hybrid is not None:
                    self.hybrid.enqueue(
                        "hang", buf, digest,
                        parent=getattr(self._credit_arm, "md5", None),
                        proxy_status=status)
        elif status == FUZZ_ERROR:
            s.errors += 1
            WARNING_MSG("target exec error on iteration %d", s.iterations)
        if new_path > 0:
            s.new_paths += 1
            reg = self.telemetry.registry
            reg.rate("new_paths", 1)
            digest = md5_hex(buf)
            recorded = self._record("new_paths", buf, digest)
            # one new_path event per counter increment: the event
            # count reconciles exactly with fuzzer_stats paths_total
            self.telemetry.event(
                "new_path", md5=digest,
                edge_novel=bool(new_path == 2),
                new_paths=int(s.new_paths))
            # corpus_seen: distinct new-path inputs ever recorded;
            # corpus_arms: entries actually in rotation (they used to
            # be conflated in one misleading corpus_size gauge)
            reg.gauge("corpus_seen", len(self._seen["new_paths"]))
            # corpus feedback keeps only EDGE-novel findings (ret 2:
            # a brand-new edge, not just a new hit-count bucket) —
            # bucket-only findings are overwhelmingly shallow
            # variants that dilute the rotation.  ``heal``: a kill
            # can land BETWEEN the finding write and the store
            # write-through, leaving the finding on disk (so the
            # resume replay dedups it, recorded=False) but absent
            # from the store — re-admit exactly that case so no
            # admission is ever lost, without ever minting a
            # duplicate arm (store md5 + rotation scan)
            heal = (not recorded and self.store is not None
                    and new_path == 2
                    and not os.path.exists(
                        self.store.entry_path(digest))
                    and not any(getattr(a, "md5", None) == digest
                                for a in self.scheduler.arms))
            if (recorded or heal) and new_path == 2 and admit and \
                    (self.feedback or self.store is not None):
                self._admit_arm(
                    buf, digest,
                    parent=getattr(self._credit_arm, "md5",
                                   None) or "base",
                    credit=self._credit_arm)
            elif recorded and new_path == 1 and self.learn is not None:
                # bucket-only new path — interesting but NOT admitted:
                # the admission stream's reject, labeled negative
                # (budget-capped inside the tier).  Parent = the
                # generating arm (the ring's base slot / the base
                # seed in -G drains — best-effort, docs/LEARN.md)
                pkey = getattr(self._credit_arm, "md5",
                               None) or "base"
                pbuf = self._parent_bytes(pkey)
                if pbuf:
                    self.learn.note_reject(pkey, pbuf, buf)

    # -- loops ----------------------------------------------------------

    def run(self, n_iterations: int = -1) -> FuzzStats:
        """Run ``n_iterations`` executions (-1 = until the mutator
        exhausts). Uses the batched path when available."""
        if self.stats.iterations == 0:
            # baseline snapshot: plot_data's first row is all-zero so
            # the sum of row deltas equals the cumulative counters
            self.telemetry.flush()
        self.telemetry.registry.run_started()
        try:
            if self.driver.supports_batch:
                if self.generations > 1:
                    self._run_generations(n_iterations)
                else:
                    self._run_batched(n_iterations)
            else:
                self._run_single(n_iterations)
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()
            self._profile_stop()
            self._update_state_gauges(force=True)
            self.telemetry.registry.run_ended()
            self.telemetry.flush()
            # flight recorder: the span ring exports on every run
            # end — interrupts included, with still-open spans closed
            # synthetically — so a killed campaign leaves a readable
            # trace.json next to events.jsonl
            if self.telemetry.trace is not None and self.write_findings:
                self.telemetry.export_trace(
                    os.path.join(self.output_dir, "trace.json"))
            # full campaign snapshot (scheduler + component states):
            # runs on clean exits AND interrupts, so --resume
            # continues exactly here
            self._persist_campaign(force=True)
            # hybrid bridge drain BEFORE the forced sync: verdicts
            # from in-flight native validations must land in sidecars
            # and the event stream before the final push
            if self.hybrid is not None:
                self.hybrid.finish(self)
                # after the drain: every verdict (and gap report) has
                # folded, so the run-end repair sees the full
                # counterexample set
                if self.repairer is not None:
                    self.repairer.finish(self)
            # one forced sync round AFTER the drain: entries triaged
            # there (a short campaign triages everything in it) must
            # still reach the fleet
            if self.sync is not None:
                self.sync.maybe_sync(self, force=True)
        INFO_MSG("Ran %d iterations in %.1f seconds "
                 "(%.0f execs/s lifetime, %.0f recent)",
                 self.stats.iterations, self.stats.elapsed,
                 self.stats.execs_per_sec, self.stats.execs_per_sec_ema)
        return self.stats

    def _remaining(self, n_iterations: int) -> int:
        """Executions still owed to THIS run() call: a resumed
        campaign restores lifetime counters, so -n counts from the
        resume point, not from zero."""
        if n_iterations < 0:
            return 2**62 - self.stats.iterations
        return n_iterations - (self.stats.iterations - self._iter_base)

    @staticmethod
    def _compact_rows(compact):
        """{batch_lane: report_row} for a CompactReport, or None when
        the report overflowed (caller falls back to a full pull).
        ``count`` is a scalar (single-chip: valid rows are the first
        count) or a per-dp-shard vector (mesh campaigns: each shard
        owns a cap-row block of the report, lane ids are global)."""
        counts = np.asarray(compact.count).reshape(-1)
        idx = np.asarray(compact.idx)
        cap = len(idx) // len(counts)
        if (counts > cap).any():
            return None
        rows = {}
        for s, c in enumerate(counts):
            for j in range(int(c)):
                r = s * cap + j
                rows[int(idx[r])] = r
        return rows

    def _triage_batch(self, out, room: int, done_through: int,
                      packed=None, arm: Optional[list] = None,
                      lane: Optional[int] = None) -> None:
        """``done_through`` is the global iteration count as of THIS
        batch — with pipelining, stats.iterations runs ahead of the
        batch being triaged, so logs must not read it.  ``packed`` is
        the device-side verdict byte built by _prefetch; when set,
        the big per-lane arrays never cross to the host unless this
        batch actually has interesting lanes.  ``lane`` is the flight
        recorder's pipeline slot for this batch: triage spans land on
        the SAME lane that dispatched it, closing its in-flight span
        (an ASYNC pair — triage can fire while unrelated sync spans
        are open on this lane, which stack-matched B/E would cross)."""
        tr = self.telemetry.trace
        if tr is not None and lane is not None:
            tr.lane = lane
            tr.async_end("in_flight", lane)
        self._credit_arm = arm
        res = out.result
        timer = self.telemetry.timer
        if packed is not None:
            from ..instrumentation.base import unpack_verdicts
            with self._wd_guard("host_transfer"), \
                    timer("host_transfer"):
                # chaos seam INSIDE the guard: a "hang" here is
                # exactly what a wedged device looks like from the
                # host — a lazy array that never materializes
                chaos_point("device_wait")
                pk = np.asarray(packed)      # prefetched: cache hit
            statuses, new_paths, uc, uh = unpack_verdicts(pk)
            statuses = statuses.astype(np.int32)
        else:
            # host-backed results are already numpy (instant); device
            # results without a prefetched pack block here — exactly
            # the wait this stage exists to expose
            with self._wd_guard("host_transfer"), \
                    timer("host_transfer"):
                chaos_point("device_wait")
                statuses = np.asarray(res.statuses)
                new_paths = np.asarray(res.new_paths)
            uc = uh = None
        interesting = np.flatnonzero(
            (statuses[:room] != FUZZ_NONE) | (new_paths[:room] > 0))
        if len(interesting):
            with timer("triage"):
                self._triage_interesting(out, interesting, statuses,
                                         new_paths, uc, uh)
        DEBUG_MSG("batch done: %d iterations total", done_through)

    def _triage_interesting(self, out, interesting, statuses,
                            new_paths, uc, uh) -> None:
        """Pull and record the interesting lanes of one batch (the
        ``triage`` stage: compact-report reads, lane gathers, dedup +
        finding writes)."""
        res = out.result
        rows = None
        if out.compact is not None:
            rows = self._compact_rows(out.compact)
            if rows is not None:
                inputs = np.asarray(out.compact.bufs)
                lengths = np.asarray(out.compact.lens)
        if rows is None:                 # full pull (host results,
            inputs = np.asarray(out.inputs)   # or compact overflow)
            lengths = np.asarray(out.lengths)
        if uc is None:
            uc = np.asarray(res.unique_crashes)
            uh = np.asarray(res.unique_hangs)
        for i in interesting:
            if rows is not None:
                r = rows.get(int(i))
                if r is None:
                    # device-side interesting predicate drifted
                    # from the host one; don't lose the rest of
                    # the pipelined drain — fall back to the full
                    # candidate tensors for this batch
                    WARNING_MSG(
                        "compact report missing lane %d; pulling "
                        "full batch", int(i))
                    inputs = np.asarray(out.inputs)
                    lengths = np.asarray(out.lengths)
                    rows = None
                    r = i
            else:
                r = i
            buf = inputs[r, :int(lengths[r])].tobytes()
            self._triage_lane(int(statuses[i]), int(new_paths[i]),
                              buf, bool(uc[i]), bool(uh[i]))

    # batches kept in flight before results are pulled to the host:
    # device backends return LAZY arrays, so later batches' work is
    # enqueued before earlier results transfer — dispatch/transfer
    # latency (severe over remote-tunnel devices) overlaps compute
    # (SURVEY hard part: "double-buffer batches, async dispatch").
    # Depth is sized for a remote-tunnel device: D2H RTT is ~150ms
    # (observed spiking to ~1s under load) regardless of size, while
    # a 16k-lane step is ~25ms — enough batches must be in flight for
    # the prefetched copies (below) to land before their triage turn.
    # The cost of extra depth is just per-batch handles + drain time.
    PIPELINE_DEPTH = 24

    @staticmethod
    def _prefetch(out):
        """Minimize what crosses the device->host tunnel per batch
        and start the copy WITHOUT blocking.  Two pathologies on a
        remote TPU: ~150ms RTT per sync transfer (np.asarray) and
        ~23MB/s bandwidth.  So: (1) bit-pack the four verdict arrays
        into ONE uint8 lane byte on device (32KB/batch instead of
        ~1MB), (2) issue copy_to_host_async at enqueue time so the
        copy lands while in-flight batches compute, and (3) leave the
        candidate tensors on device — triage gathers just the
        interesting rows.  Returns the packed device array, or None
        for host-backed results (already numpy)."""
        res = out.result
        if not hasattr(res.statuses, "copy_to_host_async"):
            return None
        from ..instrumentation.base import pack_verdicts
        packed = pack_verdicts(res.statuses, res.new_paths,
                               res.unique_crashes, res.unique_hangs)
        packed.copy_to_host_async()
        if out.compact is not None:
            for arr in out.compact:
                fn = getattr(arr, "copy_to_host_async", None)
                if fn is not None:
                    fn()
        return packed

    # -- opt-in device profiling (--profile-device) ---------------------

    def _profile_start(self) -> None:
        """Start a jax.profiler device capture into the output dir
        (next to the host trace.json).  Degrades to a warning — like
        every observability path."""
        try:
            import jax
            d = os.path.join(self.output_dir, "device_trace")
            ensure_dir(d)
            jax.profiler.start_trace(d)
            self._prof_active = True
            INFO_MSG("device profiling: capturing %d batches to %s",
                     self.profile_device, d)
        except Exception as e:
            WARNING_MSG("device profiling unavailable: %s", e)
            self.profile_device = 0

    def _profile_stop(self) -> None:
        if not self._prof_active:
            return
        self._prof_active = False
        self.profile_device = 0
        try:
            import jax
            jax.profiler.stop_trace()
            INFO_MSG("device profile written to %s",
                     os.path.join(self.output_dir, "device_trace"))
        except Exception as e:
            WARNING_MSG("device profile stop failed: %s", e)

    def _update_state_gauges(self, force: bool = False) -> None:
        """Stateful session tier: refresh the state-coverage gauges
        (state_cov_pairs / state_cov_states) from the live virgin
        map and emit one state_cov event per high-water increase.
        Time-gated on the persist interval — the read syncs a tiny
        device array, so it must never ride the per-batch hot path.
        A no-op when the tier is off."""
        instr = getattr(self.driver, "instrumentation", None)
        fn = getattr(instr, "state_coverage_stats", None)
        if fn is None:
            return
        t = time.time()
        if not force and t - self._state_gauge_t < \
                self._persist_interval:
            return
        self._state_gauge_t = t
        try:
            st = fn()
        except Exception as e:    # observability must never stop it
            WARNING_MSG("state coverage stats failed: %s", e)
            return
        if st is None:
            return                # tier off on this instrumentation
        pairs, states = st
        reg = self.telemetry.registry
        reg.gauge("state_cov_pairs", pairs)
        reg.gauge("state_cov_states", states)
        if pairs > self._state_pairs_seen:
            self._state_pairs_seen = pairs
            self.telemetry.event("state_cov", pairs=int(pairs),
                                 states=int(states))

    def _maybe_learn(self) -> None:
        """Between-dispatches learn-tier hook: train the saliency
        model when due (time- and label-gated inside the tier — the
        common case is one cheap check).  The train round runs on
        the accelerator while the in-flight fuzzing dispatches are
        still computing, which is the whole point of co-locating the
        model with the fuzzer."""
        if self.learn is None:
            return
        with self.telemetry.timer("learn"):
            self.learn.maybe_train(self.telemetry.registry,
                                   self.telemetry)

    def _wd_guard(self, stage: str):
        """Watchdog deadline over one blocking region (no-op without
        a watchdog installed)."""
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.guard(stage)

    def _watchdog_dump(self, stage: str, waited: float,
                       deadline: float) -> None:
        """Stall post-mortem, called from the WATCHDOG thread while
        the main thread is stuck: snapshot the in-flight pipeline
        lanes into <output>/watchdog_dump.json, overlay them on the
        flight recorder and export trace.json, then checkpoint the
        host-side campaign state (``now=True``, never ``force`` —
        component serialization could join the stuck pipeline)."""
        pend = []
        for item in list(self._pending or []):
            out, room, iters, packed, arm_entry, lane = item
            pend.append({"iterations": int(iters), "room": int(room),
                         "lane": lane,
                         "arm": getattr(arm_entry, "md5", None)})
        tr = self.telemetry.trace
        if tr is not None:
            for p in pend:
                tr.instant("watchdog_in_flight", args=p)
            if self.write_findings:
                self.telemetry.export_trace(
                    os.path.join(self.output_dir, "trace.json"))
        if self.write_findings:
            doc = {"t": time.time(), "stage": stage,
                   "waited_s": round(waited, 3),
                   "deadline_s": round(deadline, 3),
                   "iterations": int(self.stats.iterations),
                   "batch_seq": int(self._batch_seq),
                   "pending": pend}
            try:
                write_buffer_to_file(
                    os.path.join(self.output_dir,
                                 "watchdog_dump.json"),
                    json.dumps(doc, default=str).encode())
            except OSError as e:
                WARNING_MSG("watchdog dump write failed: %s", e)
        self._persist_campaign(now=True)

    def _trace_lane(self, tr) -> int:
        """Point the recorder at THIS batch's pipeline lane (one of
        PIPELINE_DEPTH slots, reused round-robin — a slot is free by
        the time it recurs because the pending deque caps at the
        depth) and return the lane id for the pending tuple."""
        slot = self._batch_seq % self.PIPELINE_DEPTH
        lane = slot
        tr.name_lane(lane, f"batch-{slot:02d}")
        tr.lane = lane
        return lane

    def _credit_period(self) -> None:
        """Close one feedback period: the scheduler decays every
        arm's stats and charges the period to the arm ENTRY that
        actually generated it — when the cap pops the active arm the
        index goes stale but the entry object is still the generator
        (the find credits go to the same object).

        ``feedback`` rides along as the period LENGTH in batches: the
        scheduler's decay is defined per batch, and one call here
        closes a whole -fb-batch period, so it compounds the factor
        (0.8**feedback) — see ``Scheduler.credit_period``."""
        self.scheduler.credit_period(self._active_entry, self.feedback)
        reg = self.telemetry.registry
        reg.gauge("corpus_arms", len(self.scheduler.arms))
        reg.gauge("corpus_favored", self.scheduler.favored_count())

    def _rotate_seed(self, mut) -> None:
        """Coverage-guided corpus feedback (beyond reference parity:
        the reference's equivalent is operators re-seeding campaigns
        from new_paths/ by hand or via manager jobs).

        WHICH seed fuzzes next is the scheduler's call
        (corpus/schedule.py — the default ``bandit`` policy is the
        greedy optimistic decay bandit this loop used to hard-code,
        ported verbatim; ``rare-edge`` and ``rr`` plug in through the
        same interface).  The loop keeps the mechanics: seed swaps
        hold the candidate buffer width so compiled steps never
        retrace (mutator.set_input(keep_length=True)), the walk
        position stays monotonic, and findings too wide for the
        buffer are dropped from rotation and retried."""
        self.scheduler.rotations += 1
        while True:
            best, cand = self.scheduler.select()
            if cand is None:
                return                # nothing schedulable
            try:
                it = mut.get_current_iteration()
                mut.set_input(cand, keep_length=True)
                # keep the walk position monotonic: set_input resets
                # it, but a re-visited seed must get FRESH candidate
                # keys, not replay the (seed, iteration) pairs it
                # already executed
                mut.iteration = it
                self._active_entry = (None if best is None
                                      else self.scheduler.arms[best])
                # learned mask source (host-driven loop): focus the
                # next period's mutation on the model's salient bytes
                # of the freshly rotated seed.  Mutually exclusive
                # with the crack stage's static edge_dep_mask (the
                # CLI enforces it); a None mask CLEARS — shaping must
                # never outlive the seed it was computed for.  The
                # installed mask stands the fused superbatch down,
                # exactly like the crack-stage masks (docs/LEARN.md).
                if self.learn is not None:
                    mut.set_focus_mask(
                        self.learn.focus_positions_for(cand),
                        pad_pow2=True)
                self.telemetry.event(
                    "scheduler_pick",
                    arm=(getattr(self._active_entry, "md5", None)
                         or "base"),
                    policy=self.scheduler.name,
                    rotation=int(self.scheduler.rotations))
                DEBUG_MSG("feedback: arm %s (%s), %d-byte input",
                          best, self.scheduler.name, len(cand))
                return
            except ValueError:       # finding wider than the buffer
                if best is None:
                    return            # base seed itself doesn't fit
                self.scheduler.drop(best)

    def _resolve_accumulate(self) -> int:
        """Effective superbatch depth K.  Auto engages only on the
        fused device path; corpus feedback requires the rotation
        cadence to land on superbatch boundaries (K divides
        ``feedback``), else K degrades to the largest divisor."""
        k = self.accumulate if self.accumulate > 0 \
            else self.ACCUMULATE_AUTO
        if k <= 1:
            return 1
        try:
            if not self.driver.supports_fused_multi():
                return 1
        except AttributeError:
            return 1
        if self.feedback:
            while k > 1 and self.feedback % k:
                k -= 1
            if self.accumulate > 1 and k != self.accumulate \
                    and not self._accum_warned:
                # an explicit -K is being overridden — say so (this
                # used to degrade silently)
                self._accum_warned = True
                WARNING_MSG(
                    "accumulate: explicit -K %d degraded to %d — a "
                    "superbatch may not stride a corpus-feedback "
                    "rotation boundary, so K must divide the "
                    "feedback cadence (-fb %d); pass a -K that "
                    "divides -fb (or adjust -fb) to keep it",
                    self.accumulate, k, self.feedback)
        return k

    def _run_superbatch(self, k: int, pending, depth) -> None:
        """Execute K fused batches in one device dispatch and enqueue
        K per-step triage entries over shared stacked host pulls."""
        from ..instrumentation.base import CompactReport
        from ..drivers.base import BatchOutcome
        b = self.batch_size
        tr = self.telemetry.trace
        if tr is not None:
            # the fused dispatch is ONE device call covering k
            # batches; its execute span lands on the first slot
            self._trace_lane(tr)
        with self._wd_guard("dispatch"):
            chaos_point("device_dispatch")
            packed, bufs, lens, compact = \
                self.driver.test_batch_fused_multi(b, k)
        ph = _StackedRows(packed)
        idxh, sbh, slh, cnth = (_StackedRows(a) for a in compact)
        for j in range(k):
            self.stats.iterations += b
            self._fb_batches += 1
            lane = None
            if tr is not None:
                lane = self._trace_lane(tr)
                tr.async_begin("in_flight", lane,
                               args={"batch": self._batch_seq,
                                     "n": b})
            self._batch_seq += 1
            out = BatchOutcome(
                result=None, inputs=bufs[j], lengths=lens[j],
                compact=CompactReport(idx=idxh.row(j), bufs=sbh.row(j),
                                      lens=slh.row(j),
                                      count=cnth.row(j)))
            pending.append((out, b, self.stats.iterations, ph.row(j),
                            self._active_entry, lane))
            if len(pending) >= depth:
                self._triage_batch(*pending.popleft())
        reg = self.telemetry.registry
        reg.rate("execs", b * k)
        reg.gauge("pipeline_depth", len(pending))
        self._maybe_learn()
        self.telemetry.maybe_flush()
        self._persist_campaign()
        if self.sync is not None:
            self.sync.maybe_sync(self)
        if self.hybrid is not None:
            self.hybrid.fold(self)

    def _drain_ready(self, pending) -> None:
        """Triage every leading pending batch whose device results are
        already computed (non-blocking is_ready probe): keeps the
        corpus fresh at rotation boundaries without stalling the
        pipeline on a transfer that hasn't landed."""
        while pending:
            packed = pending[0][3]
            holder = getattr(packed, "_holder", None)
            arr = packed if holder is None else holder.dev
            probe = getattr(arr, "is_ready", None)
            if probe is not None:
                try:
                    if not probe():
                        return
                except Exception:
                    pass
            self._triage_batch(*pending.popleft())

    def _run_batched(self, n_iterations: int) -> None:
        from collections import deque
        mut = self.driver.mutator
        pending: "deque" = deque()
        self._pending = pending         # watchdog-dump visibility
        # sharded campaigns execute fixed whole-mesh batches; a tail
        # smaller than the quantum is skipped with a warning instead
        # of dying mid-run
        quantum = getattr(self.driver, "batch_quantum", 1)
        # corpus feedback no longer caps the pipeline: finds are
        # credited to the arm that GENERATED the batch (lag-safe),
        # so rotation reads the corpus as-of-now without draining
        depth = self.PIPELINE_DEPTH
        accumulate = self._resolve_accumulate()
        if self.feedback and self._base_seed is None and \
                getattr(mut, "seed_bytes", None):
            # the baseline seed anchors the rotation: every other
            # rotation returns to it so findings ADD exploration
            # frontiers without halving time on the proven seed
            self._base_seed = mut.seed_bytes
        try:
            while True:
                room = min(self._remaining(n_iterations),
                           mut.remaining(), self.batch_size)
                if room <= 0:
                    break
                if room < quantum:
                    WARNING_MSG(
                        "stopping %d iterations early: the mesh "
                        "executes whole %d-lane batches (-n should "
                        "be a multiple of -b)", room, quantum)
                    break
                # cadence counter lives on self: a caller sampling
                # coverage with repeated short run() calls must not
                # reset the rotation clock
                if (self.feedback and self._fb_batches
                        and self._fb_batches % self.feedback == 0):
                    # freshen the corpus without stalling; while it is
                    # still EMPTY, force one pull — but only of an
                    # entry at least a full cadence old, whose async
                    # copy has had a cadence of compute time to land
                    # (a finding-free campaign then pays ~nothing per
                    # boundary instead of a fresh-transfer RTT)
                    with self.telemetry.timer("corpus_feedback"):
                        self._drain_ready(pending)
                        if (not self._corpus and pending
                                and self.stats.iterations
                                - pending[0][2]
                                >= self.feedback * self.batch_size):
                            self._triage_batch(*pending.popleft())
                        self._credit_period()
                        if self._corpus:
                            self._rotate_seed(mut)
                # plateau crack: when no new paths for N batches,
                # solve uncovered static edges into inputs and inject
                # them ahead of the scheduler (the injected execs
                # triage synchronously — the pipeline keeps flowing).
                # Ready batches are triaged first so the plateau
                # verdict reads coverage as fresh as non-blocking
                # probes allow (the detector itself also pads its
                # window by the pipeline depth).
                if self.cracker is not None:
                    with self.telemetry.timer("corpus_feedback"):
                        self._drain_ready(pending)
                        self.cracker.maybe_crack(self)
                # conformance repair rides the same plateau signal:
                # coverage stalls are when spending host time on the
                # accumulated proxy-gap counterexamples is free
                if self.repairer is not None:
                    self.repairer.maybe_repair(self)
                # opt-in device capture: starts at the next dispatch,
                # stops after profile_device batches
                if self.profile_device and not self._prof_active:
                    self._profile_start()
                # K-step accumulation may not stride over a feedback
                # rotation boundary (the check above only fires at
                # loop top): engage only when the next boundary is at
                # least K batches away — _fb_batches can enter run()
                # misaligned after short per-batch runs
                if self.feedback:
                    gap = (-self._fb_batches) % self.feedback \
                        or self.feedback
                else:
                    gap = accumulate
                if (accumulate > 1 and gap >= accumulate
                        and self._remaining(n_iterations)
                        >= accumulate * self.batch_size
                        and mut.remaining()
                        >= accumulate * self.batch_size
                        # re-checked per batch: a crack-stage focus
                        # mask drops fused eligibility (the fused
                        # kernel generates candidates itself and
                        # would silently ignore the mask)
                        and self.driver.supports_fused_multi()):
                    # K-step device-side accumulation: one transfer
                    # set per K batches
                    self._run_superbatch(accumulate, pending, depth)
                    if self._prof_active:
                        self.profile_device -= accumulate
                        if self.profile_device <= 0:
                            self._profile_stop()
                    continue
                self._fb_batches += 1
                # a smaller tail batch would change tensor shapes and
                # force a full XLA recompile; the driver pads to
                # batch_size with duplicate lanes (coverage no-ops)
                # and we triage only the first `room` real lanes
                # the NEXT batch's size, so host drivers prefetch
                # exactly what will be requested (a full-size stash
                # before a smaller tail would be discarded as stale)
                nxt = min(self._remaining(n_iterations) - room,
                          mut.remaining() - room, self.batch_size)
                lane = None
                tr = self.telemetry.trace
                if tr is not None:
                    # mutate/execute spans (driver stage timer) land
                    # on this batch's pipeline lane
                    lane = self._trace_lane(tr)
                with self._wd_guard("dispatch"):
                    chaos_point("device_dispatch")
                    out = self.driver.test_batch(
                        room, pad_to=self.batch_size,
                        prefetch_next=max(nxt, 0))
                self.stats.iterations += room
                packed = self._prefetch(out)
                if tr is not None:
                    tr.async_begin("in_flight", lane,
                                   args={"batch": self._batch_seq,
                                         "n": room})
                self._batch_seq += 1
                if self._prof_active:
                    self.profile_device -= 1
                    if self.profile_device <= 0:
                        self._profile_stop()
                pending.append((out, room, self.stats.iterations,
                                packed, self._active_entry, lane))
                if len(pending) >= depth:
                    self._triage_batch(*pending.popleft())
                reg = self.telemetry.registry
                reg.rate("execs", room)
                reg.gauge("pipeline_depth", len(pending))
                self._update_state_gauges()
                self._maybe_learn()
                self.telemetry.maybe_flush()
                self._persist_campaign()
                if self.sync is not None:
                    self.sync.maybe_sync(self)
                if self.hybrid is not None:
                    self.hybrid.fold(self)
        finally:
            # findings in already-executed batches must survive an
            # interrupt (Ctrl-C on an infinite run) or a raise
            while pending:
                self._triage_batch(*pending.popleft())

    # -- device-resident generations (--generations) --------------------

    def _drain_generations(self, out, room, done_through, _packed,
                           _arm, lane) -> None:
        """Drain one G-generation dispatch: materialize the bounded
        findings ring + admission ledger (the ONLY device->host
        transfer in this mode), replay each interesting lane through
        the verdict/record triage stages, and replay the device's
        ring-admission decisions through the admission stage — in
        (generation, shard, lane) order, exactly the order host-driven
        triage would have seen them (shards iterate in dp order per
        generation, the global-lane order of the mesh loop's batch
        triage).  Ring overflow is counted (``findings_ring_drops``)
        and warned, never silent.

        Mesh dispatches arrive as a MeshGenerationOutcome: one
        findings ring + ledger PER dp shard, replayed through
        per-shard ``shard(d)`` views with (shard, slot)-keyed lineage
        mirrors — the replay is deterministic in shard order, so the
        findings/store/arms sets are independent of drain
        interleaving.

        With reseeding OFF the device made no admission decisions
        (the ledger is empty), so edge-novel ring lanes admit through
        the normal host path instead — otherwise a ``-fb 0`` campaign
        with a corpus store would silently skip the write-through the
        host-driven loop performs."""
        from ..instrumentation.base import unpack_verdicts
        tr = self.telemetry.trace
        if tr is not None and lane is not None:
            tr.lane = lane
            tr.async_end("in_flight", lane)
        timer = self.telemetry.timer
        if self.watchdog is not None:
            # the guarded wait below is on THIS dispatch: arm with
            # its own generation count, not the newest dispatch's —
            # a shrunken tail dispatch queued behind a full-G one
            # must not clamp the full-G drain to a 1-batch deadline
            self.watchdog.note_dispatch_scale(max(int(out.g), 1))
        with self._wd_guard("host_transfer"), timer("host_transfer"):
            # chaos seam INSIDE the guard: a "hang" here is what a
            # wedged device looks like from the host
            chaos_point("device_wait")
            h = out.materialize()
        reg = self.telemetry.registry
        # mesh outcomes carry a leading dp axis on every field EVEN
        # at dp=1, so the discriminator is the shard() view, never
        # the shard count
        shard_view = getattr(h, "shard", None)
        n_shards = int(getattr(h, "n_shards", 1) or 1)
        views = [shard_view(d) for d in range(n_shards)] \
            if shard_view is not None else [h]
        stored = [min(int(s.fr_ptr), int(s.cap)) for s in views]
        drops = sum(int(s.fr_ptr) - st
                    for s, st in zip(views, stored))
        if drops > 0:
            reg.count("findings_ring_drops", drops)
            WARNING_MSG(
                "generations: findings ring overflowed — %d "
                "interesting lanes dropped this dispatch (finding "
                "files/events under-report them; counters track the "
                "loss; raise jit_harness gen_findings_cap)", drops)
        verdicts = [unpack_verdicts(s.fr_pack[:st])
                    for s, st in zip(views, stored)]
        replay_adm = bool(self.feedback or self.store is not None)
        # reseeding off => the device ledger is empty by construction:
        # edge-novel ring lanes go through host-side admission, same
        # gates as the host-driven loop (with reseeding on the ledger
        # replay below owns admission and ring lanes must not)
        admit_ring = not self._gen_reseed
        self._credit_arm = None

        def replay_lane(d, ei):
            s = views[d]
            statuses, new_paths, ucs, uhs = verdicts[d]
            buf = s.fr_bufs[ei, :int(s.fr_len[ei])].tobytes()
            self._triage_lane(
                int(statuses[ei]), int(new_paths[ei]), buf,
                bool(ucs[ei]), bool(uhs[ei]), admit=admit_ring)

        with timer("triage"):
            ei = [0] * len(views)
            for j in range(int(h.g)):
                gid = int(h.gen0) + j
                for d, s in enumerate(views):
                    # this generation's interesting lanes first (each
                    # ring is (gen, lane)-ordered), then the shard's
                    # admissions
                    while ei[d] < stored[d] and \
                            int(s.fr_gen[ei[d]]) <= gid:
                        replay_lane(d, ei[d])
                        ei[d] += 1
                    if not replay_adm or not int(s.adm_raw[j]):
                        continue
                    adm_cap = s.adm_valid.shape[1]
                    parent = self._ring_mirror.get(
                        (d, int(s.sel[j])), "base")
                    for a in range(adm_cap):
                        if not int(s.adm_valid[j, a]):
                            continue
                        buf = s.adm_bufs[
                            j, a, :int(s.adm_len[j, a])].tobytes()
                        digest = md5_hex(buf)
                        self._admit_arm(buf, digest, parent=parent)
                        self._ring_mirror[
                            (d, int(s.adm_slot[j, a]))] = digest
                        self.telemetry.event(
                            "ring_admit", md5=digest,
                            slot=int(s.adm_slot[j, a]), gen=gid,
                            shard=d, parent=parent)
            for d, s in enumerate(views):
                while ei[d] < stored[d]:    # defensive: trailing rows
                    replay_lane(d, ei[d])
                    ei[d] += 1
        reg.gauge("gen_ring_filled",
                  sum(int(s.ring_filled.sum()) for s in views))
        DEBUG_MSG("generations dispatch done: %d iterations total",
                  done_through)

    def _run_generations(self, n_iterations: int) -> None:
        """The device-resident dispatch mode: each device call runs up
        to ``self.generations`` full generations (mutate -> execute ->
        triage -> ring reseed, ops/generations.py) and the host only
        drains findings + the admission ledger.  Double-buffered (a
        dispatch is G batches long, so depth 2 keeps the device fed);
        stands down to the host-driven loop — with a named warning —
        when the crack stage is active or the driver/mutator can't
        run the generation loop (same discipline as the superbatch
        path).  With corpus feedback off, device reseeding is off too
        and the candidate stream is bit-identical to the host loop."""
        from collections import deque
        drv = self.driver
        mut = drv.mutator
        g_max = max(int(self.generations), 1)
        reseed = bool(self.feedback)
        self._gen_reseed = reseed
        reg = self.telemetry.registry
        # mesh campaigns execute whole mesh batches per generation;
        # a tail smaller than the quantum stops the run with the same
        # warning discipline as the host-driven mesh loop
        quantum = getattr(drv, "batch_quantum", 1)
        stood_down = self.cracker is not None \
            or not drv.supports_batch_generations()
        pending: "deque" = deque()
        if not stood_down:
            self._pending = pending     # watchdog-dump visibility
            try:
                while True:
                    room = min(self._remaining(n_iterations),
                               mut.remaining(),
                               g_max * self.batch_size)
                    if room <= 0:
                        break
                    if room < quantum:
                        WARNING_MSG(
                            "stopping %d iterations early: the mesh "
                            "executes whole %d-lane batches (-n "
                            "should be a multiple of -b)", room,
                            quantum)
                        break
                    if not drv.supports_batch_generations():
                        stood_down = True   # mid-run state change
                        break
                    if self.profile_device and not self._prof_active:
                        self._profile_start()
                    if self.learn is not None:
                        # install the LIVE model weights for this
                        # dispatch's in-scan inference (per-
                        # generation masks with zero host
                        # involvement; a v0 model quantizes to
                        # all-ones — the parity regime)
                        drv.instrumentation.learn_params = \
                            self.learn.scan_params()
                    n_real = min(room, self.batch_size)
                    g_room = min(max(room // self.batch_size, 1),
                                 g_max)
                    # g is a STATIC jit argument: an arbitrary tail
                    # count would recompile the whole G-generation
                    # scan for one dispatch, so tails quantize down
                    # to a power of two — a campaign compiles at most
                    # log2(G) tail shapes, each reusable
                    g_eff = g_room if g_room == g_max \
                        else 1 << (g_room.bit_length() - 1)
                    if self.watchdog is not None:
                        # a G-generation dispatch legitimately waits
                        # ~G x one batch: scale the guard deadline
                        self.watchdog.note_dispatch_scale(g_eff)
                    lane = None
                    tr = self.telemetry.trace
                    if tr is not None:
                        lane = self._trace_lane(tr)
                    with self._wd_guard("dispatch"):
                        chaos_point("device_dispatch")
                        out = drv.test_batch_generations(
                            n_real, g_eff, pad_to=self.batch_size,
                            reseed=reseed)
                    self.stats.iterations += g_eff * n_real
                    self._fb_batches += g_eff
                    out.prefetch()
                    if tr is not None:
                        tr.async_begin(
                            "in_flight", lane,
                            args={"batch": self._batch_seq,
                                  "n": g_eff * n_real,
                                  "generations": g_eff})
                    self._batch_seq += 1
                    if self._prof_active:
                        self.profile_device -= g_eff
                        if self.profile_device <= 0:
                            self._profile_stop()
                    pending.append((out, g_eff * n_real,
                                    self.stats.iterations, None,
                                    None, lane))
                    if len(pending) >= 2:   # double buffer
                        self._drain_generations(*pending.popleft())
                    reg.rate("execs", g_eff * n_real)
                    reg.gauge("generations_per_dispatch", g_eff)
                    reg.gauge("pipeline_depth", len(pending))
                    if self.learn is not None and \
                            self.learn.version > 0:
                        # one learned mask per generation once the
                        # model has trained (v0 masks are all-ones
                        # — shaping hasn't started)
                        self.learn.masks_applied += g_eff
                    self._update_state_gauges()
                    self._maybe_learn()
                    self.telemetry.maybe_flush()
                    self._persist_campaign()
                    if self.sync is not None:
                        self.sync.maybe_sync(self)
                    if self.hybrid is not None:
                        self.hybrid.fold(self)
            finally:
                while pending:
                    self._drain_generations(*pending.popleft())
                if self.watchdog is not None:
                    self.watchdog.note_dispatch_scale(1)
        if stood_down:
            if not self._gen_warned:
                self._gen_warned = True
                reason = ("the crack stage injects host-side "
                          "candidates and focus masks"
                          if self.cracker is not None else
                          "the driver/mutator cannot run the device "
                          "generation loop (needs jit_harness + a "
                          "fused-capable mutator, no focus mask, no "
                          "edges mode; --mesh campaigns run the "
                          "sharded generation scan)")
                WARNING_MSG("--generations stood down: %s — running "
                            "the host-driven loop", reason)
            self._run_batched(n_iterations)

    def _run_single(self, n_iterations: int) -> None:
        instr = self.driver.instrumentation
        mut = self.driver.mutator
        # feedback cadence in execs: `feedback` batches' worth
        rotate_every = self.feedback * self.batch_size
        if rotate_every and self._base_seed is None and \
                getattr(mut, "seed_bytes", None):
            self._base_seed = mut.seed_bytes
        reg = self.telemetry.registry
        while self._remaining(n_iterations) > 0:
            if (rotate_every and self.stats.iterations
                    and self.stats.iterations % rotate_every == 0):
                with self.telemetry.timer("corpus_feedback"):
                    self._credit_period()
                    if self._corpus:
                        self._rotate_seed(mut)
            # single-exec path: --profile-device counts each exec as
            # one "batch" (the flag must not silently no-op here)
            if self.profile_device and not self._prof_active:
                self._profile_start()
            with self._wd_guard("execute"), \
                    self.telemetry.timer("execute"):
                chaos_point("device_dispatch")
                result = self.driver.test_next_input()
            if result is None:  # mutator exhausted (reference -2)
                INFO_MSG("mutator exhausted after %d iterations",
                         self.stats.iterations)
                break
            if self._prof_active:
                self.profile_device -= 1
                if self.profile_device <= 0:
                    self._profile_stop()
            self.stats.iterations += 1
            reg.rate("execs", 1)
            buf = self.driver.get_last_input() or b""
            self._credit_arm = self._active_entry
            with self.telemetry.timer("triage"):
                self._triage_lane(result, instr.is_new_path(), buf,
                                  instr.last_unique_crash(),
                                  instr.last_unique_hang())
            self._maybe_learn()
            self.telemetry.maybe_flush()
            self._persist_campaign()
            if self.sync is not None:
                self.sync.maybe_sync(self)
            if self.hybrid is not None:
                self.hybrid.fold(self)
