"""The plateau auto-repair stage — counterexample-guided proxy
repair inside a running hybrid campaign (``--auto-repair``).

The crack stage (crack.py) spends plateaus extending COVERAGE; this
stage spends them repairing CONFORMANCE: when the loop plateaus and
the hybrid bridge has accumulated NEW proxy-gap reports since the
last attempt, run the bounded repair pass (analysis/repair.py) over
``<output>/proxy_gaps/``.  A verified patch is saved as a loadable
``.npz``, registered as ``<binding>+repaired`` after mandatory
native re-certification, written to the repair ledger (the
conformance lint's consumed-set), and folded back into the gap
entries' corpus sidecars.  An ``unrepairable`` verdict is recorded
just as loudly — counters, event, ledger — never retried in a hot
loop (each attempt re-arms only when the gap set GROWS).

The running campaign keeps fuzzing the ORIGINAL proxy either way:
swapping programs mid-flight would invalidate the coverage map, the
scheduler's arms and every cached trace.  The repaired binding is
for the NEXT campaign — which is why the install is registry-level
and the artifact lands on disk.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from ..utils.logging import INFO_MSG, WARNING_MSG


class ProxyRepairer:
    """Owns the plateau trigger and the repair-attempt bookkeeping
    for ONE hybrid campaign."""

    def __init__(self, bridge, *, plateau_batches: int = 16,
                 apply: bool = True):
        self.bridge = bridge
        self.plateau_batches = max(int(plateau_batches), 1)
        #: save + install + ledger on a repaired verdict (tests turn
        #: this off to keep the registry pristine)
        self.apply = bool(apply)
        self.attempts = 0
        self.last_status: Optional[str] = None
        self._last_new_paths = -1
        self._progress_iter = 0
        #: bridge.proxy_gaps at the last attempt: re-arm only when
        #: the counterexample set GROWS (an unrepairable verdict on
        #: the same evidence would just repeat)
        self._gaps_at_attempt = 0

    # -- the plateau trigger (the cracker's padded-window discipline) --

    def maybe_repair(self, fuzzer) -> None:
        s = fuzzer.stats
        if s.new_paths != self._last_new_paths:
            self._last_new_paths = s.new_paths
            self._progress_iter = s.iterations
            return
        depth = getattr(fuzzer, "PIPELINE_DEPTH", 0)
        window = (self.plateau_batches + depth) * fuzzer.batch_size
        if s.iterations - self._progress_iter < window:
            return
        self._progress_iter = s.iterations      # re-arm the window
        if self.bridge.proxy_gaps <= self._gaps_at_attempt:
            return          # no new counterexamples since last try
        self.repair(fuzzer)

    def finish(self, fuzzer) -> None:
        """Run-end attempt: gaps that accumulated after the last
        plateau still get consumed (called after bridge.finish(), so
        the queue is drained and every verdict has folded)."""
        if self.bridge.proxy_gaps > self._gaps_at_attempt:
            self.repair(fuzzer)

    # -- the repair itself ---------------------------------------------

    def repair(self, fuzzer) -> Optional[Dict[str, Any]]:
        """One bounded repair pass; returns the kbz-proxy-repair-v1
        result (None when the pass itself failed)."""
        from ..analysis.repair import (
            run_repair, save_patched_program, write_repair_ledger,
        )

        gaps_dir = os.path.join(fuzzer.output_dir, "proxy_gaps")
        self._gaps_at_attempt = self.bridge.proxy_gaps
        self.attempts += 1
        reg = fuzzer.telemetry.registry
        reg.count("repair_attempts")
        t0 = time.time()
        try:
            result, patched = run_repair(self.bridge.binding,
                                         gaps_dir)
        except Exception as e:      # repair must never kill the loop
            WARNING_MSG("proxy repair pass died: %s", e)
            reg.count("repair_errors")
            return None
        status = result["status"]
        self.last_status = status
        if status == "repaired":
            reg.count("repair_repaired")
        elif status == "unrepairable":
            reg.count("repair_unrepairable")
        if self.apply and status != "no-gaps":
            write_repair_ledger(gaps_dir, result)
        if status == "repaired" and patched is not None \
                and self.apply:
            out = os.path.join(
                gaps_dir, f"repaired_{self.bridge.binding.name}.npz")
            try:
                save_patched_program(patched, out)
                result["program_file"] = out
                from ..hybrid.registry import (
                    CertificationError, install_repaired,
                )
                try:
                    installed = install_repaired(
                        self.bridge.binding, out)
                    result["installed"] = installed.name
                except CertificationError as e:
                    # the honesty contract survives the hot loop: a
                    # patch native re-certification refuses is not a
                    # repair
                    result["status"] = status = "unrepairable"
                    result["reason"] = f"recertify:{e}"
                    reg.count("repair_unrepairable")
            except OSError as e:
                WARNING_MSG("patched proxy save failed: %s", e)
        # corpus write-back: the consumed gap entries' sidecars gain
        # validation.repair (gossip-validated by EntryValidator)
        if fuzzer.store is not None and status != "no-gaps":
            rec_t = result.get("t")
            for crec in result.get("clusters") or []:
                rec = {"verdict": crec.get("status"),
                       "patch": (crec.get("patch_desc")
                                 if crec.get("status") == "repaired"
                                 else None),
                       "reason": crec.get("reason"), "t": rec_t}
                for md5 in crec.get("inputs") or []:
                    fuzzer.store.update_repair(md5, rec)
        fuzzer.telemetry.event(
            "proxy_repair", binding=self.bridge.binding.name,
            status=status, reason=result.get("reason"),
            clusters=len(result.get("clusters") or []),
            installed=result.get("installed"),
            seconds=round(time.time() - t0, 3))
        INFO_MSG("proxy repair for binding %r: %s%s (%.2fs)",
                 self.bridge.binding.name, status,
                 f" ({result.get('reason')})"
                 if result.get("reason") else "",
                 time.time() - t0)
        return result
