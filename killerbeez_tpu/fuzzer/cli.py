"""fuzzer CLI — argument parity with the reference client
(fuzzer/main.c:34-69): positional ``driver instrumentation mutator``
plus -n/-sf/-o/-d/-i/-m/-isd/-isf/-msd/-msf/-l and a batch-size knob.

Usage:
    python -m killerbeez_tpu.fuzzer file jit_harness bit_flip \
        -i '{"target": "test"}' -sf seed.bin -n 2000 -o output
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .. import DEFAULT_BATCH_SIZE
from ..drivers.factory import driver_factory, driver_help
from ..instrumentation.factory import (
    instrumentation_factory, instrumentation_help,
)
from ..mutators.factory import mutator_factory, mutator_help
from ..utils.fileio import read_file, write_buffer_to_file
from ..utils.logging import FatalError, INFO_MSG, setup_logging
from .loop import Fuzzer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="killerbeez-tpu-fuzzer",
        description="TPU-native fuzzer (driver / instrumentation / "
                    "mutator architecture)",
        epilog="Use -h with no positionals for module help listings.",
        prefix_chars="-",
    )
    p.add_argument("driver", help="driver name (file, stdin, ...)")
    p.add_argument("instrumentation",
                   help="instrumentation name (jit_harness, return_code, ...)")
    p.add_argument("mutator", help="mutator name (bit_flip, havoc, afl, ...)")
    p.add_argument("-n", "--iterations", type=int, default=-1,
                   help="number of executions (-1 = until exhausted)")
    p.add_argument("-sf", "--seed-file", help="seed input file")
    p.add_argument("-ss", "--seed-string", help="seed input as a string")
    p.add_argument("-o", "--output", default="output",
                   help="findings directory (default ./output)")
    p.add_argument("-d", "--driver-options", help="driver JSON options")
    p.add_argument("-i", "--instrumentation-options",
                   help="instrumentation JSON options")
    p.add_argument("-m", "--mutator-options", help="mutator JSON options")
    p.add_argument("-isf", "--instrumentation-state-file",
                   help="load instrumentation state from file")
    p.add_argument("-isd", "--instrumentation-state-dump",
                   help="dump instrumentation state to file on exit")
    p.add_argument("-msf", "--mutator-state-file",
                   help="load mutator state from file")
    p.add_argument("-ms", "--mutator-state",
                   help="load mutator state from an inline string "
                        "(reference -ms; -msf for a file)")
    p.add_argument("-msd", "--mutator-state-dump",
                   help="dump mutator state to file on exit")
    p.add_argument("-l", "--logging-options", help="logging JSON options")
    p.add_argument("-fb", "--feedback", type=int, default=-1,
                   help="coverage-guided corpus loop: every N "
                        "batches, rotate the seed through new-path "
                        "findings (default: ON for randomized "
                        "mutators, every 8 batches; 0 = off)")
    p.add_argument("--corpus-dir",
                   help="persistent corpus store directory: every "
                        "edge-novel finding is written through with "
                        "its metadata sidecar (bandit stats, coverage "
                        "signature, lineage) so a campaign can be "
                        "resumed or inspected offline (kb-corpus)")
    p.add_argument("--resume", action="store_true",
                   help="continue a killed campaign from --corpus-dir "
                        "(default <output>/corpus): restores rotation "
                        "arms, scheduler stats, lifetime counters and "
                        "— when the previous run exited through its "
                        "finally block — mutator/instrumentation "
                        "state; -n counts THIS invocation's execs")
    p.add_argument("--schedule", default="bandit",
                   choices=["bandit", "rare-edge", "rr"],
                   help="seed-scheduling policy for corpus feedback "
                        "(default bandit = the historical greedy-"
                        "optimistic decay bandit; rare-edge = "
                        "FairFuzz-style rarest-edge preference; rr = "
                        "round-robin baseline)")
    p.add_argument("--sync-manager",
                   help="manager base URL for fleet corpus exchange "
                        "(POST/GET /api/corpus/<campaign>); requires "
                        "--sync-campaign")
    p.add_argument("--sync-campaign",
                   help="campaign key for --sync-manager (job id)")
    p.add_argument("--sync-worker", default=None,
                   help="worker name for corpus sync (default "
                        "worker-<pid>)")
    p.add_argument("--sync-interval", type=float, default=30.0,
                   help="seconds between corpus sync rounds "
                        "(default 30)")
    p.add_argument("--gossip", type=int, nargs="?", const=0,
                   default=None, metavar="PORT",
                   help="peer-to-peer corpus gossip (requires "
                        "--sync-manager): serve this worker's corpus "
                        "on PORT (0 = ephemeral, the bare default) "
                        "and pull a random fanout of peers each sync "
                        "round, with the manager demoted to peer "
                        "directory + anti-entropy backstop — a dead "
                        "or partitioned manager no longer stops "
                        "corpus flow.  Synced-in entries are "
                        "validated (schema/size/cov_hash) and "
                        "quarantined to <corpus>/quarantine/ on "
                        "failure; peers crossing the poison "
                        "threshold are banned with decorrelated "
                        "backoff (docs/MANAGER.md)")
    p.add_argument("--gossip-fanout", type=int, default=2,
                   metavar="N",
                   help="peers pulled per gossip round (default 2)")
    p.add_argument("--gossip-host", default="127.0.0.1",
                   metavar="ADDR",
                   help="address the gossip sidecar binds (default "
                        "127.0.0.1 = loopback-only; multi-host "
                        "fleets need 0.0.0.0 or the NIC address, "
                        "usually with --gossip-advertise)")
    p.add_argument("--gossip-advertise", metavar="URL",
                   help="URL peers should reach this worker's "
                        "sidecar at (default: its bind address; set "
                        "when NAT or 0.0.0.0 binds make that "
                        "unreachable/ambiguous)")
    p.add_argument("--hybrid", metavar="BINDING", default=None,
                   help="hybrid native⇄TPU campaign (docs/HYBRID.md): "
                        "certify the named proxy binding (kb-fuzz "
                        "refuses a binding whose benign seed diverges "
                        "across tiers), then validate every unique "
                        "TPU finding on the real native binary — "
                        "confirmed/proxy_only/flaky verdicts land in "
                        "corpus sidecars and the event stream, "
                        "proxy_only divergences emit machine-readable "
                        "proxy-gap reports under <output>/proxy_gaps/."
                        "  Built-ins: " + "test, test_safe")
    p.add_argument("--hybrid-repeats", type=int, default=3,
                   metavar="N",
                   help="native replays per finding before a verdict "
                        "(default 3: all crash = confirmed, none = "
                        "proxy_only, else flaky; clamped to 64, the "
                        "sidecar schema's statuses bound)")
    p.add_argument("--hybrid-queue", type=int, default=256,
                   metavar="N",
                   help="validation queue bound (default 256); a full "
                        "queue rejects new findings with a counted, "
                        "logged drop — never silently")
    p.add_argument("--hybrid-workers", type=int, default=1,
                   metavar="N",
                   help="native validator threads (default 1; 0 = "
                        "validate synchronously at fold points — "
                        "deterministic, for tests)")
    p.add_argument("--auto-repair", type=int, nargs="?", const=16,
                   default=0, metavar="N",
                   help="with --hybrid: plateau auto-repair stage "
                        "(docs/ANALYSIS.md 'Conformance & repair') — "
                        "after N batches with no new paths (default "
                        "16 when bare), run the counterexample-guided "
                        "repair pass over the accumulated proxy-gap "
                        "reports: localize the diverging guard, "
                        "search the bounded patch space, and install "
                        "a <binding>+repaired binding ONLY when the "
                        "patch is verdict-identical to the native "
                        "tier on every gap input + certification "
                        "seed (else an honest unrepairable verdict)")
    p.add_argument("--crack", type=int, nargs="?", const=16, default=0,
                   metavar="N",
                   help="plateau crack stage (KBVM device targets): "
                        "after N batches with no new paths (default "
                        "16 when the flag is bare), solve statically-"
                        "reachable-but-never-hit edges into concrete "
                        "inputs (analysis/solver.py) and inject them; "
                        "solve results persist to the corpus store's "
                        "solver.json so resumes don't re-solve")
    p.add_argument("--vsa", action="store_true",
                   help="with --crack: seed the solver's byte "
                        "domains from the value-set fixpoint "
                        "(analysis/vsa.py) and escalate visit caps "
                        "on honest visit-cap unknowns; the fixpoint "
                        "document caches in the corpus checkpoint "
                        "epoch so --resume and repeated cracks "
                        "never re-run it")
    p.add_argument("--descend", type=int, nargs="?", const=48,
                   default=0, metavar="N",
                   help="with --crack: escalate solver-UNKNOWN edges "
                        "(checksum loops, deep loop-carried state) to "
                        "the gradient-guided search tier — batched "
                        "branch-distance descent on device, up to N "
                        "dispatches per edge (default 48 when bare); "
                        "verified witnesses inject like solved "
                        "inputs, verdicts cache in solver.json so "
                        "--resume never re-descends")
    p.add_argument("--descend-lanes", type=int, default=1024,
                   metavar="B",
                   help="candidate lanes per descent dispatch "
                        "(default 1024)")
    p.add_argument("--descend-engine", choices=("device", "host"),
                   default="device",
                   help="descent engine: 'device' (default) fuses R "
                        "rank->probe->mutate->re-score iterations "
                        "into one dispatch with input-to-state "
                        "operand matching (search/device_descent.py; "
                        "stands down to the host engine on edges it "
                        "cannot take), 'host' forces PR 7's "
                        "host-driven engine")
    p.add_argument("--descend-scan-iters", type=int, default=0,
                   metavar="R",
                   help="with --descend-engine device: iterations "
                        "fused per device dispatch (default 8; the "
                        "kb-stats descent row shows the live value "
                        "as descent_iterations_per_dispatch)")
    p.add_argument("--learn", action="store_true",
                   help="learned mutation shaping (jit_harness): "
                        "train a small on-device byte-saliency model "
                        "from the campaign's own lineage (which "
                        "parent bytes, when mutated, produced "
                        "admitted children — provenance sidecars) "
                        "and focus havoc on the predicted-useful "
                        "positions: per generation INSIDE the -G "
                        "device scans (single-chip and --mesh, zero "
                        "host involvement), per rotation via focus "
                        "masks in the host-driven loop.  Until the "
                        "first training round masks are all-ones and "
                        "every path is bit-identical to an unshaped "
                        "campaign; model weights ride the checkpoint "
                        "epoch so --resume restores them "
                        "(docs/LEARN.md).  Mutually exclusive with "
                        "--crack (one mask source at a time)")
    p.add_argument("--learn-interval", type=float, default=5.0,
                   metavar="S",
                   help="with --learn: minimum seconds between "
                        "training rounds (default 5)")
    p.add_argument("--no-focus", action="store_true",
                   help="with --crack: do NOT install the Angora-"
                        "style focused-mutation byte masks derived "
                        "from the uncovered frontier's dependency "
                        "sets (mutators then keep their exact "
                        "unfocused candidate streams)")
    p.add_argument("-dt", "--debug-triage", action="store_true",
                   help="re-run each unique crash once under the "
                        "ptrace debug tier and save signal-level "
                        "details next to the repro (host targets)")
    p.add_argument("-b", "--batch-size", type=int,
                   default=DEFAULT_BATCH_SIZE,
                   help="candidates per device step (batched backends)")
    p.add_argument("--trace", type=int, nargs="?", const=65536,
                   default=0, metavar="MAX_SPANS",
                   help="flight recorder: record pipeline trace "
                        "spans (one lane per in-flight batch, plus "
                        "crack/sync/shard lanes) into a bounded ring "
                        "and export <output>/trace.json (Chrome "
                        "trace-event JSON — load it in Perfetto or "
                        "chrome://tracing); the optional value caps "
                        "the ring in events (default 65536); analyze "
                        "with kb-timeline")
    p.add_argument("--profile-device", type=int, nargs="?", const=8,
                   default=0, metavar="N",
                   help="capture a jax.profiler device trace for N "
                        "batches (default 8 when bare) into "
                        "<output>/device_trace, next to the host "
                        "trace; needs the jax profiler deps, degrades "
                        "to a warning without them")
    p.add_argument("--events-max-mb", type=float, default=0.0,
                   metavar="MB",
                   help="rotate <output>/events.jsonl to "
                        "events.jsonl.1 when it exceeds this many "
                        "megabytes (seq stays monotone across the "
                        "rotation; kb-timeline and the heartbeat "
                        "forwarder read the rotated tail "
                        "transparently; 0 = unbounded, the default)")
    p.add_argument("--watchdog", type=float, nargs="?", const=8.0,
                   default=0.0, metavar="MULT",
                   help="dispatch watchdog: every blocking device "
                        "wait gets a deadline of MULT x the EMA "
                        "batch time (default 8 when bare), clamped "
                        "to [--watchdog-min, --watchdog-max]; a "
                        "stalled dispatch dumps in-flight lane state "
                        "(watchdog_dump.json + trace.json), emits a "
                        "watchdog_stall event, checkpoints, and "
                        "exits 86 so kbz-supervise restarts into "
                        "--resume")
    p.add_argument("--watchdog-min", type=float, default=5.0,
                   metavar="S",
                   help="watchdog deadline floor in seconds "
                        "(default 5)")
    p.add_argument("--watchdog-max", type=float, default=120.0,
                   metavar="S",
                   help="watchdog deadline ceiling in seconds "
                        "(default 120)")
    p.add_argument("--chaos", metavar="SPEC",
                   help="fault-injection spec (JSON, or @file) fired "
                        "at the chaos points — device dispatch/wait, "
                        "persistence writes, manager RPC; see "
                        "docs/RESILIENCE.md (also honored from the "
                        "KBZ_CHAOS env var, which is how "
                        "kbz-supervise --chaos injects faults into "
                        "one child launch)")
    p.add_argument("--no-stats", action="store_true",
                   help="disable the periodic campaign stats files "
                        "(fuzzer_stats / plot_data / stats.jsonl in "
                        "-o; counters still accumulate in-process)")
    p.add_argument("--stats-interval", type=float, default=5.0,
                   help="seconds between stats-file snapshots "
                        "(default 5)")
    p.add_argument("-G", "--generations", type=int, nargs="?",
                   const=16, default=0, metavar="G",
                   help="device-resident generation loop (jit_harness "
                        "+ a fused-capable mutator): the device runs "
                        "up to G full mutate->execute->triage->reseed "
                        "generations per host dispatch (default 16 "
                        "when bare) against a device-resident virgin "
                        "map and seed-slot ring; the host only drains "
                        "the bounded findings ring + admission ledger."
                        "  With --mesh the scan shards over dp with "
                        "in-scan ICI virgin-map folds (per-shard "
                        "rings + ledgers, gen_fold_every).  Auto-"
                        "stands-down (warning) when --crack / focus "
                        "masks / a non-fused mutator is active; with "
                        "-fb 0 the candidate stream is bit-identical "
                        "to the host-driven loop "
                        "(docs/GENERATIONS.md)")
    p.add_argument("--stateful", type=int, nargs="?", const=0,
                   default=None, metavar="M",
                   help="stateful protocol sessions (jit_harness): "
                        "inputs are framed message sequences "
                        "(stateful/framing.py; build seeds with "
                        "kb-frame) executed message-by-message from "
                        "carried machine state, with a state x edge "
                        "virgin map folded alongside the classic "
                        "novelty maps.  The optional value overrides "
                        "the sequence capacity M (default: the "
                        "target's registered StatefulSpec).  Forces "
                        "the xla engine; the fused superbatch stands "
                        "down, -G runs the stateful generation scan "
                        "(docs/STATEFUL.md decision table)")
    p.add_argument("-K", "--accumulate", type=int, default=0,
                   help="fused device path: accumulate K batches "
                        "per device dispatch so the host pulls one "
                        "transfer set per K batches (0 = auto, "
                        "1 = per-batch; tunnel-RTT resilience)")
    p.add_argument("--mesh",
                   help='multi-chip campaign over a "dp,mp" device '
                        "mesh (e.g. --mesh 4,2): candidates shard "
                        "over dp, coverage maps over mp, findings "
                        "land in -o exactly like single-chip; "
                        "requires jit_harness + havoc and -b "
                        "divisible by dp; combine with -G for the "
                        "mesh-resident generation scan")
    p.add_argument("--list", action="store_true",
                   help="list components and their options, then exit")
    return p


def list_components() -> str:
    return (driver_help() + "\n" + instrumentation_help() + "\n"
            + mutator_help())


def _wire_rare_edge_signer(fuzzer, driver) -> None:
    """``--schedule rare-edge`` needs per-entry coverage signatures.
    Each admitted finding is signed with ONE extra execution —
    admissions are rare, so the batched hot path stays untouched:
    device tiers sign on a side instrumentation instance with edge
    reporting forced on (the main instance keeps its fused/superbatch
    eligibility); host tiers re-run the input on the live target and
    read the raw trace.  Tiers that cannot report edges (ipt hash
    mode) leave entries unsigned — the scheduler probes those once
    and falls back gracefully."""
    import json as _json

    import numpy as _np

    instr = driver.instrumentation
    side: dict = {}

    def sign(buf: bytes):
        if instr.device_backed:
            s = side.get("instr")
            if s is None:
                from ..tools.tracer import force_edges_option
                s = instrumentation_factory(
                    instr.name,
                    force_edges_option(_json.dumps(instr.options)))
                side["instr"] = s
            s.enable(buf)
            edges = s.get_edges()
            return [e for e, _ in edges] if edges else None
        # host tier: one extra exec on the live target (novelty fold
        # is idempotent — the entry was just executed)
        driver.test_input(buf)
        trace_fn = getattr(instr, "last_trace", None)
        if trace_fn is not None:
            trace = trace_fn()
            if trace is not None:
                return [int(i) for i in _np.flatnonzero(trace)]
        edges = instr.get_edges()
        return [e for e, _ in edges] if edges else None

    fuzzer._signer = sign


def _wire_static_prior(fuzzer, driver) -> None:
    """``--schedule rare-edge`` on a KBVM target: seed the scheduler
    with the static edge-frequency prior (analysis.static_edge_prior)
    so rarity targeting has a signal before the corpus warms up.  The
    prior only breaks cold-start ties — once dynamic edge-hit counts
    or selections differ, selection is identical to an unprimed
    scheduler (corpus/schedule.py)."""
    prog = getattr(driver.instrumentation, "program", None)
    if prog is None or \
            not hasattr(fuzzer.scheduler, "set_static_prior"):
        return
    from ..analysis import static_edge_prior
    fuzzer.scheduler.set_static_prior(static_edge_prior(prog))


def _augment_dictionary_options(mutator_options: Optional[str],
                                instr_options: Optional[str]
                                ) -> Optional[str]:
    """A ``dictionary`` mutator invoked with no token source inherits
    the instrumentation's KBVM target/program_file, so its tokens
    auto-extract from static branch-constant analysis — no token file
    needed for device targets."""
    import json as _json
    try:
        mopts = _json.loads(mutator_options) if mutator_options else {}
        iopts = _json.loads(instr_options) if instr_options else {}
    except (ValueError, TypeError):
        return mutator_options          # factories report the error
    if not isinstance(mopts, dict) or not isinstance(iopts, dict) or \
            any(k in mopts for k in ("tokens", "dictionary", "target",
                                     "program_file")):
        return mutator_options
    for k in ("target", "program_file"):
        if k in iopts:
            mopts[k] = iopts[k]
            INFO_MSG("dictionary mutator: auto-extracting tokens "
                     "from %s=%r (static branch-constant analysis)",
                     k, iopts[k])
            return _json.dumps(mopts)
    return mutator_options


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list:
        print(list_components())
        return 0
    try:
        setup_logging(args.logging_options)

        # chaos harness: explicit --chaos wins; KBZ_CHAOS is how a
        # supervisor injects faults into one child launch
        from ..resilience import chaos as _chaos
        _chaos.configure(args.chaos or os.environ.get("KBZ_CHAOS"))

        if args.seed_file:
            seed = read_file(args.seed_file)
        elif args.seed_string:
            seed = args.seed_string.encode()
        else:
            print("error: a seed is required (-sf or -ss)",
                  file=sys.stderr)
            return 2

        if args.learn:
            # inject the learn option into the instrumentation
            # config (engine coercion + tool visibility — the same
            # augmentation pattern --stateful uses)
            import json as _json
            if args.instrumentation != "jit_harness":
                print("error: --learn needs the jit_harness "
                      "instrumentation (the saliency model trains "
                      "and infers on the device the fuzzer runs "
                      "on)", file=sys.stderr)
                return 2
            if args.crack:
                print("error: --learn and --crack are mutually "
                      "exclusive — each installs its own mutation "
                      "focus masks (learned saliency vs the static "
                      "frontier dependency sets); run one mask "
                      "source at a time", file=sys.stderr)
                return 2
            try:
                iopts = _json.loads(args.instrumentation_options) \
                    if args.instrumentation_options else {}
            except ValueError:
                iopts = None     # factory reports the parse error
            if isinstance(iopts, dict):
                iopts.setdefault("learn", 1)
                args.instrumentation_options = _json.dumps(iopts)

        if args.stateful is not None:
            # inject the session-tier options into the
            # instrumentation config (the same augmentation pattern
            # the dictionary mutator uses)
            import json as _json
            if args.instrumentation != "jit_harness":
                print("error: --stateful needs the jit_harness "
                      "instrumentation (the session executor runs "
                      "the KBVM)", file=sys.stderr)
                return 2
            try:
                iopts = _json.loads(args.instrumentation_options) \
                    if args.instrumentation_options else {}
            except ValueError:
                iopts = None     # factory reports the parse error
            if isinstance(iopts, dict):
                iopts.setdefault("stateful", 1)
                if args.stateful > 0:
                    iopts["msgs"] = args.stateful
                args.instrumentation_options = _json.dumps(iopts)

        instrumentation = instrumentation_factory(
            args.instrumentation, args.instrumentation_options)
        if args.instrumentation_state_file:
            instrumentation.set_state(
                read_file(args.instrumentation_state_file).decode())

        mutator_options = args.mutator_options
        if args.mutator == "dictionary":
            mutator_options = _augment_dictionary_options(
                mutator_options, args.instrumentation_options)
        mutator = mutator_factory(args.mutator, mutator_options, seed)
        if args.mutator_state:
            mutator.set_state(args.mutator_state)
        elif args.mutator_state_file:
            mutator.set_state(read_file(args.mutator_state_file).decode())

        if args.mesh:
            from ..parallel.campaign import ShardedCampaignDriver
            from ..utils.logging import WARNING_MSG
            if args.driver != "file" or args.driver_options:
                WARNING_MSG(
                    "--mesh campaigns deliver candidates on-device; "
                    "the %r driver%s is ignored", args.driver,
                    " and -d options" if args.driver_options else "")
            if args.instrumentation != "jit_harness":
                print("error: --mesh campaigns need the jit_harness "
                      "instrumentation", file=sys.stderr)
                return 2
            if not hasattr(mutator, "fused_spec"):
                print("error: --mesh campaigns need the havoc "
                      "mutator (keyed per-lane candidate streams)",
                      file=sys.stderr)
                return 2
            driver = ShardedCampaignDriver(
                args.mesh, instrumentation, mutator,
                batch_size=args.batch_size)
        else:
            driver = driver_factory(args.driver, args.driver_options,
                                    instrumentation, mutator)

        corpus_dir = args.corpus_dir
        if args.resume and not corpus_dir:
            corpus_dir = os.path.join(args.output, "corpus")
        sync = None
        if args.gossip is not None and not args.sync_manager:
            print("error: --gossip needs --sync-manager (the peer "
                  "directory lives there)", file=sys.stderr)
            return 2
        if args.sync_manager:
            if not args.sync_campaign:
                print("error: --sync-manager needs --sync-campaign",
                      file=sys.stderr)
                return 2
            worker_name = args.sync_worker or f"worker-{os.getpid()}"
            if args.gossip is not None:
                from ..corpus.gossip import GossipSync
                sync = GossipSync(args.sync_manager,
                                  args.sync_campaign,
                                  worker=worker_name,
                                  interval_s=args.sync_interval,
                                  fanout=args.gossip_fanout,
                                  listen_host=args.gossip_host,
                                  listen_port=args.gossip,
                                  advertise=args.gossip_advertise)
            else:
                from ..corpus.sync import CorpusSync
                sync = CorpusSync(args.sync_manager,
                                  args.sync_campaign,
                                  worker=worker_name,
                                  interval_s=args.sync_interval)

        watchdog = None
        if args.watchdog > 0:
            from ..resilience.watchdog import DispatchWatchdog
            watchdog = DispatchWatchdog(
                multiplier=args.watchdog,
                min_deadline=args.watchdog_min,
                max_deadline=args.watchdog_max)

        learn_tier = None
        if args.learn:
            from ..learn import LearnTier
            learn_tier = LearnTier(
                train_interval_s=args.learn_interval,
                max_len=getattr(mutator, "max_length", 4096))

        hybrid_bridge = None
        if args.hybrid:
            from ..hybrid import CertificationError, make_bridge
            try:
                hybrid_bridge = make_bridge(
                    args.hybrid, repeats=args.hybrid_repeats,
                    queue_cap=args.hybrid_queue,
                    workers=args.hybrid_workers)
            except (KeyError, CertificationError,
                    RuntimeError) as e:
                # stand-down rule (docs/HYBRID.md): no native
                # substrate / divergent binding -> refuse the hybrid
                # campaign rather than run one that cannot validate
                print(f"error: {e}", file=sys.stderr)
                return 2

        fuzzer = Fuzzer(driver, output_dir=args.output,
                        batch_size=args.batch_size,
                        debug_triage=args.debug_triage,
                        feedback=args.feedback,
                        accumulate=args.accumulate,
                        telemetry=(False if args.no_stats else None),
                        stats_interval=args.stats_interval,
                        scheduler=args.schedule,
                        corpus_dir=corpus_dir,
                        resume=args.resume,
                        sync=sync,
                        trace=args.trace,
                        profile_device=args.profile_device,
                        events_max_mb=args.events_max_mb,
                        watchdog=watchdog,
                        generations=args.generations,
                        learn=learn_tier,
                        hybrid=hybrid_bridge)
        native_beat = None
        if hybrid_bridge is not None and args.sync_manager and \
                args.sync_campaign:
            # the native tier as a fleet citizen: its own heartbeat
            # row (meta tier "native") beside the TPU worker's, so
            # kb-fleet's per-tier fold sees both (docs/HYBRID.md)
            from ..hybrid import NativeHeartbeat
            native_beat = NativeHeartbeat(
                hybrid_bridge, args.sync_manager, args.sync_campaign,
                args.sync_worker or f"worker-{os.getpid()}",
                interval=args.sync_interval)
            native_beat.start()
        if args.schedule == "rare-edge":
            _wire_rare_edge_signer(fuzzer, driver)
            _wire_static_prior(fuzzer, driver)
        if args.descend and not args.crack:
            print("error: --descend escalates the crack stage's "
                  "solver-unknown frontier — it needs --crack",
                  file=sys.stderr)
            return 2
        if args.vsa and not args.crack:
            print("error: --vsa seeds the crack stage's solver "
                  "from the value-set fixpoint — it needs --crack",
                  file=sys.stderr)
            return 2
        if args.crack:
            prog = getattr(instrumentation, "program", None)
            if prog is None or not instrumentation.device_backed \
                    or args.mesh:
                print("error: --crack needs a KBVM device target "
                      "(jit_harness, single-chip) — the solver works "
                      "on the program text", file=sys.stderr)
                return 2
            if getattr(instrumentation, "stateful_spec", None) \
                    is not None:
                print("error: --crack models single-shot execution "
                      "(path conditions over ONE input) — it cannot "
                      "drive the stateful session tier; run it "
                      "without --stateful, or fuzz sequences with "
                      "-G/havoc/multipart (docs/STATEFUL.md)",
                      file=sys.stderr)
                return 2
            from .crack import BranchCracker
            fuzzer.cracker = BranchCracker(
                prog, plateau_batches=args.crack,
                focus=not args.no_focus, store=fuzzer.store,
                descend=args.descend,
                descend_lanes=args.descend_lanes,
                descend_engine=args.descend_engine,
                descend_scan_iters=args.descend_scan_iters,
                vsa=args.vsa)
        if args.auto_repair:
            if hybrid_bridge is None:
                print("error: --auto-repair consumes the hybrid "
                      "tier's proxy-gap reports — it needs --hybrid",
                      file=sys.stderr)
                return 2
            from .repairer import ProxyRepairer
            fuzzer.repairer = ProxyRepairer(
                hybrid_bridge, plateau_batches=args.auto_repair)
        try:
            stats = fuzzer.run(args.iterations)
        except Exception as e:
            # run()'s finally already checkpointed; classify a
            # device loss for the supervisor (exit 87 -> it
            # re-probes devices before restarting into --resume)
            from ..resilience import (
                DEVICE_LOST_EXIT_CODE, is_device_loss,
            )
            if is_device_loss(e):
                fuzzer.telemetry.event("device_lost",
                                       error=str(e)[:300])
                print(f"error: device lost: {e}", file=sys.stderr)
                return DEVICE_LOST_EXIT_CODE
            raise
        finally:
            if native_beat is not None:
                native_beat.stop()   # posts one parting beat
        # both rates read the SAME registry the loop recorded into —
        # the CLI never recomputes from its own wall clock
        INFO_MSG(
            "results: %d crashes (%d unique), %d hangs (%d unique), "
            "%d new paths; %.0f execs/s lifetime (%.0f recent)",
            stats.crashes, stats.unique_crashes, stats.hangs,
            stats.unique_hangs, stats.new_paths, stats.execs_per_sec,
            stats.execs_per_sec_ema)

        # state dumps on exit (reference fuzzer/main.c:426-447)
        if args.instrumentation_state_dump:
            write_buffer_to_file(args.instrumentation_state_dump,
                                 instrumentation.get_state().encode())
        if args.mutator_state_dump:
            write_buffer_to_file(args.mutator_state_dump,
                                 mutator.get_state().encode())
        if sync is not None:
            sync.close()        # gossip sidecar stops serving
        driver.cleanup()
        instrumentation.cleanup()
        mutator.cleanup()
        return 0
    except FatalError:
        return 1
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
