"""The fuzzer client: batched main loop + CLI
(reference fuzzer/main.c)."""

from .loop import Fuzzer, FuzzStats

__all__ = ["Fuzzer", "FuzzStats"]
