"""The plateau crack stage — solver-guided branch cracking.

Closes the loop from "observe coverage" to "compute the input that
extends it": when the fuzzing loop plateaus (no new paths for N
batches), the cracker diffs the program's STATIC edge universe
(``vm.compute_edges``) against the dynamic coverage the campaign has
actually accumulated (the instrumentation's virgin map), asks the
path-condition solver (``analysis/solver.py``) for inputs reaching
the never-hit edges, and injects the solved candidates straight
through the instrumentation — ahead of any scheduler decision.
Solved, unsat and unknown verdicts are cached (and persisted to the
corpus store's ``solver.json`` sidecar when a store is attached), so
an edge is solved at most once per campaign lineage, resumes
included.

Second consumer: **focused mutation masks**.  The dependency sets of
the branches guarding the still-uncovered frontier (dataflow layer)
become a byte mask the havoc/zzuf mutators honor — Angora's "don't
burn mutations on bytes no uncovered branch reads", bought statically
instead of with dynamic taint.  ``--no-focus`` disables the masks;
campaigns without a cracker never see one (parity-pinned).

Third tier: **gradient-guided escalation** (``--descend``).  Edges
the solver honestly reports ``unknown`` — checksum loops, deep
loop-carried state — escalate to the search tier
(``search/descent.py``): batched branch-distance descent on device,
seeded from corpus entries that reach the edge's source block,
mutation dimensions restricted to the solver's dependency-byte mask.
Verified witnesses inject through the same path as solved inputs
(same honesty contract: concretely re-checked before emission), and
per-edge verdicts (``descended``/``exhausted``, steps, final
distance) cache in the same ``solver.json`` sidecar so ``--resume``
never re-descends.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis import analyze_dataflow, edge_dep_mask
from ..analysis.solver import (
    DEFAULT_BUDGET, DEFAULT_MAX_LEN, DEFAULT_MAX_VISITS, solve_edge,
)
from ..utils.logging import DEBUG_MSG, INFO_MSG, WARNING_MSG


class BranchCracker:
    """Owns the plateau trigger, the per-edge solve cache, candidate
    injection and the focus-mask feed for ONE campaign/program."""

    #: at most this many fresh solver attempts per crack invocation
    #: (the rest wait for the next plateau — keeps a single crack's
    #: host-side pause bounded)
    MAX_SOLVES_PER_CRACK = 32

    #: at most this many descent escalations per crack invocation
    #: (a descent is many device dispatches — later plateaus pick up
    #: the rest, with fresher seeds from whatever cracked meanwhile)
    MAX_DESCENDS_PER_CRACK = 4

    #: seed-pool size cap for descent populations
    MAX_DESCENT_SEEDS = 96

    def __init__(self, program, *, plateau_batches: int = 16,
                 budget: int = DEFAULT_BUDGET,
                 max_visits: int = DEFAULT_MAX_VISITS,
                 max_len: int = DEFAULT_MAX_LEN,
                 focus: bool = True, store=None,
                 descend: int = 0, descend_lanes: int = 1024,
                 descend_engine: str = "device",
                 descend_scan_iters: int = 0,
                 max_solves: Optional[int] = None,
                 max_descends: Optional[int] = None,
                 vsa: bool = False):
        self.program = program
        self.plateau_batches = max(int(plateau_batches), 1)
        self.budget = int(budget)
        self.max_visits = int(max_visits)
        self.max_len = int(max_len)
        self.focus = bool(focus)
        self.store = store
        #: descent iteration budget per edge; 0 = the search tier is
        #: off and solver-unknown edges stay unknown
        self.descend = int(descend)
        self.descend_lanes = int(descend_lanes)
        #: which descent engine escalated edges run on: "device" =
        #: the in-scan engine (search/device_descent.py, R iterations
        #: fused per dispatch, input-to-state matching on) with an
        #: automatic stand-down to the host engine on edges it cannot
        #: take (unconditional edges); "host" = PR 7's host-driven
        #: engine only
        if descend_engine not in ("device", "host"):
            raise ValueError(
                f"descend_engine must be 'device' or 'host', "
                f"got {descend_engine!r}")
        self.descend_engine = descend_engine
        self.descend_scan_iters = int(descend_scan_iters)
        #: per-crack work caps (instance-tunable: bench/offline
        #: callers crank them to sweep a whole universe in one crack)
        self.max_solves = int(max_solves) if max_solves \
            else self.MAX_SOLVES_PER_CRACK
        self.max_descends = int(max_descends) if max_descends \
            else self.MAX_DESCENDS_PER_CRACK
        ef = np.asarray(program.edge_from)
        et = np.asarray(program.edge_to)
        slots = np.asarray(program.edge_slot)
        self.edges: List[Tuple[int, int]] = \
            [(int(f), int(t)) for f, t in zip(ef, et)]
        self.slot_of_edge: Dict[Tuple[int, int], int] = {
            e: int(s) for e, s in zip(self.edges, slots)}
        self._dataflow = None           # lazy (mask computation only)
        #: --vsa: solve through solve_edge_vsa (byte-domain seeding
        #: + the visit-cap escalation ladder); the fixpoint document
        #: is computed once and cached in the corpus store's
        #: checkpoint epoch, so --resume and repeated cracks never
        #: re-run it.  Off (default): solve_edge, bit-identical to
        #: the pre-VSA cracker.
        self.vsa = bool(vsa)
        self._vsa_result = None         # lazy (first crack)
        #: "f:t" -> {"status", "reason", "input_hex"?}
        self.cache: Dict[str, Dict] = {}
        if store is not None:
            self.cache = store.load_solver_cache()
        self._last_new_paths = -1
        self._progress_iter = 0

    # -- coverage frontier ----------------------------------------------

    def uncovered_edges(self, instr) -> List[Tuple[int, int]]:
        """Static edges whose AFL map slot the campaign has never lit
        (colliding slots conflate, exactly as novelty itself does)."""
        virgin = np.asarray(instr.virgin_bits)
        covered = set(np.flatnonzero(virgin != 0xFF).tolist())
        return [e for e in self.edges
                if self.slot_of_edge[e] not in covered]

    @staticmethod
    def _key(edge: Tuple[int, int]) -> str:
        return f"{edge[0]}:{edge[1]}"

    # -- the value-set document (--vsa) ---------------------------------

    def _get_vsa(self):
        """The VsaResult for this program: corpus-cached doc if its
        ``program_sig`` still matches, else one fresh fixpoint run
        persisted for every later crack / resume."""
        if self._vsa_result is not None:
            return self._vsa_result
        from ..analysis.vsa import VsaResult, analyze_vsa
        if self.store is not None:
            doc = self.store.load_vsa_doc()
            if doc is not None:
                cached = VsaResult.from_doc(doc, self.program)
                if cached is not None:
                    self._vsa_result = cached
                    return cached
        self._vsa_result = analyze_vsa(self.program)
        if self.store is not None:
            self.store.save_vsa_doc(self._vsa_result.to_doc())
        return self._vsa_result

    # -- the plateau trigger --------------------------------------------

    def maybe_crack(self, fuzzer) -> None:
        """Called once per loop iteration: fire ``crack`` after
        ``plateau_batches`` batches with zero new paths.

        ``stats.iterations`` advances at DISPATCH while
        ``stats.new_paths`` advances at triage, which lags by up to
        ``PIPELINE_DEPTH`` batches — so the plateau window is padded
        by the pipeline depth.  By the time the padded window
        elapses, every batch of the un-padded window has been
        triaged (the pending deque caps at the depth), and any
        finding among them would have reset the baseline: the crack
        only fires after ``plateau_batches`` PROVEN finding-free
        batches, not during warm-up."""
        s = fuzzer.stats
        if s.new_paths != self._last_new_paths:
            self._last_new_paths = s.new_paths
            self._progress_iter = s.iterations
            return
        depth = getattr(fuzzer, "PIPELINE_DEPTH", 0)
        window = (self.plateau_batches + depth) * fuzzer.batch_size
        if s.iterations - self._progress_iter < window:
            return
        self._progress_iter = s.iterations      # re-arm
        # flight recorder: the plateau itself is a campaign event —
        # kb-timeline overlays it on the span lanes, which is exactly
        # the artifact that exposed PR 4's warm-up-crack race
        fuzzer.telemetry.event(
            "plateau", execs=int(s.iterations),
            new_paths=int(s.new_paths), window_execs=int(window))
        tr = fuzzer.telemetry.trace
        with (tr.span("crack", lane="crack") if tr is not None
              else contextlib.nullcontext()):
            self.crack(fuzzer)

    # -- the crack itself -----------------------------------------------

    def crack(self, fuzzer) -> int:
        """Solve + inject the uncovered frontier; returns how many
        candidates were injected."""
        instr = fuzzer.driver.instrumentation
        reg = fuzzer.telemetry.registry
        uncovered = self.uncovered_edges(instr)
        reg.gauge("solver_frontier", len(uncovered))
        if not uncovered:
            if self.focus:
                fuzzer.driver.mutator.set_focus_mask(None)
            return 0

        fresh = [e for e in uncovered if self._key(e) not in self.cache]
        t0 = time.time()
        for e in fresh[:self.max_solves]:
            reg.count("solver_attempts")
            if self.vsa:
                from ..analysis.solver import solve_edge_vsa
                res = solve_edge_vsa(
                    self.program, e, vsa=self._get_vsa(),
                    budget=self.budget, max_visits=self.max_visits,
                    max_len=self.max_len)
            else:
                res = solve_edge(self.program, e, budget=self.budget,
                                 max_visits=self.max_visits,
                                 max_len=self.max_len)
            entry = {"status": res.status, "reason": res.reason}
            if res.status == "solved":
                reg.count("solver_solved")
                entry["input_hex"] = res.input.hex()
            elif res.status == "unsat":
                reg.count("solver_unsat")
            else:
                reg.count("solver_unknown")
                if "budget" in res.reason:
                    reg.count("solver_budget_bailed")
            self.cache[self._key(e)] = entry

        # gradient-guided escalation: the edges the solver just (or
        # previously) reported unknown are exactly the search tier's
        # intake — descend their branch distances on device.  Returns
        # ATTEMPTS, not witnesses: an exhausted verdict also mutates
        # the cache and must persist, or --resume re-descends it
        searched = self._descend_frontier(fuzzer, uncovered) \
            if self.descend else 0

        if self.store is not None and (fresh or searched):
            self._persist_verdicts(fuzzer)

        # inject every cached solve/descent whose edge is STILL
        # uncovered — includes results restored from a resumed
        # campaign's sidecar
        bufs = []
        for e in uncovered:
            entry = self.cache.get(self._key(e))
            if entry and entry.get("status") in ("solved", "descended") \
                    and "input_hex" in entry:
                bufs.append(bytes.fromhex(entry["input_hex"]))
        injected = self._inject(fuzzer, bufs) if bufs else 0
        if fresh or injected:
            fuzzer.telemetry.event(
                "crack_injection", injected=int(injected),
                attempts=len(fresh[:self.max_solves]),
                frontier=len(uncovered),
                solve_seconds=round(time.time() - t0, 3))
            INFO_MSG(
                "crack: %d uncovered edges, %d solve attempts "
                "(%.2fs), %d candidates injected",
                len(uncovered), len(fresh[:self.max_solves]),
                time.time() - t0, injected)

        # focus mask from whatever frontier remains unsolved
        if self.focus:
            remaining = self.uncovered_edges(instr)
            self._update_mask(fuzzer, remaining)
        return injected

    def _persist_verdicts(self, fuzzer) -> None:
        """Fresh verdicts hit disk through the loop's unified
        checkpoint when this cracker is the loop's (ONE atomic epoch:
        the corpus state and the solver cache can never disagree
        about a kill again — the old separate solver.json write left
        a window where a kill between the corpus persist and the
        cache save forgot crack verdicts).  Offline callers
        (kb-descend rounds, bench sweeps) keep the standalone
        solver.json path."""
        if fuzzer is not None and \
                getattr(fuzzer, "cracker", None) is self and \
                getattr(fuzzer, "store", None) is self.store:
            fuzzer._persist_campaign(now=True)
        else:
            self.store.save_solver_cache(self.cache)

    # -- the search-tier escalation (search/descent.py) -----------------

    def _seed_pool(self, fuzzer) -> List[bytes]:
        """Descent seed candidates: rotation arms, the base seed, and
        every cached solver/descent witness (those reach the deepest
        known blocks — exactly where the frontier lives)."""
        pool: List[bytes] = []
        for entry in self.cache.values():
            if "input_hex" in entry:
                pool.append(bytes.fromhex(entry["input_hex"]))
        sched = getattr(fuzzer, "scheduler", None)
        if sched is not None:
            pool.extend(a.buf for a in sched.arms)
            if sched.base_seed:
                pool.append(sched.base_seed)
        seen = set()
        out = []
        for b in pool:
            if b and b not in seen:
                seen.add(b)
                out.append(b)
        return out[:self.MAX_DESCENT_SEEDS]

    def _descend_frontier(self, fuzzer, uncovered) -> int:
        """Escalate solver-unknown uncovered edges to branch-distance
        descent; returns how many edges were ATTEMPTED (the cache
        mutated — the caller persists on any nonzero return).  One
        attempt per edge per campaign lineage: verdicts (including
        ``exhausted``) cache under the edge's ``search`` key, so
        plateaus and ``--resume`` never re-descend."""
        from ..search import (
            DEFAULT_SCAN_ITERS, descend_edge, descend_edge_device,
            seeds_reaching_block,
        )
        cand = []
        for e in uncovered:
            entry = self.cache.get(self._key(e))
            if entry is not None and entry.get("status") == "unknown" \
                    and "search" not in entry:
                cand.append(e)
        reg = fuzzer.telemetry.registry
        reg.gauge("search_frontier", len(cand))
        if not cand:
            return 0
        seeds = self._seed_pool(fuzzer)
        if self._dataflow is None:
            self._dataflow = analyze_dataflow(self.program)
        tr = fuzzer.telemetry.trace
        # one reference-interpreter trace per seed per crack: the
        # reach filter and the engine's path-guard extraction share it
        traces: Dict[bytes, object] = {}
        n = attempted = 0
        t0 = time.time()
        scan_iters = self.descend_scan_iters or DEFAULT_SCAN_ITERS
        for e in cand[:self.max_descends]:
            reg.count("search_attempts")
            attempted += 1
            mask = edge_dep_mask(self.program, [e], self._dataflow)
            se = seeds_reaching_block(self.program, seeds, e[0],
                                      cap=24, trace_cache=traces) \
                or seeds[:16]
            if self.descend_engine == "device":
                res = descend_edge_device(
                    self.program, e, se or [b"\x00"], mask=mask,
                    lanes=self.descend_lanes, budget=self.descend,
                    scan_iters=scan_iters, max_len=self.max_len,
                    trace=tr, trace_cache=traces, registry=reg)
            else:
                res = descend_edge(self.program, e, se or [b"\x00"],
                                   mask=mask,
                                   lanes=self.descend_lanes,
                                   budget=self.descend,
                                   max_len=self.max_len, trace=tr,
                                   trace_cache=traces)
            entry = dict(self.cache.get(self._key(e)) or {})
            d = res.as_dict()
            entry["search"] = {k: d[k] for k in
                               ("status", "steps", "evals",
                                "best_dist", "objective", "engine",
                                "dispatches", "iterations", "i2s")}
            if res.status == "descended":
                reg.count("search_descended")
                entry["status"] = "descended"
                entry["input_hex"] = res.input.hex()
                entry["reason"] = (f"branch-distance descent: witness "
                                   f"after {res.steps} batches")
                seeds.append(res.input)   # chain: deeper edges seed
                n += 1                    # from this witness
            else:
                reg.count("search_exhausted")
            self.cache[self._key(e)] = entry
            fuzzer.telemetry.event(
                "descent", edge=f"{e[0]}:{e[1]}", status=res.status,
                steps=int(res.steps), evals=int(res.evals),
                engine=res.engine, dispatches=int(res.dispatches),
                i2s=bool(res.i2s),
                best_dist=(None if res.input else float(res.best_dist)))
        if attempted:
            INFO_MSG("descend: %d unknown edges, %d attempts, %d "
                     "cracked (%.2fs)", len(cand), attempted, n,
                     time.time() - t0)
        return attempted

    def _inject(self, fuzzer, bufs: List[bytes]) -> int:
        """Run solved candidates through the MAIN instrumentation (so
        its virgin maps absorb the new coverage) and hand each lane to
        the loop's triage — findings dedup, persist, sync and enter
        rotation exactly like mutated ones."""
        from ..mutators.base import pack_byte_rows
        instr = fuzzer.driver.instrumentation
        inputs, lengths = pack_byte_rows(bufs)
        try:
            res = instr.run_batch(inputs, lengths)
        except Exception as e:      # cracking must never kill the loop
            WARNING_MSG("crack injection failed: %s", e)
            return 0
        # statuses arrive hang-mapped (run_batch folds FUZZ_RUNNING)
        statuses = np.asarray(res.statuses)
        new_paths = np.asarray(res.new_paths)
        uc = np.asarray(res.unique_crashes)
        uh = np.asarray(res.unique_hangs)
        reg = fuzzer.telemetry.registry
        n = len(bufs)
        fuzzer.stats.iterations += n
        reg.rate("execs", n)
        reg.count("solver_injected", n)
        prev_credit = fuzzer._credit_arm
        fuzzer._credit_arm = None       # solver finds credit the base
        try:
            for i in range(n):
                fuzzer._triage_lane(int(statuses[i]),
                                    int(new_paths[i]), bufs[i],
                                    bool(uc[i]), bool(uh[i]))
        finally:
            fuzzer._credit_arm = prev_credit
        return n

    def _update_mask(self, fuzzer, remaining) -> None:
        mut = fuzzer.driver.mutator
        if not remaining:
            mut.set_focus_mask(None)
            reg = fuzzer.telemetry.registry
            reg.gauge("solver_frontier", 0)
            return
        if self._dataflow is None:
            self._dataflow = analyze_dataflow(self.program)
        mask = edge_dep_mask(self.program, remaining, self._dataflow)
        mut.set_focus_mask(mask)
        fuzzer.telemetry.registry.gauge(
            "solver_focus_bytes", len(mask) if mask else 0)
        DEBUG_MSG("crack: focus mask %s over %d frontier edges",
                  mask, len(remaining))
