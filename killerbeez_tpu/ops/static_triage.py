"""Static-edge triage — AFL-map novelty over a known edge universe.

The KBVM compiler enumerates every dynamically possible coverage edge
of a program (``Program.edge_slot``, vm.compute_edges), so triage
never has to touch the 64KB map shape or sort per-lane streams: the
whole pipeline runs over ``[B, U]`` where U = number of distinct AFL
map slots the program can hit (a few hundred).

Semantics are the dense AFL contract (classify_counts buckets,
``has_new_bits`` ret codes, simplify_trace crash/hang maps including
the absent-edge "1" class) restricted to the static universe — which
is EXACT for jit-harness targets: slots outside the universe are
never hit, so their dense-path contribution is the constant class-1
pattern, reproduced here by ``_outside_mask`` on the first unique
crash/hang.

The reference's equivalents scan the full map every exec
(afl_instrumentation.c:600-707 has_new_bits over 64KB;
dynamorio_instrumentation.c:1428-1469 classify+hash short-circuit);
this is the TPU-shaped replacement the one-hot KBVM engine makes
possible.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import MAP_SIZE
from .coverage import classify_counts
from .sparse_coverage import _first_occurrence_multi, stream_hash


def make_static_maps(edge_slot: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(u_slots int32[U] sorted unique AFL slots, seg_id int32[E]
    edge-index -> slot-group index). Host-side, once per program."""
    u_slots, seg_id = np.unique(np.asarray(edge_slot), return_inverse=True)
    return u_slots.astype(np.int32), seg_id.astype(np.int32)


def counts_by_slot(counts: jax.Array, seg_id: jax.Array,
                   n_slots: int) -> jax.Array:
    """Fold edge hit counts into AFL map cells: colliding edges (same
    ``cur ^ prev`` slot) share a cell, wrapping at u8 exactly like the
    dense ``trace_bits[slot]++``.

    counts: uint8[B, E+1] (overflow column dropped) -> uint8[B, U].
    """
    c = counts[:, :-1]
    b = c.shape[0]
    out = jnp.zeros((b, n_slots), jnp.uint8)
    return out.at[:, seg_id].add(c)


def expand_to_map(by_slot: jax.Array, u_slots: jax.Array,
                  map_size: int = MAP_SIZE) -> jax.Array:
    """uint8[B, U] -> uint8[B, map_size] dense bitmaps (the parity /
    state-export shape; map_size = 64KB per module). u_slots are
    unique so .set suffices."""
    b = by_slot.shape[0]
    out = jnp.zeros((b, map_size), jnp.uint8)
    return out.at[:, u_slots].set(by_slot)


def _outside_mask(u_slots: jax.Array, map_size: int) -> jax.Array:
    """uint8[map_size]: the constant simplify_trace contribution of
    slots outside the universe (class 1 everywhere, 0 at u_slots)."""
    m = jnp.full((map_size,), 1, jnp.uint8)
    return m.at[u_slots].set(0)


def static_triage(vb: jax.Array, vc: jax.Array, vh: jax.Array,
                  counts: jax.Array, u_slots: jax.Array,
                  seg_id: jax.Array, crash: jax.Array,
                  hang: jax.Array):
    """Fused throughput triage over the static universe.

    Args: vb/vc/vh uint8[MAP_SIZE] virgin maps, counts uint8[B, E+1],
    u_slots int32[U], seg_id int32[E], crash/hang bool[B].
    Returns (rets int32[B], uc bool[B], uh bool[B], vb', vc', vh') —
    same contract as sparse_coverage.sparse_triage, exact dense
    semantics (all lanes judged vs the incoming maps, in-batch dedup
    by map hash, virgin updates folded over the new lanes).
    """
    u = u_slots.shape[0]
    by_slot = counts_by_slot(counts, seg_id, u)       # [B, U]
    cls = classify_counts(by_slot)
    simp = jnp.where(by_slot != 0, jnp.uint8(128), jnp.uint8(1))

    def novelty(virgin, classes):
        v = virgin[u_slots][None, :]                  # [1, U]
        new_count = jnp.any((classes & v) != 0, axis=1)
        new_tuple = jnp.any((classes != 0) & (v == 0xFF), axis=1)
        return jnp.where(new_tuple, 2, jnp.where(new_count, 1, 0))

    rets = novelty(vb, cls)
    crash_rets = novelty(vc, simp)
    hang_rets = novelty(vh, simp)

    # dedup on CLASSIFIED counts (two lanes whose hit counts fall in
    # the same AFL buckets are the same path — hashing raw counts
    # would double-report them; sparse_triage hashed classes too)
    hashes = stream_hash(cls.astype(jnp.uint32))
    first_all, first_crash, first_hang = _first_occurrence_multi(
        hashes, crash, hang)
    rets = jnp.where(first_all, rets, 0).astype(jnp.int32)
    uc = first_crash & (crash_rets > 0)
    uh = first_hang & (hang_rets > 0)

    def upd(virgin, classes, active, with_outside):
        """Clear the OR of active lanes' class bits; crash/hang maps
        also clear the constant outside-universe class-1 pattern
        (dense simplify_trace parity)."""
        def do(v):
            seen = jax.lax.reduce(
                jnp.where(active[:, None], classes, jnp.uint8(0)),
                jnp.uint8(0), jax.lax.bitwise_or, dimensions=(0,))
            v = v.at[u_slots].set(v[u_slots] & ~seen)
            if with_outside:
                v = v & ~_outside_mask(u_slots, v.shape[0])
            return v
        return jax.lax.cond(jnp.any(active), do, lambda v: v, virgin)

    vb2 = upd(vb, cls, rets > 0, False)
    vc2 = upd(vc, simp, uc, True)
    vh2 = upd(vh, simp, uh, True)
    return rets, uc, uh, vb2, vc2, vh2
