"""Device-resident generation loop — G full fuzzing generations per
host round-trip.

The host-driven loop (fuzzer/loop.py) returns to the host every K
batches even on the fused superbatch path: novelty verdicts transfer,
findings triage, and corpus reseeding all run host-side, and
kb-timeline (PR 5) exists precisely because those stages bubble the
device.  This module closes the loop ON the device (ROADMAP item 1,
the PTrix move — keep the feedback computation where the throughput
is): one jitted program runs

    seed-slot sample -> havoc mutate -> KBVM execute -> classify ->
    novelty vs device-resident virgin maps -> findings-ring append ->
    seed-slot ring reseed

G times in a ``lax.scan``, and the host drains ONE bounded findings
report + admission ledger per dispatch.

Device-resident state threaded through the scan carry:

  * the three AFL virgin maps (``virgin_bits``/``crash``/``tmout``)
    with ``_np_has_new_bits`` semantics replicated exactly (byte-wise
    ``virgin &= ~trace``, the 0xFF new-tuple vs new-count 1/2 ret
    distinction, crash/hang ``simplify_trace`` maps) — the same
    ``_triage_counts`` tail every other engine uses, parity-pinned in
    tests/test_generations.py;
  * a seed-slot ring: S slots x max_len bytes + lengths + per-slot
    hit/find stats.  Slot 0 pins the base seed; edge-novel lanes
    (ret 2) are admitted FIFO into slots 1..S-1 (deterministic
    eviction: admission k lands in slot ``1 + k % (S-1)``), at most
    ``adm_cap`` per generation in lane order.  Every admission is
    recorded in a per-generation ledger the host replays, so the
    corpus store / scheduler arms / events stay in contract with the
    host loop;
  * a bounded findings ring (packed verdict byte, generation index,
    lane iteration id, mutant bytes): interesting lanes append in
    (generation, lane) order — exactly the order host triage would
    have seen them — and overflow is COUNTED via the monotone write
    pointer (``findings_ring_drops``), never silent.

Candidate parity: per-lane PRNG keys are ``fold_in(base_key,
absolute_iteration)`` — the same derivation as the mutator's
``_keys`` and the fused kernel — so with reseeding off the candidate
stream is bit-identical to the host-driven loop and the two produce
the same findings (the determinism gate in tests).  With reseeding
on, generation g mutates the ring slot picked by a ``_mix32`` draw
over the filled slots — deterministic and host-replayable, but
intentionally different seeds than the host bandit would pick.

The stateful session tier (killerbeez_tpu/stateful/) plugs in via
the ``stateful`` static option: each candidate executes as a framed
message SEQUENCE (the sequence loop is a scan-within-this-scan) and
a fourth virgin map — state x edge — rides the carry, with the
per-lane verdict becoming ``max(classic, state)``.  Same parity
doctrine, pinned in tests/test_stateful.py.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import FUZZ_HANG, FUZZ_NONE, FUZZ_RUNNING
from ..models.vm import _mix32

#: default seed-slot ring size (slot 0 = pinned base seed)
DEFAULT_RING_SLOTS = 32
#: default bounded findings-ring capacity per dispatch
DEFAULT_FINDINGS_CAP = 16384
#: default max ring admissions per generation (lane order)
DEFAULT_ADM_CAP = 8


def gen_ring_caps(gen_admits: int, gen_findings_cap: int,
                  batch: int, slots: int) -> tuple:
    """Shared --generations ring sizing for the single-chip dispatch
    (jit_harness) AND the per-shard mesh dispatch (parallel/campaign,
    against the per-chip batch): clamp the per-generation admission
    cap to the ring's S-1 distinct admission slots, and auto-size the
    findings ring when no explicit cap is set.  Returns
    ``(adm_cap, findings_cap)``.

    Auto-cap rationale: every generation pays a nonzero + gather +
    scatter of width min(cap, batch) to append into the findings
    ring, so the default stays WELL below the batch shape — measured
    on CPU at -b 2048/G=8, cap 256 runs 1.25x the host loop while
    cap >= 1024 loses the whole win to the append machinery.
    Steady-state interesting lanes are rare (that's the premise of
    the mode); overflow is counted and warned, and explicit
    gen_findings_cap values are honored."""
    adm_cap = min(max(int(gen_admits), 1), int(slots) - 1)
    cap = int(gen_findings_cap)
    if cap <= 0:
        cap = min(DEFAULT_FINDINGS_CAP, max(int(batch) // 8, 256))
    return adm_cap, cap


def carry_donation_argnums(backend: str, argnums) -> tuple:
    """Buffer-donation policy for the generation-scan carry state:
    the ring + virgin buffers update in place instead of being copied
    every dispatch.  Never donate arrays the outcome report exports
    (``ring_filled``) — the loop's double buffer reads the report
    AFTER the next dispatch has consumed the carry.  CPU backends
    don't implement donation (jax warns per call), so the policy is
    empty there — the tier-1/CI surface stays quiet and the TPU path
    gets the in-place update."""
    if backend == "cpu":
        return ()
    return tuple(argnums)


class GenerationOutcome(NamedTuple):
    """One G-generation dispatch's host-facing report — all LAZY
    device arrays until ``materialize()``."""
    # bounded findings ring (valid rows: first min(fr_ptr, cap))
    fr_pack: Any      # uint8[F]  pack_verdicts lane byte
    fr_gen: Any       # int32[F]  global generation index
    fr_iter: Any      # uint32[F] absolute mutator iteration
    fr_len: Any       # int32[F]
    fr_bufs: Any      # uint8[F, L]
    fr_ptr: Any       # int32 scalar: TOTAL interesting lanes seen
    # per-generation ledger
    sel: Any          # int32[G] ring slot each generation mutated
    adm_raw: Any      # int32[G] edge-novel lanes (uncapped)
    adm_valid: Any    # int32[G, A]
    adm_slot: Any     # int32[G, A]
    adm_iter: Any     # uint32[G, A]
    adm_len: Any      # int32[G, A]
    adm_bufs: Any     # uint8[G, A, L]
    ring_filled: Any  # int32[S] final ring occupancy (gauge)
    # dispatch metadata (host ints)
    gen0: int = 0     # global generation index of this dispatch's gen 0
    g: int = 0        # generations in this dispatch
    n_real: int = 0   # real (non-padding) lanes per generation
    cap: int = 0      # findings-ring capacity F

    def prefetch(self) -> None:
        """Start device->host copies without blocking (the loop
        enqueues the next dispatch while these land)."""
        for a in self:
            fn = getattr(a, "copy_to_host_async", None)
            if fn is not None:
                fn()

    def materialize(self) -> "GenerationOutcome":
        """Force every field to numpy (the blocking device wait the
        loop wraps in its watchdog guard)."""
        return self._replace(**{
            f: (np.asarray(v) if hasattr(v, "shape") else v)
            for f, v in self._asdict().items()})


class MeshGenerationOutcome(NamedTuple):
    """One mesh dispatch's host-facing report: the per-dp-shard twin
    of ``GenerationOutcome``.  Every ring/ledger field carries a
    leading ``dp`` axis (shard d's findings ring, seed-slot ring and
    admission ledger are independent device state); the loop drains
    shards deterministically in shard order via ``shard(d)`` views so
    store/arms/events stay in the host-loop contract regardless of
    drain interleaving."""
    fr_pack: Any      # uint8[dp, F]
    fr_gen: Any       # int32[dp, F]
    fr_iter: Any      # uint32[dp, F]
    fr_len: Any       # int32[dp, F]
    fr_bufs: Any      # uint8[dp, F, L]
    fr_ptr: Any       # int32[dp]
    sel: Any          # int32[dp, G]
    adm_raw: Any      # int32[dp, G]
    adm_valid: Any    # int32[dp, G, A]
    adm_slot: Any     # int32[dp, G, A]
    adm_iter: Any     # uint32[dp, G, A]
    adm_len: Any      # int32[dp, G, A]
    adm_bufs: Any     # uint8[dp, G, A, L]
    ring_filled: Any  # int32[dp, S]
    gen0: int = 0
    g: int = 0
    n_real: int = 0   # GLOBAL lanes per generation (dp x per-chip)
    cap: int = 0      # findings-ring capacity F PER SHARD
    n_shards: int = 1

    def prefetch(self) -> None:
        for a in self:
            fn = getattr(a, "copy_to_host_async", None)
            if fn is not None:
                fn()

    def materialize(self) -> "MeshGenerationOutcome":
        return self._replace(**{
            f: (np.asarray(v) if hasattr(v, "shape") else v)
            for f, v in self._asdict().items()})

    def shard(self, d: int) -> GenerationOutcome:
        """Shard ``d``'s view as a single-chip-shaped outcome (call
        after ``materialize()``)."""
        return GenerationOutcome(
            fr_pack=self.fr_pack[d], fr_gen=self.fr_gen[d],
            fr_iter=self.fr_iter[d], fr_len=self.fr_len[d],
            fr_bufs=self.fr_bufs[d], fr_ptr=self.fr_ptr[d],
            sel=self.sel[d], adm_raw=self.adm_raw[d],
            adm_valid=self.adm_valid[d], adm_slot=self.adm_slot[d],
            adm_iter=self.adm_iter[d], adm_len=self.adm_len[d],
            adm_bufs=self.adm_bufs[d], ring_filled=self.ring_filled[d],
            gen0=self.gen0, g=self.g,
            n_real=self.n_real // max(self.n_shards, 1), cap=self.cap)


def _select_slot(ring_filled, gen_id, salt):
    """Deterministic seed-slot pick for one generation: a _mix32 draw
    over the FILLED slots (slot 0 is always filled).  Pure uint32
    integer mixing so the host can replay the policy bit-exactly."""
    nf = jnp.sum(ring_filled).astype(jnp.uint32)
    r = _mix32(gen_id.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
               ^ salt.astype(jnp.uint32))
    k = (r % jnp.maximum(nf, 1)).astype(jnp.int32)
    cs = jnp.cumsum(ring_filled)
    return jnp.argmax(cs > k).astype(jnp.int32)


def np_select_slot(filled: np.ndarray, gen_id: int, salt: int) -> int:
    """Host replay of ``_select_slot`` (numpy, bit-exact) — the
    deterministic-policy witness the parity tests pin."""
    m = 0xFFFFFFFF
    x = ((int(gen_id) * 0x9E3779B9) & m) ^ (int(salt) & m)
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & m
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & m
    x ^= x >> 16
    nf = max(int(np.sum(filled)), 1)
    k = x % nf
    return int(np.argmax(np.cumsum(filled) > k))


def _cached_slot_mask(learn_params, seed_buf, seed_len, sel,
                      mask_cache, mask_valid):
    """Per-slot learned-mask cache lookup, shared by BOTH generation
    scans (the single-chip scan here and the shard_map'd mesh scan in
    ``parallel/distributed.py``): a slot's quantized mask is a pure
    function of the weights (fixed for a dispatch) and the slot
    bytes, so re-selecting an unchanged slot skips saliency inference
    entirely (``lax.cond``); cached or fresh, the mask bytes are
    identical, so the candidate stream — and the v0 parity pins —
    are untouched.  Returns ``(mask, mask_cache', mask_valid')``;
    admission invalidation is ``_invalidate_admitted_masks``."""
    from ..learn.model import masked_saliency
    mask = jax.lax.cond(
        mask_valid[sel] > 0,
        lambda: mask_cache[sel],
        lambda: masked_saliency(learn_params, seed_buf, seed_len))
    return (mask, mask_cache.at[sel].set(mask),
            mask_valid.at[sel].set(1))


def _invalidate_admitted_masks(mask_valid, ledger, n_slots):
    """Ring admission overwrote slots 1..S-1 rows: their cached
    masks are stale the moment new bytes land.  ``ledger`` is the
    ``_ring_append_and_admit`` ledger (row 0 = validity, row 1 =
    slot * validity; slot 0 is never admitted into, so nonzero means
    a real admission)."""
    inv = jnp.where(ledger[0] > 0, ledger[1], n_slots)
    return mask_valid.at[inv].set(0, mode="drop")


def _ring_append_and_admit(flags, aflags, packed, its, bufs, lens,
                           gen_id, sel, ring, fr, adm_cap, reseed):
    """One generation's findings-ring append + FIFO seed-slot
    admission + admission-ledger emission, shared by BOTH generation
    scans (the single-chip ``lax.scan`` here and the shard_map'd mesh
    scan in ``parallel/distributed.py``, which runs it per dp shard).
    Host replay (``fuzzer/loop.py``) and the parity suites pin the
    semantics: the findings pointer COUNTS overflow (rows past the
    ring capacity drop, never silently), admissions are FIFO into
    slots 1..S-1, and ledger rows past the admission count are masked
    to zero.

    ``ring`` / ``fr`` are the carried ``(bufs, lens, filled, hits,
    finds, ptr)`` / ``(pack, gen, iter, len, bufs, ptr)`` tuples;
    ``flags`` marks the interesting lanes, ``aflags`` the ring-
    admissible ones (both already masked to real lanes by the
    caller).  Returns ``(ring', fr', araw, ledger)``."""
    ring_bufs, ring_lens, ring_filled, ring_hits, ring_finds, \
        ring_ptr = ring
    fr_pack, fr_gen, fr_iter, fr_len, fr_bufs, fr_ptr = fr
    F = fr_pack.shape[0]
    S, L = ring_bufs.shape
    A = int(adm_cap)
    cap_g = min(F, flags.shape[0])

    # findings ring: interesting lanes append in lane order at the
    # carried write pointer; rows past F drop (mode="drop") but the
    # pointer keeps counting so overflow is never silent
    raw = jnp.sum(flags).astype(jnp.int32)
    (idx,) = jnp.nonzero(flags, size=cap_g, fill_value=0)
    pos = fr_ptr + jnp.arange(cap_g, dtype=jnp.int32)
    valid = (jnp.arange(cap_g) < jnp.minimum(raw, cap_g)) & (pos < F)
    tgt = jnp.where(valid, pos, F)
    fr_pack = fr_pack.at[tgt].set(packed[idx], mode="drop")
    fr_gen = fr_gen.at[tgt].set(gen_id.astype(jnp.int32),
                                mode="drop")
    fr_iter = fr_iter.at[tgt].set(its[idx], mode="drop")
    fr_len = fr_len.at[tgt].set(lens[idx].astype(jnp.int32),
                                mode="drop")
    fr_bufs = fr_bufs.at[tgt].set(bufs[idx].astype(jnp.uint8),
                                  mode="drop")
    fr_ptr = fr_ptr + raw

    # per-slot stats for the GENERATING slot (before any admission
    # overwrites it)
    araw = jnp.sum(aflags).astype(jnp.int32)
    ring_hits = ring_hits.at[sel].add(1)
    ring_finds = ring_finds.at[sel].add(araw)

    if reseed:
        # FIFO admission of the first A edge-novel lanes into slots
        # 1..S-1; slots are distinct (A <= S-1)
        (aidx,) = jnp.nonzero(aflags, size=A, fill_value=0)
        n_adm = jnp.minimum(araw, A)
        avalid = jnp.arange(A) < n_adm
        slots = 1 + (ring_ptr + jnp.arange(A, dtype=jnp.int32)) \
            % (S - 1)
        tgt_s = jnp.where(avalid, slots, S)
        ring_bufs = ring_bufs.at[tgt_s].set(
            bufs[aidx].astype(jnp.uint8), mode="drop")
        ring_lens = ring_lens.at[tgt_s].set(
            lens[aidx].astype(jnp.int32), mode="drop")
        ring_filled = ring_filled.at[tgt_s].set(1, mode="drop")
        ring_hits = ring_hits.at[tgt_s].set(0, mode="drop")
        ring_finds = ring_finds.at[tgt_s].set(0, mode="drop")
        ring_ptr = ring_ptr + n_adm
        ledger = (avalid.astype(jnp.int32), slots * avalid,
                  its[aidx] * avalid.astype(jnp.uint32),
                  lens[aidx].astype(jnp.int32) * avalid,
                  bufs[aidx].astype(jnp.uint8))
    else:
        zA = jnp.zeros((A,), jnp.int32)
        ledger = (zA, zA, zA.astype(jnp.uint32), zA,
                  jnp.zeros((A, L), jnp.uint8))
    return ((ring_bufs, ring_lens, ring_filled, ring_hits,
             ring_finds, ring_ptr),
            (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs, fr_ptr),
            araw, ledger)


def _run_generations_impl(instrs, edge_table, u_slots, seg_id,
                    ring_bufs, ring_lens, ring_filled, ring_hits,
                    ring_finds, ring_ptr,
                    base_key, its0, n_real, gen0, salt,
                    vb, vc, vh, vs, learn_params=(),
                    grammar_tables=(),
                    mem_size=0, max_steps=0, n_edges=0, exact=True,
                    stack_pow2=4,
                    g=1, engine="xla", phase1_steps=0,
                    dots=("f32", "f32"), reseed=True,
                    adm_cap=DEFAULT_ADM_CAP,
                    findings_cap=DEFAULT_FINDINGS_CAP,
                    interpret=False, stateful=None, learn=False,
                    grammar=False):
    """G generations in ONE device program.  Returns (new virgin maps,
    new ring state, GenerationOutcome fields) — see module docstring
    for the state/replay contract.

    ``its0`` uint32[B] are generation 0's absolute iteration indices
    (padded to the batch shape with lane-0 repeats); generation j
    executes ``its0 + j*n_real`` — monotonic mutator consumption,
    bit-identical to k sequential host batches.  ``engine`` picks the
    mutate+execute tier: "xla" (vmapped havoc_at + the one-hot
    engine; the CPU/CI path) or "pallas"/"pallas_fused" (the fused
    VMEM kernel).  ``exact``/``dots``/``phase1_steps`` thread through
    unchanged from the jit_harness config so novelty verdicts are
    identical to the host-driven loop's.

    ``stateful`` turns each candidate into a framed SESSION (the
    sequence loop is a scan-within-this-scan): a static
    ``(m_max, n_states, state_reg)`` tuple, with ``vs`` the
    state x edge virgin map threaded through the carry alongside the
    classic three (stateful/coverage.py).  The per-lane novelty
    verdict becomes ``max(classic, state)`` — the state dimension
    ADDS findings to the ring and admissions, exactly like the
    host-driven stateful loop.  Requires engine "xla" (the session
    executor runs the one-hot engine).  With ``stateful=None`` the
    ``vs`` carry is a 1-byte dummy, returned untouched.

    ``learn`` (static) + ``learn_params`` (the byte-saliency model
    weights, learn/model.py) shape mutation IN the scan: each
    generation runs model inference on the selected seed-ring slot,
    quantizes the saliency to a focus mask, and mutates through the
    masked havoc kernel — per-generation shaping with zero host
    involvement.  Requires engine "xla" (like sessions).  A
    version-0 model quantizes to all-ones and the masked kernel is
    then bit-identical to ``havoc_at`` — the shaped scan IS the
    unshaped scan until training starts (parity-pinned in
    tests/test_learn.py).

    ``grammar`` (static) + ``grammar_tables`` (the compiled field
    program / token / alphabet tables, ``GrammarTables.device()``)
    run structure-aware mutation IN the scan: candidates come from
    ``grammar_havoc_at`` — blind havoc and structured stages
    interleaved per lane by a stage byte (killerbeez_tpu/grammar/).
    Requires engine "xla" like sessions and shaping, and is mutually
    exclusive with ``learn`` (both would own the mutation kernel).
    Under the degenerate grammar the structured kernel is
    bit-identical to ``havoc_at`` — the parity anchor pinned in
    tests/test_grammar.py.
    """
    from ..instrumentation.base import pack_verdicts
    from ..instrumentation.jit_harness import _triage_counts

    b = its0.shape[0]
    L = ring_bufs.shape[1]
    F = int(findings_cap)
    A = int(adm_cap) if reseed else 1   # ledger shape floor
    lanes_real = jnp.arange(b) < n_real
    if stateful is not None and engine != "xla":
        raise ValueError(
            "stateful generations need the xla engine (the session "
            "executor is the one-hot engine path)")
    if learn and engine != "xla":
        raise ValueError(
            "learned mutation shaping needs the xla engine (the "
            "fused VMEM kernel generates candidates in-kernel and "
            "cannot consume a per-generation mask)")
    if grammar and engine != "xla":
        raise ValueError(
            "grammar-structured generations need the xla engine "
            "(the fused VMEM kernel generates candidates in-kernel "
            "and cannot consume the structure tables)")
    if grammar and learn:
        raise ValueError(
            "grammar and learn are mutually exclusive — both tiers "
            "would own the in-scan mutation kernel (run the learned "
            "mask OR the structure tables, not both)")

    def one_generation(carry, j):
        (vb, vc, vh, vs, ring_bufs, ring_lens, ring_filled,
         ring_hits, ring_finds, ring_ptr, fr_pack, fr_gen, fr_iter,
         fr_len, fr_bufs, fr_ptr, mask_cache, mask_valid) = carry
        gen_id = gen0 + j
        if reseed:
            sel = _select_slot(ring_filled, gen_id, salt)
        else:
            sel = jnp.int32(0)
        seed_buf = ring_bufs[sel]
        seed_len = ring_lens[sel]
        its = its0 + j * n_real.astype(jnp.uint32)
        keys = jax.vmap(
            lambda i: jax.random.fold_in(base_key, i))(its)
        if engine in ("pallas", "pallas_fused"):
            from .vm_kernel import (
                fuzz_batch_pallas_2phase, havoc_words_for_keys,
            )
            words = havoc_words_for_keys(keys, stack_pow2)
            res, bufs, lens = fuzz_batch_pallas_2phase(
                instrs, edge_table, seed_buf, seed_len, words,
                mem_size, max_steps, n_edges, stack_pow2=stack_pow2,
                phase1_steps=phase1_steps, interpret=interpret,
                dots=dots)
        else:
            from .mutate_core import havoc_at, havoc_mask_at
            from ..models.vm import _run_batch_impl
            if grammar:
                # structure-aware candidates: the grammar kernel
                # interleaves blind and structured stages per lane
                # (stage byte from the side stream); degenerate
                # tables make this branch bit-identical to the
                # havoc_at branch below (the parity anchor)
                from ..grammar.device import grammar_havoc_at
                bufs, lens = jax.vmap(
                    lambda k: grammar_havoc_at(
                        seed_buf, seed_len, k, grammar_tables,
                        stack_pow2=stack_pow2))(keys)
            elif learn:
                # in-scan inference: saliency of THIS generation's
                # seed slot -> dense mask -> masked havoc.  The
                # branch is static, so campaigns without --learn
                # compile the exact historical program; the mask is
                # cached per ring slot in the carry (_cached_slot_mask,
                # admission invalidates below).
                mask, mask_cache, mask_valid = _cached_slot_mask(
                    learn_params, seed_buf, seed_len, sel,
                    mask_cache, mask_valid)
                bufs, lens = jax.vmap(
                    lambda k: havoc_mask_at(
                        seed_buf, seed_len, k, mask,
                        stack_pow2=stack_pow2))(keys)
            else:
                bufs, lens = jax.vmap(
                    lambda k: havoc_at(seed_buf, seed_len, k,
                                       stack_pow2=stack_pow2))(keys)
            if stateful is not None:
                from ..stateful.session import _run_session_impl
                m_max, n_states, state_reg = stateful
                res = _run_session_impl(
                    instrs, edge_table, bufs, lens, mem_size,
                    max_steps, n_edges, m_max, n_states, state_reg)
            else:
                res = _run_batch_impl(instrs, edge_table, bufs, lens,
                                      mem_size, max_steps, n_edges,
                                      False)
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)
        new_paths, uc, uh, vb, vc, vh = _triage_counts(
            res.counts, statuses, u_slots, seg_id, vb, vc, vh, exact)
        if stateful is not None:
            from ..stateful.coverage import (
                state_triage, state_triage_exact,
            )
            s_rets, vs = (state_triage_exact if exact
                          else state_triage)(vs, res.se_counts)
            new_paths = jnp.maximum(new_paths, s_rets)
        packed = pack_verdicts(statuses, new_paths, uc, uh)

        flags = ((statuses != FUZZ_NONE) | (new_paths > 0)) \
            & lanes_real
        aflags = (new_paths == 2) & lanes_real
        ((ring_bufs, ring_lens, ring_filled, ring_hits, ring_finds,
          ring_ptr),
         (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs, fr_ptr),
         araw, ledger) = _ring_append_and_admit(
            flags, aflags, packed, its, bufs, lens, gen_id, sel,
            (ring_bufs, ring_lens, ring_filled, ring_hits,
             ring_finds, ring_ptr),
            (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs, fr_ptr),
            A, reseed)
        if learn and reseed:
            mask_valid = _invalidate_admitted_masks(
                mask_valid, ledger, ring_bufs.shape[0])

        carry = (vb, vc, vh, vs, ring_bufs, ring_lens, ring_filled,
                 ring_hits, ring_finds, ring_ptr, fr_pack, fr_gen,
                 fr_iter, fr_len, fr_bufs, fr_ptr, mask_cache,
                 mask_valid)
        return carry, (sel, araw) + ledger

    S = ring_bufs.shape[0]
    # per-slot learned-mask cache (all-invalid at dispatch start:
    # the weights retrain between dispatches); 1-byte dummies keep
    # the carry cheap when learning is off
    mc_shape = (S, L) if learn else (1, 1)
    mv_shape = (S,) if learn else (1,)
    carry0 = (vb, vc, vh, vs, ring_bufs, ring_lens, ring_filled,
              ring_hits, ring_finds, ring_ptr,
              jnp.zeros((F,), jnp.uint8),        # fr_pack
              jnp.zeros((F,), jnp.int32),        # fr_gen
              jnp.zeros((F,), jnp.uint32),       # fr_iter
              jnp.zeros((F,), jnp.int32),        # fr_len
              jnp.zeros((F, L), jnp.uint8),      # fr_bufs
              jnp.int32(0),                      # fr_ptr
              jnp.zeros(mc_shape, jnp.uint8),    # mask_cache
              jnp.zeros(mv_shape, jnp.int32))    # mask_valid
    carry, ys = jax.lax.scan(
        one_generation, carry0,
        jnp.arange(g, dtype=jnp.uint32))
    (vb, vc, vh, vs, ring_bufs, ring_lens, ring_filled, ring_hits,
     ring_finds, ring_ptr, fr_pack, fr_gen, fr_iter, fr_len,
     fr_bufs, fr_ptr, _mc, _mv) = carry
    (sel, adm_raw, adm_valid, adm_slot, adm_iter, adm_len,
     adm_bufs) = ys
    return ((vb, vc, vh, vs),
            (ring_bufs, ring_lens, ring_filled, ring_hits,
             ring_finds, ring_ptr),
            (fr_pack, fr_gen, fr_iter, fr_len, fr_bufs, fr_ptr,
             sel, adm_raw, adm_valid, adm_slot, adm_iter, adm_len,
             adm_bufs, ring_filled))


#: positional args of _run_generations_impl that are pure carry state
#: (consumed each dispatch, safe to update in place): ring_bufs(4),
#: ring_lens(5), ring_hits(7), ring_finds(8), vb(15), vc(16), vh(17),
#: vs(18) — the state x edge virgin map (a 1-byte dummy when the
#: stateful tier is off, returned as-is so the donation stays usable).
#: ring_filled(6)/ring_ptr(9) are exported in the outcome report and
#: must survive the next dispatch — never donated.
_CARRY_ARGNUMS = (4, 5, 7, 8, 15, 16, 17, 18)

_RUN_GENERATIONS_JIT = None


def run_generations(*args, **kwargs):
    """Jitted entry point for the single-chip generation scan, built
    lazily so the donation policy can consult the active backend (see
    ``carry_donation_argnums``: donated carry on accelerators, plain
    copies on CPU)."""
    global _RUN_GENERATIONS_JIT
    if _RUN_GENERATIONS_JIT is None:
        _RUN_GENERATIONS_JIT = jax.jit(
            _run_generations_impl,
            static_argnames=("mem_size", "max_steps", "n_edges",
                             "exact", "stack_pow2", "g", "engine",
                             "phase1_steps", "dots", "reseed",
                             "adm_cap", "findings_cap", "interpret",
                             "stateful", "learn", "grammar"),
            donate_argnums=carry_donation_argnums(
                jax.default_backend(), _CARRY_ARGNUMS))
    return _RUN_GENERATIONS_JIT(*args, **kwargs)
