"""Hashes for coverage novelty short-circuiting and state dedup.

The reference short-circuits full bitmap scans with a 32-bit hash of
the classified map (reference dynamorio_instrumentation.c:1448 via
winafl_hash.h) and hashes Intel-PT packet streams with XXH64
(linux_ipt_instrumentation.c:293-377). Here:

  * ``murmur3_32`` — MurmurHash3 x86_32 (public algorithm, Austin
    Appleby, public domain), implemented in uint32 lane ops so it runs
    on TPU under vmap; used as the per-lane bitmap hash.
  * ``xxh64`` — XXH64 (public algorithm, Yann Collet, BSD) in numpy
    uint64 for host-side stream hashing (PT-style trace hashing, state
    files). TPU has no native u64, so device paths use murmur3_32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

HASH_SEED = np.uint32(0xA5B35705)  # fuzzer-wide default hash seed


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


@partial(jax.jit, static_argnames=())
def murmur3_32(data_u32: jax.Array,
               seed: jax.Array | int = HASH_SEED) -> jax.Array:
    """MurmurHash3_x86_32 over a uint32-word view of the buffer.

    ``data_u32`` is uint32[..., W] (the last axis is the word stream;
    leading axes are batch). The byte length is ``4*W`` — coverage maps
    are always word-aligned so the tail-byte path of the public
    algorithm never triggers. Returns uint32[...].
    """
    data_u32 = data_u32.astype(jnp.uint32)
    c1 = jnp.uint32(0xCC9E2D51)
    c2 = jnp.uint32(0x1B873593)

    k = data_u32 * c1
    k = _rotl32(k, 15)
    k = k * c2

    def body(h, kk):
        h = h ^ kk
        h = _rotl32(h, 13)
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
        return h, None

    batch_shape = data_u32.shape[:-1]
    h0 = jnp.broadcast_to(jnp.uint32(seed), batch_shape)
    # scan over the word axis (moved to front) — fixed trip count, jit-safe
    kt = jnp.moveaxis(k, -1, 0)
    h, _ = jax.lax.scan(body, h0, kt)

    n_bytes = jnp.uint32(4 * data_u32.shape[-1])
    h = h ^ n_bytes
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def murmur3_32_np(data: bytes, seed: int = int(HASH_SEED)) -> int:
    """Host-side MurmurHash3_x86_32 reference (full algorithm incl.
    byte tail) for parity tests and host state hashing."""
    data = bytes(data)
    n = len(data)
    nblocks = n // 4
    h = np.uint32(seed)
    c1, c2 = np.uint32(0xCC9E2D51), np.uint32(0x1B873593)
    with np.errstate(over="ignore"):
        if nblocks:
            words = np.frombuffer(data[:nblocks * 4], dtype="<u4")
            for w in words:
                k = np.uint32(w) * c1
                k = np.uint32((int(k) << 15 | int(k) >> 17) & 0xFFFFFFFF)
                k = k * c2
                h = h ^ k
                h = np.uint32((int(h) << 13 | int(h) >> 19) & 0xFFFFFFFF)
                h = h * np.uint32(5) + np.uint32(0xE6546B64)
        tail = data[nblocks * 4:]
        k = np.uint32(0)
        if len(tail) >= 3:
            k = k ^ np.uint32(tail[2] << 16)
        if len(tail) >= 2:
            k = k ^ np.uint32(tail[1] << 8)
        if len(tail) >= 1:
            k = k ^ np.uint32(tail[0])
            k = k * c1
            k = np.uint32((int(k) << 15 | int(k) >> 17) & 0xFFFFFFFF)
            k = k * c2
            h = h ^ k
        h = h ^ np.uint32(n)
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
        h = h ^ (h >> np.uint32(16))
    return int(h)


# --- XXH64 (host, numpy uint64) --------------------------------------

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x: np.uint64, r: int) -> np.uint64:
    return np.uint64((int(x) << r | int(x) >> (64 - r)) & (2**64 - 1))


def _round64(acc: np.uint64, inp: np.uint64) -> np.uint64:
    with np.errstate(over="ignore"):
        acc = acc + inp * _P2
        acc = _rotl64(acc, 31)
        return acc * _P1


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64 of a byte string (public algorithm; used for PT-style
    trace-stream hashing parity with the reference's
    linux_ipt_instrumentation.c usage)."""
    data = bytes(data)
    n = len(data)
    seed = np.uint64(seed)
    i = 0
    with np.errstate(over="ignore"):
        if n >= 32:
            v1 = seed + _P1 + _P2
            v2 = seed + _P2
            v3 = seed + np.uint64(0)
            v4 = seed - _P1
            while i + 32 <= n:
                lanes = np.frombuffer(data[i:i + 32], dtype="<u8")
                v1 = _round64(v1, lanes[0])
                v2 = _round64(v2, lanes[1])
                v3 = _round64(v3, lanes[2])
                v4 = _round64(v4, lanes[3])
                i += 32
            h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
                 + _rotl64(v4, 18))
            for v in (v1, v2, v3, v4):
                h = h ^ _round64(np.uint64(0), v)
                h = h * _P1 + _P4
        else:
            h = seed + _P5
        h = h + np.uint64(n)
        while i + 8 <= n:
            k = _round64(np.uint64(0), np.frombuffer(
                data[i:i + 8], dtype="<u8")[0])
            h = h ^ k
            h = _rotl64(h, 27) * _P1 + _P4
            i += 8
        if i + 4 <= n:
            h = h ^ (np.uint64(np.frombuffer(
                data[i:i + 4], dtype="<u4")[0]) * _P1)
            h = _rotl64(h, 23) * _P2 + _P3
            i += 4
        while i < n:
            h = h ^ (np.uint64(data[i]) * _P5)
            h = _rotl64(h, 11) * _P1
            i += 1
        h = h ^ (h >> np.uint64(33))
        h = h * _P2
        h = h ^ (h >> np.uint64(29))
        h = h * _P3
        h = h ^ (h >> np.uint64(32))
    return int(h)


def hash_bitmaps(bitmaps: jax.Array,
                 seed: jax.Array | int = HASH_SEED) -> jax.Array:
    """Per-lane 32-bit hash of uint8[B, M] bitmaps (M % 4 == 0):
    the dynamorio-style short-circuit hash, batched on device."""
    b, m = bitmaps.shape
    words = jax.lax.bitcast_convert_type(
        bitmaps.reshape(b, m // 4, 4), jnp.uint32).reshape(b, m // 4)
    return murmur3_32(words, seed)
