"""AFL-style edge-coverage bitmap ops, vectorized for TPU.

Semantics are bit-for-bit the AFL contract the reference implements in
scalar C (reference afl_instrumentation.c:600-707 ``has_new_bits`` /
``simplify_trace``; dynamorio_instrumentation.c:265-334
``classify_counts`` + ignore-byte variant; merge AND-fold at
afl_instrumentation.c:116-140) — re-expressed as whole-array XLA ops.
The word-skipping in the C versions is a scalar-CPU optimization; on
TPU the VPU scans the 64KB map in a handful of vector ops, so the
natural formulation is the semantic one.

Conventions:
  * ``trace``  — uint8[MAP_SIZE] raw hit counts (wrapping, like C u8)
  * ``virgin`` — uint8[MAP_SIZE], starts all-0xFF; bits clear as seen
  * a *classified* trace has hit counts bucketed into power-of-2
    classes so that "new hit-count bucket" is expressible as a bit test
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import MAP_SIZE

# AFL count classes: hits -> {0,1,2,4,8,16,32,64,128}
#   0->0, 1->1, 2->2, 3->4, 4..7->8, 8..15->16, 16..31->32,
#   32..127->64, 128..255->128
_lookup = np.zeros(256, dtype=np.uint8)
_lookup[0] = 0
_lookup[1] = 1
_lookup[2] = 2
_lookup[3] = 4
_lookup[4:8] = 8
_lookup[8:16] = 16
_lookup[16:32] = 32
_lookup[32:128] = 64
_lookup[128:256] = 128
COUNT_CLASS_LOOKUP = _lookup


def classify_counts(trace: jax.Array) -> jax.Array:
    """Bucket raw hit counts into AFL count classes (any shape, uint8).

    Implemented as a compare/select chain, not a LUT gather — on TPU a
    256-entry table gather over a [B, 64K] tensor is ~1000x slower
    than eight vectorized compares (measured 4s vs 5ms at B=8192).
    """
    t = trace
    u8 = jnp.uint8
    return jnp.where(
        t < 4,
        # 0->0, 1->1, 2->2, 3->4
        jnp.where(t == 3, u8(4), t.astype(jnp.uint8)),
        jnp.where(t < 8, u8(8),
                  jnp.where(t < 16, u8(16),
                            jnp.where(t < 32, u8(32),
                                      jnp.where(t < 128, u8(64),
                                                u8(128))))))


def simplify_trace(trace: jax.Array) -> jax.Array:
    """Collapse a trace for hang/crash dedup maps: 0 -> 1, hit -> 128
    (reference afl_instrumentation.c:668-707)."""
    return jnp.where(trace == 0, jnp.uint8(1), jnp.uint8(128))


def has_new_bits(virgin: jax.Array, trace: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """One-exec novelty check against a virgin map.

    Returns ``(ret, new_virgin)`` where ret is 2 if some edge was hit
    for the first time ever, 1 if only a new hit-count bucket appeared,
    0 otherwise; and ``new_virgin = virgin & ~trace``. Matches the
    scalar loop at reference afl_instrumentation.c:600-662.
    """
    inter = trace & virgin
    new_count = jnp.any(inter != 0)
    new_tuple = jnp.any((trace != 0) & (virgin == 0xFF))
    ret = jnp.where(new_tuple, 2, jnp.where(new_count, 1, 0)).astype(jnp.int32)
    return ret, virgin & ~trace


def has_new_bits_with_ignore(virgin: jax.Array, trace: jax.Array,
                             ignore: jax.Array
                             ) -> Tuple[jax.Array, jax.Array]:
    """Novelty check masking nondeterministic bytes (reference
    dynamorio ``has_new_bits_with_ignore``; ignore masks come from the
    picker tool). ``ignore`` is uint8 and byte-granular like the
    reference: any nonzero ignore byte excludes that whole trace byte."""
    masked = jnp.where(ignore != 0, jnp.uint8(0), trace)
    return has_new_bits(virgin, masked)


def update_virgin(virgin: jax.Array, trace: jax.Array) -> jax.Array:
    return virgin & ~trace


def has_new_bits_seq(virgin: jax.Array, traces: jax.Array
                     ) -> Tuple[jax.Array, jax.Array]:
    """Sequential-parity novelty over a batch.

    Lane i is judged against the virgin map *after* lanes < i, exactly
    as if the reference's single-exec loop ran B times. Returns
    ``(rets int32[B], final_virgin)``. Used by parity tests and the
    exact-new-path-count acceptance gates (smoke_test expected counts).
    """
    def step(v, t):
        ret, v2 = has_new_bits(v, t)
        return v2, ret
    final_virgin, rets = jax.lax.scan(step, virgin, traces)
    return rets, final_virgin


def has_new_bits_batch(virgin: jax.Array, traces: jax.Array,
                       hashes: jax.Array,
                       active: jax.Array | None = None
                       ) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode batched novelty.

    All lanes are judged against the *same* incoming virgin map, then
    deduped within the batch by classified-bitmap hash: a lane counts
    as new only if it is the first occurrence of its hash in the batch.
    The virgin map is then updated with the OR of all new traces at
    once. Within-batch novelty can differ from sequential semantics in
    the same benign direction the reference's persistence mode does
    (smoke_test expects 3 vs 2 new paths there).

    Args:
      virgin: uint8[M]
      traces: uint8[B, M] classified traces
      hashes: uint32[B] per-lane bitmap hashes (for in-batch dedup)
      active: optional bool[B]; inactive lanes report 0 and don't
        update the virgin map (crash/hang-map filtering)
    Returns:
      (rets int32[B], new_virgin uint8[M])
    """
    inter = traces & virgin[None, :]
    new_count = jnp.any(inter != 0, axis=1)
    new_tuple = jnp.any((traces != 0) & (virgin[None, :] == 0xFF), axis=1)
    rets = jnp.where(new_tuple, 2, jnp.where(new_count, 1, 0))

    # first-occurrence-of-hash flag, O(B^2) bitmask compare on the VPU
    b = hashes.shape[0]
    if active is None:
        active = jnp.ones((b,), dtype=bool)
    same = (hashes[:, None] == hashes[None, :]) & active[None, :]
    earlier = jnp.tril(jnp.ones((b, b), dtype=bool), k=-1)
    first = ~jnp.any(same & earlier, axis=1)
    rets = jnp.where(first & active, rets, 0).astype(jnp.int32)

    any_new = (rets > 0)[:, None]
    # bits hit by new lanes: zero out non-new lanes, then byte-wise OR-fold
    seen = jax.lax.reduce(jnp.where(any_new, traces, jnp.uint8(0)),
                          jnp.uint8(0), jax.lax.bitwise_or, dimensions=(0,))
    return rets, virgin & ~seen


def merge_virgin(a: jax.Array, b: jax.Array) -> jax.Array:
    """Combine two virgin maps: coverage union = bitwise AND (cleared
    bits mean 'seen'; reference afl_instrumentation.c:116-140)."""
    return a & b


@partial(jax.jit, static_argnames=("map_size",))
def build_bitmap(edge_ids: jax.Array, valid: jax.Array,
                 map_size: int = MAP_SIZE) -> jax.Array:
    """Build per-lane hit-count bitmaps from executed-edge streams.

    The target-side runtime in the reference does
    ``trace_bits[cur ^ prev]++`` inline (afl_progs edge trampoline);
    the KBVM instead records the stream of edge ids during the scan and
    this op materializes the bitmaps with one batched scatter-add.

    Args:
      edge_ids: int32[B, T] edge ids in [0, map_size)
      valid:    bool[B, T]  mask for steps actually executed
    Returns:
      uint8[B, map_size] wrapping hit counts
    """
    b = edge_ids.shape[0]
    # out-of-range ids (incl. negative, which .at[] would wrap) -> dropped
    ok = valid & (edge_ids >= 0) & (edge_ids < map_size)
    ids = jnp.where(ok, edge_ids, map_size)
    zeros = jnp.zeros((b, map_size), dtype=jnp.uint8)
    return zeros.at[jnp.arange(b)[:, None], ids].add(
        jnp.uint8(1), mode="drop")


def count_non_255_bytes(virgin: jax.Array) -> jax.Array:
    """Number of virgin-map bytes touched (AFL's coverage%, used in
    state reporting)."""
    return jnp.sum(virgin != 0xFF)


def count_bytes(trace: jax.Array) -> jax.Array:
    """Number of nonzero trace bytes (edges hit this exec)."""
    return jnp.sum(trace != 0)
