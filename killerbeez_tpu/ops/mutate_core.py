"""Pure, jittable mutation kernels over fixed-size byte tensors.

The reference's mutators are scalar C DLLs mutating one buffer at a
time (API: docs/api/api_mutator.tex, SURVEY §2.4). On TPU a candidate
batch is generated in one shot: every kernel here is a pure function
of ``(buf uint8[L], length int32, iteration or PRNG key)`` returning
``(buf uint8[L], length int32)``, designed to be ``vmap``-ed over the
iteration/key axis. Deterministic mutators keep AFL's walking-order
semantics (iteration index decodes to the exact mutation), so parity
tests against the scalar contract hold lane-for-lane.

Buffers are padded to a static L; ``length`` tracks the live prefix.
Length-changing ops (havoc delete/insert) move bytes with gathers and
clamp to L.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

ARITH_MAX = 35  # AFL's bound for +/- arithmetic walks

INTERESTING_8 = np.array(
    [-128, -1, 0, 1, 16, 32, 64, 100, 127], dtype=np.int32)
INTERESTING_16 = np.array(
    [-32768, -129, 128, 255, 256, 512, 1000, 1024, 4096, 32767],
    dtype=np.int32)
INTERESTING_32 = np.array(
    [-2147483648, -100663046, -32769, 32768, 65535, 65536, 100663045,
     2147483647], dtype=np.int64)


# --------------------------------------------------------------------
# primitive byte/bit edits (mask-select based; no dynamic slicing)
# --------------------------------------------------------------------

def flip_bits(buf: jax.Array, start_bit: jax.Array,
              num_bits: int) -> jax.Array:
    """Flip ``num_bits`` consecutive bits starting at ``start_bit``,
    MSB-first within each byte (AFL's FLIP_BIT: 128 >> (b & 7))."""
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    mask = jnp.zeros((L,), dtype=jnp.uint8)
    for j in range(num_bits):  # num_bits is static and small (1/2/4)
        b = start_bit + j
        byte_i = b >> 3
        bit = jnp.uint8(128) >> (b & 7).astype(jnp.uint8)
        mask = mask | jnp.where(idx == byte_i, bit, jnp.uint8(0))
    return buf ^ mask


def write_bytes(buf: jax.Array, pos: jax.Array, value: jax.Array,
                width: int, big_endian: jax.Array | bool = False
                ) -> jax.Array:
    """Overwrite ``width`` bytes at ``pos`` with integer ``value``
    (uint32), little- or big-endian."""
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    value = value.astype(jnp.uint32) if hasattr(value, "astype") \
        else jnp.uint32(value)
    off = idx - pos  # byte k of the value lands at pos+k
    k = jnp.where(jnp.asarray(big_endian), width - 1 - off, off)
    vbytes = ((value >> (8 * jnp.clip(k, 0, width - 1))) & 0xFF
              ).astype(jnp.uint8)
    in_range = (off >= 0) & (off < width)
    return jnp.where(in_range, vbytes, buf)


def read_bytes(buf: jax.Array, pos: jax.Array, width: int,
               big_endian: jax.Array | bool = False) -> jax.Array:
    """Read ``width`` bytes at ``pos`` as uint32.

    One-hot selects instead of per-position scalar gathers: under vmap
    a scalar ``buf[pos+k]`` lowers to a lane-indexed gather the TPU
    executes orders of magnitude slower than the equivalent
    compare-select reduction."""
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    picked = [
        jnp.sum(jnp.where(idx == jnp.clip(pos + k, 0, L - 1),
                          buf, 0).astype(jnp.uint32))
        for k in range(width)]
    le = sum(picked[k] << (8 * k) for k in range(width))
    be = sum(picked[k] << (8 * (width - 1 - k)) for k in range(width))
    return jnp.where(jnp.asarray(big_endian), be, le).astype(jnp.uint32)


def delete_block(buf: jax.Array, length: jax.Array, pos: jax.Array,
                 del_len: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Remove ``del_len`` bytes at ``pos`` (shift-left gather)."""
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    src = jnp.where(idx >= pos, idx + del_len, idx)
    out = buf[jnp.clip(src, 0, L - 1)]
    new_len = jnp.maximum(length - del_len, 1)
    return out, new_len


def insert_block(buf: jax.Array, length: jax.Array, pos: jax.Array,
                 ins_len: jax.Array, src_pos: jax.Array,
                 fill: jax.Array, use_fill: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """Insert ``ins_len`` bytes at ``pos``: either a copy from
    ``src_pos`` (clone) or a constant ``fill`` byte. Result clamped
    to the static buffer size."""
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    after = idx >= pos + ins_len
    inside = (idx >= pos) & ~after
    shifted = buf[jnp.clip(idx - ins_len, 0, L - 1)]
    cloned = buf[jnp.clip(src_pos + (idx - pos), 0, L - 1)]
    ins = jnp.where(use_fill, fill.astype(jnp.uint8), cloned)
    out = jnp.where(after, shifted, jnp.where(inside, ins, buf))
    new_len = jnp.minimum(length + ins_len, L)
    return out, new_len


def overwrite_block(buf: jax.Array, pos: jax.Array, blk_len: jax.Array,
                    src_pos: jax.Array, fill: jax.Array,
                    use_fill: jax.Array) -> jax.Array:
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    inside = (idx >= pos) & (idx < pos + blk_len)
    cloned = buf[jnp.clip(src_pos + (idx - pos), 0, L - 1)]
    src = jnp.where(use_fill, fill.astype(jnp.uint8), cloned)
    return jnp.where(inside, src, buf)


# --------------------------------------------------------------------
# deterministic walking mutators (iteration index -> exact mutation)
# --------------------------------------------------------------------

def bit_flip_total(length_bytes: int, num_bits: int) -> int:
    """Number of iterations for a bit_flip walk (AFL: flip windows of
    num_bits consecutive bits, one start position per bit)."""
    total_bits = length_bytes * 8
    return max(total_bits - (num_bits - 1), 0)


@partial(jax.jit, static_argnames=("num_bits",))
def bit_flip_at(buf: jax.Array, length: jax.Array, it: jax.Array,
                num_bits: int = 1) -> Tuple[jax.Array, jax.Array]:
    """Iteration ``it`` of the bit_flip walk: flip bits
    [it, it+num_bits)."""
    return flip_bits(buf, it.astype(jnp.int32), num_bits), length


def arithmetic_total(length_bytes: int) -> int:
    """Iterations in the arithmetic walk: widths 1/2/4 bytes x
    positions x ARITH_MAX deltas x {+,-} x {LE, BE for w>1}."""
    n = 0
    for w, ends in ((1, 1), (2, 2), (4, 2)):
        pos = max(length_bytes - w + 1, 0)
        n += pos * ARITH_MAX * 2 * ends
    return n


def _arith_decode(it, length):
    """Decode iteration index -> (width_sel, pos, delta, sign, be).

    Stage layout per width w: pos-major, then delta (1..35), then sign,
    then endianness. Uses the static padded length for stage sizes is
    wrong — sizes depend on live length, so this returns stage-relative
    values computed with jnp ops from the dynamic ``length``.
    """
    it = it.astype(jnp.int32)
    sizes = []
    for w, ends in ((1, 1), (2, 2), (4, 2)):
        pos_n = jnp.maximum(length - w + 1, 0)
        sizes.append(pos_n * ARITH_MAX * 2 * ends)
    s1, s2, s4 = sizes
    in1 = it < s1
    in2 = (~in1) & (it < s1 + s2)
    local = jnp.where(in1, it, jnp.where(in2, it - s1, it - s1 - s2))
    width_sel = jnp.where(in1, 0, jnp.where(in2, 1, 2))  # 0:1B 1:2B 2:4B

    def split(local, ends):
        # local = ((pos * ARITH_MAX + (delta-1)) * 2 + sign) * ends + be
        be = local % ends
        rest = local // ends
        sign = rest % 2
        rest = rest // 2
        delta = rest % ARITH_MAX + 1
        pos = rest // ARITH_MAX
        return pos, delta, sign, be

    p1, d1, g1, b1 = split(local, 1)
    p2, d2, g2, b2 = split(local, 2)
    p4, d4, g4, b4 = split(local, 2)
    pos = jnp.where(in1, p1, jnp.where(in2, p2, p4))
    delta = jnp.where(in1, d1, jnp.where(in2, d2, d4))
    sign = jnp.where(in1, g1, jnp.where(in2, g2, g4))
    be = jnp.where(in1, b1, jnp.where(in2, b2, b4))
    return width_sel, pos, delta, sign, be


@jax.jit
def arithmetic_at(buf: jax.Array, length: jax.Array, it: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """Iteration ``it`` of the arithmetic walk: add/sub delta at a
    position for width 1/2/4, both endiannesses for wide ops."""
    width_sel, pos, delta, sign, be = _arith_decode(it, length)
    sdelta = jnp.where(sign == 0, delta, -delta).astype(jnp.uint32)

    outs = []
    for wi, w in enumerate((1, 2, 4)):
        cur = read_bytes(buf, pos, w, be.astype(bool))
        newv = (cur + sdelta) & jnp.uint32((1 << (8 * w)) - 1)
        outs.append(write_bytes(buf, pos, newv, w, be.astype(bool)))
    out = jnp.where(width_sel == 0, outs[0],
                    jnp.where(width_sel == 1, outs[1], outs[2]))
    return out, length


def interesting_total(length_bytes: int) -> int:
    n = max(length_bytes, 0) * len(INTERESTING_8)
    n += max(length_bytes - 1, 0) * len(INTERESTING_16) * 2
    n += max(length_bytes - 3, 0) * len(INTERESTING_32) * 2
    return n


def _interesting_decode(it, length):
    it = it.astype(jnp.int32)
    n8 = len(INTERESTING_8)
    n16 = len(INTERESTING_16)
    n32 = len(INTERESTING_32)
    s8 = jnp.maximum(length, 0) * n8
    s16 = jnp.maximum(length - 1, 0) * n16 * 2
    in8 = it < s8
    in16 = (~in8) & (it < s8 + s16)
    local = jnp.where(in8, it, jnp.where(in16, it - s8, it - s8 - s16))
    width_sel = jnp.where(in8, 0, jnp.where(in16, 1, 2))

    def split(local, nvals, ends):
        be = local % ends
        rest = local // ends
        val_i = rest % nvals
        pos = rest // nvals
        return pos, val_i, be

    p8, v8, _ = split(local, n8, 1)
    p16, v16, b16 = split(local, n16, 2)
    p32, v32, b32 = split(local, n32, 2)
    pos = jnp.where(in8, p8, jnp.where(in16, p16, p32))
    val_i = jnp.where(in8, v8, jnp.where(in16, v16, v32))
    be = jnp.where(in8, 0, jnp.where(in16, b16, b32))
    return width_sel, pos, val_i, be


@jax.jit
def interesting_at(buf: jax.Array, length: jax.Array, it: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Iteration ``it`` of the interesting-value walk."""
    width_sel, pos, val_i, be = _interesting_decode(it, length)
    i8 = jnp.asarray(INTERESTING_8.astype(np.uint32))
    i16 = jnp.asarray(INTERESTING_16.astype(np.uint32))
    i32 = jnp.asarray((INTERESTING_32 & 0xFFFFFFFF).astype(np.uint32))
    out8 = write_bytes(buf, pos, i8[jnp.clip(val_i, 0, len(INTERESTING_8) - 1)]
                       & 0xFF, 1)
    out16 = write_bytes(buf, pos,
                        i16[jnp.clip(val_i, 0, len(INTERESTING_16) - 1)]
                        & 0xFFFF, 2, be.astype(bool))
    out32 = write_bytes(buf, pos,
                        i32[jnp.clip(val_i, 0, len(INTERESTING_32) - 1)],
                        4, be.astype(bool))
    out = jnp.where(width_sel == 0, out8,
                    jnp.where(width_sel == 1, out16, out32))
    return out, length


# --------------------------------------------------------------------
# randomized mutators (PRNG-key driven)
# --------------------------------------------------------------------

N_HAVOC_OPS = 15


def _havoc_one(buf, length, words, positions=None, mask=None):
    """One stacked havoc edit, chosen uniformly from the op table.

    Branch-free: under vmap a 15-way ``lax.switch`` lowers to
    computing every branch for every lane (~120 vector ops/step).
    Instead every op is expressed in one unified form —

        out[i] = set_mask[i] ? set_val[i]
                             : buf[src_idx[i]] ^ xor_mask[i]

    and the per-op differences collapse into scalar parameter selects
    (~30 vector ops/step, ~3x faster havoc end-to-end).

    Op table (AFL havoc mix): 0 bit flip, 1-3 interesting 8/16/32,
    4-9 arith +/- on 8/16/32, 10 xor byte, 11-12 delete block (double
    odds, like AFL), 13 insert clone/fill block, 14 overwrite
    clone/fill block.
    """
    L = buf.shape[-1]
    # words: uint32[8] of pre-generated random bits (one bulk threefry
    # call in havoc_at instead of 16 split/randint chains per edit —
    # the PRNG was the majority of mutation time).  Ranged draws use
    # modulo (AFL's rand() % n has the same bias).
    op = (words[0] % N_HAVOC_OPS).astype(jnp.int32)
    maxlen = jnp.maximum(length, 1).astype(jnp.uint32)
    pos = (words[1] % maxlen).astype(jnp.int32)
    pos2 = (words[2] % maxlen).astype(jnp.int32)
    rbyte = words[3] % 256
    rint = words[4] & 0x7FFFFFFF
    be = (words[5] & 1) == 1
    blk_span = jnp.maximum(length // 2, 2).astype(jnp.uint32) - 1
    blk = (1 + words[6] % jnp.maximum(blk_span, 1)).astype(jnp.int32)
    bit = (words[7] % jnp.maximum(length * 8, 1).astype(jnp.uint32)
           ).astype(jnp.int32)
    if positions is not None:
        # Angora-style focus: anchor the primary edit position (and
        # the bit-flip byte) on the frontier-dependency byte set
        # instead of the whole buffer — mutations stop burning on
        # bytes no uncovered branch reads.  Clone sources (pos2) and
        # block spans stay unrestricted: material may come from
        # anywhere, it just lands on a frontier byte.
        np_ = positions.shape[0]
        lim = jnp.maximum(length, 1).astype(jnp.int32) - 1
        pidx = jnp.arange(np_, dtype=jnp.int32)

        def pick(sel):
            return jnp.sum(jnp.where(pidx == sel.astype(jnp.int32),
                                     positions, 0))

        pos = jnp.minimum(pick(words[1] % np_), lim)
        bit = jnp.minimum(pick(words[7] % np_), lim) * 8 + \
            (words[7] >> 16).astype(jnp.int32) % 8
    elif mask is not None:
        # learned per-byte mask (learn/): the primary edit position
        # and the bit-flip byte draw from the mask's SET bytes within
        # the live prefix via rank selection — the k-th allowed byte
        # for k = word % count.  An ALL-ONES mask is bit-identical to
        # the unmasked draw (count == maxlen, rank k lands at byte k,
        # and maxlen*8 == max(length*8, 1) for length >= 1 — the
        # generation-scan parity contract, pinned in test_learn.py);
        # an all-zero mask falls back to uniform (a mask must never
        # pin mutation to nothing).  Clone sources / spans stay
        # unrestricted, exactly like the `positions` focus variant.
        idx_m = jnp.arange(L, dtype=jnp.int32)
        live = idx_m < maxlen.astype(jnp.int32)
        allowed = (mask != 0) & live
        empty = ~jnp.any(allowed)
        allowed = allowed | (empty & live)
        cnt = jnp.sum(allowed).astype(jnp.uint32)
        cs = jnp.cumsum(allowed.astype(jnp.int32))

        def rank(k):
            return jnp.argmax(cs > k.astype(jnp.int32)
                              ).astype(jnp.int32)

        pos = rank(words[1] % cnt)
        bk = words[7] % (cnt * 8)
        bit = rank(bk >> 3) * 8 + (bk & 7).astype(jnp.int32)
    delta = (rint % ARITH_MAX + 1).astype(jnp.uint32)
    use_fill = (rint % 4) == 0  # insert/overwrite: 25% fill, 75% clone

    is_flip = op == 0
    is_int = (op >= 1) & (op <= 3)
    is_arith = (op >= 4) & (op <= 9)
    is_xor = op == 10
    is_del = (op == 11) | (op == 12)
    is_ins = op == 13
    is_ovw = op == 14
    is_write = is_int | is_arith  # value write through set-mask

    # --- scalar parameters ---
    width = jnp.select(
        [is_int, is_arith],
        [jnp.select([op == 1, op == 2], [1, 2], 4),
         jnp.select([op <= 5, op <= 7], [1, 2], 4)], 1)
    i8 = jnp.asarray(INTERESTING_8.astype(np.uint32))
    i16 = jnp.asarray(INTERESTING_16.astype(np.uint32))
    i32 = jnp.asarray((INTERESTING_32 & 0xFFFFFFFF).astype(np.uint32))
    int_val = jnp.select(
        [op == 1, op == 2],
        [i8[rint % len(INTERESTING_8)] & 0xFF,
         i16[rint % len(INTERESTING_16)] & 0xFFFF],
        i32[rint % len(INTERESTING_32)])
    cur = read_bytes(buf, pos, 4, False)  # LE dword at pos
    cur_w = jnp.select(
        [width == 1, width == 2],
        [cur & 0xFF,
         jnp.where(be, ((cur & 0xFF) << 8) | ((cur >> 8) & 0xFF),
                   cur & 0xFFFF)],
        jnp.where(be,
                  ((cur & 0xFF) << 24) | ((cur & 0xFF00) << 8)
                  | ((cur >> 8) & 0xFF00) | ((cur >> 24) & 0xFF),
                  cur))
    sign_add = (op == 5) | (op == 7) | (op == 9)
    d = jnp.where(sign_add, delta, jnp.uint32(0) - delta)
    arith_val = (cur_w + d) & jnp.uint32(0xFFFFFFFF)
    wmask = jnp.select([width == 1, width == 2],
                       [jnp.uint32(0xFF), jnp.uint32(0xFFFF)],
                       jnp.uint32(0xFFFFFFFF))
    write_val = jnp.where(is_arith, arith_val, int_val) & wmask

    # --- vector masks ---
    idx = jnp.arange(L, dtype=jnp.int32)

    # source index remap (delete shifts left; insert shifts right and
    # clones; overwrite clones in place)
    src_del = jnp.where(idx >= pos, idx + blk, idx)
    in_ins = (idx >= pos) & (idx < pos + blk)
    src_ins = jnp.where(idx >= pos + blk, idx - blk,
                        jnp.where(in_ins, pos2 + (idx - pos), idx))
    src_ovw = jnp.where(in_ins & ~use_fill, pos2 + (idx - pos), idx)
    src = jnp.where(is_del, src_del,
                    jnp.where(is_ins, src_ins,
                              jnp.where(is_ovw, src_ovw, idx)))
    # one-hot shuffle instead of buf[src]: a per-lane dynamic gather
    # is the slowest construct on the VPU (see read_bytes)
    src_c = jnp.clip(src, 0, L - 1)
    oh = src_c[:, None] == idx[None, :]                     # [L, L]
    gathered = jnp.sum(jnp.where(oh, buf[None, :], 0),
                       axis=1, dtype=jnp.int32).astype(jnp.uint8)

    # xor mask (bit flip / xor byte)
    xval = jnp.where(is_flip, jnp.uint32(128) >> (bit & 7).astype(
        jnp.uint32), jnp.maximum(rbyte, 1))
    xbyte = jnp.where(is_flip, bit >> 3, pos)
    xor_mask = jnp.where((idx == xbyte) & (is_flip | is_xor),
                         xval.astype(jnp.uint8), jnp.uint8(0))

    # set mask/val: width-w value write at pos, or block fill
    off = idx - pos
    k = jnp.where(be, width - 1 - off, off)
    vbytes = ((write_val >> (8 * jnp.clip(k, 0, 3))) & 0xFF).astype(
        jnp.uint8)
    in_write = is_write & (off >= 0) & (off < width)
    in_fill = (is_ins | is_ovw) & use_fill & in_ins
    set_mask = in_write | in_fill
    set_val = jnp.where(in_write, vbytes, rbyte.astype(jnp.uint8))

    out = jnp.where(set_mask, set_val, gathered ^ xor_mask)
    new_len = jnp.select(
        [is_del, is_ins],
        [jnp.maximum(length - blk, 1), jnp.minimum(length + blk, L)],
        length)
    return out, new_len


@partial(jax.jit, static_argnames=("stack_pow2",))
def havoc_at(buf: jax.Array, length: jax.Array, key: jax.Array,
             stack_pow2: int = 4) -> Tuple[jax.Array, jax.Array]:
    """AFL-style havoc: 2..2**stack_pow2 stacked random edits.

    The reference's havoc stacks up to 128 edits (HAVOC_STACK_POW2=7);
    the default here is 16 because under vmap every switch branch is
    computed for every lane — raise ``stack_pow2`` via mutator options
    to trade throughput for per-candidate aggression.
    """
    n_steps = 1 << stack_pow2
    # ALL random bits for the stacked edits in one threefry call
    words = jax.random.bits(key, (n_steps + 1, 8), dtype=jnp.uint32)
    stack = jnp.uint32(1) << (1 + words[0, 0] % stack_pow2)

    # scan, not unroll: unrolling the 16 edits was measured at zero
    # runtime gain on chip but ~6x the trace/compile time
    def step(carry, xs):
        i, w = xs
        b, ln = carry
        nb, nln = _havoc_one(b, ln, w)
        active = i < stack
        b = jnp.where(active, nb, b)
        ln = jnp.where(active, nln, ln)
        return (b, ln), None

    (out, out_len), _ = jax.lax.scan(
        step, (buf, length),
        (jnp.arange(n_steps, dtype=jnp.uint32), words[1:]))
    return out, out_len


@partial(jax.jit, static_argnames=("stack_pow2",))
def havoc_focus_at(buf: jax.Array, length: jax.Array, key: jax.Array,
                   positions: jax.Array, stack_pow2: int = 4
                   ) -> Tuple[jax.Array, jax.Array]:
    """``havoc_at`` with edit positions drawn from ``positions``
    (int32[P], the frontier-dependency byte set from the static
    layer).  A SEPARATE entry point on purpose: the unfocused path
    keeps its exact historical RNG stream and compiled program, so
    ``--no-focus`` (and every campaign without a mask) is bit-for-bit
    parity-pinned against prior releases."""
    n_steps = 1 << stack_pow2
    words = jax.random.bits(key, (n_steps + 1, 8), dtype=jnp.uint32)
    stack = jnp.uint32(1) << (1 + words[0, 0] % stack_pow2)

    def step(carry, xs):
        i, w = xs
        b, ln = carry
        nb, nln = _havoc_one(b, ln, w, positions=positions)
        active = i < stack
        b = jnp.where(active, nb, b)
        ln = jnp.where(active, nln, ln)
        return (b, ln), None

    (out, out_len), _ = jax.lax.scan(
        step, (buf, length),
        (jnp.arange(n_steps, dtype=jnp.uint32), words[1:]))
    return out, out_len


@partial(jax.jit, static_argnames=("stack_pow2",))
def havoc_mask_at(buf: jax.Array, length: jax.Array, key: jax.Array,
                  mask: jax.Array, stack_pow2: int = 4
                  ) -> Tuple[jax.Array, jax.Array]:
    """``havoc_at`` with edit positions drawn from the SET bytes of
    a dense uint8[L] ``mask`` — the learned-saliency variant the
    device generation scans inline (the mask is computed per
    generation from the model, so it must be a dense tensor, not a
    host-built position list like ``havoc_focus_at``'s).  With an
    all-ones mask the RNG stream AND every edit are bit-identical to
    ``havoc_at`` (see ``_havoc_one``), which is what keeps the
    shaped generation scan parity-pinned while the model is
    untrained."""
    n_steps = 1 << stack_pow2
    words = jax.random.bits(key, (n_steps + 1, 8), dtype=jnp.uint32)
    stack = jnp.uint32(1) << (1 + words[0, 0] % stack_pow2)

    def step(carry, xs):
        i, w = xs
        b, ln = carry
        nb, nln = _havoc_one(b, ln, w, mask=mask)
        active = i < stack
        b = jnp.where(active, nb, b)
        ln = jnp.where(active, nln, ln)
        return (b, ln), None

    (out, out_len), _ = jax.lax.scan(
        step, (buf, length),
        (jnp.arange(n_steps, dtype=jnp.uint32), words[1:]))
    return out, out_len


@jax.jit
def zzuf_focus_at(buf: jax.Array, length: jax.Array, key: jax.Array,
                  positions: jax.Array, ratio: jax.Array | float = 0.004
                  ) -> Tuple[jax.Array, jax.Array]:
    """``zzuf_at`` restricted to the focus byte set, with the flip
    ratio rescaled by buffer/mask size so the EXPECTED flip count is
    preserved — a 2-byte mask on a 64-byte buffer at the default
    ratio would otherwise leave ~94% of candidates byte-identical to
    the seed (duplicate execs, exactly when the campaign is
    plateaued)."""
    L = buf.shape[-1]
    scaled = jnp.minimum(
        jnp.asarray(ratio, jnp.float32) * L / positions.shape[0], 1.0)
    out, ln = zzuf_at(buf, length, key, scaled)
    idx = jnp.arange(L, dtype=jnp.int32)
    allowed = (idx[:, None] == positions[None, :]).any(axis=1)
    return jnp.where(allowed, out, buf), ln


@jax.jit
def zzuf_at(buf: jax.Array, length: jax.Array, key: jax.Array,
            ratio: jax.Array | float = 0.004) -> Tuple[jax.Array, jax.Array]:
    """zzuf-style fuzzing: flip each bit independently with
    probability ``ratio`` (zzuf's default 0.004)."""
    L = buf.shape[-1]
    bits = jax.random.bernoulli(key, ratio, (L, 8))
    mask = jnp.packbits(bits, axis=-1, bitorder="big").reshape(L)
    idx = jnp.arange(L, dtype=jnp.int32)
    mask = jnp.where(idx < length, mask, jnp.uint8(0))
    return buf ^ mask, length


@jax.jit
def splice_at(buf_a: jax.Array, len_a: jax.Array, buf_b: jax.Array,
              len_b: jax.Array, key: jax.Array
              ) -> Tuple[jax.Array, jax.Array]:
    """Splice: head of A up to a random split point, then tail of B
    from its own split point (AFL splice stage semantics)."""
    L = buf_a.shape[-1]
    k0, k1 = jax.random.split(key)
    cut_a = jax.random.randint(k0, (), 1, jnp.maximum(len_a, 2))
    cut_b = jax.random.randint(k1, (), 1, jnp.maximum(len_b, 2))
    idx = jnp.arange(L, dtype=jnp.int32)
    from_b = buf_b[jnp.clip(cut_b + (idx - cut_a), 0, L - 1)]
    out = jnp.where(idx < cut_a, buf_a, from_b)
    new_len = jnp.clip(cut_a + (len_b - cut_b), 1, L)
    out = jnp.where(idx < new_len, out, jnp.uint8(0))
    return out, new_len


def dictionary_total(length_bytes: int, n_tokens: int) -> int:
    # per token: overwrite at each position + insert at each position+1
    return n_tokens * (2 * max(length_bytes, 1))


@jax.jit
def dictionary_at(buf: jax.Array, length: jax.Array, it: jax.Array,
                  tokens: jax.Array, token_lens: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """Iteration ``it`` of the dictionary walk: token t overwritten at
    position p (first half) or inserted at p (second half).

    ``tokens`` is uint8[N, TL] padded; ``token_lens`` int32[N].
    """
    n_tokens = tokens.shape[0]
    per_tok = 2 * jnp.maximum(length, 1)
    tok_i = (it // per_tok) % n_tokens
    local = it % per_tok
    insert = local >= jnp.maximum(length, 1)
    pos = jnp.where(insert, local - jnp.maximum(length, 1), local)
    tok = tokens[tok_i]
    tlen = token_lens[tok_i]
    L = buf.shape[-1]
    idx = jnp.arange(L, dtype=jnp.int32)
    inside = (idx >= pos) & (idx < pos + tlen)
    tbytes = tok[jnp.clip(idx - pos, 0, tokens.shape[1] - 1)]
    ow = jnp.where(inside, tbytes, buf)
    ow_len = jnp.maximum(length, jnp.minimum(pos + tlen, L))
    ins, ins_len = insert_block(buf, length, pos, tlen, 0, jnp.uint8(0),
                                False)
    ins = jnp.where(inside, tbytes, ins)
    out = jnp.where(insert, ins, ow)
    out_len = jnp.where(insert, ins_len, ow_len)
    return out, out_len


# --------------------------------------------------------------------
# honggfuzz-style mangle (distinct op mix from havoc)
# --------------------------------------------------------------------

def _mangle_one(buf, length, key):
    """One honggfuzz-style mangle op: byte-run set/copy, magic values,
    inc/dec runs, ASCII digit corruption."""
    L = buf.shape[-1]
    ks = jax.random.split(key, 6)
    op = jax.random.randint(ks[0], (), 0, 6)
    pos = jax.random.randint(ks[1], (), 0, jnp.maximum(length, 1))
    pos2 = jax.random.randint(ks[2], (), 0, jnp.maximum(length, 1))
    run = jax.random.randint(ks[3], (), 1, jnp.maximum(length // 4, 2))
    rbyte = jax.random.randint(ks[4], (), 0, 256).astype(jnp.uint8)
    idx = jnp.arange(L, dtype=jnp.int32)
    inside = (idx >= pos) & (idx < pos + run)

    def f_byteset(b, ln):
        return jnp.where(inside, rbyte, b), ln

    def f_memcpy(b, ln):
        src = b[jnp.clip(pos2 + (idx - pos), 0, L - 1)]
        return jnp.where(inside, src, b), ln

    def f_magic(b, ln):
        magics = jnp.asarray(np.array(
            [0x00, 0x01, 0x7F, 0x80, 0xFF, 0x41, 0x25, 0x2F],
            dtype=np.uint8))
        m = magics[jax.random.randint(ks[5], (), 0, 8)]
        return jnp.where(inside, m, b), ln

    def f_inc(b, ln):
        return jnp.where(inside, b + jnp.uint8(1), b), ln

    def f_dec(b, ln):
        return jnp.where(inside, b - jnp.uint8(1), b), ln

    def f_digit(b, ln):
        is_digit = (b >= ord("0")) & (b <= ord("9"))
        d = (rbyte % 10) + jnp.uint8(ord("0"))
        return jnp.where(inside & is_digit, d, b), ln

    return jax.lax.switch(
        op, [f_byteset, f_memcpy, f_magic, f_inc, f_dec, f_digit],
        buf, length)


@partial(jax.jit, static_argnames=("max_ops",))
def mangle_at(buf: jax.Array, length: jax.Array, key: jax.Array,
              max_ops: int = 8) -> Tuple[jax.Array, jax.Array]:
    """honggfuzz-style mangle: 1..max_ops stacked run-oriented edits."""
    k0, k1 = jax.random.split(key)
    n = jax.random.randint(k0, (), 1, max_ops + 1).astype(jnp.uint32)

    def step(carry, i):
        b, ln = carry
        nb, nln = _mangle_one(b, ln, jax.random.fold_in(k1, i))
        active = i < n
        return (jnp.where(active, nb, b), jnp.where(active, nln, ln)), None

    (out, out_len), _ = jax.lax.scan(
        step, (buf, length), jnp.arange(max_ops, dtype=jnp.uint32))
    return out, out_len
