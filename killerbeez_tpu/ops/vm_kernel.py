"""Pallas TPU kernel for the KBVM step machine.

The XLA while_loop engine (models/vm._run_batch_impl) round-trips the
full interpreter state (registers, scratch memory, edge counts —
~25MB at B=16k) through HBM on every VM step; at ~400 steps per batch
that traffic, not compute, bounds throughput.  This kernel runs the
ENTIRE step loop inside one pallas_call: each grid instance owns a
TILE-lane slice whose state lives in VMEM for the whole execution,
and only the final verdicts/counts are written back.

Mosaic constraints shape the code:
  * lane-LAST layout everywhere — per-lane scalars are [1, T] rows
    and tables are [X, T], so every broadcast is a sublane
    replication (a [T, 1] column would need lane replication, which
    Mosaic's relayout rejects);
  * no 1D arrays (1D boolean vectors fail to lower) and no
    `jnp.select` (it lowers through an f32-only argmax);
  * selects operate on i32 0/1, never on bool VALUES (Mosaic widens
    selected bools to i8 and cannot truncate back to a mask).

The two per-lane "gathers" (instruction fetch, edge-table lookup) are
transposed one-hot MXU matmuls — the TPU has no per-lane gather in
either programming model.

Semantics are bit-identical to models/vm._step_batched (parity-tested
against it); stream recording is not supported here — tracer/ipt runs
stay on the XLA engine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE
from ..models.vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB, ALU_XOR,
    CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_ADDI, OP_ALU, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP, OP_LDB,
    OP_LDI, OP_LDM, OP_LEN, OP_STM, VMResult, _mix32,
)

LANE_TILE = 512  # lanes per grid instance (multiple of 128)

# MXU dtype modes for the two one-hot "gathers".  The round-3 kernel
# ran both as Precision.HIGHEST f32 dots -- a 6-pass decomposition on
# the MXU that dominated the whole step (measured 1.9us -> 0.45us per
# 512-lane tile-step when replaced).  Because a one-hot operand makes
# every dot output a SINGLE product, the results are exact (no
# accumulation rounding) whenever the data-side values are exactly
# representable in bf16, i.e. |v| <= 256:
#   * edge dot: edge ids <= n_edges, guarded n_edges < 255;
#   * fetch dot: instruction words live in [-2^16, 2^16): split into
#     hi/lo bytes, STACKED into one [8, NI] operand (the MXU output
#     tile rounds 4 rows to 8, so one dot covers both limbs; each
#     limb < 256 exact, f32 accumulators) and recombined
#     (rhi << 8) + rlo.
# dot_modes() picks the fast modes iff the guards hold; "f32" keeps
# the round-3 behavior.  Parity is enforced bit-for-bit by the
# engine-equivalence tests either way.
DEFAULT_DOTS = ("f32", "f32")


def dot_modes(instrs, n_edges):
    """(fetch_mode, edge_mode) for a CONCRETE program -- callers that
    jit their step compute this once at setup time and pass it as a
    static argument."""
    a = np.asarray(instrs)
    fetch = "bf16x2" if (int(a.min()) >= -(1 << 16)
                         and int(a.max()) < (1 << 16)) else "f32"
    edge = "bf16" if int(n_edges) < 255 else "f32"
    return (fetch, edge)


def _pick_rows(table, idx, rows=None):
    """out[0, t] = table[idx[0, t], t] for table [R, T], idx [1, T]:
    one-hot over the (small, static) row axis.  ``rows`` is the
    precomputed iota — VM-loop callers pass the hoisted copy (Mosaic
    LICM already hoists in-body iotas on chip, measured neutral; the
    explicit form documents the invariant and helps interpret mode)."""
    if rows is None:
        rows = jax.lax.broadcasted_iota(jnp.int32, table.shape, 0)
    return jnp.sum(jnp.where(rows == idx, table, 0), axis=0,
                   keepdims=True).astype(table.dtype)


def _chain(pairs, default):
    """Branch-free first-match select."""
    out = default
    for cond, val in reversed(pairs):
        out = jnp.where(cond, val, out)
    return out


def _vm_loop(instrs_t, table_t, bufs, lengths, z,
             mem_size, max_steps, n_edges, status0=None,
             dots=DEFAULT_DOTS, narrow=None):
    """The VM step loop shared by the plain and fused kernels: takes
    lane-last [L, T] candidate bytes + [1, T] lengths, returns the
    final carry tuple.  ``z`` is a loaded [1, T] zeros row (see the
    carry-layout note in state0).  ``status0`` overrides the initial
    per-lane status (two-phase scheduling marks already-finished
    lanes FUZZ_NONE so their tiles exit the while-loop immediately);
    it must be load-derived like everything else.  The program
    tables arrive RAW int32; ``dots`` selects the MXU dtypes (see
    the DEFAULT_DOTS note).  ``narrow`` (requires max_steps < 2^15)
    carries the static-edge counts as int16 — halving the widest VPU
    rows of the step, the [E+1, T] accounting — exact because a
    count can never exceed max_steps."""
    t = bufs.shape[1]
    ni = instrs_t.shape[1]
    nb = table_t.shape[0]
    L = bufs.shape[0]
    fetch_mode, edge_mode = dots
    if fetch_mode == "bf16x2":
        # hi/lo limbs STACKED into one [8, NI] operand: the MXU's
        # output tile rounds 4 rows up to 8 anyway, so one dot does
        # the work of the two separate limb dots (measured 1.08x on
        # the flagship step, bit-identical)
        ins_cat = jnp.concatenate(
            [(instrs_t & 0xFF).astype(jnp.bfloat16),
             (instrs_t >> 8).astype(jnp.bfloat16)], axis=0)
    else:
        ins_f = instrs_t.astype(jnp.float32)
    table_f = table_t.astype(
        jnp.bfloat16 if edge_mode == "bf16" else jnp.float32)

    # loop-invariant iotas, hoisted (the fetch one-hot alone is
    # [NI, T]); on-chip this measured neutral — Mosaic's LICM already
    # lifts them — but it documents the invariant explicitly
    io_ni = jax.lax.broadcasted_iota(jnp.int32, (ni, t), 0)
    io_regs = jax.lax.broadcasted_iota(jnp.int32, (N_REGS, t), 0)
    io_mem = jax.lax.broadcasted_iota(jnp.int32, (mem_size, t), 0)
    io_buf = jax.lax.broadcasted_iota(jnp.int32, (L, t), 0)
    io_nb1 = jax.lax.broadcasted_iota(jnp.int32, (nb + 1, t), 0)
    io_nb = jax.lax.broadcasted_iota(jnp.int32, (nb, t), 0)
    io_e = jax.lax.broadcasted_iota(jnp.int32, (n_edges + 1, t), 0)

    def step(state):
        (pc, regs, mem, prev_loc, status, exit_code, prev_idx,
         counts, path_hash, i, lane_steps) = state
        running = status == FUZZ_RUNNING                 # [1, T] bool

        # ---- instruction fetch: transposed one-hot MXU matmul ----
        pcc = jnp.clip(pc, 0, ni - 1)
        if fetch_mode == "bf16x2":
            onehot_pc = (io_ni == pcc).astype(jnp.bfloat16)  # [NI, T]
            row8 = jax.lax.dot(ins_cat, onehot_pc,
                               preferred_element_type=jnp.float32)
            rlo, rhi = row8[:4], row8[4:]
            row = (rhi.astype(jnp.int32) << 8) + rlo.astype(jnp.int32)
        else:
            onehot_pc = (io_ni == pcc).astype(jnp.float32)   # [NI, T]
            row = jax.lax.dot(ins_f, onehot_pc,
                              precision=jax.lax.Precision.HIGHEST)
            row = row.astype(jnp.int32)                  # [4, T]
        op = row[0:1, :]
        a = row[1:2, :]
        b = row[2:3, :]
        c = row[3:4, :]

        rb_idx = (c >> 3) & (N_REGS - 1)
        alu_sel = c & 7
        cmp_sel = b & 3
        cmp_rb = (b >> 2) & (N_REGS - 1)

        ra = _pick_rows(regs, jnp.clip(a, 0, N_REGS - 1), io_regs)
        rb = _pick_rows(regs, jnp.clip(b, 0, N_REGS - 1), io_regs)
        ry = _pick_rows(regs, rb_idx, io_regs)
        cmp_y = _pick_rows(regs, cmp_rb, io_regs)

        # LDB
        ldb_ok = (rb >= 0) & (rb < lengths)
        ldb_val = _pick_rows(bufs, jnp.clip(rb, 0, L - 1), io_buf)
        ldb_val = jnp.where(ldb_ok, ldb_val, 0)

        x, y = rb, ry
        shift = jnp.clip(y, 0, 31)
        alu_val = _chain(
            [(alu_sel == ALU_ADD, x + y), (alu_sel == ALU_SUB, x - y),
             (alu_sel == ALU_AND, x & y), (alu_sel == ALU_OR, x | y),
             (alu_sel == ALU_XOR, x ^ y), (alu_sel == ALU_SHL, x << shift),
             (alu_sel == ALU_SHR, jax.lax.shift_right_logical(x, shift)),
             (alu_sel == ALU_MUL, x * y)], jnp.zeros_like(x))
        taken = _chain(
            [(cmp_sel == CMP_EQ, (ra == cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_NE, (ra != cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_LT, (ra < cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_GE, (ra >= cmp_y).astype(jnp.int32))],
            jnp.zeros_like(ra)) != 0

        mem_ok_ld = (rb >= 0) & (rb < mem_size)
        ldm_val = _pick_rows(mem, jnp.clip(rb, 0, mem_size - 1), io_mem)
        ldm_val = jnp.where(mem_ok_ld, ldm_val, 0)
        mem_ok_st = (ra >= 0) & (ra < mem_size)

        nxt = pc + 1
        new_pc = _chain([(op == OP_JMP, a),
                         (op == OP_BR, jnp.where(taken, c, nxt))], nxt)
        wr_val = _chain(
            [(op == OP_LDB, ldb_val), (op == OP_LDI, b),
             (op == OP_ALU, alu_val), (op == OP_ADDI, rb + c),
             (op == OP_LEN, lengths), (op == OP_LDM, ldm_val)],
            jnp.zeros_like(pc))
        writes_reg = ((op == OP_LDB) | (op == OP_LDI) | (op == OP_ALU) |
                      (op == OP_ADDI) | (op == OP_LEN) | (op == OP_LDM))
        wmask = (writes_reg & running) & \
            (io_regs == jnp.clip(a, 0, N_REGS - 1))
        new_regs = jnp.where(wmask, wr_val, regs)

        do_store = (op == OP_STM) & mem_ok_st & running
        smask = do_store & (io_mem == jnp.clip(ra, 0, mem_size - 1))
        new_mem = jnp.where(smask, rb, mem)

        crashes = (op == OP_CRASH) | \
                  ((op == OP_LDM) & ~mem_ok_ld) | \
                  ((op == OP_STM) & ~mem_ok_st) | \
                  (pc < 0) | (pc >= ni)
        halts = op == OP_HALT
        new_status = jnp.where(crashes, FUZZ_CRASH,
                               jnp.where(halts, FUZZ_NONE, status))
        new_exit = jnp.where(halts & running, a, exit_code)

        # ---- static-edge accounting ----
        is_block = (op == OP_BLOCK) & running
        cur_loc = a & (MAP_SIZE - 1)
        new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)
        cur_idx = jnp.clip(b, 0, nb - 1)
        if edge_mode == "bf16":
            onehot_prev = (io_nb1 == prev_idx).astype(jnp.bfloat16)
            rows_e = jax.lax.dot(table_f, onehot_prev,
                                 preferred_element_type=jnp.float32)
        else:
            onehot_prev = (io_nb1 == prev_idx).astype(jnp.float32)
            rows_e = jax.lax.dot(table_f, onehot_prev,
                                 precision=jax.lax.Precision.HIGHEST)
        # rows_e[cidx, t] = edge index for (prev[t], cidx)   [nb, T]
        eidx = jnp.sum(jnp.where(io_nb == cur_idx, rows_e, 0),
                       axis=0, keepdims=True).astype(jnp.int32)
        emask = (io_e == eidx) & is_block
        new_counts = counts + emask.astype(counts.dtype)
        new_prev_idx = jnp.where(is_block, cur_idx + 1, prev_idx)
        new_hash = jnp.where(
            is_block, _mix32(path_hash ^ cur_loc.astype(jnp.uint32)),
            path_hash)

        def keep(new, old):
            return jnp.where(running, new, old)

        return (keep(new_pc, pc),
                jnp.where(running, new_regs, regs),
                jnp.where(running, new_mem, mem),
                keep(new_prev, prev_loc),
                keep(new_status, status),
                keep(new_exit, exit_code),
                keep(new_prev_idx, prev_idx),
                new_counts, keep(new_hash, path_hash),
                i + 1,
                lane_steps + running.astype(jnp.int32))

    # Loop carries must descend from a memory LOAD: a constant splat
    # (or anything folded to one, like lens*0) gets Mosaic's
    # fully-replicated {*,*} layout, and the loop back-edge cannot
    # relayout the computed {0,0} values into it.
    if narrow is None:  # auto: exact whenever a count can't overflow
        import os as _os
        narrow = (max_steps < (1 << 15)
                  and not _os.environ.get("KB_VM_WIDE"))
    cdt = jnp.int16 if narrow else jnp.int32
    if narrow and max_steps >= (1 << 15):
        raise ValueError("narrow counts need max_steps < 32768")
    state0 = (z,
              jnp.zeros((N_REGS, t), jnp.int32) + z,
              jnp.zeros((mem_size, t), jnp.int32) + z,
              z,
              (z + FUZZ_RUNNING) if status0 is None else status0,
              z,
              z,
              jnp.zeros((n_edges + 1, t), cdt) + z.astype(cdt),
              z.astype(jnp.uint32),
              jnp.int32(0),
              z)

    def cond(s):
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[9] < max_steps)

    final = jax.lax.while_loop(cond, lambda s: step(s), state0)
    if narrow:  # outputs stay int32 regardless of the carry width
        final = final[:7] + (final[7].astype(jnp.int32),) + final[8:]
    return final


def _vm_kernel(instrs_t_ref, table_t_ref, bufs_ref, lens_ref, zero_ref,
               status_ref, exit_ref, counts_ref, steps_ref, hash_ref,
               *, mem_size, max_steps, n_edges, dots):
    final = _vm_loop(instrs_t_ref[...], table_t_ref[...],
                     bufs_ref[...], lens_ref[...],
                     zero_ref[...], mem_size, max_steps, n_edges,
                     dots=dots)
    status_ref[...] = final[4]
    exit_ref[...] = final[5]
    counts_ref[...] = final[7]
    steps_ref[...] = final[10]
    hash_ref[...] = final[8]


def _vm_kernel_skip(instrs_t_ref, table_t_ref, bufs_ref, lens_ref,
                    skip_ref, zero_ref,
                    status_ref, exit_ref, counts_ref, steps_ref,
                    hash_ref, *, mem_size, max_steps, n_edges, dots):
    """_vm_kernel with a per-lane skip mask: skipped lanes start
    FUZZ_NONE, so a tile of all-skipped lanes exits its while-loop
    after zero iterations — the phase-2 half of two-phase scheduling
    pays only for tiles that contain real survivors."""
    skip = skip_ref[...]                                 # [1, T] 0/1
    status0 = (1 - skip) * FUZZ_RUNNING + zero_ref[...]
    final = _vm_loop(instrs_t_ref[...], table_t_ref[...],
                     bufs_ref[...], lens_ref[...],
                     zero_ref[...], mem_size, max_steps, n_edges,
                     status0=status0, dots=dots)
    status_ref[...] = final[4]
    exit_ref[...] = final[5]
    counts_ref[...] = final[7]
    steps_ref[...] = final[10]
    hash_ref[...] = final[8]


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "interpret", "dots"))
def run_batch_pallas(instrs, edge_table, inputs, lengths, mem_size,
                     max_steps, n_edges, interpret=False,
                     skip=None, dots=DEFAULT_DOTS) -> VMResult:
    """Pallas engine entry: same contract as vm._run_batch_impl with
    record_stream=False.  B must be a multiple of LANE_TILE (callers
    pad; padded lanes are regular executions of duplicated inputs).
    ``skip`` (optional int32[B] 0/1) marks lanes to not execute at
    all (status FUZZ_NONE, zero counts) — see _vm_kernel_skip."""
    b, L = inputs.shape
    if b % LANE_TILE:
        raise ValueError(f"batch {b} not a multiple of {LANE_TILE}")
    grid = (b // LANE_TILE,)
    instrs_t = instrs.T                          # [4, NI]
    table_t = edge_table.T                       # [nb, nb+1]
    bufs_t = inputs.T.astype(jnp.int32)          # [L, B]
    lens = lengths.astype(jnp.int32).reshape(1, b)
    zeros = jnp.zeros((1, b), jnp.int32)         # carry-init source

    out_shapes = (
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # status
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # exit
        jax.ShapeDtypeStruct((n_edges + 1, b), jnp.int32),  # counts
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # steps
        jax.ShapeDtypeStruct((1, b), jnp.uint32),         # path hash
    )
    whole = lambda *_: (0, 0)  # noqa: E731 — replicate full array
    lane_block = lambda i: (0, i)  # noqa: E731
    in_specs = [
        pl.BlockSpec(instrs_t.shape, whole),
        pl.BlockSpec(table_t.shape, whole),
        pl.BlockSpec((L, LANE_TILE), lane_block),
        pl.BlockSpec((1, LANE_TILE), lane_block),
    ]
    operands = [instrs_t, table_t, bufs_t, lens]
    if skip is None:
        kernel = partial(_vm_kernel, mem_size=mem_size,
                         max_steps=max_steps, n_edges=n_edges,
                         dots=dots)
    else:
        kernel = partial(_vm_kernel_skip, mem_size=mem_size,
                         max_steps=max_steps, n_edges=n_edges,
                         dots=dots)
        in_specs.append(pl.BlockSpec((1, LANE_TILE), lane_block))
        operands.append(skip.astype(jnp.int32).reshape(1, b))
    in_specs.append(pl.BlockSpec((1, LANE_TILE), lane_block))
    operands.append(zeros)
    status, exit_code, counts, steps, path_hash = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((n_edges + 1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(*operands)
    return VMResult(status=status.reshape(b),
                    exit_code=exit_code.reshape(b),
                    counts=counts.T.astype(jnp.uint8),
                    steps=steps.reshape(b),
                    path_hash=path_hash.reshape(b),
                    edge_ids=None)


def _slice_vmresult(res: VMResult, b: int) -> VMResult:
    return res._replace(
        status=res.status[:b], exit_code=res.exit_code[:b],
        counts=res.counts[:b], steps=res.steps[:b],
        path_hash=res.path_hash[:b])


def run_batch_pallas_padded(instrs, edge_table, inputs, lengths,
                            mem_size, max_steps, n_edges,
                            interpret=False, skip=None,
                            dots=DEFAULT_DOTS) -> VMResult:
    """run_batch_pallas for ANY batch size: pads to a LANE_TILE
    multiple and slices results back.  Padded lanes are skip-masked
    when a skip vector is given, else duplicate lane 0 (coverage
    no-ops either way).  The shared pad/unpad used by the jit_harness
    engine and the sharded step."""
    b = inputs.shape[0]
    pad = (-b) % LANE_TILE
    if pad:
        inputs = jnp.concatenate(
            [inputs, jnp.repeat(inputs[:1], pad, axis=0)], axis=0)
        lengths = jnp.concatenate(
            [lengths, jnp.repeat(lengths[:1], pad)])
        if skip is not None:
            skip = jnp.concatenate(
                [skip, jnp.ones((pad,), skip.dtype)])
    res = run_batch_pallas(instrs, edge_table, inputs, lengths,
                           mem_size, max_steps, n_edges,
                           interpret=interpret, skip=skip, dots=dots)
    return _slice_vmresult(res, b) if pad else res


# --------------------------------------------------------------------
# Fused mutate + execute: the whole fuzz candidate lifecycle in VMEM
# --------------------------------------------------------------------
#
# havoc's stacked edits are elementwise over the candidate buffer, so
# they port to the kernel's lane-last layout directly — the buffer
# never leaves VMEM between mutation and execution.  Bit-for-bit
# parity with ops/mutate_core.havoc_at (same PRNG words, generated on
# host with the same keys) is enforced by tests.

def _havoc_edit(buf, length, w, active, L):
    """One stacked havoc edit, lane-last: buf [L, T] i32 (byte
    values), length [1, T] i32, w [8, T] u32 random words, active
    [1, T] bool.  Mirrors mutate_core._havoc_one exactly."""
    from .mutate_core import (
        ARITH_MAX, INTERESTING_8, INTERESTING_16, INTERESTING_32,
        N_HAVOC_OPS,
    )
    u32 = jnp.uint32
    op = (w[0:1] % N_HAVOC_OPS).astype(jnp.int32)
    maxlen = jnp.maximum(length, 1).astype(u32)
    pos = (w[1:2] % maxlen).astype(jnp.int32)
    pos2 = (w[2:3] % maxlen).astype(jnp.int32)
    rbyte = w[3:4] % 256
    rint = w[4:5] & 0x7FFFFFFF
    be = (w[5:6] & 1) == 1
    # maxes stay in i32: Mosaic has no unsigned-max (arith.maxui)
    blk_span = jnp.maximum(
        jnp.maximum(length // 2, 2) - 1, 1).astype(u32)
    blk = (1 + w[6:7] % blk_span).astype(jnp.int32)
    bit = (w[7:8] % jnp.maximum(length * 8, 1).astype(u32)
           ).astype(jnp.int32)
    delta = (rint % ARITH_MAX + 1).astype(u32)
    use_fill = (rint % 4) == 0

    is_flip = op == 0
    is_int = (op >= 1) & (op <= 3)
    is_arith = (op >= 4) & (op <= 9)
    is_xor = op == 10
    is_del = (op == 11) | (op == 12)
    is_ins = op == 13
    is_ovw = op == 14
    is_write = is_int | is_arith

    width = _chain(
        [(is_int, _chain([(op == 1, jnp.full_like(op, 1)),
                          (op == 2, jnp.full_like(op, 2))],
                         jnp.full_like(op, 4))),
         (is_arith, _chain([(op <= 5, jnp.full_like(op, 1)),
                            (op <= 7, jnp.full_like(op, 2))],
                           jnp.full_like(op, 4)))],
        jnp.full_like(op, 1))

    def const_pick(sel, values):
        """values[sel] for a small python tuple of scalar constants."""
        out = jnp.zeros_like(sel, dtype=u32) + u32(values[0])
        for r, v in enumerate(values[1:], start=1):
            out = jnp.where(sel == r, u32(v), out)
        return out

    int_val = _chain(
        [(op == 1, const_pick(rint % len(INTERESTING_8),
                              tuple(int(x) for x in
                                    INTERESTING_8.astype(np.uint32)))
          & 0xFF),
         (op == 2, const_pick(rint % len(INTERESTING_16),
                              tuple(int(x) for x in
                                    INTERESTING_16.astype(np.uint32)))
          & 0xFFFF)],
        const_pick(rint % len(INTERESTING_32),
                   tuple(int(x) for x in
                         (INTERESTING_32 & 0xFFFFFFFF).astype(np.uint32))))

    # LE dword at pos (mirrors read_bytes(buf, pos, 4, False))
    cur = jnp.zeros_like(rint)
    for k in range(4):
        byte = _pick_rows(buf, jnp.clip(pos + k, 0, L - 1)).astype(u32)
        cur = cur | (byte << (8 * k))
    cur_w = _chain(
        [(width == 1, cur & 0xFF),
         (width == 2, jnp.where(be,
                                ((cur & 0xFF) << 8) | ((cur >> 8) & 0xFF),
                                cur & 0xFFFF))],
        jnp.where(be,
                  ((cur & 0xFF) << 24) | ((cur & 0xFF00) << 8)
                  | ((cur >> 8) & 0xFF00) | ((cur >> 24) & 0xFF),
                  cur))
    sign_add = (op == 5) | (op == 7) | (op == 9)
    d = jnp.where(sign_add, delta, u32(0) - delta)
    arith_val = cur_w + d
    wmask = _chain([(width == 1, jnp.zeros_like(rint) + u32(0xFF)),
                    (width == 2, jnp.zeros_like(rint) + u32(0xFFFF))],
                   jnp.zeros_like(rint) + u32(0xFFFFFFFF))
    write_val = jnp.where(is_arith, arith_val, int_val) & wmask

    idx = jax.lax.broadcasted_iota(jnp.int32, buf.shape, 0)  # [L, T]
    src_del = jnp.where(idx >= pos, idx + blk, idx)
    in_ins = (idx >= pos) & (idx < pos + blk)
    src_ins = jnp.where(idx >= pos + blk, idx - blk,
                        jnp.where(in_ins, pos2 + (idx - pos), idx))
    src_ovw = jnp.where(in_ins & ~use_fill, pos2 + (idx - pos), idx)
    src = jnp.where(is_del, src_del,
                    jnp.where(is_ins, src_ins,
                              jnp.where(is_ovw, src_ovw, idx)))
    src_c = jnp.clip(src, 0, L - 1)
    gathered = jnp.zeros_like(buf)
    for j in range(L):
        gathered = jnp.where(src_c == j, buf[j:j + 1, :], gathered)

    xval = jnp.where(is_flip,
                     128 >> (bit & 7),
                     jnp.maximum(rbyte.astype(jnp.int32), 1))
    xbyte = jnp.where(is_flip, bit >> 3, pos)
    xor_mask = jnp.where((idx == xbyte) & (is_flip | is_xor), xval, 0)

    off = idx - pos
    k = jnp.where(be, width - 1 - off, off)
    vbytes = ((write_val >> (8 * jnp.clip(k, 0, 3)).astype(u32))
              & 0xFF).astype(jnp.int32)
    in_write = is_write & (off >= 0) & (off < width)
    in_fill = (is_ins | is_ovw) & use_fill & in_ins
    set_mask = in_write | in_fill
    set_val = jnp.where(in_write, vbytes, rbyte.astype(jnp.int32))

    out = jnp.where(set_mask, set_val, gathered ^ xor_mask) & 0xFF
    new_len = _chain(
        [(is_del, jnp.maximum(length - blk, 1)),
         (is_ins, jnp.minimum(length + blk, L))], length)
    return (jnp.where(active, out, buf),
            jnp.where(active, new_len, length))


def _fuzz_kernel(instrs_t_ref, table_t_ref, seed_ref, lens_ref,
                 words_ref, zero_ref,
                 status_ref, exit_ref, counts_ref, steps_ref, hash_ref,
                 bufs_out_ref, lens_out_ref,
                 *, mem_size, max_steps, n_edges, stack_pow2, dots):
    instrs_t = instrs_t_ref[...]
    table_t = table_t_ref[...]
    z = zero_ref[...]
    buf = seed_ref[...] + z                     # [L, T] (load-derived)
    length = lens_ref[...] + z                  # [1, T]
    words = words_ref[...]                      # [(n_steps+1)*8, T] u32
    L = buf.shape[0]
    n_steps = 1 << stack_pow2

    stack = jnp.uint32(1) << (1 + words[0:1] % stack_pow2)
    for i in range(n_steps):
        w = words[(i + 1) * 8:(i + 2) * 8]
        active = (jnp.zeros_like(length, dtype=jnp.uint32)
                  + jnp.uint32(i)) < stack
        buf, length = _havoc_edit(buf, length, w, active, L)

    final = _vm_loop(instrs_t, table_t, buf, length, z,
                     mem_size, max_steps, n_edges, dots=dots)
    status_ref[...] = final[4]
    exit_ref[...] = final[5]
    counts_ref[...] = final[7]
    steps_ref[...] = final[10]
    hash_ref[...] = final[8]
    bufs_out_ref[...] = buf
    lens_out_ref[...] = length


def havoc_words_for_keys(keys, stack_pow2=4):
    """The per-lane PRNG words the fused kernel consumes, one column
    per key — generated with EXACTLY havoc_at's stream (one
    ``jax.random.bits(key, (n_steps+1, 8))`` draw per lane) so fused
    mutants are bit-identical to the mutate-then-execute pipeline for
    the SAME per-lane keys, however the caller derived them (the CLI
    mutator folds in absolute iteration indices; bench folds in
    0..B-1).

    Returns uint32[(2**stack_pow2 + 1) * 8, b] (lane-last)."""
    n_steps = 1 << stack_pow2
    b = keys.shape[0]
    words = jax.vmap(
        lambda k: jax.random.bits(k, (n_steps + 1, 8),
                                  dtype=jnp.uint32))(keys)
    return words.reshape(b, (n_steps + 1) * 8).T


def havoc_words(key, b, stack_pow2=4):
    """havoc_words_for_keys over fold_in(key, 0..b-1)."""
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(b, dtype=jnp.uint32))
    return havoc_words_for_keys(keys, stack_pow2)


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "stack_pow2", "interpret", "dots"))
def fuzz_batch_pallas(instrs, edge_table, seed_buf, seed_len, words,
                      mem_size, max_steps, n_edges, stack_pow2=4,
                      interpret=False, dots=DEFAULT_DOTS):
    """Fused fuzz step: havoc mutation AND VM execution in one
    pallas_call — candidates are born, run and triaged (counts) while
    resident in VMEM.  ``seed_buf`` uint8[L], ``words`` from
    havoc_words().  Returns (VMResult, bufs uint8[B, L], lens)."""
    n_words, b = words.shape
    L = seed_buf.shape[0]
    if b % LANE_TILE:
        raise ValueError(f"batch {b} not a multiple of {LANE_TILE}")
    if n_words != ((1 << stack_pow2) + 1) * 8:
        raise ValueError(
            f"words has {n_words} rows but stack_pow2={stack_pow2} "
            f"needs {((1 << stack_pow2) + 1) * 8} — generate with "
            f"havoc_words(key, b, stack_pow2)")
    grid = (b // LANE_TILE,)
    instrs_t = instrs.T
    table_t = edge_table.T
    seed_b = jnp.broadcast_to(seed_buf.astype(jnp.int32)[:, None],
                              (L, b))
    lens = jnp.broadcast_to(
        seed_len.astype(jnp.int32).reshape(1, 1), (1, b))
    zeros = jnp.zeros((1, b), jnp.int32)

    kernel = partial(_fuzz_kernel, mem_size=mem_size,
                     max_steps=max_steps, n_edges=n_edges,
                     stack_pow2=stack_pow2, dots=dots)
    out_shapes = (
        jax.ShapeDtypeStruct((1, b), jnp.int32),
        jax.ShapeDtypeStruct((1, b), jnp.int32),
        jax.ShapeDtypeStruct((n_edges + 1, b), jnp.int32),
        jax.ShapeDtypeStruct((1, b), jnp.int32),
        jax.ShapeDtypeStruct((1, b), jnp.uint32),
        jax.ShapeDtypeStruct((L, b), jnp.int32),
        jax.ShapeDtypeStruct((1, b), jnp.int32),
    )
    whole = lambda *_: (0, 0)  # noqa: E731
    lane_block = lambda i: (0, i)  # noqa: E731
    (status, exit_code, counts, steps, path_hash, bufs,
     out_lens) = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(instrs_t.shape, whole),
            pl.BlockSpec(table_t.shape, whole),
            pl.BlockSpec((L, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((n_words, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
        ],
        out_specs=(
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((n_edges + 1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((L, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(instrs_t, table_t, seed_b, lens, words, zeros)
    res = VMResult(status=status.reshape(b),
                   exit_code=exit_code.reshape(b),
                   counts=counts.T.astype(jnp.uint8),
                   steps=steps.reshape(b),
                   path_hash=path_hash.reshape(b),
                   edge_ids=None)
    return res, bufs.T.astype(jnp.uint8), out_lens.reshape(b)


# --------------------------------------------------------------------
# Two-phase scheduling: break the tail-latency ceiling
# --------------------------------------------------------------------
#
# Each grid tile runs its while-loop until the DEEPEST live lane
# halts.  Mutant depth is heavy-tailed (flagship tlvstack_vm at 16k
# lanes: mean 71 steps, p50 26, but per-tile max ~366 — every tile
# pays ~5x the mean).  Phase 1 runs the fused kernel with a small
# budget K; the ~15% of lanes still running are stably sorted to the
# front and re-executed from scratch with the full budget, with every
# finished lane skip-masked so its tile exits after zero iterations.
# Re-execution (instead of carrying VM state across kernels) keeps
# the kernels unchanged and is cheap: survivors * K wasted steps vs
# the ~all-tiles * (max - K) saved.  Results are bit-identical to the
# single-phase kernel: finished lanes' fields are final at K, and
# survivors re-run deterministically.

def auto_phase1_steps(max_steps: int) -> int:
    """The product's default phase-1 budget: max_steps/8 on deep
    targets (measured best on the flagship: K=128 of 1024), single
    phase on shallow ones where a second kernel's ~3.6ms fixed cost
    can't pay for itself.  jit_harness (phase1_steps=-1) and bench
    both resolve through here so they can never measure different
    schedules."""
    return max_steps // 8 if max_steps >= 256 else 0


def fuzz_batch_pallas_2phase(instrs, edge_table, seed_buf, seed_len,
                             words, mem_size, max_steps, n_edges,
                             stack_pow2=4, phase1_steps=0,
                             interpret=False, dots=DEFAULT_DOTS):
    """fuzz_batch_pallas with two-phase tail scheduling.
    ``phase1_steps``: <0 = auto (auto_phase1_steps); 0 or >=
    max_steps disables phase 2."""
    if phase1_steps < 0:
        phase1_steps = auto_phase1_steps(max_steps)
    res1, bufs, lens = fuzz_batch_pallas(
        instrs, edge_table, seed_buf, seed_len, words, mem_size,
        min(phase1_steps, max_steps) if phase1_steps else max_steps,
        n_edges, stack_pow2=stack_pow2, interpret=interpret,
        dots=dots)
    if not phase1_steps or phase1_steps >= max_steps:
        return res1, bufs, lens

    surv = res1.status == FUZZ_RUNNING
    # stable: equal keys keep lane order -> deterministic tiling
    order = jnp.argsort(jnp.where(surv, 0, 1), stable=True)
    inv = jnp.argsort(order, stable=True)
    res2 = run_batch_pallas(
        instrs, edge_table,
        jnp.take(bufs, order, axis=0), jnp.take(lens, order),
        mem_size, max_steps, n_edges, interpret=interpret,
        skip=jnp.take((~surv).astype(jnp.int32), order), dots=dots)

    def mix(f1, f2_sorted):
        f2 = jnp.take(f2_sorted, inv, axis=0)
        m = surv if f1.ndim == 1 else surv[:, None]
        return jnp.where(m, f2, f1)

    res = VMResult(status=mix(res1.status, res2.status),
                   exit_code=mix(res1.exit_code, res2.exit_code),
                   counts=mix(res1.counts, res2.counts),
                   steps=mix(res1.steps, res2.steps),
                   path_hash=mix(res1.path_hash, res2.path_hash),
                   edge_ids=None)
    return res, bufs, lens
