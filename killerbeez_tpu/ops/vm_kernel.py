"""Pallas TPU kernel for the KBVM step machine.

The XLA while_loop engine (models/vm._run_batch_impl) round-trips the
full interpreter state (registers, scratch memory, edge counts —
~25MB at B=16k) through HBM on every VM step; at ~400 steps per batch
that traffic, not compute, bounds throughput.  This kernel runs the
ENTIRE step loop inside one pallas_call: each grid instance owns a
TILE-lane slice whose state lives in VMEM for the whole execution,
and only the final verdicts/counts are written back.

Mosaic constraints shape the code:
  * lane-LAST layout everywhere — per-lane scalars are [1, T] rows
    and tables are [X, T], so every broadcast is a sublane
    replication (a [T, 1] column would need lane replication, which
    Mosaic's relayout rejects);
  * no 1D arrays (1D boolean vectors fail to lower) and no
    `jnp.select` (it lowers through an f32-only argmax);
  * selects operate on i32 0/1, never on bool VALUES (Mosaic widens
    selected bools to i8 and cannot truncate back to a mask).

The two per-lane "gathers" (instruction fetch, edge-table lookup) are
transposed one-hot MXU matmuls — the TPU has no per-lane gather in
either programming model.

Semantics are bit-identical to models/vm._step_batched (parity-tested
against it); stream recording is not supported here — tracer/ipt runs
stay on the XLA engine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .. import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE
from ..models.vm import (
    ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB, ALU_XOR,
    CMP_EQ, CMP_GE, CMP_LT, CMP_NE, N_REGS,
    OP_ADDI, OP_ALU, OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP, OP_LDB,
    OP_LDI, OP_LDM, OP_LEN, OP_STM, VMResult, _mix32,
)

LANE_TILE = 512  # lanes per grid instance (multiple of 128)


def _pick_rows(table, idx):
    """out[0, t] = table[idx[0, t], t] for table [R, T], idx [1, T]:
    one-hot over the (small, static) row axis."""
    rows = jax.lax.broadcasted_iota(jnp.int32, table.shape, 0)
    return jnp.sum(jnp.where(rows == idx, table, 0), axis=0,
                   keepdims=True).astype(table.dtype)


def _chain(pairs, default):
    """Branch-free first-match select."""
    out = default
    for cond, val in reversed(pairs):
        out = jnp.where(cond, val, out)
    return out


def _vm_kernel(instrs_t_ref, table_t_ref, bufs_ref, lens_ref, zero_ref,
               status_ref, exit_ref, counts_ref, steps_ref, hash_ref,
               *, mem_size, max_steps, n_edges):
    t = bufs_ref.shape[1]                       # TILE lanes
    instrs_t = instrs_t_ref[...].astype(jnp.float32)     # [4, NI]
    table_t = table_t_ref[...].astype(jnp.float32)       # [nb, nb+1]
    ni = instrs_t.shape[1]
    nb = table_t.shape[0]
    bufs = bufs_ref[...]                                 # [L, T] i32
    lengths = lens_ref[...]                              # [1, T]
    L = bufs.shape[0]

    def step(state):
        (pc, regs, mem, prev_loc, status, exit_code, prev_idx,
         counts, path_hash, i, lane_steps) = state
        running = status == FUZZ_RUNNING                 # [1, T] bool

        # ---- instruction fetch: transposed one-hot MXU matmul ----
        pcc = jnp.clip(pc, 0, ni - 1)
        onehot_pc = (jax.lax.broadcasted_iota(jnp.int32, (ni, t), 0)
                     == pcc).astype(jnp.float32)         # [NI, T]
        row = jax.lax.dot(instrs_t, onehot_pc,
                          precision=jax.lax.Precision.HIGHEST)
        row = row.astype(jnp.int32)                      # [4, T]
        op = row[0:1, :]
        a = row[1:2, :]
        b = row[2:3, :]
        c = row[3:4, :]

        rb_idx = (c >> 3) & (N_REGS - 1)
        alu_sel = c & 7
        cmp_sel = b & 3
        cmp_rb = (b >> 2) & (N_REGS - 1)

        ra = _pick_rows(regs, jnp.clip(a, 0, N_REGS - 1))
        rb = _pick_rows(regs, jnp.clip(b, 0, N_REGS - 1))
        ry = _pick_rows(regs, rb_idx)
        cmp_y = _pick_rows(regs, cmp_rb)

        # LDB
        ldb_ok = (rb >= 0) & (rb < lengths)
        ldb_val = _pick_rows(bufs, jnp.clip(rb, 0, L - 1))
        ldb_val = jnp.where(ldb_ok, ldb_val, 0)

        x, y = rb, ry
        shift = jnp.clip(y, 0, 31)
        alu_val = _chain(
            [(alu_sel == ALU_ADD, x + y), (alu_sel == ALU_SUB, x - y),
             (alu_sel == ALU_AND, x & y), (alu_sel == ALU_OR, x | y),
             (alu_sel == ALU_XOR, x ^ y), (alu_sel == ALU_SHL, x << shift),
             (alu_sel == ALU_SHR, jax.lax.shift_right_logical(x, shift)),
             (alu_sel == ALU_MUL, x * y)], jnp.zeros_like(x))
        taken = _chain(
            [(cmp_sel == CMP_EQ, (ra == cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_NE, (ra != cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_LT, (ra < cmp_y).astype(jnp.int32)),
             (cmp_sel == CMP_GE, (ra >= cmp_y).astype(jnp.int32))],
            jnp.zeros_like(ra)) != 0

        mem_ok_ld = (rb >= 0) & (rb < mem_size)
        ldm_val = _pick_rows(mem, jnp.clip(rb, 0, mem_size - 1))
        ldm_val = jnp.where(mem_ok_ld, ldm_val, 0)
        mem_ok_st = (ra >= 0) & (ra < mem_size)

        nxt = pc + 1
        new_pc = _chain([(op == OP_JMP, a),
                         (op == OP_BR, jnp.where(taken, c, nxt))], nxt)
        wr_val = _chain(
            [(op == OP_LDB, ldb_val), (op == OP_LDI, b),
             (op == OP_ALU, alu_val), (op == OP_ADDI, rb + c),
             (op == OP_LEN, lengths), (op == OP_LDM, ldm_val)],
            jnp.zeros_like(pc))
        writes_reg = ((op == OP_LDB) | (op == OP_LDI) | (op == OP_ALU) |
                      (op == OP_ADDI) | (op == OP_LEN) | (op == OP_LDM))
        ridx = jax.lax.broadcasted_iota(jnp.int32, (N_REGS, t), 0)
        wmask = (writes_reg & running) & \
            (ridx == jnp.clip(a, 0, N_REGS - 1))
        new_regs = jnp.where(wmask, wr_val, regs)

        do_store = (op == OP_STM) & mem_ok_st & running
        midx = jax.lax.broadcasted_iota(jnp.int32, (mem_size, t), 0)
        smask = do_store & (midx == jnp.clip(ra, 0, mem_size - 1))
        new_mem = jnp.where(smask, rb, mem)

        crashes = (op == OP_CRASH) | \
                  ((op == OP_LDM) & ~mem_ok_ld) | \
                  ((op == OP_STM) & ~mem_ok_st) | \
                  (pc < 0) | (pc >= ni)
        halts = op == OP_HALT
        new_status = jnp.where(crashes, FUZZ_CRASH,
                               jnp.where(halts, FUZZ_NONE, status))
        new_exit = jnp.where(halts & running, a, exit_code)

        # ---- static-edge accounting ----
        is_block = (op == OP_BLOCK) & running
        cur_loc = a & (MAP_SIZE - 1)
        new_prev = jnp.where(is_block, cur_loc >> 1, prev_loc)
        cur_idx = jnp.clip(b, 0, nb - 1)
        onehot_prev = (jax.lax.broadcasted_iota(
            jnp.int32, (nb + 1, t), 0) == prev_idx).astype(jnp.float32)
        rows_e = jax.lax.dot(table_t, onehot_prev,
                             precision=jax.lax.Precision.HIGHEST)
        # rows_e[cidx, t] = edge index for (prev[t], cidx)   [nb, T]
        eidx = jnp.sum(jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (nb, t), 0) == cur_idx,
            rows_e, 0), axis=0, keepdims=True).astype(jnp.int32)
        eiota = jax.lax.broadcasted_iota(jnp.int32, (n_edges + 1, t), 0)
        emask = (eiota == eidx) & is_block
        new_counts = counts + emask.astype(jnp.int32)
        new_prev_idx = jnp.where(is_block, cur_idx + 1, prev_idx)
        new_hash = jnp.where(
            is_block, _mix32(path_hash ^ cur_loc.astype(jnp.uint32)),
            path_hash)

        def keep(new, old):
            return jnp.where(running, new, old)

        return (keep(new_pc, pc),
                jnp.where(running, new_regs, regs),
                jnp.where(running, new_mem, mem),
                keep(new_prev, prev_loc),
                keep(new_status, status),
                keep(new_exit, exit_code),
                keep(new_prev_idx, prev_idx),
                new_counts, keep(new_hash, path_hash),
                i + 1,
                lane_steps + running.astype(jnp.int32))

    # Loop carries must descend from a memory LOAD: a constant splat
    # (or anything folded to one, like lens*0) gets Mosaic's
    # fully-replicated {*,*} layout, and the loop back-edge cannot
    # relayout the computed {0,0} values into it.
    z = zero_ref[...]                                    # [1, T] zeros
    state0 = (z,
              jnp.zeros((N_REGS, t), jnp.int32) + z,
              jnp.zeros((mem_size, t), jnp.int32) + z,
              z,
              z + FUZZ_RUNNING,
              z,
              z,
              jnp.zeros((n_edges + 1, t), jnp.int32) + z,
              z.astype(jnp.uint32),
              jnp.int32(0),
              z)

    def cond(s):
        return jnp.any(s[4] == FUZZ_RUNNING) & (s[9] < max_steps)

    final = jax.lax.while_loop(cond, lambda s: step(s), state0)
    status_ref[...] = final[4]
    exit_ref[...] = final[5]
    counts_ref[...] = final[7]
    steps_ref[...] = final[10]
    hash_ref[...] = final[8]


@partial(jax.jit, static_argnames=("mem_size", "max_steps", "n_edges",
                                   "interpret"))
def run_batch_pallas(instrs, edge_table, inputs, lengths, mem_size,
                     max_steps, n_edges, interpret=False) -> VMResult:
    """Pallas engine entry: same contract as vm._run_batch_impl with
    record_stream=False.  B must be a multiple of LANE_TILE (callers
    pad; padded lanes are regular executions of duplicated inputs)."""
    b, L = inputs.shape
    if b % LANE_TILE:
        raise ValueError(f"batch {b} not a multiple of {LANE_TILE}")
    grid = (b // LANE_TILE,)
    instrs_t = instrs.T                          # [4, NI]
    table_t = edge_table.T                       # [nb, nb+1]
    bufs_t = inputs.T.astype(jnp.int32)          # [L, B]
    lens = lengths.astype(jnp.int32).reshape(1, b)
    zeros = jnp.zeros((1, b), jnp.int32)         # carry-init source

    kernel = partial(_vm_kernel, mem_size=mem_size,
                     max_steps=max_steps, n_edges=n_edges)
    out_shapes = (
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # status
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # exit
        jax.ShapeDtypeStruct((n_edges + 1, b), jnp.int32),  # counts
        jax.ShapeDtypeStruct((1, b), jnp.int32),          # steps
        jax.ShapeDtypeStruct((1, b), jnp.uint32),         # path hash
    )
    whole = lambda *_: (0, 0)  # noqa: E731 — replicate full array
    lane_block = lambda i: (0, i)  # noqa: E731
    status, exit_code, counts, steps, path_hash = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(instrs_t.shape, whole),
            pl.BlockSpec(table_t.shape, whole),
            pl.BlockSpec((L, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
        ],
        out_specs=(
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((n_edges + 1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
            pl.BlockSpec((1, LANE_TILE), lane_block),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(instrs_t, table_t, bufs_t, lens, zeros)
    return VMResult(status=status.reshape(b),
                    exit_code=exit_code.reshape(b),
                    counts=counts.T.astype(jnp.uint8),
                    steps=steps.reshape(b),
                    path_hash=path_hash.reshape(b),
                    edge_ids=None)
