"""Sparse coverage triage — novelty straight from edge streams.

The dense path materializes a uint8[B, MAP_SIZE] bitmap per batch
(512MB at B=8192) and scans it several times; but a KBVM exec touches
at most ``max_steps`` edges, so everything triage needs is computable
from the [B, T] edge stream directly:

  1. sort each lane's edge ids (invalid -> MAP_SIZE sentinel)
  2. run-length-encode: per unique edge, its hit count -> AFL class
  3. novelty = gather virgin[ids] and test bits (T gathers per lane,
     not MAP_SIZE)
  4. in-batch dedup via a hash of the sorted (id, class) stream
  5. virgin update: scatter-max the class bits of new lanes into a
     [MAP_SIZE, 8] bit-plane table (class is one-hot in bits, so OR
     decomposes into per-bit max), then fold planes into a byte mask

This is the same AFL contract as the dense ops (same classes, same
ret codes, same virgin clearing) with O(B*T) instead of O(B*MAP_SIZE)
memory traffic — the difference between ~2k and ~100k execs/sec/chip.
Parity with the dense path is tested edge-for-edge.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .. import MAP_SIZE
from .coverage import classify_counts


def stream_hash(words: jax.Array) -> jax.Array:
    """Order-aware mixing hash of uint32[B, T] streams in one parallel
    pass (murmur's word chain is sequential — a T-step scan costs as
    much as the whole VM; dedup only needs good mixing, not murmur
    parity, so mix each (word, position) pair and XOR-reduce)."""
    t = words.shape[-1]
    pos = jnp.arange(t, dtype=jnp.uint32)
    x = words.astype(jnp.uint32) ^ (pos[None, :] * jnp.uint32(0x9E3779B9))
    # murmur3 finalizer as the per-element mixer
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return jax.lax.reduce(x, jnp.uint32(0), jax.lax.bitwise_xor,
                          dimensions=(1,))


def first_occurrence(hashes: jax.Array, active: jax.Array) -> jax.Array:
    """bool[B]: lane carries the lowest index among active lanes with
    its hash. O(B log B) via sort (the naive pairwise matrix is O(B^2)
    and dominates the whole fuzz step beyond B~8k)."""
    b = hashes.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)
    # sort by (hash, active-first, index) so each hash-run's head is
    # the lowest-index ACTIVE lane of that hash
    order = jnp.lexsort((idx, ~active, hashes))
    sk = hashes[order]
    head = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    out = jnp.zeros((b,), bool).at[order].set(head)
    return out & active


def sparse_classify(edge_ids: jax.Array, valid: jax.Array
                    ) -> Tuple[jax.Array, jax.Array]:
    """Per-lane sorted unique edges and their AFL count classes.

    Args:  edge_ids int32[B, T], valid bool[B, T]
    Returns (ids int32[B, T], cls uint8[B, T]) where ids are sorted,
    duplicates collapsed to the run head, and non-heads/invalid
    entries carry id == MAP_SIZE, cls == 0.
    """
    ids = jnp.where(valid, edge_ids, MAP_SIZE)
    ids = jnp.sort(ids, axis=1)
    is_head = jnp.concatenate(
        [jnp.ones_like(ids[:, :1], dtype=bool),
         ids[:, 1:] != ids[:, :-1]], axis=1) & (ids < MAP_SIZE)
    # hit count per position = run length; compute via positional
    # cumsum difference: index of next head minus index of this head
    t = ids.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    # for each position, the index of the run head it belongs to
    head_pos = jax.lax.cummax(jnp.where(is_head, pos, -1), axis=1)
    head_pos = jnp.where(head_pos < 0, t, head_pos)  # dead -> sentinel
    # count for a head = number of positions whose head_pos == its pos
    ones = (ids < MAP_SIZE).astype(jnp.int32)
    counts = jax.vmap(
        lambda hp, o: jnp.zeros((t,), jnp.int32).at[hp].add(o,
                                                            mode="drop")
    )(head_pos, ones)
    counts = counts % 256  # wrap like the dense path's u8 increments
    cls = jnp.where(is_head, classify_counts(counts.astype(jnp.uint8)),
                    jnp.uint8(0))
    out_ids = jnp.where(is_head, ids, MAP_SIZE)
    return out_ids, cls


def sparse_has_new_bits_batch(virgin: jax.Array, ids: jax.Array,
                              cls: jax.Array,
                              active: jax.Array | None = None
                              ) -> Tuple[jax.Array, jax.Array]:
    """Batched novelty from sparse (ids, cls) vs a shared virgin map.

    Same semantics as dense ``has_new_bits_batch``: all lanes judged
    against the incoming map, deduped in-batch by stream hash, then
    the map is updated with the union of new lanes' bits.

    Returns (rets int32[B], new_virgin uint8[MAP_SIZE]).
    """
    b = ids.shape[0]
    rets = _novelty_rets(virgin, ids, cls)

    # in-batch dedup: hash the sorted (id, cls) stream
    words = ids.astype(jnp.uint32) ^ (cls.astype(jnp.uint32) << 20)
    hashes = stream_hash(words)
    if active is None:
        active = jnp.ones((b,), dtype=bool)
    first = first_occurrence(hashes, active)
    rets = jnp.where(first & active, rets, 0).astype(jnp.int32)
    return rets, virgin & ~_virgin_update_mask(ids, cls, rets > 0)


def _virgin_update_mask(ids: jax.Array, cls: jax.Array,
                        is_new: jax.Array) -> jax.Array:
    """OR of new lanes' class bits per edge -> uint8[MAP_SIZE] mask,
    via per-bit scatter-max into bit planes."""
    live = ids < MAP_SIZE
    flat_ids = jnp.where(is_new[:, None] & live, ids,
                         MAP_SIZE).reshape(-1)
    flat_cls = jnp.where(is_new[:, None], cls, 0).reshape(-1)
    bitpos = jnp.arange(8, dtype=jnp.uint8)
    bits = ((flat_cls[:, None] >> bitpos[None, :]) & 1)
    planes = jnp.zeros((MAP_SIZE + 1, 8), dtype=jnp.uint8)
    planes = planes.at[flat_ids].max(bits, mode="drop")
    return jnp.sum(
        planes[:MAP_SIZE].astype(jnp.uint32)
        << bitpos[None, :].astype(jnp.uint32), axis=1).astype(jnp.uint8)


def _novelty_rets(virgin, ids, cls):
    live = ids < MAP_SIZE
    v = virgin[jnp.clip(ids, 0, MAP_SIZE - 1)]
    v = jnp.where(live, v, jnp.uint8(0))
    new_count = jnp.any((cls & v) != 0, axis=1)
    new_tuple = jnp.any((cls != 0) & (v == 0xFF), axis=1)
    return jnp.where(new_tuple, 2, jnp.where(new_count, 1, 0))


def _first_occurrence_multi(hashes: jax.Array, crash: jax.Array,
                            hang: jax.Array
                            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(first_all, first_crash, first_hang) bool[B] in ONE argsort:
    group lanes into hash-runs, then per run take the lowest original
    index overall / among crash lanes / among hang lanes via
    segment-min. Three separate first_occurrence calls cost three
    lexsorts; the sort is the expensive part and it's shared here."""
    b = hashes.shape[0]
    order = jnp.argsort(hashes)  # stable: ties keep index order
    sh = hashes[order]
    head = jnp.concatenate([jnp.ones((1,), bool), sh[1:] != sh[:-1]])
    run = jnp.cumsum(head.astype(jnp.int32)) - 1  # segment id, sorted dom.

    def firsts(pred_orig):
        vals = jnp.where(pred_orig[order], order, b)
        m = jax.ops.segment_min(vals, run, num_segments=b)
        hit_sorted = pred_orig[order] & (order == m[run])
        return jnp.zeros((b,), bool).at[order].set(hit_sorted)

    return (firsts(jnp.ones((b,), bool)), firsts(crash), firsts(hang))


def _presence_mask(ids: jax.Array, is_new: jax.Array) -> jax.Array:
    """uint8[MAP_SIZE] with bit 7 set on every edge touched by a new
    lane — the sparse ``simplify_trace`` contribution (presence-only,
    see sparse_simplify). One scatter plane instead of eight."""
    live = ids < MAP_SIZE
    flat = jnp.where(is_new[:, None] & live, ids, MAP_SIZE).reshape(-1)
    plane = jnp.zeros((MAP_SIZE + 1,), jnp.uint8)
    plane = plane.at[flat].max(jnp.ones_like(flat, jnp.uint8),
                               mode="drop")
    return plane[:MAP_SIZE] << 7


def sparse_triage(vb: jax.Array, vc: jax.Array, vh: jax.Array,
                  edge_ids: jax.Array, valid: jax.Array,
                  crash: jax.Array, hang: jax.Array):
    """Fused throughput triage over all three AFL maps, sharing the
    sort/classify/hash work (three separate sparse_has_new_bits_batch
    calls triple it).

    The virgin scatters are the step's dominant cost at large B, and
    most steady-state batches find nothing new — each update runs
    under ``lax.cond`` so a batch with no new lanes skips its scatter
    entirely (TPU executes only the taken branch of a conditional).

    Returns (rets, unique_crash, unique_hang, vb', vc', vh').
    """
    ids, cls = sparse_classify(edge_ids, valid)
    simp = sparse_simplify(ids)
    words = ids.astype(jnp.uint32) ^ (cls.astype(jnp.uint32) << 20)
    hashes = stream_hash(words)

    rets = _novelty_rets(vb, ids, cls)
    crash_rets = _novelty_rets(vc, ids, simp)
    hang_rets = _novelty_rets(vh, ids, simp)

    first_all, first_crash, first_hang = _first_occurrence_multi(
        hashes, crash, hang)
    rets = jnp.where(first_all, rets, 0).astype(jnp.int32)
    uc = first_crash & (crash_rets > 0)
    uh = first_hang & (hang_rets > 0)

    def upd(virgin, mask_fn, any_new):
        return jax.lax.cond(any_new,
                            lambda v: v & ~mask_fn(),
                            lambda v: v, virgin)

    vb2 = upd(vb, lambda: _virgin_update_mask(ids, cls, rets > 0),
              jnp.any(rets > 0))
    vc2 = upd(vc, lambda: _presence_mask(ids, uc), jnp.any(uc))
    vh2 = upd(vh, lambda: _presence_mask(ids, uh), jnp.any(uh))
    return rets, uc, uh, vb2, vc2, vh2


def sparse_simplify(ids: jax.Array) -> jax.Array:
    """Simplified-trace classes for crash/hang maps: every live edge
    contributes the 128 ("hit") bit.

    Known divergence from the dense ``simplify_trace``: the dense form
    also gives *absent* edges a 1 bit, so a crash distinguished only
    by NOT hitting an edge counts as unique. The sparse path can't see
    absence without materializing the map, so throughput-mode unique-
    crash/hang counting is presence-only; ``novelty="exact"`` keeps
    the full dense semantics."""
    return jnp.where(ids < MAP_SIZE, jnp.uint8(128), jnp.uint8(0))
