"""Device-side coverage and hashing primitives.

These are the TPU re-implementations of the reference's hot bitmap
loops (reference afl_instrumentation.c:600-707,
dynamorio_instrumentation.c:1428-1469) as vectorized XLA ops.
"""

from .coverage import (
    classify_counts,
    simplify_trace,
    has_new_bits,
    has_new_bits_with_ignore,
    has_new_bits_seq,
    has_new_bits_batch,
    update_virgin,
    merge_virgin,
    build_bitmap,
    count_non_255_bytes,
    count_bytes,
)
from .hashing import murmur3_32, murmur3_32_np, xxh64, hash_bitmaps

__all__ = [
    "classify_counts", "simplify_trace", "has_new_bits",
    "has_new_bits_with_ignore", "has_new_bits_seq", "has_new_bits_batch",
    "update_virgin", "merge_virgin", "build_bitmap",
    "count_non_255_bytes", "count_bytes",
    "murmur3_32", "murmur3_32_np", "xxh64", "hash_bitmaps",
]
