/* kb_trace — binary-only coverage tracer (the QEMU-mode tier).
 *
 * The reference fuzzes uninstrumented binaries by running them under
 * a patched QEMU user-mode emulator that logs translated-block edges
 * into the AFL SHM bitmap and acts as the forkserver
 * (SURVEY.md §2.5, reference afl_progs/qemu_mode/ +
 * afl-qemu-cpu-inl.h semantics).  This is the same capability built
 * on ptrace instead of an emulator: kb_trace IS the forkserver
 * (protocol in kb_protocol.h, fds 198/199), forks the target under
 * PTRACE_TRACEME, single-steps it, and hashes every program-counter
 * transition into the __AFL_SHM_ID bitmap with the AFL edge
 * encoding (cur ^ prev, prev = cur >> 1).
 *
 * Trade-offs vs the reference's QEMU tier, documented honestly:
 *   + zero target cooperation: works on any ELF the kernel can run,
 *     no compile-time instrumentation, no emulator build;
 *   + real syscalls/signals (no emulation gaps);
 *   - single-stepping costs ~2 context switches per instruction —
 *     orders slower than QEMU block translation; this tier is for
 *     triage and coverage of small binary-only targets, not
 *     throughput fuzzing (the jit_harness/afl tiers are);
 *   - per-instruction (not per-block) granularity: slot density is
 *     higher than compiled-in edge logging; within-tier novelty is
 *     consistent, cross-tier maps are not comparable.
 *
 * ASLR: the child runs under ADDR_NO_RANDOMIZE, so PCs (and
 * therefore bitmap slots) are stable across execs of one campaign —
 * the property coverage merging needs.
 *
 * Usage: kb_trace TARGET [ARGS...]  (the fuzzer prepends this via
 * the afl instrumentation's qemu_mode/qemu_path options).
 */
#define _GNU_SOURCE
#include <elf.h>
#include <errno.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/personality.h>
#include <sys/ptrace.h>
#include <sys/shm.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

#define KB_FORKSERVER_IMPL_NOT_USED /* we implement our own loop */
#include "kb_protocol.h"

static unsigned char kb_local_map[KB_SHM_TOTAL];
static unsigned char *kb_map = kb_local_map;

/* Guard against runaway children when no fuzzer is attached to kill
 * them (the fuzzer's own hang timeout is the primary mechanism). */
#define KB_MAX_STEPS (1u << 26)

static void kb_attach_shm(void) {
  const char *id_str = getenv(KB_SHM_ENV);
  if (!id_str) return;
  void *addr = shmat(atoi(id_str), NULL, 0);
  if (addr != (void *)-1) kb_map = (unsigned char *)addr;
}

static uintptr_t kb_read_pc(pid_t pid) {
#if defined(__x86_64__)
  struct user_regs_struct regs;
  if (ptrace(PTRACE_GETREGS, pid, NULL, &regs) != 0) return 0;
  return (uintptr_t)regs.rip;
#elif defined(__aarch64__)
  struct user_regs_struct regs;
  struct iovec iov = {&regs, sizeof regs};
  if (ptrace(PTRACE_GETREGSET, pid, (void *)NT_PRSTATUS, &iov) != 0)
    return 0;
  return (uintptr_t)regs.pc;
#else
#error "kb_trace: unsupported architecture"
#endif
}

/* Same PC mixer as kb_rt.c's compiled-in hook — per-instruction here
 * instead of per-edge-callback there. */
static inline unsigned kb_slot(uintptr_t pc) {
  uintptr_t h = pc;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 29;
  return (unsigned)(h & (KB_MAP_SIZE - 1));
}

/* ---- skip-to-entry: the dynamic loader + libc init are millions of
 * instructions; stepping them per exec cost ~8s.  Plant a breakpoint
 * at the target ELF's entry point, PTRACE_CONT to it at full speed,
 * and single-step only from there (QEMU's translation cache plays
 * the same role for the reference's tier).  Any failure falls back
 * to stepping everything. ---- */

static uintptr_t kb_image_base(pid_t pid, const char *real) {
  char mp[64], line[512];
  snprintf(mp, sizeof mp, "/proc/%d/maps", (int)pid);
  FILE *f = fopen(mp, "r");
  uintptr_t base = 0;
  while (f && fgets(line, sizeof line, f)) {
    unsigned long lo, hi;
    char path[384];
    path[0] = 0;
    if (sscanf(line, "%lx-%lx %*s %*s %*s %*s %383s",
               &lo, &hi, path) >= 2 && !strcmp(path, real)) {
      base = lo;
      break; /* lowest mapping of the image */
    }
  }
  if (f) fclose(f);
  return base;
}

static uintptr_t kb_entry_addr(pid_t pid, const char *target) {
  char real[512];
  if (!realpath(target, real)) return 0;
  FILE *f = fopen(real, "rb");
  if (!f) return 0;
  Elf64_Ehdr eh;
  size_t n = fread(&eh, 1, sizeof eh, f);
  fclose(f);
  if (n != sizeof eh || memcmp(eh.e_ident, ELFMAG, SELFMAG) != 0 ||
      eh.e_ident[EI_CLASS] != ELFCLASS64)
    return 0;
  if (eh.e_type == ET_EXEC) return (uintptr_t)eh.e_entry;
  if (eh.e_type != ET_DYN) return 0;
  uintptr_t base = kb_image_base(pid, real);
  return base ? base + (uintptr_t)eh.e_entry : 0;
}

#if defined(__x86_64__)
#define KB_BP_WORD(orig) (((orig) & ~0xFFUL) | 0xCCUL) /* int3 */
#define KB_BP_PC_REWIND 1 /* int3 leaves pc past the trap byte */
#elif defined(__aarch64__)
#define KB_BP_WORD(orig) \
  (((orig) & ~0xFFFFFFFFUL) | 0xD4200000UL) /* brk #0 */
#define KB_BP_PC_REWIND 0
#endif

static void kb_set_pc(pid_t pid, uintptr_t pc) {
#if defined(__x86_64__)
  struct user_regs_struct regs;
  if (ptrace(PTRACE_GETREGS, pid, NULL, &regs) != 0) return;
  regs.rip = pc;
  ptrace(PTRACE_SETREGS, pid, NULL, &regs);
#elif defined(__aarch64__)
  struct user_regs_struct regs;
  struct iovec iov = {&regs, sizeof regs};
  if (ptrace(PTRACE_GETREGSET, pid, (void *)NT_PRSTATUS, &iov) != 0)
    return;
  regs.pc = pc;
  ptrace(PTRACE_SETREGSET, pid, (void *)NT_PRSTATUS, &iov);
#endif
}

/* Returns 0 if the child is stopped and ready for stepping (at entry
 * or, on any fallback, wherever it already was), or sets *status_out
 * and returns 1 if the child terminated while getting there. */
static int kb_run_to_entry(pid_t pid, const char *target,
                           int *status_out) {
  errno = 0;
  uintptr_t entry = kb_entry_addr(pid, target);
  if (!entry) return 0;
  long orig = ptrace(PTRACE_PEEKTEXT, pid, (void *)entry, NULL);
  if (orig == -1 && errno) return 0;
  if (ptrace(PTRACE_POKETEXT, pid, (void *)entry,
             (void *)KB_BP_WORD((unsigned long)orig)) != 0)
    return 0;
  if (ptrace(PTRACE_CONT, pid, NULL, NULL) != 0) return 0;
  int status;
  if (waitpid(pid, &status, 0) < 0) return 0;
  if (WIFEXITED(status) || WIFSIGNALED(status)) {
    *status_out = status;
    return 1;
  }
  /* restore the original word and re-aim the pc at the entry */
  ptrace(PTRACE_POKETEXT, pid, (void *)entry, (void *)orig);
  if (WSTOPSIG(status) == SIGTRAP && KB_BP_PC_REWIND)
    kb_set_pc(pid, entry);
  return 0;
}

static pid_t kb_spawn(char **argv) {
  pid_t pid = fork();
  if (pid < 0) return -1;
  if (pid == 0) {
    close(KB_FORKSRV_FD);
    close(KB_STATUS_FD);
    personality(ADDR_NO_RANDOMIZE); /* stable PCs -> stable slots */
    if (ptrace(PTRACE_TRACEME, 0, NULL, NULL) != 0) _exit(124);
    execvp(argv[0], argv);
    _exit(125); /* exec failed */
  }
  /* child stops with SIGTRAP at the execvp boundary */
  int status;
  if (waitpid(pid, &status, 0) < 0 || !WIFSTOPPED(status)) {
    if (pid > 0) kill(pid, SIGKILL);
    return -1;
  }
  return pid;
}

/* Single-step `pid` to completion, filling the bitmap.  Returns the
 * final wait status (exit or fatal signal). */
static int kb_step_loop(pid_t pid, const char *target) {
  unsigned prev = 0;
  int status = 0;
  int deliver = 0;
  if (kb_run_to_entry(pid, target, &status)) return status;
  for (unsigned n = 0; n < KB_MAX_STEPS; n++) {
    if (ptrace(PTRACE_SINGLESTEP, pid, NULL,
               (void *)(uintptr_t)deliver) != 0) {
      /* child vanished (e.g. fuzzer SIGKILLed it on hang timeout) */
      waitpid(pid, &status, 0);
      return status;
    }
    if (waitpid(pid, &status, 0) < 0) return status;
    if (WIFEXITED(status) || WIFSIGNALED(status)) return status;
    if (!WIFSTOPPED(status)) return status;
    int sig = WSTOPSIG(status);
    if (sig == SIGTRAP) {
      deliver = 0;
      unsigned cur = kb_slot(kb_read_pc(pid));
      kb_map[cur ^ prev]++;
      prev = cur >> 1;
    } else {
      /* deliver the real signal; default dispositions (SIGSEGV...)
       * then terminate the child and we report that status */
      deliver = sig;
    }
  }
  kill(pid, SIGKILL); /* runaway: no fuzzer attached to time it out */
  waitpid(pid, &status, 0);
  return status;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s target [args...]\n", argv[0]);
    return 2;
  }
  kb_attach_shm();

  uint32_t hello = KB_HELLO;
  if (write(KB_STATUS_FD, &hello, 4) != 4) {
    /* no fuzzer attached: trace one run, report coverage, propagate */
    pid_t pid = kb_spawn(argv + 1);
    if (pid < 0) return 2;
    int status = kb_step_loop(pid, argv[1]);
    unsigned touched = 0;
    for (unsigned i = 0; i < KB_MAP_SIZE; i++) touched += kb_map[i] != 0;
    fprintf(stderr, "kb_trace: %u bitmap slots touched\n", touched);
    if (WIFSIGNALED(status)) {
      raise(WTERMSIG(status));
      return 128 + WTERMSIG(status);
    }
    return WIFEXITED(status) ? WEXITSTATUS(status) : 2;
  }

  pid_t child = -1;
  for (;;) {
    unsigned char cmd;
    if (read(KB_FORKSRV_FD, &cmd, 1) != 1) _exit(0);
    switch (cmd) {
      case KB_CMD_EXIT:
        if (child > 0) kill(child, SIGKILL);
        _exit(0);

      case KB_CMD_FORK:
      case KB_CMD_FORK_RUN: {
        child = kb_spawn(argv + 1);
        int32_t pid32 = (int32_t)child;
        if (write(KB_STATUS_FD, &pid32, 4) != 4) _exit(1);
        if (child < 0) _exit(1);
        break;
      }

      case KB_CMD_RUN:
        /* stepping happens under GET_STATUS (the fuzzer's wait
         * point); the child stays stopped until then */
        break;

      case KB_CMD_GET_STATUS: {
        int32_t st32 = -1;
        if (child > 0) {
          st32 = (int32_t)kb_step_loop(child, argv[1]);
          child = -1;
        }
        if (write(KB_STATUS_FD, &st32, 4) != 4) _exit(1);
        break;
      }

      default:
        _exit(2);
    }
  }
}
