/* kb_trace — binary-only coverage tracer (the QEMU-mode tier).
 *
 * The reference fuzzes uninstrumented binaries by running them under
 * a patched QEMU user-mode emulator that logs translated-block edges
 * into the AFL SHM bitmap and acts as the forkserver
 * (SURVEY.md §2.5, reference afl_progs/qemu_mode/ +
 * afl-qemu-cpu-inl.h semantics).  This is the same capability built
 * on ptrace instead of an emulator: kb_trace IS the forkserver
 * (protocol in kb_protocol.h, fds 198/199), forks the target under
 * PTRACE_TRACEME and records block-level control flow into the
 * __AFL_SHM_ID bitmap with the AFL edge encoding
 * (cur ^ prev, prev = cur >> 1).
 *
 * Coverage engine (x86_64): BLOCK-granular, not instruction-granular.
 *   - PTRACE_SINGLEBLOCK (DEBUGCTL.BTF branch-step) stops the child
 *     only at branch targets, one stop per basic block executed —
 *     the same granularity QEMU's translated-block hook gives the
 *     reference tier (afl-qemu-cpu-inl.h: one log call per TB).
 *   - Stepping is confined to the main executable's x-ranges.  When
 *     control leaves the image (library/loader code), the tracer
 *     plants an int3 at the call's return address (validated by a
 *     preceding-CALL byte check) and PTRACE_CONTs, so libc runs at
 *     full native speed; on the first excursion it also breaks on
 *     any in-image function pointers riding the SysV argument
 *     registers — that is how main() itself is caught when
 *     _start -> __libc_start_main(main, ...) leaves the image.
 *   - The dynamic loader is skipped the same way (entry breakpoint),
 *     and in forkserver mode a fork-template parked at main() mints
 *     each exec's child via an injected clone() — the reference QEMU
 *     forkserver's fork-at-first-translated-block play — so
 *     steady-state execs skip execve + dynamic loading entirely.
 *   This turned ~0.2s/exec (per-instruction stepping, round 3) into
 *   low-single-digit ms/exec — measured numbers in docs/HOST_TIER.md.
 *
 * Trade-offs vs the reference's QEMU tier, documented honestly:
 *   + zero target cooperation: works on any ELF the kernel can run,
 *     no compile-time instrumentation, no emulator build;
 *   + real syscalls/signals (no emulation gaps);
 *   - coverage is main-image-only (the reference's default
 *     AFL_INST_LIBS=0 has the same scope); library-internal paths
 *     and callbacks invoked from library code via non-argument
 *     function pointers are not traced;
 *   - block boundaries come from the hardware branch trap, so slot
 *     identities differ from compiled-in edge logging; within-tier
 *     novelty is consistent, cross-tier maps are not comparable.
 *
 * Default engine (forkserver mode, x86_64): UnTracer-style
 * coverage-only breakpoints — steady-state execs run at native
 * PTRACE_CONT speed and only novelty pays for tracing; see the
 * "UnTracer mode" comment block below.  KB_TRACE_FULL=1 forces the
 * block engine for every exec.
 *
 * Fallback engine: per-instruction PTRACE_SINGLESTEP over everything
 * (the round-3 engine) on non-x86 hosts, when the kernel rejects
 * PTRACE_SINGLEBLOCK, or when KB_TRACE_STEP=1 is set.
 *
 * ASLR: the child runs under ADDR_NO_RANDOMIZE, so PCs (and
 * therefore bitmap slots) are stable across execs of one campaign —
 * the property coverage merging needs.
 *
 * Usage: kb_trace TARGET [ARGS...]  (the fuzzer prepends this via
 * the afl instrumentation's qemu_mode/qemu_path options).
 */
#define _GNU_SOURCE
#include <elf.h>
#include <errno.h>
#include <fcntl.h>
#include <limits.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/personality.h>
#include <time.h>
#include <sys/ptrace.h>
#include <sys/shm.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

#define KB_FORKSERVER_IMPL_NOT_USED /* we implement our own loop */
#include "kb_protocol.h"

static unsigned char kb_local_map[KB_SHM_TOTAL];
static unsigned char *kb_map = kb_local_map;

/* Guard against runaway children when no fuzzer is attached to kill
 * them (the fuzzer's own hang timeout is the primary mechanism). */
#define KB_MAX_STEPS (1u << 26)

/* A stop signal with no terminating disposition (SIGSTOP group-stop,
 * or a handled signal whose handler re-raises) would otherwise be
 * re-delivered forever; bound identical consecutive stops. */
#define KB_MAX_STALL 16384

static void kb_attach_shm(void) {
  const char *id_str = getenv(KB_SHM_ENV);
  if (!id_str) return;
  void *addr = shmat(atoi(id_str), NULL, 0);
  if (addr != (void *)-1) kb_map = (unsigned char *)addr;
}

static uintptr_t kb_read_pc(pid_t pid) {
#if defined(__x86_64__)
  struct user_regs_struct regs;
  if (ptrace(PTRACE_GETREGS, pid, NULL, &regs) != 0) return 0;
  return (uintptr_t)regs.rip;
#elif defined(__aarch64__)
  struct user_regs_struct regs;
  struct iovec iov = {&regs, sizeof regs};
  if (ptrace(PTRACE_GETREGSET, pid, (void *)NT_PRSTATUS, &iov) != 0)
    return 0;
  return (uintptr_t)regs.pc;
#else
#error "kb_trace: unsupported architecture"
#endif
}

/* Same PC mixer as kb_rt.c's compiled-in hook — per-block here
 * instead of per-edge-callback there. */
static inline unsigned kb_slot(uintptr_t pc) {
  uintptr_t h = pc;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 29;
  return (unsigned)(h & (KB_MAP_SIZE - 1));
}

static unsigned kb_prev; /* rolling AFL edge state, reset per exec */

static FILE *kb_log; /* KB_TRACE_LOG=path: per-exec PC stream dump */

/* ---- hash mode (KB_TRACE_HASH=1): the host-binary `ipt` tier.
 * The reference's flagship Linux instrumentation reduces each exec
 * to an XXH64 (tip, tnt) pair over the Intel-PT packet stream and
 * calls the exec novel when the pair is unseen
 * (linux_ipt_instrumentation.c:212-426).  This host has no PT PMU;
 * the block tracer already observes the same control flow, so hash
 * mode folds the ordered block-PC stream into two murmur-style
 * 64-bit accumulators — tip over the targets, tnt over the
 * transition stream (pc ^ prev>>1), the two roles the reference's
 * TIP/TNT packets play — and publishes the pair in the first 16
 * bytes of the SHM region at exec end (hash coverage does not use
 * the bitmap).  Path-sensitive novelty requires observing every
 * block, so hash mode forces the full block engine (no UnTracer). */
static int kb_opt_hash;
static uint64_t kb_h_tip, kb_h_tnt;
static uintptr_t kb_h_prev;
#define KB_H_TIP_SEED 0x1994C9A500000001ULL
#define KB_H_TNT_SEED 0x7E57ED0100000001ULL

static inline uint64_t kb_mix64(uint64_t h, uint64_t v) {
  v *= 0x87c37b91114253d5ULL;
  v = (v << 31) | (v >> 33);
  v *= 0x4cf5ad432745937fULL;
  h ^= v;
  h = (h << 27) | (h >> 37);
  return h * 5 + 0x52dce729;
}

static void kb_hash_reset(void) {
  kb_h_tip = KB_H_TIP_SEED;
  kb_h_tnt = KB_H_TNT_SEED;
  kb_h_prev = 0;
}

static void kb_hash_writeout(void) {
  if (!kb_opt_hash) return;
  memcpy(kb_map, &kb_h_tip, 8);
  memcpy(kb_map + 8, &kb_h_tnt, 8);
}

static inline void kb_record(uintptr_t pc) {
  unsigned cur = kb_slot(pc);
  if (kb_log) fprintf(kb_log, "%lx\n", (unsigned long)pc);
  kb_map[cur ^ kb_prev]++;
  kb_prev = cur >> 1;
  if (kb_opt_hash) {
    kb_h_tip = kb_mix64(kb_h_tip, (uint64_t)pc);
    kb_h_tnt = kb_mix64(kb_h_tnt, (uint64_t)(pc ^ (kb_h_prev >> 1)));
    kb_h_prev = pc;
  }
}

/* ---- main-image executable ranges (block mode steps only inside
 * these; everything else runs under PTRACE_CONT at native speed) */

typedef struct {
  uintptr_t lo, hi;
} kb_range;
#define KB_MAX_XR 16
static kb_range kb_xr[KB_MAX_XR];
static int kb_nxr;

static int kb_in_image(uintptr_t pc) {
  for (int i = 0; i < kb_nxr; i++)
    if (pc >= kb_xr[i].lo && pc < kb_xr[i].hi) return 1;
  return 0;
}

static int kb_load_xranges(pid_t pid, const char *target) {
  static char real[PATH_MAX], line[PATH_MAX + 128], path[PATH_MAX];
  char mp[64];
  /* ADDR_NO_RANDOMIZE pins the layout, so the ranges from the first
   * exec hold for every later fork of the same target: parse once. */
  if (kb_nxr) return kb_nxr;
  if (!realpath(target, real)) return 0;
  snprintf(mp, sizeof mp, "/proc/%d/maps", (int)pid);
  FILE *f = fopen(mp, "r");
  while (f && fgets(line, sizeof line, f)) {
    unsigned long lo, hi;
    char perms[8];
    path[0] = 0;
    if (sscanf(line, "%lx-%lx %7s %*s %*s %*s %4095s",
               &lo, &hi, perms, path) >= 3 &&
        strchr(perms, 'x') && !strcmp(path, real) &&
        kb_nxr < KB_MAX_XR) {
      kb_xr[kb_nxr].lo = lo;
      kb_xr[kb_nxr].hi = hi;
      kb_nxr++;
    }
  }
  if (f) fclose(f);
  return kb_nxr;
}

/* ---- skip-to-entry: the dynamic loader is millions of
 * instructions; plant a breakpoint at the target ELF's entry point,
 * PTRACE_CONT to it at full speed, and trace only from there (QEMU's
 * translation cache plays the same role for the reference's tier).
 * Any failure falls back to tracing everything. ---- */

static uintptr_t kb_image_base(pid_t pid, const char *real) {
  static char line[PATH_MAX + 128], path[PATH_MAX];
  char mp[64];
  snprintf(mp, sizeof mp, "/proc/%d/maps", (int)pid);
  FILE *f = fopen(mp, "r");
  uintptr_t base = 0;
  while (f && fgets(line, sizeof line, f)) {
    unsigned long lo, hi;
    path[0] = 0;
    if (sscanf(line, "%lx-%lx %*s %*s %*s %*s %4095s",
               &lo, &hi, path) >= 2 && !strcmp(path, real)) {
      base = lo;
      break; /* lowest mapping of the image */
    }
  }
  if (f) fclose(f);
  return base;
}

static uintptr_t kb_entry_addr(pid_t pid, const char *target) {
  static uintptr_t cached; /* stable: ADDR_NO_RANDOMIZE, one target */
  static char real[PATH_MAX];
  if (cached) return cached;
  if (!realpath(target, real)) return 0;
  FILE *f = fopen(real, "rb");
  if (!f) return 0;
  Elf64_Ehdr eh;
  size_t n = fread(&eh, 1, sizeof eh, f);
  fclose(f);
  if (n != sizeof eh || memcmp(eh.e_ident, ELFMAG, SELFMAG) != 0 ||
      eh.e_ident[EI_CLASS] != ELFCLASS64)
    return 0;
  if (eh.e_type == ET_EXEC) return cached = (uintptr_t)eh.e_entry;
  if (eh.e_type != ET_DYN) return 0;
  uintptr_t base = kb_image_base(pid, real);
  return cached = base ? base + (uintptr_t)eh.e_entry : 0;
}

#if defined(__x86_64__)
#define KB_BP_WORD(orig) (((orig) & ~0xFFUL) | 0xCCUL) /* int3 */
#define KB_BP_PC_REWIND 1 /* int3 leaves pc past the trap byte */
#elif defined(__aarch64__)
#define KB_BP_WORD(orig) \
  (((orig) & ~0xFFFFFFFFUL) | 0xD4200000UL) /* brk #0 */
#define KB_BP_PC_REWIND 0
#endif

static void kb_set_pc(pid_t pid, uintptr_t pc) {
#if defined(__x86_64__)
  struct user_regs_struct regs;
  if (ptrace(PTRACE_GETREGS, pid, NULL, &regs) != 0) return;
  regs.rip = pc;
  ptrace(PTRACE_SETREGS, pid, NULL, &regs);
#elif defined(__aarch64__)
  struct user_regs_struct regs;
  struct iovec iov = {&regs, sizeof regs};
  if (ptrace(PTRACE_GETREGSET, pid, (void *)NT_PRSTATUS, &iov) != 0)
    return;
  regs.pc = pc;
  ptrace(PTRACE_SETREGSET, pid, (void *)NT_PRSTATUS, &iov);
#endif
}

/* ---- re-entry breakpoints (block mode): int3s planted in the
 * child's image so control returning from an untraced library
 * excursion hands the stop back to the tracer.  Per-exec table (the
 * child's text is fresh each fork). ---- */

typedef struct {
  uintptr_t addr;
  long orig;
} kb_bp;
#define KB_MAX_BP 256
static kb_bp kb_bps[KB_MAX_BP];
static int kb_nbps;
static unsigned kb_dbg_bp_dropped; /* plants skipped: table full */

static int kb_bp_find(uintptr_t addr) {
  for (int i = 0; i < kb_nbps; i++)
    if (kb_bps[i].addr == addr) return i;
  return -1;
}

static void kb_bp_plant(pid_t pid, uintptr_t addr) {
  if (!kb_in_image(addr) || kb_bp_find(addr) >= 0) return;
  if (kb_nbps >= KB_MAX_BP) {
    /* control returning from a later excursion will not be re-trapped
     * — count it so truncated coverage is observable (KB_TRACE_DEBUG)
     * instead of silent */
    kb_dbg_bp_dropped++;
    return;
  }
  errno = 0;
  long orig = ptrace(PTRACE_PEEKTEXT, pid, (void *)addr, NULL);
  if (orig == -1 && errno) return;
  if (ptrace(PTRACE_POKETEXT, pid, (void *)addr,
             (void *)KB_BP_WORD((unsigned long)orig)) != 0)
    return;
  kb_bps[kb_nbps].addr = addr;
  kb_bps[kb_nbps].orig = orig;
  kb_nbps++;
}

/* Restore the original word at addr if we have a breakpoint there;
 * returns 1 if one was armed. */
static int kb_bp_clear(pid_t pid, uintptr_t addr) {
  int i = kb_bp_find(addr);
  if (i < 0) return 0;
  ptrace(PTRACE_POKETEXT, pid, (void *)kb_bps[i].addr,
         (void *)kb_bps[i].orig);
  kb_bps[i] = kb_bps[--kb_nbps];
  return 1;
}

#if defined(__x86_64__)
/* A genuine return address is preceded by a CALL: E8 rel32 (5 bytes)
 * or an FF /2 indirect form (2-7 bytes).  Rejecting non-CALL-preceded
 * stack words keeps us from planting int3 mid-instruction off stale
 * stack data when the image is left via `ret` (callback returning to
 * its library caller). */
static int kb_looks_like_retaddr(pid_t pid, uintptr_t r) {
  errno = 0;
  unsigned long w =
      (unsigned long)ptrace(PTRACE_PEEKTEXT, pid, (void *)(r - 8), NULL);
  if (errno) return 0;
  unsigned char b[8];
  memcpy(b, &w, 8);
  if (b[3] == 0xE8) return 1; /* call rel32 at r-5 */
  for (int k = 2; k <= 7; k++)
    /* call r/m64 is FF /2: opcode at r-k, ModRM reg field == 2 */
    if (b[8 - k] == 0xFF && ((b[8 - k + 1] >> 3) & 7) == 2) return 1;
  return 0;
}

/* main()'s address, learned on the first exec: at the
 * _start -> __libc_start_main(main, ...) excursion, main rides rdi.
 * Later execs start tracing THERE instead of at the ELF entry —
 * skipping the csu init blocks, and teardown too, because the
 * ret-from-main excursion plants no breakpoints so the child just
 * runs to exit at native speed.  Stable across execs
 * (ADDR_NO_RANDOMIZE). */
static uintptr_t kb_main_addr;

/* main() may only be learned before the first recorded exec (warm-up
 * or one-shot); learning mid-campaign would flip later execs from
 * traced-from-entry to traced-from-main and make identical inputs
 * produce different maps. */
static int kb_allow_learn = 1;

/* The child just branched out of the image (library/loader call).
 * Arrange to regain control when it comes back: break on the call's
 * return address, and — first excursion of a learning (traced-from-
 * entry) exec, which is _start -> __libc_start_main(main, ...) — on
 * any in-image function pointers riding the argument registers,
 * which is how main()/init are delivered to libc. */
static void kb_plant_excursion_bps(pid_t pid, int first) {
  struct user_regs_struct regs;
  if (ptrace(PTRACE_GETREGS, pid, NULL, &regs) != 0) return;
  /* [rsp] is the return address for a call/PLT-jmp excursion; the
   * lazy-resolver shape (push link_map; push reloc; jmp resolver)
   * buries it at [rsp+16] — accept the first stack word that looks
   * like a genuine in-image return address. */
  for (int d = 0; d <= 2; d++) {
    errno = 0;
    unsigned long ret = (unsigned long)ptrace(
        PTRACE_PEEKDATA, pid, (void *)(regs.rsp + 8ul * d), NULL);
    if (!errno && kb_in_image(ret) && kb_looks_like_retaddr(pid, ret)) {
      kb_bp_plant(pid, ret);
      break;
    }
  }
  if (first) {
    unsigned long cand[6] = {regs.rdi, regs.rsi, regs.rdx,
                             regs.rcx, regs.r8,  regs.r9};
    for (int i = 0; i < 6; i++)
      if (kb_in_image(cand[i])) kb_bp_plant(pid, cand[i]);
    if (kb_allow_learn && kb_in_image(regs.rdi))
      kb_main_addr = regs.rdi;
  }
}
#endif /* __x86_64__ */

/* Returns 0 if the child is stopped and ready for stepping (at addr
 * or, on any fallback, wherever it already was), or sets *status_out
 * and returns 1 if the child terminated while getting there. */
static int kb_run_to(pid_t pid, uintptr_t entry, int *status_out) {
  errno = 0;
  if (!entry) return 0;
  long orig = ptrace(PTRACE_PEEKTEXT, pid, (void *)entry, NULL);
  if (orig == -1 && errno) return 0;
  if (ptrace(PTRACE_POKETEXT, pid, (void *)entry,
             (void *)KB_BP_WORD((unsigned long)orig)) != 0)
    return 0;
  if (ptrace(PTRACE_CONT, pid, NULL, NULL) != 0) return 0;
  int status;
  if (waitpid(pid, &status, __WALL) < 0) return 0;
  if (WIFEXITED(status) || WIFSIGNALED(status)) {
    *status_out = status;
    return 1;
  }
  /* restore the original word and re-aim the pc at the entry */
  ptrace(PTRACE_POKETEXT, pid, (void *)entry, (void *)orig);
  if (WSTOPSIG(status) == SIGTRAP && KB_BP_PC_REWIND)
    kb_set_pc(pid, entry);
  return 0;
}

static pid_t kb_spawn(char **argv) {
  pid_t pid = fork();
  if (pid < 0) return -1;
  if (pid == 0) {
    close(KB_FORKSRV_FD);
    close(KB_STATUS_FD);
    personality(ADDR_NO_RANDOMIZE); /* stable PCs -> stable slots */
    /* lazy PLT binding would bounce the first call of every import
     * through the dynamic resolver, whose stack frame hides the
     * caller's return address from the excursion breakpoint logic
     * (the child would escape tracing there); bind everything up
     * front instead.  Template mode pays this once. */
    putenv((char *)"LD_BIND_NOW=1");
    if (ptrace(PTRACE_TRACEME, 0, NULL, NULL) != 0) _exit(124);
    execvp(argv[0], argv);
    _exit(125); /* exec failed */
  }
  /* child stops with SIGTRAP at the execvp boundary */
  int status;
  if (waitpid(pid, &status, __WALL) < 0 || !WIFSTOPPED(status)) {
    if (pid > 0) kill(pid, SIGKILL);
    return -1;
  }
  return pid;
}

/* Watchdog for the startup runs (warm-up, template parking) and the
 * UnTracer full-map re-runs: kills the guarded child if it outlives
 * its budget. */
static volatile pid_t kb_guard_pid;
static volatile sig_atomic_t kb_guard_fired;

static void kb_guard_alarm(int sig) {
  (void)sig;
  kb_guard_fired = 1;
  if (kb_guard_pid > 0) kill(kb_guard_pid, SIGKILL);
}

/* Re-run time budget: the re-run happens inside the exec's status
 * window, so it must finish before the FUZZER's per-exec timeout or
 * the exec is misreported as a hang (and a long enough overrun tears
 * the forkserver down).  The fuzzer passes the FULL per-exec timeout
 * via KB_TRACE_BUDGET (seconds, fractional); default/cap 10s.  The
 * guard is armed with what is LEFT of that window —
 * max(min_budget, timeout - elapsed_fast_exec) — because for targets
 * whose normal runtime approaches the timeout, a fixed fraction of
 * it ignores the time the fast exec already spent: fast-exec +
 * full-trace re-run would overrun the window, the exec would be
 * misreported as a hang, the re-armed leaders would re-fire, and the
 * pattern would repeat on every novelty-bearing exec.  Armed via
 * setitimer, not alarm(), so sub-second fuzzer timeouts are
 * honored. */
#define KB_RERUN_MIN_BUDGET 0.05

static struct timespec kb_exec_t0;

static void kb_exec_mark(void) {
  clock_gettime(CLOCK_MONOTONIC, &kb_exec_t0);
}

static double kb_exec_elapsed(void) {
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  return (double)(now.tv_sec - kb_exec_t0.tv_sec) +
         (double)(now.tv_nsec - kb_exec_t0.tv_nsec) / 1e9;
}

static double kb_rerun_budget(void) {
  const char *e = getenv("KB_TRACE_BUDGET");
  double d = e ? atof(e) : 0;
  if (d <= 0 || d > 10) d = 10;
  d -= kb_exec_elapsed();
  if (d < KB_RERUN_MIN_BUDGET) d = KB_RERUN_MIN_BUDGET;
  return d;
}

static void kb_guard_arm(double secs) {
  struct itimerval it;
  memset(&it, 0, sizeof it);
  it.it_value.tv_sec = (time_t)secs;
  it.it_value.tv_usec = (suseconds_t)((secs - (double)(time_t)secs) * 1e6);
  if (it.it_value.tv_sec == 0 && it.it_value.tv_usec < 1000)
    it.it_value.tv_usec = 1000;
  setitimer(ITIMER_REAL, &it, NULL);
}

static void kb_guard_disarm(void) {
  struct itimerval z;
  memset(&z, 0, sizeof z);
  setitimer(ITIMER_REAL, &z, NULL);
}

/* ---- fork-template (x86_64): the reference's QEMU tier starts its
 * forkserver at the first translated block inside the emulated
 * target (afl-qemu-cpu-inl.h semantics), so steady-state execs pay
 * one fork, not execve + dynamic loading.  Same play here with pure
 * ptrace: keep one "template" child stopped at main, and mint each
 * exec's child by injecting a clone() syscall into it —
 * CLONE_PARENT (the new child is OURS to waitpid) | CLONE_PTRACE
 * (it is born traced by us), exit_signal 0 (no SIGCHLD floods the
 * stopped template).  The clone starts at a planted int3, gets its
 * text and registers restored, and is then traced from main like
 * any other child.  Any failure falls back to plain spawn. ---- */
#if defined(__x86_64__)
#define KB_SYS_CLONE 56
#define KB_CLONE_FLAGS (0x00008000UL /*CLONE_PARENT*/ | \
                        0x00002000UL /*CLONE_PTRACE*/)

static pid_t kb_template;
static struct user_regs_struct kb_tmpl_regs;
static long kb_tmpl_word;

static void kb_template_drop(void) {
  if (kb_template > 0) {
    kill(kb_template, SIGKILL);
    waitpid(kb_template, NULL, __WALL);
  }
  kb_template = 0;
}

static void kb_template_setup(char **argv) {
  int status;
  if (!kb_main_addr) return;
  pid_t pid = kb_spawn(argv);
  if (pid < 0) return;
  kb_guard_pid = pid;
  alarm(5);
  int died = kb_run_to(pid, kb_main_addr, &status);
  alarm(0);
  kb_guard_pid = 0;
  if (died) {
    /* the child ran to completion without ever hitting the learned
     * main() — the first-excursion rdi heuristic picked a
     * never-executed address (non-glibc startup, unusual _start).
     * Tracing every exec from there would silently produce EMPTY
     * maps; fall back to entry tracing instead, loudly. */
    fprintf(stderr,
            "kb_trace: learned main 0x%lx never reached; falling back "
            "to entry tracing\n", (unsigned long)kb_main_addr);
    kb_main_addr = 0;
    return;
  }
  if (kb_read_pc(pid) != kb_main_addr ||
      ptrace(PTRACE_GETREGS, pid, NULL, &kb_tmpl_regs) != 0) {
    kill(pid, SIGKILL);
    waitpid(pid, NULL, __WALL);
    return;
  }
  errno = 0;
  kb_tmpl_word = ptrace(PTRACE_PEEKTEXT, pid, (void *)kb_main_addr, NULL);
  if (kb_tmpl_word == -1 && errno) {
    kill(pid, SIGKILL);
    waitpid(pid, NULL, __WALL);
    return;
  }
  kb_template = pid;
}

/* Mint one child from the template.  Returns its pid stopped at
 * kb_main_addr with clean text, or -1 (caller falls back to spawn). */
static pid_t kb_template_fork(void) {
  if (kb_template <= 0) return -1;
  uintptr_t a = kb_main_addr;
  /* gadget: syscall; int3 — the clone child runs into the int3 */
  unsigned long gadget =
      ((unsigned long)kb_tmpl_word & ~0xFFFFFFUL) | 0xCC050FUL;
  if (ptrace(PTRACE_POKETEXT, kb_template, (void *)a, (void *)gadget) != 0)
    goto dead;
  {
    struct user_regs_struct r = kb_tmpl_regs;
    r.rip = a;
    r.rax = KB_SYS_CLONE;
    r.rdi = KB_CLONE_FLAGS;
    r.rsi = 0; /* child_stack NULL: share the CoW stack like fork */
    r.rdx = 0;
    r.r10 = 0;
    r.r8 = 0;
    if (ptrace(PTRACE_SETREGS, kb_template, NULL, &r) != 0) goto dead;
  }
  {
    int st, tries;
    for (tries = 0; tries < 64; tries++) {
      if (ptrace(PTRACE_SINGLESTEP, kb_template, NULL, NULL) != 0)
        goto dead;
      if (waitpid(kb_template, &st, __WALL) < 0) goto dead;
      if (!WIFSTOPPED(st)) {
        kb_template = 0; /* template died; nothing to clean up */
        return -1;
      }
      if (WSTOPSIG(st) == SIGTRAP) break; /* syscall retired */
      /* stray pending signal: suppress and retry the step */
    }
    if (tries == 64) goto dead;
  }
  {
    struct user_regs_struct r2;
    pid_t child;
    int st2;
    if (ptrace(PTRACE_GETREGS, kb_template, NULL, &r2) != 0) goto dead;
    child = (pid_t)(long)r2.rax;
    /* park the template back at main with original text */
    ptrace(PTRACE_POKETEXT, kb_template, (void *)a, (void *)kb_tmpl_word);
    ptrace(PTRACE_SETREGS, kb_template, NULL, &kb_tmpl_regs);
    if (child <= 0) return -1;
    if (waitpid(child, &st2, __WALL) < 0) return -1;
    if (!WIFSTOPPED(st2)) return -1; /* died before the int3?! */
    if (ptrace(PTRACE_POKETEXT, child, (void *)a,
               (void *)kb_tmpl_word) != 0 ||
        ptrace(PTRACE_SETREGS, child, NULL, &kb_tmpl_regs) != 0) {
      kill(child, SIGKILL);
      waitpid(child, NULL, __WALL);
      return -1;
    }
    return child;
  }
dead:
  kb_template_drop();
  return -1;
}
#endif /* __x86_64__ */

static unsigned kb_dbg_stops, kb_dbg_excursions;
static unsigned kb_dbg_tforks, kb_dbg_spawns;
static unsigned kb_dbg_head_hits, kb_dbg_reruns, kb_dbg_fast_execs;

#if defined(__x86_64__)
/* ---- UnTracer mode: coverage-only breakpoints ----------------------
 *
 * Block-stepping every exec pays one ptrace stop per basic block
 * (~38us each on this class of host) even when the exec discovers
 * nothing — and steady-state fuzzing discovers nothing almost
 * always.  UnTracer-style coverage-guided tracing inverts the cost:
 *
 *   - setup: one `objdump -d` pass over the target finds basic-block
 *     leaders (branch targets, post-terminator fallthroughs,
 *     function entries); an int3 is planted at every NOT-YET-SEEN
 *     leader in the fork TEMPLATE's text, which acts as the
 *     persistent "oracle" — children minted from it inherit the
 *     armed text by CoW;
 *   - steady state: the child runs under plain PTRACE_CONT at native
 *     speed; no armed leader executes, no stop happens, the map
 *     stays empty, and the fuzzer's has_new_bits correctly reports
 *     "nothing new" — total cost is the fork+cont+reap floor;
 *   - novelty: an armed int3 fires -> that block is new GLOBALLY;
 *     record it, restore the original byte in the child (to resume)
 *     AND the template (so no future exec traps there), and when
 *     the exec finishes RE-RUN the same input once under the full
 *     block-step tracer to rebuild a complete, hit-counted map with
 *     the same slot identities every other map in the campaign uses.
 *     Crashing execs re-run too, so crash triage always sees full
 *     maps.  Execs the fuzzer killed (hang timeout, SIGKILL) skip
 *     the re-run — re-tracing a hang would hang the tracer.
 *
 * Trade-off (documented in docs/HOST_TIER.md): novelty is
 * block-granular.  A new EDGE between two already-seen blocks, or a
 * hit-count bucket change, fires no breakpoint and is not reported.
 * This matches UnTracer's published design point; the reference's
 * QEMU tier pays per-TB hooks on every exec instead.
 *
 * The reference analogue is the QEMU tier's cost model
 * (afl_progs/qemu_mode/afl-qemu-cpu-inl.h: per-translated-block
 * hook + fork at first block); this replaces the per-block tax with
 * a pay-only-for-novelty scheme on raw ptrace.
 *
 * Indirect-only block entries (jump tables, virtual calls into
 * blocks objdump can't prove are leaders) are invisible until some
 * direct path reaches them — the same blind spot static-rewriting
 * UnTracer has.  KB_TRACE_FULL=1 opts back into full block-stepping
 * per exec. ---- */

static int kb_stopped_on_int3(pid_t pid); /* defined with the block engine */

typedef struct {
  uintptr_t addr;          /* runtime address (bias applied) */
  unsigned char orig;      /* original first byte */
  unsigned char armed;
} kb_head;
static kb_head *kb_heads;
static int kb_nheads;
static int kb_untracer;    /* engine active for template children */

static int kb_head_cmp(const void *a, const void *b) {
  uintptr_t x = ((const kb_head *)a)->addr;
  uintptr_t y = ((const kb_head *)b)->addr;
  return x < y ? -1 : x > y ? 1 : 0;
}

static int kb_head_find(uintptr_t addr) {
  int lo = 0, hi = kb_nheads - 1;
  while (lo <= hi) {
    int mid = (lo + hi) / 2;
    if (kb_heads[mid].addr == addr) return mid;
    if (kb_heads[mid].addr < addr) lo = mid + 1;
    else hi = mid - 1;
  }
  return -1;
}

/* Parse `objdump -d` output into file-relative basic-block leaders.
 * Leaders: numeric jmp/jcc/call targets, the instruction after any
 * terminator (jmp/jcc/ret/ud2/hlt — jcc fallthroughs are the
 * frontier that matters), and function-symbol entries.  Returns the
 * count, leaders in kb_heads[].addr (unbiased). */
static int kb_load_heads(const char *target) {
  static char real[PATH_MAX], line[4096];
  if (!realpath(target, real)) return 0;
  /* argv exec, not popen: a shell would re-interpret quote characters
   * in the target path */
  int pfd[2];
  if (pipe(pfd)) return 0;
  pid_t dp = fork();
  if (dp < 0) {
    close(pfd[0]);
    close(pfd[1]);
    return 0;
  }
  if (dp == 0) {
    dup2(pfd[1], 1);
    close(pfd[0]);
    close(pfd[1]);
    int devnull = open("/dev/null", O_RDWR);
    if (devnull >= 0) dup2(devnull, 2);
    execlp("objdump", "objdump", "-d", "--no-show-raw-insn", real,
           (char *)NULL);
    _exit(127);
  }
  close(pfd[1]);
  FILE *f = fdopen(pfd[0], "r");
  if (!f) {
    close(pfd[0]);
    waitpid(dp, NULL, 0);
    return 0;
  }
  int cap = 1024;
  kb_heads = malloc(cap * sizeof *kb_heads);
  if (!kb_heads) {
    fclose(f);
    waitpid(dp, NULL, 0);
    return 0;
  }
  int pending = 0; /* previous insn ended a block */
#define KB_HEAD_ADD(a)                                         \
  do {                                                         \
    if (kb_nheads == cap) {                                    \
      int ncap = cap * 2;                                      \
      void *p = realloc(kb_heads, ncap * sizeof *kb_heads);    \
      if (!p) break;                                           \
      kb_heads = p;                                            \
      cap = ncap;                                              \
    }                                                          \
    kb_heads[kb_nheads].addr = (a);                            \
    kb_heads[kb_nheads].armed = 0;                             \
    kb_nheads++;                                               \
  } while (0)
  while (fgets(line, sizeof line, f)) {
    unsigned long addr;
    int off = 0;
    /* over-long line (huge mangled symbol): fgets split it — drop
     * the tail too, or its fragment could sscanf-match as a bogus
     * leader address and arm an int3 mid-instruction */
    if (!strchr(line, '\n')) {
      int c;
      while ((c = fgetc(f)) != EOF && c != '\n') {}
      continue;
    }
    /* function symbol line: "0000000000001030 <name>:" */
    if (line[0] != ' ' && line[0] != '\t') {
      if (sscanf(line, "%lx <%*[^>]>:", &addr) == 1) {
        KB_HEAD_ADD((uintptr_t)addr);
        pending = 0;
      }
      continue;
    }
    /* instruction line: "  1012:\tmnemonic operand..." */
    if (sscanf(line, " %lx: %n", &addr, &off) != 1 || !off) continue;
    if (pending) {
      KB_HEAD_ADD((uintptr_t)addr);
      pending = 0;
    }
    char m[16] = {0};
    const char *p = line + off;
    while (*p == ' ' || *p == '\t') p++;
    /* skip prefixes objdump prints as separate tokens */
    while (!strncmp(p, "bnd ", 4) || !strncmp(p, "notrack ", 8) ||
           !strncmp(p, "lock ", 5) || !strncmp(p, "rep ", 4) ||
           !strncmp(p, "repz ", 5) || !strncmp(p, "repnz ", 6))
      p = strchr(p, ' ') + 1;
    int mi = 0;
    while (*p && *p != ' ' && *p != '\t' && *p != '\n' &&
           mi < (int)sizeof m - 1)
      m[mi++] = *p++;
    while (*p == ' ' || *p == '\t') p++;
    int is_jmp = !strcmp(m, "jmp") || !strcmp(m, "jmpq");
    int is_jcc = m[0] == 'j' && !is_jmp; /* jne/ja/.../jecxz/jrcxz */
    int is_loop = !strncmp(m, "loop", 4);
    int is_call = !strcmp(m, "call") || !strcmp(m, "callq");
    if (is_jmp || is_jcc || is_loop || is_call) {
      /* numeric direct target ("1150 <sym+0x10>"); '*' = indirect */
      if (*p != '*') {
        char *end;
        unsigned long tgt = strtoul(p, &end, 16);
        if (end != p) KB_HEAD_ADD((uintptr_t)tgt);
      }
      if (is_jmp || is_jcc || is_loop) pending = 1;
    } else if (!strcmp(m, "ret") || !strcmp(m, "retq") ||
               !strcmp(m, "ud2") || !strcmp(m, "hlt")) {
      pending = 1;
    }
  }
#undef KB_HEAD_ADD
  fclose(f);
  waitpid(dp, NULL, 0);
  return kb_nheads;
}

/* Patch ONE byte at addr in pid's text, preserving neighbours (two
 * leaders can share a word; word-granular restore would clobber the
 * neighbour's int3). */
static int kb_poke_byte(pid_t pid, uintptr_t addr, unsigned char b,
                        unsigned char *orig_out) {
  errno = 0;
  unsigned long w =
      (unsigned long)ptrace(PTRACE_PEEKTEXT, pid, (void *)addr, NULL);
  if (errno) return -1;
  if (orig_out) *orig_out = (unsigned char)(w & 0xFF);
  unsigned long nw = (w & ~0xFFUL) | b;
  return (int)ptrace(PTRACE_POKETEXT, pid, (void *)addr, (void *)nw);
}

/* Bias file-relative leaders to runtime addresses, drop the ones the
 * engine must not trap (outside the image; main, whose byte the
 * fork-template gadget rewrites), sort, dedupe, and arm every leader
 * in the parked template.  Called once, after template setup. */
static void kb_untracer_arm(const char *target) {
  static char real[PATH_MAX];
  if (kb_template <= 0 || !kb_nheads) return;
  uintptr_t bias = 0;
  if (realpath(target, real)) {
    FILE *e = fopen(real, "rb");
    if (e) {
      Elf64_Ehdr eh;
      if (fread(&eh, 1, sizeof eh, e) == sizeof eh &&
          eh.e_type == ET_DYN)
        bias = kb_image_base(kb_template, real);
      fclose(e);
    }
  }
  int n = 0;
  for (int i = 0; i < kb_nheads; i++) {
    uintptr_t a = kb_heads[i].addr + bias;
    /* exclude the word at main: the fork-template clone gadget
     * rewrites and restores that whole 8-byte word from its
     * pre-arming snapshot, which would silently strip any int3
     * armed inside it */
    if (!kb_in_image(a) ||
        (a >= kb_main_addr && a < kb_main_addr + 8))
      continue;
    kb_heads[n].addr = a;
    kb_heads[n].armed = 0;
    n++;
  }
  kb_nheads = n;
  qsort(kb_heads, kb_nheads, sizeof *kb_heads, kb_head_cmp);
  n = 0;
  for (int i = 0; i < kb_nheads; i++)
    if (!n || kb_heads[i].addr != kb_heads[n - 1].addr)
      kb_heads[n++] = kb_heads[i];
  kb_nheads = n;
  int armed = 0;
  for (int i = 0; i < kb_nheads; i++) {
    if (kb_poke_byte(kb_template, kb_heads[i].addr, 0xCC,
                     &kb_heads[i].orig) == 0) {
      kb_heads[i].armed = 1;
      armed++;
    }
  }
  kb_untracer = armed > 0;
  if (getenv("KB_TRACE_DEBUG"))
    fprintf(stderr, "kb_trace: untracer armed %d/%d leaders\n",
            armed, kb_nheads);
}

static void kb_head_disarm(pid_t pid, int i) {
  if (pid > 0) kb_poke_byte(pid, kb_heads[i].addr, kb_heads[i].orig, NULL);
}

/* Leaders that fired during the current fast exec.  If the full-map
 * re-run cannot happen (fuzzer hang-killed the child, or the re-run
 * spawn failed), these are RE-ARMED in the template: the novelty is
 * deferred to a later exec that reaches the block instead of being
 * lost forever (the map itself stays empty — fast execs never write
 * provisional slots, whose breakpoint-sequence edge identities would
 * not be comparable with block-step maps). */
#define KB_MAX_FIRED 512
static int kb_fired[KB_MAX_FIRED];
static int kb_nfired;
static int kb_fired_overflow;

static void kb_rearm_one(int i) {
  if (!kb_heads[i].armed &&
      kb_poke_byte(kb_template, kb_heads[i].addr, 0xCC, NULL) == 0)
    kb_heads[i].armed = 1;
}

static void kb_rearm_fired(void) {
  if (kb_fired_overflow) {
    /* more leaders fired this exec than the table holds — re-arm
     * every disarmed leader.  Long-disarmed ones re-fire once and
     * re-report blocks the virgin maps already hold (novelty no-op,
     * one extra re-run); losing the overflow leaders forever would
     * not be a no-op. */
    for (int i = 0; i < kb_nheads; i++) kb_rearm_one(i);
  } else {
    for (int k = 0; k < kb_nfired; k++) kb_rearm_one(kb_fired[k]);
  }
  kb_nfired = 0;
  kb_fired_overflow = 0;
}

/* Native-speed exec over a template child with armed leaders.
 * Returns the wait status; *newcov = 1 iff any leader fired. */
static int kb_untracer_loop(pid_t pid, int *newcov) {
  int status = 0, deliver = 0, stall = 0, last_sig = 0;
  uintptr_t last_pc = 0;
  *newcov = 0;
  kb_nfired = 0;
  kb_fired_overflow = 0; /* stale overflow from an exec whose re-run
                          * SUCCEEDED must not make a later failed
                          * re-run re-arm every disarmed leader */
  for (;;) {
    if (ptrace(PTRACE_CONT, pid, NULL, (void *)(uintptr_t)deliver) != 0) {
      waitpid(pid, &status, __WALL); /* vanished (hang-timeout kill) */
      return status;
    }
    deliver = 0;
    if (waitpid(pid, &status, __WALL) < 0) return status;
    if (!WIFSTOPPED(status)) return status;
    int sig = WSTOPSIG(status);
    if (sig == SIGTRAP) {
      uintptr_t pc = kb_read_pc(pid);
      int i = kb_head_find(pc - KB_BP_PC_REWIND);
      if (i >= 0 && kb_heads[i].armed && kb_stopped_on_int3(pid)) {
        uintptr_t a = kb_heads[i].addr;
        kb_head_disarm(pid, i);          /* resume this child */
        kb_head_disarm(kb_template, i);  /* future children skip it */
        kb_heads[i].armed = 0;
        if (kb_nfired < KB_MAX_FIRED) kb_fired[kb_nfired++] = i;
        else kb_fired_overflow = 1;
        kb_set_pc(pid, a);
        *newcov = 1;
        kb_dbg_head_hits++;
        if (kb_log) fprintf(kb_log, "bp %lx\n", (unsigned long)a);
      } else {
        deliver = SIGTRAP; /* the target's own int3/trap */
      }
    } else {
      uintptr_t pc = kb_read_pc(pid);
      if (sig == last_sig && pc == last_pc) {
        if (++stall > KB_MAX_STALL) break;
      } else {
        stall = 0;
        last_sig = sig;
        last_pc = pc;
      }
      deliver = sig == SIGSTOP ? 0 : sig;
    }
  }
  kill(pid, SIGKILL);
  waitpid(pid, &status, __WALL);
  return status;
}
#endif /* __x86_64__ */

/* Fallback engine: single-step `pid` to completion over everything,
 * per-instruction edges (non-x86 hosts, SINGLEBLOCK-less kernels,
 * KB_TRACE_STEP=1).  Returns the final wait status. */
static int kb_step_loop(pid_t pid, const char *target) {
  int status = 0;
  int deliver = 0, stall = 0, last_sig = 0;
  uintptr_t last_pc = 0;
  kb_prev = 0;
  kb_hash_reset();
  if (kb_run_to(pid, kb_entry_addr(pid, target), &status)) return status;
  for (unsigned n = 0; n < KB_MAX_STEPS; n++) {
    if (ptrace(PTRACE_SINGLESTEP, pid, NULL,
               (void *)(uintptr_t)deliver) != 0) {
      /* child vanished (e.g. fuzzer SIGKILLed it on hang timeout) */
      waitpid(pid, &status, __WALL);
      return status;
    }
    if (waitpid(pid, &status, __WALL) < 0) return status;
    if (WIFEXITED(status) || WIFSIGNALED(status)) return status;
    if (!WIFSTOPPED(status)) return status;
    int sig = WSTOPSIG(status);
    if (sig == SIGTRAP) {
      deliver = 0;
      stall = 0;
      kb_dbg_stops++;
      kb_record(kb_read_pc(pid));
    } else {
      /* deliver the real signal; default dispositions (SIGSEGV...)
       * then terminate the child and we report that status.
       * SIGSTOP has no terminating disposition — re-delivering it
       * just re-stops the child every step; suppress it, and bound
       * any identical repeating stop (handler that re-raises). */
      uintptr_t pc = kb_read_pc(pid);
      if (sig == last_sig && pc == last_pc) {
        if (++stall > KB_MAX_STALL) break;
      } else {
        stall = 0;
        last_sig = sig;
        last_pc = pc;
      }
      deliver = sig == SIGSTOP ? 0 : sig;
    }
  }
  kill(pid, SIGKILL); /* runaway: no fuzzer attached to time it out */
  waitpid(pid, &status, __WALL);
  return status;
}

#if defined(__x86_64__)
/* An int3 stop (planted breakpoint) reports si_code SI_KERNEL or
 * TRAP_BRKPT; a branch/single-step stop reports TRAP_TRACE.  A
 * branch-step stop can legitimately land one byte past an armed
 * breakpoint that was never executed — without this check it would
 * be mis-rewound onto the byte before it. */
static int kb_stopped_on_int3(pid_t pid) {
  siginfo_t si;
  if (ptrace(PTRACE_GETSIGINFO, pid, NULL, &si) != 0) return 1;
  return si.si_code != TRAP_TRACE;
}

/* Block engine: branch-granular stepping inside the main image,
 * native-speed PTRACE_CONT over everything else.  Returns the final
 * wait status, or -2 meaning "SINGLEBLOCK unsupported, child still
 * stopped at entry untouched — use the step loop". */
static int kb_block_loop(pid_t pid, const char *target) {
  int status = 0;
  int deliver = 0, stall = 0, last_sig = 0, excursions = 0;
  uintptr_t last_pc = 0;
  kb_prev = 0;
  kb_hash_reset();
  kb_nbps = 0;
  if (!kb_load_xranges(pid, target)) return -2;
  int from_entry = kb_main_addr == 0;
  uintptr_t start =
      from_entry ? kb_entry_addr(pid, target) : kb_main_addr;
  /* template-forked children are already parked at start */
  uintptr_t pc = kb_read_pc(pid);
  if (pc != start) {
    if (kb_run_to(pid, start, &status)) return status;
    pc = kb_read_pc(pid); /* == start, or wherever run_to fell back */
  }
  for (unsigned n = 0; n < KB_MAX_STEPS; n++) {
    int stepping = kb_in_image(pc);
    kb_dbg_stops++;
    if (!stepping) {
      kb_dbg_excursions++;
      kb_plant_excursion_bps(pid, from_entry && excursions++ == 0);
    }
    long req = stepping ? PTRACE_SINGLEBLOCK : PTRACE_CONT;
    if (ptrace(req, pid, NULL, (void *)(uintptr_t)deliver) != 0) {
      if (n == 0 && req == PTRACE_SINGLEBLOCK &&
          (errno == EIO || errno == EINVAL || errno == ENOSYS))
        return -2; /* kernel lacks branch-step: fall back untouched */
      waitpid(pid, &status, __WALL); /* vanished (hang-timeout kill) */
      return status;
    }
    deliver = 0;
    if (waitpid(pid, &status, __WALL) < 0) return status;
    if (!WIFSTOPPED(status)) return status;
    int sig = WSTOPSIG(status);
    if (sig == SIGTRAP) {
      stall = 0;
      uintptr_t pc2 = kb_read_pc(pid);
      if (kb_bp_find(pc2 - KB_BP_PC_REWIND) >= 0 &&
          kb_stopped_on_int3(pid)) {
        /* re-entry breakpoint: rewind over the int3 and resume
         * block-stepping from the block it guards */
        pc = pc2 - KB_BP_PC_REWIND;
        kb_bp_clear(pid, pc);
        kb_set_pc(pid, pc);
        kb_record(pc);
      } else if (req == PTRACE_SINGLEBLOCK) {
        if (kb_in_image(pc2)) {
          /* branch-step stop at a block head; if a pending re-entry
           * bp sits exactly here, disarm it before it executes */
          kb_bp_clear(pid, pc2);
          kb_record(pc2);
        }
        /* else: left the image; next iteration plants + CONTs */
        pc = pc2;
      } else {
        deliver = SIGTRAP; /* target's own int3/trap under CONT */
        pc = pc2;
      }
    } else {
      pc = kb_read_pc(pid);
      if (sig == last_sig && pc == last_pc) {
        if (++stall > KB_MAX_STALL) break;
      } else {
        stall = 0;
        last_sig = sig;
        last_pc = pc;
      }
      deliver = sig == SIGSTOP ? 0 : sig;
    }
  }
  kill(pid, SIGKILL); /* runaway: no fuzzer attached to time it out */
  waitpid(pid, &status, __WALL);
  return status;
}
#endif /* __x86_64__ */


/* Diagnostic engine (KB_TRACE_OFF=1): no coverage at all, just run
 * the child to completion delivering signals — isolates the ptrace
 * fork/exec floor when profiling the tracer itself. */
static int kb_null_loop(pid_t pid) {
  int status = 0;
  int deliver = 0;
  for (;;) {
    if (ptrace(PTRACE_CONT, pid, NULL, (void *)(uintptr_t)deliver) != 0) {
      waitpid(pid, &status, __WALL);
      return status;
    }
    if (waitpid(pid, &status, __WALL) < 0) return status;
    if (!WIFSTOPPED(status)) return status;
    deliver = WSTOPSIG(status) == SIGSTOP ? 0 : WSTOPSIG(status);
  }
}

static int kb_opt_off, kb_opt_step; /* KB_TRACE_OFF / KB_TRACE_STEP */

static int kb_env_flag(const char *name) {
  const char *e = getenv(name);
  return e && e[0] && e[0] != '0';
}

/* Trace `pid` to completion with the best available engine. */
static int kb_trace_child(pid_t pid, const char *target) {
  if (kb_opt_off) return kb_null_loop(pid);
#if defined(__x86_64__)
  if (!kb_opt_step) {
    int st = kb_block_loop(pid, target);
    if (st != -2) return st;
    kb_opt_step = 1; /* unsupported here; don't retry every exec */
  }
#endif
  return kb_step_loop(pid, target);
}

/* ---- startup warm-up (forkserver mode): one throwaway exec, its
 * coverage diverted to a scratch map, that learns the image ranges
 * and main()'s address BEFORE any real exec.  Every recorded exec
 * then traces from main via the template, so identical inputs
 * produce identical maps — without this, exec 1 (traced from the
 * ELF entry) and exec 2+ (traced from main) would differ and the
 * second exec of a seed would look novel.  Stdin is the fuzzer's
 * not-yet-staged (empty) input file: reads hit EOF, and the fuzzer
 * re-stages + rewinds the shared description before every real
 * exec, so nothing is consumed.  An alarm bounds targets that hang
 * before exiting (the learned ranges survive the kill). */
static void kb_warmup(char **argv) {
  static unsigned char scratch[KB_SHM_TOTAL];
  unsigned char *saved = kb_map;
  pid_t pid = kb_spawn(argv);
  if (pid < 0) return;
  kb_map = scratch;
  kb_guard_pid = pid;
  alarm(5);
  kb_trace_child(pid, argv[0]);
  alarm(0);
  kb_guard_pid = 0;
  kb_map = saved;
  kb_allow_learn = 0; /* from here on the trace start is frozen */
  /* the warm-up child shares our stdin description; if the fuzzer
   * had already staged the first input (forkserver starts lazily on
   * the first exec), the warm-up consumed it — rewind.  ESPIPE on
   * non-seekable stdin is harmless. */
  lseek(0, 0, SEEK_SET);
  if (kb_log) {
    fprintf(kb_log, "--- warmup\n");
    fflush(kb_log);
  }
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s target [args...]\n", argv[0]);
    return 2;
  }
  kb_attach_shm();
  {
    const char *lp = getenv("KB_TRACE_LOG");
    if (lp) kb_log = fopen(lp, "a");
  }
  kb_opt_off = kb_env_flag("KB_TRACE_OFF");
  kb_opt_step = kb_env_flag("KB_TRACE_STEP");
  kb_opt_hash = kb_env_flag("KB_TRACE_HASH");

  uint32_t hello = KB_HELLO;
  if (write(KB_STATUS_FD, &hello, 4) != 4) {
    /* no fuzzer attached: trace one run, report coverage, propagate */
    pid_t pid = kb_spawn(argv + 1);
    if (pid < 0) return 2;
    int status = kb_trace_child(pid, argv[1]);
    kb_hash_writeout();
    unsigned touched = 0;
    for (unsigned i = 0; i < KB_MAP_SIZE; i++) touched += kb_map[i] != 0;
    fprintf(stderr, "kb_trace: %u bitmap slots touched\n", touched);
    if (getenv("KB_TRACE_DEBUG"))
      fprintf(stderr, "kb_trace: %u stops, %u excursions\n",
              kb_dbg_stops, kb_dbg_excursions);
    if (WIFSIGNALED(status)) {
      raise(WTERMSIG(status));
      return 128 + WTERMSIG(status);
    }
    return WIFEXITED(status) ? WEXITSTATUS(status) : 2;
  }

  signal(SIGALRM, kb_guard_alarm);
  if (!kb_opt_off && !kb_opt_step) {
    kb_warmup(argv + 1);
#if defined(__x86_64__)
    if (!getenv("KB_TRACE_NOFORK")) kb_template_setup(argv + 1);
    if (kb_template > 0 && !kb_env_flag("KB_TRACE_FULL") &&
        !kb_opt_hash && kb_load_heads(argv[1]))
      kb_untracer_arm(argv[1]);
    if (kb_untracer)
      /* the default engine changes the coverage SEMANTICS, not just
       * the speed — say so once, loudly, so campaigns know which
       * fidelity they ran under without KB_TRACE_DEBUG archaeology */
      fprintf(stderr,
              "kb_trace: UnTracer engine active — coverage is "
              "block-granular (a new edge between already-seen "
              "blocks or a hit-count change is not reported); set "
              "KB_TRACE_FULL=1 to restore edge-fidelity "
              "block-stepping\n");
#endif
  }

  pid_t child = -1;
  int child_tmpl = 0; /* current child minted from the armed template */
  for (;;) {
    unsigned char cmd;
    if (read(KB_FORKSRV_FD, &cmd, 1) != 1) _exit(0);
    switch (cmd) {
      case KB_CMD_EXIT:
#if defined(__x86_64__)
        kb_template_drop();
#endif
        if (child > 0) kill(child, SIGKILL);
        if (getenv("KB_TRACE_DEBUG"))
          fprintf(stderr,
                  "kb_trace: %u stops, %u excursions, %u tforks, "
                  "%u spawns, %u bp-drops, %u fast execs, "
                  "%u head hits, %u reruns\n",
                  kb_dbg_stops, kb_dbg_excursions, kb_dbg_tforks,
                  kb_dbg_spawns, kb_dbg_bp_dropped, kb_dbg_fast_execs,
                  kb_dbg_head_hits, kb_dbg_reruns);
        _exit(0);

      case KB_CMD_FORK:
      case KB_CMD_FORK_RUN: {
        /* the fuzzer's per-exec status window opens here: the
         * UnTracer re-run budget is measured from this mark */
        kb_exec_mark();
        child = -1;
        child_tmpl = 0;
#if defined(__x86_64__)
        child = kb_template_fork();
        if (child > 0) {
          kb_dbg_tforks++;
          child_tmpl = 1;
        }
#endif
        if (child < 0) {
          child = kb_spawn(argv + 1);
          kb_dbg_spawns++;
        }
        int32_t pid32 = (int32_t)child;
        if (write(KB_STATUS_FD, &pid32, 4) != 4) _exit(1);
        if (child < 0) _exit(1);
        break;
      }

      case KB_CMD_RUN:
        /* stepping happens under GET_STATUS (the fuzzer's wait
         * point); the child stays stopped until then */
        break;

      case KB_CMD_GET_STATUS: {
        static int kb_first_recorded = 1;
        int32_t st32 = -1;
        if (child > 0) {
#if defined(__x86_64__)
          if (kb_untracer && child_tmpl) {
            int newcov = 0;
            st32 = (int32_t)kb_untracer_loop(child, &newcov);
            child = -1;
            kb_dbg_fast_execs++;
            /* fuzzer-killed children (hang timeout) must not be
             * re-traced — the re-run would hang the tracer while
             * the fuzzer is already moving on */
            int killed = WIFSIGNALED(st32) && WTERMSIG(st32) == SIGKILL;
            int crashed = WIFSIGNALED(st32) && !killed;
            int retraced = 0;
            if ((newcov || crashed) && !killed) {
              /* rebuild a complete hit-counted map for this input
               * with the block-step engine (same slot identities as
               * every other full map); the fast run's status is the
               * verdict either way */
              lseek(0, 0, SEEK_SET); /* fast child consumed stdin */
              pid_t r = kb_spawn(argv + 1);
              if (r > 0) {
                memset(kb_map, 0, KB_SHM_TOTAL);
                kb_dbg_reruns++;
                kb_guard_pid = r;
                kb_guard_fired = 0;
                kb_guard_arm(kb_rerun_budget());
                kb_trace_child(r, argv[1]);
                kb_guard_disarm();
                kb_guard_pid = 0;
                /* guard-killed re-run: the map holds a valid PREFIX
                 * of the full trace (real block-step slots, just
                 * incomplete) — keep it, but treat the re-run as
                 * failed so the fired leaders re-arm and the rest of
                 * the discovery re-fires on a later exec */
                retraced = !kb_guard_fired;
              }
            }
            if (newcov && !retraced) {
              /* the novelty could not be turned into a full map
               * (hang-killed child, or the re-run spawn failed) —
               * re-arm the fired leaders so a later exec that
               * reaches those blocks re-reports them instead of
               * the discovery being lost forever */
              kb_rearm_fired();
            }
            if (kb_log) {
              fprintf(kb_log, "---\n");
              fflush(kb_log);
            }
            if (write(KB_STATUS_FD, &st32, 4) != 4) _exit(1);
            break;
          }
#endif
          st32 = (int32_t)kb_trace_child(child, argv[1]);
          child = -1;
          /* a fuzzer-killed (hang-timeout) exec stopped at an
           * arbitrary block: its partial hash pair is timing-noise
           * that would make every hang look like a new unique path.
           * Publish the deterministic empty-trace pair instead so
           * hangs dedupe. */
          if (kb_opt_hash && WIFSIGNALED(st32) &&
              WTERMSIG(st32) == SIGKILL)
            kb_hash_reset();
          kb_hash_writeout();
          if (kb_first_recorded) {
            kb_first_recorded = 0;
            int validated = 0;
#if defined(__x86_64__)
            validated = kb_template > 0; /* setup reached main alive */
#endif
            if (kb_main_addr && !kb_opt_off && !validated) {
              /* template setup validates main; without a template
               * (KB_TRACE_NOFORK, or setup failure) nothing did:
               * verify the first traced-from-main exec actually
               * produced coverage, else reset to entry tracing. */
              unsigned tch = 0;
              for (unsigned i = 0; i < KB_MAP_SIZE && !tch; i++)
                tch = kb_map[i] != 0;
              if (!tch) {
                fprintf(stderr,
                        "kb_trace: empty map tracing from main 0x%lx; "
                        "falling back to entry tracing\n",
                        (unsigned long)kb_main_addr);
                kb_main_addr = 0;
              }
            }
          }
          if (kb_log) {
            fprintf(kb_log, "---\n");
            fflush(kb_log);
          }
        }
        if (write(KB_STATUS_FD, &st32, 4) != 4) _exit(1);
        break;
      }

      default:
        _exit(2);
    }
  }
}
