/* Micro-benchmark: drive a kb_protocol forkserver (kb-trace or any
 * target runtime) in a tight loop and report execs/s.  Used by
 * docs/HOST_TIER.md's qemu-tier numbers.
 * Usage: bench-trace N -- forkserver-argv...
 * (children's stdin = $BT_STDIN if set, else /dev/null)
 */
#define _GNU_SOURCE
#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/shm.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <unistd.h>
#include "kb_protocol.h"

static double now(void) {
  struct timeval tv;
  gettimeofday(&tv, NULL);
  return tv.tv_sec + tv.tv_usec * 1e-6;
}

int main(int argc, char **argv) {
  if (argc < 4 || strcmp(argv[2], "--")) {
    fprintf(stderr, "usage: %s N -- forkserver argv...\n", argv[0]);
    return 2;
  }
  int n = atoi(argv[1]);
  int shm = shmget(IPC_PRIVATE, KB_SHM_TOTAL, IPC_CREAT | 0600);
  char env[32];
  snprintf(env, sizeof env, "%d", shm);
  setenv(KB_SHM_ENV, env, 1);
  int cmd_pipe[2], st_pipe[2];
  if (pipe(cmd_pipe) || pipe(st_pipe)) return 2;
  /* open stdin in the parent so the loop below can rewind the shared
   * description per exec, the way the fuzzer's staging does */
  const char *in = getenv("BT_STDIN");
  int infd = in ? open(in, O_RDONLY) : -1;
  pid_t fs = fork();
  if (fs == 0) {
    dup2(cmd_pipe[0], KB_FORKSRV_FD);
    dup2(st_pipe[1], KB_STATUS_FD);
    int devnull = open("/dev/null", O_RDWR);
    dup2(infd >= 0 ? infd : devnull, 0);
    dup2(devnull, 1);
    execv(argv[3], argv + 3);
    _exit(125);
  }
  close(cmd_pipe[0]);
  close(st_pipe[1]);
  uint32_t hello;
  if (read(st_pipe[0], &hello, 4) != 4 || hello != KB_HELLO) {
    fprintf(stderr, "no hello\n");
    return 2;
  }
  unsigned char fork_cmd = KB_CMD_FORK_RUN, status_cmd = KB_CMD_GET_STATUS;
  int32_t pid32, st32;
  double t0 = now();
  for (int i = 0; i < n; i++) {
    if (infd >= 0) lseek(infd, 0, SEEK_SET);
    if (write(cmd_pipe[1], &fork_cmd, 1) != 1) return 3;
    if (read(st_pipe[0], &pid32, 4) != 4) return 3;
    if (write(cmd_pipe[1], &status_cmd, 1) != 1) return 3;
    if (read(st_pipe[0], &st32, 4) != 4) return 3;
  }
  double dt = now() - t0;
  printf("%d execs in %.3fs = %.0f execs/s (%.2f ms/exec)\n", n, dt,
         n / dt, dt / n * 1e3);
  unsigned char exit_cmd = KB_CMD_EXIT;
  write(cmd_pipe[1], &exit_cmd, 1);
  waitpid(fs, NULL, 0);
  unsigned char *map = shmat(shm, NULL, 0);
  unsigned touched = 0;
  for (unsigned i = 0; i < KB_MAP_SIZE; i++) touched += map[i] != 0;
  printf("%u slots touched\n", touched);
  shmctl(shm, IPC_RMID, NULL);
  return 0;
}
