/* kb_preload — LD_PRELOAD forkserver for targets that were NOT built
 * with kb-cc (no compiled-in runtime).  Interposes glibc's
 * __libc_start_main so the forkserver starts exactly at the main()
 * entry point, after dynamic linking is finished — the same hook point
 * the reference's hooking library uses (SURVEY.md §2.3, reference
 * instrumentation/forkserver_hooking.c behavior; fresh implementation).
 *
 * No coverage: this library only removes execve cost.  Pair it with
 * return_code instrumentation, or with targets whose coverage comes
 * from elsewhere.
 *
 * Env knobs:
 *   KB_NO_FORKSERVER=1  — disable entirely (run normally)
 *   KB_DEFER_FORKSRV=1  — not supported here (no target cooperation);
 *                         use the compiled-in runtime for deferral.
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <signal.h>
#include <stdint.h>
#include <stdlib.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include "kb_protocol.h"

typedef int (*kb_main_fn)(int, char **, char **);
static kb_main_fn kb_real_main;

static void kb_forkserver(void) {
  uint32_t hello = KB_HELLO;
  if (write(KB_STATUS_FD, &hello, 4) != 4) return; /* no fuzzer */

  pid_t child_pid = -1;
  for (;;) {
    unsigned char cmd;
    if (read(KB_FORKSRV_FD, &cmd, 1) != 1) _exit(0);
    switch (cmd) {
      case KB_CMD_EXIT:
        if (child_pid > 0) kill(child_pid, SIGKILL);
        _exit(0);
      case KB_CMD_FORK:
      case KB_CMD_FORK_RUN: {
        child_pid = fork();
        if (child_pid < 0) _exit(1);
        if (child_pid == 0) {
          close(KB_FORKSRV_FD);
          close(KB_STATUS_FD);
          if (cmd == KB_CMD_FORK) raise(SIGSTOP);
          return; /* fall through into the real main() */
        }
        int32_t pid32 = (int32_t)child_pid;
        if (write(KB_STATUS_FD, &pid32, 4) != 4) _exit(1);
        break;
      }
      case KB_CMD_RUN:
        if (child_pid > 0) kill(child_pid, SIGCONT);
        break;
      case KB_CMD_GET_STATUS: {
        int status = -1;
        if (child_pid > 0) {
          if (waitpid(child_pid, &status, WUNTRACED) < 0) status = -1;
          if (!WIFSTOPPED(status)) child_pid = -1;
        }
        int32_t st32 = (int32_t)status;
        if (write(KB_STATUS_FD, &st32, 4) != 4) _exit(1);
        break;
      }
      default:
        _exit(2);
    }
  }
}

static int kb_wrapped_main(int argc, char **argv, char **envp) {
  if (!getenv("KB_NO_FORKSERVER")) kb_forkserver();
  return kb_real_main(argc, argv, envp);
}

int __libc_start_main(kb_main_fn main_fn, int argc, char **argv,
                      void (*init)(void), void (*fini)(void),
                      void (*rtld_fini)(void), void *stack_end) {
  typedef int (*start_fn)(kb_main_fn, int, char **, void (*)(void),
                          void (*)(void), void (*)(void), void *);
  start_fn real_start =
      (start_fn)dlsym(RTLD_NEXT, "__libc_start_main");
  kb_real_main = main_fn;
  return real_start(kb_wrapped_main, argc, argv, init, fini, rtld_fini,
                    stack_end);
}
