/* kb_preload — LD_PRELOAD forkserver for targets that were NOT built
 * with kb-cc (no compiled-in runtime).  Interposes glibc's
 * __libc_start_main so the forkserver starts exactly at the main()
 * entry point, after dynamic linking is finished — the same hook point
 * the reference's hooking library uses (SURVEY.md §2.3, reference
 * instrumentation/forkserver_hooking.c behavior; fresh implementation).
 *
 * No coverage: this library only removes execve cost.  Pair it with
 * return_code instrumentation, or with targets whose coverage comes
 * from elsewhere.
 *
 * Env knobs:
 *   KB_NO_FORKSERVER=1  — disable entirely (run normally)
 *   KB_DEFER_FORKSRV=1  — not supported here (no target cooperation);
 *                         use the compiled-in runtime for deferral.
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <stdlib.h>
#include <unistd.h>

#define KB_FORKSERVER_IMPL /* pull in the shared command loop */
#include "kb_protocol.h"

typedef int (*kb_main_fn)(int, char **, char **);
static kb_main_fn kb_real_main;

static int kb_wrapped_main(int argc, char **argv, char **envp) {
  if (!getenv("KB_NO_FORKSERVER")) kb_serve_forkserver(NULL);
  return kb_real_main(argc, argv, envp);
}

int __libc_start_main(kb_main_fn main_fn, int argc, char **argv,
                      void (*init)(void), void (*fini)(void),
                      void (*rtld_fini)(void), void *stack_end) {
  typedef int (*start_fn)(kb_main_fn, int, char **, void (*)(void),
                          void (*)(void), void (*)(void), void *);
  start_fn real_start =
      (start_fn)dlsym(RTLD_NEXT, "__libc_start_main");
  kb_real_main = main_fn;
  return real_start(kb_wrapped_main, argc, argv, init, fini, rtld_fini,
                    stack_end);
}
