/* kb_rt — target-side instrumentation runtime (compiled into targets
 * by the kb-cc wrapper together with -fsanitize-coverage=trace-pc).
 *
 * Three jobs, mirroring the behavior of the reference's compiled-in
 * runtime (SURVEY.md §2.5, reference afl_progs/llvm_mode/afl-llvm-rt.o.c
 * semantics — implementation here is fresh, built on GCC sancov):
 *
 *   1. Edge coverage: __sanitizer_cov_trace_pc() is invoked by the
 *      compiler at every edge; we hash the call site PC into a 64KB
 *      bitmap slot and do trace_bits[cur ^ prev]++, prev = cur >> 1 —
 *      the classic AFL edge transition encoding.
 *   2. Forkserver: before main (ELF constructor), speak the protocol in
 *      kb_protocol.h over fds 198/199 so the fuzzer pays fork+COW per
 *      exec instead of fork+execve.  Deferred mode (KB_DEFER_FORKSRV=1)
 *      skips the constructor; the target calls __kb_manual_init() at a
 *      point of its choosing.
 *   3. Persistence: __kb_persistent_loop(n) lets one process run n
 *      inputs, signalling iteration boundaries with SIGSTOP and being
 *      resumed with SIGCONT (reference forkserver.c persistence
 *      contract per SURVEY.md §2.3).
 */
#include <signal.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/shm.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define KB_FORKSERVER_IMPL /* pull in the shared command loop */
#include "kb_protocol.h"

static unsigned char kb_dummy_map[KB_MAP_SIZE];
unsigned char *__kb_trace_bits = kb_dummy_map;

static __thread uintptr_t kb_prev_loc;
static int kb_persist_active = -1; /* -1 = not yet checked */

/* ------------------------------------------------------------------ */
/* Coverage                                                            */
/* ------------------------------------------------------------------ */

/* Mix the return address into a bitmap slot.  The shift folds out the
 * low alignment bits; the xor-shift spreads nearby PCs across the map
 * (same role as afl-as's per-block random ids, but derived from the PC
 * because sancov gives us no compile-time id). */
/* kb_rt.o is compiled WITHOUT -fsanitize-coverage, so this hook is
 * never itself instrumented (no recursion risk). */

/* ASLR normalization: PIE executables load at a random base, so raw
 * PCs — and therefore bitmap slots — would differ between fuzzer
 * instances, breaking cross-process state merge (the merger tool's
 * whole point).  kb_rt.o is linked into the target executable, so the
 * distance from any of its own symbols to an instrumented PC is a
 * link-time constant; subtracting it makes slots load-address
 * invariant (same role as the reference IPT path's /proc/pid/maps
 * normalization, linux_ipt_instrumentation.c:163-189). */
static void kb_anchor(void) {}

void __sanitizer_cov_trace_pc(void) {
  uintptr_t pc = (uintptr_t)__builtin_return_address(0) -
                 (uintptr_t)&kb_anchor;
  uintptr_t h = pc;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 29;
  uintptr_t cur = h & (KB_MAP_SIZE - 1);
  __kb_trace_bits[cur ^ kb_prev_loc]++;
  kb_prev_loc = cur >> 1;
}

static void kb_map_shm(void) {
  static int mapped;
  const char *id_str = getenv(KB_SHM_ENV);
  if (mapped || !id_str) return;
  mapped = 1;
  void *addr = shmat(atoi(id_str), NULL, 0);
  if (addr != (void *)-1) __kb_trace_bits = (unsigned char *)addr;
}

/* ------------------------------------------------------------------ */
/* Forkserver                                                          */
/* ------------------------------------------------------------------ */

static void kb_child_reset(void) { kb_prev_loc = 0; }

static void kb_forkserver(void) { kb_serve_forkserver(kb_child_reset); }

void __kb_manual_init(void) {
  static int done;
  if (done) return;
  done = 1;
  kb_map_shm();
  kb_forkserver();
}

__attribute__((constructor))
static void kb_auto_init(void) {
  if (getenv(KB_DEFER_ENV)) {
    kb_map_shm(); /* coverage from process start even when deferred */
    return;
  }
  __kb_manual_init();
}

/* ------------------------------------------------------------------ */
/* Persistence                                                         */
/* ------------------------------------------------------------------ */

/* while (__kb_persistent_loop(1000)) { one_input(); }
 *
 * Without PERSISTENCE_MAX_CNT in the environment the body runs exactly
 * once (plain fork-per-exec).  With it, each completed iteration
 * SIGSTOPs so the fuzzer can harvest the bitmap and stage the next
 * input before SIGCONTing us. */
int __kb_persistent_loop(unsigned max_cnt) {
  static unsigned iter, env_cap;
  if (kb_persist_active < 0) {
    const char *env = getenv(KB_PERSIST_ENV);
    kb_persist_active = env != NULL;
    if (env && atoi(env) > 0) env_cap = (unsigned)atoi(env);
  }
  if (!kb_persist_active) return iter++ == 0;
  if (env_cap && (!max_cnt || env_cap < max_cnt)) max_cnt = env_cap;
  /* Cap check must come BEFORE the stop: once the cap is reached the
   * process exits at the boundary, so the fuzzer sees an exit instead
   * of a stop and re-forks — the input it staged for the next exec
   * then runs in the fresh child rather than being swallowed by a
   * child that only woke up to die. */
  if (max_cnt && iter >= max_cnt) return 0; /* exit -> fuzzer re-forks */
  if (iter) {
    raise(SIGSTOP); /* iteration boundary; resumed by SIGCONT */
  }
  iter++;
  kb_prev_loc = 0;
  return 1;
}
