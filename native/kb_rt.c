/* kb_rt — target-side instrumentation runtime (compiled into targets
 * by the kb-cc wrapper together with -fsanitize-coverage=trace-pc).
 *
 * Three jobs, mirroring the behavior of the reference's compiled-in
 * runtime (SURVEY.md §2.5, reference afl_progs/llvm_mode/afl-llvm-rt.o.c
 * semantics — implementation here is fresh, built on GCC sancov):
 *
 *   1. Edge coverage: __sanitizer_cov_trace_pc() is invoked by the
 *      compiler at every edge; we hash the call site PC into a 64KB
 *      bitmap slot and do trace_bits[cur ^ prev]++, prev = cur >> 1 —
 *      the classic AFL edge transition encoding.
 *   2. Forkserver: before main (ELF constructor), speak the protocol in
 *      kb_protocol.h over fds 198/199 so the fuzzer pays fork+COW per
 *      exec instead of fork+execve.  Deferred mode (KB_DEFER_FORKSRV=1)
 *      skips the constructor; the target calls __kb_manual_init() at a
 *      point of its choosing.
 *   3. Persistence: __kb_persistent_loop(n) lets one process run n
 *      inputs, signalling iteration boundaries with SIGSTOP and being
 *      resumed with SIGCONT (reference forkserver.c persistence
 *      contract per SURVEY.md §2.3).
 */
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/shm.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define KB_FORKSERVER_IMPL /* pull in the shared command loop */
#include "kb_protocol.h"

/* Every kb-cc-built object (main executable AND each shared library)
 * carries its own copy of this runtime.  The coverage internals are
 * HIDDEN so each copy binds to its own state — with default
 * visibility the dynamic linker would interpose every DSO's
 * references onto the executable's copy, collapsing per-module
 * anchors/partitions and mis-normalizing library ASLR. */
static unsigned char kb_dummy_map[KB_SHM_TOTAL];
__attribute__((visibility("hidden")))
unsigned char *__kb_trace_bits = kb_dummy_map;

static __thread uintptr_t kb_prev_loc;
static int kb_persist_active = -1; /* -1 = not yet checked */

/* Per-module mode (KB_MODULES=1): this runtime copy's submap.  In the
 * default mode base stays 0 and the mask covers the whole map, so the
 * hot hook is branch-free either way. */
static uintptr_t kb_mod_base = 0;
static uintptr_t kb_loc_mask = KB_MAP_SIZE - 1;

/* ------------------------------------------------------------------ */
/* Coverage                                                            */
/* ------------------------------------------------------------------ */

/* Mix the return address into a bitmap slot.  The shift folds out the
 * low alignment bits; the xor-shift spreads nearby PCs across the map
 * (same role as afl-as's per-block random ids, but derived from the PC
 * because sancov gives us no compile-time id). */
/* kb_rt.o is compiled WITHOUT -fsanitize-coverage, so this hook is
 * never itself instrumented (no recursion risk). */

/* ASLR normalization: PIE executables load at a random base, so raw
 * PCs — and therefore bitmap slots — would differ between fuzzer
 * instances, breaking cross-process state merge (the merger tool's
 * whole point).  kb_rt.o is linked into the target executable, so the
 * distance from any of its own symbols to an instrumented PC is a
 * link-time constant; subtracting it makes slots load-address
 * invariant (same role as the reference IPT path's /proc/pid/maps
 * normalization, linux_ipt_instrumentation.c:163-189). */
static void kb_anchor(void) {}

__attribute__((visibility("hidden")))
void __sanitizer_cov_trace_pc(void) {
  uintptr_t pc = (uintptr_t)__builtin_return_address(0) -
                 (uintptr_t)&kb_anchor;
  uintptr_t h = pc;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 29;
  uintptr_t cur = h & kb_loc_mask;
  __kb_trace_bits[kb_mod_base | (cur ^ kb_prev_loc)]++;
  kb_prev_loc = cur >> 1;
}

/* Basename of the object this runtime copy is linked into, via
 * /proc/self/maps (no dladdr dependency): find the mapping holding
 * kb_anchor's address. */
static void kb_module_name(char *out, size_t n) {
  uintptr_t addr = (uintptr_t)&kb_anchor;
  FILE *f = fopen("/proc/self/maps", "r");
  char line[512];
  out[0] = 0;
  while (f && fgets(line, sizeof line, f)) {
    unsigned long lo, hi;
    char path[384];
    path[0] = 0;
    if (sscanf(line, "%lx-%lx %*s %*s %*s %*s %383s",
               &lo, &hi, path) >= 2 &&
        lo <= addr && addr < hi && path[0] == '/') {
      const char *base = strrchr(path, '/');
      snprintf(out, n, "%s", base ? base + 1 : path);
      break;
    }
  }
  if (f) fclose(f);
  if (!out[0]) snprintf(out, n, "target");
}

/* Claim (or find) this module's submap in the name table at the end
 * of the SHM segment.  Constructors run serially under the loader, so
 * no locking is needed; forked children only read.
 *
 * Degraded-accounting flag: snprintf always NUL-terminates, so byte
 * KB_MODTAB_NAME-1 of an entry is never part of a name.  It is set
 * nonzero when that entry's coverage aliases more than one module —
 * table overflow (later modules share the last partition) or a
 * truncated-name match (two >63-char basenames merging) — so the
 * fuzzer side can surface the degradation instead of silently
 * mis-attributing per-module novelty. */
static void kb_register_module(void) {
  char name[KB_MODTAB_NAME];
  kb_module_name(name, sizeof name);
  char *tab = (char *)__kb_trace_bits + KB_MAP_SIZE;
  int idx = 0;
  for (; idx < KB_N_MODULES; idx++) {
    char *entry = tab + idx * KB_MODTAB_NAME;
    if (!entry[0]) {
      /* width-1: names keep a NUL at <= byte KB_MODTAB_NAME-2, so
       * the flag byte never clobbers a maximal name's terminator.
       * Bit 1 of the flag records "stored name is a truncation" at
       * write time, so a LATER full-width matcher can tell it might
       * be aliasing a different long basename (the order-independent
       * half of the check below). */
      snprintf(entry, KB_MODTAB_NAME - 1, "%s", name);
      if (strlen(name) > KB_MODTAB_NAME - 2)
        entry[KB_MODTAB_NAME - 1] |= 2;
      break;
    }
    if (!strncmp(entry, name, KB_MODTAB_NAME - 2)) {
      /* a full-width match may be a truncated alias of a DIFFERENT
       * long basename — either ours (longer than the field) or the
       * stored one (truncated bit recorded at write time) */
      if (strlen(name) > KB_MODTAB_NAME - 2 ||
          (entry[KB_MODTAB_NAME - 1] & 2))
        entry[KB_MODTAB_NAME - 1] |= 1;
      break;
    }
  }
  if (idx >= KB_N_MODULES) { /* table full: share the last partition */
    idx = KB_N_MODULES - 1;
    tab[idx * KB_MODTAB_NAME + KB_MODTAB_NAME - 1] |= 1;
  }
  kb_mod_base = (uintptr_t)idx * KB_MOD_SIZE;
  kb_loc_mask = KB_MOD_SIZE - 1;
}

static void kb_map_shm(void) {
  static int mapped;
  const char *id_str = getenv(KB_SHM_ENV);
  if (mapped || !id_str) return;
  mapped = 1;
  void *addr = shmat(atoi(id_str), NULL, 0);
  if (addr != (void *)-1) __kb_trace_bits = (unsigned char *)addr;
  if (getenv(KB_MODULES_ENV)) kb_register_module();
}

/* ------------------------------------------------------------------ */
/* Forkserver                                                          */
/* ------------------------------------------------------------------ */

static void kb_child_reset(void) { kb_prev_loc = 0; }

static void kb_forkserver(void) { kb_serve_forkserver(kb_child_reset); }

/* Per-copy init (static: the exported __kb_manual_init would be
 * interposed to the executable's copy, so library constructors must
 * call their own). */
static void kb_init_local(void) {
  static int done;
  if (done) return;
  done = 1;
  kb_map_shm();
  /* Only ONE runtime copy may speak the forkserver protocol: a
   * kb-cc-built shared library carries its own copy whose constructor
   * runs before the executable's — the first claims, later copies
   * just map coverage and register their module. */
  if (!getenv(KB_CLAIM_ENV)) {
    setenv(KB_CLAIM_ENV, "1", 1);
    kb_forkserver();
  }
}

void __kb_manual_init(void) { kb_init_local(); }

__attribute__((constructor))
static void kb_auto_init(void) {
  if (getenv(KB_DEFER_ENV)) {
    kb_map_shm(); /* coverage from process start even when deferred */
    return;
  }
  kb_init_local();
}

/* ------------------------------------------------------------------ */
/* Persistence                                                         */
/* ------------------------------------------------------------------ */

/* while (__kb_persistent_loop(1000)) { one_input(); }
 *
 * Without PERSISTENCE_MAX_CNT in the environment the body runs exactly
 * once (plain fork-per-exec).  With it, each completed iteration
 * SIGSTOPs so the fuzzer can harvest the bitmap and stage the next
 * input before SIGCONTing us. */
int __kb_persistent_loop(unsigned max_cnt) {
  static unsigned iter, env_cap;
  if (kb_persist_active < 0) {
    const char *env = getenv(KB_PERSIST_ENV);
    kb_persist_active = env != NULL;
    if (env && atoi(env) > 0) env_cap = (unsigned)atoi(env);
  }
  if (!kb_persist_active) return iter++ == 0;
  if (env_cap && (!max_cnt || env_cap < max_cnt)) max_cnt = env_cap;
  /* Cap check must come BEFORE the stop: once the cap is reached the
   * process exits at the boundary, so the fuzzer sees an exit instead
   * of a stop and re-forks — the input it staged for the next exec
   * then runs in the fresh child rather than being swallowed by a
   * child that only woke up to die. */
  if (max_cnt && iter >= max_cnt) return 0; /* exit -> fuzzer re-forks */
  if (iter) {
    raise(SIGSTOP); /* iteration boundary; resumed by SIGCONT */
  }
  iter++;
  kb_prev_loc = 0;
  return 1;
}
