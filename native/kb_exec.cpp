/* kb_exec — host-side target execution backend (C++).
 *
 * The native twin of the fuzzer-side process control in the reference
 * (SURVEY.md §2.3: reference instrumentation/instrumentation.c
 * run_target / fork_server_init / fork_server_* command senders —
 * re-implemented from scratch against the documented protocol in
 * kb_protocol.h).  Exposed as a C ABI for ctypes.
 *
 * Responsibilities:
 *   - spawn a target (plain fork+execve, or under the forkserver with
 *     fds 198/199), with stdio redirection, setsid, rlimits, optional
 *     LD_PRELOAD, sanitizer option defaults and the SHM env var;
 *   - SysV SHM coverage region create/attach/clear;
 *   - one-exec and batched dispatch: write input (file and/or stdin),
 *     FORK_RUN or SIGCONT (persistence), poll the status pipe with a
 *     timeout, classify exit/signal/hang;
 *   - batch mode copies each exec's 64KB bitmap into a caller buffer
 *     [n, 65536] so Python ships ONE array to the TPU for classify +
 *     novelty instead of 65536-byte round trips per exec.
 *
 * Status encoding returned to Python:
 *   0..255   normal exit code
 *   512+sig  terminated by signal `sig`
 *   -1       hang (killed after timeout)
 *   -2       backend error (see kb_last_error)
 */
#include <cerrno>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <elf.h>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <sys/ipc.h>
#include <sys/ptrace.h>
#include <sys/resource.h>
#include <sys/shm.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

#include "kb_protocol.h"

namespace {

thread_local char g_err[512];

void set_err(const char *fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(g_err, sizeof(g_err), fmt, ap);
  va_end(ap);
}

double now_s() {
  struct timeval tv;
  gettimeofday(&tv, nullptr);
  return tv.tv_sec + tv.tv_usec * 1e-6;
}

/* Read exactly n bytes from fd, waiting at most timeout_s.  Returns 0
 * on success, -1 on timeout, -2 on error/EOF. */
int read_timed(int fd, void *buf, size_t n, double timeout_s) {
  char *p = static_cast<char *>(buf);
  double deadline = now_s() + timeout_s;
  while (n > 0) {
    double left = deadline - now_s();
    if (left <= 0) return -1;
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = poll(&pfd, 1, static_cast<int>(left * 1000) + 1);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -2;
    }
    if (pr == 0) return -1;
    ssize_t r = read(fd, p, n);
    if (r <= 0) return -2;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

struct kb_target {
  std::vector<std::string> argv;
  std::string input_file;   /* staged input path ("" = none) */
  std::string preload;      /* LD_PRELOAD library ("" = none) */
  int use_stdin = 0;        /* input_file is also the target's stdin */
  int use_forkserver = 0;
  int persistent = 0;       /* persistence_max_cnt (0 = off) */
  int deferred = 0;
  long mem_limit_mb = 0;
  int use_shm = 0;
  std::vector<std::string> extra_env; /* KEY=VALUE set in the child */

  /* runtime state */
  int shm_id = -1;
  unsigned char *trace_bits = nullptr;
  pid_t forksrv_pid = -1;
  pid_t child_pid = -1;
  int ctl_fd = -1;   /* -> forkserver fd 198 */
  int st_fd = -1;    /* <- forkserver fd 199 */
  int input_fd = -1; /* shared-description fd for stdin delivery */
  int child_stopped = 0; /* persistent child is SIGSTOPped */
  int pending_status = 0; /* wstatus harvested early by kb_target_alive */
  int pending_valid = 0;
  long total_execs = 0;
};

const char *kb_last_error(void) { return g_err; }

/* ------------------------------------------------------------------ */
/* SHM                                                                 */
/* ------------------------------------------------------------------ */

static int setup_shm(kb_target *t) {
  /* KB_SHM_TOTAL = coverage map + per-module name table (the table
   * stays zero unless the target runs with KB_MODULES=1). */
  t->shm_id = shmget(IPC_PRIVATE, KB_SHM_TOTAL,
                     IPC_CREAT | IPC_EXCL | 0600);
  if (t->shm_id < 0) {
    set_err("shmget: %s", strerror(errno));
    return -1;
  }
  t->trace_bits = static_cast<unsigned char *>(shmat(t->shm_id, nullptr, 0));
  if (t->trace_bits == reinterpret_cast<unsigned char *>(-1)) {
    set_err("shmat: %s", strerror(errno));
    t->trace_bits = nullptr;
    return -1;
  }
  /* Mark for removal now; the segment lives until the last detach, so
   * no leak even if we crash. */
  shmctl(t->shm_id, IPC_RMID, nullptr);
  return 0;
}

/* ------------------------------------------------------------------ */
/* Construction                                                        */
/* ------------------------------------------------------------------ */

kb_target *kb_target_create(const char *const *argv, int use_stdin,
                            const char *input_file, int use_forkserver,
                            const char *preload, int persistent,
                            int deferred, long mem_limit_mb, int use_shm) {
  if (!argv || !argv[0]) {
    set_err("empty argv");
    return nullptr;
  }
  auto *t = new kb_target();
  for (int i = 0; argv[i]; i++) t->argv.emplace_back(argv[i]);
  t->input_file = input_file ? input_file : "";
  t->preload = preload ? preload : "";
  t->use_stdin = use_stdin;
  t->use_forkserver = use_forkserver;
  t->persistent = persistent;
  t->deferred = deferred;
  t->mem_limit_mb = mem_limit_mb;
  t->use_shm = use_shm;
  if (use_shm && setup_shm(t) != 0) {
    delete t;
    return nullptr;
  }
  return t;
}

/* Add a KEY=VALUE pair to the child environment.  Must be called
 * before kb_target_start/launch (env is applied at spawn). */
void kb_target_add_env(kb_target *t, const char *kv) {
  if (t && kv) t->extra_env.emplace_back(kv);
}

/* Child-side setup common to forkserver and plain spawns.  Never
 * returns on failure. */
static void child_setup(kb_target *t, int ctl_fd, int st_fd) {
  setsid();
  int devnull = open("/dev/null", O_RDWR);
  if (!getenv("KB_DEBUG_CHILD")) {
    dup2(devnull, 1);
    dup2(devnull, 2);
  }
  if (t->use_stdin && t->input_fd >= 0) {
    dup2(t->input_fd, 0);
  } else {
    dup2(devnull, 0);
  }
  if (devnull > 2) close(devnull);

  if (ctl_fd >= 0) {
    if (dup2(ctl_fd, KB_FORKSRV_FD) < 0 || dup2(st_fd, KB_STATUS_FD) < 0)
      _exit(124);
    if (ctl_fd != KB_FORKSRV_FD) close(ctl_fd);
    if (st_fd != KB_STATUS_FD) close(st_fd);
  }

  if (t->mem_limit_mb > 0) {
    struct rlimit rl;
    rl.rlim_cur = rl.rlim_max =
        static_cast<rlim_t>(t->mem_limit_mb) << 20;
    setrlimit(RLIMIT_AS, &rl);
  }
  struct rlimit core = {0, 0};
  setrlimit(RLIMIT_CORE, &core); /* crashes should not write cores */

  if (t->use_shm) {
    char buf[32];
    snprintf(buf, sizeof(buf), "%d", t->shm_id);
    setenv(KB_SHM_ENV, buf, 1);
  }
  if (!t->preload.empty()) setenv("LD_PRELOAD", t->preload.c_str(), 1);
  if (t->persistent > 0) {
    char buf[32];
    snprintf(buf, sizeof(buf), "%d", t->persistent);
    setenv(KB_PERSIST_ENV, buf, 1);
  }
  if (t->deferred) setenv(KB_DEFER_ENV, "1", 1);
  for (auto &kv : t->extra_env)
    putenv(const_cast<char *>(kv.c_str())); /* t outlives the execv */
  setenv("LD_BIND_NOW", "1", 0); /* resolve PLT before the fork point */
  /* Sanitizer defaults so crashes surface as signals / magic exit
   * codes (reference sets the same class of defaults). */
  setenv("ASAN_OPTIONS",
         "abort_on_error=1:detect_leaks=0:symbolize=0:"
         "allocator_may_return_null=1",
         0);
  setenv("MSAN_OPTIONS", "exit_code=86:symbolize=0", 0);

  std::vector<char *> cargv;
  for (auto &a : t->argv) cargv.push_back(const_cast<char *>(a.c_str()));
  cargv.push_back(nullptr);
  execv(cargv[0], cargv.data());
  _exit(127);
}

/* Open the staged-input file with a shared description so lseek here
 * repositions the target's inherited stdin.  Idempotent: a forkserver
 * restart must NOT reopen (O_TRUNC would wipe an already-staged
 * input). */
static int open_input_fd(kb_target *t) {
  if (t->input_file.empty() || t->input_fd >= 0) return 0;
  t->input_fd = open(t->input_file.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (t->input_fd < 0) {
    set_err("open %s: %s", t->input_file.c_str(), strerror(errno));
    return -1;
  }
  return 0;
}

int kb_target_start(kb_target *t, double timeout_s) {
  if (open_input_fd(t) != 0) return -2;
  if (!t->use_forkserver) return 0; /* plain mode spawns per exec */

  int ctl[2], st[2];
  if (pipe(ctl) != 0 || pipe(st) != 0) {
    set_err("pipe: %s", strerror(errno));
    return -2;
  }
  pid_t pid = fork();
  if (pid < 0) {
    set_err("fork: %s", strerror(errno));
    return -2;
  }
  if (pid == 0) {
    close(ctl[1]);
    close(st[0]);
    child_setup(t, ctl[0], st[1]);
  }
  close(ctl[0]);
  close(st[1]);
  t->ctl_fd = ctl[1];
  t->st_fd = st[0];
  t->forksrv_pid = pid;

  uint32_t hello = 0;
  int r = read_timed(t->st_fd, &hello, 4, timeout_s);
  if (r != 0 || hello != KB_HELLO) {
    int status = 0;
    /* Harvest the exec failure for diagnostics before reporting. */
    waitpid(pid, &status, WNOHANG);
    set_err("forkserver handshake failed (r=%d hello=0x%x wstatus=0x%x) "
            "— is the target built with kb-cc or preloaded?",
            r, hello, status);
    kill(pid, SIGKILL);
    waitpid(pid, nullptr, 0);
    t->forksrv_pid = -1;
    close(t->ctl_fd);
    close(t->st_fd);
    t->ctl_fd = t->st_fd = -1;
    return -2;
  }
  return 0;
}

/* ------------------------------------------------------------------ */
/* Execution                                                           */
/* ------------------------------------------------------------------ */

static int stage_input(kb_target *t, const uint8_t *input, int32_t len) {
  if (t->input_fd < 0) return 0;
  if (lseek(t->input_fd, 0, SEEK_SET) < 0 ||
      write(t->input_fd, input, static_cast<size_t>(len)) != len ||
      ftruncate(t->input_fd, len) != 0 ||
      lseek(t->input_fd, 0, SEEK_SET) < 0) {
    set_err("staging input: %s", strerror(errno));
    return -1;
  }
  return 0;
}

static int classify_wstatus(int wstatus) {
  if (WIFSIGNALED(wstatus)) return 512 + WTERMSIG(wstatus);
  if (WIFEXITED(wstatus)) {
    int code = WEXITSTATUS(wstatus);
    if (code == 86) return 512 + SIGSEGV; /* MSAN magic exit */
    return code;
  }
  return 0;
}

static void kill_forkserver(kb_target *t) {
  if (t->child_pid > 0) kill(t->child_pid, SIGKILL);
  if (t->forksrv_pid > 0) {
    kill(t->forksrv_pid, SIGKILL);
    waitpid(t->forksrv_pid, nullptr, 0);
  }
  if (t->ctl_fd >= 0) close(t->ctl_fd);
  if (t->st_fd >= 0) close(t->st_fd);
  t->ctl_fd = t->st_fd = -1;
  t->forksrv_pid = t->child_pid = -1;
  t->child_stopped = 0;
}

/* One exec through the forkserver.  Assumes input already staged. */
static int forkserver_exec(kb_target *t, double timeout_s) {
  unsigned char cmd;
  if (t->child_stopped) {
    cmd = KB_CMD_RUN; /* resume the persistent child */
  } else {
    cmd = KB_CMD_FORK_RUN;
  }
  if (write(t->ctl_fd, &cmd, 1) != 1) {
    set_err("forkserver write failed: %s", strerror(errno));
    return -2;
  }
  if (cmd == KB_CMD_FORK_RUN) {
    int32_t pid = 0;
    if (read_timed(t->st_fd, &pid, 4, timeout_s) != 0 || pid <= 0) {
      set_err("forkserver did not return a child pid");
      return -2;
    }
    t->child_pid = pid;
  }
  t->child_stopped = 0;

  cmd = KB_CMD_GET_STATUS;
  if (write(t->ctl_fd, &cmd, 1) != 1) {
    set_err("forkserver write failed: %s", strerror(errno));
    return -2;
  }
  int32_t wstatus = 0;
  int r = read_timed(t->st_fd, &wstatus, 4, timeout_s);
  if (r == -1) {
    /* Hang: kill the run; the forkserver's pending waitpid completes
     * and sends the (now SIGKILL) status, which we must drain. */
    if (t->child_pid > 0) kill(t->child_pid, SIGKILL);
    if (read_timed(t->st_fd, &wstatus, 4, 2.0) != 0) {
      kill_forkserver(t); /* wedged beyond recovery */
      return -1;
    }
    t->child_pid = -1;
    return -1;
  }
  if (r != 0) {
    set_err("forkserver status read failed");
    return -2;
  }
  if (WIFSTOPPED(wstatus)) {
    /* Persistent iteration boundary: child alive, input consumed. */
    t->child_stopped = 1;
    return 0;
  }
  t->child_pid = -1;
  return classify_wstatus(wstatus);
}

/* One plain fork+execve exec. */
static int plain_exec(kb_target *t, double timeout_s) {
  pid_t pid = fork();
  if (pid < 0) {
    set_err("fork: %s", strerror(errno));
    return -2;
  }
  if (pid == 0) child_setup(t, -1, -1);
  t->child_pid = pid;

  double deadline = now_s() + timeout_s;
  int wstatus = 0;
  for (;;) {
    pid_t r = waitpid(pid, &wstatus, WNOHANG);
    if (r == pid) break;
    if (r < 0) {
      set_err("waitpid: %s", strerror(errno));
      return -2;
    }
    if (now_s() > deadline) {
      kill(pid, SIGKILL);
      waitpid(pid, &wstatus, 0);
      t->child_pid = -1;
      return -1;
    }
    usleep(200);
  }
  t->child_pid = -1;
  return classify_wstatus(wstatus);
}

int kb_target_run(kb_target *t, const uint8_t *input, int32_t len,
                  double timeout_s) {
  if (stage_input(t, input, len) != 0) return -2;
  t->total_execs++;
  if (!t->use_forkserver) return plain_exec(t, timeout_s);
  if (t->forksrv_pid < 0) {
    /* (Re)start a dead forkserver transparently. */
    if (kb_target_start(t, timeout_s > 10 ? timeout_s : 10) != 0) return -2;
  }
  int st = forkserver_exec(t, timeout_s);
  if (st == -2) {
    /* One restart attempt per exec: a crashed forkserver (e.g. the
     * persistent child wrecked shared state) should not end the
     * campaign. */
    kill_forkserver(t);
    if (kb_target_start(t, 10) != 0) return -2;
    st = forkserver_exec(t, timeout_s);
  }
  return st;
}

int kb_target_run_batch(kb_target *t, const uint8_t *inputs,
                        const int32_t *lens, int n, int stride,
                        double timeout_s, int32_t *statuses_out,
                        uint8_t *bitmaps_out) {
  for (int i = 0; i < n; i++) {
    if (t->trace_bits) memset(t->trace_bits, 0, KB_MAP_SIZE);
    int st = kb_target_run(t, inputs + static_cast<size_t>(i) * stride,
                           lens[i], timeout_s);
    statuses_out[i] = st;
    if (bitmaps_out && t->trace_bits)
      memcpy(bitmaps_out + static_cast<size_t>(i) * KB_MAP_SIZE,
             t->trace_bits, KB_MAP_SIZE);
    if (st == -2) return i; /* backend error: report execs completed */
  }
  return n;
}

/* Async pair for drivers that interact with a RUNNING target (network
 * servers/clients): launch starts one exec and returns the pid without
 * waiting; wait_done collects the verdict afterwards (reference
 * pattern: enable starts the process, the driver talks to it, then
 * generic_wait_for_process_completion polls — SURVEY §2.2). */
int kb_target_launch(kb_target *t, double timeout_s) {
  t->total_execs++;
  if (!t->use_forkserver) {
    pid_t pid = fork();
    if (pid < 0) {
      set_err("fork: %s", strerror(errno));
      return -2;
    }
    if (pid == 0) child_setup(t, -1, -1);
    t->child_pid = pid;
    return pid;
  }
  if (t->forksrv_pid < 0 && kb_target_start(t, 10) != 0) return -2;
  unsigned char cmd = KB_CMD_FORK_RUN;
  if (write(t->ctl_fd, &cmd, 1) != 1) {
    set_err("forkserver write failed: %s", strerror(errno));
    return -2;
  }
  int32_t pid = 0;
  if (read_timed(t->st_fd, &pid, 4, timeout_s) != 0 || pid <= 0) {
    set_err("forkserver did not return a child pid");
    kill_forkserver(t);
    return -2;
  }
  t->child_pid = pid;
  t->child_stopped = 0;
  return pid;
}

/* 1 = the launched child is still running, 0 = done/absent. */
int kb_target_alive(kb_target *t) {
  if (t->child_pid <= 0) return 0;
  if (!t->use_forkserver) {
    int st;
    pid_t r = waitpid(t->child_pid, &st, WNOHANG);
    if (r == t->child_pid) {
      /* Done: remember the status for kb_target_wait_done. */
      t->child_stopped = 0;
      t->child_pid = -1;
      t->pending_status = st;
      t->pending_valid = 1;
      return 0;
    }
    return r == 0;
  }
  /* Forkserver child is not our direct child (the forkserver reaps it
   * on GET_STATUS), so a crashed-at-startup target lingers as a
   * zombie that kill(pid, 0) still sees.  Read the state field of
   * /proc/<pid>/stat instead: 'Z'/'X' = done. */
  char path[64], buf[256];
  snprintf(path, sizeof(path), "/proc/%d/stat", (int)t->child_pid);
  int fd = open(path, O_RDONLY);
  if (fd < 0) return 0; /* gone entirely */
  ssize_t n = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  if (n <= 0) return 0;
  buf[n] = 0;
  /* state is the first non-space char after the ")" that closes comm */
  const char *p = strrchr(buf, ')');
  if (!p) return 0;
  p++;
  while (*p == ' ') p++;
  return *p != 'Z' && *p != 'X' && *p != 0;
}

int kb_target_wait_done(kb_target *t, double timeout_s) {
  if (!t->use_forkserver) {
    if (t->pending_valid) {
      t->pending_valid = 0;
      return classify_wstatus(t->pending_status);
    }
    if (t->child_pid <= 0) {
      set_err("no launched child to wait for");
      return -2;
    }
    double deadline = now_s() + timeout_s;
    int wstatus = 0;
    for (;;) {
      pid_t r = waitpid(t->child_pid, &wstatus, WNOHANG);
      if (r == t->child_pid) break;
      if (r < 0) {
        set_err("waitpid: %s", strerror(errno));
        return -2;
      }
      if (now_s() > deadline) {
        kill(t->child_pid, SIGKILL);
        waitpid(t->child_pid, &wstatus, 0);
        t->child_pid = -1;
        return -1;
      }
      usleep(500);
    }
    t->child_pid = -1;
    return classify_wstatus(wstatus);
  }
  unsigned char cmd = KB_CMD_GET_STATUS;
  if (write(t->ctl_fd, &cmd, 1) != 1) {
    set_err("forkserver write failed: %s", strerror(errno));
    return -2;
  }
  int32_t wstatus = 0;
  int r = read_timed(t->st_fd, &wstatus, 4, timeout_s);
  if (r == -1) {
    if (t->child_pid > 0) kill(t->child_pid, SIGKILL);
    if (read_timed(t->st_fd, &wstatus, 4, 2.0) != 0) {
      kill_forkserver(t);
      return -1;
    }
    t->child_pid = -1;
    return -1;
  }
  if (r != 0) {
    set_err("forkserver status read failed");
    return -2;
  }
  t->child_pid = -1;
  return classify_wstatus(wstatus);
}

/* FORK (stopped child) + RUN split — the attach window an external
 * tracer (perf, ptrace) needs between fork and first instruction
 * (reference fork_server_fork / fork_server_run pair). */
int kb_target_fork(kb_target *t, double timeout_s) {
  if (!t->use_forkserver || t->forksrv_pid < 0) {
    set_err("fork command requires a running forkserver");
    return -2;
  }
  unsigned char cmd = KB_CMD_FORK;
  if (write(t->ctl_fd, &cmd, 1) != 1) return -2;
  int32_t pid = 0;
  if (read_timed(t->st_fd, &pid, 4, timeout_s) != 0 || pid <= 0) {
    set_err("fork: no child pid");
    return -2;
  }
  t->child_pid = pid;
  t->child_stopped = 1;
  return pid;
}

int kb_target_resume(kb_target *t, double timeout_s) {
  if (t->child_pid <= 0) {
    set_err("no forked child to resume");
    return -2;
  }
  unsigned char cmd = KB_CMD_RUN;
  if (write(t->ctl_fd, &cmd, 1) != 1) return -2;
  t->child_stopped = 0;
  cmd = KB_CMD_GET_STATUS;
  if (write(t->ctl_fd, &cmd, 1) != 1) return -2;
  int32_t wstatus = 0;
  int r = read_timed(t->st_fd, &wstatus, 4, timeout_s);
  if (r == -1) {
    if (t->child_pid > 0) kill(t->child_pid, SIGKILL);
    if (read_timed(t->st_fd, &wstatus, 4, 2.0) != 0) {
      kill_forkserver(t);
      return -1;
    }
    t->child_pid = -1;
    return -1;
  }
  if (r != 0) return -2;
  if (WIFSTOPPED(wstatus)) {
    t->child_stopped = 1;
    return 0;
  }
  t->child_pid = -1;
  return classify_wstatus(wstatus);
}

/* ------------------------------------------------------------------ */
/* Debugger-mode execution (ptrace)                                    */
/* ------------------------------------------------------------------ */

/* The reference's Windows debug instrumentation attaches a debugger
 * and classifies EXCEPTION_DEBUG_EVENTs (debug_instrumentation.c:
 * 19-88).  The Linux equivalent: run the child under ptrace and, on a
 * fatal-signal stop, harvest siginfo (fault address) and the PC
 * before letting the signal kill it — crash *details*, not just a
 * waitpid status. */

struct kb_crash_info {
  int32_t signal_no;   /* 0 = no crash */
  int32_t si_code;
  uint64_t fault_addr; /* siginfo si_addr */
  uint64_t pc;         /* instruction pointer at the fault */
};

/* Base address of the module CONTAINING the fault PC — subtracting it
 * makes the PC load-address invariant under ASLR (same normalization
 * as the reference IPT path's /proc/pid/maps pass,
 * linux_ipt_instrumentation.c:163-189); without it every re-exec of
 * the same crash looks like a new crash site.  Two passes: find the
 * mapping that contains pc and its backing path, then the lowest
 * mapping of that same path (= the module base; a module maps several
 * segments).  Anonymous regions return 0 (PC stays raw). */
static uint64_t module_base_for_pc(pid_t pid, uint64_t pc) {
  char path[64];
  snprintf(path, sizeof(path), "/proc/%d/maps", (int)pid);
  FILE *f = fopen(path, "r");
  if (!f) return 0;
  char containing[256] = "";
  char line[512];
  while (fgets(line, sizeof(line), f)) {
    unsigned long start = 0, end = 0;
    int name_off = 0;
    if (sscanf(line, "%lx-%lx %*4s %*x %*x:%*x %*u %n",
               &start, &end, &name_off) < 2)
      continue;
    if (pc >= start && pc < end) {
      if (name_off > 0 && line[name_off] == '/')
        sscanf(line + name_off, "%255[^\n]", containing);
      break;
    }
  }
  uint64_t base = 0;
  if (containing[0]) {
    rewind(f);
    while (fgets(line, sizeof(line), f)) {
      unsigned long start = 0, end = 0;
      int name_off = 0;
      if (sscanf(line, "%lx-%lx %*4s %*x %*x:%*x %*u %n",
                 &start, &end, &name_off) < 2)
        continue;
      char name[256] = "";
      if (name_off > 0 && line[name_off] == '/')
        sscanf(line + name_off, "%255[^\n]", name);
      if (strcmp(name, containing) == 0) {
        base = start; /* maps are sorted: first hit is the base */
        break;
      }
    }
  }
  fclose(f);
  return base;
}

static int is_fatal_signal(int sig) {
  switch (sig) {
    case SIGSEGV: case SIGBUS: case SIGILL: case SIGFPE:
    case SIGABRT: case SIGSYS: case SIGTRAP:
      return 1;
    default:
      return 0;
  }
}

int kb_target_run_debug(kb_target *t, const uint8_t *input, int32_t len,
                        double timeout_s, struct kb_crash_info *info) {
  memset(info, 0, sizeof(*info));
  if (stage_input(t, input, len) != 0) return -2;
  t->total_execs++;

  pid_t pid = fork();
  if (pid < 0) {
    set_err("fork: %s", strerror(errno));
    return -2;
  }
  if (pid == 0) {
    ptrace(PTRACE_TRACEME, 0, nullptr, nullptr);
    child_setup(t, -1, -1); /* never returns */
  }
  t->child_pid = pid;

  double deadline = now_s() + timeout_s;
  int wstatus = 0;
  int seen_exec_trap = 0;
  for (;;) {
    pid_t r = waitpid(pid, &wstatus, WNOHANG);
    if (r < 0) {
      set_err("waitpid: %s", strerror(errno));
      kill(pid, SIGKILL);
      waitpid(pid, nullptr, 0);
      t->child_pid = -1;
      return -2;
    }
    if (r == 0) {
      if (now_s() > deadline) {
        kill(pid, SIGKILL);
        ptrace(PTRACE_DETACH, pid, nullptr, nullptr);
        waitpid(pid, &wstatus, 0);
        t->child_pid = -1;
        return -1; /* hang */
      }
      usleep(200);
      continue;
    }
    if (WIFEXITED(wstatus) || WIFSIGNALED(wstatus)) break;
    if (WIFSTOPPED(wstatus)) {
      int sig = WSTOPSIG(wstatus);
      if (!seen_exec_trap && sig == SIGTRAP) {
        /* the post-execve trap, not a fault */
        seen_exec_trap = 1;
        ptrace(PTRACE_CONT, pid, nullptr, nullptr);
        continue;
      }
      if (is_fatal_signal(sig) && info->signal_no == 0) {
        siginfo_t si;
        if (ptrace(PTRACE_GETSIGINFO, pid, nullptr, &si) == 0) {
          info->signal_no = sig;
          info->si_code = si.si_code;
          info->fault_addr = (uint64_t)(uintptr_t)si.si_addr;
        }
#if defined(__x86_64__)
        struct user_regs_struct regs;
        if (ptrace(PTRACE_GETREGS, pid, nullptr, &regs) == 0)
          info->pc = (uint64_t)regs.rip;
#elif defined(__aarch64__)
        struct user_regs_struct regs;
        struct iovec iov = {&regs, sizeof(regs)};
        if (ptrace(PTRACE_GETREGSET, pid, (void *)NT_PRSTATUS, &iov) == 0)
          info->pc = (uint64_t)regs.pc;
#endif
        /* module-relative PC: stable across ASLR re-execs even when
         * the fault is inside a shared library */
        uint64_t base = module_base_for_pc(pid, info->pc);
        if (base && info->pc >= base) info->pc -= base;
      }
      /* deliver the signal untouched — fatal ones kill the child,
       * others pass through.  (Only the single post-execve SIGTRAP is
       * suppressed above; a later SIGTRAP is a real int3/breakpoint
       * crash and must land.) */
      ptrace(PTRACE_CONT, pid, nullptr, (void *)(uintptr_t)sig);
    }
  }
  t->child_pid = -1;
  return classify_wstatus(wstatus);
}

/* ------------------------------------------------------------------ */
/* Introspection / teardown                                            */
/* ------------------------------------------------------------------ */

const uint8_t *kb_target_trace_bits(kb_target *t) { return t->trace_bits; }

/* Per-module name table (written by kb_rt copies under KB_MODULES=1):
 * KB_N_MODULES fixed-size entries after the map; empty name = free. */
const char *kb_target_module_table(kb_target *t) {
  if (!t->trace_bits) return nullptr;
  return reinterpret_cast<const char *>(t->trace_bits) + KB_MAP_SIZE;
}

void kb_target_clear_trace(kb_target *t) {
  if (t->trace_bits) memset(t->trace_bits, 0, KB_MAP_SIZE);
}

int kb_target_pid(kb_target *t) { return static_cast<int>(t->child_pid); }

long kb_target_total_execs(kb_target *t) { return t->total_execs; }

void kb_target_stop(kb_target *t) {
  if (t->use_forkserver && t->forksrv_pid > 0 && t->ctl_fd >= 0) {
    unsigned char cmd = KB_CMD_EXIT;
    if (write(t->ctl_fd, &cmd, 1) == 1) {
      /* Give it a moment to exit cleanly, then force. */
      double deadline = now_s() + 1.0;
      int status;
      while (now_s() < deadline &&
             waitpid(t->forksrv_pid, &status, WNOHANG) == 0)
        usleep(1000);
    }
  }
  kill_forkserver(t);
}

void kb_target_free(kb_target *t) {
  if (!t) return;
  kb_target_stop(t);
  if (t->input_fd >= 0) close(t->input_fd);
  if (t->trace_bits) shmdt(t->trace_bits);
  delete t;
}

int kb_map_size(void) { return KB_MAP_SIZE; }

}  // extern "C"
