/* kb-cc — compiler wrapper that builds targets with Killerbeez-TPU
 * edge instrumentation (the role afl-gcc/afl-clang-fast play in the
 * reference, SURVEY.md §2.5 — fresh implementation: instead of an
 * assembler rewriter or LLVM pass we use GCC's built-in
 * -fsanitize-coverage=trace-pc and link the kb_rt runtime that
 * provides the __sanitizer_cov_trace_pc hook, SHM bitmap, forkserver
 * and persistence).
 *
 * Usage: kb-cc [cc args...]           (C, via gcc)
 *        kb-c++ [cc args...]          (C++, via g++; argv[0] switch)
 * Env:   KB_CC / KB_CXX — override the real compiler
 *        KB_RT_OBJ      — path to kb_rt.o (default: alongside kb-cc)
 *        KB_CC_VERBOSE  — print the final command line
 */
#include <libgen.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static char rt_path[PATH_MAX];

static void find_rt(const char *argv0) {
  const char *env = getenv("KB_RT_OBJ");
  if (env) {
    snprintf(rt_path, sizeof(rt_path), "%s", env);
    return;
  }
  char self[PATH_MAX];
  ssize_t n = readlink("/proc/self/exe", self, sizeof(self) - 1);
  if (n > 0) {
    self[n] = 0;
  } else {
    snprintf(self, sizeof(self), "%s", argv0);
  }
  char *dir = dirname(self);
  snprintf(rt_path, sizeof(rt_path), "%s/kb_rt.o", dir);
}

int main(int argc, char **argv) {
  int is_cxx = strstr(argv[0], "c++") != NULL || strstr(argv[0], "cxx");
  const char *cc = is_cxx ? getenv("KB_CXX") : getenv("KB_CC");
  if (!cc) cc = is_cxx ? "g++" : "gcc";
  find_rt(argv[0]);

  /* Compile-only invocations (-c/-E/-S) must not link the runtime. */
  int linking = 1;
  for (int i = 1; i < argc; i++)
    if (!strcmp(argv[i], "-c") || !strcmp(argv[i], "-E") ||
        !strcmp(argv[i], "-S"))
      linking = 0;

  char **out = calloc((size_t)argc + 8, sizeof(char *));
  int n = 0;
  out[n++] = (char *)cc;
  for (int i = 1; i < argc; i++) out[n++] = argv[i];
  out[n++] = "-fsanitize-coverage=trace-pc";
  out[n++] = "-g";
  out[n++] = "-fno-omit-frame-pointer";
  if (linking) out[n++] = rt_path;
  out[n] = NULL;

  if (getenv("KB_CC_VERBOSE")) {
    for (int i = 0; i < n; i++) fprintf(stderr, "%s ", out[i]);
    fprintf(stderr, "\n");
  }
  execvp(cc, out);
  perror("kb-cc: execvp");
  return 127;
}
