/* Killerbeez-TPU native protocol constants.
 *
 * Wire-compatible with the reference forkserver contract described in
 * SURVEY.md §2.3 (reference instrumentation/forkserver_internal.h:8-20,
 * docs/AFL.md:28-43): 1-byte commands on fd 198, 4-byte little-endian
 * int responses on fd 199, coverage in a SysV SHM region advertised by
 * the __AFL_SHM_ID env var (reference afl_progs/config.h:267,308).
 * Implementation here is from scratch against that documented contract.
 */
#ifndef KB_PROTOCOL_H
#define KB_PROTOCOL_H

#define KB_FORKSRV_FD 198   /* fuzzer -> forkserver commands */
#define KB_STATUS_FD  199   /* forkserver -> fuzzer responses */

#define KB_CMD_EXIT       0
#define KB_CMD_FORK       1   /* fork a child but leave it SIGSTOPped  */
#define KB_CMD_RUN        2   /* SIGCONT the forked child              */
#define KB_CMD_FORK_RUN   3   /* fork and run immediately              */
#define KB_CMD_GET_STATUS 4   /* waitpid the child, return its status  */

#define KB_SHM_ENV       "__AFL_SHM_ID"
#define KB_PERSIST_ENV   "PERSISTENCE_MAX_CNT"
#define KB_DEFER_ENV     "KB_DEFER_FORKSRV"
#define KB_MAP_SIZE_POW2 16
#define KB_MAP_SIZE      (1 << KB_MAP_SIZE_POW2)

/* Handshake: the forkserver announces itself with this 4-byte magic on
 * KB_STATUS_FD as soon as it is ready for commands. */
#define KB_HELLO 0x4b42465aU /* "KBFZ" */

#endif /* KB_PROTOCOL_H */
