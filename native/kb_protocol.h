/* Killerbeez-TPU native protocol constants.
 *
 * Wire-compatible with the reference forkserver contract described in
 * SURVEY.md §2.3 (reference instrumentation/forkserver_internal.h:8-20,
 * docs/AFL.md:28-43): 1-byte commands on fd 198, 4-byte little-endian
 * int responses on fd 199, coverage in a SysV SHM region advertised by
 * the __AFL_SHM_ID env var (reference afl_progs/config.h:267,308).
 * Implementation here is from scratch against that documented contract.
 */
#ifndef KB_PROTOCOL_H
#define KB_PROTOCOL_H

#define KB_FORKSRV_FD 198   /* fuzzer -> forkserver commands */
#define KB_STATUS_FD  199   /* forkserver -> fuzzer responses */

#define KB_CMD_EXIT       0
#define KB_CMD_FORK       1   /* fork a child but leave it SIGSTOPped  */
#define KB_CMD_RUN        2   /* SIGCONT the forked child              */
#define KB_CMD_FORK_RUN   3   /* fork and run immediately              */
#define KB_CMD_GET_STATUS 4   /* waitpid the child, return its status  */

#define KB_SHM_ENV       "__AFL_SHM_ID"
#define KB_PERSIST_ENV   "PERSISTENCE_MAX_CNT"
#define KB_DEFER_ENV     "KB_DEFER_FORKSRV"
#define KB_MAP_SIZE_POW2 16
#define KB_MAP_SIZE      (1 << KB_MAP_SIZE_POW2)

/* Per-module coverage (KB_MODULES=1): the 64KB map is partitioned
 * into KB_N_MODULES submaps; every kb_rt copy (main executable and
 * each kb-cc-built shared library carries its own) claims one submap
 * and logs its edges there — the role of the reference's one-SHM-per-
 * module design (dynamorio_instrumentation.h:27-41) inside a single
 * segment.  A name table in the page after the map tells the fuzzer
 * which submap belongs to which module. */
#define KB_MODULES_ENV   "KB_MODULES"
#define KB_MOD_BITS      3
#define KB_N_MODULES     (1 << KB_MOD_BITS)
#define KB_MOD_SIZE      (KB_MAP_SIZE >> KB_MOD_BITS)
#define KB_MODTAB_NAME   64
#define KB_MODTAB_SIZE   (KB_N_MODULES * KB_MODTAB_NAME)
#define KB_SHM_TOTAL     (KB_MAP_SIZE + KB_MODTAB_SIZE)

/* Set by the first runtime copy to start the forkserver so copies in
 * other DSOs (and forked children re-running constructors) skip it. */
#define KB_CLAIM_ENV     "KB_FORKSRV_CLAIMED"

/* Handshake: the forkserver announces itself with this 4-byte magic on
 * KB_STATUS_FD as soon as it is ready for commands. */
#define KB_HELLO 0x4b42465aU /* "KBFZ" */

#ifdef KB_FORKSERVER_IMPL
/* Shared target-side forkserver command loop, used by both the
 * compiled-in runtime (kb_rt.c) and the LD_PRELOAD library
 * (kb_preload.c).  Returns only in the CHILD (which then continues
 * into main); the serving parent never returns.  `child_reset` runs in
 * the child right before it proceeds (coverage state reset; may be
 * NULL).  If fd 199 is not wired up there is no fuzzer attached and
 * the function returns immediately so the target runs normally. */
#include <signal.h>
#include <stdint.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static void kb_serve_forkserver(void (*child_reset)(void)) {
  uint32_t hello = KB_HELLO;
  if (write(KB_STATUS_FD, &hello, 4) != 4) return; /* no fuzzer */

  pid_t child_pid = -1;
  for (;;) {
    unsigned char cmd;
    if (read(KB_FORKSRV_FD, &cmd, 1) != 1) _exit(0);
    switch (cmd) {
      case KB_CMD_EXIT:
        if (child_pid > 0) kill(child_pid, SIGKILL);
        _exit(0);

      case KB_CMD_FORK:
      case KB_CMD_FORK_RUN: {
        child_pid = fork();
        if (child_pid < 0) _exit(1);
        if (child_pid == 0) {
          close(KB_FORKSRV_FD);
          close(KB_STATUS_FD);
          if (cmd == KB_CMD_FORK) raise(SIGSTOP); /* tracer attach */
          if (child_reset) child_reset();
          return; /* continue into main() */
        }
        int32_t pid32 = (int32_t)child_pid;
        if (write(KB_STATUS_FD, &pid32, 4) != 4) _exit(1);
        break;
      }

      case KB_CMD_RUN:
        if (child_pid > 0) kill(child_pid, SIGCONT);
        break;

      case KB_CMD_GET_STATUS: {
        int status = -1;
        if (child_pid > 0) {
          if (waitpid(child_pid, &status, WUNTRACED) < 0) status = -1;
          if (!WIFSTOPPED(status)) child_pid = -1;
        }
        int32_t st32 = (int32_t)status;
        if (write(KB_STATUS_FD, &st32, 4) != 4) _exit(1);
        break;
      }

      default:
        _exit(2);
    }
  }
}
#endif /* KB_FORKSERVER_IMPL */

#endif /* KB_PROTOCOL_H */
