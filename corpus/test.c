/* Canonical crasher fixture — same observable behavior as the
 * reference's corpus/test (SURVEY.md §2.9: 4-byte "ABCD" input
 * triggers a NULL write; each matched prefix byte takes a distinct
 * branch so coverage deepens as a fuzzer homes in).  Written from
 * scratch.
 *
 * Input: first argv[1] names a file; with no argument, stdin.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

int __kb_persistent_loop(unsigned max_cnt) __attribute__((weak));
void __kb_manual_init(void) __attribute__((weak));

static int check(const unsigned char *buf, size_t n) {
  if (n < 1 || buf[0] != 'A') return 0;
  if (n < 2 || buf[1] != 'B') return 1;
  if (n < 3 || buf[2] != 'C') return 2;
  if (n < 4 || buf[3] != 'D') return 3;
  /* full magic: die */
  *(volatile int *)0 = 42;
  return 4;
}

static int run_once(const char *path) {
  unsigned char buf[64];
  size_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    /* Raw read: under persistence the fuzzer rewinds our stdin's file
     * description each iteration; stdio's EOF latch would hide that. */
    ssize_t r = read(0, buf, sizeof(buf));
    n = r > 0 ? (size_t)r : 0;
  }
  int depth = check(buf, n);
  printf("matched %d bytes\n", depth);
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : NULL;
  /* Deferred-startup init point: under KB_DEFER_FORKSRV=1 the runtime
   * constructor skipped the forkserver; starting it here puts the fork
   * point after main()'s entry ("expensive setup done").  Idempotent
   * no-op when the forkserver already ran pre-main. */
  if (__kb_manual_init) __kb_manual_init();
  if (__kb_persistent_loop) {
    while (__kb_persistent_loop(1000)) {
      if (run_once(path)) return 1;
    }
    return 0;
  }
  return run_once(path);
}
