/* ndlib — deterministic main half of the per-module picker fixture:
 * the main binary's coverage is identical across repeated runs of
 * one input; all nondeterminism lives in libnd1.so (its own map
 * partition under KB_MODULES=1). */
#include <stdio.h>
#include <unistd.h>

int nd_check(const unsigned char *buf, int n);

static int run_once(const char *path) {
  unsigned char buf[64];
  ssize_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = (ssize_t)fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    n = read(0, buf, sizeof(buf));
  }
  if (n < 1) {
    printf("empty\n");
    return 0;
  }
  printf("nd %d\n", nd_check(buf, (int)n));
  return 0;
}

int main(int argc, char **argv) {
  return run_once(argc > 1 ? argv[1] : 0);
}
