/* ndlib1 — NONdeterministic shared-library half of the picker
 * fixture (reference picker/main.c:163-282 scenario: a module whose
 * coverage varies across repeated runs of the SAME input must be
 * classified multi-path-same-file and its bitmap bytes masked).
 * The loop trip count depends on the clock, so hit-count buckets in
 * THIS module's map partition differ run to run while the main
 * binary's stay stable. */
#include <time.h>

int nd_check(const unsigned char *buf, int n) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  int d = 0;
  int trips = 1 + (int)((ts.tv_nsec >> 6) & 7);
  for (int i = 0; i < trips; i++) d++;
  if ((ts.tv_nsec >> 9) & 1) d += 100;
  if (n > 1 && buf[1] == 'Q') d += 10;
  return d;
}
