/* rledec — CGC-style run-length + back-reference decompressor
 * (realistic target: a decode loop whose output cursor is guarded by
 * an overflowable accounting variable — the classic decompressor CVE
 * shape, written from scratch).
 *
 * Format: "RLE2" [out_len u16le] then tokens:
 *   0x00 <n> <byte>      — emit byte n times
 *   0x01 <n>             — emit n bytes copied verbatim from input
 *   0x02 <n> <dist u8>   — back-reference: copy n bytes from
 *                          out_cursor - dist (dist validated > 0)
 *   0x03                 — end of stream
 *
 * The decode loop accounts output space with a signed `budget`
 * instead of checking the cursor against the buffer end, and its
 * reject condition only fires while the cursor still LOOKS in-bounds
 * (`op + cnt <= OUT_CAP`) — so the first token that both exhausts the
 * budget and crosses the buffer end slips through, and every later
 * token inherits an out-of-bounds cursor: the copy loops then walk
 * megabytes past the static buffer into unmapped pages (SIGSEGV).
 *
 * Input: argv[1] file, else stdin.  Seed: seeds/rledec.rle.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

int __kb_persistent_loop(unsigned max_cnt) __attribute__((weak));
void __kb_manual_init(void) __attribute__((weak));

#define OUT_CAP 1024

static int decode(const unsigned char *buf, size_t n) {
  /* Heap output buffer: the overflow walks up through the (small) brk
   * heap into unmapped pages — and cannot corrupt the input, which
   * lives below in BSS. */
  static unsigned char *out;
  if (!out) out = malloc(OUT_CAP);
  if (n < 6) return 1;
  if (memcmp(buf, "RLE2", 4) != 0) return 1;
  unsigned out_len = buf[4] | (buf[5] << 8);
  if (out_len > OUT_CAP) return 2;
  short budget = (short)out_len;             /* BUG: signed 16-bit */
  size_t ip = 6, op = 0;
  while (ip < n) {
    unsigned char tok = buf[ip++];
    if (tok == 0x03) { printf("decoded %zu bytes\n", op); return 0; }
    if (ip >= n) return 3;
    unsigned cnt = buf[ip++];
    if (cnt == 0) return 4;
    switch (tok) {
      case 0x00: {                           /* run */
        if (ip >= n) return 3;
        unsigned char b = buf[ip++];
        budget -= (short)cnt;
        if (budget < 0 && op + cnt <= OUT_CAP) return 5;  /* BUG: only
             rejects when the cursor ALSO looks in-bounds — the wrap
             case (op past cap) sails through */
        for (unsigned i = 0; i < cnt; i++) out[op++] = b;
        break;
      }
      case 0x01: {                           /* literal copy */
        if (ip + cnt > n) return 3;
        budget -= (short)cnt;
        if (budget < 0 && op + cnt <= OUT_CAP) return 5;
        for (unsigned i = 0; i < cnt; i++) out[op++] = buf[ip++];
        break;
      }
      case 0x02: {                           /* back-reference */
        if (ip >= n) return 3;
        unsigned dist = buf[ip++];
        if (dist == 0 || dist > op) return 6;
        budget -= (short)cnt;
        if (budget < 0 && op + cnt <= OUT_CAP) return 5;
        for (unsigned i = 0; i < cnt; i++, op++)
          out[op] = out[op - dist];
        break;
      }
      default:
        return 7;
    }
  }
  return 8;
}

static int run_once(const char *path) {
  static unsigned char buf[65536];
  size_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    ssize_t r = read(0, buf, sizeof(buf));
    n = r > 0 ? (size_t)r : 0;
  }
  printf("decode rc=%d\n", decode(buf, n));
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : NULL;
  if (__kb_manual_init) __kb_manual_init();
  if (__kb_persistent_loop) {
    while (__kb_persistent_loop(1000)) {
      if (run_once(path)) return 1;
    }
    return 0;
  }
  return run_once(path);
}
