/* Crash-variety fixture for the debugger instrumentation: the first
 * 4 input bytes select HOW to die, so tests can assert per-signal
 * triage (fresh code; exercises SURVEY §2.3 debug-instrumentation
 * behaviors: exception kind + faulting location).
 *
 *   "TRAP" -> int3 breakpoint (SIGTRAP)
 *   "LIBC" -> NULL memset, faulting inside libc (shared library PC)
 *   "ABRT" -> abort() (SIGABRT)
 *   "SEGV" -> NULL write in our own code (SIGSEGV, main-exe PC)
 *   else   -> exit 0
 */
#include <signal.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

/* opaque pointer the optimizer can't see through, so the NULL memset
 * really reaches libc */
void *kb_sink;

int main(void) {
  unsigned char buf[8];
  ssize_t n = read(0, buf, sizeof(buf));
  if (n < 4) return 0;
  if (memcmp(buf, "TRAP", 4) == 0) {
#if defined(__x86_64__) || defined(__i386__)
    __asm__ volatile("int3");
#else
    raise(SIGTRAP);
#endif
  } else if (memcmp(buf, "LIBC", 4) == 0) {
    memset(kb_sink, 0xee, 64); /* kb_sink is NULL */
  } else if (memcmp(buf, "ABRT", 4) == 0) {
    abort();
  } else if (memcmp(buf, "SEGV", 4) == 0) {
    *(volatile int *)0 = 7;
  }
  return 0;
}
