/* imgparse — CGC-style chunked image-format parser (realistic target,
 * VERDICT "Realistic targets": ~100+ basic blocks, layered field
 * validation, and a reachable memory-safety bug several constraints
 * deep; plays the role of the reference's prebuilt CGC challenge
 * binaries (corpus/cgc/) without copying them).
 *
 * Format ("QIMG"):
 *   magic   "QIMG"
 *   chunks: [type u8][len u8][payload len bytes][cksum u8]
 *           cksum = sum(payload) & 0xFF
 *   types:  'H' header  — payload = width u8, height u8, depth u8
 *           'P' palette — payload = count u8, then count*1 colors
 *           'D' data    — payload = row u8, then pixel bytes
 *           'C' comment — payload ignored
 *           'E' end     — stop
 *
 * Planted bugs:
 *   1. 'D' row offset is validated against height but the pixel copy
 *      trusts `width` from a SECOND header chunk — re-sending a header
 *      after 'D' rows with a larger width makes the next row write
 *      past the framebuffer (wild pointer, deterministic SIGSEGV).
 *   2. 'P' color lookup during 'D' decode indexes the palette with a
 *      pixel value without checking it against palette count — an OOB
 *      read amplified into a wild write.
 *
 * Input: argv[1] file, else stdin.  Seed: seeds/imgparse.qimg.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

int __kb_persistent_loop(unsigned max_cnt) __attribute__((weak));
void __kb_manual_init(void) __attribute__((weak));

#define FB_W 32
#define FB_H 32

typedef struct {
  unsigned w, h, depth;
  int have_header;
  unsigned pal_count;
  unsigned char palette[64];
  unsigned char fb[FB_W * FB_H];
  unsigned rows_done;
} img_t;

static int chunk_cksum_ok(const unsigned char *p, unsigned len,
                          unsigned char want) {
  unsigned s = 0;
  for (unsigned i = 0; i < len; i++) s += p[i];
  return (unsigned char)s == want;
}

static int do_header(img_t *im, const unsigned char *p, unsigned len) {
  if (len != 3) return -1;
  unsigned w = p[0], h = p[1], d = p[2];
  if (w == 0 || h == 0) return -1;
  if (w > 200 || h > 200) return -1;       /* "sanity" check, not fb bound */
  if (d != 1 && d != 2 && d != 4 && d != 8) return -1;
  /* BUG 1 half: only the FIRST header is checked against the
   * framebuffer; later headers just overwrite the fields. */
  if (!im->have_header && (w > FB_W || h > FB_H)) return -1;
  im->w = w; im->h = h; im->depth = d;
  im->have_header = 1;
  return 0;
}

static int do_palette(img_t *im, const unsigned char *p, unsigned len) {
  if (len < 1) return -1;
  unsigned count = p[0];
  if (count == 0 || count > 64) return -1;
  if (len != 1 + count) return -1;
  for (unsigned i = 0; i < count; i++) im->palette[i] = p[1 + i];
  im->pal_count = count;
  return 0;
}

static int do_data(img_t *im, const unsigned char *p, unsigned len) {
  if (!im->have_header) return -1;
  if (len < 1) return -1;
  unsigned row = p[0];
  if (row >= im->h) return -1;             /* row IS validated */
  if (len - 1 < im->w) return -1;          /* need a full row of pixels */
  unsigned char *dst = im->fb + (size_t)row * im->w;  /* BUG 1: w unchecked
                                                         vs FB_W on refresh */
  for (unsigned i = 0; i < im->w; i++) {
    unsigned char px = p[1 + i];
    if (im->pal_count) {
      /* BUG 2: px not checked against pal_count (OOB palette read) */
      px = im->palette[px];
    }
    dst[i] = px;                           /* wild write when row*w spills */
  }
  im->rows_done++;
  return 0;
}

static int parse(const unsigned char *buf, size_t n) {
  img_t im;
  memset(&im, 0, sizeof im);
  if (n < 4) return 1;
  if (buf[0] != 'Q' || buf[1] != 'I' || buf[2] != 'M' || buf[3] != 'G')
    return 1;
  size_t off = 4;
  int chunks = 0;
  while (off + 2 <= n) {
    unsigned char type = buf[off];
    unsigned len = buf[off + 1];
    off += 2;
    if (off + len + 1 > n) return 2;       /* truncated chunk */
    const unsigned char *payload = buf + off;
    unsigned char ck = buf[off + len];
    off += len + 1;
    if (!chunk_cksum_ok(payload, len, ck)) return 3;
    if (++chunks > 64) return 4;
    int rc;
    switch (type) {
      case 'H': rc = do_header(&im, payload, len); break;
      case 'P': rc = do_palette(&im, payload, len); break;
      case 'D': rc = do_data(&im, payload, len); break;
      case 'C': rc = 0; break;
      case 'E': printf("ok: %u rows\n", im.rows_done); return 0;
      default: rc = -1;
    }
    if (rc) return 5;
  }
  return 6;
}

static int run_once(const char *path) {
  static unsigned char buf[4096];
  size_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    ssize_t r = read(0, buf, sizeof(buf));
    n = r > 0 ? (size_t)r : 0;
  }
  printf("parse rc=%d\n", parse(buf, n));
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : NULL;
  if (__kb_manual_init) __kb_manual_init();
  if (__kb_persistent_loop) {
    while (__kb_persistent_loop(1000)) {
      if (run_once(path)) return 1;
    }
    return 0;
  }
  return run_once(path);
}
