/* libtest — main half of the native per-module fixture: input 'L...'
 * routes into the shared library (its own coverage module under
 * KB_MODULES=1); anything else stays in the main binary's blocks. */
#include <stdio.h>
#include <unistd.h>

int lib_check(const unsigned char *buf, int n);

int __kb_persistent_loop(unsigned max_cnt) __attribute__((weak));
void __kb_manual_init(void) __attribute__((weak));

static int run_once(const char *path) {
  unsigned char buf[64];
  ssize_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = (ssize_t)fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    n = read(0, buf, sizeof(buf));
  }
  if (n < 1) {
    printf("empty\n");
    return 0;
  }
  if (buf[0] == 'L') {
    printf("lib depth %d\n", lib_check(buf, (int)n));
  } else if (buf[0] == 'M') {
    printf("main deep\n");
  } else {
    printf("main shallow\n");
  }
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : 0;
  if (__kb_manual_init) __kb_manual_init();
  if (__kb_persistent_loop) {
    while (__kb_persistent_loop(1000)) {
      if (run_once(path)) return 1;
    }
    return 0;
  }
  return run_once(path);
}
