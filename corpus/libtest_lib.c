/* libtest1 — shared-library half of the native per-module coverage
 * fixture (reference corpus/libtest role): built with kb-cc into
 * libtest1.so, so it carries its own kb_rt copy and, under
 * KB_MODULES=1, claims its own map partition. */

int lib_check(const unsigned char *buf, int n) {
  int depth = 0;
  if (n < 2) return 0;
  if (buf[1] == 'X') {
    depth = 2;
    if (n > 2 && buf[2] == 'Y') depth = 3;
  } else if (buf[1] == 'Z') {
    depth = 1;
  }
  return depth;
}
