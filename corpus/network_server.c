/* Network-server fixture — a TCP server that accepts one connection,
 * reads packets and crashes on a 2-packet magic sequence (reference
 * corpus/network server role per SURVEY.md §2.9; fresh code).
 *
 * Usage: network_server <port> [udp]
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 2) return 2;
  int port = atoi(argv[1]);
  int udp = argc > 2 && strcmp(argv[2], "udp") == 0;

  int s = socket(AF_INET, udp ? SOCK_DGRAM : SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((unsigned short)port);
  if (bind(s, (struct sockaddr *)&addr, sizeof(addr)) != 0) return 3;

  int c = s;
  if (!udp) {
    if (listen(s, 1) != 0) return 4;
    c = accept(s, NULL, NULL);
    if (c < 0) return 5;
  }

  /* Length-framed protocol: each message is exactly 4 bytes, so TCP
   * segment coalescing of back-to-back sends cannot merge messages
   * (keeps the crash sequence deterministic for the test suite). */
  unsigned char buf[4];
  int got_hello = 0;
  for (;;) {
    size_t have = 0;
    while (have < sizeof(buf)) {
      ssize_t n = recv(c, buf + have, sizeof(buf) - have, 0);
      if (n <= 0) return 0;
      have += (size_t)n;
      if (udp) break; /* one datagram per message in udp mode */
    }
    if (have < 4) return 0;
    if (!got_hello) {
      if (memcmp(buf, "HELO", 4) == 0) got_hello = 1;
    } else if (memcmp(buf, "BOOM", 4) == 0) {
      *(volatile int *)0 = 1; /* crash on the 2-packet sequence */
    }
    if (udp) break; /* one datagram per run in udp mode */
  }
  return 0;
}
