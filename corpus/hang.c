/* Hang fixture — input beginning with 'H' loops forever (reference
 * corpus/hang behavior per SURVEY.md §2.9; fresh implementation). */
#include <stdio.h>
#include <unistd.h>

int main(int argc, char **argv) {
  unsigned char buf[16];
  size_t n;
  if (argc > 1) {
    FILE *f = fopen(argv[1], "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    n = fread(buf, 1, sizeof(buf), stdin);
  }
  if (n > 0 && buf[0] == 'H') {
    for (;;) usleep(1000);
  }
  printf("no hang\n");
  return 0;
}
