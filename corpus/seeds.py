"""Generate seed inputs (and known crash reproducers) for the
CGC-style corpus targets.  Valid seeds exercise the happy path without
crashing; the *_crash reproducers are the planted-bug proofs used by
tests to confirm each bug is real and deterministic.

Usage: python corpus/seeds.py [outdir]   (default corpus/seeds/)
"""

import os
import sys


def chunk(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + bytes([len(payload)]) + payload + \
        bytes([sum(payload) & 0xFF])


def imgparse_seed() -> bytes:
    out = b"QIMG"
    out += chunk(b"H", bytes([8, 8, 1]))
    out += chunk(b"P", bytes([2, 0x10, 0x20]))
    out += chunk(b"D", bytes([0]) + bytes([i & 1 for i in range(8)]))
    out += chunk(b"C", b"hi")
    out += chunk(b"E", b"")
    return out


def imgparse_crash() -> bytes:
    """Header re-send widens the image after validation: row 199 x
    width 200 lands ~39KB past the framebuffer."""
    out = b"QIMG"
    out += chunk(b"H", bytes([8, 8, 1]))          # first header: sane
    out += chunk(b"H", bytes([200, 200, 1]))      # BUG: unchecked resize
    out += chunk(b"D", bytes([199]) + bytes(200))  # row*w >> FB size
    return out


def tlvstack_seed() -> bytes:
    ops = [(0x01, 5), (0x01, 7), (0x03, 0), (0x06, 0), (0x07, 0),
           (0x02, 0), (0x0B, 0)]
    return b"STK1" + b"".join(bytes(p) for p in ops)


def tlvstack_crash() -> bytes:
    """255^4 wraps negative via MUL; SIND's signed bound check passes
    and slots[big_negative] writes ~1GB below the data segment."""
    ops = [(0x01, 255), (0x05, 0), (0x04, 0),     # 255*255
           (0x05, 0), (0x04, 0),                  # ^2 -> wraps negative
           (0x01, 1), (0x09, 0), (0x0A, 0)]       # val, swap, SIND
    return b"STK1" + b"".join(bytes(p) for p in ops)


def rledec_seed() -> bytes:
    out = b"RLE2" + (16).to_bytes(2, "little")
    out += bytes([0x00, 8, ord("A")])             # run of 8 'A'
    out += bytes([0x01, 4]) + b"abcd"             # literal
    out += bytes([0x02, 4, 4])                    # back-reference
    out += bytes([0x03])
    return out


def rledec_crash() -> bytes:
    """Fill the budget exactly, then emit runs forever: the reject
    check only fires while the cursor looks in-bounds, so the cursor
    walks megabytes past the output buffer."""
    out = b"RLE2" + (1024).to_bytes(2, "little")
    for _ in range(5):                            # 5*205=1025 > 1024
        out += bytes([0x00, 205, ord("B")])
    out += bytes([0x00, 255, ord("C")]) * 25000   # ~6.4MB of writes
    return out


SEEDS = {
    "imgparse.qimg": imgparse_seed,
    "imgparse_crash.qimg": imgparse_crash,
    "tlvstack.stk": tlvstack_seed,
    "tlvstack_crash.stk": tlvstack_crash,
    "rledec.rle": rledec_seed,
    "rledec_crash.rle": rledec_crash,
}


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "seeds")
    os.makedirs(outdir, exist_ok=True)
    for name, fn in SEEDS.items():
        with open(os.path.join(outdir, name), "wb") as f:
            f.write(fn())
    print(f"wrote {len(SEEDS)} seeds to {outdir}")


if __name__ == "__main__":
    main()
