/* qemu-stub — a minimal EXTERNAL "__AFL_SHM_ID-honoring emulator".
 *
 * The afl instrumentation's qemu_path option claims any emulator
 * that speaks the forkserver wire contract (docs/AFL.md: 1-byte
 * commands on fd 198, 4-byte replies on fd 199, hello 0x4b42465a,
 * coverage into the SysV SHM segment named by __AFL_SHM_ID) plugs in
 * unchanged.  This stub is the proof: it is built standalone from
 * the DOCUMENTED contract — it does not include kb_protocol.h, link
 * any killerbeez code, or ptrace anything — and the gated
 * test_qemu_path_external_emulator runs real campaigns through it.
 *
 * Per exec it plays the role a real emulator's translated-block hook
 * plays, reduced to the minimum that exercises every consumer:
 *   - input-dependent coverage: the staged stdin bytes are hashed
 *     into map slots before being rewound for the child (a real
 *     emulator derives slots from executed blocks; the test only
 *     needs different inputs -> different maps, same input -> same
 *     map);
 *   - real verdicts: the target runs natively via fork+execv and its
 *     wait status is relayed verbatim (crash signals included).
 *
 * Usage: qemu-stub TARGET [ARGS...]
 */
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/shm.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

/* The documented wire contract (docs/AFL.md), restated locally on
 * purpose: an external emulator has only the docs to build against. */
#define CMD_FD 198
#define ST_FD 199
#define CMD_EXIT 0
#define CMD_FORK 1
#define CMD_RUN 2
#define CMD_FORK_RUN 3
#define CMD_GET_STATUS 4
#define HELLO 0x4b42465aU
#define MAP_SIZE 65536

static unsigned char fallback[MAP_SIZE];
static unsigned char *map = fallback;

static void attach_map(void) {
  const char *id = getenv("__AFL_SHM_ID");
  if (!id) return;
  void *p = shmat(atoi(id), NULL, 0);
  if (p != (void *)-1) map = (unsigned char *)p;
}

/* Hash the staged input into map slots, then rewind it for the
 * child.  FNV-1a over a sliding window: every byte prefix lands a
 * distinct slot, so novelty deepens as inputs diverge — the shape a
 * block-coverage stream has, without pretending to be one.
 * Non-seekable stdin (one-shot manual run with piped input) is left
 * UNTOUCHED: consuming it would truncate the child's input. */
static void record_input_coverage(void) {
  unsigned char buf[4096];
  off_t here = lseek(0, 0, SEEK_CUR);
  map[0]++; /* the "entry block": even empty inputs leave a mark */
  if (here < 0) return; /* pipe: cannot rewind, do not consume */
  uint32_t h = 0x811c9dc5u;
  ssize_t n;
  while ((n = read(0, buf, sizeof buf)) > 0) /* hash the WHOLE input */
    for (ssize_t i = 0; i < n; i++) {
      h = (h ^ buf[i]) * 0x01000193u;
      map[h % MAP_SIZE]++;
    }
  lseek(0, here, SEEK_SET);
}

static pid_t spawn_target(char **argv) {
  pid_t pid = fork();
  if (pid == 0) {
    close(CMD_FD);
    close(ST_FD);
    execv(argv[0], argv);
    _exit(125);
  }
  return pid;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s target [args...]\n", argv[0]);
    return 2;
  }
  attach_map();

  uint32_t hello = HELLO;
  if (write(ST_FD, &hello, 4) != 4) {
    /* no fuzzer attached: one-shot run */
    record_input_coverage();
    pid_t pid = spawn_target(argv + 1);
    int status = 0;
    waitpid(pid, &status, 0);
    if (WIFSIGNALED(status)) {
      raise(WTERMSIG(status));
      return 128 + WTERMSIG(status);
    }
    return WIFEXITED(status) ? WEXITSTATUS(status) : 2;
  }

  pid_t child = -1;
  for (;;) {
    unsigned char cmd;
    if (read(CMD_FD, &cmd, 1) != 1) _exit(0);
    switch (cmd) {
      case CMD_EXIT:
        if (child > 0) kill(child, SIGKILL);
        _exit(0);
      case CMD_FORK:
      case CMD_FORK_RUN: {
        record_input_coverage();
        child = spawn_target(argv + 1);
        int32_t pid32 = (int32_t)child;
        if (write(ST_FD, &pid32, 4) != 4) _exit(1);
        if (child < 0) _exit(1);
        break;
      }
      case CMD_RUN:
        break; /* child already running (plain fork+exec stub) */
      case CMD_GET_STATUS: {
        int32_t st32 = -1;
        if (child > 0) {
          int status = 0;
          waitpid(child, &status, 0);
          st32 = (int32_t)status;
          child = -1;
        }
        if (write(ST_FD, &st32, 4) != 4) _exit(1);
        break;
      }
      default:
        _exit(2);
    }
  }
}
