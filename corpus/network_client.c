/* Network-client fixture — connects to a server (the fuzzer's
 * listener), reads packets, crashes on a magic packet (reference
 * corpus/network client role per SURVEY.md §2.9; fresh code).
 *
 * Usage: network_client <port>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 2) return 2;
  int port = atoi(argv[1]);
  int s = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((unsigned short)port);
  /* Retry briefly: the fuzzer's listener may still be coming up. */
  int ok = -1;
  for (int i = 0; i < 50 && ok != 0; i++) {
    ok = connect(s, (struct sockaddr *)&addr, sizeof(addr));
    if (ok != 0) usleep(20000);
  }
  if (ok != 0) return 3;

  unsigned char buf[256];
  for (;;) {
    ssize_t n = recv(s, buf, sizeof(buf), 0);
    if (n <= 0) break;
    if (n >= 4 && memcmp(buf, "KILL", 4) == 0)
      *(volatile int *)0 = 1;
  }
  close(s);
  return 0;
}
