/* Deliberately-divergent hybrid fixture (docs/HYBRID.md): same input
 * interface as test.c (argv[1] file, else stdin) and the same benign
 * behavior — prints matched depth, exits 0 — but NEVER crashes, even
 * on the full "ABCD" magic.  Binding a KBVM "test" proxy against this
 * binary certifies clean (benign seeds agree) yet every proxy crash
 * replays clean natively, so cross-tier triage must produce
 * `proxy_only` verdicts and proxy-gap reports.  That is the fixture's
 * whole job: a controlled semantic gap for validating the gap path.
 */
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>

static int check(const unsigned char *buf, size_t n) {
  if (n < 1 || buf[0] != 'A') return 0;
  if (n < 2 || buf[1] != 'B') return 1;
  if (n < 3 || buf[2] != 'C') return 2;
  if (n < 4 || buf[3] != 'D') return 3;
  /* proxy dies here; we just report the match */
  return 4;
}

int main(int argc, char **argv) {
  unsigned char buf[64];
  size_t n;
  if (argc > 1) {
    FILE *f = fopen(argv[1], "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    ssize_t r = read(0, buf, sizeof(buf));
    n = r > 0 ? (size_t)r : 0;
  }
  printf("matched %d bytes\n", check(buf, n));
  return 0;
}
