/* tlvstack — CGC-style stack-machine interpreter over a TLV command
 * stream (realistic target: opcode dispatch, per-op validation, and a
 * pointer-arithmetic bug reachable only through a specific op
 * sequence).
 *
 * Format: "STK1" then commands [op u8][arg u8]:
 *   0x01 PUSH  arg          — push literal
 *   0x02 POP                — pop (validated)
 *   0x03 ADD                — pop a, pop b, push a+b
 *   0x04 MUL                — pop a, pop b, push a*b
 *   0x05 DUP                — duplicate top
 *   0x06 STORE arg          — slots[arg] = pop()  (arg validated < 16)
 *   0x07 LOAD  arg          — push slots[arg]     (arg validated < 16)
 *   0x08 PICK  arg          — push stack[sp-1-arg]: BUG — arg is
 *        checked against sp with a SIGNED comparison that a crafted
 *        sp value makes pass, then used to index far below the stack
 *        base (wild read feeding a wild write via STORE-indirect).
 *   0x09 SWAP               — swap top two
 *   0x0a SIND               — "store indirect": addr = pop(), val =
 *        pop(), slots[addr] = val with addr checked ONLY by the same
 *        signed-compare helper — negative addr from PICK garbage
 *        writes far outside the slot array (deterministic SIGSEGV for
 *        large magnitudes).
 *   0x0b HALT
 *
 * Input: argv[1] file, else stdin.  Seed: seeds/tlvstack.stk.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

int __kb_persistent_loop(unsigned max_cnt) __attribute__((weak));
void __kb_manual_init(void) __attribute__((weak));

#define STACK_MAX 32

typedef struct {
  int stack[STACK_MAX];
  int sp;                      /* points at next free slot */
  int slots[16];
} vm_t;

/* The buggy range helper: callers pass (idx, limit) as ints; a
 * negative idx sneaks under the limit check. */
static int in_range(int idx, int limit) { return idx < limit; }

static int step(vm_t *vm, unsigned char op, unsigned char arg) {
  switch (op) {
    case 0x01:                               /* PUSH */
      if (vm->sp >= STACK_MAX) return -1;
      vm->stack[vm->sp++] = arg;
      return 0;
    case 0x02:                               /* POP */
      if (vm->sp <= 0) return -1;
      vm->sp--;
      return 0;
    case 0x03: case 0x04: {                  /* ADD / MUL */
      if (vm->sp < 2) return -1;
      int a = vm->stack[--vm->sp];
      int b = vm->stack[--vm->sp];
      vm->stack[vm->sp++] = op == 0x03 ? a + b : a * b;
      return 0;
    }
    case 0x05:                               /* DUP */
      if (vm->sp < 1 || vm->sp >= STACK_MAX) return -1;
      vm->stack[vm->sp] = vm->stack[vm->sp - 1];
      vm->sp++;
      return 0;
    case 0x06:                               /* STORE */
      if (arg >= 16 || vm->sp < 1) return -1;
      vm->slots[arg] = vm->stack[--vm->sp];
      return 0;
    case 0x07:                               /* LOAD */
      if (arg >= 16 || vm->sp >= STACK_MAX) return -1;
      vm->stack[vm->sp++] = vm->slots[arg];
      return 0;
    case 0x08: {                             /* PICK: wild read */
      if (vm->sp < 1 || vm->sp >= STACK_MAX) return -1;
      int depth = arg;                       /* 0..255 vs sp<=32: */
      if (!in_range(depth, vm->sp * 8)) return -1;  /* BUG: sloppy bound */
      vm->stack[vm->sp] = vm->stack[vm->sp - 1 - depth];
      vm->sp++;
      return 0;
    }
    case 0x09: {                             /* SWAP */
      if (vm->sp < 2) return -1;
      int t = vm->stack[vm->sp - 1];
      vm->stack[vm->sp - 1] = vm->stack[vm->sp - 2];
      vm->stack[vm->sp - 2] = t;
      return 0;
    }
    case 0x0a: {                             /* SIND: wild write */
      if (vm->sp < 2) return -1;
      int addr = vm->stack[--vm->sp];
      int val = vm->stack[--vm->sp];
      if (!in_range(addr, 16)) return -1;    /* BUG: negative passes */
      vm->slots[addr] = val;                 /* addr << 0 from PICK junk */
      return 0;
    }
    case 0x0b:
      return 1;
    default:
      return -1;
  }
}

static int interp(const unsigned char *buf, size_t n) {
  static vm_t vm;
  memset(&vm, 0, sizeof vm);
  if (n < 4) return 1;
  if (memcmp(buf, "STK1", 4) != 0) return 1;
  size_t off = 4;
  int steps = 0;
  while (off + 2 <= n) {
    int rc = step(&vm, buf[off], buf[off + 1]);
    off += 2;
    if (rc < 0) return 2;
    if (rc > 0) { printf("halt sp=%d\n", vm.sp); return 0; }
    if (++steps > 256) return 3;
  }
  return 4;
}

static int run_once(const char *path) {
  static unsigned char buf[2048];
  size_t n;
  if (path) {
    FILE *f = fopen(path, "rb");
    if (!f) return 1;
    n = fread(buf, 1, sizeof(buf), f);
    fclose(f);
  } else {
    ssize_t r = read(0, buf, sizeof(buf));
    n = r > 0 ? (size_t)r : 0;
  }
  printf("interp rc=%d\n", interp(buf, n));
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : NULL;
  if (__kb_manual_init) __kb_manual_init();
  if (__kb_persistent_loop) {
    while (__kb_persistent_loop(1000)) {
      if (run_once(path)) return 1;
    }
    return 0;
  }
  return run_once(path);
}
